"""Whole-plan fusion (execution/fusion.py + fusion_boundaries.py).

Covers: fusion-on vs fusion-off byte-identity over verbatim TPC-H q3/q17
and a bounded TPC-DS sample (the r10 parity-test pattern), per-barrier
fallback behavior (sort, outer join, chunked source, duplicate probe
keys, COUNT DISTINCT), the dispatch-count acceptance (strictly fewer
exec.stage/exec.fused spans fused than staged, second-run compiles = 0
through the ProgramBank), cross-session program sharing (two sessions
compile <= 1.2x one session's count), per-join actuals from fused
regions, the result-cache contracts (a HIT on a fused query is exactly
the {query, serving.cache_lookup} two-span trace; toggling fusion never
orphans warm entries), the frozen boundary-kind registry, and the
distributed tier's fused co-bucketed join+filter+aggregate MeshProgram
(zero resharding collectives on compiled HLO, ONE dispatch).

Sessions run the default conf; stream leaves stay below
``distributed.minStreamRows`` so the single-device fusion tier (not the
SPMD mesh, which keeps right of way) is what executes.
"""

from __future__ import annotations

import datetime
import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace
from hyperspace_tpu.execution import fusion
from hyperspace_tpu.execution import fusion_boundaries as FB
from hyperspace_tpu.execution import shapes
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col, count_distinct, sum_
from hyperspace_tpu.serving.constants import ServingConstants
from hyperspace_tpu.telemetry import span_names as sn
from hyperspace_tpu.telemetry.constants import TelemetryConstants as TC

import test_tpch_sql as tpch_mod
from goldstandard import tpcds_real

FUSION = IndexConstants.TPU_FUSION_ENABLED


def _fusion(session, on: bool) -> None:
    session.conf.set(FUSION, "true" if on else "false")


def _norm(df: pd.DataFrame) -> pd.DataFrame:
    return tpch_mod._norm(df)


# ---------------------------------------------------------------------------
# Fixtures.
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tpch(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("tpch_fusion"))
    session = hst.Session(system_path=os.path.join(root, "indexes"))
    tables = tpch_mod._make_tables(np.random.default_rng(20260804))
    for name, t in tables.items():
        d = os.path.join(root, name)
        os.makedirs(d)
        pq.write_table(t, os.path.join(d, "part0.parquet"))
        session.create_temp_view(name, session.read.parquet(d))
    return session


@pytest.fixture()
def mini(tmp_path):
    """A q3-shaped miniature: lineitem (nullable discount) x orders."""
    rng = np.random.default_rng(11)
    n, n_od = 2400, 300
    li_dir, od_dir = str(tmp_path / "li"), str(tmp_path / "od")
    os.makedirs(li_dir)
    os.makedirs(od_dir)
    disc = rng.uniform(0, 0.1, n).round(3)
    disc_mask = rng.random(n) < 0.1
    pq.write_table(pa.table({
        "l_orderkey": rng.integers(0, n_od, n).astype(np.int64),
        "l_shipdate": rng.integers(0, 1000, n).astype(np.int64),
        "l_extendedprice": rng.uniform(1, 1000, n).round(2),
        "l_discount": pa.array(
            [None if m else float(v) for m, v in zip(disc_mask, disc)],
            type=pa.float64()),
    }), os.path.join(li_dir, "part0.parquet"))
    pq.write_table(pa.table({
        "o_orderkey": np.arange(n_od, dtype=np.int64),
        "o_orderdate": rng.integers(0, 1000, n_od).astype(np.int64),
        "o_shippriority": rng.integers(0, 3, n_od).astype(np.int64),
    }), os.path.join(od_dir, "part0.parquet"))
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    return session, li_dir, od_dir


def _build_q3ish(session, li_dir, od_dir, cut=500):
    li = session.read.parquet(li_dir).filter(col("l_shipdate") > int(cut))
    od = session.read.parquet(od_dir).filter(col("o_orderdate") < 700)
    return (li.join(od, on=col("l_orderkey") == col("o_orderkey"))
            .group_by("o_shippriority")
            .agg(sum_(col("l_extendedprice") * (1 - col("l_discount")))
                 .alias("revenue"))
            .sort("o_shippriority"))


def _on_off(session, build):
    """(fused result, staged result) as pandas, fusion restored to on."""
    _fusion(session, True)
    on = build().to_pandas()
    _fusion(session, False)
    off = build().to_pandas()
    _fusion(session, True)
    return on, off


# ---------------------------------------------------------------------------
# The fused path is taken, and is byte-identical.
# ---------------------------------------------------------------------------

class TestFusedExecution:
    def test_q3ish_fuses_and_matches_staged(self, mini):
        session, li_dir, od_dir = mini
        d0 = fusion.DISPATCH_COUNT
        on, off = _on_off(session, lambda: _build_q3ish(session, li_dir,
                                                        od_dir))
        assert fusion.DISPATCH_COUNT > d0
        pd.testing.assert_frame_equal(on, off)

    def test_fused_region_spans_and_dispatch_counts(self, mini):
        """THE dispatch acceptance: with fusion on, the traced run shows
        one exec.fused span covering the region's nodes and strictly
        fewer total execution spans (exec.stage + exec.fused) than the
        staged run of the same query."""
        session, li_dir, od_dir = mini
        hs = Hyperspace(session)
        q = _build_q3ish(session, li_dir, od_dir)
        q.to_arrow()  # warm compiles untraced
        session.conf.set(TC.TRACE_ENABLED, "true")
        q.to_arrow()
        fused_tr = hs.last_trace()
        _fusion(session, False)
        q.to_arrow()
        staged_tr = hs.last_trace()
        _fusion(session, True)
        session.conf.set(TC.TRACE_ENABLED, "false")
        fused_spans = fused_tr.find(sn.EXEC_FUSED)
        # The main agg+project+join+project+filter region, plus the join
        # SIDE's own project+filter chain region.
        assert len(fused_spans) >= 1
        assert max(s.attrs["fused_nodes"] for s in fused_spans) >= 3
        assert staged_tr.find(sn.EXEC_FUSED) == []
        n_fused = len(fused_tr.find(sn.EXEC_STAGE)) + len(fused_spans)
        n_staged = len(staged_tr.find(sn.EXEC_STAGE))
        assert n_fused < n_staged

    def test_second_run_compiles_zero_through_bank(self, mini):
        session, li_dir, od_dir = mini
        q = _build_q3ish(session, li_dir, od_dir)
        q.to_arrow()  # cold: compiles the region program
        c0 = shapes.compile_count()
        q.to_arrow()
        assert shapes.compile_count() == c0  # warm: bank hit, 0 compiles

    def test_fused_join_records_actuals(self, mini):
        """Fused regions feed the r10/r13 join-actuals store, so the
        join-reorder q-error pairing keeps learning."""
        session, li_dir, od_dir = mini
        session._join_actuals.clear()
        _fusion(session, True)
        _build_q3ish(session, li_dir, od_dir).to_arrow()
        fused_actuals = dict(session._join_actuals)
        assert fused_actuals, "fused region recorded no join actuals"
        session._join_actuals.clear()
        _fusion(session, False)
        _build_q3ish(session, li_dir, od_dir).to_arrow()
        staged_actuals = dict(session._join_actuals)
        _fusion(session, True)
        assert fused_actuals == staged_actuals

    def test_literal_sweep_reuses_one_region_program(self, mini):
        """Literal slots ride as runtime args: shifting a predicate
        literal must not recompile the region."""
        session, li_dir, od_dir = mini
        _build_q3ish(session, li_dir, od_dir, cut=500).to_arrow()
        c0 = shapes.compile_count()
        for cut in (510, 520, 530):
            _build_q3ish(session, li_dir, od_dir, cut=cut).to_arrow()
        assert shapes.compile_count() == c0


# ---------------------------------------------------------------------------
# Parity over verbatim TPC-H and a bounded TPC-DS sample (r10 pattern).
# ---------------------------------------------------------------------------

class TestTpchParity:
    @pytest.mark.parametrize("name", ["q3", "q17"])
    def test_acceptance_queries_identical(self, tpch, name):
        text = dict((c[0], c[1]) for c in tpch_mod._CASES)[name]
        _fusion(tpch, True)
        on = tpch.sql(text).to_pandas()
        _fusion(tpch, False)
        off = tpch.sql(text).to_pandas()
        _fusion(tpch, True)
        pd.testing.assert_frame_equal(on, off)
        assert len(on) > 0

    @pytest.mark.parametrize(
        "name", [c[0] for c in tpch_mod._CASES
                 if c[0] not in ("q3", "q17")])
    def test_full_suite_identical(self, tpch, name):
        text = dict((c[0], c[1]) for c in tpch_mod._CASES)[name]
        on, off = _on_off(tpch, lambda: tpch.sql(text))
        pd.testing.assert_frame_equal(on, off)


TPCDS_EXEC_BUDGET = 6  # deterministic first-K (r10 parity budget pattern)


@pytest.fixture(scope="module")
def tpcds(tmp_path_factory):
    root = tmp_path_factory.mktemp("tpcds_fusion")
    session = hst.Session(system_path=str(root / "indexes"))
    tpcds_real.register_tables(session, str(root / "data"))
    return session


class TestTpcdsParity:
    @pytest.mark.parametrize(
        "name", tpcds_real.QUERY_NAMES[:TPCDS_EXEC_BUDGET])
    def test_sample_identical(self, tpcds, name):
        text = tpcds_real.QUERY_TEXTS[name]
        on, off = _on_off(tpcds, lambda: tpcds.sql(text))
        pd.testing.assert_frame_equal(_norm(on), _norm(off),
                                      check_dtype=False)


# ---------------------------------------------------------------------------
# Barriers and runtime fallbacks (per-kind behavior).
# ---------------------------------------------------------------------------

def _fallbacks():
    return fusion.stats()["fallbacks"]


class TestBarriers:
    def test_sort_barrier_splits_region(self, mini):
        """A Sort inside the chain is a barrier: it executes staged and
        the stages ABOVE it fuse over its output."""
        session, li_dir, od_dir = mini

        def build():
            li = session.read.parquet(li_dir)
            return (li.sort("l_orderkey")
                    .filter(col("l_shipdate") > 300)
                    .filter(col("l_extendedprice") > 10.0)
                    .select("l_orderkey", "l_extendedprice"))
        before = _fallbacks().get(FB.SORT, 0)
        d0 = fusion.DISPATCH_COUNT
        on, off = _on_off(session, build)
        assert _fallbacks().get(FB.SORT, 0) > before
        assert fusion.DISPATCH_COUNT > d0  # the region above still fused
        pd.testing.assert_frame_equal(on, off)

    def test_outer_join_barrier(self, mini):
        session, li_dir, od_dir = mini

        def build():
            li = session.read.parquet(li_dir)
            od = session.read.parquet(od_dir)
            return (li.join(od, on=col("l_orderkey") == col("o_orderkey"),
                            how="left")
                    .filter(col("l_shipdate") > 300)
                    .filter(col("l_extendedprice") > 10.0)
                    .select("l_orderkey", "o_shippriority"))
        before = _fallbacks().get(FB.OUTER_JOIN, 0)
        on, off = _on_off(session, build)
        assert _fallbacks().get(FB.OUTER_JOIN, 0) > before
        pd.testing.assert_frame_equal(on, off)

    def test_chunked_source_falls_back(self, mini):
        """A leaf past the chunk budget belongs to the streaming staged
        path — the fused program must never materialize it whole."""
        session, li_dir, od_dir = mini
        session.conf.set(IndexConstants.TPU_MAX_CHUNK_ROWS, "512")
        try:
            before = _fallbacks().get(FB.CHUNKED_SOURCE, 0)
            on, off = _on_off(
                session, lambda: _build_q3ish(session, li_dir, od_dir))
            assert _fallbacks().get(FB.CHUNKED_SOURCE, 0) > before
            pd.testing.assert_frame_equal(on, off)
        finally:
            session.conf.set(IndexConstants.TPU_MAX_CHUNK_ROWS,
                             IndexConstants.TPU_MAX_CHUNK_ROWS_DEFAULT)

    def test_duplicate_probe_keys_fall_back(self, mini):
        """m:n joins (duplicate side keys) stay with the staged merge
        join, discovered at prep with one host sync."""
        session, li_dir, od_dir = mini

        def build():
            li = session.read.parquet(li_dir)
            li2 = session.read.parquet(li_dir).select(
                col("l_orderkey").alias("r_orderkey"),
                col("l_extendedprice").alias("r_price"))
            return (li.filter(col("l_shipdate") > 800)
                    .join(li2, on=col("l_orderkey") == col("r_orderkey"))
                    .group_by("l_orderkey")
                    .agg(sum_(col("r_price")).alias("s")))
        before = _fallbacks().get(FB.DUPLICATE_PROBE_KEYS, 0)
        on, off = _on_off(session, build)
        assert _fallbacks().get(FB.DUPLICATE_PROBE_KEYS, 0) > before
        pd.testing.assert_frame_equal(
            _norm(on), _norm(off), check_dtype=False)

    def test_count_distinct_barrier(self, mini):
        session, li_dir, od_dir = mini

        def build():
            li = session.read.parquet(li_dir)
            return (li.filter(col("l_shipdate") > 300)
                    .group_by("l_orderkey")
                    .agg(count_distinct(col("l_extendedprice"))
                         .alias("n")))
        before = _fallbacks().get(FB.COUNT_DISTINCT, 0)
        on, off = _on_off(session, build)
        assert _fallbacks().get(FB.COUNT_DISTINCT, 0) > before
        pd.testing.assert_frame_equal(on, off)

    def test_bucket_ordered_stream_falls_back_to_staged(self, tmp_path):
        """A covering-index scan materializes bucket order — the staged
        executor's sort-skipping group-by keeps its home (its counter
        still moves) and the fused tier steps aside at runtime."""
        from hyperspace_tpu.api import IndexConfig
        from hyperspace_tpu.execution import executor as ex
        rng = np.random.default_rng(2)
        d = str(tmp_path / "t")
        os.makedirs(d)
        pq.write_table(pa.table({
            "k": rng.integers(0, 40, 2000).astype(np.int64),
            "w": rng.integers(0, 900, 2000).astype(np.int64),
            "v": rng.uniform(0, 10, 2000),
        }), os.path.join(d, "p.parquet"))
        session = hst.Session(system_path=str(tmp_path / "ix"))
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(d),
                        IndexConfig("kidx", ["k"], ["w", "v"]))
        session.enable_hyperspace()

        def build():
            return (session.read.parquet(d)
                    .filter(col("k") > 5).filter(col("w") > 100)
                    .group_by("k").agg(sum_(col("v")).alias("sv")))
        before = _fallbacks().get(FB.BUCKET_ORDER, 0)
        g0 = ex.GROUPBY_SORT_SKIPPED
        on, off = _on_off(session, build)
        assert _fallbacks().get(FB.BUCKET_ORDER, 0) > before
        assert ex.GROUPBY_SORT_SKIPPED > g0
        pd.testing.assert_frame_equal(on, off)

    def test_disabled_restores_staged(self, mini):
        session, li_dir, od_dir = mini
        _fusion(session, False)
        try:
            before = _fallbacks().get(FB.DISABLED, 0)
            d0 = fusion.DISPATCH_COUNT
            _build_q3ish(session, li_dir, od_dir).to_arrow()
            assert fusion.DISPATCH_COUNT == d0
            assert _fallbacks().get(FB.DISABLED, 0) > before
        finally:
            _fusion(session, True)


class TestBoundaryRegistry:
    def test_registry_is_the_expected_frozen_vocabulary(self):
        # Referencing every kind here is also what satisfies the
        # scripts/lint.py boundary-coverage gate — like the span-name
        # registry, this vocabulary only changes deliberately.
        assert FB.BOUNDARY_KINDS == frozenset({
            "leaf", "sort", "window", "limit", "union", "aggregate",
            "outer-join", "cross-join", "non-equi-join", "multi-key-join",
            "count-distinct", "unsupported-agg", "unsupported-expr",
            "disabled", "sweep", "region-too-small", "chunked-source",
            "bucket-order", "duplicate-probe-keys", "key-dtype",
            "empty-input", "fused-program-error",
        })


# ---------------------------------------------------------------------------
# ProgramBank integration.
# ---------------------------------------------------------------------------

class TestProgramBankSharing:
    def test_two_sessions_share_fused_regions(self, tmp_path):
        """Acceptance: two sessions running the same warm fused workload
        compile <= 1.2x one session's count (the r11 bank contract,
        extended to region programs)."""
        rng = np.random.default_rng(3)
        li_dir = str(tmp_path / "li")
        os.makedirs(li_dir)
        pq.write_table(pa.table({
            "k": rng.integers(0, 50, 1500).astype(np.int64),
            "v": rng.uniform(0, 100, 1500),
            "w": rng.integers(0, 900, 1500).astype(np.int64),
        }), os.path.join(li_dir, "part0.parquet"))

        def run(session):
            li = session.read.parquet(li_dir)
            return (li.filter(col("w") > 200)
                    .filter(col("v") > 1.0)
                    .group_by("k").agg(sum_(col("v")).alias("s"))
                    ).to_arrow()

        s1 = hst.Session(system_path=str(tmp_path / "ix1"))
        c0 = shapes.compile_count()
        run(s1)
        c1 = shapes.compile_count() - c0
        run(s1)  # warm: second run free
        s2 = hst.Session(system_path=str(tmp_path / "ix2"))
        c2_before = shapes.compile_count()
        run(s2)
        c2 = shapes.compile_count() - c2_before
        assert c1 + c2 <= 1.2 * max(c1, 1), (c1, c2)


# ---------------------------------------------------------------------------
# Result-cache contracts.
# ---------------------------------------------------------------------------

class TestResultCacheContracts:
    def _enable_cache(self, session):
        session.conf.set(ServingConstants.RESULT_CACHE_ENABLED, "true")
        session.conf.set(
            ServingConstants.RESULT_CACHE_MIN_COMPUTE_SECONDS, "0")

    def test_cache_hit_on_fused_query_is_two_span_trace(self, mini):
        """Satellite regression: a result-cache HIT on a fused query must
        still produce the exact {query, serving.cache_lookup} trace — no
        exec.fused, no exec.stage, no reads."""
        session, li_dir, od_dir = mini
        self._enable_cache(session)
        hs = Hyperspace(session)
        q = _build_q3ish(session, li_dir, od_dir)
        session.conf.set(TC.TRACE_ENABLED, "true")
        q.to_arrow()  # cold: fused execution, admitted
        cold = hs.last_trace()
        q.to_arrow()  # hit
        hit = hs.last_trace()
        session.conf.set(TC.TRACE_ENABLED, "false")
        assert cold.find(sn.EXEC_FUSED) != []
        assert {s.name for s in hit.spans} == {sn.QUERY, sn.CACHE_LOOKUP}
        assert hit.find(sn.EXEC_FUSED) == []
        assert hit.find(sn.EXEC_STAGE) == []

    def test_fusion_toggle_keeps_warm_entries(self, mini):
        """fusion.* is excluded from the result-cache config hash:
        answers are byte-identical by contract, so toggling the tier must
        not orphan warm entries."""
        session, li_dir, od_dir = mini
        self._enable_cache(session)
        q = _build_q3ish(session, li_dir, od_dir)
        q.to_arrow()  # fused, admitted
        stats0 = session.result_cache.stats()
        _fusion(session, False)
        try:
            q.to_arrow()
        finally:
            _fusion(session, True)
        stats1 = session.result_cache.stats()
        assert stats1["hits"] == stats0["hits"] + 1


# ---------------------------------------------------------------------------
# The distributed tier's fused region (co-bucketed join + consumers).
# ---------------------------------------------------------------------------

class TestDistributedFusedRegion:
    def test_join_filter_agg_single_program_zero_resharding(self):
        """The fused sharded region: the shuffle-free co-bucketed join
        composes with a consumer filter + aggregate in ONE partitioned
        executable — compiled HLO still moves zero rows between devices
        (no all-to-all/all-gather/collective-permute/reduce-scatter) and
        the dispatch counter moves by exactly one."""
        from hyperspace_tpu.execution.columnar import Table
        from hyperspace_tpu.parallel import sharding
        from hyperspace_tpu.parallel.distributed_build import \
            distributed_build_sorted_buckets
        from hyperspace_tpu.parallel.distributed_query import (
            distributed_join_filter_agg, join_filter_agg_collectives)
        from hyperspace_tpu.parallel.mesh import make_mesh
        rng = np.random.default_rng(9)
        n = 2048
        left = Table.from_arrow(pa.table({
            "k": rng.integers(0, 64, n).astype(np.int64),
            "lv": rng.integers(0, 50, n).astype(np.int64),
            "f": rng.integers(0, 100, n).astype(np.int64)}))
        right = Table.from_arrow(pa.table({
            "k": rng.integers(0, 64, n // 2).astype(np.int64),
            "rv": rng.integers(0, 50, n // 2).astype(np.int64)}))
        mesh = make_mesh()
        lt, lvalid, _ = distributed_build_sorted_buckets(
            left, ["k"], 16, mesh)
        rt, rvalid, _ = distributed_build_sorted_buckets(
            right, ["k"], 16, mesh)
        counts = join_filter_agg_collectives(
            lt, lvalid, rt, rvalid, "k", "lv", "rv", "f", 10, 60, mesh)
        assert counts["all-to-all"] == 0, counts
        assert counts["all-gather"] == 0, counts
        assert counts["collective-permute"] == 0, counts
        assert counts["reduce-scatter"] == 0, counts
        assert counts["all-reduce"] >= 1, counts
        d0 = sharding.DISPATCH_COUNT
        cnt, lsum, rsum = distributed_join_filter_agg(
            lt, lvalid, rt, rvalid, "k", "lv", "rv", "f", 10, 60, mesh)
        assert sharding.DISPATCH_COUNT - d0 == 1
        dfl = pd.DataFrame({
            "k": np.asarray(left.column("k").data),
            "lv": np.asarray(left.column("lv").data),
            "f": np.asarray(left.column("f").data)})
        dfr = pd.DataFrame({
            "k": np.asarray(right.column("k").data),
            "rv": np.asarray(right.column("rv").data)})
        joined = dfl[(dfl.f >= 10) & (dfl.f <= 60)].merge(dfr, on="k")
        assert cnt == len(joined)
        assert lsum == joined["lv"].sum()
        assert rsum == joined["rv"].sum()


# ---------------------------------------------------------------------------
# Metrics surface.
# ---------------------------------------------------------------------------

class TestFusionStats:
    def test_metrics_collector_registered(self, mini):
        session, li_dir, od_dir = mini
        _build_q3ish(session, li_dir, od_dir).to_arrow()
        m = Hyperspace(session).metrics()
        assert "fusion" in m["collectors"]
        assert m["collectors"]["fusion"]["fused_executions"] >= 1
        assert isinstance(m["collectors"]["fusion"]["fallbacks"], dict)
        # Region programs are visible in the bank's per-kind breakdown.
        from hyperspace_tpu.serving.program_bank import get_bank
        kinds = get_bank().stats()["stages_by_kind"]
        assert kinds.get("fused-region", 0) >= 1

    def test_datetime_literals_fuse(self, tmp_path):
        """Date-typed slot literals (the q3 shape) ride as runtime args."""
        rng = np.random.default_rng(4)
        d = str(tmp_path / "t")
        os.makedirs(d)
        base = datetime.date(1995, 1, 1)
        pq.write_table(pa.table({
            "ship": pa.array([base + datetime.timedelta(days=int(x))
                              for x in rng.integers(0, 400, 1200)]),
            "price": rng.uniform(1, 100, 1200),
        }), os.path.join(d, "part0.parquet"))
        session = hst.Session(system_path=str(tmp_path / "ix"))
        q = (session.read.parquet(d)
             .filter(col("ship") > datetime.date(1995, 6, 1))
             .filter(col("price") > 5.0)
             .agg(sum_(col("price")).alias("s")))
        d0 = fusion.DISPATCH_COUNT
        on = q.to_pandas()
        assert fusion.DISPATCH_COUNT > d0
        _fusion(session, False)
        off = q.to_pandas()
        _fusion(session, True)
        pd.testing.assert_frame_equal(on, off)
