"""Grouped SPMD aggregation past the 65k local-capacity floor
(VERDICT r5 #6: TPC-DS groups by customer/item keys — 65,536 local groups
was a real-query ceiling that silently serialized the largest queries).

MAX_LOCAL_GROUPS is now the INITIAL capacity: on overflow the program
reports the exact worldwide need and ONE retry re-runs with that many
segment slots (distinct groups never exceed per-device rows, so the
retry always fits). The test runs >=1M distinct groups over the 8-device
mesh and asserts the SPMD path is taken — no single-device fallback —
with a pandas oracle on the results.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.execution import spmd
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col, count, sum_


@pytest.fixture()
def session(tmp_system_path):
    s = hst.Session(system_path=tmp_system_path)
    # Gate off: these fixtures are deliberately small meshes.
    s.conf.set(IndexConstants.TPU_DISTRIBUTED_MIN_STREAM_ROWS, "0")
    return s


def test_million_groups_no_fallback(session, tmp_path):
    rng = np.random.default_rng(31)
    # 1.05M distinct keys guaranteed (arange) plus 150k repeats drawn
    # from a hot range so the aggregation is not a pure identity.
    k = np.concatenate([np.arange(1_050_000, dtype=np.int64),
                        rng.integers(0, 1000, 150_000).astype(np.int64)])
    rng.shuffle(k)
    n = len(k)
    v = rng.integers(0, 100, n).astype(np.int64)
    t = pa.table({"k": pa.array(k), "v": pa.array(v)})
    d = tmp_path / "big"
    d.mkdir()
    pq.write_table(t, str(d / "p.parquet"))

    df = session.read.parquet(str(d)).group_by("k").agg(
        sum_(col("v")).alias("sv"), count(None).alias("n"))
    before = spmd.DISPATCH_COUNT
    out = df.to_pandas()
    assert spmd.DISPATCH_COUNT == before + 1, \
        "grouped SPMD fell back below the group-capacity retry"

    ref = (pd.DataFrame({"k": k, "v": v}).groupby("k")
           .agg(sv=("v", "sum"), n=("v", "size")).reset_index())
    assert len(out) == len(ref) >= 950_000
    got = out.sort_values("k").reset_index(drop=True)
    want = ref.sort_values("k").reset_index(drop=True)
    pd.testing.assert_series_equal(got["k"], want["k"])
    pd.testing.assert_series_equal(got["sv"], want["sv"],
                                   check_dtype=False)
    pd.testing.assert_series_equal(got["n"], want["n"],
                                   check_dtype=False)


def test_overflow_retry_is_single_shot(session, tmp_path):
    """A shape just past the floor: the retry fires once and succeeds
    (observable through the result; a second overflow would raise and
    fall back, failing the dispatch assertion)."""
    n = 150_000
    k = np.arange(n, dtype=np.int64)  # every row its own group per shard
    t = pa.table({"k": pa.array(k),
                  "v": pa.array(np.ones(n, dtype=np.int64))})
    d = tmp_path / "edge"
    d.mkdir()
    pq.write_table(t, str(d / "p.parquet"))
    df = session.read.parquet(str(d)).group_by("k").agg(
        count(None).alias("n"))
    before = spmd.DISPATCH_COUNT
    out = df.to_pandas()
    assert spmd.DISPATCH_COUNT == before + 1
    assert len(out) == n and (out["n"] == 1).all()
