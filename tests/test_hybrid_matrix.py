"""Hybrid Scan matrix: {appends, deletes, appends+deletes} ×
{flat parquet, hive-partitioned, delta, iceberg} (VERDICT r2 #8; parity:
HybridScanSuite 741 LoC + its ForPartitionedData / ForDeltaLake /
ForIceberg variants).

Every cell asserts (a) the rewrite kept the index with the right hybrid
state attached (appended_files / deleted_file_ids on the IndexScan), and
(b) results equal the source-scan run (disable-and-compare).
"""

import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.lake.delta import DeltaTable
from hyperspace_tpu.lake.iceberg import IcebergTable
from hyperspace_tpu.plan.expr import col, count, sum_
from hyperspace_tpu.plan.nodes import IndexScan


N_BASE = 1200
N_EXTRA = 150


def _frame(lo, hi, seed=1):
    rng = np.random.default_rng(seed)
    n = hi - lo
    return pd.DataFrame({
        "k": np.arange(lo, hi, dtype=np.int64),
        "grp": (np.arange(lo, hi) % 17).astype(np.int64),
        "v": np.round(rng.uniform(0, 100, n), 3),
    })


class _FlatSource:
    """Plain parquet directory, many part files (deletes must stay under
    the 0.2 byte-ratio threshold)."""

    name = "flat"

    def __init__(self, tmp_path, session):
        self.dir = tmp_path / "flat"
        self.dir.mkdir()
        self.session = session
        base = _frame(0, N_BASE)
        step = N_BASE // 8
        for i in range(8):
            pq.write_table(
                pa.Table.from_pandas(
                    base.iloc[i * step:(i + 1) * step].reset_index(drop=True)),
                self.dir / f"part{i}.parquet")
        self.frame = base

    def read(self):
        return self.session.read.parquet(str(self.dir))

    def append(self):
        extra = _frame(N_BASE, N_BASE + N_EXTRA, seed=2)
        pq.write_table(pa.Table.from_pandas(extra),
                       self.dir / "extra.parquet")
        self.frame = pd.concat([self.frame, extra], ignore_index=True)

    def delete(self):
        victim = self.dir / "part0.parquet"
        kept = pd.read_parquet(victim)
        os.remove(victim)
        self.frame = self.frame.merge(kept, how="outer", indicator=True) \
            .query("_merge == 'left_only'").drop(columns="_merge")


class _PartitionedSource:
    name = "partitioned"

    def __init__(self, tmp_path, session):
        self.dir = tmp_path / "hive"
        self.session = session
        base = _frame(0, N_BASE)
        frames = []
        for region in range(6):
            sub = base[base.grp % 6 == region].reset_index(drop=True)
            d = self.dir / f"region={region}"
            d.mkdir(parents=True)
            pq.write_table(pa.Table.from_pandas(sub), d / "part0.parquet")
            frames.append(sub.assign(region=region))
        self.frame = pd.concat(frames, ignore_index=True)

    def read(self):
        return self.session.read.parquet(str(self.dir))

    def append(self):
        extra = _frame(N_BASE, N_BASE + N_EXTRA, seed=2)
        d = self.dir / "region=6"
        d.mkdir()
        pq.write_table(pa.Table.from_pandas(extra), d / "part0.parquet")
        self.frame = pd.concat([self.frame, extra.assign(region=6)],
                               ignore_index=True)

    def delete(self):
        victim = self.dir / "region=0" / "part0.parquet"
        os.remove(victim)
        self.frame = self.frame[self.frame.region != 0]


class _DeltaSource:
    name = "delta"

    def __init__(self, tmp_path, session):
        self.path = str(tmp_path / "delta_t")
        self.session = session
        base = _frame(0, N_BASE)
        self.table = DeltaTable(self.path)
        self.table.create(pa.Table.from_pandas(base),
                          max_rows_per_file=N_BASE // 8)
        self._base_files = list(self.table.snapshot().file_paths)
        self.frame = base

    def read(self):
        return self.session.read.delta(self.path)

    def append(self):
        extra = _frame(N_BASE, N_BASE + N_EXTRA, seed=2)
        self.table.append(pa.Table.from_pandas(extra))
        self.frame = pd.concat([self.frame, extra], ignore_index=True)

    def delete(self):
        victim = self._base_files[0]  # always a pre-index file
        kept = pq.read_table(victim).to_pandas()
        self.table.remove_files([victim])
        self.frame = self.frame.merge(kept, how="outer", indicator=True) \
            .query("_merge == 'left_only'").drop(columns="_merge")


class _IcebergSource:
    name = "iceberg"

    def __init__(self, tmp_path, session):
        self.path = str(tmp_path / "ice_t")
        self.session = session
        base = _frame(0, N_BASE)
        self.table = IcebergTable(self.path)
        self.table.create(pa.Table.from_pandas(base),
                          max_rows_per_file=N_BASE // 8)
        self._base_files = list(self.table.snapshot().file_paths)
        self.frame = base

    def read(self):
        return self.session.read.iceberg(self.path)

    def append(self):
        extra = _frame(N_BASE, N_BASE + N_EXTRA, seed=2)
        self.table.append(pa.Table.from_pandas(extra))
        self.frame = pd.concat([self.frame, extra], ignore_index=True)

    def delete(self):
        victim = self._base_files[0]  # always a pre-index file
        kept = pq.read_table(victim).to_pandas()
        self.table.remove_files([victim])
        self.frame = self.frame.merge(kept, how="outer", indicator=True) \
            .query("_merge == 'left_only'").drop(columns="_merge")


_SOURCES = {
    "flat": _FlatSource,
    "partitioned": _PartitionedSource,
    "delta": _DeltaSource,
    "iceberg": _IcebergSource,
}


@pytest.fixture()
def session(tmp_system_path):
    s = hst.Session(system_path=tmp_system_path)
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    s.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, "true")
    s.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
    return s


def _index_leaf(q, name):
    for l in q.optimized_plan().collect_leaves():
        if isinstance(l, IndexScan) and l.index_entry.name == name:
            return l
    return None


def _filter_query(src):
    return src.read().filter(col("k").between(100, 900)).select("k", "v")


def _agg_query(src):
    return (src.read().filter(col("v") < 80).group_by("grp")
            .agg(sum_(col("v")).alias("sv"), count(None).alias("n")))


def _check_answers(session, q, oracle: pd.DataFrame, key):
    got = q.to_pandas()
    session.disable_hyperspace()
    without = q.to_pandas()
    session.enable_hyperspace()
    g = got.sort_values(key).reset_index(drop=True)
    w = without.sort_values(key).reset_index(drop=True)
    o = oracle.sort_values(key).reset_index(drop=True)
    pd.testing.assert_frame_equal(g, w, check_dtype=False)
    pd.testing.assert_frame_equal(g, o, check_dtype=False)


@pytest.mark.parametrize("source_kind", list(_SOURCES))
@pytest.mark.parametrize("mutation", ["append", "delete", "append+delete"])
class TestHybridScanMatrix:
    def test_cell(self, session, tmp_path, source_kind, mutation):
        src = _SOURCES[source_kind](tmp_path, session)
        hs = Hyperspace(session)
        hs.create_index(src.read(),
                        IndexConfig("mIdx", ["k"], ["v", "grp"]))
        if "append" in mutation:
            src.append()
        if "delete" in mutation:
            src.delete()
        session.enable_hyperspace()

        q = _filter_query(src)
        leaf = _index_leaf(q, "mIdx")
        assert leaf is not None, "hybrid scan rejected the index"
        if "append" in mutation:
            assert leaf.appended_files, "appended files not attached"
        if "delete" in mutation:
            assert leaf.deleted_file_ids, "deleted ids not attached"

        f = src.frame
        oracle = f[(f.k >= 100) & (f.k <= 900)][["k", "v"]]
        _check_answers(session, q, oracle, ["k", "v"])

        # Aggregate over the same hybrid state.
        qa = _agg_query(src)
        oracle_a = f[f.v < 80].groupby("grp").agg(
            sv=("v", "sum"), n=("v", "size")).reset_index()
        _check_answers(session, qa, oracle_a, ["grp"])
