"""The driver-facing bench artifact contract (VERDICT r4 weak #1).

BENCH_r04.json came back ``parsed: null`` because the per-program
compile-log banks flooded the final JSON line past the driver's stdout
tail-capture window, losing the head fields (backend, filter speedup,
build rate). The contract tested here: the ONE emitted line always
parses, stays under a hard size bound, and keeps the essential fields
no matter how much debug state the run banked — the unbounded arrays
move to a sidecar file referenced from the line.
"""

import importlib.util
import json
import os
import sys

import pytest

_BENCH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench.py")


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location("hs_bench_module", _BENCH)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["hs_bench_module"] = mod
    spec.loader.exec_module(mod)
    return mod


def _flooded_result():
    r = {
        "metric": "tpch_filter_wallclock_speedup_indexed_vs_scan",
        "value": 12.9,
        "unit": "x",
        "vs_baseline": 1.51,
        "backend": "tpu",
        "device": "TPU_0",
        "scale": 100.0,
        "index_build_s": 2341.7,
        "build_rows_per_s": 256000.0,
        "errors": ["phase q3: " + "x" * 2000] * 20,
    }
    # The round-4 killer: hundreds of compile-log lines across phases.
    for phase in ("build", "filter", "q3", "q17", "hybrid", "mesh"):
        r[f"compile_log_{phase}"] = [
            f"Compiling jit(_take_{i}) with global shapes ..." + "y" * 200
            for i in range(200)
        ]
    return r


def test_final_line_parses_and_is_bounded(bench, tmp_path, monkeypatch):
    monkeypatch.setenv("BENCH_DEBUG_PATH", str(tmp_path / "debug.json"))
    line = bench._final_line(_flooded_result())
    assert "\n" not in line
    assert len(line) <= bench._FINAL_LINE_MAX
    parsed = json.loads(line)
    # Head fields the driver reads must survive any debug flood.
    for key in ("metric", "value", "unit", "vs_baseline", "backend",
                "index_build_s", "build_rows_per_s"):
        assert key in parsed, key
    # Raw compile logs are gone from the line; counts remain.
    assert not any(k.startswith("compile_log_") for k in parsed)
    assert parsed["compile_counts"]["q3"] == 200
    # Errors are capped in count and per-entry length.
    assert len(parsed["errors"]) <= 8
    assert all(len(e) <= 500 for e in parsed["errors"])


def test_sidecar_keeps_full_debug(bench, tmp_path, monkeypatch):
    debug_path = tmp_path / "debug.json"
    monkeypatch.setenv("BENCH_DEBUG_PATH", str(debug_path))
    line = bench._final_line(_flooded_result())
    parsed = json.loads(line)
    assert parsed["debug_file"] == str(debug_path)
    with open(debug_path) as f:
        sidecar = json.load(f)
    assert len(sidecar["compile_log_q3"]) == 200
    assert len(sidecar["errors_full"]) == 20


def test_small_result_passes_through_unchanged(bench, tmp_path, monkeypatch):
    monkeypatch.setenv("BENCH_DEBUG_PATH", str(tmp_path / "debug.json"))
    r = {"metric": "m", "value": 1.0, "unit": "x", "vs_baseline": 1.0,
         "errors": []}
    parsed = json.loads(bench._final_line(r))
    assert parsed["value"] == 1.0
    assert "debug_file" not in parsed
    assert "compile_counts" not in parsed


def test_nonfinite_floats_become_null(bench, tmp_path, monkeypatch):
    """inf/nan serialize as Infinity/NaN, which strict JSON parsers (the
    driver's) reject — they must be nulled, not emitted."""
    monkeypatch.setenv("BENCH_DEBUG_PATH", str(tmp_path / "debug.json"))
    r = {"metric": "m", "value": float("inf"), "unit": "x",
         "vs_baseline": float("nan"), "errors": [],
         "mesh": {"speedup": float("-inf"), "ok": 2.0}}
    line = bench._final_line(r)
    json.loads(line, parse_constant=lambda c: pytest.fail(
        f"non-standard JSON constant {c} in final line"))
    parsed = json.loads(line)
    assert parsed["value"] is None
    assert parsed["mesh"] == {"speedup": None, "ok": 2.0}


def test_oversize_string_fields_are_capped(bench, tmp_path, monkeypatch):
    """No single string value — essential or not — may threaten the line
    bound; strings are capped at ingest (2000 chars)."""
    monkeypatch.setenv("BENCH_DEBUG_PATH", str(tmp_path / "debug.json"))
    r = {"metric": "m" * 50000, "value": 1.0, "unit": "x",
         "vs_baseline": 1.0, "errors": [], "backend_probe": "y" * 60000,
         "index_build_s": 5.0}
    line = bench._final_line(r)
    assert len(line) <= bench._FINAL_LINE_MAX
    parsed = json.loads(line)
    assert len(parsed["backend_probe"]) <= 2000
    assert len(parsed["metric"]) <= 2000
    assert parsed["index_build_s"] == 5.0  # head fields survive


def test_oversize_scalar_free_result_still_bounded(bench, tmp_path,
                                                   monkeypatch):
    """Even without compile_log_* keys, any list/dict flood must be moved
    aside rather than breaking the size bound."""
    monkeypatch.setenv("BENCH_DEBUG_PATH", str(tmp_path / "debug.json"))
    r = {"metric": "m", "value": 1.0, "unit": "x", "vs_baseline": 1.0,
         "errors": [],
         "giant_debug": ["z" * 400] * 200,
         "mesh": {"build_rows_per_s": 639000.0}}
    line = bench._final_line(r)
    assert len(line) <= bench._FINAL_LINE_MAX
    parsed = json.loads(line)
    assert parsed["value"] == 1.0
    assert "giant_debug" not in parsed
