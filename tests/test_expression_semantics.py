"""Expression-level SQL semantics: three-valued logic, null propagation,
and null handling in aggregates/joins/distinct — the evaluator's contract
(execution/evaluator.py: "a comparison touching a null evaluates to null,
and Filter keeps only rows whose predicate is true-and-valid").

Parity: the reference inherits these semantics from Spark SQL; its E2E
suites assert them implicitly through checkAnswer. Here they are pinned
explicitly against pandas/pyarrow oracles so an engine regression cannot
hide behind a passing rewrite test.
"""

import datetime

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.plan.expr import avg, col, count, lit, max_, min_, sum_


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    root = tmp_path_factory.mktemp("exprsem")
    d = root / "t"
    d.mkdir()
    # Hand-built rows: every null interaction shape appears at least once.
    a = pa.array([1, 2, None, 4, None, 6, 7, None], type=pa.int64())
    b = pa.array([10, None, 30, None, 50, 60, None, 80], type=pa.int64())
    f = pa.array([1.5, None, 3.5, 4.5, None, 6.5, 7.5, None],
                 type=pa.float64())
    s = pa.array(["x", "y", None, "x", None, "z", "y", None],
                 type=pa.string())
    dt = pa.array([datetime.date(1995, 1, 1), None,
                   datetime.date(1995, 3, 1), datetime.date(1995, 4, 1),
                   None, datetime.date(1995, 6, 1),
                   datetime.date(1995, 7, 1), None], type=pa.date32())
    pq.write_table(pa.table({"a": a, "b": b, "f": f, "s": s, "dt": dt}),
                   d / "p0.parquet")
    session = hst.Session(system_path=str(root / "idx"))
    return session, str(d)


def rows(df, *cols):
    t = df.to_arrow()
    out = list(zip(*[t.column(c).to_pylist() for c in cols])) if cols else []
    return out


class TestComparisonNulls:
    """A comparison touching null is null → the row is dropped by Filter."""

    def test_gt_drops_null_operands(self, env):
        session, d = env
        df = session.read.parquet(d)
        got = rows(df.filter(col("a") > 1).select("a"), "a")
        assert got == [(2,), (4,), (6,), (7,)]

    def test_eq_null_never_matches(self, env):
        session, d = env
        df = session.read.parquet(d)
        # a == a is TRUE for non-null rows only; null == null is null.
        got = rows(df.filter(col("a") == col("a")).select("a"), "a")
        assert got == [(1,), (2,), (4,), (6,), (7,)]

    def test_between_drops_nulls(self, env):
        session, d = env
        df = session.read.parquet(d)
        got = rows(df.filter(col("f").between(2.0, 7.0)).select("f"), "f")
        assert got == [(3.5,), (4.5,), (6.5,)]

    def test_string_comparison_nulls(self, env):
        session, d = env
        df = session.read.parquet(d)
        got = rows(df.filter(col("s") >= "y").select("s"), "s")
        assert got == [("y",), ("z",), ("y",)]

    def test_date_comparison_nulls(self, env):
        session, d = env
        df = session.read.parquet(d)
        got = rows(df.filter(col("dt") < datetime.date(1995, 4, 1))
                   .select("a"), "a")
        assert got == [(1,), (None,)]


class TestThreeValuedLogic:
    def test_and_null_false_is_false_dropped(self, env):
        # (null AND false)=false, (null AND true)=null: both rows dropped,
        # but for different reasons — only rows TRUE on both legs survive.
        session, d = env
        df = session.read.parquet(d)
        got = rows(df.filter((col("a") > 0) & (col("b") > 0)).select(
            "a", "b"), "a", "b")
        assert got == [(1, 10), (6, 60)]

    def test_or_null_true_is_true_kept(self, env):
        # (null OR true)=true: a row with a null leg survives if the other
        # leg is true. Row (2, None): a>5 false, b... null → null → drop;
        # row (None, 50): a>5 null, b>40 true → keep.
        session, d = env
        df = session.read.parquet(d)
        got = rows(df.filter((col("a") > 5) | (col("b") > 40)).select(
            "a", "b"), "a", "b")
        assert got == [(None, 50), (6, 60), (7, None), (None, 80)]

    def test_not_null_is_null_dropped(self, env):
        # NOT(null) = null: rows where a is null stay dropped under ~.
        session, d = env
        df = session.read.parquet(d)
        got = rows(df.filter(~(col("a") > 2)).select("a"), "a")
        assert got == [(1,), (2,)]

    def test_isin_with_null_value(self, env):
        session, d = env
        df = session.read.parquet(d)
        got = rows(df.filter(col("a").isin([1, 7])).select("a"), "a")
        assert got == [(1,), (7,)]
        got = rows(df.filter(~col("a").isin([1, 7])).select("a"), "a")
        assert got == [(2,), (4,), (6,)]  # nulls in neither side


class TestArithmeticNullPropagation:
    def test_add_propagates_null(self, env):
        session, d = env
        df = session.read.parquet(d)
        got = rows(df.select((col("a") + col("b")).alias("ab")), "ab")
        assert got == [(11,), (None,), (None,), (None,), (None,), (66,),
                       (None,), (None,)]

    def test_mul_with_literal_keeps_null(self, env):
        session, d = env
        df = session.read.parquet(d)
        got = rows(df.select((col("f") * 2).alias("f2")), "f2")
        assert got == [(3.0,), (None,), (7.0,), (9.0,), (None,), (13.0,),
                       (15.0,), (None,)]

    def test_div_propagates_null(self, env):
        session, d = env
        df = session.read.parquet(d)
        got = rows(df.select((col("b") / col("a")).alias("q")), "q")
        assert got == [(10.0,), (None,), (None,), (None,), (None,),
                       (10.0,), (None,), (None,)]

    def test_filter_on_derived_null_drops(self, env):
        session, d = env
        df = session.read.parquet(d)
        got = rows(df.with_column("ab", col("a") + col("b"))
                   .filter(col("ab") > 0).select("ab"), "ab")
        assert got == [(11,), (66,)]


class TestAggregateNulls:
    def test_global_aggs_skip_nulls(self, env):
        session, d = env
        df = session.read.parquet(d)
        t = df.agg(sum_(col("a")).alias("sa"),
                   count(col("a")).alias("ca"),
                   count(None).alias("cn"),
                   avg(col("f")).alias("af"),
                   min_(col("b")).alias("mb"),
                   max_(col("b")).alias("xb")).to_arrow()
        assert t.column("sa").to_pylist() == [20]     # 1+2+4+6+7
        assert t.column("ca").to_pylist() == [5]      # non-null a
        assert t.column("cn").to_pylist() == [8]      # count(*) counts all
        assert t.column("af").to_pylist() == [pytest.approx(4.7)]
        assert t.column("mb").to_pylist() == [10]
        assert t.column("xb").to_pylist() == [80]

    def test_grouped_aggs_skip_null_values_keep_null_groups(self, env):
        session, d = env
        df = session.read.parquet(d)
        t = (df.group_by("s")
             .agg(sum_(col("a")).alias("sa"), count(col("a")).alias("ca"))
             .sort("s").to_arrow())
        # Null group first (engine sorts nulls first ascending). SUM over a
        # group whose every value is null is NULL (SQL standard); COUNT is 0.
        assert t.column("s").to_pylist() == [None, "x", "y", "z"]
        assert t.column("sa").to_pylist() == [None, 5, 9, 6]
        assert t.column("ca").to_pylist() == [0, 2, 2, 1]

    def test_empty_input_count_is_zero(self, env):
        session, d = env
        df = session.read.parquet(d)
        t = (df.filter(~(col("s") == col("s")))  # keep nothing non-null
             .agg(count(col("a")).alias("c")).to_arrow())
        assert t.column("c").to_pylist() == [0]


class TestJoinDistinctUnionNulls:
    def test_join_null_keys_never_match(self, env, tmp_path):
        session, d = env
        other = tmp_path / "r"
        other.mkdir()
        pq.write_table(pa.table({
            "k": pa.array([1, None, 7, 9], type=pa.int64()),
            "v": pa.array([100, 200, 700, 900], type=pa.int64()),
        }), other / "p0.parquet")
        df = session.read.parquet(d)
        r = session.read.parquet(str(other))
        got = rows(df.join(r, on=col("a") == col("k")).select("a", "v"),
                   "a", "v")
        assert sorted(got) == [(1, 100), (7, 700)]

    def test_left_outer_null_keys_padded_not_matched(self, env, tmp_path):
        session, d = env
        other = tmp_path / "r2"
        other.mkdir()
        pq.write_table(pa.table({
            "k": pa.array([1, None], type=pa.int64()),
            "v": pa.array([100, 200], type=pa.int64()),
        }), other / "p0.parquet")
        df = session.read.parquet(d)
        r = session.read.parquet(str(other))
        got = rows(df.join(r, on=col("a") == col("k"), how="left")
                   .select("a", "v"), "a", "v")
        # Every left row survives; only a=1 matches. Null left keys padded.
        assert sorted(got, key=lambda x: (x[0] is None, x)) == \
            [(1, 100), (2, None), (4, None), (6, None), (7, None),
             (None, None), (None, None), (None, None)]

    def test_distinct_keeps_one_null_row(self, env):
        session, d = env
        df = session.read.parquet(d)
        got = rows(df.select("s").distinct().sort("s"), "s")
        assert got == [(None,), ("x",), ("y",), ("z",)]

    def test_union_preserves_nulls(self, env):
        session, d = env
        df = session.read.parquet(d)
        u = df.select("a").union(df.select(col("b").alias("a")))
        t = u.to_arrow()
        vals = t.column("a").to_pylist()
        assert len(vals) == 16 and vals.count(None) == 6
