"""SQL ROLLUP/GROUPING, INTERSECT/EXCEPT, UNION-distinct, and STDDEV
(round 5 wave 2 — the constructs gating TPC-DS q5/q18/q22/q27/q36/q38/
q47/q57/q77/q86/q87 and the q17 family). Oracles are pandas
recomputations.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.exceptions import HyperspaceException


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    root = tmp_path_factory.mktemp("rollup")
    rng = np.random.default_rng(9)
    n = 500
    t = pa.table({
        "a": pa.array(rng.integers(0, 4, n).astype(np.int64)),
        "b": pa.array(rng.choice(["x", "y", "z"], n)),
        "v": pa.array(np.round(rng.uniform(0, 10, n), 2)),
    })
    d = root / "t"
    d.mkdir()
    pq.write_table(t, str(d / "p.parquet"))
    session = hst.Session(system_path=str(root / "idx"))
    session.create_temp_view("t", session.read.parquet(str(d)))
    return session, t.to_pandas()


def test_rollup_grouping_sets(env):
    session, pdf = env
    out = session.sql("""
        SELECT b, a, sum(v) sv, count(*) n,
               grouping(a) ga, grouping(b) gb
        FROM t GROUP BY ROLLUP (b, a) ORDER BY gb, ga, b, a
    """).to_pandas()
    fine = pdf.groupby(["b", "a"]).agg(sv=("v", "sum"), n=("v", "size"))
    n_b = pdf["b"].nunique()
    assert len(out) == len(fine) + n_b + 1
    # Finest set.
    finest = out[(out.ga == 0) & (out.gb == 0)]
    assert len(finest) == len(fine)
    np.testing.assert_allclose(sorted(finest["sv"]), sorted(fine["sv"]),
                               rtol=1e-9)
    # Per-b subtotals: a is NULL, grouping(a) = 1.
    sub = out[(out.ga == 1) & (out.gb == 0)]
    assert sub["a"].isna().all()
    np.testing.assert_allclose(
        sorted(sub["sv"]), sorted(pdf.groupby("b")["v"].sum()), rtol=1e-9)
    # Grand total.
    total = out[(out.ga == 1) & (out.gb == 1)]
    assert len(total) == 1 and total["b"].isna().all()
    assert abs(total["sv"].iloc[0] - pdf["v"].sum()) < 1e-6
    assert int(total["n"].iloc[0]) == len(pdf)


def test_rollup_with_avg_is_exact(env):
    """avg cannot be re-aggregated from the finest set — the lowering
    recomputes each grouping set from the pre-aggregation input."""
    session, pdf = env
    out = session.sql("""
        SELECT b, avg(v) av, grouping(b) gb
        FROM t GROUP BY ROLLUP (b) ORDER BY gb, b
    """).to_pandas()
    total = out[out.gb == 1]
    assert abs(total["av"].iloc[0] - pdf["v"].mean()) < 1e-9
    per_b = out[out.gb == 0].set_index("b")["av"]
    exp = pdf.groupby("b")["v"].mean()
    for k in exp.index:
        assert abs(per_b[k] - exp[k]) < 1e-9


def test_grouping_expression_item(env):
    """The q27 shape: grouping(a) + grouping(b) AS lochierarchy."""
    session, _ = env
    out = session.sql("""
        SELECT a, b, sum(v) sv, grouping(a) + grouping(b) lochierarchy
        FROM t GROUP BY ROLLUP (a, b)
        ORDER BY lochierarchy DESC, a, b
    """).to_pandas()
    assert out["lochierarchy"].iloc[0] == 2  # grand total first
    assert set(out["lochierarchy"]) == {0, 1, 2}


def test_intersect_and_except(env):
    session, pdf = env
    out = session.sql("""
        SELECT a FROM t WHERE v > 5 INTERSECT SELECT a FROM t WHERE v <= 5
        ORDER BY a
    """).to_pandas()
    exp = sorted(set(pdf[pdf.v > 5].a) & set(pdf[pdf.v <= 5].a))
    assert out["a"].tolist() == exp
    out = session.sql("""
        SELECT a, b FROM t EXCEPT SELECT a, b FROM t WHERE v < 9
        ORDER BY a, b
    """).to_pandas()
    have = set(map(tuple, pdf[["a", "b"]].itertuples(index=False)))
    minus = set(map(tuple, pdf[pdf.v < 9][["a", "b"]]
                    .itertuples(index=False)))
    assert sorted(map(tuple, out.itertuples(index=False))) == \
        sorted(have - minus)


def test_parenthesized_set_operands(env):
    """The q87 shape: (SELECT ...) EXCEPT (SELECT ...) wrapped as a
    derived table under count(*)."""
    session, pdf = env
    out = session.sql("""
        SELECT count(*) n FROM (
          (SELECT DISTINCT a, b FROM t)
          EXCEPT
          (SELECT DISTINCT a, b FROM t WHERE v < 5)
        ) cool
    """).to_pandas()
    have = set(map(tuple, pdf[["a", "b"]].itertuples(index=False)))
    minus = set(map(tuple, pdf[pdf.v < 5][["a", "b"]]
                    .itertuples(index=False)))
    assert int(out["n"].iloc[0]) == len(have - minus)


def test_union_distinct(env):
    session, pdf = env
    out = session.sql("""
        SELECT a FROM t WHERE v > 8 UNION SELECT a FROM t WHERE v < 2
        ORDER BY a
    """).to_pandas()
    exp = sorted(set(pdf[pdf.v > 8].a) | set(pdf[pdf.v < 2].a))
    assert out["a"].tolist() == exp


def test_stddev_samp(env):
    session, pdf = env
    out = session.sql(
        "SELECT b, stddev_samp(v) sd FROM t GROUP BY b ORDER BY b"
    ).to_pandas()
    exp = pdf.groupby("b")["v"].std()
    np.testing.assert_allclose(out.set_index("b")["sd"], exp, rtol=1e-9)
    # n = 1 group: NULL, not a division error.
    one = session.sql(
        "SELECT stddev_samp(v) sd FROM t WHERE v = (0 - 1)").to_pandas()
    assert one["sd"].isna().all() or len(one) == 1


def test_rollup_with_having_is_clear_error(env):
    session, _ = env
    with pytest.raises(HyperspaceException, match="HAVING with ROLLUP"):
        session.sql("SELECT a, sum(v) FROM t GROUP BY ROLLUP (a) "
                    "HAVING sum(v) > 0")


def test_rollup_under_window(env):
    """The q36/q86 shape: rank() over the rollup output, partitioned by
    the grouping flags."""
    session, pdf = env
    out = session.sql("""
        SELECT b, sum(v) sv, grouping(b) gb,
               rank() OVER (PARTITION BY grouping(b) ORDER BY sum(v) DESC)
               rk
        FROM t GROUP BY ROLLUP (b) ORDER BY gb, rk
    """).to_pandas()
    per_b = out[out.gb == 0]
    assert per_b["rk"].tolist() == list(range(1, len(per_b) + 1))
    assert per_b["sv"].is_monotonic_decreasing
    assert out[out.gb == 1]["rk"].tolist() == [1]
