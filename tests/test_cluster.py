"""Shared-nothing serving cluster (cluster/).

Acceptance contracts of the fleet tier:

- **Disabled is a hard no-op**: with ``cluster.enabled`` unset nothing
  binds a socket, no membership record is written, the router hook is
  one conf read, and results + metrics text are byte-identical to a
  build without the tier.
- **Routing degrades, never breaks**: an unreachable shard owner, a
  refused forward, or an injected ``cluster.forward`` fault falls back
  to local execution with identical bytes; an injected/failed
  ``cluster.broadcast`` costs one peer's standing-query firing, never
  the commit.
- **A real two-process fleet works**: two workers over one lake route
  submissions to the consistent-hash owner (byte-identical), a second
  submission is served from the OWNER's result cache across the wire,
  ONE commit fires standing queries on BOTH workers, and kill -9 of
  the owner mid-fleet degrades the next forward to local execution.
- **The ring moves ~1/N keys per membership change** (the consistent-
  hash contract that makes worker death invalidate one shard, not the
  whole placement).
"""

import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace
from hyperspace_tpu.cluster import gather, membership, transport, worker
from hyperspace_tpu.cluster.constants import ClusterConstants as CC
from hyperspace_tpu.cluster.hashring import HashRing
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col
from hyperspace_tpu.robustness import fault_names as FN
from hyperspace_tpu.robustness import faults
from hyperspace_tpu.robustness.faults import FaultRegistry
from hyperspace_tpu.serving.frontend import ServingFrontend
from hyperspace_tpu.telemetry import metric_names as MN
from hyperspace_tpu.telemetry import span_names as SN

from conftest import capture_logger as sink  # noqa: E402

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_cluster():
    yield
    worker.shutdown_for_tests()
    gather.reset_for_tests()
    from hyperspace_tpu.serving import frontend as fe_mod
    with fe_mod._DEFAULT_LOCK:
        fe_mod._DEFAULT = None


def _rng(seed=17):
    return np.random.default_rng(seed)


def _frame(rng, n):
    return pd.DataFrame({
        "k": rng.integers(0, 40, n).astype(np.int64),
        "v": rng.integers(0, 9, n).astype(np.int64)})


def _write_base(d, rng, n=2000):
    os.makedirs(d, exist_ok=True)
    pq.write_table(pa.Table.from_pandas(_frame(rng, n)),
                   os.path.join(d, "p0.parquet"))


def _session(tmp_path, capture=False, **conf):
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    if capture:
        session.conf.set(IndexConstants.EVENT_LOGGER_CLASS,
                         "tests.conftest.CaptureLogger")
        sink().events.clear()
    for key, value in conf.items():
        session.conf.set(key, value)
    return session


def _lake(tmp_path, capture=False, **conf):
    data = str(tmp_path / "tbl")
    _write_base(data, _rng())
    return _session(tmp_path, capture=capture, **conf), data


def _table_pd(table):
    host = table.to_host()
    return pd.DataFrame(
        {n: np.asarray(c.data) for n, c in host.columns.items()}
    ).sort_values(["k", "v"]).reset_index(drop=True)


def _plant_peer(session, wid, port):
    """A fresh-looking membership record for an unreachable worker."""
    root = membership.membership_dir(session)
    os.makedirs(root, exist_ok=True)
    now = time.time() * 1000.0
    with open(os.path.join(root, f"member-{wid}.json"), "w",
              encoding="utf-8") as f:
        f.write(json.dumps({
            "worker_id": wid, "host": "127.0.0.1", "port": port,
            "pid": 999999, "started_ms": now, "heartbeat_ms": now}))


def _variant_owned_by(session, data, node, owner_wid):
    """A plan variant whose cache-key digest the ring assigns to
    ``owner_wid`` under the current roster."""
    from hyperspace_tpu.serving.fingerprint import compute_key
    ids = [m.worker_id for m in node.membership.live_members()]
    t = session.read.parquet(data)
    for i in range(60):
        q = t.filter(col("k") < 3 + i).select("k", "v")
        key = compute_key(session, q.plan)
        if key is None:
            continue
        ring = HashRing(ids, vnodes=session.hs_conf.cluster_vnodes())
        if ring.owner(key.digest()) == owner_wid:
            return q
    raise AssertionError(f"no variant owned by {owner_wid}")


# ---------------------------------------------------------------------------
# Registries: names, events, ring.
# ---------------------------------------------------------------------------

class TestRegistries:
    def test_names_are_the_frozen_literals(self):
        assert SN.CLUSTER_FORWARD == "cluster.forward"
        assert SN.CLUSTER_BROADCAST == "cluster.broadcast"
        assert SN.CLUSTER_GATHER == "cluster.gather"
        assert FN.CLUSTER_FORWARD == "cluster.forward"
        assert FN.CLUSTER_BROADCAST == "cluster.broadcast"
        assert MN.COLLECTOR_CLUSTER == "cluster"

    def test_event_hierarchy(self):
        from hyperspace_tpu.telemetry.events import (
            ClusterBroadcastEvent, ClusterEvent, ClusterForwardEvent,
            ClusterJoinEvent, ClusterLeaveEvent, HyperspaceEvent)
        assert issubclass(ClusterEvent, HyperspaceEvent)
        for cls in (ClusterJoinEvent, ClusterLeaveEvent,
                    ClusterForwardEvent, ClusterBroadcastEvent):
            assert issubclass(cls, ClusterEvent)


class TestHashRing:
    def test_deterministic_and_total(self):
        ring = HashRing(["a", "b", "c"], vnodes=32)
        again = HashRing(["c", "b", "a"], vnodes=32)
        keys = [f"digest-{i}" for i in range(500)]
        owners = [ring.owner(k) for k in keys]
        assert owners == [again.owner(k) for k in keys]
        assert set(owners) == {"a", "b", "c"}

    def test_join_moves_about_one_over_n(self):
        keys = [f"digest-{i}" for i in range(2000)]
        before = HashRing(["w0", "w1", "w2", "w3"])
        after = HashRing(["w0", "w1", "w2", "w3", "w4"])
        moved = sum(1 for k in keys if before.owner(k) != after.owner(k))
        frac = moved / len(keys)
        # Expected 1/5; consistent hashing's whole point is that it is
        # nowhere near the naive (N-1)/N reshuffle.
        assert 0.08 <= frac <= 0.35, frac
        # Every moved key moved TO the joiner, never between survivors.
        assert all(after.owner(k) == "w4" for k in keys
                   if before.owner(k) != after.owner(k))

    def test_empty_ring_and_replica_walk(self):
        assert HashRing([]).owner("x") is None
        assert HashRing([]).owners("x", 2) == []
        ring = HashRing(["a", "b", "c"], vnodes=16)
        replicas = ring.owners("some-digest", 2)
        assert len(replicas) == 2 and len(set(replicas)) == 2
        assert replicas[0] == ring.owner("some-digest")
        assert set(ring.owners("some-digest", 99)) == {"a", "b", "c"}


# ---------------------------------------------------------------------------
# Transport.
# ---------------------------------------------------------------------------

class TestTransport:
    def test_round_trip_and_error_envelope(self):
        def handler(request):
            if request.get("boom"):
                raise ValueError("boom")
            return {"ok": True, "echo": request["x"]}

        srv = transport.Server("127.0.0.1", 0, handler, name="t")
        try:
            resp = transport.send_request(
                srv.host, srv.port, {"x": [1, "a", (2, 3)]})
            assert resp == {"ok": True, "echo": [1, "a", (2, 3)]}
            resp = transport.send_request(srv.host, srv.port,
                                          {"boom": True})
            assert resp["ok"] is False
            assert "ValueError: boom" in resp["error"]
        finally:
            srv.stop()

    def test_dead_port_raises_after_retries(self):
        srv = transport.Server("127.0.0.1", 0, lambda r: r, name="t")
        host, port = srv.host, srv.port
        srv.stop()
        time.sleep(0.05)
        with pytest.raises(OSError):
            transport.send_request(host, port, {"op": "ping"},
                                   timeout_s=0.5, attempts=2)

    def test_numpy_payload_survives_framing(self):
        srv = transport.Server(
            "127.0.0.1", 0,
            lambda r: {"ok": True, "twice": r["arr"] * 2}, name="t")
        try:
            arr = np.arange(12, dtype=np.int64).reshape(3, 4)
            resp = transport.send_request(srv.host, srv.port,
                                          {"arr": arr})
            np.testing.assert_array_equal(resp["twice"], arr * 2)
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# Membership.
# ---------------------------------------------------------------------------

class TestMembership:
    def test_register_heartbeat_expire_reclaim(self, tmp_path):
        session = _session(tmp_path)
        session.conf.set(CC.STALENESS_MS, "150")
        a = membership.Membership(session, "w-a", "127.0.0.1", 1111)
        a.register()
        # A second LIVE claimant of the same identity loses the race.
        dup = membership.Membership(session, "w-a", "127.0.0.1", 2222)
        with pytest.raises(FileExistsError):
            dup.register()
        b = membership.Membership(session, "w-b", "127.0.0.1", 3333)
        b.register()
        assert [m.worker_id for m in a.live_members()] == ["w-a", "w-b"]
        assert [m.worker_id for m in a.peers()] == ["w-b"]
        # b goes silent past the staleness horizon: routed around.
        time.sleep(0.2)
        a.heartbeat()
        assert [m.worker_id for m in a.live_members()] == ["w-a"]
        # ... and its corpse is reclaimable in place, not an error.
        b2 = membership.Membership(session, "w-b", "127.0.0.1", 4444)
        b2.register()
        assert [m.worker_id for m in b2.live_members()] == ["w-a", "w-b"]
        a.leave()
        assert [m.worker_id for m in b2.live_members()] == ["w-b"]

    def test_torn_record_skipped_not_fatal(self, tmp_path):
        session = _session(tmp_path)
        a = membership.Membership(session, "w-a", "127.0.0.1", 1111)
        a.register()
        root = membership.membership_dir(session)
        with open(os.path.join(root, "member-torn.json"), "w") as f:
            f.write('{"worker_id": "torn", "ho')  # torn mid-write
        assert [m.worker_id for m in a.live_members()] == ["w-a"]

    def test_heartbeat_daemon_refreshes(self, tmp_path):
        session = _session(tmp_path)
        session.conf.set(CC.HEARTBEAT_MS, "50")
        a = membership.Membership(session, "w-a", "127.0.0.1", 1111)
        a.register()
        first = a.live_members()[0].heartbeat_ms
        a.start_heartbeat()
        deadline = time.time() + 5.0
        while time.time() < deadline:
            members = a.live_members()
            if members and members[0].heartbeat_ms > first:
                break
            time.sleep(0.02)
        else:
            raise AssertionError("heartbeat never refreshed the record")
        a.leave()


# ---------------------------------------------------------------------------
# Gather shim.
# ---------------------------------------------------------------------------

class TestGather:
    def test_single_process_byte_identical_to_native(self):
        from jax.experimental import multihost_utils as mhu
        for x in (np.arange(6, dtype=np.int64),
                  np.arange(12, dtype=np.float32).reshape(3, 4),
                  np.array([b"ab", b"c"], dtype=object)):
            ours = gather.allgather(x)
            native = np.asarray(mhu.process_allgather(x))
            assert ours.shape == native.shape
            assert ours.dtype == native.dtype
            assert np.array_equal(ours, native)

    def test_threaded_three_rank_star(self, tmp_path):
        """Every rank of the owned host path gets the full rank-ordered
        stack — ranks run as threads so one process plays the fleet."""
        rdv = str(tmp_path / "rdv")
        parts = [np.full((4,), r, dtype=np.int64) for r in range(3)]
        out = [None] * 3
        errors = []

        def rank(r):
            try:
                out[r] = gather.host_allgather(
                    parts[r], rank=r, n=3, seq=1, rendezvous_dir=rdv,
                    timeout_s=30.0)
            except Exception as e:  # noqa: BLE001 — collected
                errors.append(f"rank {r}: {type(e).__name__}: {e}")

        threads = [threading.Thread(target=rank, args=(r,))
                   for r in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        expected = np.stack(parts)
        for r in range(3):
            np.testing.assert_array_equal(out[r], expected)

    def test_forced_mode_seam(self):
        gather.force_mode("host")
        try:
            assert gather._mode() == "host"
        finally:
            gather.force_mode(None)
        assert gather._mode() in ("auto", "native", "host")


# ---------------------------------------------------------------------------
# Disabled = hard no-op; fingerprint indifference.
# ---------------------------------------------------------------------------

class TestDisabledNoOp:
    def test_disabled_runs_local_and_writes_nothing(self, tmp_path):
        session, data = _lake(tmp_path)
        assert worker.get_node(session) is None
        assert worker.maybe_node() is None
        front = ServingFrontend(session)
        q = session.read.parquet(data).filter(col("k") == 7) \
            .select("k", "v")
        base = q.to_pandas().sort_values(["k", "v"]) \
            .reset_index(drop=True)
        table = front.submit(q).result(timeout=120.0)
        pd.testing.assert_frame_equal(_table_pd(table), base)
        # No membership dir, no worker label, no broadcast.
        assert not os.path.exists(
            os.path.join(session.hs_conf.system_path(), "_hst_cluster"))
        text = Hyperspace(session).metrics_text()
        assert "worker=" not in text
        assert worker.broadcast_commit(session, "tbl") == 0
        fleet = Hyperspace(session).fleet_metrics()
        assert set(fleet["workers"]) == {"local"}

    def test_config_hash_ignores_cluster_keys(self, tmp_path):
        from hyperspace_tpu.serving.fingerprint import config_hash
        plain = _session(tmp_path)
        tuned = _session(tmp_path)
        tuned.conf.set(CC.ENABLED, "true")
        tuned.conf.set(CC.WORKER_ID, "w-elsewhere")
        tuned.conf.set(CC.PORT, "12345")
        tuned.conf.set(CC.VNODES, "8")
        assert config_hash(plain) == config_hash(tuned)


# ---------------------------------------------------------------------------
# One enabled worker: lifecycle, metrics surfaces, degradation.
# ---------------------------------------------------------------------------

class TestSingleWorker:
    def _node(self, tmp_path, capture=False, **conf):
        session, data = _lake(tmp_path, capture=capture, **conf)
        session.conf.set(CC.ENABLED, "true")
        session.conf.set(CC.WORKER_ID, "w-solo")
        node = worker.get_node(session)
        assert node is not None
        return session, data, node

    def test_lifecycle_ping_and_metrics_surfaces(self, tmp_path):
        session, data, node = self._node(tmp_path, capture=True)
        assert node.worker_id == "w-solo"
        me = node.membership.live_members()[0]
        resp = transport.send_request(me.host, me.port, {"op": "ping"})
        assert resp == {"ok": True, "worker": "w-solo"}
        hs = Hyperspace(session)
        text = hs.metrics_text()
        assert 'worker="w-solo"' in text
        assert text.rstrip().endswith("# EOF")
        fleet = hs.fleet_metrics()
        assert set(fleet["workers"]) == {"w-solo"}
        assert fleet["aggregate"]
        snap = hs.metrics()
        assert snap["collectors"]["cluster"]["members"] == 1
        worker.shutdown_for_tests()
        names = [type(e).__name__ for e in sink().events]
        assert "ClusterJoinEvent" in names
        assert "ClusterLeaveEvent" in names

    def test_lonely_worker_serves_locally(self, tmp_path):
        session, data, node = self._node(tmp_path)
        front = ServingFrontend(session)
        q = session.read.parquet(data).filter(col("k") == 5) \
            .select("k", "v")
        base = q.to_pandas().sort_values(["k", "v"]) \
            .reset_index(drop=True)
        table = front.submit(q).result(timeout=120.0)
        pd.testing.assert_frame_equal(_table_pd(table), base)
        stats = node.stats()
        assert stats["forwarded"] == 0 and stats["forward_fallbacks"] == 0

    def test_unreachable_owner_falls_back_byte_identical(self, tmp_path):
        session, data, node = self._node(tmp_path, capture=True)
        session.conf.set(CC.FORWARD_TIMEOUT_MS, "300")
        _plant_peer(session, "w-gone", port=1)  # nothing listens on 1
        q = _variant_owned_by(session, data, node, "w-gone")
        base = q.to_pandas().sort_values(["k", "v"]) \
            .reset_index(drop=True)
        front = ServingFrontend(session)
        table = front.submit(q).result(timeout=120.0)
        pd.testing.assert_frame_equal(_table_pd(table), base)
        assert node.stats()["forward_fallbacks"] >= 1
        fwd = [e for e in sink().events
               if type(e).__name__ == "ClusterForwardEvent"]
        assert fwd and not fwd[0].ok

    def test_injected_forward_fault_falls_back(self, tmp_path):
        session, data, node = self._node(tmp_path)
        _plant_peer(session, "w-gone", port=1)
        q = _variant_owned_by(session, data, node, "w-gone")
        base = q.to_pandas().sort_values(["k", "v"]) \
            .reset_index(drop=True)
        front = ServingFrontend(session)
        before = node.stats()["forward_fallbacks"]
        reg = FaultRegistry.from_conf_specs(
            {FN.CLUSTER_FORWARD: "error:p=1"}, seed=7)
        with faults.scope(reg):
            table = front.submit(q).result(timeout=120.0)
        pd.testing.assert_frame_equal(_table_pd(table), base)
        assert node.stats()["forward_fallbacks"] == before + 1
        assert reg.hit_count(FN.CLUSTER_FORWARD) >= 1

    def test_broadcast_failure_and_fault_degrade(self, tmp_path):
        session, data, node = self._node(tmp_path, capture=True)
        session.conf.set(CC.FORWARD_TIMEOUT_MS, "300")
        _plant_peer(session, "w-gone", port=1)
        assert node.broadcast_commit("tbl") == 0  # unreachable peer
        assert node.stats()["broadcast_failures"] >= 1
        reg = FaultRegistry.from_conf_specs(
            {FN.CLUSTER_BROADCAST: "error:p=1"}, seed=7)
        before = node.stats()["broadcast_failures"]
        with faults.scope(reg):
            assert node.broadcast_commit("tbl") == 0
        assert node.stats()["broadcast_failures"] == before + 1
        assert reg.hit_count(FN.CLUSTER_BROADCAST) >= 1
        names = [type(e).__name__ for e in sink().events]
        assert "ClusterBroadcastEvent" in names


# ---------------------------------------------------------------------------
# The real thing: two worker processes over one lake.
# ---------------------------------------------------------------------------

_CHILD_SETUP = textwrap.dedent("""
    import json, os, sys, time
    import numpy as np
    import pandas as pd
    import hyperspace_tpu as hst
    from hyperspace_tpu.cluster import worker as cw
    from hyperspace_tpu.cluster.constants import ClusterConstants as CC
    from hyperspace_tpu.index.constants import IndexConstants
    from hyperspace_tpu.plan.expr import col
    from hyperspace_tpu.serving.constants import ServingConstants
    from hyperspace_tpu.serving.frontend import get_frontend

    LAKE, RUN, WID = sys.argv[1], sys.argv[2], sys.argv[3]
    DATA = os.path.join(LAKE, "tbl")
    session = hst.Session(system_path=os.path.join(LAKE, "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    session.conf.set(ServingConstants.SERVING_ENABLED, "true")
    session.conf.set(ServingConstants.RESULT_CACHE_ENABLED, "true")
    session.conf.set(ServingConstants.RESULT_CACHE_MIN_COMPUTE_SECONDS,
                     "0")
    session.conf.set(CC.ENABLED, "true")
    session.conf.set(CC.WORKER_ID, WID)
    session.conf.set(CC.HEARTBEAT_MS, "200")
    session.conf.set(CC.FORWARD_TIMEOUT_MS, "60000")

    def table_pd(table):
        host = table.to_host()
        return pd.DataFrame(
            {n: np.asarray(c.data) for n, c in host.columns.items()}
        ).sort_values(["k", "v"]).reset_index(drop=True)
""")

_OWNER_BODY = textwrap.dedent("""
    node = cw.get_node(session)
    fe = get_frontend(session)
    sub = fe.subscribe(session.read.parquet(DATA)
                       .filter(col("k") == 7).select("k", "v"))
    with open(os.path.join(RUN, "owner-ready"), "w") as f:
        f.write(json.dumps({"pid": os.getpid(),
                            "worker": node.worker_id}))
    deliveries = sub.wait_for(1, timeout=180.0)
    with open(os.path.join(RUN, "owner-fired"), "w") as f:
        f.write(str(len(deliveries)))
    while True:  # stay up to serve forwards until the client kills us
        time.sleep(0.2)
""")

_CLIENT_BODY = textwrap.dedent("""
    from hyperspace_tpu.api import Hyperspace
    from hyperspace_tpu.cluster.hashring import HashRing
    from hyperspace_tpu.serving.fingerprint import compute_key

    node = cw.get_node(session)
    fe = get_frontend(session)
    hs = Hyperspace(session)
    deadline = time.time() + 120
    while len(node.membership.live_members()) < 2:
        assert time.time() < deadline, "owner never joined the roster"
        time.sleep(0.05)

    def owned_variant(owner_wid):
        ids = [m.worker_id for m in node.membership.live_members()]
        t = session.read.parquet(DATA)
        for i in range(60):
            q = t.filter(col("k") < 3 + i).select("k", "v")
            key = compute_key(session, q.plan)
            if key is None:
                continue
            ring = HashRing(ids,
                            vnodes=session.hs_conf.cluster_vnodes())
            if ring.owner(key.digest()) == owner_wid:
                return q
        raise AssertionError("no variant owned by " + owner_wid)

    summary = {}
    q = owned_variant("w-owner")
    base = q.to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
    t1 = fe.submit(q).result(timeout=180.0)
    s1 = node.stats()
    summary["first_forwarded"] = s1["forwarded"] >= 1
    summary["first_was_execution"] = s1["forward_hits"] == 0
    summary["first_identical"] = table_pd(t1).equals(base)

    t2 = fe.submit(q).result(timeout=180.0)
    s2 = node.stats()
    summary["second_was_owner_cache_hit"] = s2["forward_hits"] >= 1
    summary["second_identical"] = table_pd(t2).equals(base)

    fleet = hs.fleet_metrics()
    summary["fleet_workers"] = sorted(fleet["workers"])
    owner_cl = (fleet["workers"].get("w-owner", {})
                .get("collectors", {}) or {}).get("cluster", {}) or {}
    summary["owner_counted_cache_hit"] = \\
        owner_cl.get("forward_cache_hits", 0) >= 1
    summary["worker_label"] = 'worker="w-client"' in hs.metrics_text()

    sub = fe.subscribe(session.read.parquet(DATA)
                       .filter(col("k") == 7).select("k", "v"))
    rng = np.random.default_rng(4)
    frame = pd.DataFrame(
        {"k": rng.integers(0, 40, 80).astype(np.int64),
         "v": rng.integers(0, 9, 80).astype(np.int64)})
    hs.append(DATA, frame)
    out = hs.commit(DATA)
    summary["local_fired"] = out.get("subscriptions_fired", 0) >= 1
    summary["local_delivered"] = len(sub.wait_for(1, timeout=120.0)) >= 1
    fired_path = os.path.join(RUN, "owner-fired")
    deadline = time.time() + 120
    while not os.path.exists(fired_path) and time.time() < deadline:
        time.sleep(0.1)
    summary["owner_fired"] = (
        os.path.exists(fired_path)
        and open(fired_path).read().strip() == "1")

    ready = json.loads(open(os.path.join(RUN, "owner-ready")).read())
    os.kill(ready["pid"], 9)
    time.sleep(0.3)
    q3 = owned_variant("w-owner")
    base3 = q3.to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
    t3 = fe.submit(q3).result(timeout=180.0)
    summary["fallback_counted"] = \\
        node.stats()["forward_fallbacks"] >= 1
    summary["fallback_identical"] = table_pd(t3).equals(base3)

    with open(os.path.join(RUN, "summary.json"), "w") as f:
        f.write(json.dumps(summary))
""")


class TestTwoWorkerFleet:
    def test_fleet_end_to_end(self, tmp_path):
        """Forwarded execution, cross-worker cache hit, fleet-wide
        standing-query firing from one commit, and kill -9 degradation
        — all over two REAL worker processes sharing one lake."""
        _write_base(str(tmp_path / "tbl"), _rng())
        run = str(tmp_path / "run")
        os.makedirs(run)
        owner_py = os.path.join(run, "owner_child.py")
        client_py = os.path.join(run, "client_child.py")
        with open(owner_py, "w") as f:
            f.write(_CHILD_SETUP + _OWNER_BODY)
        with open(client_py, "w") as f:
            f.write(_CHILD_SETUP + _CLIENT_BODY)
        env = dict(os.environ)
        env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        owner = subprocess.Popen(
            [sys.executable, owner_py, str(tmp_path), run, "w-owner"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        try:
            ready = os.path.join(run, "owner-ready")
            deadline = time.time() + 180
            while not os.path.exists(ready):
                if owner.poll() is not None:
                    raise AssertionError(
                        f"owner died early:\n{owner.stdout.read()}")
                assert time.time() < deadline, "owner never came up"
                time.sleep(0.1)
            client = subprocess.run(
                [sys.executable, client_py, str(tmp_path), run,
                 "w-client"],
                env=env, capture_output=True, text=True, timeout=600)
            assert client.returncode == 0, \
                f"client failed:\n{client.stdout}\n{client.stderr}"
            with open(os.path.join(run, "summary.json")) as f:
                summary = json.load(f)
            expected_true = [
                "first_forwarded", "first_was_execution",
                "first_identical", "second_was_owner_cache_hit",
                "second_identical", "owner_counted_cache_hit",
                "worker_label", "local_fired", "local_delivered",
                "owner_fired", "fallback_counted",
                "fallback_identical"]
            failed = [k for k in expected_true if summary.get(k) is not True]
            assert not failed, f"{failed}; summary={summary}"
            assert summary["fleet_workers"] == ["w-client", "w-owner"]
        finally:
            if owner.poll() is None:
                owner.kill()
            owner.wait(timeout=30)
