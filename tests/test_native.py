"""Native host-ops tests: the C++ library must agree bit-for-bit with the
numpy fallback and the original per-row Python probes."""

import datetime

import numpy as np
import pyarrow as pa
import pytest

from hyperspace_tpu import native
from hyperspace_tpu.execution.columnar import Table
from hyperspace_tpu.ops import sketches
from hyperspace_tpu.schema import DATE, FLOAT64, INT64, STRING


@pytest.fixture(scope="module")
def lib_available():
    if not native.available():
        pytest.skip("no C++ toolchain available")


@pytest.fixture(autouse=True)
def _enable_native_probe(monkeypatch):
    # The C++ probe loops are opt-in since round 5 (numpy measured
    # faster at every lake scale) and file-count-gated since round 7;
    # these tests exist to pin the C++ implementations against the
    # references, so force them past both gates.
    monkeypatch.setenv("HST_NATIVE_PROBE", "force")


def _bloom_rows(n_filters=40, num_bits=256, num_hashes=4, seed=0):
    """Per-filter bitsets built by the real device/host builder."""
    rng = np.random.default_rng(seed)
    rows, contents = [], []
    for i in range(n_filters):
        vals = rng.integers(0, 1000, 20).astype(np.int64)
        t = Table.from_arrow(pa.table({"v": pa.array(vals)}))
        rows.append(sketches.bloom_build(
            t.column("v"), num_bits, num_hashes).tobytes())
        contents.append(set(vals.tolist()))
    return rows, contents


class TestBloomProbeMany:
    def test_native_matches_reference_probe(self, lib_available):
        rows, contents = _bloom_rows()
        for value in (3, 57, 999, 123456):
            got = native.bloom_probe_many(rows, value, INT64, 256, 4)
            want = np.array([
                sketches.bloom_might_contain(
                    np.frombuffer(b, np.uint8), value, INT64, 256, 4)
                for b in rows])
            np.testing.assert_array_equal(got, want)
            # No false negatives ever.
            present = np.array([value in c for c in contents])
            assert np.all(got[present])

    def test_none_rows_kept(self, lib_available):
        rows, _ = _bloom_rows(n_filters=5)
        rows[2] = None
        got = native.bloom_probe_many(rows, 1, INT64, 256, 4)
        assert got[2]

    def test_fallback_agrees_with_native(self, lib_available, monkeypatch):
        rows, _ = _bloom_rows(n_filters=16, seed=3)
        with_native = native.bloom_probe_many(rows, 57, INT64, 256, 4)
        monkeypatch.setattr(native, "_lib", None)
        monkeypatch.setattr(native, "_lib_tried", True)
        without = native.bloom_probe_many(rows, 57, INT64, 256, 4)
        np.testing.assert_array_equal(with_native, without)


class TestMinMaxPrune:
    CASES = [
        ("EqualTo", 5), ("LessThan", 5), ("LessThanOrEqual", 1),
        ("GreaterThan", 9), ("GreaterThanOrEqual", 10)]

    def test_int_semantics(self, lib_available):
        lo = [1, None, 5, 8]
        hi = [4, None, 9, 10]
        for op, v in self.CASES:
            got = native.minmax_prune(lo, hi, op, v, INT64)
            assert got is not None and got[1]  # all-null row always kept.
            for i in (0, 2, 3):
                if op == "EqualTo":
                    want = lo[i] <= v <= hi[i]
                elif op == "LessThan":
                    want = lo[i] < v
                elif op == "LessThanOrEqual":
                    want = lo[i] <= v
                elif op == "GreaterThan":
                    want = hi[i] > v
                else:
                    want = hi[i] >= v
                assert got[i] == want, (op, v, i)

    def test_date_and_float(self, lib_available):
        d = datetime.date
        got = native.minmax_prune(
            [d(2020, 1, 1), d(2021, 1, 1)], [d(2020, 6, 1), d(2021, 6, 1)],
            "EqualTo", d(2020, 3, 1), DATE)
        np.testing.assert_array_equal(got, [True, False])
        got = native.minmax_prune([0.5, 2.5], [1.0, 3.0],
                                  "LessThan", 0.9, FLOAT64)
        np.testing.assert_array_equal(got, [True, False])

    def test_string_unsupported(self):
        assert native.minmax_prune(["a"], ["b"], "EqualTo", "a", STRING) is None

    def test_fractional_literal_on_int_column(self):
        """col < 5.5 must keep a file with min=5 (rows with value 5 match);
        int() truncation would wrongly prune it."""
        got = native.minmax_prune([5], [9], "LessThan", 5.5, INT64)
        np.testing.assert_array_equal(got, [True])
        got = native.minmax_prune([-9], [-4], "GreaterThan", -4.5, INT64)
        np.testing.assert_array_equal(got, [True])
        # Fractional equality matches no integer: prune stats-backed files,
        # keep all-null ones.
        got = native.minmax_prune([5, None], [9, None], "EqualTo", 5.5, INT64)
        np.testing.assert_array_equal(got, [False, True])
        # Fractional bounds that exclude: col < 4.5 ⇔ col <= 4 prunes min=5.
        got = native.minmax_prune([5], [9], "LessThan", 4.5, INT64)
        np.testing.assert_array_equal(got, [False])

    def test_out_of_int64_range_literals(self):
        """Literals beyond int64 must not wrap through c_int64."""
        got = native.minmax_prune([5], [9], "LessThan", 2**63, INT64)
        np.testing.assert_array_equal(got, [True])
        got = native.minmax_prune([5], [9], "GreaterThan", 2**63, INT64)
        np.testing.assert_array_equal(got, [False])
        got = native.minmax_prune([5, None], [9, None], "EqualTo", 2**70,
                                  INT64)
        np.testing.assert_array_equal(got, [False, True])
        got = native.minmax_prune([5], [9], "GreaterThan", -(2**70), INT64)
        np.testing.assert_array_equal(got, [True])
        got = native.minmax_prune([5], [9], "LessThan", float("inf"), INT64)
        np.testing.assert_array_equal(got, [True])
        got = native.minmax_prune([5], [9], "GreaterThan", float("inf"),
                                  INT64)
        np.testing.assert_array_equal(got, [False])

    def test_fallback_agrees(self, lib_available, monkeypatch):
        rng = np.random.default_rng(1)
        lo = rng.integers(0, 50, 200).tolist()
        hi = [l + int(d) for l, d in zip(lo, rng.integers(0, 30, 200))]
        for op, v in self.CASES:
            with_native = native.minmax_prune(lo, hi, op, v * 3, INT64)
            # A dedicated MonkeyPatch: undo() on the shared fixture
            # instance would also revert the autouse HST_NATIVE_PROBE=on,
            # turning the remaining iterations into numpy-vs-numpy.
            mp = pytest.MonkeyPatch()
            try:
                mp.setattr(native, "_lib", None)
                mp.setattr(native, "_lib_tried", True)
                without = native.minmax_prune(lo, hi, op, v * 3, INT64)
            finally:
                mp.undo()
            np.testing.assert_array_equal(with_native, without)


class TestDataSkippingWithNative:
    def test_e2e_prune_same_with_and_without_native(
            self, lib_available, tmp_system_path, tmp_path, monkeypatch):
        import pyarrow.parquet as pq

        import hyperspace_tpu as hst
        from hyperspace_tpu.api import (DataSkippingIndexConfig, Hyperspace,
                                        MinMaxSketch, BloomFilterSketch)
        from hyperspace_tpu.plan.expr import col

        d = tmp_path / "t"
        d.mkdir()
        for i in range(6):
            pq.write_table(pa.table({
                "k": pa.array(np.arange(i * 100, (i + 1) * 100, dtype=np.int64)),
                "v": pa.array(np.random.default_rng(i).uniform(0, 1, 100)),
            }), str(d / f"p{i}.parquet"))
        session = hst.Session(system_path=tmp_system_path)
        hs = Hyperspace(session)
        df = session.read.parquet(str(d))
        hs.create_index(df, DataSkippingIndexConfig(
            "sk", [MinMaxSketch("k"), BloomFilterSketch("k")]))
        session.enable_hyperspace()
        q = df.filter(col("k") == 250).select("k", "v")
        native_plan = q.optimized_plan().tree_string()
        res_native = q.to_arrow()
        monkeypatch.setattr(native, "_lib", None)
        monkeypatch.setattr(native, "_lib_tried", True)
        fallback_plan = q.optimized_plan().tree_string()
        res_fallback = q.to_arrow()
        assert native_plan == fallback_plan
        assert res_native.equals(res_fallback)
        session.disable_hyperspace()
        assert res_native.equals(q.to_arrow())
