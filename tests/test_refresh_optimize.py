"""Refresh (full/incremental/quick) + optimize lifecycle tests.

Parity: RefreshIndexTest.scala, OptimizeActionTest semantics, and the Hybrid
Scan interplay with quick refresh. Core oracle throughout is
disable-and-compare (results with the refreshed index == source-scan results).
"""

import datetime
import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.constants import IndexConstants, States
from hyperspace_tpu.ops.index_build import bucket_id_from_file
from hyperspace_tpu.plan.expr import col
from hyperspace_tpu.plan.nodes import IndexScan


def write_sample(root, name, df, parts=2):
    d = root / name
    d.mkdir(parents=True, exist_ok=True)
    step = max(1, len(df) // parts)
    for i in range(parts):
        chunk = df.iloc[i * step:(i + 1) * step if i < parts - 1 else len(df)]
        pq.write_table(pa.Table.from_pandas(chunk.reset_index(drop=True)),
                       d / f"part{i}.parquet")
    return str(d)


def make_df(n=800, seed=0):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "k": rng.integers(0, 200, n).astype(np.int64),
        "v": rng.integers(0, 1000, n).astype(np.int64),
        "d": [datetime.date(1995, 1, 1) + datetime.timedelta(days=int(x))
              for x in rng.integers(0, 365, n)],
    })


@pytest.fixture()
def env(tmp_path):
    base = make_df()
    path = write_sample(tmp_path, "data", base)
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    return dict(session=session, hs=Hyperspace(session), path=path,
                base=base, tmp=tmp_path)


def uses_index(df, name):
    return any(isinstance(l, IndexScan) and l.index_entry.name == name
               for l in df.optimized_plan().collect_leaves())


def check_disable_and_compare(session, df):
    session.enable_hyperspace()
    with_index = df.to_pandas()
    session.disable_hyperspace()
    without = df.to_pandas()
    session.enable_hyperspace()
    a = with_index.sort_values(list(with_index.columns)).reset_index(drop=True)
    b = without.sort_values(list(without.columns)).reset_index(drop=True)
    pd.testing.assert_frame_equal(a, b, check_dtype=False)
    return with_index


def append_file(env, df, name="extra.parquet"):
    pq.write_table(pa.Table.from_pandas(df.reset_index(drop=True)),
                   env["tmp"] / "data" / name)


class TestRefreshFull:
    def test_full_refresh_after_append(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("fIdx", ["k"], ["v"]))
        extra = make_df(100, seed=9)
        append_file(env, extra)

        fresh = session.read.parquet(env["path"])
        q = fresh.filter(col("k") == 11).select("k", "v")
        session.enable_hyperspace()
        assert not uses_index(q, "fIdx")  # stale signature.

        hs.refresh_index("fIdx", "full")
        entry = hs.index_manager.get_index("fIdx")
        assert entry.state == States.ACTIVE
        assert entry.log_version == 1  # new data version dir.
        assert uses_index(q, "fIdx")
        out = check_disable_and_compare(session, q)
        all_rows = pd.concat([env["base"], extra])
        assert len(out) == (all_rows.k == 11).sum()

    def test_refresh_no_changes_is_noop(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("nIdx", ["k"], ["v"]))
        before = hs.index_manager.get_index("nIdx")
        hs.refresh_index("nIdx", "full")  # NoChangesException → quiet no-op.
        after = hs.index_manager.get_index("nIdx")
        assert after.id == before.id and after.state == States.ACTIVE

    def test_refresh_nonexistent_index_fails(self, env):
        hs = env["hs"]
        with pytest.raises(HyperspaceException):
            hs.refresh_index("ghost", "full")

    def test_refresh_bad_mode_fails(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("mIdx", ["k"], ["v"]))
        with pytest.raises(HyperspaceException):
            hs.refresh_index("mIdx", "sideways")


class TestRefreshIncremental:
    def test_incremental_append_only(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("iIdx", ["k"], ["v"]))
        extra = make_df(150, seed=10)
        append_file(env, extra)
        hs.refresh_index("iIdx", "incremental")

        entry = hs.index_manager.get_index("iIdx")
        assert entry.state == States.ACTIVE
        # Old + new index files coexist; buckets may hold several files.
        versions = {f.split("v__=")[1].split(os.sep)[0]
                    for f in entry.content.files}
        assert versions == {"0", "1"}

        fresh = session.read.parquet(env["path"])
        q = fresh.filter(col("k") == 11).select("k", "v")
        session.enable_hyperspace()
        assert uses_index(q, "iIdx")
        out = check_disable_and_compare(session, q)
        all_rows = pd.concat([env["base"], extra])
        assert len(out) == (all_rows.k == 11).sum()

    def test_incremental_with_deletes_requires_lineage(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("delIdx", ["k"], ["v"]))
        os.remove(os.path.join(env["path"], "part0.parquet"))
        with pytest.raises(HyperspaceException, match="lineage"):
            hs.refresh_index("delIdx", "incremental")

    def test_incremental_with_deletes(self, env):
        session, hs = env["session"], env["hs"]
        session.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("linIdx", ["k"], ["v"]))
        # Delete one source file, append another.
        os.remove(os.path.join(env["path"], "part0.parquet"))
        extra = make_df(120, seed=11)
        append_file(env, extra)
        hs.refresh_index("linIdx", "incremental")

        entry = hs.index_manager.get_index("linIdx")
        assert entry.state == States.ACTIVE

        fresh = session.read.parquet(env["path"])
        q = fresh.filter(col("k") < 40).select("k", "v")
        session.enable_hyperspace()
        assert uses_index(q, "linIdx")
        out = check_disable_and_compare(session, q)
        # part0 held the first half of base.
        kept = env["base"].iloc[len(env["base"]) // 2:]
        all_rows = pd.concat([kept, extra])
        assert len(out) == (all_rows.k < 40).sum()


class TestRefreshQuick:
    def test_quick_refresh_deletes_require_lineage(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("qnIdx", ["k"], ["v"]))
        os.remove(os.path.join(env["path"], "part0.parquet"))
        with pytest.raises(HyperspaceException, match="lineage"):
            hs.refresh_index("qnIdx", "quick")

    def test_quick_refresh_records_update_and_hybrid_scan_answers(self, env):
        session, hs = env["session"], env["hs"]
        session.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("qIdx", ["k"], ["v"]))
        os.remove(os.path.join(env["path"], "part1.parquet"))
        extra = make_df(60, seed=12)
        append_file(env, extra)
        hs.refresh_index("qIdx", "quick")

        entry = hs.index_manager.get_index("qIdx")
        assert entry.state == States.ACTIVE
        assert len(entry.appended_files) == 1
        assert len(entry.deleted_files) == 1
        # Index data untouched: only v__=0 files.
        assert all("v__=0" in f for f in entry.content.files)

        session.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, "true")
        # Generous thresholds: the deltas here are large fractions.
        session.conf.set(
            IndexConstants.INDEX_HYBRID_SCAN_APPENDED_RATIO_THRESHOLD, "0.99")
        session.conf.set(
            IndexConstants.INDEX_HYBRID_SCAN_DELETED_RATIO_THRESHOLD, "0.99")
        fresh = session.read.parquet(env["path"])
        q = fresh.filter(col("k") < 40).select("k", "v")
        session.enable_hyperspace()
        assert uses_index(q, "qIdx")
        out = check_disable_and_compare(session, q)
        kept = env["base"].iloc[:len(env["base"]) // 2]
        all_rows = pd.concat([kept, extra])
        assert len(out) == (all_rows.k < 40).sum()


class TestOptimize:
    def test_optimize_compacts_to_one_file_per_bucket(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("oIdx", ["k"], ["v"]))
        # Two incremental refreshes → up to 3 files per bucket.
        for seed in (20, 21):
            append_file(env, make_df(100, seed=seed), f"x{seed}.parquet")
            hs.refresh_index("oIdx", "incremental")
        entry = hs.index_manager.get_index("oIdx")
        buckets = [bucket_id_from_file(f) for f in entry.content.files]
        assert len(buckets) > len(set(buckets))  # multi-file buckets exist.

        hs.optimize_index("oIdx", "quick")
        entry = hs.index_manager.get_index("oIdx")
        buckets = [bucket_id_from_file(f) for f in entry.content.files]
        assert len(buckets) == len(set(buckets))  # compacted.

        # Rows within each compacted file are sorted by the indexed column.
        for f in entry.content.files:
            keys = pq.read_table(f).column("k").to_pylist()
            assert keys == sorted(keys)

        fresh = session.read.parquet(env["path"])
        q = fresh.filter(col("k") == 11).select("k", "v")
        session.enable_hyperspace()
        assert uses_index(q, "oIdx")
        check_disable_and_compare(session, q)

    def test_optimize_noop_when_single_files(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("o1Idx", ["k"], ["v"]))
        before = hs.index_manager.get_index("o1Idx")
        hs.optimize_index("o1Idx", "quick")  # nothing to compact → no-op.
        after = hs.index_manager.get_index("o1Idx")
        assert after.id == before.id

    def test_optimize_quick_skips_large_files(self, env):
        session, hs = env["session"], env["hs"]
        session.conf.set(IndexConstants.OPTIMIZE_FILE_SIZE_THRESHOLD, 1)
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("bigIdx", ["k"], ["v"]))
        append_file(env, make_df(100, seed=22))
        hs.refresh_index("bigIdx", "incremental")
        before = hs.index_manager.get_index("bigIdx")
        hs.optimize_index("bigIdx", "quick")  # all files above 1 byte → no-op.
        after = hs.index_manager.get_index("bigIdx")
        assert after.id == before.id
        # full mode compacts regardless of size.
        hs.optimize_index("bigIdx", "full")
        entry = hs.index_manager.get_index("bigIdx")
        buckets = [bucket_id_from_file(f) for f in entry.content.files]
        assert len(buckets) == len(set(buckets))
        fresh = session.read.parquet(env["path"])
        q = fresh.filter(col("k") == 3).select("k", "v")
        session.enable_hyperspace()
        check_disable_and_compare(session, q)
