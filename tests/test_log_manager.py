"""IndexLogManager semantics (parity: IndexLogManagerImplTest.scala)."""

import os

from hyperspace_tpu.index.constants import States
from hyperspace_tpu.index.data_manager import IndexDataManager
from hyperspace_tpu.index.log_manager import IndexLogManager

from test_log_entry import make_entry


class TestIndexLogManager:
    def test_write_and_get(self, tmp_path):
        mgr = IndexLogManager(str(tmp_path))
        entry = make_entry(state=States.CREATING)
        assert mgr.write_log(0, entry)
        got = mgr.get_log(0)
        assert got is not None and got.state == States.CREATING and got.id == 0

    def test_write_existing_id_fails(self, tmp_path):
        mgr = IndexLogManager(str(tmp_path))
        assert mgr.write_log(0, make_entry())
        assert not mgr.write_log(0, make_entry())

    def test_latest_id(self, tmp_path):
        mgr = IndexLogManager(str(tmp_path))
        assert mgr.get_latest_id() is None
        for i in (0, 1, 2):
            assert mgr.write_log(i, make_entry())
        assert mgr.get_latest_id() == 2
        assert mgr.get_latest_log().id == 2

    def test_latest_stable_backward_scan(self, tmp_path):
        mgr = IndexLogManager(str(tmp_path))
        e0 = make_entry(state=States.CREATING)
        e1 = make_entry(state=States.ACTIVE)
        e2 = make_entry(state=States.REFRESHING)
        for i, e in enumerate([e0, e1, e2]):
            assert mgr.write_log(i, e)
        stable = mgr.get_latest_stable_log()
        assert stable is not None and stable.state == States.ACTIVE and stable.id == 1

    def test_latest_stable_stops_at_creating(self, tmp_path):
        mgr = IndexLogManager(str(tmp_path))
        assert mgr.write_log(0, make_entry(state=States.CREATING))
        assert mgr.get_latest_stable_log() is None

    def test_create_latest_stable_log(self, tmp_path):
        mgr = IndexLogManager(str(tmp_path))
        assert mgr.write_log(0, make_entry(state=States.ACTIVE))
        assert mgr.create_latest_stable_log(0)
        stable = mgr.get_latest_stable_log()
        assert stable.state == States.ACTIVE
        # Non-stable id refused.
        assert mgr.write_log(1, make_entry(state=States.REFRESHING))
        assert not mgr.create_latest_stable_log(1)
        assert mgr.delete_latest_stable_log()
        # Falls back to backward scan after deletion.
        assert mgr.get_latest_stable_log().id == 0

    def test_get_index_versions(self, tmp_path):
        mgr = IndexLogManager(str(tmp_path))
        e0 = make_entry(state=States.ACTIVE).with_log_version(0)
        e1 = make_entry(state=States.REFRESHING).with_log_version(1)
        e2 = make_entry(state=States.ACTIVE).with_log_version(1)
        for i, e in enumerate([e0, e1, e2]):
            assert mgr.write_log(i, e)
        assert mgr.get_index_versions([States.ACTIVE]) == [1, 0]


class TestIndexDataManager:
    def test_versions(self, tmp_path):
        mgr = IndexDataManager(str(tmp_path))
        assert mgr.get_latest_version_id() is None
        os.makedirs(mgr.get_path(0))
        os.makedirs(mgr.get_path(3))
        assert mgr.get_all_version_ids() == [0, 3]
        assert mgr.get_latest_version_id() == 3
        mgr.delete(3)
        assert mgr.get_latest_version_id() == 0
