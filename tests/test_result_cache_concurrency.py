"""Result-cache invalidation races (the satellite of serving/result_cache).

No stale result may EVER be served: a cached entry's key pins the plan,
the source files (size/mtime/path), the index op-log state, and the conf
— so any interleaved `refreshIndex` / source append / index create must
make old entries unreachable. These tests interleave cached queries with
every mutating action (the deterministic oracle loop), race a real OS
process running a refresh against a querying parent (the
test_log_concurrency reader/writer pattern), and hammer the cache object
itself from threads (the serving access pattern).
"""

import multiprocessing as mp
import os
import threading

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col
from hyperspace_tpu.serving.constants import ServingConstants
from hyperspace_tpu.serving.fingerprint import ResultCacheKey
from hyperspace_tpu.serving.result_cache import ResultCache, table_nbytes


def _seed(tmp_path, n=4000):
    rng = np.random.default_rng(5)
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    df = pd.DataFrame({
        "k": rng.integers(0, 60, n).astype(np.int64),
        "v": rng.integers(0, 9, n).astype(np.int64),
    })
    pq.write_table(pa.Table.from_pandas(df), data_dir / "p.parquet")
    (tmp_path / "indexes").mkdir()
    return df


def _session(tmp_path, cache_on=True):
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    if cache_on:
        session.conf.set(ServingConstants.RESULT_CACHE_ENABLED, "true")
        session.conf.set(
            ServingConstants.RESULT_CACHE_MIN_COMPUTE_SECONDS, "0")
    return session


class TestInterleavedInvalidation:
    def test_oracle_loop_across_every_mutation(self, tmp_path):
        """Cached session vs cache-off oracle session over one dataset:
        after EVERY mutating step (append, create, incremental refresh,
        optimize, full refresh, delete) both sessions run the same fresh
        query and must agree — a stale serve would break equality."""
        _seed(tmp_path)
        cached = _session(tmp_path, cache_on=True)
        oracle = _session(tmp_path, cache_on=False)
        hs = Hyperspace(cached)
        data_dir = str(tmp_path / "data")

        def check(tag):
            q_c = cached.read.parquet(data_dir) \
                .filter(col("k") == 7).select("k", "v")
            q_o = oracle.read.parquet(data_dir) \
                .filter(col("k") == 7).select("k", "v")
            # Twice on the cached side: the second run exercises a hit
            # (or a just-invalidated miss). Serving must be byte-exact
            # between the two; the cross-session oracle compares row
            # MULTISETS (the query has no ORDER BY, and the two sessions
            # may legally pick different physical plans/row orders).
            a1, a2 = q_c.to_pandas(), q_c.to_pandas()
            expected = q_o.to_pandas()
            pd.testing.assert_frame_equal(a1, a2, obj=tag + "/hit")

            def canon(frame):
                return frame.sort_values(list(frame.columns)) \
                    .reset_index(drop=True)

            pd.testing.assert_frame_equal(canon(a1), canon(expected),
                                          obj=tag)

        def append(seed, n=500):
            rng = np.random.default_rng(seed)
            pq.write_table(pa.Table.from_pandas(pd.DataFrame({
                "k": rng.integers(0, 60, n).astype(np.int64),
                "v": rng.integers(0, 9, n).astype(np.int64)})),
                tmp_path / "data" / f"extra{seed}.parquet")

        check("baseline")
        append(1)
        check("after append")
        df = cached.read.parquet(data_dir)
        cached.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
        hs.create_index(df, IndexConfig("ccIdx", ["k"], ["v"]))
        cached.enable_hyperspace()
        oracle.enable_hyperspace()
        check("after create, enabled")
        append(2)
        hs.refresh_index("ccIdx", "incremental")
        check("after incremental refresh")
        hs.optimize_index("ccIdx", "quick")
        check("after optimize")
        append(3)
        hs.refresh_index("ccIdx", "full")
        check("after full refresh")
        hs.delete_index("ccIdx")
        check("after delete")
        stats = cached.result_cache.stats()
        assert stats["hits"] >= 1, stats  # the loop did exercise serving


class TestThreadSafety:
    def test_concurrent_get_put_under_pressure(self):
        """16 threads share one budget-constrained cache: no exceptions,
        byte accounting stays within budgets, counters reconcile."""
        from hyperspace_tpu.execution.columnar import Table

        def make(i):
            return Table.from_arrow(pa.table(
                {"x": pa.array(np.full(256, i, np.int64))}))

        tables = [make(i) for i in range(8)]
        nbytes = table_nbytes(tables[0])
        cache = ResultCache(device_bytes=3 * nbytes,
                            host_bytes=3 * nbytes)
        errors = []
        gets = 24 * 40

        def worker(tid):
            try:
                rng = np.random.default_rng(tid)
                for i in range(40):
                    key = ResultCacheKey(
                        f"p{int(rng.integers(0, 8))}", "s", (), "c")
                    r = cache.get(key)
                    if r is None:
                        cache.put(key, tables[int(rng.integers(0, 8))])
                    # A second probe mixes tiers while others evict.
                    cache.get(ResultCacheKey(
                        f"p{int(rng.integers(0, 8))}", "s", (), "c"))
            except Exception as e:  # pragma: no cover - failure channel
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        s = cache.stats()
        assert s["device_nbytes"] <= cache.device_bytes
        assert s["host_nbytes"] <= cache.host_bytes
        assert s["hits"] + s["misses"] == gets
        assert s["device_nbytes"] == sum(
            n for _, n in cache._device.values())
        assert s["host_nbytes"] == sum(n for _, n in cache._host.values())


def _refresh_worker(root, q):
    """Child process: run an incremental refresh while the parent serves
    cached queries (test_log_concurrency._refresh_worker pattern)."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import hyperspace_tpu as hst
    from hyperspace_tpu.api import Hyperspace

    session = hst.Session(system_path=os.path.join(root, "indexes"))
    from hyperspace_tpu.index.constants import IndexConstants as IC
    try:
        Hyperspace(session).refresh_index("raceIdx", "incremental")
        q.put(("refresh", "ok"))
    except Exception as e:  # pragma: no cover - diagnostic channel
        q.put(("refresh", f"err: {e}"))


class TestReaderWriterRace:
    def test_cached_queries_stable_during_refresh(self, tmp_path):
        """With the result cache ON, a refresh racing in another process
        must never change the answers of a pinned-snapshot query
        mid-flight (cache keys flip with the op log, recomputes land on
        the same snapshot), and a FRESH relation after the refresh must
        see the appended rows — not a stale cached result."""
        df = _seed(tmp_path)
        session = _session(tmp_path, cache_on=True)
        hs = Hyperspace(session)
        t = session.read.parquet(str(tmp_path / "data"))
        hs.create_index(t, IndexConfig("raceIdx", ["k"], ["v"]))
        rng = np.random.default_rng(6)
        pq.write_table(pa.Table.from_pandas(pd.DataFrame({
            "k": rng.integers(0, 60, 1500).astype(np.int64),
            "v": rng.integers(0, 9, 1500).astype(np.int64),
        })), tmp_path / "data" / "extra.parquet")

        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        p = ctx.Process(target=_refresh_worker, args=(str(tmp_path), q))
        p.start()
        session.enable_hyperspace()
        expected = (df.k == 7).sum()
        query = t.filter(col("k") == 7).select("k", "v")
        import time
        deadline = time.monotonic() + 300
        while p.is_alive():
            assert time.monotonic() < deadline, "refresh child hung"
            assert len(query.to_pandas()) == expected
        tag, status = q.get(timeout=300)
        p.join(timeout=300)
        assert status == "ok", status
        # Post-refresh, a fresh listing must produce the bigger answer —
        # the cache serves it only under the fresh key.
        t2 = session.read.parquet(str(tmp_path / "data"))
        got = len(t2.filter(col("k") == 7).select("k", "v").to_pandas())
        session.disable_hyperspace()
        raw = len(t2.filter(col("k") == 7).select("k", "v").to_pandas())
        assert got == raw > expected
