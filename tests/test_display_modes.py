"""Display-mode + BufferStream unit tests (parity: the reference's
plananalysis/DisplayModeTest.scala, BufferStreamTest.scala, and the
operator-count section of PhysicalOperatorAnalyzerTest.scala).
"""

import pytest

from hyperspace_tpu.plananalysis.display import (BufferStream, ConsoleMode,
                                                 DisplayMode, HTMLMode,
                                                 PlainTextMode, get_mode)


class TestGetMode:
    def test_names_resolve_case_insensitively(self):
        assert isinstance(get_mode("plaintext"), PlainTextMode)
        assert isinstance(get_mode("Console"), ConsoleMode)
        assert isinstance(get_mode("HTML"), HTMLMode)

    def test_instance_passes_through(self):
        m = ConsoleMode()
        assert get_mode(m) is m

    def test_unknown_mode_raises_with_choices(self):
        with pytest.raises(ValueError, match="console"):
            get_mode("markdown")


class TestPlainText:
    def test_no_decoration(self):
        buf = BufferStream(PlainTextMode())
        buf.write_line("a <plan> & b", highlight=True)
        buf.write_line("second")
        assert buf.build() == "a <plan> & b\nsecond"


class TestConsole:
    def test_ansi_highlight_only_on_highlighted_lines(self):
        buf = BufferStream(ConsoleMode())
        buf.write_line("normal")
        buf.write_line("hot", highlight=True)
        out = buf.build()
        assert "normal" in out and "\033[93mhot\033[0m" in out
        assert not out.startswith("\033")  # first line undecorated

    def test_blank_highlight_lines_not_decorated(self):
        # Highlighting whitespace-only lines would print bare ANSI codes.
        buf = BufferStream(ConsoleMode())
        buf.write_line("   ", highlight=True)
        assert "\033" not in buf.build()


class TestHTML:
    def test_escaping_newlines_and_wrap(self):
        buf = BufferStream(HTMLMode())
        buf.write_line("a <b> & c")
        buf.write_line("hot", highlight=True)
        out = buf.build()
        assert out.startswith("<pre>") and out.endswith("</pre>")
        assert "a &lt;b&gt; &amp; c" in out
        assert "<b>hot</b>" in out
        assert "<br>" in out

    def test_escape_happens_before_highlight_tags(self):
        # The highlight markup itself must survive escaping.
        buf = BufferStream(HTMLMode())
        buf.write_line("<x>", highlight=True)
        assert buf.build() == "<pre><b>&lt;x&gt;</b></pre>"


class TestCustomMode:
    def test_mode_contract_is_open(self):
        # A user-defined mode only needs the four class attributes
        # (parity: DisplayMode.scala is a pluggable trait).
        class Brackets(DisplayMode):
            highlight_begin = "["
            highlight_end = "]"
            new_line = "|"

        buf = BufferStream(Brackets())
        buf.write_line("a")
        buf.write_line("b", highlight=True)
        assert buf.build() == "a|[b]"


class TestOperatorCounts:
    def test_physical_operator_stats_section(self, tmp_path):
        """The explain output's operator-count diff (parity:
        PhysicalOperatorAnalyzerTest): rewritten plans report IndexScan
        appearing and Scan disappearing."""
        import numpy as np
        import pandas as pd
        import pyarrow as pa
        import pyarrow.parquet as pq

        import hyperspace_tpu as hst
        from hyperspace_tpu.api import Hyperspace, IndexConfig
        from hyperspace_tpu.index.constants import IndexConstants
        from hyperspace_tpu.plan.expr import col
        from hyperspace_tpu.plananalysis.explain import explain_string

        d = tmp_path / "data"
        d.mkdir()
        rng = np.random.default_rng(4)
        pq.write_table(pa.Table.from_pandas(pd.DataFrame({
            "k": rng.integers(0, 30, 200).astype(np.int64),
            "v": rng.integers(0, 9, 200).astype(np.int64),
        })), d / "p0.parquet")
        session = hst.Session(system_path=str(tmp_path / "idx"))
        session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 2)
        hs = Hyperspace(session)
        df = session.read.parquet(str(d))
        hs.create_index(df, IndexConfig("opIdx", ["k"], ["v"]))
        session.enable_hyperspace()
        q = df.filter(col("k") > 10).select("k", "v")
        out = explain_string(session, q.plan, verbose=True)
        assert "Physical operator stats" in out
        assert "IndexScan: 0 -> 1" in out
        assert "Scan: 1 -> 0" in out
