"""Filter-pushdown normalization (rules/pushdown.py).

The reference's FilterIndexRule only matches Scan→Filter(→Project)
(FilterIndexRule.scala:165) and relies on Spark's PushDownPredicate to
normalize plans first; these tests pin that our pipeline provides the
same normalization — the index rewrite must not depend on whether the
user wrote where-then-select or select-then-where.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.plan import expr as E
from hyperspace_tpu.plan.expr import col, lit
from hyperspace_tpu.plan.nodes import Filter, IndexScan, Project, Scan
from hyperspace_tpu.rules.pushdown import push_filters


@pytest.fixture()
def env(tmp_path):
    rng = np.random.default_rng(11)
    df = pd.DataFrame({
        "k": rng.integers(0, 100, 20_000).astype(np.int64),
        "v": rng.random(20_000),
        "w": rng.integers(0, 7, 20_000).astype(np.int64),
    })
    d = tmp_path / "data"
    d.mkdir()
    pq.write_table(pa.Table.from_pandas(df), d / "p0.parquet")
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    hs = Hyperspace(session)
    t = session.read.parquet(str(d))
    hs.create_index(t, IndexConfig("pd_idx", ["k"], ["v", "w"]))
    session.enable_hyperspace()
    return dict(session=session, t=t, df=df)


class TestPlanShape:
    def test_filter_sinks_below_project(self, env):
        t = env["t"]
        q = t.select("k", "v").where(col("k") == 5)
        plan = q.optimized_plan()
        assert isinstance(plan, Project)
        assert isinstance(plan.child, Filter)
        leaves = plan.collect_leaves()
        assert len(leaves) == 1 and isinstance(leaves[0], IndexScan)

    def test_both_orders_rewrite_identically(self, env):
        t = env["t"]
        q1 = t.where(col("k") == 5).select("k", "v")
        q2 = t.select("k", "v").where(col("k") == 5)
        l1 = q1.optimized_plan().collect_leaves()
        l2 = q2.optimized_plan().collect_leaves()
        assert all(isinstance(l, IndexScan) for l in l1 + l2)

    def test_sinks_through_stacked_projects(self, env):
        t = env["t"]
        q = t.select("k", "v", "w").select("k", "v").where(col("k") == 5)
        leaves = q.optimized_plan().collect_leaves()
        assert len(leaves) == 1 and isinstance(leaves[0], IndexScan)

    def test_alias_substitution(self, env):
        t = env["t"]
        q = t.select(col("k").alias("key"), col("v")).where(col("key") == 5)
        plan = push_filters(q.plan)
        # The filter now sits below the project, referencing the base col.
        assert isinstance(plan, Project)
        assert isinstance(plan.child, Filter)
        assert plan.child.condition.references == ["k"]

    def test_computed_column_substitution(self, env):
        t = env["t"]
        q = t.select((col("k") + lit(1)).alias("k1"), col("v")) \
             .where(col("k1") == 6)
        plan = push_filters(q.plan)
        assert isinstance(plan, Project)
        assert isinstance(plan.child, Filter)
        assert plan.child.condition.references == ["k"]

    def test_aggregate_projection_not_pushed(self, env):
        # A filter above an Aggregate output must stay put (HAVING shape).
        t = env["t"]
        q = t.group_by("k").agg(E.Sum(col("v")).alias("sv")) \
             .where(col("sv") > 1.0)
        plan = push_filters(q.plan)
        assert isinstance(plan, Filter)  # unchanged root


class TestResults:
    def _expect(self, df, k):
        out = df[df.k == k][["k", "v"]]
        return out.sort_values(["k", "v"]).reset_index(drop=True)

    def test_select_then_where_results(self, env):
        t, df, session = env["t"], env["df"], env["session"]
        q = t.select("k", "v").where(col("k") == 42)
        got = q.to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
        pd.testing.assert_frame_equal(got, self._expect(df, 42))
        session.disable_hyperspace()
        raw = q.to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
        pd.testing.assert_frame_equal(got, raw)

    def test_alias_filter_results(self, env):
        t, df = env["t"], env["df"]
        q = t.select(col("k").alias("key"), col("v")).where(
            (col("key") >= 10) & (col("key") < 13))
        got = q.to_pandas()
        exp = df[(df.k >= 10) & (df.k < 13)]
        assert len(got) == len(exp)
        assert set(got.columns) == {"key", "v"}

    def test_computed_filter_results(self, env):
        t, df = env["t"], env["df"]
        q = t.select((col("k") * lit(2)).alias("k2"), col("w")) \
             .where(col("k2") == 84)
        got = q.to_pandas()
        exp = df[df.k * 2 == 84]
        assert len(got) == len(exp)
        assert (got["k2"] == 84).all()


class TestPushThroughJoin:
    """Catalyst's PushDownPredicate analogue for inner joins: conjuncts
    of a WHERE above a join sink to the side they reference; mixed-side
    conjuncts stay above; outer joins are untouched."""

    @pytest.fixture()
    def joined(self, tmp_path):
        import numpy as np
        import pandas as pd
        import pyarrow as pa
        import pyarrow.parquet as pq
        rng = np.random.default_rng(19)
        d1, d2 = tmp_path / "a", tmp_path / "b"
        d1.mkdir(); d2.mkdir()
        pq.write_table(pa.Table.from_pandas(pd.DataFrame({
            "k": rng.integers(0, 30, 400).astype(np.int64),
            "v": rng.integers(0, 99, 400).astype(np.int64)})),
            d1 / "p.parquet")
        pq.write_table(pa.table({
            "k2": pa.array(np.arange(30, dtype=np.int64)),
            "w": pa.array(rng.integers(0, 99, 30).astype(np.int64))}),
            d2 / "p.parquet")
        session = hst.Session(system_path=str(tmp_path / "idx"))
        return session, session.read.parquet(str(d1)), \
            session.read.parquet(str(d2))

    def test_conjuncts_split_to_sides(self, joined):
        session, a, b = joined
        q = (a.join(b, on=col("k") == col("k2"))
             .filter((col("v") > 50) & (col("w") < 40)
                     & (col("v") + col("w") < 120)))
        plan = session.optimize(q.plan).tree_string()
        lines = plan.splitlines()
        join_at = next(i for i, l in enumerate(lines) if "Join" in l)
        # Single-side conjuncts are BELOW the join, the mixed one above.
        assert any("col(v) > lit(50)" in l for l in lines[join_at:])
        assert any("col(w) < lit(40)" in l for l in lines[join_at:])
        assert any("(col(v) + col(w)) < lit(120)" in l
                   for l in lines[:join_at])
        # Oracle.
        got = q.to_pandas().sort_values(["k", "v", "w"]).reset_index(drop=True)
        pdf_a, pdf_b = a.to_pandas(), b.to_pandas()
        m = pdf_a.merge(pdf_b, left_on="k", right_on="k2")
        exp = (m[(m.v > 50) & (m.w < 40) & (m.v + m.w < 120)]
               .sort_values(["k", "v", "w"]).reset_index(drop=True)
               [["k", "v", "k2", "w"]])
        import pandas as pd
        pd.testing.assert_frame_equal(got[["k", "v", "k2", "w"]], exp)

    def test_outer_join_untouched(self, joined):
        session, a, b = joined
        q = (a.join(b, on=col("k") == col("k2"), how="left")
             .filter(col("w") < 40))
        plan = session.optimize(q.plan).tree_string()
        lines = plan.splitlines()
        join_at = next(i for i, l in enumerate(lines) if "Join" in l)
        # The right-side predicate must stay ABOVE the left outer join.
        assert any("col(w) < lit(40)" in l for l in lines[:join_at])
        assert not any("Filter" in l for l in lines[join_at:])
