"""Kernel-level tests: hashing stability, sort, join expansion, membership."""

import datetime

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.ops import kernels
from hyperspace_tpu.schema import DATE, FLOAT64, INT32, INT64, STRING


class TestHashing:
    @pytest.mark.parametrize("dtype,values", [
        (INT32, np.array([0, 1, -5, 2**31 - 1, -2**31], np.int32)),
        (INT64, np.array([0, 1, -5, 2**62, -2**62, 123456789012345], np.int64)),
        (DATE, np.array([0, 9131, -365], np.int32)),
        (FLOAT64, np.array([0.0, 1.5, -3.25, 1e300], np.float64)),
    ])
    def test_host_matches_device(self, dtype, values):
        device = np.asarray(jax.device_get(
            kernels.hash32_values(jnp.asarray(values), dtype)))
        host = [kernels.hash32_value_host(int(v) if dtype != FLOAT64 else float(v),
                                          dtype) for v in values]
        np.testing.assert_array_equal(device, np.asarray(host, np.uint32))

    def test_host_matches_device_strings(self):
        dictionary = np.array(sorted(["apple", "banana", "cherry"]))
        codes = jnp.asarray(np.array([0, 1, 2, 1], np.int32))
        device = np.asarray(jax.device_get(
            kernels.hash32_values(codes, STRING, dictionary)))
        host = [kernels.hash32_value_host(dictionary[c], STRING)
                for c in [0, 1, 2, 1]]
        np.testing.assert_array_equal(device, np.asarray(host, np.uint32))

    def test_bucket_distribution_roughly_uniform(self):
        keys = jnp.arange(100000, dtype=jnp.int64)
        h = kernels.hash32_values(keys, INT64)
        b = np.asarray(jax.device_get(kernels.bucket_ids(h, 32)))
        counts = np.bincount(b, minlength=32)
        assert counts.min() > 0.8 * counts.mean()
        assert counts.max() < 1.2 * counts.mean()


class TestSortJoin:
    def test_lex_sort_multi_key_desc(self):
        a = jnp.asarray(np.array([2, 1, 2, 1], np.int64))
        b = jnp.asarray(np.array([1.0, 2.0, 3.0, 4.0]))
        perm = np.asarray(jax.device_get(
            kernels.lex_sort_indices([a, b], [True, False])))
        assert list(perm) == [3, 1, 2, 0]

    def test_merge_join_duplicates(self):
        left = jnp.asarray(np.array([1, 2, 2, 5], np.int64))
        right = jnp.asarray(np.array([2, 2, 3, 5, 5, 5], np.int64))
        li, ri = kernels.merge_join_indices(left, right)
        pairs = sorted(zip(np.asarray(li).tolist(), np.asarray(ri).tolist()))
        # left row 1 (key 2) matches right rows 0,1; left row 2 likewise;
        # left row 3 (key 5) matches right rows 3,4,5.
        assert pairs == [(1, 0), (1, 1), (2, 0), (2, 1), (3, 3), (3, 4), (3, 5)]

    def test_merge_join_empty(self):
        li, ri = kernels.merge_join_indices(
            jnp.zeros(0, jnp.int64), jnp.zeros(0, jnp.int64))
        assert li.shape == (0,) and ri.shape == (0,)

    def test_isin_sorted(self):
        data = jnp.asarray(np.array([1, 4, 7, 9], np.int64))
        vals = jnp.asarray(np.array([4, 9], np.int64))
        mask = np.asarray(jax.device_get(kernels.isin_sorted(data, vals)))
        assert list(mask) == [False, True, False, True]


class TestGrouping:
    def test_group_ids(self):
        keys = jnp.asarray(np.array([1, 1, 2, 2, 2, 9], np.int64))
        gids, n = kernels.group_ids_from_sorted([keys])
        assert n == 3
        assert list(np.asarray(gids)) == [0, 0, 1, 1, 1, 2]
