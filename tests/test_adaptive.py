"""adaptive/: the self-driving control plane (feedback-corrected
planning, mid-query re-planning, the budgeted background builder, and
SLO-driven admission).

The closed-loop acceptance evidence lives here:

  1. q-error over a replayed workload SHRINKS with feedback on (second
     half of the replay beats the first half) and stays flat with the
     master switch off;
  2. a seeded mis-estimate triggers ONE mid-query re-plan
     (ReplanEvent) and the answer is identical to the non-adaptive
     plan's;
  3. the builder materializes the advisor's top recommendation in an
     idle window, a later query actually uses it (usageCount > 0), and
     a never-used index is retired after the observation window;
  4. an armed-and-breached SLO sheds or degrades at submit — the
     degraded answer carries its stated error bound — and the first
     healthy verdict recovers to exact answers;
  5. ``adaptive.enabled=false`` (the default) is inert end to end.

Plus the satellite regression: join actuals are keyed on (condition
repr, left/right relation signatures), so the same condition text over
two different table pairs no longer collides in the correction store.
"""

from __future__ import annotations

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.adaptive import feedback
from hyperspace_tpu.adaptive.admission import get_controller
from hyperspace_tpu.adaptive.builder import AdaptiveBuilder, BuilderLedger
from hyperspace_tpu.adaptive.constants import AdaptiveConstants
from hyperspace_tpu.adaptive.feedback import get_store
from hyperspace_tpu.advisor.constants import AdvisorConstants
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.exceptions import ServingRejectedError
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.optimizer.constants import OptimizerConstants
from hyperspace_tpu.plan.expr import col, count, sum_
from hyperspace_tpu.serving.frontend import ServingFrontend
from hyperspace_tpu.telemetry.constants import TelemetryConstants
from hyperspace_tpu.telemetry.events import (AdaptiveActionEvent,
                                             ReplanEvent)

from conftest import capture_logger as sink  # noqa: E402


def _session(tmp_path, **conf):
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    for k, v in conf.items():
        session.conf.set(k, v)
    return session


def _write(dirpath, table, parts=1):
    os.makedirs(dirpath, exist_ok=True)
    n = table.num_rows // parts
    for i in range(parts):
        length = n if i < parts - 1 else table.num_rows - i * n
        pq.write_table(table.slice(i * n, length),
                       os.path.join(dirpath, f"p{i}.parquet"))
    return str(dirpath)


def _sorted_rows(df):
    out = df.to_pandas()
    return out.sort_values(list(out.columns)).reset_index(drop=True)


ADAPTIVE_ON = {AdaptiveConstants.ENABLED: "true",
               OptimizerConstants.JOIN_REORDER_ENABLED: "true"}


# ---------------------------------------------------------------------------
# A star schema with a planner-hostile skew: ~95% of fact rows hit ONE
# dim1 key, and the selective dim1 category selects exactly that key.
# The uniform-NDV estimate for (fact x dim1-filtered) lands near 400
# rows while the actual is ~3800 — a q-error of ~9.5, past the default
# re-plan threshold of 8.
# ---------------------------------------------------------------------------

@pytest.fixture()
def skew_star(tmp_path):
    rng = np.random.default_rng(11)
    n_f, n_d1, n_d2 = 4000, 50, 20
    f_d1 = np.zeros(n_f, dtype=np.int64)
    f_d1[:200] = np.arange(200) % (n_d1 - 1) + 1  # stragglers span 1..49
    rng.shuffle(f_d1)
    fact = pa.table({
        "f_d1": pa.array(f_d1),
        "f_d2": pa.array(rng.integers(0, n_d2, n_f).astype(np.int64)),
        "f_val": pa.array(np.round(rng.uniform(0, 100, n_f), 3)),
    })
    d1_cat = np.array([f"c{i % 9}" for i in range(n_d1)], dtype=object)
    d1_cat[0] = "b"  # the selective category IS the skewed key
    dim1 = pa.table({
        "d1_key": pa.array(np.arange(n_d1, dtype=np.int64)),
        "d1_cat": pa.array(d1_cat),
    })
    dim2 = pa.table({
        "d2_key": pa.array(np.arange(n_d2, dtype=np.int64)),
        "d2_cat": pa.array(rng.choice(["x", "y"], n_d2)),
    })
    return {
        "fact": _write(tmp_path / "fact", fact),
        "dim1": _write(tmp_path / "dim1", dim1),
        "dim2": _write(tmp_path / "dim2", dim2),
    }


def _three_way(session, paths):
    fact = session.read.parquet(paths["fact"])
    d1 = session.read.parquet(paths["dim1"]).filter(col("d1_cat") == "b")
    d2 = session.read.parquet(paths["dim2"])
    return (fact.join(d2, on=col("f_d2") == col("d2_key"))
            .join(d1, on=col("f_d1") == col("d1_key"))
            .select("d1_cat", "d2_cat", "f_val"))


def _run_q_error(session, paths):
    """Execute the 3-way once; return the worst per-step q-error of the
    reordered chain. A run where the optimizer kept the text order
    (no reorder steps) counts as converged (1.0)."""
    _three_way(session, paths).to_arrow()
    steps = [s for r in (session._last_join_order or [])
             for s in r["steps"]]
    qs = []
    for s in steps:
        actual = session._join_actuals.get(s["key"])
        if actual is None:
            continue
        est = max(float(s["est_rows"]), 1.0)
        act = max(float(actual), 1.0)
        qs.append(max(est / act, act / est))
    return max(qs) if qs else 1.0


# ---------------------------------------------------------------------------
# Satellite regression: composite join-actual keys.
# ---------------------------------------------------------------------------

class TestJoinActualKeying:
    def test_same_condition_text_two_table_pairs_no_collision(
            self, tmp_path):
        """col("k") == col("k2") has ONE repr; over two different table
        pairs the recorded actuals must land under TWO keys (the old
        bare-condition keying folded them into one entry and poisoned
        the correction store across pairs)."""
        a1 = _write(tmp_path / "a1", pa.table(
            {"k": pa.array([0, 0, 0, 1, 2], type=pa.int64())}))
        a2 = _write(tmp_path / "a2", pa.table(
            {"k2": pa.array([0], type=pa.int64())}))
        b1 = _write(tmp_path / "b1", pa.table(
            {"k": pa.array([5, 5, 5, 5, 6, 7], type=pa.int64())}))
        b2 = _write(tmp_path / "b2", pa.table(
            {"k2": pa.array([5, 5], type=pa.int64())}))
        session = _session(tmp_path)
        session.read.parquet(a1).join(
            session.read.parquet(a2),
            on=col("k") == col("k2")).to_arrow()
        session.read.parquet(b1).join(
            session.read.parquet(b2),
            on=col("k") == col("k2")).to_arrow()

        parsed = {}
        for key, rows in session._join_actuals.items():
            hit = feedback.parse_key(key)
            assert hit is not None, key
            cond, lsig, rsig = hit
            parsed.setdefault(cond, []).append((lsig, rsig, rows))
        cond = repr(col("k") == col("k2"))
        entries = parsed[cond]
        assert len(entries) == 2, entries
        assert entries[0][:2] != entries[1][:2]  # distinct side sigs
        assert sorted(e[2] for e in entries) == [3, 8]


# ---------------------------------------------------------------------------
# Acceptance 1: q-error shrinks over a replayed workload.
# ---------------------------------------------------------------------------

class TestFeedbackQError:
    RUNS = 8

    def test_qerror_second_half_beats_first_half(self, tmp_path,
                                                 skew_star):
        session = _session(tmp_path, **ADAPTIVE_ON)
        # Isolate the feedback loop: re-planning would fix run 1
        # mid-flight and contaminate the halves comparison.
        session.conf.set(AdaptiveConstants.REPLAN_ENABLED, "false")
        get_store().clear()
        qs = [_run_q_error(session, skew_star) for _ in range(self.RUNS)]
        first = sum(qs[:self.RUNS // 2]) / (self.RUNS // 2)
        second = sum(qs[self.RUNS // 2:]) / (self.RUNS // 2)
        assert first > 2.0, qs      # the seeded skew actually mis-estimated
        assert second < first * 0.5, qs
        assert second < 2.0, qs     # converged, not merely improved
        stats = get_store().stats()
        assert stats["observed"] > 0
        assert stats["paired"] > 0

    def test_qerror_flat_with_adaptive_off(self, tmp_path, skew_star):
        session = _session(
            tmp_path, **{OptimizerConstants.JOIN_REORDER_ENABLED: "true"})
        get_store().clear()
        qs = [_run_q_error(session, skew_star) for _ in range(self.RUNS)]
        assert max(qs) - min(qs) < 1e-9, qs  # nothing learned, by design
        assert qs[0] > 2.0, qs               # same mis-estimate every run
        assert get_store().stats()["observed"] == 0

    def test_feedback_changes_no_answers(self, tmp_path, skew_star):
        baseline = _sorted_rows(_three_way(
            _session(tmp_path), skew_star))
        session = _session(tmp_path, **ADAPTIVE_ON)
        get_store().clear()
        for _ in range(3):
            out = _sorted_rows(_three_way(session, skew_star))
            assert out.equals(baseline)


# ---------------------------------------------------------------------------
# Acceptance 2: mid-query re-planning.
# ---------------------------------------------------------------------------

class TestReplan:
    def _wired(self, tmp_path):
        session = _session(tmp_path, **ADAPTIVE_ON)
        # Pin the staged executor: it owns the stage boundaries where
        # ReplanRequested can fire (fused regions record actuals only
        # after the whole region ran).
        session.conf.set(IndexConstants.TPU_FUSION_ENABLED, "false")
        session.conf.set(IndexConstants.EVENT_LOGGER_CLASS,
                         "tests.conftest.CaptureLogger")
        sink().events.clear()
        return session

    def test_misestimate_triggers_one_replan_same_answer(
            self, tmp_path, skew_star):
        baseline = _sorted_rows(_three_way(
            _session(tmp_path), skew_star))
        session = self._wired(tmp_path)
        get_store().clear()

        out = _sorted_rows(_three_way(session, skew_star))
        assert out.equals(baseline)  # byte-identical despite the abort
        assert get_store().stats()["replans"] == 1
        replans = [e for e in sink().events
                   if isinstance(e, ReplanEvent)]
        assert len(replans) == 1
        ev = replans[0]
        assert ev.threshold == pytest.approx(8.0)
        assert ev.actual_rows > ev.est_rows * 8
        assert " @ " in ev.key and " >< " in ev.key  # composite key

        # The retry ran under suppress_replans and the store now holds
        # the correction: the NEXT run must not re-plan again.
        out = _sorted_rows(_three_way(session, skew_star))
        assert out.equals(baseline)
        assert get_store().stats()["replans"] == 1

    def test_replan_disabled_no_trigger(self, tmp_path, skew_star):
        session = self._wired(tmp_path)
        session.conf.set(AdaptiveConstants.REPLAN_ENABLED, "false")
        get_store().clear()
        _three_way(session, skew_star).to_arrow()
        assert get_store().stats()["replans"] == 0
        assert not [e for e in sink().events
                    if isinstance(e, ReplanEvent)]


# ---------------------------------------------------------------------------
# Acceptance 3: the budgeted background builder.
# ---------------------------------------------------------------------------

@pytest.fixture()
def served(tmp_path):
    """The advisor-test shape: a 2-part fact (MinMax-prunable), a dim,
    a captured workload the advisor can rank, and an armed adaptive
    session with a capture sink."""
    rng = np.random.default_rng(3)
    ks = np.sort(rng.integers(0, 100, 4000)).astype(np.int64)
    fact = pa.table({
        "k": pa.array(ks),
        "v": pa.array(rng.integers(0, 9, 4000).astype(np.int64)),
        "w": pa.array(np.round(rng.uniform(0, 1, 4000), 3)),
        "pad": pa.array(rng.integers(0, 5, 4000).astype(np.int64)),
    })
    dim = pa.table({
        "dk": pa.array(np.arange(100, dtype=np.int64)),
        "dv": pa.array(rng.integers(0, 5, 100).astype(np.int64)),
    })
    session = _session(tmp_path, **ADAPTIVE_ON)
    session.conf.set(IndexConstants.EVENT_LOGGER_CLASS,
                     "tests.conftest.CaptureLogger")
    session.enable_hyperspace()
    sink().events.clear()
    env = dict(session=session, hs=Hyperspace(session),
               fact=_write(tmp_path / "fact", fact, parts=2),
               dim=_write(tmp_path / "dim", dim))

    session.conf.set(AdvisorConstants.CAPTURE_ENABLED, "true")
    fdf = session.read.parquet(env["fact"])
    env["q_filter"] = fdf.filter(col("k") > 50).select("k", "v")
    env["q_filter"].to_arrow()
    session.conf.set(AdvisorConstants.CAPTURE_ENABLED, "false")
    return env


class TestBuilder:
    def test_builds_top_recommendation_and_query_uses_it(self, served):
        session, hs = served["session"], served["hs"]
        ledger = BuilderLedger()
        builder = AdaptiveBuilder(hs, ledger=ledger)

        out = builder.run_once(force=True)
        assert out["ran"]
        assert out["built"], out
        listed = set(hs.indexes()["name"])
        assert set(out["built"]) <= listed
        assert ledger.stats()["bytes_spent"] > 0
        assert ledger.stats()["in_progress"] == []
        builds = [e for e in sink().events
                  if isinstance(e, AdaptiveActionEvent)
                  and e.action == "builder.build"]
        assert {e.subject for e in builds} == set(out["built"])

        # The closed loop: a workload query now rides the built index.
        served["q_filter"].to_arrow()
        usage = sum(session._index_usage_counts.get(n, 0)
                    for n in out["built"])
        assert usage > 0

        # A later pass moves DOWN the ranking (or builds nothing) —
        # it never re-builds what already exists.
        again = builder.run_once(force=True)
        assert not set(again["built"]) & set(out["built"])

    def test_budget_retire_and_gating(self, served):
        session, hs = served["session"], served["hs"]
        ledger = BuilderLedger()
        builder = AdaptiveBuilder(hs, ledger=ledger)
        first = builder.run_once(force=True)
        assert first["built"]
        served["q_filter"].to_arrow()  # mark the built index used

        # Budget: bytes already spent >= maxBytes stops further builds.
        session.conf.set(AdaptiveConstants.BUILDER_MAX_BYTES, "1")
        # A never-used index enters the retirement observation window.
        hs.create_index(session.read.parquet(served["dim"]),
                        IndexConfig("cold_dim", ["dk"], ["dv"]))
        session.conf.set(AdaptiveConstants.BUILDER_RETIRE_MIN_QUERIES,
                         "1")
        pass_a = builder.run_once(force=True)
        assert pass_a["built"] == []          # budget exhausted
        assert "cold_dim" not in pass_a["retired"]  # clock just started

        served["q_filter"].to_arrow()  # >=1 completed query since seen
        pass_b = builder.run_once(force=True)
        assert "cold_dim" in pass_b["retired"]
        listed = hs.indexes()
        by_name = dict(zip(listed["name"], listed["state"]))
        assert by_name["cold_dim"] == "DELETED"       # soft delete
        assert by_name[first["built"][0]] == "ACTIVE"  # survivor
        retires = [e for e in sink().events
                   if isinstance(e, AdaptiveActionEvent)
                   and e.action == "builder.retire"]
        assert [e.subject for e in retires] == ["cold_dim"]

        # Idle-window gating: fresh activity restarts the clock.
        session.conf.set(AdaptiveConstants.BUILDER_IDLE_MS, "60000")
        ledger.note_activity()
        warming = builder.run_once(force=False)
        assert not warming["ran"]
        assert warming["reason"] == "idle window still warming"

        # Busy serving pool: never share the machine with a build.
        builder._serving_busy = lambda: True
        busy = builder.run_once(force=True)
        assert not busy["ran"]
        assert busy["reason"] == "serving busy"
        del builder._serving_busy

        session.conf.set(AdaptiveConstants.BUILDER_ENABLED, "false")
        assert builder.run_once(force=True)["reason"] == "disabled"


# ---------------------------------------------------------------------------
# Acceptance 4: SLO-driven admission (shed / degrade / recover).
# ---------------------------------------------------------------------------

class TestAdmission:
    @pytest.fixture()
    def overload(self, tmp_path):
        """A 4-part table (approx-eligible), an armed p99 objective no
        query can meet, and a clean controller."""
        rng = np.random.default_rng(5)
        v = rng.integers(0, 1000, 4000).astype(np.int64)
        table = pa.table({
            "k": pa.array(np.arange(4000, dtype=np.int64)),
            "v": pa.array(v),
        })
        path = _write(tmp_path / "wide", table, parts=4)
        session = _session(tmp_path, **ADAPTIVE_ON)
        session.conf.set(IndexConstants.EVENT_LOGGER_CLASS,
                         "tests.conftest.CaptureLogger")
        session.conf.set(TelemetryConstants.SLO_P99_MS, "0.001")
        session.conf.set(TelemetryConstants.SLO_MIN_COUNT, "1")
        sink().events.clear()
        controller = get_controller()
        controller.reset()
        # Guarantee the monitor window holds at least one sample.
        session.read.parquet(path).filter(col("k") < 10).to_arrow()
        yield dict(session=session, path=path, v=v,
                   controller=controller)
        controller.reset()

    def test_degrade_to_approximate_with_stated_bound(self, overload):
        session, path = overload["session"], overload["path"]
        df = session.read.parquet(path)
        agg = df.agg(sum_(col("v")).alias("sv"), count().alias("n"))
        fe = ServingFrontend(session)

        table = fe.submit(agg).result(timeout=300)
        bound = getattr(table, "approx_error_bound", None)
        assert bound is not None, "breached SLO did not degrade"
        assert bound["kind"] == "relative"
        assert bound["confidence"] == 0.95
        assert 0.0 < bound["sample_fraction"] < 1.0
        assert 0.0 <= bound["bound"] <= 1.0
        assert set(bound["scaled"]) == {"sv", "n"}

        # The sampled answer is deterministic: the kept prefix of the
        # sorted listing, scaled by the inverse kept-byte fraction.
        files = sorted(os.path.join(path, f) for f in os.listdir(path))
        scale = sum(os.path.getsize(f) for f in files) \
            / os.path.getsize(files[0])
        row = table.to_pandas().iloc[0]
        v = overload["v"]
        assert row["n"] == pytest.approx(1000 * scale)
        assert row["sv"] == pytest.approx(float(v[:1000].sum()) * scale)
        assert overload["controller"].stats()["degrades"] >= 1
        engaged = [e for e in sink().events
                   if isinstance(e, AdaptiveActionEvent)
                   and e.action == "admission.engage"]
        assert engaged and engaged[0].subject == "degrade"

    def test_ineligible_plan_runs_exact_under_breach(self, overload):
        session, path = overload["session"], overload["path"]
        df = session.read.parquet(path)
        q = df.filter(col("k") < 100).select("k", "v")
        exact = _sorted_rows(q)
        fe = ServingFrontend(session)
        table = fe.submit(q).result(timeout=300)
        assert getattr(table, "approx_error_bound", None) is None
        out = table.to_pandas()
        assert out.sort_values(list(out.columns)) \
            .reset_index(drop=True).equals(exact)

    def test_shed_mode_rejects_typed(self, overload):
        session, path = overload["session"], overload["path"]
        session.conf.set(AdaptiveConstants.ADMISSION_MODE, "shed")
        fe = ServingFrontend(session)
        df = session.read.parquet(path)
        with pytest.raises(ServingRejectedError, match="slo breach"):
            fe.submit(df.agg(count().alias("n")))
        assert overload["controller"].stats()["sheds"] >= 1

    def test_recovery_restores_exact_answers(self, overload):
        session, path = overload["session"], overload["path"]
        controller = overload["controller"]
        df = session.read.parquet(path)
        agg = df.agg(sum_(col("v")).alias("sv"), count().alias("n"))
        fe = ServingFrontend(session)
        degraded = fe.submit(agg).result(timeout=300)
        assert getattr(degraded, "approx_error_bound", None) is not None

        # health() clears: disarm the objective and force a refresh
        # (decide() would otherwise serve the cached verdict for 1s).
        session.conf.set(TelemetryConstants.SLO_P99_MS, "0")
        assert controller.refresh(session, force=True) is False
        table = fe.submit(agg).result(timeout=300)
        assert getattr(table, "approx_error_bound", None) is None
        row = table.to_pandas().iloc[0]
        assert row["n"] == 4000
        assert row["sv"] == overload["v"].sum()
        stats = controller.stats()
        assert stats["recoveries"] >= 1
        assert not stats["overloaded"]
        recovered = [e for e in sink().events
                     if isinstance(e, AdaptiveActionEvent)
                     and e.action == "admission.recover"]
        assert recovered


# ---------------------------------------------------------------------------
# Acceptance 5: the master switch really is a master switch.
# ---------------------------------------------------------------------------

class TestMasterSwitchOff:
    def test_everything_inert_by_default(self, tmp_path, skew_star):
        session = _session(
            tmp_path, **{OptimizerConstants.JOIN_REORDER_ENABLED: "true"})
        # Sub-features all true (their defaults) — the master switch
        # alone must keep the whole plane inert.
        assert not session.hs_conf.adaptive_enabled()
        assert not session.hs_conf.adaptive_feedback_enabled()
        assert not session.hs_conf.adaptive_replan_enabled()
        assert not session.hs_conf.adaptive_builder_enabled()
        assert not session.hs_conf.adaptive_admission_enabled()

        get_store().clear()
        a = _three_way(session, skew_star).to_arrow()
        b = _three_way(session, skew_star).to_arrow()
        assert a.equals(b)
        stats = get_store().stats()
        assert stats["observed"] == 0
        assert stats["replans"] == 0

        session.enable_hyperspace()
        hs = Hyperspace(session)
        out = AdaptiveBuilder(hs, ledger=BuilderLedger()) \
            .run_once(force=True)
        assert out == {"ran": False, "built": [], "retired": [],
                       "maintained": [], "reason": "disabled"}

        controller = get_controller()
        controller.reset()
        assert controller.decide(session) == "admit"
        # Nothing routes to the approximate tier: submit-side admission
        # is gated on the master switch.
        fe = ServingFrontend(session)
        table = fe.submit(session.read.parquet(skew_star["fact"])
                          .agg(count().alias("n"))).result(timeout=300)
        assert getattr(table, "approx_error_bound", None) is None
        assert table.to_pandas().iloc[0]["n"] == 4000
        controller.reset()
