"""Streaming at traffic scale (streaming/ r22): group commit,
continuous sources with backpressure, and subscription fan-out.

Acceptance contracts:

- **Group commit**: 16 concurrent ``commit()`` callers coalesce into
  ONE publication wave — one op-log entry per table (and one delta
  landing per index) for the whole wave, riders observe the leader's
  summary (``joined_wave``), and a deeper queue drains in bounded
  sub-waves of ``groupCommit.maxWave``. Answers are byte-identical to
  serial per-batch commits, and ``groupCommit.enabled=false`` restores
  the per-commit behavior exactly.
- **Backpressure**: ``append(block=True)`` parks on a full staged
  budget until a commit frees it (or raises the same full-table error
  after ``backpressure.timeoutMs``); the API default stays
  raise-on-full.
- **Crash safety**: kill -9 mid-wave (armed ``ingest.publish``) rolls
  the WHOLE wave back on ``recover()`` — no partial wave is ever
  visible.
- **Fan-out**: N same-template standing queries fire from one commit
  as ONE literal-sweep wave — one shared scan and one vmapped sweep
  invocation per template group at 10/100/1000 subscriptions, each
  subscription delivered exactly once with its own literal's answer.
- **Cluster coalescing**: one wave sends ONE commit broadcast carrying
  the wave width; a lost peer costs only that peer's firing, never the
  commit.
- **Continuous sources**: directory/JSONL tailers drive append/commit
  themselves, survive injected ``streaming.source`` faults, pause
  while admission reports overload, and drain cleanly on stop.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.constants import (IndexConstants, STABLE_STATES,
                                            States)
from hyperspace_tpu.index.log_manager import IndexLogManager
from hyperspace_tpu.plan.expr import col, sum_
from hyperspace_tpu.robustness import fault_names as FN
from hyperspace_tpu.robustness import faults
from hyperspace_tpu.robustness.faults import FaultRegistry
from hyperspace_tpu.streaming import ingest
from hyperspace_tpu.streaming.constants import StreamingConstants as SC
from hyperspace_tpu.streaming.ingest import (get_coordinator, table_key,
                                             table_log_dir)
from hyperspace_tpu.streaming.sources import (DirectoryTailSource,
                                              LogTailSource)
from hyperspace_tpu.telemetry import span_names as SN
from hyperspace_tpu.telemetry.events import (ClusterBroadcastEvent,
                                             StandingQueryEvent,
                                             StreamingSourceEvent,
                                             StreamingWaveEvent)

from conftest import capture_logger as sink  # noqa: E402

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rng(seed=17):
    return np.random.default_rng(seed)


def _frame(rng, n):
    return pd.DataFrame({
        "k": rng.integers(0, 40, n).astype(np.int64),
        "v": rng.integers(0, 9, n).astype(np.int64)})


def _write_base(d, rng, n=2000):
    os.makedirs(d, exist_ok=True)
    pq.write_table(pa.Table.from_pandas(_frame(rng, n)),
                   os.path.join(d, "p0.parquet"))


def _mk_session(root, capture=False, **conf):
    session = hst.Session(system_path=str(root / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    session.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
    session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "false")
    if capture:
        session.conf.set(IndexConstants.EVENT_LOGGER_CLASS,
                         "tests.conftest.CaptureLogger")
        sink().events.clear()
    for key, value in conf.items():
        session.conf.set(key, value)
    return session


def _mk_lake(root, capture=False, index=True, **conf):
    """Base table (+ covering index cx so waves land index deltas)."""
    root.mkdir(exist_ok=True)
    data = str(root / "tbl")
    _write_base(data, _rng())
    session = _mk_session(root, capture=capture, **conf)
    hs = Hyperspace(session)
    if index:
        hs.create_index(session.read.parquet(data),
                        IndexConfig("cx", ["k"], ["v"]))
    return session, hs, data


def _answers(session, data):
    t = session.read.parquet(data)
    q = t.filter(col("k") == 7).select("k", "v")
    session.enable_hyperspace()
    a = q.to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
    session.disable_hyperspace()
    b = q.to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
    return a, b


def _count_log_files(log_dir):
    """Digit-named op-log entries under an index/table log root."""
    sub = os.path.join(log_dir, IndexConstants.HYPERSPACE_LOG)
    if os.path.isdir(sub):
        log_dir = sub
    return len([n for n in os.listdir(log_dir) if n.isdigit()])


def _fresh_frontend(session, **conf):
    from hyperspace_tpu.serving import frontend as fe_mod
    # Commits notify the PROCESS-DEFAULT frontend; make this test's
    # frontend the default (first-constructed-wins otherwise).
    with fe_mod._DEFAULT_LOCK:
        fe_mod._DEFAULT = None
    session.conf.set("hyperspace.tpu.serving.maxConcurrency", "8")
    session.conf.set("hyperspace.tpu.serving.queueDepth", "64")
    for key, value in conf.items():
        session.conf.set(key, value)
    return fe_mod.ServingFrontend(session)


def _concurrent_commits(hs, data, n, timeout=180.0):
    """n commit() callers released together; (results, errors)."""
    results = [None] * n
    errors = []
    barrier = threading.Barrier(n)

    def run(i):
        try:
            barrier.wait(30)
            results[i] = hs.commit(data)
        except Exception as e:  # surfaced to the asserting test
            errors.append(e)

    threads = [threading.Thread(target=run, args=(i,))
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
    assert not any(t.is_alive() for t in threads), "commit hung"
    return results, errors


def _wait_until(pred, timeout=60.0, interval=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


def _delivery_pd(result):
    host = result.to_host()
    return pd.DataFrame(
        {n: np.asarray(c.data) for n, c in host.columns.items()}
    ).reset_index(drop=True)


# ---------------------------------------------------------------------------
# Group commit: concurrent committers coalesce into one wave.
# ---------------------------------------------------------------------------

class TestGroupCommit:
    def test_sixteen_committers_one_wave_one_log_entry(self, tmp_path):
        """Width 16: every concurrent commit() rides ONE publication —
        the table log and the index log each grow by exactly one
        commit's worth of entries for the whole wave."""
        session, hs, data = _mk_lake(tmp_path, capture=True)
        rng = _rng(31)
        # Calibrate: what one serial commit costs in log entries.
        hs.append(data, _frame(rng, 60))
        hs.commit(data)  # creates the table log
        tbl_log = table_log_dir(session, data)
        idx_log = os.path.join(str(tmp_path / "indexes"), "cx")
        hs.append(data, _frame(rng, 60))
        t0, i0 = _count_log_files(tbl_log), _count_log_files(idx_log)
        hs.commit(data)
        per_commit_tbl = _count_log_files(tbl_log) - t0
        per_commit_idx = _count_log_files(idx_log) - i0
        assert per_commit_tbl >= 1 and per_commit_idx >= 1

        frames = [_frame(rng, 60) for _ in range(16)]
        for f in frames:
            hs.append(data, f)
        before = get_coordinator().stats()
        t1, i1 = _count_log_files(tbl_log), _count_log_files(idx_log)
        sink().events.clear()

        results, errors = _concurrent_commits(hs, data, 16)
        assert not errors, errors

        # ONE wave, ONE sub-wave: all 16 batches staged before any
        # caller arrived, so the first leader pops them all and every
        # other caller rides (or observes the landed wave).
        after = get_coordinator().stats()
        assert after["commit_calls"] - before["commit_calls"] == 16
        assert after["waves"] - before["waves"] == 1
        assert after["sub_waves"] - before["sub_waves"] == 1
        assert after["joined"] - before["joined"] >= 1
        assert after["wave_batches"] - before["wave_batches"] == 16

        # One commit's worth of log entries for the WHOLE wave — the
        # amortization the tier exists for.
        assert _count_log_files(tbl_log) - t1 == per_commit_tbl
        assert _count_log_files(idx_log) - i1 == per_commit_idx

        # Every caller observed the same full-wave outcome; riders are
        # marked as such.
        full = [r for r in results if r["committed_batches"] == 16]
        assert full, results
        assert sum(r["committed_batches"] for r in results
                   if not r.get("joined_wave")) <= 16
        assert any(r.get("joined_wave") for r in results)

        # The wave was observable: one StreamingWaveEvent carrying the
        # width and the rider count.
        waves = [e for e in sink().events
                 if isinstance(e, StreamingWaveEvent)]
        assert len(waves) == 1
        assert waves[0].batches == 16 and waves[0].joined >= 1

        # Nothing was lost: the wave's rows answer queries.
        a, b = _answers(session, data)
        pd.testing.assert_frame_equal(a, b)
        expect = sum(int((f["k"] == 7).sum()) for f in frames)
        assert len(a) >= expect

    def test_deep_queue_drains_in_bounded_sub_waves(self, tmp_path):
        """maxWave bounds one publication's width: 8 staged batches at
        maxWave=4 land as one WAVE of two SUB-WAVES (two op-log
        entries), and the leader's summary still covers all 8."""
        session, hs, data = _mk_lake(
            tmp_path, **{SC.GROUP_COMMIT_MAX_WAVE: "4"})
        rng = _rng(37)
        hs.append(data, _frame(rng, 40))
        hs.commit(data)
        tbl_log = table_log_dir(session, data)
        hs.append(data, _frame(rng, 40))
        t0 = _count_log_files(tbl_log)
        hs.commit(data)
        per_commit = _count_log_files(tbl_log) - t0

        for _ in range(8):
            hs.append(data, _frame(rng, 40))
        before = get_coordinator().stats()
        t1 = _count_log_files(tbl_log)
        out = hs.commit(data)
        after = get_coordinator().stats()

        assert out["committed_batches"] == 8
        assert after["waves"] - before["waves"] == 1
        assert after["sub_waves"] - before["sub_waves"] == 2
        assert _count_log_files(tbl_log) - t1 == 2 * per_commit

    def test_byte_identical_with_group_commit_off(self, tmp_path):
        """The SAME batch sequence committed as one 8-wide wave and as
        8 serial per-batch commits (groupCommit.enabled=false) answers
        queries byte-identically."""
        frames = [_frame(_rng(100 + i), 60) for i in range(8)]

        s_on, hs_on, d_on = _mk_lake(tmp_path / "on")
        for f in frames:
            hs_on.append(d_on, f)
        results, errors = _concurrent_commits(hs_on, d_on, 8)
        assert not errors, errors
        assert max(r["committed_batches"] for r in results) == 8

        s_off, hs_off, d_off = _mk_lake(
            tmp_path / "off", **{SC.GROUP_COMMIT_ENABLED: "false"})
        for f in frames:
            hs_off.append(d_off, f)
            out = hs_off.commit(d_off)
            assert out["committed_batches"] == 1
            assert "joined_wave" not in out

        a_on, b_on = _answers(s_on, d_on)
        a_off, b_off = _answers(s_off, d_off)
        pd.testing.assert_frame_equal(a_on, b_on)
        pd.testing.assert_frame_equal(a_off, b_off)
        pd.testing.assert_frame_equal(a_on, a_off)

    def test_off_switch_restores_per_commit_behavior(self, tmp_path):
        """groupCommit.enabled=false: the coordinator is never
        consulted, every commit pays its own op-log entry, and no
        StreamingWaveEvent is emitted."""
        session, hs, data = _mk_lake(
            tmp_path, capture=True,
            **{SC.GROUP_COMMIT_ENABLED: "false"})
        rng = _rng(41)
        hs.append(data, _frame(rng, 40))
        hs.commit(data)
        tbl_log = table_log_dir(session, data)
        hs.append(data, _frame(rng, 40))
        t0 = _count_log_files(tbl_log)
        hs.commit(data)
        per_commit = _count_log_files(tbl_log) - t0

        before = get_coordinator().stats()
        t1 = _count_log_files(tbl_log)
        sink().events.clear()
        for _ in range(3):
            hs.append(data, _frame(rng, 40))
            hs.commit(data)
        after = get_coordinator().stats()
        assert after["commit_calls"] == before["commit_calls"]
        assert _count_log_files(tbl_log) - t1 == 3 * per_commit
        assert not [e for e in sink().events
                    if isinstance(e, StreamingWaveEvent)]


# ---------------------------------------------------------------------------
# Blocking backpressure on the staged-batch budget.
# ---------------------------------------------------------------------------

class TestBlockingBackpressure:
    def test_blocked_append_parks_until_commit_frees(self, tmp_path):
        session, hs, data = _mk_lake(
            tmp_path, index=False, **{SC.MAX_STAGED_BATCHES: "1"})
        hs.append(data, _frame(_rng(51), 40))  # budget now full
        done = threading.Event()
        caught = []

        def blocked():
            try:
                ingest.append(session, data, _frame(_rng(52), 40),
                              block=True)
            except Exception as e:
                caught.append(e)
            finally:
                done.set()

        t = threading.Thread(target=blocked)
        start = time.monotonic()
        t.start()
        time.sleep(0.3)
        assert not done.is_set(), "append did not block on full budget"
        hs.commit(data)  # frees the budget; the waiter lands
        t.join(60)
        assert done.is_set() and not caught, caught
        assert time.monotonic() - start >= 0.25
        out = hs.commit(data)
        assert out["committed_batches"] == 1

    def test_blocked_append_times_out(self, tmp_path):
        session, hs, data = _mk_lake(
            tmp_path, index=False,
            **{SC.MAX_STAGED_BATCHES: "1",
               SC.BACKPRESSURE_TIMEOUT_MS: "200"})
        hs.append(data, _frame(_rng(53), 40))
        with pytest.raises(HyperspaceException, match="timed out"):
            ingest.append(session, data, _frame(_rng(54), 40),
                          block=True)
        # The staged batch survived the stranger's timeout.
        assert hs.commit(data)["committed_batches"] == 1

    def test_default_stays_raise_on_full(self, tmp_path):
        session, hs, data = _mk_lake(
            tmp_path, index=False, **{SC.MAX_STAGED_BATCHES: "1"})
        hs.append(data, _frame(_rng(55), 40))
        t0 = time.monotonic()
        with pytest.raises(HyperspaceException,
                           match="maxStagedBatches"):
            hs.append(data, _frame(_rng(56), 40))
        assert time.monotonic() - t0 < 5.0  # immediate, no park


# ---------------------------------------------------------------------------
# Kill -9 mid-wave: whole-wave atomicity under crash.
# ---------------------------------------------------------------------------

_WAVE_CHILD = textwrap.dedent("""
    import os, sys
    import numpy as np
    import pandas as pd

    spec, data_dir, sys_dir = sys.argv[1:4]

    import hyperspace_tpu as hst
    from hyperspace_tpu.api import Hyperspace, IndexConfig

    session = hst.Session(system_path=sys_dir)
    session.conf.set("hyperspace.index.numBuckets", 4)
    session.conf.set("hyperspace.index.lineage.enabled", "true")
    session.conf.set("hyperspace.tpu.distributed.enabled", "false")
    hs = Hyperspace(session)

    rng = np.random.default_rng(41)
    def frame(n):
        return pd.DataFrame({
            "k": rng.integers(0, 40, n).astype(np.int64),
            "v": rng.integers(0, 9, n).astype(np.int64)})

    # A healthy first commit establishes the table log.
    hs.append(data_dir, frame(150))
    hs.commit(data_dir)

    # Stage a 4-wide wave, then die publishing it.
    for _ in range(4):
        hs.append(data_dir, frame(200))
    session.conf.set(
        "hyperspace.tpu.robustness.faults.ingest.publish", spec)
    hs.commit(data_dir)
    print("CHILD-SURVIVED")
""")


class TestKill9MidWave:
    def test_kill9_rolls_back_the_whole_wave(self, tmp_path):
        """A SIGKILL during a 4-wide wave's publication leaves nothing
        behind after recover(): not one of the wave's batches is
        visible — per-wave atomicity, not per-batch."""
        data = str(tmp_path / "tbl")
        _write_base(data, _rng())
        (tmp_path / "indexes").mkdir(exist_ok=True)
        session = _mk_session(tmp_path)
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(data),
                        IndexConfig("cx", ["k"], ["v"]))

        script = str(tmp_path / "wave_child.py")
        with open(script, "w") as f:
            f.write(_WAVE_CHILD)
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        env["PYTHONPATH"] = ROOT + os.pathsep + env.get(
            "PYTHONPATH", "")
        proc = subprocess.run(
            [sys.executable, script, "kill:nth=1", data,
             str(tmp_path / "indexes")],
            env=env, capture_output=True, text=True, timeout=420,
            cwd=ROOT)
        assert proc.returncode == -signal.SIGKILL, \
            f"rc={proc.returncode}\n{proc.stdout}\n{proc.stderr}"
        assert "CHILD-SURVIVED" not in proc.stdout

        mgr = IndexLogManager(table_log_dir(session, data))
        assert mgr.get_latest_log().state == States.REFRESHING

        summary = hs.recover()
        assert not summary["errors"], summary
        stream = summary["streaming"]
        key = table_key(data)
        assert key in stream["rolled_back"]
        assert stream["staging_swept"] >= 1

        # Exactly the pre-crash committed state: base + the first
        # healthy batch. None of the 4-wide wave survived.
        files = session.read.parquet(data).plan.relation.all_files()
        assert len(files) == 2
        assert mgr.get_latest_log().state in STABLE_STATES
        a, b = _answers(session, data)
        pd.testing.assert_frame_equal(a, b)

        # The recovered lake ingests again — as a wave.
        for _ in range(4):
            hs.append(data, _frame(_rng(77), 120))
        out = hs.commit(data)
        assert out["committed_batches"] == 4
        a2, b2 = _answers(session, data)
        pd.testing.assert_frame_equal(a2, b2)


# ---------------------------------------------------------------------------
# Subscription fan-out: one shared scan per template group per wave.
# ---------------------------------------------------------------------------

class TestSubscriptionFanout:
    def _lake(self, tmp_path, capture=True, **conf):
        """Plain table, NO covering index and hyperspace disabled: the
        standing plans must stay Filter-over-Scan so the literal
        batcher's shared-scan hook engages (an IndexScan rewrite would
        bypass it — test_serving_frontend pins that contract)."""
        root = tmp_path
        root.mkdir(exist_ok=True)
        data = str(root / "tbl")
        _write_base(data, _rng())
        session = _mk_session(root, capture=capture, **conf)
        return session, Hyperspace(session), data

    def _variant(self, session, data, i):
        return (session.read.parquet(data)
                .filter(col("k") < (i % 37) + 2).group_by("k")
                .agg(sum_(col("v")).alias("sv")).sort("k"))

    @pytest.mark.parametrize("n", [10, 100, 1000])
    def test_fanout_one_shared_scan_exactly_once(self, tmp_path, n):
        session, hs, data = self._lake(
            tmp_path, **{SC.SUBSCRIPTIONS_MAX: "1200"})
        front = _fresh_frontend(session)
        subs = [front.subscribe(self._variant(session, data, i))
                for i in range(n)]
        before = front.stats()
        sink().events.clear()

        hs.append(data, _frame(_rng(61), 300))
        out = hs.commit(data)
        assert out["subscriptions_fired"] == n

        for sub in subs:
            ds = sub.wait_for(1, timeout=180.0)
            assert len(ds) == 1 and ds[0].ok, getattr(
                ds[0], "error", None)

        # Deliveries land member-by-member INSIDE the batch loop while
        # the wave's counters are noted once AFTER it (plus sweep-trace
        # retention) — so wait_for can return a beat before the batch
        # thread publishes stats. Give the counters a moment to settle.
        deadline = time.monotonic() + 30.0
        while (front.stats()["batches"] == before["batches"]
               and time.monotonic() < deadline):
            time.sleep(0.05)
        after = front.stats()
        # ONE wave for the whole fan-out: every same-template fire
        # shares one scan and one vmapped sweep invocation.
        assert after["batches"] - before["batches"] == 1
        assert after["batched_queries"] - before["batched_queries"] == n
        assert after["sweep_invocations"] - \
            before["sweep_invocations"] == 1
        assert after["shared_scans"] - before["shared_scans"] == 1
        assert after["shared_scan_hits"] - \
            before["shared_scan_hits"] == n - 1

        regs = after["subscriptions"]
        regs_before = before["subscriptions"]
        assert regs["wave_groups"] - regs_before["wave_groups"] == 1
        assert regs["wave_members"] - regs_before["wave_members"] == n
        assert regs["fired_queries"] - \
            regs_before["fired_queries"] == n
        assert regs["rejected_queries"] == regs_before[
            "rejected_queries"]

        # Exactly once: one delivery per subscription, no more arrive.
        assert all(s.delivered_total == 1 for s in subs)

        fired = [e for e in sink().events
                 if isinstance(e, StandingQueryEvent)]
        assert len(fired) == 1
        assert fired[0].fired == n and fired[0].groups == 1

        # Spot-check answers: each subscription got ITS literal's rows,
        # byte-identical to submitting the same plan ad hoc.
        for i in (0, n // 2, n - 1):
            want = _delivery_pd(front.submit(
                self._variant(session, data, i)).result(timeout=120.0))
            got = _delivery_pd(subs[i].latest(timeout=10.0).result)
            pd.testing.assert_frame_equal(got, want)

    def test_batching_off_falls_back_to_singles(self, tmp_path):
        """serving.batching.enabled=false: fires run as N independent
        submissions (no wave groups), same deliveries."""
        session, hs, data = self._lake(tmp_path)
        front = _fresh_frontend(
            session, **{"hyperspace.tpu.serving.batching.enabled":
                        "false"})
        subs = [front.subscribe(self._variant(session, data, i))
                for i in range(6)]
        before = front.stats()
        hs.append(data, _frame(_rng(62), 100))
        assert hs.commit(data)["subscriptions_fired"] == 6
        for sub in subs:
            assert sub.wait_for(1, timeout=120.0)[0].ok
        after = front.stats()
        assert after["subscriptions"]["wave_groups"] == \
            before["subscriptions"]["wave_groups"]
        assert after["batches"] == before["batches"]

    def test_mixed_templates_one_group_per_template(self, tmp_path):
        """Two distinct templates on one table: one commit fires one
        wave PER template group; the lone odd-one-out runs single."""
        session, hs, data = self._lake(
            tmp_path, **{SC.SUBSCRIPTIONS_MAX: "64"})
        front = _fresh_frontend(session)
        agg = [front.subscribe(self._variant(session, data, i))
               for i in range(4)]
        sel = [front.subscribe(
            session.read.parquet(data).filter(col("k") == i)
            .select("k", "v")) for i in range(3)]
        lone = front.subscribe(
            session.read.parquet(data).group_by("v")
            .agg(sum_(col("k")).alias("sk")))
        before = front.stats()
        hs.append(data, _frame(_rng(63), 100))
        assert hs.commit(data)["subscriptions_fired"] == 8
        for sub in agg + sel + [lone]:
            assert sub.wait_for(1, timeout=120.0)[0].ok
        after = front.stats()
        regs, regs0 = after["subscriptions"], before["subscriptions"]
        assert regs["wave_groups"] - regs0["wave_groups"] == 2
        assert regs["wave_members"] - regs0["wave_members"] == 7
        assert regs["fired_queries"] - regs0["fired_queries"] == 8


# ---------------------------------------------------------------------------
# Cluster coalescing: one broadcast per wave, lost peers degrade.
# ---------------------------------------------------------------------------

class TestBroadcastCoalescing:
    @pytest.fixture(autouse=True)
    def _fresh_cluster(self):
        yield
        from hyperspace_tpu.cluster import worker
        worker.shutdown_for_tests()

    def _node(self, tmp_path):
        from hyperspace_tpu.cluster import membership, worker
        from hyperspace_tpu.cluster.constants import (
            ClusterConstants as CC)
        session, hs, data = _mk_lake(
            tmp_path, capture=True,
            **{CC.ENABLED: "true", CC.WORKER_ID: "w-solo",
               CC.FORWARD_TIMEOUT_MS: "300"})
        node = worker.get_node(session)
        assert node is not None
        # An unreachable peer: every notice to it fails.
        root = membership.membership_dir(session)
        os.makedirs(root, exist_ok=True)
        now = time.time() * 1000.0
        with open(os.path.join(root, "member-w-gone.json"), "w",
                  encoding="utf-8") as f:
            f.write(json.dumps({
                "worker_id": "w-gone", "host": "127.0.0.1", "port": 1,
                "pid": 999999, "started_ms": now,
                "heartbeat_ms": now}))
        return session, hs, data, node

    def test_one_broadcast_per_wave_carries_width(self, tmp_path):
        session, hs, data, node = self._node(tmp_path)
        rng = _rng(71)
        for _ in range(16):
            hs.append(data, _frame(rng, 40))
        sink().events.clear()
        failures_before = node.stats()["broadcast_failures"]
        results, errors = _concurrent_commits(hs, data, 16)
        assert not errors, errors
        assert max(r["committed_batches"] for r in results) == 16

        # ONE notice for the whole wave, stamped with its width — not
        # 16 per-batch notices.
        notices = [e for e in sink().events
                   if isinstance(e, ClusterBroadcastEvent)]
        assert len(notices) == 1
        assert notices[0].batches == 16
        assert notices[0].peers == 1 and notices[0].delivered == 0

        # The dead peer cost its own firing only: the commit landed.
        assert node.stats()["broadcast_failures"] > failures_before
        a, b = _answers(session, data)
        pd.testing.assert_frame_equal(a, b)

    def test_injected_broadcast_fault_never_fails_commit(self,
                                                         tmp_path):
        session, hs, data, node = self._node(tmp_path)
        for _ in range(4):
            hs.append(data, _frame(_rng(72), 40))
        reg = FaultRegistry.from_conf_specs(
            {FN.CLUSTER_BROADCAST: "error:p=1"}, seed=7)
        failures_before = node.stats()["broadcast_failures"]
        with faults.scope(reg):
            out = hs.commit(data)
        assert out["committed_batches"] == 4
        assert reg.hit_count(FN.CLUSTER_BROADCAST) >= 1
        assert node.stats()["broadcast_failures"] > failures_before
        a, b = _answers(session, data)
        pd.testing.assert_frame_equal(a, b)


# ---------------------------------------------------------------------------
# Continuous sources: tailing daemons drive append/commit themselves.
# ---------------------------------------------------------------------------

class TestContinuousSources:
    def _lake(self, tmp_path, **conf):
        conf.setdefault(SC.SOURCE_POLL_MS, "20")
        return _mk_lake(tmp_path, index=False, **conf)

    def _drop(self, watch, name, frame):
        tmp = os.path.join(watch, name + ".tmp")
        pq.write_table(pa.Table.from_pandas(frame), tmp)
        os.replace(tmp, os.path.join(watch, name))

    def test_directory_tail_ingests_and_commits(self, tmp_path):
        session, hs, data = self._lake(
            tmp_path, capture=True, **{SC.SOURCE_COMMIT_BATCHES: "2"})
        watch = str(tmp_path / "drop")
        os.makedirs(watch)
        rows_before = len(session.read.parquet(data).to_pandas())
        frames = [_frame(_rng(80 + i), 30) for i in range(5)]
        for i, f in enumerate(frames):
            self._drop(watch, f"b{i}.parquet", f)
        src = DirectoryTailSource(session, watch, data).start()
        try:
            _wait_until(lambda: src.stats()["batches"] == 5,
                        msg="5 batches tailed")
            assert src.running()
        finally:
            out = src.stop(drain=True)
        assert out["commits"] >= 3  # 2 flushes of 2 + the drain
        assert out["errors"] == 0 and out["pending"] == 0
        assert not src.running()
        rows = len(session.read.parquet(data).to_pandas())
        assert rows == rows_before + sum(len(f) for f in frames)
        assert any(isinstance(e, StreamingSourceEvent)
                   for e in sink().events)

    def test_log_tail_consumes_only_complete_lines(self, tmp_path):
        session, hs, data = self._lake(tmp_path)
        log = str(tmp_path / "events.jsonl")
        lines = [json.dumps({"k": int(i % 40), "v": int(i % 9)})
                 for i in range(6)]
        with open(log, "w") as f:
            f.write("\n".join(lines) + "\n")
            f.write('{"k": 3, "v"')  # producer mid-write
        rows_before = len(session.read.parquet(data).to_pandas())
        src = LogTailSource(session, log, data).start()
        try:
            _wait_until(lambda: src.stats()["rows"] == 6,
                        msg="complete lines tailed")
            # The partial line is never consumed...
            time.sleep(0.2)
            assert src.stats()["rows"] == 6
            # ...until the producer finishes it.
            with open(log, "a") as f:
                f.write(': 5}\n')
            _wait_until(lambda: src.stats()["rows"] == 7,
                        msg="completed line tailed")
        finally:
            src.stop(drain=True)
        rows = len(session.read.parquet(data).to_pandas())
        assert rows == rows_before + 7

    def test_source_survives_injected_faults(self, tmp_path):
        """An armed streaming.source fault (error:times=2) costs two
        polls, after which the daemon keeps tailing — counters span
        polls because the source arms ONE fault scope for its life."""
        session, hs, data = self._lake(tmp_path)
        session.conf.set(
            "hyperspace.tpu.robustness.faults."
            + FN.STREAMING_SOURCE, "error:times=2")
        watch = str(tmp_path / "drop")
        os.makedirs(watch)
        self._drop(watch, "b0.parquet", _frame(_rng(85), 30))
        src = DirectoryTailSource(session, watch, data).start()
        try:
            _wait_until(lambda: src.stats()["batches"] == 1,
                        msg="batch landed despite faults")
            stats = src.stats()
            assert stats["errors"] == 2
            assert src.running()
        finally:
            out = src.stop(drain=True)
        assert out["errors"] == 2

    def test_admission_pause_stops_pulling_input(self, tmp_path):
        """While admission reports overload the tailer pulls NOTHING;
        when the breach clears it resumes where it left off."""
        from hyperspace_tpu.adaptive.admission import get_controller
        session, hs, data = self._lake(
            tmp_path, **{"hyperspace.tpu.adaptive.enabled": "true"})
        watch = str(tmp_path / "drop")
        os.makedirs(watch)
        self._drop(watch, "b0.parquet", _frame(_rng(86), 30))
        controller = get_controller()
        controller.reset()
        try:
            controller._overloaded = True
            controller._last_refresh = time.monotonic()
            src = DirectoryTailSource(session, watch, data).start()
            try:
                deadline = time.monotonic() + 30.0
                while src.stats()["pauses"] < 3:
                    # Keep the cached verdict fresh past the 1s
                    # re-evaluation window.
                    controller._overloaded = True
                    controller._last_refresh = time.monotonic()
                    assert time.monotonic() < deadline, "never paused"
                    time.sleep(0.02)
                assert src.stats()["batches"] == 0  # nothing pulled
                controller._overloaded = False
                controller._last_refresh = time.monotonic()
                _wait_until(lambda: src.stats()["batches"] == 1,
                            msg="resumed after breach cleared")
            finally:
                src.stop(drain=True)
        finally:
            controller.reset()

    def test_blocked_source_frees_on_external_commit(self, tmp_path):
        """A tailer that outruns the staged budget parks in blocking
        append (counted in waits) and resumes when ANY committer frees
        the table."""
        session, hs, data = self._lake(
            tmp_path, **{SC.MAX_STAGED_BATCHES: "2",
                         SC.SOURCE_COMMIT_BATCHES: "100"})
        watch = str(tmp_path / "drop")
        os.makedirs(watch)
        for i in range(3):
            self._drop(watch, f"b{i}.parquet", _frame(_rng(87 + i), 30))
        src = DirectoryTailSource(session, watch, data).start()
        try:
            _wait_until(
                lambda: ingest.get_queue().staged_count(data) >= 2,
                msg="budget filled")
            hs.commit(data)  # an external commit frees the waiter
            _wait_until(lambda: src.stats()["batches"] == 3,
                        msg="tail resumed after commit")
        finally:
            out = src.stop(drain=True)
        assert out["waits"] >= 1
        rows = len(session.read.parquet(data).to_pandas())
        assert rows == 2000 + 3 * 30


# ---------------------------------------------------------------------------
# Registries: the r22 names exist, and tracing records the spans.
# ---------------------------------------------------------------------------

class TestScaleRegistries:
    def test_names_registered(self):
        assert SN.INGEST_WAVE == "ingest.wave"
        assert SN.INGEST_SOURCE == "ingest.source"
        assert {SN.INGEST_WAVE, SN.INGEST_SOURCE} <= SN.SPAN_NAMES
        assert FN.STREAMING_SOURCE == "streaming.source"
        assert FN.STREAMING_SOURCE in FN.FAULT_NAMES

    def _span_names_of(self, trace):
        return [s.name for s in trace.spans] \
            if hasattr(trace, "spans") else \
            [s.name for s in trace._spans]

    def test_wave_span_recorded_under_tracing(self, tmp_path):
        session, hs, data = _mk_lake(tmp_path, index=False)
        session.conf.set("hyperspace.tpu.telemetry.trace.enabled",
                         "true")
        hs.append(data, _frame(_rng(91), 40))
        hs.append(data, _frame(_rng(92), 40))
        out = hs.commit(data)
        assert out["committed_batches"] == 2
        assert SN.INGEST_WAVE in self._span_names_of(
            session._last_trace)

    def test_source_span_recorded_under_tracing(self, tmp_path):
        # commitBatches=1: the commit lands INSIDE the source's poll
        # trace (maintenance_trace is reentrancy-aware), so the fully
        # drained stop() below never opens a later trace that would
        # shadow ``_last_trace``.
        session, hs, data = _mk_lake(
            tmp_path, index=False,
            **{SC.SOURCE_POLL_MS: "20", SC.SOURCE_COMMIT_BATCHES: "1"})
        session.conf.set("hyperspace.tpu.telemetry.trace.enabled",
                         "true")
        watch = str(tmp_path / "drop")
        os.makedirs(watch)
        tmp = os.path.join(watch, "b0.parquet.tmp")
        pq.write_table(pa.Table.from_pandas(_frame(_rng(93), 30)), tmp)
        os.replace(tmp, os.path.join(watch, "b0.parquet"))
        src = DirectoryTailSource(session, watch, data).start()
        try:
            _wait_until(lambda: src.stats()["commits"] == 1,
                        msg="source poll traced")
        finally:
            src.stop(drain=True)
        names = self._span_names_of(session._last_trace)
        assert SN.INGEST_SOURCE in names
        assert SN.INGEST_WAVE in names  # the commit nested in the poll
