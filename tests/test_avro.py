"""Avro source format (util/avro.py + sources/default.py registration).

Closes the last format gap vs the reference's default provider
(DefaultFileBasedSource.scala:37-44: avro/csv/json/orc/parquet/text).
The OCF reader/writer is self-contained, so these tests exercise the
binary encoding itself (zigzag varints, unions, deflate blocks, sync
markers) plus the engine integration: scan, filter, and a covering index
built over avro sources with disable-and-compare.
"""

import datetime
import io
import json
import struct
import zlib

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.plan.expr import col
from hyperspace_tpu.util.avro import (_encode_bytes, _encode_long, read_avro,
                                      read_avro_schema, write_avro)


def _sample_table(n=1000, seed=5, nulls=False):
    rng = np.random.default_rng(seed)
    cols = {
        "id": pa.array(np.arange(n, dtype=np.int64)),
        "small": pa.array(rng.integers(0, 100, n).astype(np.int32)),
        "price": pa.array(rng.random(n) * 10),
        "flag": pa.array(rng.integers(0, 2, n).astype(bool)),
        "name": pa.array(rng.choice(["alpha", "beta", "gamma"], n)),
        "day": pa.array(
            [datetime.date(2024, 1, 1) + datetime.timedelta(days=int(d))
             for d in rng.integers(0, 300, n)], type=pa.date32()),
    }
    if nulls:
        mask = rng.random(n) < 0.15
        vals = rng.integers(0, 50, n)
        cols["maybe"] = pa.array(
            [None if m else int(v) for m, v in zip(mask, vals)],
            type=pa.int64())
    return pa.table(cols)


class TestRoundTrip:
    def test_all_primitive_types(self, tmp_path):
        t = _sample_table()
        p = str(tmp_path / "t.avro")
        write_avro(t, p)
        back = read_avro(p)
        assert back.schema.names == t.schema.names
        pd.testing.assert_frame_equal(back.to_pandas(), t.to_pandas())

    def test_nullable_union(self, tmp_path):
        t = _sample_table(nulls=True)
        p = str(tmp_path / "n.avro")
        write_avro(t, p)
        back = read_avro(p)
        assert back.column("maybe").null_count == t.column("maybe").null_count
        pd.testing.assert_frame_equal(back.to_pandas(), t.to_pandas())

    def test_empty_table(self, tmp_path):
        t = _sample_table(n=0)
        p = str(tmp_path / "e.avro")
        write_avro(t, p)
        back = read_avro(p)
        assert back.num_rows == 0
        assert back.schema.names == t.schema.names

    def test_column_projection(self, tmp_path):
        t = _sample_table()
        p = str(tmp_path / "prj.avro")
        write_avro(t, p)
        back = read_avro(p, columns=["name", "id"])
        assert back.schema.names == ["name", "id"]
        with pytest.raises(HyperspaceException, match="not in"):
            read_avro(p, columns=["nope"])

    def test_header_only_schema(self, tmp_path):
        t = _sample_table(nulls=True)
        p = str(tmp_path / "s.avro")
        write_avro(t, p)
        sch = read_avro_schema(p)
        assert sch.field("maybe").nullable
        assert sch.field("day").type == pa.date32()
        assert sch.field("small").type == pa.int32()


def _write_deflate_ocf(path, rows):
    """Hand-rolled deflate-codec OCF with two blocks (the writer only emits
    the null codec, so the deflate read path needs its own fixture)."""
    schema = {"type": "record", "name": "R", "fields": [
        {"name": "k", "type": "long"},
        {"name": "s", "type": "string"},
    ]}
    sync = b"0123456789abcdef"
    out = io.BytesIO()
    out.write(b"Obj\x01")
    out.write(_encode_long(2))
    out.write(_encode_bytes(b"avro.schema"))
    out.write(_encode_bytes(json.dumps(schema).encode()))
    out.write(_encode_bytes(b"avro.codec"))
    out.write(_encode_bytes(b"deflate"))
    out.write(_encode_long(0))
    out.write(sync)
    half = len(rows) // 2
    for chunk in (rows[:half], rows[half:]):
        body = b"".join(
            _encode_long(k) + _encode_bytes(s.encode()) for k, s in chunk)
        comp = zlib.compress(body)[2:-4]  # raw deflate: strip zlib wrapper
        out.write(_encode_long(len(chunk)))
        out.write(_encode_long(len(comp)))
        out.write(comp)
        out.write(sync)
    with open(path, "wb") as fh:
        fh.write(out.getvalue())


class TestBinaryFormat:
    def test_deflate_codec_multi_block(self, tmp_path):
        rows = [(i * 7 - 50, f"row{i}") for i in range(501)]
        p = str(tmp_path / "d.avro")
        _write_deflate_ocf(p, rows)
        back = read_avro(p)
        assert back.column("k").to_pylist() == [k for k, _ in rows]
        assert back.column("s").to_pylist() == [s for _, s in rows]

    def test_zero_row_block_mid_file(self, tmp_path):
        """Zero-object blocks (legal, emitted by some writers on flush)
        must not disable the native path mid-file — that silently dropped
        every row decoded after the empty block."""
        schema = {"type": "record", "name": "R", "fields": [
            {"name": "k", "type": "long"}]}
        sync = b"0123456789abcdef"
        out = io.BytesIO()
        out.write(b"Obj\x01")
        out.write(_encode_long(1))
        out.write(_encode_bytes(b"avro.schema"))
        out.write(_encode_bytes(json.dumps(schema).encode()))
        out.write(_encode_long(0))
        out.write(sync)
        for chunk in ([1, 2], [], [3, 4]):
            body = b"".join(_encode_long(v) for v in chunk)
            out.write(_encode_long(len(chunk)))
            out.write(_encode_long(len(body)))
            out.write(body)
            out.write(sync)
        p = tmp_path / "zb.avro"
        p.write_bytes(out.getvalue())
        assert read_avro(str(p)).column("k").to_pylist() == [1, 2, 3, 4]

    def test_zigzag_negative_longs(self, tmp_path):
        t = pa.table({"v": pa.array([0, -1, 1, -2**62, 2**62], pa.int64())})
        p = str(tmp_path / "z.avro")
        write_avro(t, p)
        assert read_avro(p).column("v").to_pylist() == \
            [0, -1, 1, -2**62, 2**62]

    def test_null_second_union_branch_order(self, tmp_path):
        """["T", "null"] is as legal as ["null", T]; the null branch index
        must come from the schema, not be assumed 0 (decoding [5, null]
        with the assumption yields [None, 1] — silent corruption)."""
        schema = {"type": "record", "name": "R", "fields": [
            {"name": "v", "type": ["long", "null"]}]}
        sync = b"0123456789abcdef"
        out = io.BytesIO()
        out.write(b"Obj\x01")
        out.write(_encode_long(1))
        out.write(_encode_bytes(b"avro.schema"))
        out.write(_encode_bytes(json.dumps(schema).encode()))
        out.write(_encode_long(0))
        out.write(sync)
        body = (_encode_long(0) + _encode_long(5)  # branch 0 = long 5
                + _encode_long(1))                 # branch 1 = null
        out.write(_encode_long(2))
        out.write(_encode_long(len(body)))
        out.write(body)
        out.write(sync)
        p = tmp_path / "bo.avro"
        p.write_bytes(out.getvalue())
        assert read_avro(str(p)).column("v").to_pylist() == [5, None]

    def test_truncated_varint_is_loud_domain_error(self, tmp_path):
        p = tmp_path / "tr.avro"
        p.write_bytes(b"Obj\x01" + b"\x80\x80")  # varint never terminates
        with pytest.raises(HyperspaceException, match="truncated"):
            read_avro(str(p))

    def test_write_schema_nullability_not_data_dependent(self, tmp_path):
        """A nullable column slice that happens to contain no nulls must
        still be written as a null union, or multi-file datasets get
        inconsistent schemas (engine reads schema from files[0] only)."""
        t = _sample_table(nulls=True)
        no_null_slice = t.filter(pa.compute.is_valid(t.column("maybe")))
        p = str(tmp_path / "nn.avro")
        write_avro(no_null_slice, p)
        assert read_avro_schema(p).field("maybe").nullable

    def test_huge_corrupt_string_length_is_loud(self, tmp_path):
        """A crafted block claiming a string of ~INT64_MAX bytes must fail
        cleanly (the naive `pos + n > len` bounds check overflows signed
        int64 in C++ and would memcpy past the buffer)."""
        schema = {"type": "record", "name": "R", "fields": [
            {"name": "s", "type": "string"}]}
        sync = b"0123456789abcdef"
        out = io.BytesIO()
        out.write(b"Obj\x01")
        out.write(_encode_long(1))
        out.write(_encode_bytes(b"avro.schema"))
        out.write(_encode_bytes(json.dumps(schema).encode()))
        out.write(_encode_long(0))
        out.write(sync)
        body = _encode_long(2**62) + b"xy"  # huge claimed length
        out.write(_encode_long(1))
        out.write(_encode_long(len(body)))
        out.write(body)
        out.write(sync)
        p = tmp_path / "huge.avro"
        p.write_bytes(out.getvalue())
        with pytest.raises(HyperspaceException, match="truncated"):
            read_avro(str(p))

    def test_bad_magic_rejected(self, tmp_path):
        p = tmp_path / "bad.avro"
        p.write_bytes(b"NOPE" + b"\x00" * 64)
        with pytest.raises(HyperspaceException, match="bad magic"):
            read_avro(str(p))

    def test_unsupported_complex_type_loud(self, tmp_path):
        schema = {"type": "record", "name": "R", "fields": [
            {"name": "a", "type": {"type": "array", "items": "long"}}]}
        out = io.BytesIO()
        out.write(b"Obj\x01")
        out.write(_encode_long(1))
        out.write(_encode_bytes(b"avro.schema"))
        out.write(_encode_bytes(json.dumps(schema).encode()))
        out.write(_encode_long(0))
        out.write(b"0123456789abcdef")
        p = tmp_path / "cx.avro"
        p.write_bytes(out.getvalue())
        with pytest.raises(HyperspaceException, match="unsupported"):
            read_avro(str(p))


class TestNativeDecoder:
    def test_native_and_python_decodes_identical(self, tmp_path, monkeypatch):
        """The C++ block decoder (native/hst_native.cpp) and the Python
        row loop must produce bit-identical tables — every type, nulls,
        dates, strings, multi-block deflate."""
        from hyperspace_tpu import native as hst_native
        if not hst_native.available():
            pytest.skip("no native toolchain")
        t = _sample_table(n=5000, nulls=True)
        p1 = str(tmp_path / "x.avro")
        write_avro(t, p1)
        p2 = str(tmp_path / "d.avro")
        _write_deflate_ocf(p2, [(i - 100, f"s{i}") for i in range(999)])
        native_tables = [read_avro(p1), read_avro(p2)]
        monkeypatch.setattr(hst_native, "avro_decode_block",
                            lambda *a, **k: None)  # force the Python loop
        python_tables = [read_avro(p1), read_avro(p2)]
        for nt, pt in zip(native_tables, python_tables):
            assert nt.equals(pt)

    def test_native_rejects_corrupt_block(self, tmp_path):
        from hyperspace_tpu import native as hst_native
        if not hst_native.available():
            pytest.skip("no native toolchain")
        schema = {"type": "record", "name": "R", "fields": [
            {"name": "s", "type": "string"}]}
        out = io.BytesIO()
        out.write(b"Obj\x01")
        out.write(_encode_long(1))
        out.write(_encode_bytes(b"avro.schema"))
        out.write(_encode_bytes(json.dumps(schema).encode()))
        out.write(_encode_long(0))
        sync = b"0123456789abcdef"
        out.write(sync)
        body = _encode_long(1000) + b"xy"  # claims 500-byte string, has 2
        out.write(_encode_long(1))
        out.write(_encode_long(len(body)))
        out.write(body)
        out.write(sync)
        p = tmp_path / "corrupt.avro"
        p.write_bytes(out.getvalue())
        with pytest.raises(HyperspaceException, match="truncated"):
            read_avro(str(p))


class TestEngineIntegration:
    @pytest.fixture()
    def env(self, tmp_path):
        t = _sample_table(n=30_000, nulls=True)
        d = tmp_path / "avrodata"
        d.mkdir()
        n = t.num_rows
        write_avro(t.slice(0, n // 2), str(d / "a.avro"))
        write_avro(t.slice(n // 2), str(d / "b.avro"))
        session = hst.Session(system_path=str(tmp_path / "indexes"))
        return dict(session=session, hs=Hyperspace(session),
                    path=str(d), df=t.to_pandas())

    def test_scan_and_filter(self, env):
        session, df = env["session"], env["df"]
        q = session.read.avro(env["path"]).where(col("small") == 42)
        got = q.to_pandas()
        exp = df[df.small == 42]
        assert len(got) == len(exp)

    def test_covering_index_over_avro(self, env):
        session, hs, df = env["session"], env["hs"], env["df"]
        t = session.read.avro(env["path"])
        hs.create_index(t, IndexConfig("av_idx", ["small"], ["price", "name"]))
        q = t.select("small", "price", "name").where(col("small") == 7)
        session.enable_hyperspace()
        from hyperspace_tpu.plan.nodes import IndexScan
        leaves = q.optimized_plan().collect_leaves()
        assert isinstance(leaves[0], IndexScan)
        got = q.to_pandas().sort_values(["small", "price"]) \
               .reset_index(drop=True)
        session.disable_hyperspace()
        raw = q.to_pandas().sort_values(["small", "price"]) \
               .reset_index(drop=True)
        pd.testing.assert_frame_equal(got, raw)
        exp = df[df.small == 7]
        assert len(got) == len(exp)


class TestDeflateWrite:
    def test_deflate_round_trip_multi_block(self, tmp_path):
        """Writer-side deflate: multi-block compressed file reads back
        bit-identical (both by our reader's null-codec expectations and
        across the native/python decode paths)."""
        t = _sample_table(n=5000, nulls=True)
        p = str(tmp_path / "defl.avro")
        write_avro(t, p, codec="deflate", block_rows=1200)
        back = read_avro(p)
        pd.testing.assert_frame_equal(back.to_pandas(), t.to_pandas())
        import os
        null_p = str(tmp_path / "plain.avro")
        write_avro(t, null_p)
        # Compressed output should actually be smaller on this data.
        assert os.path.getsize(p) < os.path.getsize(null_p)

    def test_unknown_codec_rejected(self, tmp_path):
        with pytest.raises(HyperspaceException, match="unsupported codec"):
            write_avro(_sample_table(n=4), str(tmp_path / "x.avro"),
                       codec="snappy")

    def test_bad_block_rows_is_loud(self, tmp_path):
        with pytest.raises(HyperspaceException, match="block_rows"):
            write_avro(_sample_table(n=4), str(tmp_path / "y.avro"),
                       block_rows=0)
        with pytest.raises(HyperspaceException, match="block_rows"):
            write_avro(_sample_table(n=4), str(tmp_path / "y.avro"),
                       block_rows=-1)
