"""Next-gen rule framework tests: CandidateIndexCollector filter chain,
whyNot reason tagging, and the score-based index plan optimizer.

Parity: CandidateIndexCollectorTest / the disabled filter-chain suites
(src/test/scala/.../index/rules/) and the FILTER_REASONS tag semantics
(rules/IndexFilter.scala:41-52).
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col
from hyperspace_tpu.plan.nodes import IndexScan, Join
from hyperspace_tpu.rules.apply_hyperspace import active_indexes
from hyperspace_tpu.rules.index_filters import (CandidateIndexCollector,
                                                ReasonCollector)


def write_parquet(root, name, df, parts=2):
    d = root / name
    d.mkdir(parents=True, exist_ok=True)
    step = max(1, len(df) // parts)
    for i in range(parts):
        chunk = df.iloc[i * step:(i + 1) * step if i < parts - 1 else len(df)]
        pq.write_table(pa.Table.from_pandas(chunk.reset_index(drop=True)),
                       d / f"part{i}.parquet")
    return str(d)


@pytest.fixture()
def env(tmp_path):
    rng = np.random.default_rng(7)
    n = 1000
    left = pd.DataFrame({
        "k": rng.integers(0, 100, n).astype(np.int64),
        "a": rng.integers(0, 1000, n).astype(np.int64),
        "b": np.round(rng.uniform(0, 1, n), 3),
    })
    right = pd.DataFrame({
        "k2": np.arange(100, dtype=np.int64),
        "c": rng.integers(0, 10, 100).astype(np.int64),
    })
    l_path = write_parquet(tmp_path, "left", left)
    r_path = write_parquet(tmp_path, "right", right)
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    return dict(session=session, hs=Hyperspace(session), l_path=l_path,
                r_path=r_path, left=left, right=right, tmp=tmp_path)


def scans_of(plan):
    return [l for l in plan.collect_leaves() if isinstance(l, IndexScan)]


class TestCandidateIndexCollector:
    def test_column_schema_filter_drops_wrong_schema(self, env):
        session, hs = env["session"], env["hs"]
        ldf = session.read.parquet(env["l_path"])
        rdf = session.read.parquet(env["r_path"])
        hs.create_index(ldf, IndexConfig("li", ["k"], ["a"]))
        hs.create_index(rdf, IndexConfig("ri", ["k2"], ["c"]))

        ctx = ReasonCollector(enabled=True)
        out = CandidateIndexCollector.collect(
            session, ldf.plan, active_indexes(session), ctx)
        assert len(out) == 1
        (_, cands), = out.values()
        assert [e.name for e in cands] == ["li"]
        # ri was dropped for schema mismatch, with a recorded reason.
        assert any(r.code == "COL_SCHEMA_MISMATCH" and r.index_name == "ri"
                   for r in ctx.reasons)

    def test_file_signature_filter_drops_stale_index(self, env):
        session, hs = env["session"], env["hs"]
        ldf = session.read.parquet(env["l_path"])
        hs.create_index(ldf, IndexConfig("li", ["k"], ["a"]))
        # Append a file -> fingerprint mismatch (hybrid scan off).
        extra = pd.DataFrame({"k": [1], "a": [2], "b": [0.5]})
        pq.write_table(pa.Table.from_pandas(extra),
                       env["tmp"] / "left" / "extra.parquet")

        ldf2 = session.read.parquet(env["l_path"])
        ctx = ReasonCollector(enabled=True)
        out = CandidateIndexCollector.collect(
            session, ldf2.plan, active_indexes(session), ctx)
        assert not out
        assert any(r.code == "SOURCE_DATA_CHANGED" for r in ctx.reasons)


class TestScoreBasedOptimizer:
    def test_filter_rewrite_applied(self, env):
        session, hs = env["session"], env["hs"]
        ldf = session.read.parquet(env["l_path"])
        hs.create_index(ldf, IndexConfig("li", ["k"], ["a"]))
        session.enable_hyperspace()
        q = ldf.filter(col("k") == 5).select("k", "a")
        assert scans_of(q.optimized_plan())
        expected = env["left"].query("k == 5")[["k", "a"]]
        got = q.to_arrow().to_pandas()
        assert sorted(got["a"]) == sorted(expected["a"])

    def test_join_preferred_over_two_filters(self, env):
        """A join rewrite (score 140) must beat filter-rewriting each side
        (2 x 50) when both are possible."""
        session, hs = env["session"], env["hs"]
        ldf = session.read.parquet(env["l_path"])
        rdf = session.read.parquet(env["r_path"])
        hs.create_index(ldf, IndexConfig("lj", ["k"], ["a"]))
        hs.create_index(rdf, IndexConfig("rj", ["k2"], ["c"]))
        session.enable_hyperspace()

        q = (ldf.filter(col("k") > 10)
             .join(rdf.filter(col("k2") > 10), on=col("k") == col("k2"))
             .select("k", "a", "c"))
        plan = q.optimized_plan()
        idx_scans = scans_of(plan)
        assert len(idx_scans) == 2
        assert all(s.use_bucket_spec for s in idx_scans), \
            "join rewrite (bucketed) should win over per-side filter rewrites"

        # Disable-and-compare oracle.
        got = q.to_arrow().to_pandas().sort_values(["k", "a", "c"]
                                                   ).reset_index(drop=True)
        session.disable_hyperspace()
        want = q.to_arrow().to_pandas().sort_values(["k", "a", "c"]
                                                    ).reset_index(drop=True)
        pd.testing.assert_frame_equal(got, want)

    def test_score_based_matches_legacy(self, env):
        session, hs = env["session"], env["hs"]
        ldf = session.read.parquet(env["l_path"])
        hs.create_index(ldf, IndexConfig("li", ["k"], ["a", "b"]))
        session.enable_hyperspace()
        q = ldf.filter(col("k") < 20).select("k", "b")

        ng = q.optimized_plan().tree_string()
        session.conf.set(IndexConstants.SCORE_BASED_OPTIMIZER_ENABLED, "false")
        legacy = q.optimized_plan().tree_string()
        assert ng == legacy


class TestWhyNot:
    def test_why_not_reports_reasons(self, env):
        session, hs = env["session"], env["hs"]
        ldf = session.read.parquet(env["l_path"])
        hs.create_index(ldf, IndexConfig("li", ["k"], ["a"]))

        # Query filters on a non-first-indexed column -> not applied.
        q = ldf.filter(col("a") == 3).select("k", "a")
        text = hs.why_not(q)
        assert "NO_FIRST_INDEXED_COL_COND" in text
        assert "li" in text

        # Query the index does not cover -> missing-column reason.
        q2 = ldf.filter(col("k") == 3).select("k", "b")
        text2 = hs.why_not(q2, index_name="li")
        assert "MISSING_REQUIRED_COL" in text2

        # An applied query reports the application.
        q3 = ldf.filter(col("k") == 3).select("k", "a")
        assert "Applied indexes: li" in hs.why_not(q3)

    def test_reason_collection_off_by_default(self, env):
        session, hs = env["session"], env["hs"]
        ldf = session.read.parquet(env["l_path"])
        hs.create_index(ldf, IndexConfig("li", ["k"], ["a"]))
        session.enable_hyperspace()
        q = ldf.filter(col("a") == 3).select("k", "a")
        q.optimized_plan()
        ctx = session._last_reason_collector
        assert ctx is not None and not ctx.reasons  # off by default

        session.conf.set(IndexConstants.INDEX_FILTER_REASON_ENABLED, "true")
        q.optimized_plan()
        assert session._last_reason_collector.reasons
