"""Reorder-on/off parity over the real TPC-H and TPC-DS suites.

For every verbatim query text the SQL front-end runs, the plan is
optimized with ``optimizer.joinReorder.enabled`` off and on; wherever
the reorderer actually changed the tree, both versions execute and the
answers must agree under sorted-row comparison (results are defined
modulo row order only — reordering legitimately permutes rows). Queries
the reorderer leaves untouched are asserted untouched (plan
tree-strings identical), so parity there is structural, not timed.

Sessions run with the default distributed tier (partitioned-jit SPMD
over the virtual 8-device CPU mesh; the r12 port retired the old
quarantine).
"""

from __future__ import annotations

import os

import numpy as np
import pandas as pd
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.optimizer.constants import OptimizerConstants

import test_tpch_sql as tpch_mod
from goldstandard import tpcds_real


def _norm(df: pd.DataFrame) -> pd.DataFrame:
    return tpch_mod._norm(df)


def _optimized(session, plan, enabled: bool):
    session.conf.set(OptimizerConstants.JOIN_REORDER_ENABLED,
                     "true" if enabled else "false")
    try:
        return session.optimize(plan, diagnostic=True)
    finally:
        session.conf.set(OptimizerConstants.JOIN_REORDER_ENABLED, "false")


def _assert_parity(session, name: str, text: str,
                   budget: dict = None) -> bool:
    """Structural parity for every query (plan optimized reorder-off and
    reorder-on); wherever the reorderer changed the tree, BOTH versions
    execute and the answers must match. ``budget`` (mutable {"n": K})
    bounds the number of executed pairs per suite — the TPC-DS corpus
    reorders 29 of 55 queries and executing every pair would cost the
    tier-1 wall-clock budget more than the marginal coverage is worth;
    the subset is deterministic (first K in parametrize order). Returns
    True when the plan changed."""
    plan = session.sql(text).plan
    off_plan = _optimized(session, plan, False)
    on_plan = _optimized(session, plan, True)
    if on_plan.tree_string() == off_plan.tree_string():
        return False
    if budget is not None:
        if budget["n"] <= 0:
            return True
        budget["n"] -= 1
    df = session.sql(text)
    off = _norm(df.to_pandas())
    session.conf.set(OptimizerConstants.JOIN_REORDER_ENABLED, "true")
    try:
        on = _norm(df.to_pandas())
    finally:
        session.conf.set(OptimizerConstants.JOIN_REORDER_ENABLED, "false")
    pd.testing.assert_frame_equal(on, off, check_dtype=False)
    return True


# ---------------------------------------------------------------------------
# TPC-H (the verbatim texts of tests/test_tpch_sql.py).
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tpch(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("tpch_reorder"))
    session = hst.Session(system_path=os.path.join(root, "indexes"))
    tables = tpch_mod._make_tables(np.random.default_rng(20260731))
    for name, t in tables.items():
        d = os.path.join(root, name)
        os.makedirs(d)
        pq.write_table(t, os.path.join(d, "part0.parquet"))
        session.create_temp_view(name, session.read.parquet(d))
    return session


class TestTpchReorderParity:
    @pytest.mark.parametrize(
        "name,text", [(c[0], c[1]) for c in tpch_mod._CASES],
        ids=[c[0] for c in tpch_mod._CASES])
    def test_parity(self, tpch, name, text):
        _assert_parity(tpch, name, text)

    def test_reorder_fires_somewhere(self, tpch):
        """Sanity: at least one multi-join TPC-H text actually reorders
        (otherwise the parity above is vacuous). Plan-level only — the
        parametrized cases above already executed the answers."""
        changed = []
        for name, text, _oracle, _sorted in tpch_mod._CASES:
            plan = tpch.sql(text).plan
            off = _optimized(tpch, plan, False)
            on = _optimized(tpch, plan, True)
            if on.tree_string() != off.tree_string():
                assert "[reordered" in on.tree_string()
                changed.append(name)
        assert changed, "no TPC-H query was reordered"


# ---------------------------------------------------------------------------
# TPC-DS (the verbatim texts of tests/goldstandard/tpcds_real.py).
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tpcds(tmp_path_factory):
    root = tmp_path_factory.mktemp("tpcds_reorder")
    session = hst.Session(system_path=str(root / "indexes"))
    tpcds_real.register_tables(session, str(root / "data"))
    return session


@pytest.fixture(scope="module")
def tpcds_exec_budget():
    return {"n": 8}


@pytest.mark.parametrize("name", tpcds_real.QUERY_NAMES)
class TestTpcdsReorderParity:
    def test_parity(self, tpcds, tpcds_exec_budget, name):
        _assert_parity(tpcds, name, tpcds_real.QUERY_TEXTS[name],
                       budget=tpcds_exec_budget)
