"""Advisor subsystem: workload capture, candidate generation, what-if
planning, and cost-ranked recommendation (advisor/).

Key invariants under test:

  - capture is conf-gated and records fingerprint/shapes/latency/applied;
  - `what_if` confirms a rewrite WITHOUT building index data, and the
    index log store's byte-state is unchanged by what_if/recommend;
  - `recommend` deterministically ranks the known-good covering indexes
    ahead of strictly-worse candidates (ones whose rewrite never fires);
  - per-index usageCount surfaces through hs.indexes()/hs.index(name).

Sessions run with the default distributed tier (the partitioned-jit
SPMD path over the virtual 8-device CPU mesh) — the r12 port retired
the old quarantine.
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import (BloomFilterSketch, DataSkippingIndexConfig,
                                Hyperspace, IndexConfig, MinMaxSketch)
from hyperspace_tpu.advisor.constants import AdvisorConstants
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col, sum_


def _dir_state(path):
    """{file path: bytes} for every file under ``path`` — the byte-state
    oracle for 'hypothetical entries are never persisted'."""
    out = {}
    for r, _dirs, files in os.walk(path):
        for f in files:
            p = os.path.join(r, f)
            with open(p, "rb") as fh:
                out[p] = fh.read()
    return out


@pytest.fixture()
def env(tmp_path):
    fact_dir = tmp_path / "fact"
    fact_dir.mkdir()
    rng = np.random.default_rng(3)
    # Two time-ordered part files (MinMax-prunable shape).
    ks = np.sort(rng.integers(0, 100, 4000)).astype(np.int64)
    t = pa.table({
        "k": pa.array(ks),
        "v": pa.array(rng.integers(0, 9, 4000).astype(np.int64)),
        "w": pa.array(np.round(rng.uniform(0, 1, 4000), 3)),
        "pad": pa.array(rng.integers(0, 5, 4000).astype(np.int64)),
    })
    pq.write_table(t.slice(0, 2000), fact_dir / "p0.parquet")
    pq.write_table(t.slice(2000, 2000), fact_dir / "p1.parquet")
    dim_dir = tmp_path / "dim"
    dim_dir.mkdir()
    pq.write_table(pa.table({
        "dk": pa.array(np.arange(100, dtype=np.int64)),
        "dv": pa.array(rng.integers(0, 5, 100).astype(np.int64)),
    }), dim_dir / "p0.parquet")

    session = hst.Session(system_path=str(tmp_path / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    session.enable_hyperspace()
    return dict(session=session, hs=Hyperspace(session),
                fact=str(fact_dir), dim=str(dim_dir),
                system_path=str(tmp_path / "indexes"))


def _capture(session, *queries):
    session.conf.set(AdvisorConstants.CAPTURE_ENABLED, "true")
    for q in queries:
        q.to_arrow()
    session.conf.set(AdvisorConstants.CAPTURE_ENABLED, "false")


class TestWorkloadCapture:
    def test_disabled_by_default(self, env):
        session, hs = env["session"], env["hs"]
        session.read.parquet(env["fact"]).filter(col("k") > 5) \
            .select("k", "v").to_arrow()
        assert len(hs.workload()) == 0

    def test_record_contents(self, env):
        session, hs = env["session"], env["hs"]
        fact = session.read.parquet(env["fact"])
        q = fact.filter(col("k") > 50).select("k", "v")
        _capture(session, q)
        from hyperspace_tpu.advisor.workload import log_for
        records = log_for(session).snapshot()
        assert len(records) == 1
        r = records[0]
        assert r.fingerprint is not None
        assert r.latency_s > 0
        assert r.applied_indexes == ()  # no index exists
        (shape,) = r.scan_shapes
        assert shape.root_paths == (env["fact"],)
        assert shape.filter_cols == ("k",)
        assert set(shape.project_cols) == {"k", "v"}
        assert shape.range_cols == ("k",)
        assert shape.equality_cols == ()

    def test_capture_records_applied_indexes(self, env):
        session, hs = env["session"], env["hs"]
        fact = session.read.parquet(env["fact"])
        hs.create_index(fact, IndexConfig("kv", ["k"], ["v"]))
        q = fact.filter(col("k") > 50).select("k", "v")
        _capture(session, q)
        from hyperspace_tpu.advisor.workload import log_for
        (r,) = log_for(session).snapshot()
        assert r.applied_indexes == ("kv",)
        assert r.rules_fired == ("CoveringIndexRules",)

    def test_join_shape_extraction(self, env):
        session = env["session"]
        fact = session.read.parquet(env["fact"])
        dim = session.read.parquet(env["dim"])
        q = (fact.join(dim, on=col("k") == col("dk"))
             .group_by("dv").agg(sum_(col("v")).alias("sv")))
        _capture(session, q)
        from hyperspace_tpu.advisor.workload import log_for
        (r,) = log_for(session).snapshot()
        (js,) = r.join_shapes
        assert js.left.join_cols == ("k",)
        assert js.right.join_cols == ("dk",)
        assert "v" in js.left.referenced_cols
        assert "dv" in js.right.referenced_cols

    def test_max_entries_bound(self, env):
        session = env["session"]
        session.conf.set(AdvisorConstants.CAPTURE_MAX_ENTRIES, 3)
        fact = session.read.parquet(env["fact"])
        q = fact.filter(col("k") > 10).select("k")
        _capture(session, q, q, q, q, q)
        from hyperspace_tpu.advisor.workload import log_for
        log = log_for(session)
        assert len(log) == 3
        assert log.dropped == 2

    def test_workload_dataframe(self, env):
        session, hs = env["session"], env["hs"]
        q = session.read.parquet(env["fact"]).filter(col("k") > 1) \
            .select("k")
        _capture(session, q)
        df = hs.workload()
        assert list(df.columns) == ["fingerprint", "tables", "latency_s",
                                    "appliedIndexes", "rulesFired"]
        assert len(df) == 1


class TestWhatIf:
    def test_filter_rewrite_confirmed_without_build(self, env):
        session, hs = env["session"], env["hs"]
        fact = session.read.parquet(env["fact"])
        q = fact.filter(col("k") > 50).select("k", "v")
        before = _dir_state(env["system_path"])
        out = hs.what_if(q, [IndexConfig("hypo", ["k"], ["v"])])
        assert out.rewritten
        assert out.applied == ("hypo",)
        assert "IndexScan" in out.plan_after
        assert "IndexScan" not in out.plan_before
        assert out.cost_after_bytes < out.cost_before_bytes
        assert out.predicted_speedup > 1.0
        # Metadata only: nothing persisted, byte-for-byte.
        assert _dir_state(env["system_path"]) == before
        assert "What-If" in out.explain()

    def test_wrong_column_config_does_not_rewrite(self, env):
        session, hs = env["session"], env["hs"]
        fact = session.read.parquet(env["fact"])
        q = fact.filter(col("k") > 50).select("k", "v")
        # First indexed column not in the predicate -> rule refuses.
        out = hs.what_if(q, [IndexConfig("bad", ["w"], ["k", "v"])])
        assert not out.rewritten
        assert out.cost_after_bytes == out.cost_before_bytes

    def test_join_pair_rewrite(self, env):
        session, hs = env["session"], env["hs"]
        fact = session.read.parquet(env["fact"])
        dim = session.read.parquet(env["dim"])
        q = (fact.join(dim, on=col("k") == col("dk"))
             .group_by("dv").agg(sum_(col("v")).alias("sv")))
        out = hs.what_if(q, [IndexConfig("h_l", ["k"], ["v"]),
                             IndexConfig("h_r", ["dk"], ["dv"])])
        assert set(out.applied) == {"h_l", "h_r"}

    def test_join_needs_both_sides(self, env):
        session, hs = env["session"], env["hs"]
        fact = session.read.parquet(env["fact"])
        dim = session.read.parquet(env["dim"])
        # Project both sides' columns so neither a filter rewrite nor a
        # join rewrite can fire with only ONE side's index.
        q = fact.join(dim, on=col("k") == col("dk")) \
            .select("k", "v", "dk", "dv")
        out = hs.what_if(q, [IndexConfig("h_l", ["k"], ["v"])])
        assert not out.rewritten

    def test_sketch_static_applicability(self, env):
        session, hs = env["session"], env["hs"]
        fact = session.read.parquet(env["fact"])
        q = fact.filter(col("k") > 50).select("k", "v")
        out = hs.what_if(q, [
            DataSkippingIndexConfig("sk_ok", [MinMaxSketch("k")]),
            DataSkippingIndexConfig("sk_wrong", [BloomFilterSketch("v")]),
        ])
        assert out.sketch_applicable == {"sk_ok": True, "sk_wrong": False}

    def test_what_if_sees_existing_indexes(self, env):
        session, hs = env["session"], env["hs"]
        fact = session.read.parquet(env["fact"])
        hs.create_index(fact, IndexConfig("real_kv", ["k"], ["v"]))
        q = fact.filter(col("k") > 50).select("k", "v")
        # A hypothetical strictly wider than the real index loses the
        # size tie-break: the plan keeps the real index.
        out = hs.what_if(q, [IndexConfig("hypo_wide", ["k"],
                                         ["v", "w", "pad"])])
        assert not out.rewritten
        assert out.applied_existing == ("real_kv",)

    def test_what_if_emits_telemetry(self, env, tmp_path):
        from tests.conftest import capture_logger
        session, hs = env["session"], env["hs"]
        capture_logger().events = []
        session.conf.set(IndexConstants.EVENT_LOGGER_CLASS,
                         "tests.conftest.CaptureLogger")
        fact = session.read.parquet(env["fact"])
        q = fact.filter(col("k") > 50).select("k", "v")
        hs.what_if(q, [IndexConfig("hypo", ["k"], ["v"])])
        names = [type(e).__name__ for e in capture_logger().events]
        assert "AdvisorWhatIfEvent" in names
        (ev,) = [e for e in capture_logger().events
                 if type(e).__name__ == "AdvisorWhatIfEvent"]
        assert ev.applied_names == ["hypo"]


class TestRecommend:
    def _workload(self, env):
        session = env["session"]
        fact = session.read.parquet(env["fact"])
        dim = session.read.parquet(env["dim"])
        q_filter = fact.filter(col("k") > 50).select("k", "v")
        q_join = (fact.join(dim, on=col("k") == col("dk"))
                  .group_by("dv").agg(sum_(col("v")).alias("sv")))
        _capture(session, q_filter, q_join)
        return q_filter, q_join

    def test_recommends_known_good_ahead_of_worse(self, env):
        hs = env["hs"]
        self._workload(env)
        report = hs.recommend(top_k=10)
        assert report.records_considered == 2
        assert report.recommendations, report.explain()
        top = report.recommendations[0]
        # The known-good proposals: fact indexed on the join/filter key k
        # covering v, dim indexed on dk covering dv. Every recommendation
        # that ranks must have fired somewhere (strictly-worse candidates
        # whose rewrite never applies are cut).
        covering = [r for r in report.recommendations
                    if r.kind in ("filter", "join")]
        assert covering and all(r.queries_matched > 0 for r in covering)
        assert top.kind in ("filter", "join")
        assert top.predicted_benefit_s > 0
        flat = [list(c.indexed_columns) + sorted(c.included_columns)
                for r in covering for c in r.configs]
        assert ["k", "v"] in flat  # the known-good fact index
        # Sketch proposals exist but rank behind confirmed benefit.
        sketches = [r for r in report.recommendations if r.kind == "sketch"]
        for s in sketches:
            assert s.predicted_benefit_s == 0.0
            assert s.rank > top.rank
        assert "Index Recommendations" in report.explain()

    def test_deterministic(self, env):
        hs = env["hs"]
        self._workload(env)
        r1 = hs.recommend(top_k=5)
        r2 = hs.recommend(top_k=5)
        as_tuples = lambda rep: [
            (r.rank, r.names, round(r.predicted_benefit_s, 9))
            for r in rep.recommendations]
        assert as_tuples(r1) == as_tuples(r2)

    def test_log_store_bytes_unchanged(self, env):
        hs = env["hs"]
        session = env["session"]
        fact = session.read.parquet(env["fact"])
        hs.create_index(fact, IndexConfig("pre", ["pad"], ["w"]))
        self._workload(env)
        before = _dir_state(env["system_path"])
        hs.recommend(top_k=5)
        assert _dir_state(env["system_path"]) == before

    def test_existing_index_not_reproposed(self, env):
        session, hs = env["session"], env["hs"]
        fact = session.read.parquet(env["fact"])
        q = fact.filter(col("k") > 50).select("k", "v")
        _capture(session, q)
        # Build exactly what the workload needs; the same shape must not
        # be proposed again.
        hs.create_index(fact, IndexConfig("kv", ["k"], ["v"]))
        report = hs.recommend(top_k=5)
        for r in report.recommendations:
            for cfg, _tbl in zip(r.configs, r.tables):
                if hasattr(cfg, "indexed_columns"):
                    assert not (list(cfg.indexed_columns) == ["k"]
                                and set(cfg.included_columns) <= {"v"})

    def test_build_recommendation_then_rewrite_fires(self, env):
        session, hs = env["session"], env["hs"]
        q_filter, q_join = self._workload(env)
        report = hs.recommend(top_k=3)
        top = report.recommendations[0]
        hs.build_recommendation(top)
        listed = set(hs.indexes()["name"])
        assert set(top.names) <= listed
        # The workload query the recommendation matched now rewrites.
        plans = [q_filter.optimized_plan().tree_string(),
                 q_join.optimized_plan().tree_string()]
        assert any("IndexScan" in p for p in plans)

    def test_candidates_pinned_to_their_table(self, env, tmp_path):
        # Two tables with IDENTICAL schemas: a candidate generated from
        # one table's workload must not accrue benefit by "applying" to
        # the other table's queries (and build_recommendation would
        # otherwise build an index that can't deliver the prediction).
        session, hs = env["session"], env["hs"]
        clone_dir = tmp_path / "fact_clone"
        clone_dir.mkdir()
        rng = np.random.default_rng(5)
        pq.write_table(pa.table({
            "k": pa.array(rng.integers(0, 100, 1000).astype(np.int64)),
            "v": pa.array(rng.integers(0, 9, 1000).astype(np.int64)),
            "w": pa.array(np.round(rng.uniform(0, 1, 1000), 3)),
            "pad": pa.array(rng.integers(0, 5, 1000).astype(np.int64)),
        }), clone_dir / "p0.parquet")
        fact = session.read.parquet(env["fact"])
        clone = session.read.parquet(str(clone_dir))
        _capture(session,
                 fact.filter(col("k") > 50).select("k", "v"),
                 clone.filter(col("k") > 50).select("k", "v"))
        report = hs.recommend(top_k=10)
        filters = [r for r in report.recommendations if r.kind == "filter"]
        assert len(filters) == 2  # one per table, not one matching both
        for r in filters:
            assert r.queries_matched == 1

    def test_min_support_filters(self, env):
        session, hs = env["session"], env["hs"]
        session.conf.set(AdvisorConstants.MIN_SUPPORT, 2)
        fact = session.read.parquet(env["fact"])
        q = fact.filter(col("k") > 50).select("k", "v")
        _capture(session, q)  # support 1 < 2
        assert hs.recommend(top_k=5).recommendations == []
        _capture(session, q)  # support 2
        assert hs.recommend(top_k=5).recommendations

    def test_recommend_emits_telemetry(self, env):
        from tests.conftest import capture_logger
        session, hs = env["session"], env["hs"]
        self._workload(env)
        capture_logger().events = []
        session.conf.set(IndexConstants.EVENT_LOGGER_CLASS,
                         "tests.conftest.CaptureLogger")
        report = hs.recommend(top_k=2)
        evs = [e for e in capture_logger().events
               if type(e).__name__ == "AdvisorRecommendationEvent"]
        assert len(evs) == 1
        assert evs[0].records_considered == 2
        assert set(evs[0].recommended) == {
            n for r in report.recommendations for n in r.names}


class TestUsageCounts:
    def test_usage_counts_surface(self, env):
        session, hs = env["session"], env["hs"]
        fact = session.read.parquet(env["fact"])
        hs.create_index(fact, IndexConfig("hot", ["k"], ["v"]))
        hs.create_index(fact, IndexConfig("dead", ["pad"], ["w"]))
        q = fact.filter(col("k") > 50).select("k", "v")
        q.to_arrow()
        q.to_arrow()
        t = hs.indexes().set_index("name")
        assert t.loc["hot", "usageCount"] == 2
        assert t.loc["dead", "usageCount"] == 0
        assert hs.index("hot").iloc[0]["usageCount"] == 2
        assert hs.index("dead").iloc[0]["usageCount"] == 0

    def test_explain_advisor_section(self, env):
        session, hs = env["session"], env["hs"]
        fact = session.read.parquet(env["fact"])
        hs.create_index(fact, IndexConfig("hot", ["k"], ["v"]))
        q = fact.filter(col("k") > 50).select("k", "v")
        # Advisor-less session: no section (goldens untouched).
        assert "Advisor:" not in hs.explain(q)
        _capture(session, q)
        out = hs.explain(q)
        assert "Advisor:" in out
        assert "workload capture: off (1 record(s)" in out
        assert "index 'hot' applied" in out

    def test_explain_does_not_count_usage(self, env):
        session, hs = env["session"], env["hs"]
        fact = session.read.parquet(env["fact"])
        hs.create_index(fact, IndexConfig("hot", ["k"], ["v"]))
        q = fact.filter(col("k") > 50).select("k", "v")
        # Diagnostic passes: neither explain surface may inflate the
        # dead-index detector for a query that never executed.
        hs.explain(q)
        q.explain()
        assert hs.indexes().set_index("name").loc["hot", "usageCount"] == 0

    def test_why_not_does_not_count_usage(self, env):
        session, hs = env["session"], env["hs"]
        fact = session.read.parquet(env["fact"])
        hs.create_index(fact, IndexConfig("hot", ["k"], ["v"]))
        q = fact.filter(col("k") > 50).select("k", "v")
        hs.why_not(q)  # diagnostic: silent pass
        assert hs.indexes().set_index("name").loc["hot", "usageCount"] == 0

    def test_what_if_does_not_count_usage(self, env):
        session, hs = env["session"], env["hs"]
        fact = session.read.parquet(env["fact"])
        hs.create_index(fact, IndexConfig("hot", ["k"], ["v"]))
        q = fact.filter(col("k") > 50).select("k", "v")
        hs.what_if(q, [IndexConfig("hypo", ["pad"], ["w"])])
        assert hs.indexes().set_index("name").loc["hot", "usageCount"] == 0
