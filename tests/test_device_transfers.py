"""Device↔host transfer discipline (TPU-tunnel latency regression guard).

On a remote-attached TPU the per-transfer round trip, not bandwidth,
dominates wall-clock: the round-3 on-chip run showed the index build
spending ~70 s of its 76 s warm time in per-bucket ``device_get`` calls
(one per column per bucket file). The fix is wholesale fetching — one
``device_get`` over the full sorted table, host-numpy slicing afterwards.
These tests pin that discipline so a refactor can't quietly reintroduce
an O(num_buckets) transfer count.

Reference analogy: Spark writes each bucket from executor-local shuffle
blocks (DataFrameWriterExtensions.scala:50-68) — the data never crosses
the driver per bucket; here it must not cross the tunnel per bucket.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import jax
import jax.numpy as jnp

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.execution.columnar import Column, Table
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.schema import INT64, STRING


N_ROWS = 40_000
NUM_BUCKETS = 64  # deliberately large: transfer count must NOT scale with it


@pytest.fixture()
def env(tmp_path):
    rng = np.random.default_rng(7)
    df = pd.DataFrame({
        "k": rng.integers(0, 2000, N_ROWS).astype(np.int64),
        "v": rng.integers(0, 100, N_ROWS).astype(np.int64),
        "s": rng.choice(["ab", "cd", "ef"], N_ROWS),
    })
    d = tmp_path / "data"
    d.mkdir()
    pq.write_table(pa.Table.from_pandas(df), d / "part0.parquet")
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, NUM_BUCKETS)
    # Force the single-device path even on the 8-device CPU mesh.
    session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "false")
    return dict(session=session, hs=Hyperspace(session), path=str(d))


class TestToHost:
    def test_values_nulls_dictionary_and_order_hint(self):
        validity = jnp.asarray([True, False, True, True])
        t = Table(
            {
                "a": Column(INT64, jnp.asarray([1, 2, 3, 4]), validity),
                "s": Column(STRING, jnp.asarray([0, 1, 1, 0]), None,
                            np.asarray(["x", "y"], object)),
            },
            bucket_order=(8, ("a",)),
        )
        h = t.to_host()
        assert isinstance(h.column("a").data, np.ndarray)
        assert isinstance(h.column("a").validity, np.ndarray)
        np.testing.assert_array_equal(h.column("a").data, [1, 2, 3, 4])
        np.testing.assert_array_equal(h.column("a").validity,
                                      [True, False, True, True])
        assert h.column("s").validity is None
        np.testing.assert_array_equal(h.column("s").dictionary, ["x", "y"])
        assert h.bucket_order == (8, ("a",))

    def test_single_device_get_for_whole_table(self, monkeypatch):
        calls = []
        orig = jax.device_get

        def counting(x):
            calls.append(x)
            return orig(x)

        monkeypatch.setattr(jax, "device_get", counting)
        t = Table({
            "a": Column(INT64, jnp.arange(10), jnp.ones(10, jnp.bool_)),
            "b": Column(INT64, jnp.arange(10)),
        })
        t.to_host()
        assert len(calls) == 1  # one pytree fetch, not one per column

    def test_single_device_get_for_to_arrow(self, monkeypatch):
        """Query results cross the host boundary in ONE batched fetch: on
        the TPU tunnel each device_get is a full round trip, so per-column
        fetches made a 4-column result cost 8."""
        calls = []
        orig = jax.device_get

        def counting(x):
            calls.append(x)
            return orig(x)

        monkeypatch.setattr(jax, "device_get", counting)
        t = Table({
            "a": Column(INT64, jnp.arange(6), jnp.ones(6, jnp.bool_)),
            "b": Column(INT64, jnp.arange(6)),
            "s": Column(STRING, jnp.asarray([0, 1, 0, 1, 0, 1]), None,
                        np.asarray(["x", "y"], object)),
        })
        out = t.to_arrow()
        assert len(calls) == 1
        assert out.num_rows == 6 and out.column_names == ["a", "b", "s"]
        # Host-resident tables skip the fetch entirely.
        calls.clear()
        t.to_host().to_arrow()
        assert len(calls) == 1  # the to_host fetch; to_arrow adds none


class TestBuildTransferBudget:
    def test_build_device_gets_independent_of_bucket_count(
            self, env, monkeypatch):
        """The whole create_index flow must issue O(1) device_get calls
        w.r.t. num_buckets (wholesale fetch + boundaries + sketches), never
        one per bucket file."""
        session, hs = env["session"], env["hs"]
        li = session.read.parquet(env["path"])

        count = {"n": 0}
        orig = jax.device_get

        def counting(x):
            count["n"] += 1
            return orig(x)

        monkeypatch.setattr(jax, "device_get", counting)
        hs.create_index(li, IndexConfig("t_idx", ["k"], ["v", "s"]))
        # Generous fixed budget: wholesale fetch (1) + bucket boundaries
        # (1) + a handful of incidental scalar syncs. The pre-fix code
        # issued >= NUM_BUCKETS * n_cols (= 192+) calls here.
        assert count["n"] <= 12, (
            f"create_index issued {count['n']} device_get calls; "
            f"per-bucket transfers have crept back in")
        # Layout sanity: one parquet per non-empty bucket, readable back.
        import glob
        import os
        vdirs = glob.glob(os.path.join(
            session.conf.get(IndexConstants.INDEX_SYSTEM_PATH), "t_idx", "v__=*"))
        assert vdirs
        parts = glob.glob(os.path.join(vdirs[0], "part-*.parquet"))
        assert 1 <= len(parts) <= NUM_BUCKETS
        total = sum(pq.ParquetFile(p).metadata.num_rows for p in parts)
        assert total == N_ROWS

    def test_build_result_identical_to_pre_fetch_semantics(self, env):
        """Disable-and-compare: the wholesale-fetch write path returns the
        same query answers as a fresh scan."""
        session, hs = env["session"], env["hs"]
        li = session.read.parquet(env["path"])
        hs.create_index(li, IndexConfig("t_idx2", ["k"], ["v"]))
        from hyperspace_tpu.plan.expr import col
        q = li.select("k", "v").where(col("k") == 123)
        session.enable_hyperspace()
        with_idx = q.to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
        session.disable_hyperspace()
        without = q.to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
        pd.testing.assert_frame_equal(with_idx, without)
