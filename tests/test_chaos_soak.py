"""Chaos soak: concurrent serving under random fault injection.

8 client threads hammer the serving frontend with a mixed TPC-H/TPC-DS
workload across two sessions while ONE seeded fault registry injects
transient read faults, scan/dispatch/compile errors, latency, spill
corruption, and worker deaths at every query-path fault point — plus a
few submissions carrying unmeetable deadlines. The robustness
invariants under fire:

- NO deadlock: every client thread joins, every future completes;
- NO stranded worker slot: after drain the frontend reports zero queued
  entries, zero active workers, zero in-flight bytes;
- every submission ends in a byte-identical result (the ladders +
  retries absorbed the fault) or a TYPED HyperspaceException
  (InjectedFaultError / QueryDeadlineError / ...) — never a bare
  exception, never a silent wrong answer.
"""

import threading

import hyperspace_tpu as hst
from hyperspace_tpu.artifacts.constants import ArtifactConstants
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.robustness import fault_names as FN
from hyperspace_tpu.robustness import faults
from hyperspace_tpu.robustness.faults import FaultRegistry
from hyperspace_tpu.serving.constants import ServingConstants
from hyperspace_tpu.serving.frontend import ServingFrontend

SOAK_QUERIES = ["tpch_q1", "tpch_q3", "tpch_q6", "tpch_q12",
                "tpcds_q1_like", "tpcds_q3_like", "tpcds_q42_like",
                "tpch_q17"]

# Every query-path fault point, armed probabilistically (seeded RNG —
# the run replays deterministically up to thread scheduling). The
# action-path points (log.*, action.op) are armed too but never hit:
# the soak runs no index mutations.
CHAOS_SPECS = {
    FN.IO_POOLED_READ: "transient:p=0.05",
    FN.IO_PREFETCH_PRODUCE: "error:p=0.01",
    FN.SCAN_PARQUET_DECODE: "error:p=0.02",
    # Buffer-pool probe in the blast radius: a struck load degrades to
    # a silent miss + re-read (execution/buffer_pool.py), so results
    # stay byte-identical under fire.
    FN.BUFFER_LOAD: "error:p=0.3",
    FN.SPMD_DISPATCH: "error:p=0.1",
    FN.SPMD_COMPILE: "error:p=0.05",
    FN.BANK_COMPILE: "error:p=0.03",
    FN.RESULT_CACHE_DEVICE_PUT: "error:p=0.2",
    FN.RESULT_CACHE_SPILL_READ: "error:p=0.3",
    FN.SERVING_WORKER: "error:p=0.08",
    FN.ARTIFACTS_WRITE: "error:p=0.3",
    FN.ARTIFACTS_READ: "error:p=0.3",
    FN.LOG_WRITE: "error:p=0.5",
    FN.LOG_STABLE: "error:p=0.5",
    FN.ACTION_OP: "error:p=0.5",
    # Cluster points armed like the action-path ones: the soak runs a
    # single process (no fleet), so they never fire here — the
    # dedicated injection tests live in tests/test_cluster.py.
    FN.CLUSTER_FORWARD: "error:p=0.1",
    FN.CLUSTER_BROADCAST: "error:p=0.1",
    # Continuous-source point (streaming.source), same posture: armed
    # for completeness, never hit by the query-only soak — the
    # dedicated tailer-survives-injection tests live in
    # tests/test_streaming_scale.py.
    FN.STREAMING_SOURCE: "error:p=0.2",
}


def _session(tmp_path, spill_dir):
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    # Shared result cache with a spill tier in the blast radius.
    session.conf.set(ServingConstants.RESULT_CACHE_ENABLED, "true")
    session.conf.set(ServingConstants.RESULT_CACHE_MIN_COMPUTE_SECONDS,
                     "0")
    session.conf.set(ServingConstants.RESULT_CACHE_SPILL_DIR, spill_dir)
    # Artifact store in the blast radius: failed exports/imports must
    # degrade to plain compiles, never corrupt a result.
    session.conf.set(ArtifactConstants.ENABLED, "true")
    # Starve the buffer pool so the 8-thread mix drives constant
    # eviction storms down the device→host→drop ladder while the
    # buffer.load fault fires — residency churn must never change a
    # result, only counters.
    session.conf.set(IndexConstants.TPU_BUFFER_POOL_DEVICE_BYTES,
                     str(256 * 1024))
    session.conf.set(IndexConstants.TPU_BUFFER_POOL_HOST_BYTES,
                     str(64 * 1024))
    return session


def test_chaos_soak_no_deadlock_no_strand_typed_or_identical(tmp_path):
    from goldstandard import tpc
    root = str(tmp_path / "tpc")
    spill_dir = str(tmp_path / "spill")
    ref_session = _session(tmp_path, spill_dir)
    dfs = tpc.register_tables(ref_session, root)
    serial = {name: tpc.queries(dfs)[name].to_arrow()
              for name in SOAK_QUERIES}

    sessions = [_session(tmp_path, spill_dir) for _ in range(2)]
    plans = []
    for s in sessions:
        qdict = tpc.queries(tpc.register_tables(s, root))
        plans.append({n: qdict[n] for n in SOAK_QUERIES})
    fe = ServingFrontend(sessions[0])

    reg = FaultRegistry.from_conf_specs(CHAOS_SPECS, seed=1234)
    results = {}
    typed_errors = {}
    hard_errors = []

    def client(tid):
        try:
            for rnd in range(2):
                for j, name in enumerate(SOAK_QUERIES):
                    if (j + tid + rnd) % 2 == 0:
                        continue
                    q = plans[tid % 2][name]
                    deadline = 1 if (tid, j, rnd) in ((3, 2, 0),
                                                      (5, 6, 1)) else None
                    with faults.scope(reg):
                        try:
                            p = fe.submit(q, client=f"c{tid}",
                                          deadline_ms=deadline)
                        except HyperspaceException as e:
                            typed_errors[(tid, name, rnd)] = e
                            continue
                    try:
                        table = p.result(timeout=300)
                    except HyperspaceException as e:
                        typed_errors[(tid, name, rnd)] = e
                        continue
                    results[(tid, name, rnd)] = table.to_arrow()
        except BaseException as e:  # pragma: no cover
            hard_errors.append((tid, repr(e)))

    threads = [threading.Thread(target=client, args=(t,))
               for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=900)
        assert not t.is_alive(), "chaos client hung (deadlock?)"

    # Every failure was a TYPED framework error — a bare Exception (or
    # a stranded future's TimeoutError) lands in hard_errors.
    assert not hard_errors, hard_errors
    assert all(isinstance(e, HyperspaceException)
               for e in typed_errors.values())
    # Submissions all terminated, and the fault mix actually bit AND
    # was partly absorbed (results exist on both sides).
    total = len(results) + len(typed_errors)
    assert total == 8 * len(SOAK_QUERIES)  # 2 rounds x half the mix
    assert results, "chaos killed every query — ladders absorbed nothing"

    # Absorbed-or-typed is not enough: absorbed must mean IDENTICAL.
    for (tid, name, rnd), table in results.items():
        assert table.equals(serial[name]), \
            f"thread {tid} round {rnd} query {name} diverged under chaos"

    # No stranded slots or leaked admission budget.
    fe.drain(timeout=120)
    st = fe.stats()
    assert st["queued"] == 0
    assert st["active_workers"] == 0
    assert st["inflight_bytes"] == 0
    # The chaos actually exercised the machinery.
    s = faults.stats()
    assert s["injected"] > 0


def _canon(arrow_table):
    """Row-order-insensitive canonical form: the adaptive soak's
    builder materializes indexes MID-soak, and a covering-index scan
    may legally permute row order for order-free queries — same
    multiset of rows is the invariant (strict byte-order is pinned by
    the controller-off soak above)."""
    return arrow_table.sort_by(
        [(c, "ascending") for c in arrow_table.column_names])


def test_chaos_soak_with_adaptive_controller_armed(tmp_path):
    """The same chaos mix with the adaptive control plane ON: the
    feedback store recording every join actual, the budgeted builder
    attempting passes mid-flight (with the action-path faults armed
    against it), and SLO-driven admission armed with a p99 objective no
    real query can meet — once the window fills, every submission sheds
    or degrades. Invariants: every submission ends row-identical, as a
    TYPED HyperspaceException, or as an approximate answer carrying its
    stated error bound; no stranded builder work after drain."""
    from goldstandard import tpc

    from hyperspace_tpu.adaptive.admission import get_controller
    from hyperspace_tpu.adaptive.builder import (AdaptiveBuilder,
                                                 BuilderLedger)
    from hyperspace_tpu.adaptive.constants import AdaptiveConstants
    from hyperspace_tpu.advisor.constants import AdvisorConstants
    from hyperspace_tpu.telemetry.constants import TelemetryConstants

    root = str(tmp_path / "tpc")
    spill_dir = str(tmp_path / "spill")

    def _arm(s):
        s.conf.set(AdaptiveConstants.ENABLED, "true")
        s.conf.set(AdvisorConstants.CAPTURE_ENABLED, "true")
        # A p99 objective nothing can meet: admission trips as soon as
        # the window holds minCount completed queries.
        s.conf.set(TelemetryConstants.SLO_P99_MS, "0.01")
        s.conf.set(TelemetryConstants.SLO_MIN_COUNT, "3")
        return s

    # Exact reference computed WITHOUT the controller.
    ref_session = _session(tmp_path, spill_dir)
    dfs = tpc.register_tables(ref_session, root)
    serial = {name: _canon(tpc.queries(dfs)[name].to_arrow())
              for name in SOAK_QUERIES}

    sessions = [_arm(_session(tmp_path, spill_dir)) for _ in range(2)]
    plans = []
    for s in sessions:
        qdict = tpc.queries(tpc.register_tables(s, root))
        plans.append({n: qdict[n] for n in SOAK_QUERIES})
    fe = ServingFrontend(sessions[0])
    hs = hst.Hyperspace(sessions[0])
    ledger = BuilderLedger()
    builder = AdaptiveBuilder(hs, ledger=ledger)
    controller = get_controller()
    controller.reset()

    reg = FaultRegistry.from_conf_specs(CHAOS_SPECS, seed=4321)
    results = {}
    typed_errors = {}
    hard_errors = []
    stop_ops = threading.Event()

    def client(tid):
        try:
            for rnd in range(2):
                for j, name in enumerate(SOAK_QUERIES):
                    if (j + tid + rnd) % 2 == 0:
                        continue
                    q = plans[tid % 2][name]
                    with faults.scope(reg):
                        try:
                            p = fe.submit(q, client=f"c{tid}")
                        except HyperspaceException as e:
                            typed_errors[(tid, name, rnd)] = e
                            continue
                    try:
                        table = p.result(timeout=300)
                    except HyperspaceException as e:
                        typed_errors[(tid, name, rnd)] = e
                        continue
                    bound = getattr(table, "approx_error_bound", None)
                    results[(tid, name, rnd)] = (table.to_arrow(), bound)
        except BaseException as e:  # pragma: no cover
            hard_errors.append((tid, repr(e)))

    def ops():
        # The builder rides the soak: the busy check keeps it off the
        # serving path (zero impact on in-flight queries); the armed
        # action-path faults bite any build attempt that does fire.
        try:
            while not stop_ops.is_set():
                with faults.scope(reg):
                    builder.run_once(force=True)
                stop_ops.wait(0.05)
        except BaseException as e:  # pragma: no cover
            hard_errors.append(("ops", repr(e)))

    threads = [threading.Thread(target=client, args=(t,))
               for t in range(8)]
    ops_thread = threading.Thread(target=ops)
    ops_thread.start()
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=900)
            assert not t.is_alive(), "chaos client hung (deadlock?)"
    finally:
        stop_ops.set()
        ops_thread.join(timeout=60)
    assert not ops_thread.is_alive(), "builder ops thread hung"

    assert not hard_errors, hard_errors
    assert all(isinstance(e, HyperspaceException)
               for e in typed_errors.values())
    total = len(results) + len(typed_errors)
    assert total == 8 * len(SOAK_QUERIES)
    assert results, "controller + chaos killed every query"

    # The armed objective actually tripped and the controller acted.
    cstats = controller.stats()
    assert cstats["breaches"] >= 1
    assert cstats["degrades"] + cstats["sheds"] >= 1

    # Row-identical, or approximate WITH the stated bound — never a
    # silent wrong answer.
    for (tid, name, rnd), (arrow, bound) in results.items():
        if bound is not None:
            assert bound["kind"] == "relative"
            assert 0.0 < bound["sample_fraction"] < 1.0
            assert 0.0 <= bound["bound"] <= 1.0
            assert bound["confidence"] == 0.95
            continue
        assert _canon(arrow).equals(serial[name]), \
            f"thread {tid} round {rnd} query {name} diverged (exact path)"

    # Builder after the storm: forced passes with faults still armed,
    # then clean — either way NO stranded in-progress work.
    for _ in range(3):
        with faults.scope(reg):
            builder.run_once(force=True)
    builder.run_once(force=True)
    fe.drain(timeout=120)
    st = fe.stats()
    assert st["queued"] == 0
    assert st["active_workers"] == 0
    assert st["inflight_bytes"] == 0
    assert ledger.stats()["in_progress"] == []
    controller.reset()
