"""Chaos soak: concurrent serving under random fault injection.

8 client threads hammer the serving frontend with a mixed TPC-H/TPC-DS
workload across two sessions while ONE seeded fault registry injects
transient read faults, scan/dispatch/compile errors, latency, spill
corruption, and worker deaths at every query-path fault point — plus a
few submissions carrying unmeetable deadlines. The robustness
invariants under fire:

- NO deadlock: every client thread joins, every future completes;
- NO stranded worker slot: after drain the frontend reports zero queued
  entries, zero active workers, zero in-flight bytes;
- every submission ends in a byte-identical result (the ladders +
  retries absorbed the fault) or a TYPED HyperspaceException
  (InjectedFaultError / QueryDeadlineError / ...) — never a bare
  exception, never a silent wrong answer.
"""

import threading

import hyperspace_tpu as hst
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.robustness import fault_names as FN
from hyperspace_tpu.robustness import faults
from hyperspace_tpu.robustness.faults import FaultRegistry
from hyperspace_tpu.serving.constants import ServingConstants
from hyperspace_tpu.serving.frontend import ServingFrontend

SOAK_QUERIES = ["tpch_q1", "tpch_q3", "tpch_q6", "tpch_q12",
                "tpcds_q1_like", "tpcds_q3_like", "tpcds_q42_like",
                "tpch_q17"]

# Every query-path fault point, armed probabilistically (seeded RNG —
# the run replays deterministically up to thread scheduling). The
# action-path points (log.*, action.op) are armed too but never hit:
# the soak runs no index mutations.
CHAOS_SPECS = {
    FN.IO_POOLED_READ: "transient:p=0.05",
    FN.IO_PREFETCH_PRODUCE: "error:p=0.01",
    FN.SCAN_PARQUET_DECODE: "error:p=0.02",
    FN.SPMD_DISPATCH: "error:p=0.1",
    FN.SPMD_COMPILE: "error:p=0.05",
    FN.BANK_COMPILE: "error:p=0.03",
    FN.RESULT_CACHE_DEVICE_PUT: "error:p=0.2",
    FN.RESULT_CACHE_SPILL_READ: "error:p=0.3",
    FN.SERVING_WORKER: "error:p=0.08",
    FN.LOG_WRITE: "error:p=0.5",
    FN.LOG_STABLE: "error:p=0.5",
    FN.ACTION_OP: "error:p=0.5",
}


def _session(tmp_path, spill_dir):
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    # Shared result cache with a spill tier in the blast radius.
    session.conf.set(ServingConstants.RESULT_CACHE_ENABLED, "true")
    session.conf.set(ServingConstants.RESULT_CACHE_MIN_COMPUTE_SECONDS,
                     "0")
    session.conf.set(ServingConstants.RESULT_CACHE_SPILL_DIR, spill_dir)
    return session


def test_chaos_soak_no_deadlock_no_strand_typed_or_identical(tmp_path):
    from goldstandard import tpc
    root = str(tmp_path / "tpc")
    spill_dir = str(tmp_path / "spill")
    ref_session = _session(tmp_path, spill_dir)
    dfs = tpc.register_tables(ref_session, root)
    serial = {name: tpc.queries(dfs)[name].to_arrow()
              for name in SOAK_QUERIES}

    sessions = [_session(tmp_path, spill_dir) for _ in range(2)]
    plans = []
    for s in sessions:
        qdict = tpc.queries(tpc.register_tables(s, root))
        plans.append({n: qdict[n] for n in SOAK_QUERIES})
    fe = ServingFrontend(sessions[0])

    reg = FaultRegistry.from_conf_specs(CHAOS_SPECS, seed=1234)
    results = {}
    typed_errors = {}
    hard_errors = []

    def client(tid):
        try:
            for rnd in range(2):
                for j, name in enumerate(SOAK_QUERIES):
                    if (j + tid + rnd) % 2 == 0:
                        continue
                    q = plans[tid % 2][name]
                    deadline = 1 if (tid, j, rnd) in ((3, 2, 0),
                                                      (5, 6, 1)) else None
                    with faults.scope(reg):
                        try:
                            p = fe.submit(q, client=f"c{tid}",
                                          deadline_ms=deadline)
                        except HyperspaceException as e:
                            typed_errors[(tid, name, rnd)] = e
                            continue
                    try:
                        table = p.result(timeout=300)
                    except HyperspaceException as e:
                        typed_errors[(tid, name, rnd)] = e
                        continue
                    results[(tid, name, rnd)] = table.to_arrow()
        except BaseException as e:  # pragma: no cover
            hard_errors.append((tid, repr(e)))

    threads = [threading.Thread(target=client, args=(t,))
               for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=900)
        assert not t.is_alive(), "chaos client hung (deadlock?)"

    # Every failure was a TYPED framework error — a bare Exception (or
    # a stranded future's TimeoutError) lands in hard_errors.
    assert not hard_errors, hard_errors
    assert all(isinstance(e, HyperspaceException)
               for e in typed_errors.values())
    # Submissions all terminated, and the fault mix actually bit AND
    # was partly absorbed (results exist on both sides).
    total = len(results) + len(typed_errors)
    assert total == 8 * len(SOAK_QUERIES)  # 2 rounds x half the mix
    assert results, "chaos killed every query — ladders absorbed nothing"

    # Absorbed-or-typed is not enough: absorbed must mean IDENTICAL.
    for (tid, name, rnd), table in results.items():
        assert table.equals(serial[name]), \
            f"thread {tid} round {rnd} query {name} diverged under chaos"

    # No stranded slots or leaked admission budget.
    fe.drain(timeout=120)
    st = fe.stats()
    assert st["queued"] == 0
    assert st["active_workers"] == 0
    assert st["inflight_bytes"] == 0
    # The chaos actually exercised the machinery.
    s = faults.stats()
    assert s["injected"] > 0
