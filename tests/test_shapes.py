"""Shape-class execution layer (execution/shapes.py).

Two contracts under test:

1. BYTE-IDENTITY — every padded+masked kernel (hash, sort, merge join,
   segment ops, sketch builds) and the padded executor pipeline must
   return byte-identical results to exact-shape execution, across all
   dtypes including the STRING dictionary path.

2. COMPILE COLLAPSE — a mixed-length batch of file scans must compile
   each kernel a small constant number of times (one per length CLASS),
   not once per distinct file length: the recompilation storm this layer
   exists to kill.
"""

import datetime
import os

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.execution import shapes
from hyperspace_tpu.execution.columnar import Column, Table
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.ops import kernels, sketches
from hyperspace_tpu.plan.expr import avg, col, sum_
from hyperspace_tpu.schema import (BOOL, DATE, FLOAT32, FLOAT64, INT32,
                                   INT64, STRING)

ENABLED = shapes.ShapeParams(enabled=True, min_pad=64, growth_factor=2.0)
DISABLED = shapes.ShapeParams(enabled=False)

# Lengths straddling class boundaries: empty, tiny, one below/at/above a
# class edge, and a mid-class odd size.
LENGTHS = [0, 1, 63, 64, 65, 127, 128, 200]


def _session(tmp_path, **conf):
    s = hst.Session(system_path=str(tmp_path / "idx"))
    for k, v in conf.items():
        s.conf.set(k, v)
    return s


class TestPaddedLength:
    def test_at_least_n_and_on_ladder(self):
        with shapes.use_params(ENABLED):
            for n in range(1, 5000, 37):
                c = shapes.padded_length(n)
                assert c >= n
                assert c >= ENABLED.min_pad
                # Ladder membership: min_pad * growth^k.
                k = c
                while k > ENABLED.min_pad:
                    assert k % 2 == 0
                    k //= 2
                assert k == ENABLED.min_pad

    def test_idempotent_and_monotone_ladder(self):
        with shapes.use_params(ENABLED):
            for n in (1, 64, 65, 1000, 4096):
                c = shapes.padded_length(n)
                assert shapes.padded_length(c) == c

    def test_disabled_and_zero(self):
        with shapes.use_params(DISABLED):
            assert shapes.padded_length(77) == 77
        with shapes.use_params(ENABLED):
            assert shapes.padded_length(0) == 0

    def test_huge_exact_fallback(self):
        p = shapes.ShapeParams(enabled=True, min_pad=64, growth_factor=2.0,
                               max_waste_ratio=0.25,
                               exact_fallback_rows=1000)
        with shapes.use_params(p):
            # 1100 -> next class 2048 wastes 86% > 25% and n >= fallback.
            assert shapes.padded_length(1100) == 1100
            # 2000 -> 2048 wastes 2.4% <= 25%: still bucketed.
            assert shapes.padded_length(2000) == 2048
            # below the huge threshold, waste is always accepted.
            assert shapes.padded_length(70) == 128

    def test_conf_roundtrip(self, tmp_path):
        s = _session(
            tmp_path,
            **{IndexConstants.TPU_SHAPE_BUCKETING_MIN_PAD: "32",
               IndexConstants.TPU_SHAPE_BUCKETING_GROWTH_FACTOR: "4.0"})
        p = shapes.params_from_conf(s.hs_conf)
        assert p.min_pad == 32 and p.growth_factor == 4.0 and p.enabled
        s.conf.set(IndexConstants.TPU_SHAPE_BUCKETING_ENABLED, "false")
        assert not shapes.params_from_conf(s.hs_conf).enabled


class TestPadPrimitives:
    def test_pad_host_and_device_roundtrip(self):
        for arr in (np.arange(10, dtype=np.int64),
                    jnp.arange(10, dtype=jnp.float64)):
            out = shapes.pad_to(arr, 16, 7)
            assert out.shape == (16,)
            np.testing.assert_array_equal(np.asarray(out[:10]),
                                          np.asarray(arr))
            np.testing.assert_array_equal(np.asarray(out[10:]),
                                          np.full(6, 7))
            np.testing.assert_array_equal(
                np.asarray(shapes.unpad(out, 10)), np.asarray(arr))

    def test_mask_tail_and_valid_mask(self):
        arr = jnp.arange(8, dtype=jnp.int32)
        np.testing.assert_array_equal(
            np.asarray(shapes.mask_tail(arr, 5, -1)),
            [0, 1, 2, 3, 4, -1, -1, -1])
        np.testing.assert_array_equal(
            np.asarray(shapes.valid_mask(6, 2)),
            [True, True, False, False, False, False])


def _rand_keys(rng, n, dtype):
    if dtype == INT32:
        return jnp.asarray(rng.integers(-50, 50, n).astype(np.int32))
    if dtype == INT64:
        return jnp.asarray(rng.integers(-10**12, 10**12, n))
    if dtype == DATE:
        return jnp.asarray(rng.integers(0, 10000, n).astype(np.int32))
    if dtype == BOOL:
        return jnp.asarray(rng.integers(0, 2, n).astype(bool))
    if dtype == FLOAT32:
        return jnp.asarray(rng.normal(size=n).astype(np.float32))
    return jnp.asarray(rng.normal(size=n))


class TestKernelByteIdentity:
    """Each kernel: padded-class execution == exact execution, bit for bit."""

    @pytest.mark.parametrize("dtype", [INT32, INT64, DATE, BOOL,
                                       FLOAT32, FLOAT64])
    @pytest.mark.parametrize("n", LENGTHS)
    def test_hash32(self, dtype, n):
        rng = np.random.default_rng(n)
        data = _rand_keys(rng, n, dtype)
        with shapes.use_params(DISABLED):
            want = np.asarray(kernels.hash32_values(data, dtype))
        with shapes.use_params(ENABLED):
            got = np.asarray(kernels.hash32_values(data, dtype))
        np.testing.assert_array_equal(got, want)

    @pytest.mark.parametrize("n", LENGTHS)
    def test_hash32_string_dictionary(self, n):
        rng = np.random.default_rng(n)
        dictionary = np.array(sorted({f"s{i:03d}" for i in range(17)}))
        codes = jnp.asarray(rng.integers(0, len(dictionary), n)
                            .astype(np.int32))
        with shapes.use_params(DISABLED):
            want = np.asarray(kernels.hash32_values(codes, STRING,
                                                    dictionary))
        with shapes.use_params(ENABLED):
            got = np.asarray(kernels.hash32_values(codes, STRING,
                                                   dictionary))
        np.testing.assert_array_equal(got, want)

    @pytest.mark.parametrize("n", LENGTHS)
    def test_lex_sort_indices(self, n):
        rng = np.random.default_rng(n)
        k1 = _rand_keys(rng, n, INT64)
        k2 = _rand_keys(rng, n, FLOAT64)
        for ascending in (None, [False, True]):
            with shapes.use_params(DISABLED):
                want = np.asarray(kernels.lex_sort_indices([k1, k2],
                                                           ascending))
            with shapes.use_params(ENABLED):
                got = np.asarray(kernels.lex_sort_indices([k1, k2],
                                                          ascending))
            np.testing.assert_array_equal(got, want)

    @pytest.mark.parametrize("n", LENGTHS)
    def test_lex_sort_padded_out_prefix(self, n):
        with shapes.use_params(ENABLED):
            cls = shapes.padded_length(n)
            rng = np.random.default_rng(n)
            k = shapes.pad_to(_rand_keys(rng, n, INT64), cls, 123)
            perm = kernels.lex_sort_indices([k], valid_count=n,
                                            padded_out=True)
            assert perm.shape[0] == cls
            with shapes.use_params(DISABLED):
                want = np.asarray(kernels.lex_sort_indices([k[:n]]))
            np.testing.assert_array_equal(np.asarray(perm)[:n], want)
            # Pad entries index pad rows (sorted last).
            assert np.all(np.asarray(perm)[n:] >= n)

    @pytest.mark.parametrize("n", LENGTHS)
    def test_merge_join_indices(self, n):
        rng = np.random.default_rng(n)
        left = jnp.asarray(rng.integers(0, max(n, 1), max(n, 1)))
        right = jnp.sort(jnp.asarray(
            rng.integers(0, max(n, 1), max(n // 2, 1))))
        with shapes.use_params(DISABLED):
            wl, wr, wc = kernels.merge_join_indices(left, right,
                                                    return_counts=True)
        with shapes.use_params(ENABLED):
            gl, gr, gc = kernels.merge_join_indices(left, right,
                                                    return_counts=True)
        np.testing.assert_array_equal(np.asarray(gl), np.asarray(wl))
        np.testing.assert_array_equal(np.asarray(gr), np.asarray(wr))
        np.testing.assert_array_equal(np.asarray(gc), np.asarray(wc))

    def test_merge_join_dtype_max_keys(self):
        # Real keys equal to the pad sentinel must still match exactly.
        m = np.iinfo(np.int64).max
        left = jnp.asarray(np.array([1, m, 5, m], dtype=np.int64))
        right = jnp.asarray(np.array([1, 5, m], dtype=np.int64))
        with shapes.use_params(DISABLED):
            wl, wr = kernels.merge_join_indices(left, right)
        with shapes.use_params(ENABLED):
            gl, gr = kernels.merge_join_indices(left, right)
        np.testing.assert_array_equal(np.asarray(gl), np.asarray(wl))
        np.testing.assert_array_equal(np.asarray(gr), np.asarray(wr))

    @pytest.mark.parametrize("n", LENGTHS)
    def test_group_ids_and_segment_ops(self, n):
        rng = np.random.default_rng(n)
        keys = jnp.sort(jnp.asarray(rng.integers(0, 20, n)))
        vals = jnp.asarray(rng.normal(size=n))
        with shapes.use_params(DISABLED):
            wg, wn = kernels.group_ids_from_sorted([keys])
            want = {
                "sum": np.asarray(kernels.segment_sum(vals, wg, wn)),
                "min": np.asarray(kernels.segment_min(vals, wg, wn)),
                "max": np.asarray(kernels.segment_max(vals, wg, wn)),
                "cnt": np.asarray(kernels.segment_count(wg, wn)),
                "first": np.asarray(kernels.segment_first_index(wg, wn)),
            } if wn else {}
        with shapes.use_params(ENABLED):
            gg, gn = kernels.group_ids_from_sorted([keys])
            assert gn == wn
            np.testing.assert_array_equal(np.asarray(gg), np.asarray(wg))
            if gn:
                np.testing.assert_array_equal(
                    np.asarray(kernels.segment_sum(vals, gg, gn)),
                    want["sum"])
                np.testing.assert_array_equal(
                    np.asarray(kernels.segment_min(vals, gg, gn)),
                    want["min"])
                np.testing.assert_array_equal(
                    np.asarray(kernels.segment_max(vals, gg, gn)),
                    want["max"])
                np.testing.assert_array_equal(
                    np.asarray(kernels.segment_count(gg, gn)), want["cnt"])
                np.testing.assert_array_equal(
                    np.asarray(kernels.segment_first_index(gg, gn)),
                    want["first"])


class TestSketchByteIdentity:
    @pytest.mark.parametrize("n", [1, 63, 200])
    @pytest.mark.parametrize("with_nulls", [False, True])
    def test_bloom_build(self, n, with_nulls):
        rng = np.random.default_rng(n)
        data = jnp.asarray(rng.integers(0, 1000, n))
        validity = jnp.asarray(rng.integers(0, 2, n).astype(bool)) \
            if with_nulls else None
        c = Column(INT64, data, validity)
        with shapes.use_params(DISABLED):
            want = sketches.bloom_build(c, 256, 4)
        with shapes.use_params(ENABLED):
            got = sketches.bloom_build(c, 256, 4)
        assert got.tobytes() == want.tobytes()

    @pytest.mark.parametrize("n", [1, 63, 200])
    def test_bloom_build_string(self, n):
        rng = np.random.default_rng(n)
        dictionary = np.array(sorted({f"v{i}" for i in range(9)}))
        codes = jnp.asarray(rng.integers(0, len(dictionary), n)
                            .astype(np.int32))
        c = Column(STRING, codes, None, dictionary)
        with shapes.use_params(DISABLED):
            want = sketches.bloom_build(c, 128, 3)
        with shapes.use_params(ENABLED):
            got = sketches.bloom_build(c, 128, 3)
        assert got.tobytes() == want.tobytes()

    @pytest.mark.parametrize("dtype", [INT32, INT64, DATE, FLOAT64])
    @pytest.mark.parametrize("n", [1, 63, 200])
    def test_minmax(self, dtype, n):
        rng = np.random.default_rng(n)
        data = _rand_keys(rng, n, dtype)
        validity = jnp.asarray(rng.integers(0, 2, n).astype(bool))
        for v in (None, validity):
            c = Column(dtype, data, v)
            with shapes.use_params(DISABLED):
                want = sketches.minmax_values(c)
            with shapes.use_params(ENABLED):
                got = sketches.minmax_values(c)
            assert got == want

    def test_minmax_all_null(self):
        c = Column(INT64, jnp.arange(70), jnp.zeros(70, jnp.bool_))
        with shapes.use_params(ENABLED):
            assert sketches.minmax_values(c) == (None, None)


class TestEndToEndByteIdentity:
    """Padded pipeline vs exact pipeline over a query exercising filter,
    string predicates, join, group-by, sort and nulls."""

    def _write(self, tmp_path):
        rng = np.random.default_rng(7)
        n = 3000
        pq.write_table(pa.table({
            "k": pa.array(rng.integers(0, 40, n).astype(np.int64)),
            "v": pa.array(np.round(rng.uniform(0, 100, n), 2)),
            "s": pa.array(rng.choice(["red", "green", "blue", None], n)),
            "d": pa.array((rng.integers(0, 3000, n)).astype("int32"),
                          type=pa.int32()).cast(pa.date32()),
        }), str(tmp_path / "t.parquet"))
        m = 400
        pq.write_table(pa.table({
            "k2": pa.array(rng.integers(0, 40, m).astype(np.int64)),
            "w": pa.array(np.round(rng.uniform(0, 10, m), 2)),
        }), str(tmp_path / "u.parquet"))

    def test_query_identical(self, tmp_path):
        self._write(tmp_path)
        s = _session(tmp_path)
        t = s.read.parquet(str(tmp_path / "t.parquet"))
        u = s.read.parquet(str(tmp_path / "u.parquet"))
        q = (t.filter((col("k") > 3) & (col("s") != "red"))
             .join(u, on=col("k") == col("k2"))
             .group_by("k", "s")
             .agg(sum_(col("v") * col("w")).alias("vw"),
                  avg(col("v")).alias("va"))
             .sort(("vw", False), "k")
             .limit(50))
        got = q.to_arrow()
        s.conf.set(IndexConstants.TPU_SHAPE_BUCKETING_ENABLED, "false")
        want = q.to_arrow()
        assert got.equals(want)

    def test_filter_result_identical_and_compact(self, tmp_path):
        self._write(tmp_path)
        s = _session(tmp_path)
        t = s.read.parquet(str(tmp_path / "t.parquet"))
        q = t.filter(col("d") >= datetime.date(1975, 1, 1)).select("k", "v")
        res = q.execute()
        assert not res.is_padded  # execute() compacts at the boundary
        got = q.to_arrow()
        s.conf.set(IndexConstants.TPU_SHAPE_BUCKETING_ENABLED, "false")
        assert got.equals(q.to_arrow())


class TestCompileCollapse:
    def test_mixed_length_scans_compile_bounded(self, tmp_path):
        """A batch of file scans with MANY distinct lengths within one
        length class compiles only for the first (plus the tiny per-file
        host boundary) — not one chain per length."""
        rng = np.random.default_rng(3)
        paths = []
        # 8 distinct lengths, all inside the (1024, 2048] class.
        for i, n in enumerate([1100, 1205, 1333, 1478, 1555, 1717, 1890,
                               2047]):
            p = str(tmp_path / f"f{i}.parquet")
            pq.write_table(pa.table({
                "a": pa.array(rng.integers(0, 1000, n).astype(np.int64)),
                "b": pa.array(rng.uniform(0, 1, n)),
            }), p)
            paths.append(p)
        s = _session(tmp_path)

        def scan(p):
            # ~10% selectivity keeps every file's pushdown survivor count
            # inside ONE length class (the scan lengths already share one).
            df = s.read.parquet(p)
            return df.filter(col("a") > 900).agg(
                sum_(col("b")).alias("t")).to_arrow()

        scan(paths[0])  # warm the class's programs
        before = shapes.compile_count()
        for p in paths[1:]:
            scan(p)
        delta = shapes.compile_count() - before
        # Every later scan shares the first one's compiled class programs.
        assert delta <= 3, f"expected near-zero compiles, got {delta}"

    def test_compile_counter_monotone(self):
        a = shapes.compile_count()
        jnp.sort(jnp.arange(4097) % 7).block_until_ready()
        assert shapes.compile_count() >= a

    def test_kernel_compile_event_emitted(self, tmp_path):
        from tests.conftest import capture_logger
        rng = np.random.default_rng(0)
        p = str(tmp_path / "e.parquet")
        pq.write_table(pa.table({
            "a": pa.array(rng.integers(0, 9999, 5000).astype(np.int64))}),
            p)
        s = _session(tmp_path)
        s.conf.set(IndexConstants.EVENT_LOGGER_CLASS,
                   "tests.conftest.CaptureLogger")
        cap = capture_logger()
        cap.events = []
        # A fresh filter on a fresh length class forces compiles.
        s.read.parquet(p).filter(col("a") > 123).to_arrow()
        names = [e.event_name for e in cap.events]
        assert "KernelCompileEvent" in names
        ev = [e for e in cap.events
              if e.event_name == "KernelCompileEvent"][0]
        assert ev.count > 0 and ev.total >= ev.count

    def test_explain_compilation_section(self, tmp_path):
        rng = np.random.default_rng(0)
        p = str(tmp_path / "x.parquet")
        pq.write_table(pa.table({
            "a": pa.array(rng.integers(0, 99, 100).astype(np.int64))}), p)
        s = _session(tmp_path)
        from hyperspace_tpu.api import Hyperspace
        hs = Hyperspace(s)
        df = s.read.parquet(p).filter(col("a") > 5)
        text = hs.explain(df, verbose=False)
        assert "Compilation:" in text
        assert "shape bucketing: on" in text
        s.conf.set(IndexConstants.TPU_SHAPE_BUCKETING_ENABLED, "false")
        text = hs.explain(df, verbose=False)
        assert "shape bucketing: off" in text


class TestXlaCacheOptIn:
    def test_cpu_opt_in(self, monkeypatch, tmp_path):
        from hyperspace_tpu import execution as ex
        monkeypatch.setenv("HST_XLA_CACHE", "on")
        monkeypatch.setenv("HST_XLA_CACHE_DIR", str(tmp_path / "xla"))
        prev = jax.config.jax_compilation_cache_dir
        try:
            ex.ensure_compilation_cache(force=True)
            assert jax.config.jax_compilation_cache_dir == \
                str(tmp_path / "xla")
            assert os.path.isdir(str(tmp_path / "xla"))
        finally:
            jax.config.update("jax_compilation_cache_dir", prev)
            ex._cache_configured = False

    def test_cpu_default_stays_off(self, monkeypatch):
        from hyperspace_tpu import execution as ex
        monkeypatch.setenv("HST_XLA_CACHE", "auto")
        prev = jax.config.jax_compilation_cache_dir
        try:
            jax.config.update("jax_compilation_cache_dir", None)
            ex.ensure_compilation_cache(force=True)
            assert jax.config.jax_compilation_cache_dir is None
        finally:
            jax.config.update("jax_compilation_cache_dir", prev)
            ex._cache_configured = False
