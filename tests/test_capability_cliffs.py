"""Capability-cliff regression tests (VERDICT r2 #4).

The single-device engine used to raise on nullable sort/group-by keys and
on joins with >2 keys or non-int32 multi-key dtypes, and the distributed
build silently skipped tables with nullable columns. Each test here pins
the removed cliff with a pandas oracle and — where an index applies — the
disable-and-compare oracle; fallback observability is asserted through the
DistributedFallbackEvent telemetry.
"""

import datetime

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col, count, sum_

from conftest import capture_logger as capture_logger_cls


def write_dir(tmp_path, name, table, parts=2):
    d = tmp_path / name
    d.mkdir(parents=True, exist_ok=True)
    n = table.num_rows
    step = max(1, n // parts)
    for i in range(parts):
        lo = i * step
        hi = (i + 1) * step if i < parts - 1 else n
        pq.write_table(table.slice(lo, hi - lo), d / f"part{i}.parquet")
    return str(d)


@pytest.fixture()
def session(tmp_system_path):
    s = hst.Session(system_path=tmp_system_path)
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 8)
    # Gate off: these fixtures deliberately exercise the mesh paths on
    # small tables.
    s.conf.set(IndexConstants.TPU_DISTRIBUTED_MIN_STREAM_ROWS, "0")
    return s


@pytest.fixture()
def nullable_dir(tmp_path):
    rng = np.random.default_rng(21)
    n = 3000
    key = rng.integers(-40, 40, n).astype(np.int64)
    key_null = rng.random(n) < 0.15
    val = np.round(rng.uniform(0, 100, n), 2)
    tag = rng.choice(["x", "y", "z"], n).astype(object)
    tag_null = rng.random(n) < 0.1
    tag[tag_null] = None
    t = pa.table({
        "key": pa.array(np.where(key_null, 0, key), type=pa.int64(),
                        mask=key_null),
        "val": pa.array(val),
        "tag": pa.array(tag, type=pa.string()),
        "seq": pa.array(np.arange(n, dtype=np.int64)),
    })
    return write_dir(tmp_path, "nullable", t), t.to_pandas()


class TestNullableSort:
    def test_sort_nulls_first_asc(self, session, nullable_dir):
        path, pdf = nullable_dir
        df = session.read.parquet(path).sort("key", "seq")
        got = df.to_pandas()
        exp = pdf.sort_values(["key", "seq"], na_position="first") \
            .reset_index(drop=True)
        assert got["seq"].tolist() == exp["seq"].tolist()
        assert got["key"].isna().sum() == pdf["key"].isna().sum()
        # NULLS FIRST for ascending order.
        n_null = int(pdf["key"].isna().sum())
        assert got["key"].head(n_null).isna().all()

    def test_sort_nulls_last_desc(self, session, nullable_dir):
        path, pdf = nullable_dir
        df = session.read.parquet(path).sort(("key", False), "seq")
        got = df.to_pandas()
        exp = pdf.sort_values(["key", "seq"], ascending=[False, True],
                              na_position="last").reset_index(drop=True)
        assert got["seq"].tolist() == exp["seq"].tolist()
        n_null = int(pdf["key"].isna().sum())
        assert got["key"].tail(n_null).isna().all()

    def test_sort_nullable_string(self, session, nullable_dir):
        path, pdf = nullable_dir
        got = session.read.parquet(path).sort("tag", "seq").to_pandas()
        exp = pdf.sort_values(["tag", "seq"], na_position="first") \
            .reset_index(drop=True)
        assert got["seq"].tolist() == exp["seq"].tolist()


class TestNullableGroupBy:
    def test_group_by_nullable_int(self, session, nullable_dir):
        path, pdf = nullable_dir
        got = session.read.parquet(path).group_by("key").agg(
            sum_(col("val")).alias("sv"), count(None).alias("n")).to_pandas()
        exp = pdf.groupby("key", dropna=False).agg(
            sv=("val", "sum"), n=("val", "size")).reset_index()
        # Null group is present exactly once, with the right aggregates.
        assert got["key"].isna().sum() == 1
        null_row = got[got["key"].isna()].iloc[0]
        exp_null = exp[exp["key"].isna()].iloc[0]
        assert null_row["n"] == exp_null["n"]
        assert null_row["sv"] == pytest.approx(exp_null["sv"])
        merged = got.dropna(subset=["key"]).sort_values("key").reset_index(drop=True)
        expv = exp.dropna(subset=["key"]).sort_values("key").reset_index(drop=True)
        assert merged["key"].tolist() == expv["key"].tolist()
        assert merged["n"].tolist() == expv["n"].tolist()
        assert np.allclose(merged["sv"], expv["sv"])
        # Null group sorts first (matching the SPMD path's order).
        assert pd.isna(got["key"].iloc[0])

    def test_group_by_two_nullable_keys(self, session, nullable_dir):
        path, pdf = nullable_dir
        got = session.read.parquet(path).group_by("key", "tag").agg(
            count(None).alias("n")).to_pandas()
        exp = pdf.groupby(["key", "tag"], dropna=False).size() \
            .reset_index(name="n")
        assert len(got) == len(exp)
        gk = got.fillna({"tag": "<null>"})
        ek = exp.fillna({"tag": "<null>"})
        gm = {(None if pd.isna(k) else k, t): n
              for k, t, n in zip(gk["key"], gk["tag"], gk["n"])}
        em = {(None if pd.isna(k) else k, t): n
              for k, t, n in zip(ek["key"], ek["tag"], ek["n"])}
        assert gm == em


class TestMultiKeyJoins:
    def _two_sided(self, tmp_path, key_dtypes):
        rng = np.random.default_rng(33)
        n_l, n_r = 2500, 400

        def keys(n, seed_off):
            r = np.random.default_rng(100 + seed_off)
            a = r.integers(0, 30, n)
            b = r.integers(0, 7, n)
            c = r.integers(0, 4, n)
            return a, b, c

        la, lb, lc = keys(n_l, 0)
        ra, rb, rc = keys(n_r, 1)

        def encode(arr, dtype, names):
            if dtype == "int64":
                return pa.array(arr.astype(np.int64))
            if dtype == "int32":
                return pa.array(arr.astype(np.int32))
            if dtype == "string":
                return pa.array(np.asarray(names)[arr % len(names)])
            raise AssertionError(dtype)

        names = [f"s{i:02d}" for i in range(30)]
        left = pa.table({
            "a": encode(la, key_dtypes[0], names),
            "b": encode(lb, key_dtypes[1], names),
            "c": encode(lc, key_dtypes[2], names),
            "lv": pa.array(rng.uniform(0, 10, n_l)),
        })
        right = pa.table({
            "ra": encode(ra, key_dtypes[0], names),
            "rb": encode(rb, key_dtypes[1], names),
            "rc": encode(rc, key_dtypes[2], names),
            "rv": pa.array(rng.uniform(0, 10, n_r)),
        })
        lp = write_dir(tmp_path, "left", left)
        rp = write_dir(tmp_path, "right", right)
        return lp, rp, left.to_pandas(), right.to_pandas()

    def _check(self, session, tmp_path, dtypes):
        lp, rp, lpdf, rpdf = self._two_sided(tmp_path, dtypes)
        l = session.read.parquet(lp)
        r = session.read.parquet(rp)
        got = l.join(r, on=(col("a") == col("ra")) & (col("b") == col("rb"))
                     & (col("c") == col("rc"))) \
            .select("a", "b", "c", "lv", "rv").to_pandas()
        exp = lpdf.merge(rpdf, left_on=["a", "b", "c"],
                         right_on=["ra", "rb", "rc"])[
            ["a", "b", "c", "lv", "rv"]]
        key = ["a", "b", "c", "lv", "rv"]
        g = got.sort_values(key).reset_index(drop=True)
        e = exp.sort_values(key).reset_index(drop=True)
        pd.testing.assert_frame_equal(g, e, check_dtype=False)

    def test_three_int64_keys(self, session, tmp_path):
        self._check(session, tmp_path, ("int64", "int64", "int64"))

    def test_three_mixed_int_keys(self, session, tmp_path):
        self._check(session, tmp_path, ("int64", "int32", "int32"))

    def test_two_int64_keys(self, session, tmp_path):
        lp, rp, lpdf, rpdf = self._two_sided(
            tmp_path, ("int64", "int64", "int64"))
        l = session.read.parquet(lp)
        r = session.read.parquet(rp)
        got = l.join(r, on=(col("a") == col("ra")) & (col("b") == col("rb"))) \
            .select("a", "b", "lv", "rv").to_pandas()
        exp = lpdf.merge(rpdf, left_on=["a", "b"], right_on=["ra", "rb"])[
            ["a", "b", "lv", "rv"]]
        key = ["a", "b", "lv", "rv"]
        pd.testing.assert_frame_equal(
            got.sort_values(key).reset_index(drop=True),
            exp.sort_values(key).reset_index(drop=True), check_dtype=False)

    def test_string_key_in_multi_key_join(self, session, tmp_path):
        self._check(session, tmp_path, ("string", "int64", "string"))


class TestNullableDistributedBuild:
    def test_mesh_build_with_nullable_columns(self, session, nullable_dir,
                                              monkeypatch):
        """A nullable table now takes the mesh build (previously a silent
        single-device fallback), and the index round-trips nulls."""
        from hyperspace_tpu.actions import create as create_mod

        path, pdf = nullable_dir
        calls = []
        orig = create_mod.CreateActionBase._write_index_files_distributed

        def spy(self, *a, **kw):
            calls.append(1)
            return orig(self, *a, **kw)

        monkeypatch.setattr(
            create_mod.CreateActionBase, "_write_index_files_distributed", spy)
        hs = Hyperspace(session)
        df = session.read.parquet(path)
        hs.create_index(df, IndexConfig("null_idx", ["seq"], ["key", "val", "tag"]))
        assert calls, "mesh build was not taken for a nullable table"

        session.enable_hyperspace()
        q = df.filter(col("seq") < 500).select("seq", "key", "tag")
        from hyperspace_tpu.plan.nodes import IndexScan
        assert any(isinstance(l, IndexScan)
                   for l in q.optimized_plan().collect_leaves())
        got = q.to_pandas().sort_values("seq").reset_index(drop=True)
        exp = pdf[pdf["seq"] < 500][["seq", "key", "tag"]] \
            .sort_values("seq").reset_index(drop=True)
        pd.testing.assert_frame_equal(got, exp, check_dtype=False)

    def test_fallback_event_on_empty_table(self, session, tmp_path):
        cap = capture_logger_cls()
        cap.events.clear()
        session.conf.set(IndexConstants.EVENT_LOGGER_CLASS,
                         "tests.conftest.CaptureLogger")
        t = pa.table({"k": pa.array([], type=pa.int64()),
                      "v": pa.array([], type=pa.float64())})
        d = tmp_path / "empty"
        d.mkdir()
        pq.write_table(t, d / "part0.parquet")
        hs = Hyperspace(session)
        df = session.read.parquet(str(d))
        hs.create_index(df, IndexConfig("empty_idx", ["k"], ["v"]))
        falls = [e for e in cap.events
                 if type(e).__name__ == "DistributedFallbackEvent"]
        assert falls and falls[0].where == "index_build"
        assert "empty" in falls[0].reason


class TestSpmdFallbackEvent:
    def test_unsupported_plan_emits_event(self, session, tmp_path):
        cap = capture_logger_cls()
        cap.events.clear()
        session.conf.set(IndexConstants.EVENT_LOGGER_CLASS,
                         "tests.conftest.CaptureLogger")
        rng = np.random.default_rng(5)
        t = pa.table({"k": rng.integers(0, 10, 100).astype(np.int64),
                      "v": rng.uniform(0, 1, 100)})
        d = tmp_path / "plain"
        d.mkdir()
        pq.write_table(t, d / "part0.parquet")
        df = session.read.parquet(str(d))
        # Sort under Aggregate is outside the SPMD shape → fallback + event.
        q = df.sort("k").group_by("k").agg(sum_(col("v")).alias("sv"))
        q.to_pandas()
        falls = [e for e in cap.events
                 if type(e).__name__ == "DistributedFallbackEvent"
                 and e.where == "spmd_query"]
        assert falls, "no fallback event for unsupported SPMD plan"
