"""Index lifecycle breadth (VERDICT r2 #8; parity: IndexManagerTest.scala,
821 LoC): every state transition, invalid transitions per state, refresh
modes against source mutations, optimize modes, cancel recovery, version
accumulation, and multi-index independence.
"""

import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.constants import IndexConstants, States
from hyperspace_tpu.index.log_manager import IndexLogManager
from hyperspace_tpu.plan.expr import col
from hyperspace_tpu.plan.nodes import IndexScan


def write_sample(root, name, df, parts=3):
    d = root / name
    d.mkdir(parents=True, exist_ok=True)
    step = max(1, len(df) // parts)
    for i in range(parts):
        chunk = df.iloc[i * step:(i + 1) * step if i < parts - 1 else len(df)]
        pq.write_table(pa.Table.from_pandas(chunk.reset_index(drop=True)),
                       d / f"part{i}.parquet")
    return str(d)


def make_df(n=600, seed=0):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "k": rng.integers(0, 100, n).astype(np.int64),
        "v": rng.integers(0, 1000, n).astype(np.int64),
    })


@pytest.fixture()
def env(tmp_path):
    path = write_sample(tmp_path, "data", make_df())
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    hs = Hyperspace(session)
    return dict(session=session, hs=hs, path=path, tmp=tmp_path)


def state_of(env, name):
    rows = env["hs"].indexes()
    row = rows[rows["name"] == name]
    return row.iloc[0]["state"] if len(row) else None


def log_mgr(env, name) -> IndexLogManager:
    return IndexLogManager(os.path.join(str(env["tmp"] / "indexes"), name))


class TestStateMachine:
    def test_full_lifecycle_walk(self, env):
        """ACTIVE → DELETED → ACTIVE → DELETED → DOESNOTEXIST."""
        hs, session = env["hs"], env["session"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("walk", ["k"], ["v"]))
        assert state_of(env, "walk") == States.ACTIVE
        hs.delete_index("walk")
        assert state_of(env, "walk") == States.DELETED
        hs.restore_index("walk")
        assert state_of(env, "walk") == States.ACTIVE
        hs.delete_index("walk")
        hs.vacuum_index("walk")
        assert state_of(env, "walk") in (States.DOESNOTEXIST, None)
        # Version data dirs are gone after vacuum.
        idx_dir = str(env["tmp"] / "indexes" / "walk")
        assert not [d for d in os.listdir(idx_dir) if d.startswith("v__=")]

    def test_recreate_after_vacuum(self, env):
        hs, session = env["hs"], env["session"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("reuse", ["k"], ["v"]))
        hs.delete_index("reuse")
        hs.vacuum_index("reuse")
        hs.create_index(df, IndexConfig("reuse", ["k"], ["v"]))
        assert state_of(env, "reuse") == States.ACTIVE
        session.enable_hyperspace()
        q = df.filter(col("k") == 5).select("k", "v")
        assert any(isinstance(l, IndexScan)
                   for l in q.optimized_plan().collect_leaves())

    def test_invalid_transitions_raise(self, env):
        hs, session = env["hs"], env["session"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("inv", ["k"], ["v"]))
        # restore on ACTIVE
        with pytest.raises(HyperspaceException):
            hs.restore_index("inv")
        # vacuum on ACTIVE
        with pytest.raises(HyperspaceException):
            hs.vacuum_index("inv")
        hs.delete_index("inv")
        # delete on DELETED
        with pytest.raises(HyperspaceException):
            hs.delete_index("inv")
        # refresh on DELETED
        with pytest.raises(HyperspaceException):
            hs.refresh_index("inv", "full")
        # optimize on DELETED
        with pytest.raises(HyperspaceException):
            hs.optimize_index("inv", "quick")

    def test_ops_on_missing_index_raise(self, env):
        hs = env["hs"]
        for op in (lambda: hs.delete_index("ghost"),
                   lambda: hs.restore_index("ghost"),
                   lambda: hs.vacuum_index("ghost"),
                   lambda: hs.refresh_index("ghost", "full"),
                   lambda: hs.optimize_index("ghost", "quick")):
            with pytest.raises(HyperspaceException):
                op()

    def test_deleted_index_not_used_in_rewrite(self, env):
        hs, session = env["hs"], env["session"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("hide", ["k"], ["v"]))
        session.enable_hyperspace()
        q = df.filter(col("k") == 1).select("k", "v")
        assert any(isinstance(l, IndexScan)
                   for l in q.optimized_plan().collect_leaves())
        hs.delete_index("hide")
        assert not any(isinstance(l, IndexScan)
                       for l in q.optimized_plan().collect_leaves())
        hs.restore_index("hide")
        assert any(isinstance(l, IndexScan)
                   for l in q.optimized_plan().collect_leaves())


class TestCancelRecovery:
    def _wedge(self, env, name, state):
        """Simulate a crash: append a transient-state entry by hand."""
        mgr = log_mgr(env, name)
        latest = mgr.get_latest_log()
        wedged = latest.with_state(state) if hasattr(latest, "with_state") \
            else None
        if wedged is None:
            import copy
            wedged = copy.deepcopy(latest)
            wedged.state = state
        assert mgr.write_log(mgr.get_latest_id() + 1, wedged)

    def test_cancel_restores_last_stable(self, env):
        hs, session = env["hs"], env["session"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("canc", ["k"], ["v"]))
        self._wedge(env, "canc", States.REFRESHING)
        hs.cancel("canc")
        assert state_of(env, "canc") == States.ACTIVE
        session.enable_hyperspace()
        q = df.filter(col("k") == 2).select("k", "v")
        assert any(isinstance(l, IndexScan)
                   for l in q.optimized_plan().collect_leaves())

    def test_cancel_on_stable_state_raises(self, env):
        hs, session = env["hs"], env["session"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("canc2", ["k"], ["v"]))
        with pytest.raises(HyperspaceException):
            hs.cancel("canc2")

    def test_wedged_index_not_used_until_cancel(self, env):
        hs, session = env["hs"], env["session"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("wedge", ["k"], ["v"]))
        self._wedge(env, "wedge", States.OPTIMIZING)
        session.enable_hyperspace()
        q = df.filter(col("k") == 3).select("k", "v")
        assert not any(isinstance(l, IndexScan)
                       for l in q.optimized_plan().collect_leaves())
        hs.cancel("wedge")
        assert any(isinstance(l, IndexScan)
                   for l in q.optimized_plan().collect_leaves())


class TestRefreshModes:
    def _mutate_append(self, env, seed=9):
        extra = make_df(120, seed=seed)
        pq.write_table(pa.Table.from_pandas(extra),
                       env["tmp"] / "data" / f"extra{seed}.parquet")
        return extra

    def test_full_refresh_after_append(self, env):
        hs, session = env["hs"], env["session"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("rf", ["k"], ["v"]))
        self._mutate_append(env)
        hs.refresh_index("rf", "full")
        session.enable_hyperspace()
        q = session.read.parquet(env["path"]).filter(col("k") == 7) \
            .select("k", "v")
        assert any(isinstance(l, IndexScan)
                   for l in q.optimized_plan().collect_leaves())
        got = q.to_pandas()
        session.disable_hyperspace()
        exp = q.to_pandas()
        pd.testing.assert_frame_equal(
            got.sort_values(["k", "v"]).reset_index(drop=True),
            exp.sort_values(["k", "v"]).reset_index(drop=True),
            check_dtype=False)

    def test_incremental_refresh_appends_only_new_files(self, env):
        session = env["session"]
        session.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
        hs = env["hs"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("ri", ["k"], ["v"]))
        v0_files = set(os.listdir(
            str(env["tmp"] / "indexes" / "ri" / "v__=0")))
        self._mutate_append(env)
        hs.refresh_index("ri", "incremental")
        # Incremental creates a NEW version dir holding only appended rows.
        idx_dir = str(env["tmp"] / "indexes" / "ri")
        versions = sorted(d for d in os.listdir(idx_dir)
                          if d.startswith("v__="))
        assert len(versions) >= 2
        assert set(os.listdir(os.path.join(idx_dir, versions[0]))) == v0_files

    def test_quick_refresh_is_metadata_only(self, env):
        session = env["session"]
        session.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, "true")
        hs = env["hs"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("rq", ["k"], ["v"]))
        idx_dir = str(env["tmp"] / "indexes" / "rq")
        before = {d: set(os.listdir(os.path.join(idx_dir, d)))
                  for d in os.listdir(idx_dir) if d.startswith("v__=")}
        self._mutate_append(env)
        hs.refresh_index("rq", "quick")
        after = {d: set(os.listdir(os.path.join(idx_dir, d)))
                 for d in os.listdir(idx_dir) if d.startswith("v__=")}
        assert before == after  # no data written
        entry = log_mgr(env, "rq").get_latest_stable_log()
        assert entry.appended_files

    def test_refresh_unknown_mode_raises(self, env):
        hs, session = env["hs"], env["session"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("rm", ["k"], ["v"]))
        with pytest.raises(HyperspaceException):
            hs.refresh_index("rm", "sideways")


class TestVersionsAndListing:
    def test_versions_accumulate_across_operations(self, env):
        hs, session = env["hs"], env["session"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("ver", ["k"], ["v"]))
        mgr = log_mgr(env, "ver")
        id_after_create = mgr.get_latest_id()
        hs.delete_index("ver")
        hs.restore_index("ver")
        assert mgr.get_latest_id() > id_after_create
        # Every commit is immutable history: old ids still readable.
        for log_id in range(0, mgr.get_latest_id() + 1):
            assert mgr.get_log(log_id) is not None

    def test_listing_shows_multiple_indexes_with_states(self, env):
        hs, session = env["hs"], env["session"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("lsA", ["k"], ["v"]))
        hs.create_index(df, IndexConfig("lsB", ["v"], ["k"]))
        hs.delete_index("lsB")
        rows = hs.indexes()
        states = dict(zip(rows["name"], rows["state"]))
        assert states["lsA"] == States.ACTIVE
        assert states["lsB"] == States.DELETED

    def test_index_stats_surface(self, env):
        hs, session = env["hs"], env["session"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("st", ["k"], ["v"]))
        row = hs.index("st").iloc[0]
        assert row["indexedColumns"] == ["k"]
        assert row["numBuckets"] == 4

    def test_operations_do_not_cross_indexes(self, env):
        hs, session = env["hs"], env["session"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("indA", ["k"], ["v"]))
        hs.create_index(df, IndexConfig("indB", ["v"], ["k"]))
        hs.delete_index("indA")
        assert state_of(env, "indB") == States.ACTIVE
        hs.vacuum_index("indA")
        session.enable_hyperspace()
        q = df.filter(col("v") == 10).select("v", "k")
        leaves = q.optimized_plan().collect_leaves()
        assert any(isinstance(l, IndexScan)
                   and l.index_entry.name == "indB" for l in leaves)


class TestOptimizeModes:
    def _fragmented_index(self, env, name):
        """Incremental refreshes fragment bucket files across versions."""
        session = env["session"]
        session.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
        hs = env["hs"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig(name, ["k"], ["v"]))
        for seed in (21, 22):
            extra = make_df(100, seed=seed)
            pq.write_table(pa.Table.from_pandas(extra),
                           env["tmp"] / "data" / f"x{seed}.parquet")
            hs.refresh_index(name, "incremental")
        return df

    def test_optimize_full_compacts_to_one_file_per_bucket(self, env):
        hs, session = env["hs"], env["session"]
        df = self._fragmented_index(env, "opt")
        entry_before = log_mgr(env, "opt").get_latest_stable_log()
        files_before = len(entry_before.content.files)
        hs.optimize_index("opt", "full")
        entry = log_mgr(env, "opt").get_latest_stable_log()
        assert len(entry.content.files) <= files_before
        by_bucket = {}
        from hyperspace_tpu.ops.index_build import bucket_id_from_file
        for f in entry.content.files:
            b = bucket_id_from_file(f)
            by_bucket.setdefault(b, []).append(f)
        assert all(len(v) == 1 for v in by_bucket.values())
        # Answers still correct.
        session.enable_hyperspace()
        q = session.read.parquet(env["path"]).filter(col("k") < 30) \
            .select("k", "v")
        got = q.to_pandas()
        session.disable_hyperspace()
        exp = q.to_pandas()
        pd.testing.assert_frame_equal(
            got.sort_values(["k", "v"]).reset_index(drop=True),
            exp.sort_values(["k", "v"]).reset_index(drop=True),
            check_dtype=False)
