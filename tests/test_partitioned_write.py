"""Hive-partitioned writes: df.write.partition_by(cols).parquet(path).

The write side of the partitioned-data support (VERDICT r2 #6 covered
reads; this closes the loop): output lands in `col=value/` directories,
reads back with the partition columns restored, and partition pruning
fires on the written layout.
"""

import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.plan.expr import col


@pytest.fixture()
def env(tmp_path):
    rng = np.random.default_rng(41)
    n = 1200
    d = tmp_path / "src"
    d.mkdir()
    pq.write_table(pa.Table.from_pandas(pd.DataFrame({
        "region": rng.choice(["emea", "apac", "amer"], n),
        "year": rng.choice([2022, 2023], n).astype(np.int64),
        "amount": rng.integers(0, 500, n).astype(np.int64),
    })), d / "p0.parquet")
    session = hst.Session(system_path=str(tmp_path / "idx"))
    return session, str(d), tmp_path


class TestPartitionedWrite:
    def test_hive_layout_and_roundtrip(self, env):
        session, src, tmp = env
        df = session.read.parquet(src)
        out = str(tmp / "out1")
        df.write.partition_by("region").parquet(out)
        subdirs = sorted(x for x in os.listdir(out)
                         if os.path.isdir(os.path.join(out, x)))
        assert subdirs == ["region=amer", "region=apac", "region=emea"]
        back = session.read.parquet(out)
        # Partition column restored by the reader's discovery.
        assert sorted(back.columns) == ["amount", "region", "year"]
        key = ["region", "year", "amount"]
        a = back.to_pandas().sort_values(key).reset_index(drop=True)[key]
        b = df.to_pandas().sort_values(key).reset_index(drop=True)[key]
        pd.testing.assert_frame_equal(a, b)

    def test_two_level_partitioning(self, env):
        session, src, tmp = env
        df = session.read.parquet(src)
        out = str(tmp / "out2")
        df.write.partition_by("region", "year").parquet(out)
        assert os.path.isdir(os.path.join(out, "region=emea", "year=2022"))
        back = session.read.parquet(out)
        assert back.count() == 1200

    def test_partition_pruning_on_written_layout(self, env):
        session, src, tmp = env
        df = session.read.parquet(src)
        out = str(tmp / "out3")
        df.write.partition_by("region").parquet(out)
        back = session.read.parquet(out)
        q = back.filter(col("region") == "apac")
        leaves = q.optimized_plan().collect_leaves()
        files = leaves[0].relation.all_files()
        # Planning-time pruning: only the apac partition's files remain.
        assert files and all("region=apac" in f for f in files)
        assert q.count() == int(
            (df.to_pandas()["region"] == "apac").sum())

    def test_modes(self, env):
        session, src, tmp = env
        df = session.read.parquet(src)
        out = str(tmp / "out4")
        df.write.partition_by("region").parquet(out)
        with pytest.raises(HyperspaceException, match="not empty"):
            df.write.partition_by("region").parquet(out)
        df.write.mode("append").partition_by("region").parquet(out)
        assert session.read.parquet(out).count() == 2400
        df.write.mode("overwrite").partition_by("region").parquet(out)
        assert session.read.parquet(out).count() == 1200

    def test_validation(self, env):
        session, src, tmp = env
        df = session.read.parquet(src)
        with pytest.raises(HyperspaceException, match="at least one"):
            df.write.partition_by()
        with pytest.raises(HyperspaceException, match="not in the result"):
            df.write.partition_by("ghost")
        with pytest.raises(HyperspaceException, match="every output"):
            df.write.partition_by("region", "year", "amount")
        with pytest.raises(HyperspaceException, match="cannot be combined"):
            df.write.partition_by("region").bucket_by(3, "amount")
        with pytest.raises(HyperspaceException, match="cannot be combined"):
            df.write.bucket_by(3, "amount").partition_by("region")

    def test_partition_by_rejected_for_non_parquet(self, env):
        session, src, tmp = env
        df = session.read.parquet(src)
        for fmt in ("csv", "json", "avro"):
            with pytest.raises(HyperspaceException, match="only supported"):
                getattr(df.write.partition_by("region"), fmt)(
                    str(tmp / f"o_{fmt}"))

    def test_partitioned_append_into_bucketed_dir_rejected(self, env):
        session, src, tmp = env
        df = session.read.parquet(src)
        out = str(tmp / "out5")
        df.write.bucket_by(3, "amount").parquet(out)
        with pytest.raises(HyperspaceException, match="bucketed dataset"):
            df.write.mode("append").partition_by("region").parquet(out)

    def test_empty_result_keeps_schema(self, env):
        session, src, tmp = env
        df = session.read.parquet(src)
        out = str(tmp / "out6")
        df.filter(col("amount") > 10_000).write.partition_by(
            "region").parquet(out)
        back = session.read.parquet(out)
        assert back.count() == 0
        assert sorted(back.columns) == ["amount", "region", "year"]

    def test_duplicate_partition_columns_rejected(self, env):
        session, src, tmp = env
        df = session.read.parquet(src)
        with pytest.raises(HyperspaceException, match="repeat"):
            df.write.partition_by("region", "region")
