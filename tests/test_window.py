"""Window (analytic) functions: executor semantics vs a pandas oracle.

The reference inherits window execution from Spark SQL (its TPC-DS golden
corpus is full of rank()/sum() OVER — e.g. queries q51, q53, q63, q89);
here Window is a first-class plan node (plan/nodes.py) executed as
sort + segmented scans (execution/executor.py _execute_window), and these
tests pin the semantics: rank families, the three frames (whole partition,
RANGE-running with order peers, ROWS-running), null handling, and
order-preservation of the operator itself.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.plan import expr as E


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    root = tmp_path_factory.mktemp("window")
    rng = np.random.default_rng(11)
    n = 400
    v = np.round(rng.uniform(0, 100, n), 2)
    valid = rng.random(n) > 0.15
    t = pa.table({
        "g": pa.array(rng.integers(0, 6, n).astype(np.int64)),
        "o": pa.array(rng.integers(0, 25, n).astype(np.int64)),
        "v": pa.array(v),
        "nv": pa.array([float(x) if ok else None
                        for x, ok in zip(v, valid)], type=pa.float64()),
        "s": pa.array(rng.choice(["aa", "bb", "cc", "dd"], n)),
    })
    d = root / "t"
    d.mkdir()
    pq.write_table(t, str(d / "p.parquet"))
    session = hst.Session(system_path=str(root / "idx"))
    df = session.read.parquet(str(d))
    return session, df, t.to_pandas()


def _sorted(df, cols):
    return df.sort_values(cols, kind="stable").reset_index(drop=True)


def test_rank_min_semantics(env):
    _, df, pdf = env
    out = df.with_window("rk", E.window(
        "rank", partition_by=["g"], order_by=[("o", False)])).to_pandas()
    exp = pdf.assign(rk=pdf.groupby("g")["o"].rank(
        method="min", ascending=False).astype("int64"))
    pd.testing.assert_series_equal(_sorted(out, ["g", "o", "v"])["rk"],
                                   _sorted(exp, ["g", "o", "v"])["rk"])


def test_dense_rank_and_row_number(env):
    _, df, pdf = env
    out = df.with_window(
        "dr", E.window("dense_rank", partition_by=["g"], order_by=["o"])) \
        .with_window(
        "rn", E.window("row_number", partition_by=["g"], order_by=["o"])) \
        .to_pandas()
    exp = pdf.assign(dr=pdf.groupby("g")["o"].rank(
        method="dense").astype("int64"))
    pd.testing.assert_series_equal(_sorted(out, ["g", "o", "v"])["dr"],
                                   _sorted(exp, ["g", "o", "v"])["dr"])
    for _, grp in out.groupby("g"):
        assert sorted(grp["rn"]) == list(range(1, len(grp) + 1))
        # row_number refines rank: within a partition, ordering rows by
        # rn must keep o non-decreasing.
        assert grp.sort_values("rn")["o"].is_monotonic_increasing


def test_whole_partition_aggregates(env):
    _, df, pdf = env
    out = df.with_window("sm", E.window("sum", arg="v", partition_by=["g"])) \
        .with_window("av", E.window("avg", arg="v", partition_by=["g"])) \
        .with_window("mn", E.window("min", arg="v", partition_by=["g"])) \
        .with_window("mx", E.window("max", arg="v", partition_by=["g"])) \
        .with_window("ct", E.window("count", partition_by=["g"])) \
        .to_pandas()
    gb = pdf.groupby("g")["v"]
    exp = pdf.assign(sm=gb.transform("sum"), av=gb.transform("mean"),
                     mn=gb.transform("min"), mx=gb.transform("max"),
                     ct=gb.transform("size").astype("int64"))
    got, want = _sorted(out, ["g", "o", "v"]), _sorted(exp, ["g", "o", "v"])
    for c in ("sm", "av", "mn", "mx", "ct"):
        pd.testing.assert_series_equal(got[c], want[c], rtol=1e-9)


def test_running_sum_rows_frame(env):
    _, df, pdf = env
    out = df.with_window("rr", E.window(
        "sum", arg="v", partition_by=["g"], order_by=["o"],
        frame="rows")).to_pandas()
    got = _sorted(out, ["g", "o"])
    exp = _sorted(pdf, ["g", "o"])
    exp["rr"] = exp.groupby("g")["v"].cumsum()
    pd.testing.assert_series_equal(got["rr"], exp["rr"], rtol=1e-9)


def test_running_sum_range_frame_includes_peers(env):
    _, df, pdf = env
    out = df.with_window("rs", E.window(
        "sum", arg="v", partition_by=["g"], order_by=["o"])).to_pandas()
    exp = _sorted(pdf, ["g", "o"])
    exp["cum"] = exp.groupby("g")["v"].cumsum()
    # Default RANGE frame: order-key peers all take the peer group's total.
    exp["rs"] = exp.groupby(["g", "o"])["cum"].transform("max")
    pd.testing.assert_series_equal(_sorted(out, ["g", "o", "v"])["rs"],
                                   _sorted(exp, ["g", "o", "v"])["rs"],
                                   rtol=1e-9)


def test_nullable_argument(env):
    _, df, pdf = env
    out = df.with_window("sm", E.window("sum", arg="nv", partition_by=["g"])) \
        .with_window("av", E.window("avg", arg="nv", partition_by=["g"])) \
        .with_window("ct", E.window("count", arg="nv", partition_by=["g"])) \
        .to_pandas()
    gb = pdf.groupby("g")["nv"]
    exp = pdf.assign(sm=gb.transform("sum"), av=gb.transform("mean"),
                     ct=gb.transform("count").astype("int64"))
    got, want = _sorted(out, ["g", "o", "v"]), _sorted(exp, ["g", "o", "v"])
    for c in ("sm", "av", "ct"):
        pd.testing.assert_series_equal(got[c], want[c], rtol=1e-9)


def test_global_window_no_partition(env):
    _, df, pdf = env
    out = df.with_window("mx", E.window("max", arg="v")).to_pandas()
    assert np.allclose(out["mx"], pdf["v"].max())


def test_string_min_max_over_partition(env):
    _, df, pdf = env
    out = df.with_window("smin", E.window(
        "min", arg="s", partition_by=["g"])).to_pandas()
    exp = pdf.assign(smin=pdf.groupby("g")["s"].transform("min"))
    pd.testing.assert_series_equal(_sorted(out, ["g", "o", "v"])["smin"],
                                   _sorted(exp, ["g", "o", "v"])["smin"])


def test_window_preserves_row_order(env):
    _, df, pdf = env
    out = df.with_window("rn", E.window(
        "row_number", partition_by=["g"], order_by=["o"])).to_pandas()
    # The operator appends a column without permuting existing rows.
    pd.testing.assert_frame_equal(out[["g", "o", "v"]],
                                  pdf[["g", "o", "v"]])


def test_rank_requires_order_by(env):
    with pytest.raises(HyperspaceException, match="requires ORDER BY"):
        E.window("rank", partition_by=["g"])


def test_empty_input(env):
    _, df, _ = env
    out = df.filter(E.col("o") < -1).with_window(
        "rk", E.window("rank", partition_by=["g"],
                       order_by=["o"])).to_pandas()
    assert len(out) == 0 and "rk" in out.columns
