"""Delta-analogue integration tests.

Mirrors the reference's DeltaLakeIntegrationTest scenarios (599 LoC,
sources/delta/): version-based signatures, hybrid scan over table mutations,
version history accumulation on create/refresh, and time-travel-aware
closest-index selection.
"""

import os

import numpy as np
import pyarrow as pa
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.lake.delta import (DeltaConcurrentModificationException,
                                       DeltaTable)
from hyperspace_tpu.plan.expr import col
from hyperspace_tpu.plan.nodes import IndexScan
from hyperspace_tpu.sources.delta import (DELTA_VERSION_HISTORY_PROPERTY,
                                          DeltaLakeRelation)


def _arrow(lo, hi, seed=0):
    rng = np.random.default_rng(seed)
    n = hi - lo
    return pa.table({
        "k": pa.array(np.arange(lo, hi, dtype=np.int64)),
        "grp": pa.array((np.arange(lo, hi) % 13).astype(np.int64)),
        "v": pa.array(rng.uniform(0, 1, n)),
    })


def _sorted(t):
    return t.sort_by([(c, "ascending") for c in t.column_names])


def _index_leaves(df):
    return [l for l in df.optimized_plan().collect_leaves()
            if isinstance(l, IndexScan)]


class TestDeltaTable:
    def test_create_append_remove_time_travel(self, tmp_path):
        t = DeltaTable(str(tmp_path / "t"))
        assert t.create(_arrow(0, 100), max_rows_per_file=40) == 0
        assert t.append(_arrow(100, 150)) == 1
        snap0, snap1 = t.snapshot(0), t.snapshot(1)
        assert len(snap0.file_paths) == 3
        assert len(snap1.file_paths) == 4
        victim = snap0.file_paths[0]
        assert t.remove_files([victim]) == 2
        assert victim not in t.snapshot(2).file_paths
        assert victim in t.snapshot(0).file_paths  # history immutable.
        ops = [h["operation"] for h in t.history()]
        assert ops == ["WRITE", "APPEND", "DELETE"]

    def test_concurrent_commit_conflicts(self, tmp_path):
        t = DeltaTable(str(tmp_path / "t"))
        t.create(_arrow(0, 10))
        # Simulate a racer that claimed version 1 first.
        t._write_commit(1, [{"commitInfo": {"operation": "APPEND"}}])
        with pytest.raises(DeltaConcurrentModificationException):
            t._write_commit(1, [{"commitInfo": {"operation": "APPEND"}}])

    def test_overwrite_resets_files(self, tmp_path):
        t = DeltaTable(str(tmp_path / "t"))
        t.create(_arrow(0, 50), max_rows_per_file=25)
        t.overwrite(_arrow(0, 10))
        assert len(t.snapshot().file_paths) == 1
        assert len(t.snapshot(0).file_paths) == 2


class TestDeltaIndexIntegration:
    @pytest.fixture()
    def session(self, tmp_system_path):
        s = hst.Session(system_path=tmp_system_path)
        s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
        return s

    def test_index_used_and_answers_match(self, session, tmp_path):
        DeltaTable(str(tmp_path / "t")).create(_arrow(0, 500))
        hs = Hyperspace(session)
        df = session.read.delta(str(tmp_path / "t"))
        hs.create_index(df, IndexConfig("dix", ["grp"], ["k", "v"]))
        q = df.filter(col("grp") == 5).select("k", "v")
        session.enable_hyperspace()
        with_idx = _sorted(q.to_arrow())
        assert _index_leaves(q)
        session.disable_hyperspace()
        assert with_idx.equals(_sorted(q.to_arrow()))

    def test_version_signature_and_hybrid_scan(self, session, tmp_path):
        table = DeltaTable(str(tmp_path / "t"))
        table.create(_arrow(0, 400))
        hs = Hyperspace(session)
        df = session.read.delta(str(tmp_path / "t"))
        hs.create_index(df, IndexConfig("dix", ["grp"], ["k"]))
        table.append(_arrow(400, 430))
        df2 = session.read.delta(str(tmp_path / "t"))
        q = df2.filter(col("grp") == 3).select("k")
        session.enable_hyperspace()
        # New delta version → signature mismatch → unused without hybrid.
        assert not _index_leaves(q)
        session.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, "true")
        leaves = _index_leaves(q)
        assert leaves and leaves[0].appended_files
        with_idx = _sorted(q.to_arrow())
        session.disable_hyperspace()
        assert with_idx.equals(_sorted(q.to_arrow()))

    def test_hybrid_scan_deleted_files(self, session, tmp_path):
        session.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
        session.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, "true")
        # Removing 1 of 3 equal files ≈ 0.33 deleted-bytes ratio > the 0.2
        # default cap; lift it so the delete rides Hybrid Scan.
        session.conf.set(
            IndexConstants.INDEX_HYBRID_SCAN_DELETED_RATIO_THRESHOLD, "0.5")
        table = DeltaTable(str(tmp_path / "t"))
        table.create(_arrow(0, 300), max_rows_per_file=100)
        hs = Hyperspace(session)
        df = session.read.delta(str(tmp_path / "t"))
        hs.create_index(df, IndexConfig("dix", ["grp"], ["k"]))
        table.remove_files([table.snapshot().file_paths[0]])
        df2 = session.read.delta(str(tmp_path / "t"))
        q = df2.filter(col("grp") == 1).select("k")
        session.enable_hyperspace()
        leaves = _index_leaves(q)
        assert leaves and leaves[0].deleted_file_ids
        with_idx = _sorted(q.to_arrow())
        session.disable_hyperspace()
        assert with_idx.equals(_sorted(q.to_arrow()))

    def test_version_history_accumulates(self, session, tmp_path):
        table = DeltaTable(str(tmp_path / "t"))
        table.create(_arrow(0, 200))
        hs = Hyperspace(session)
        df = session.read.delta(str(tmp_path / "t"))
        hs.create_index(df, IndexConfig("dix", ["grp"], ["k"]))
        entry = session.index_collection_manager.get_index("dix")
        hist1 = DeltaLakeRelation.parse_version_history(
            entry.derivedDataset.properties)
        assert hist1 == [(1, 0)]  # create commits at log id 1, delta v0.
        table.append(_arrow(200, 260))
        hs.refresh_index("dix", "incremental")
        entry = session.index_collection_manager.get_index("dix")
        hist2 = DeltaLakeRelation.parse_version_history(
            entry.derivedDataset.properties)
        assert hist2 == [(1, 0), (3, 1)]

    def test_closest_index_time_travel(self, session, tmp_path):
        """Time travel picks the index log version built nearest (≤) the
        scanned delta version (reference: DeltaLakeRelation.closestIndex)."""
        table = DeltaTable(str(tmp_path / "t"))
        table.create(_arrow(0, 200))
        hs = Hyperspace(session)
        df = session.read.delta(str(tmp_path / "t"))
        hs.create_index(df, IndexConfig("dix", ["grp"], ["k"]))
        table.append(_arrow(200, 260))
        hs.refresh_index("dix", "incremental")   # log 3 ↔ delta v1.

        session.enable_hyperspace()
        # Scan of old version v0 → index log version 1 (exact source match).
        q0 = session.read.delta(str(tmp_path / "t"), version_as_of=0) \
            .filter(col("grp") == 2).select("k")
        leaves = _index_leaves(q0)
        assert leaves and leaves[0].index_entry.id == 1
        with_idx = _sorted(q0.to_arrow())
        session.disable_hyperspace()
        assert with_idx.equals(_sorted(q0.to_arrow()))
        session.enable_hyperspace()

        # Latest scan → the refreshed entry (log 3).
        q1 = session.read.delta(str(tmp_path / "t")) \
            .filter(col("grp") == 2).select("k")
        leaves = _index_leaves(q1)
        assert leaves and leaves[0].index_entry.id == 3

    def test_refresh_unpins_time_traveled_create(self, session, tmp_path):
        """An index created over a versionAsOf read must track the live
        table on refresh (refresh() strips the version pin)."""
        table = DeltaTable(str(tmp_path / "t"))
        table.create(_arrow(0, 200))
        table.append(_arrow(200, 260))
        hs = Hyperspace(session)
        df0 = session.read.delta(str(tmp_path / "t"), version_as_of=0)
        hs.create_index(df0, IndexConfig("dix", ["grp"], ["k"]))
        hs.refresh_index("dix", "incremental")  # must see v1's appends.
        session.enable_hyperspace()
        q = session.read.delta(str(tmp_path / "t")) \
            .filter(col("grp") == 2).select("k")
        leaves = _index_leaves(q)
        assert leaves and not leaves[0].appended_files
        with_idx = _sorted(q.to_arrow())
        session.disable_hyperspace()
        assert with_idx.equals(_sorted(q.to_arrow()))

    def test_optimize_keeps_latest_entry(self, session, tmp_path):
        """optimize() commits a new ACTIVE log id without a history pair;
        latest-version queries must keep the optimized entry rather than
        falling back to the pre-compaction one."""
        table = DeltaTable(str(tmp_path / "t"))
        table.create(_arrow(0, 200))
        hs = Hyperspace(session)
        df = session.read.delta(str(tmp_path / "t"))
        hs.create_index(df, IndexConfig("dix", ["grp"], ["k"]))
        table.append(_arrow(200, 260))
        hs.refresh_index("dix", "incremental")  # log 3: multi-file buckets.
        hs.optimize_index("dix", "full")        # log 5: compacted.
        session.enable_hyperspace()
        q = session.read.delta(str(tmp_path / "t")) \
            .filter(col("grp") == 2).select("k")
        leaves = _index_leaves(q)
        assert leaves and leaves[0].index_entry.id == 5
        with_idx = _sorted(q.to_arrow())
        session.disable_hyperspace()
        assert with_idx.equals(_sorted(q.to_arrow()))

    def test_explain_mentions_delta_index(self, session, tmp_path):
        DeltaTable(str(tmp_path / "t")).create(_arrow(0, 100))
        hs = Hyperspace(session)
        df = session.read.delta(str(tmp_path / "t"))
        hs.create_index(df, IndexConfig("dix", ["grp"], ["k"]))
        session.enable_hyperspace()
        out = hs.explain(df.filter(col("grp") == 1).select("k"))
        assert "dix" in out


class TestClosestIndexSelection:
    def test_prefers_at_or_before_then_nearest(self, tmp_path):
        t = DeltaTable(str(tmp_path / "t"))
        t.create(_arrow(0, 10))
        t.append(_arrow(10, 20))
        t.append(_arrow(20, 30))
        props = {DELTA_VERSION_HISTORY_PROPERTY: "1:0,3:2"}
        rel_v0 = DeltaLakeRelation(str(tmp_path / "t"),
                                   {"versionAsOf": "0"})
        rel_v1 = DeltaLakeRelation(str(tmp_path / "t"),
                                   {"versionAsOf": "1"})
        rel_v2 = DeltaLakeRelation(str(tmp_path / "t"))
        assert rel_v0.closest_index_log_version(props) == 1
        assert rel_v1.closest_index_log_version(props) == 1  # ≤ wins.
        # Latest history pair covers the scanned version → None (keep the
        # current entry even if its log id is newer, e.g. post-optimize).
        assert rel_v2.closest_index_log_version(props) is None
        # No history at or before → nearest overall.
        assert rel_v0.closest_index_log_version(
            {DELTA_VERSION_HISTORY_PROPERTY: "5:1,7:2"}) == 5
        assert rel_v0.closest_index_log_version({}) is None


class TestDeltaRelationBasics:
    def test_signature_is_version_based(self, tmp_path):
        t = DeltaTable(str(tmp_path / "t"))
        t.create(_arrow(0, 50))
        r0 = DeltaLakeRelation(str(tmp_path / "t"))
        sig0 = r0.signature()
        assert DeltaLakeRelation(str(tmp_path / "t")).signature() == sig0
        t.append(_arrow(50, 60))
        r1 = DeltaLakeRelation(str(tmp_path / "t"))
        assert r1.signature() != sig0
        # Time travel back to v0 reproduces the original signature.
        assert DeltaLakeRelation(str(tmp_path / "t"),
                                 {"versionAsOf": "0"}).signature() == sig0

    def test_file_infos_from_log_match_stat(self, tmp_path):
        t = DeltaTable(str(tmp_path / "t"))
        t.create(_arrow(0, 50))
        rel = DeltaLakeRelation(str(tmp_path / "t"))
        from hyperspace_tpu.util.file_utils import file_info_triple
        assert rel.all_file_infos() == [
            file_info_triple(p) for p in rel.all_files()]
