"""Bucketed dataset writes: df.write.bucket_by(n, cols).parquet(path).

Parity: index/DataFrameWriterExtensionsTest.scala:160-178 (saveWithBuckets
with a single bucket column, multiple bucketing columns, and Append mode) —
every row lands in the file its hash says, rows within a file are sorted by
the bucketing columns, and appends add files without disturbing either
invariant.
"""

import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.execution.columnar import Table
from hyperspace_tpu.ops import index_build
from hyperspace_tpu.plan.expr import col


@pytest.fixture()
def env(tmp_path):
    rng = np.random.default_rng(21)
    n = 3000
    d = tmp_path / "src"
    d.mkdir()
    pq.write_table(pa.Table.from_pandas(pd.DataFrame({
        "query": rng.choice(["donde", "bolsa", "santander", "fitbit"], n),
        "clicks": rng.integers(0, 500, n).astype(np.int64),
        "ts": rng.integers(0, 10_000, n).astype(np.int64),
    })), d / "p0.parquet")
    session = hst.Session(system_path=str(tmp_path / "idx"))
    return session, str(d), tmp_path


def check_bucketed_dir(session, out, num_buckets, cols, expect_rows):
    # The _bucket_spec.json sidecar records the layout; readers only list
    # format suffixes so it is invisible to them.
    files = sorted(f for f in os.listdir(out) if f.endswith(".parquet"))
    seen_buckets = set()
    total = 0
    for f in files:
        b = index_build.bucket_id_from_file(f)
        assert b is not None and 0 <= b < num_buckets, f
        seen_buckets.add(b)
        t = pq.read_table(os.path.join(out, f))
        total += t.num_rows
        # Rows in the file hash to exactly this bucket: recompute ids
        # through the same pipeline.
        dev = Table.from_arrow(t)
        bids = np.asarray(index_build.bucket_ids_for(dev, cols, num_buckets))
        assert (bids == b).all(), f"{f}: foreign rows present"
        # Within-file sort by the bucketing columns.
        pdf = t.to_pandas()
        expect = pdf.sort_values(cols, kind="stable").reset_index(drop=True)
        pd.testing.assert_frame_equal(
            pdf[cols].reset_index(drop=True), expect[cols])
    assert total == expect_rows
    return seen_buckets


class TestBucketedWrite:
    def test_single_bucket_column(self, env):
        session, src, tmp = env
        df = session.read.parquet(src)
        out = str(tmp / "out1")
        df.write.bucket_by(3, "query").parquet(out)
        check_bucketed_dir(session, out, 3, ["query"], 3000)
        # Round trip: same multiset of rows.
        back = session.read.parquet(out).to_pandas()
        orig = df.to_pandas()
        key = ["query", "clicks", "ts"]
        pd.testing.assert_frame_equal(
            back.sort_values(key).reset_index(drop=True)[key],
            orig.sort_values(key).reset_index(drop=True)[key])

    def test_multiple_bucket_columns(self, env):
        session, src, tmp = env
        df = session.read.parquet(src)
        out = str(tmp / "out2")
        df.write.bucket_by(3, "clicks", "query").parquet(out)
        check_bucketed_dir(session, out, 3, ["clicks", "query"], 3000)

    def test_append_mode(self, env):
        session, src, tmp = env
        df = session.read.parquet(src)
        out = str(tmp / "out3")
        df.write.bucket_by(3, "clicks", "query").parquet(out)
        df.write.mode("append").bucket_by(3, "clicks", "query").parquet(out)
        check_bucketed_dir(session, out, 3, ["clicks", "query"], 6000)

    def test_writes_query_result_not_source(self, env):
        session, src, tmp = env
        df = session.read.parquet(src)
        out = str(tmp / "out4")
        q = df.filter(col("clicks") > 250).select("query", "clicks")
        q.write.bucket_by(2, "query").parquet(out)
        n = q.count()
        assert n > 0
        check_bucketed_dir(session, out, 2, ["query"], n)

    def test_bucket_by_validation(self, env):
        session, src, tmp = env
        df = session.read.parquet(src)
        with pytest.raises(HyperspaceException, match="positive"):
            df.write.bucket_by(0, "query")
        with pytest.raises(HyperspaceException, match="at least one"):
            df.write.bucket_by(3)
        with pytest.raises(HyperspaceException, match="not in the result"):
            df.write.bucket_by(3, "ghost")
        with pytest.raises(HyperspaceException, match="only supported"):
            df.write.bucket_by(3, "query").csv(str(tmp / "o"))

    def test_overwrite_replaces_files(self, env):
        session, src, tmp = env
        df = session.read.parquet(src)
        out = str(tmp / "out5")
        parquets = lambda: {f for f in os.listdir(out)
                            if f.endswith(".parquet")}
        df.write.bucket_by(3, "query").parquet(out)
        first = parquets()
        df.write.mode("overwrite").bucket_by(3, "query").parquet(out)
        second = parquets()
        assert first.isdisjoint(second)  # fresh per-write suffix
        check_bucketed_dir(session, out, 3, ["query"], 3000)

    def test_empty_result_preserves_schema(self, env):
        session, src, tmp = env
        df = session.read.parquet(src)
        out = str(tmp / "out6")
        df.filter(col("clicks") > 10_000).write.bucket_by(
            3, "query").parquet(out)
        back = session.read.parquet(out)
        assert back.count() == 0
        assert back.columns == ["query", "clicks", "ts"]

    def test_append_with_different_spec_rejected(self, env):
        session, src, tmp = env
        df = session.read.parquet(src)
        out = str(tmp / "out7")
        df.write.bucket_by(3, "query").parquet(out)
        with pytest.raises(HyperspaceException, match="does not match"):
            df.write.mode("append").bucket_by(5, "query").parquet(out)
        with pytest.raises(HyperspaceException, match="does not match"):
            df.write.mode("append").bucket_by(3, "clicks").parquet(out)
        # The matching spec still appends fine.
        df.write.mode("append").bucket_by(3, "query").parquet(out)
        check_bucketed_dir(session, out, 3, ["query"], 6000)

    def test_unbucketed_append_into_bucketed_dir_rejected(self, env):
        session, src, tmp = env
        df = session.read.parquet(src)
        out = str(tmp / "out8")
        df.write.bucket_by(3, "query").parquet(out)
        with pytest.raises(HyperspaceException, match="bucketed dataset"):
            df.write.mode("append").parquet(out)

    def test_bucket_append_into_plain_dir_rejected(self, env):
        session, src, tmp = env
        df = session.read.parquet(src)
        out = str(tmp / "out9")
        df.write.parquet(out)
        with pytest.raises(HyperspaceException, match="no bucket spec"):
            df.write.mode("append").bucket_by(3, "query").parquet(out)
