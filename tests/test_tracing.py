"""Unified query tracing + process metrics registry
(telemetry/trace.py, telemetry/metrics.py, telemetry/span_names.py).

Covers: the span-tree shape of a TPC-H-q3-like run (cold vs
result-cache-hit traces differ exactly at the cache-lookup span), trace
propagation through a multi-threaded ServingFrontend (no cross-query
span leakage, hammer-asserted), the shared literal-sweep span, the
Chrome-trace-event JSON exporter, tracing-off byte-identity + no-op
guarantees, trace_id stamping on every event emitted during a traced
run, the frozen span-name registry, the metrics registry's unified
surface (Hyperspace.metrics()), and the live serving latency histogram.

Sessions run with the default distributed tier; sources are kept below
``distributed.minStreamRows`` so the traced path is the (fast,
deterministic) fused single-device pipeline — the SPMD dispatch span is
covered by tests/test_join_reorder.py's un-pinned actuals tests and the
spmd.compile registry entry below.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col, sum_
from hyperspace_tpu.serving.constants import ServingConstants
from hyperspace_tpu.telemetry import span_names as sn
from hyperspace_tpu.telemetry.constants import TelemetryConstants as TC

from conftest import capture_logger  # noqa: E402


N_ORDERS = 400
LI_FILES = 4
LI_ROWS_PER_FILE = 500  # 2000 total: under the 4096 minStreamRows gate


@pytest.fixture()
def q3ish(tmp_path):
    """A miniature TPC-H q3 shape: filtered lineitem x filtered orders,
    grouped revenue, sorted — lineitem split over several files so the
    pooled reader fan-out (and its io.read span) engages."""
    rng = np.random.default_rng(13)
    li_dir = tmp_path / "lineitem"
    os.makedirs(li_dir)
    for i in range(LI_FILES):
        n = LI_ROWS_PER_FILE
        t = pa.table({
            "l_orderkey": pa.array(
                rng.integers(0, N_ORDERS, n).astype(np.int64)),
            "l_shipdate": pa.array(
                rng.integers(0, 1000, n).astype(np.int64)),
            "l_extendedprice": pa.array(rng.uniform(1, 1000, n).round(2)),
            "l_discount": pa.array(rng.uniform(0, 0.1, n).round(3)),
        })
        pq.write_table(t, os.path.join(li_dir, f"part{i}.parquet"))
    od_dir = tmp_path / "orders"
    os.makedirs(od_dir)
    od = pa.table({
        "o_orderkey": pa.array(np.arange(N_ORDERS, dtype=np.int64)),
        "o_orderdate": pa.array(
            rng.integers(0, 1000, N_ORDERS).astype(np.int64)),
        "o_shippriority": pa.array(
            rng.integers(0, 3, N_ORDERS).astype(np.int64)),
    })
    pq.write_table(od, os.path.join(od_dir, "part0.parquet"))
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    return session, str(li_dir), str(od_dir)


def _build_q3(session, li_dir, od_dir, ship_cut=500):
    li = session.read.parquet(li_dir).filter(
        col("l_shipdate") > int(ship_cut))
    od = session.read.parquet(od_dir).filter(col("o_orderdate") < 700)
    return (li.join(od, on=col("l_orderkey") == col("o_orderkey"))
            .group_by("o_shippriority")
            .agg(sum_(col("l_extendedprice") * (1 - col("l_discount")))
                 .alias("revenue"))
            .sort("o_shippriority"))


def _tracing(session, on: bool) -> None:
    session.conf.set(TC.TRACE_ENABLED, "true" if on else "false")


# ---------------------------------------------------------------------------
# Span-tree shape.
# ---------------------------------------------------------------------------

class TestTraceShape:
    def test_q3_cold_trace_covers_every_boundary(self, q3ish):
        session, li_dir, od_dir = q3ish
        session.enable_hyperspace()
        hs = Hyperspace(session)
        q = _build_q3(session, li_dir, od_dir)
        q.to_arrow()  # warm (compiles) untraced
        # Drop the warm-up read's buffers so the traced run performs
        # real pooled I/O — a buffer-pool hit would skip io.read spans.
        from hyperspace_tpu.execution import buffer_pool
        buffer_pool.get_pool().clear()
        _tracing(session, True)
        q.to_arrow()
        tr = hs.last_trace()
        assert tr is not None and tr.dropped == 0
        names = {s.name for s in tr.spans}
        # The acceptance set: optimize, rewrite, per-stage execution,
        # program-bank lookups, and pooled I/O reads, under one root.
        assert sn.QUERY in names
        assert sn.PLAN_NORMALIZE in names
        assert sn.INDEX_REWRITE in names
        assert sn.EXEC_STAGE in names
        assert sn.BANK_LOOKUP in names
        assert sn.IO_READ in names
        # Tree integrity: exactly one root, every parent id resolves,
        # every span carries the trace's id.
        roots = [s for s in tr.spans if s.parent_id is None]
        assert [r.name for r in roots] == [sn.QUERY]
        ids = {s.span_id for s in tr.spans}
        for s in tr.spans:
            assert s.trace_id == tr.trace_id
            assert s.parent_id is None or s.parent_id in ids
        # exec.stage spans nest with the plan tree (a join stage has a
        # child stage), and the io.read span hangs off a scan stage.
        exec_ids = {s.span_id for s in tr.spans if s.name == sn.EXEC_STAGE}
        assert any(s.parent_id in exec_ids for s in tr.spans
                   if s.name == sn.EXEC_STAGE)
        assert any(s.parent_id in exec_ids for s in tr.spans
                   if s.name == sn.IO_READ)
        # Node attributes ride the stage spans. The Join (and the filter
        # chain under it) executes inside the whole-plan FUSED region by
        # default — its exec.fused span hangs off the Aggregate stage and
        # reports how many plan nodes it collapsed.
        stage_nodes = {s.attrs.get("node") for s in tr.spans
                       if s.name == sn.EXEC_STAGE}
        assert {"Aggregate", "Sort"} <= stage_nodes
        fused = tr.find(sn.EXEC_FUSED)
        assert fused and max(s.attrs["fused_nodes"] for s in fused) >= 2
        assert any(s.parent_id in exec_ids for s in fused)

    def test_cold_vs_hit_traces_differ_at_cache_lookup(self, q3ish):
        session, li_dir, od_dir = q3ish
        session.conf.set(ServingConstants.RESULT_CACHE_ENABLED, "true")
        session.conf.set(
            ServingConstants.RESULT_CACHE_MIN_COMPUTE_SECONDS, "0")
        hs = Hyperspace(session)
        q = _build_q3(session, li_dir, od_dir)
        _tracing(session, True)
        q.to_arrow()
        cold = hs.last_trace()
        q.to_arrow()
        hit = hs.last_trace()
        assert cold is not None and hit is not None
        assert cold.trace_id != hit.trace_id
        cold_lookup = cold.find(sn.CACHE_LOOKUP)
        hit_lookup = hit.find(sn.CACHE_LOOKUP)
        assert len(cold_lookup) == len(hit_lookup) == 1
        assert cold_lookup[0].attrs["hit"] is False
        assert hit_lookup[0].attrs["hit"] is True
        assert hit_lookup[0].attrs["tier"] in ("device", "host")
        # The hit trace is EXACTLY root + cache lookup: no optimize, no
        # execution, no reads. The cold trace carries the rest.
        assert {s.name for s in hit.spans} == {sn.QUERY, sn.CACHE_LOOKUP}
        assert hit.find(sn.EXEC_STAGE) == []
        assert cold.find(sn.EXEC_STAGE) != []

    def test_max_spans_cap_drops_not_grows(self, q3ish):
        session, li_dir, od_dir = q3ish
        session.conf.set(TC.TRACE_MAX_SPANS, "4")
        _tracing(session, True)
        q = _build_q3(session, li_dir, od_dir)
        q.to_arrow()
        tr = Hyperspace(session).last_trace()
        assert len(tr.spans) <= 4
        assert tr.dropped > 0
        # The capped trace still renders and exports.
        assert json.loads(tr.to_chrome_json())["otherData"][
            "dropped_spans"] == tr.dropped


# ---------------------------------------------------------------------------
# Tracing-off contract.
# ---------------------------------------------------------------------------

class TestTracingOff:
    def test_off_is_byte_identical_and_traceless(self, q3ish):
        # Tracing defaults ON since the observability round; the off
        # CONTRACT (hard no-op, byte identity) is now an explicit
        # opt-out.
        session, li_dir, od_dir = q3ish
        _tracing(session, False)
        hs = Hyperspace(session)
        q = _build_q3(session, li_dir, od_dir)
        off = q.to_arrow()
        assert hs.last_trace() is None
        _tracing(session, True)
        on = q.to_arrow()
        assert hs.last_trace() is not None
        _tracing(session, False)
        off2 = q.to_arrow()
        assert on.equals(off)
        assert off2.equals(off)
        # Turning tracing back off leaves the LAST trace readable but
        # records no new one (its id stays put).
        tid = hs.last_trace().trace_id
        q.to_arrow()
        assert hs.last_trace().trace_id == tid

    def test_off_events_carry_no_stamp(self, q3ish):
        session, li_dir, od_dir = q3ish
        _tracing(session, False)
        session.conf.set(IndexConstants.EVENT_LOGGER_CLASS,
                         "tests.conftest.CaptureLogger")
        sink = capture_logger()
        sink.events.clear()
        _build_q3(session, li_dir, od_dir).to_arrow()
        assert sink.events
        assert all(e.trace_id == "" and e.span_id == ""
                   for e in sink.events)


# ---------------------------------------------------------------------------
# Event stamping.
# ---------------------------------------------------------------------------

class TestEventStamping:
    def test_every_event_in_a_traced_run_is_stamped(self, q3ish):
        session, li_dir, od_dir = q3ish
        session.conf.set(IndexConstants.EVENT_LOGGER_CLASS,
                         "tests.conftest.CaptureLogger")
        session.conf.set(ServingConstants.RESULT_CACHE_ENABLED, "true")
        session.conf.set(
            ServingConstants.RESULT_CACHE_MIN_COMPUTE_SECONDS, "0")
        hs = Hyperspace(session)
        sink = capture_logger()
        q = _build_q3(session, li_dir, od_dir)
        _tracing(session, True)
        sink.events.clear()
        q.to_arrow()   # miss + admit (+ io reads, bank traffic)
        miss_tid = hs.last_trace().trace_id
        q.to_arrow()   # hit
        hit_tid = hs.last_trace().trace_id
        assert sink.events
        classes = {type(e).__name__ for e in sink.events}
        # Several distinct event classes fired, and EVERY one of them
        # carries the trace stamp of the query that emitted it.
        assert "ResultCacheMissEvent" in classes
        assert "ResultCacheHitEvent" in classes
        assert "IoReadEvent" in classes
        assert len(classes) >= 3
        for e in sink.events:
            assert e.trace_id in (miss_tid, hit_tid), type(e).__name__
            assert e.span_id != ""
        hit_events = [e for e in sink.events
                      if type(e).__name__ == "ResultCacheHitEvent"]
        assert all(e.trace_id == hit_tid for e in hit_events)


# ---------------------------------------------------------------------------
# Chrome trace-event export.
# ---------------------------------------------------------------------------

class TestChromeExport:
    def test_export_matches_trace_event_schema(self, q3ish):
        session, li_dir, od_dir = q3ish
        _tracing(session, True)
        _build_q3(session, li_dir, od_dir).to_arrow()
        tr = Hyperspace(session).last_trace()
        doc = json.loads(tr.to_chrome_json())
        assert set(doc) == {"traceEvents", "displayTimeUnit", "otherData"}
        events = doc["traceEvents"]
        assert len(events) == len(tr.spans)
        ids = set()
        for ev in events:
            # The complete-event ("X") schema chrome://tracing/Perfetto
            # require: name/cat/ph/ts/dur/pid/tid, args carrying the
            # span tree.
            assert {"name", "cat", "ph", "ts", "dur", "pid",
                    "tid"} <= set(ev)
            assert ev["ph"] == "X"
            assert ev["cat"] == "hyperspace"
            assert ev["ts"] >= 0 and ev["dur"] >= 0
            assert isinstance(ev["pid"], int)
            assert ev["name"] in sn.SPAN_NAMES
            ids.add(ev["args"]["span_id"])
        for ev in events:
            parent = ev["args"].get("parent_id")
            assert parent is None or parent in ids
        assert doc["otherData"]["trace_id"] == tr.trace_id


# ---------------------------------------------------------------------------
# Serving frontend: propagation, leakage, the shared sweep span.
# ---------------------------------------------------------------------------

class TestServingPropagation:
    def _frontend(self, session, concurrency, batching: bool):
        from hyperspace_tpu.serving.frontend import ServingFrontend
        session.conf.set(
            ServingConstants.SERVING_MAX_CONCURRENCY, str(concurrency))
        session.conf.set(ServingConstants.SERVING_BATCHING_ENABLED,
                         "true" if batching else "false")
        return ServingFrontend(session)

    def test_8_thread_hammer_no_cross_query_leakage(self, q3ish):
        session, li_dir, od_dir = q3ish
        _tracing(session, True)
        fe = self._frontend(session, 8, batching=False)
        for _round in range(3):
            queries = [_build_q3(session, li_dir, od_dir,
                                 ship_cut=100 + 40 * i)
                       for i in range(8)]
            pend = [fe.submit(q) for q in queries]
            for p in pend:
                p.result(timeout=300)
            traces = [p.context.trace for p in pend]
            assert all(t is not None for t in traces)
            assert len({t.trace_id for t in traces}) == 8
            shapes = []
            for t in traces:
                # One root per query, every span stamped with ITS
                # trace's id — a leaked span would land in another
                # trace's list with a foreign structure.
                roots = [s for s in t.spans if s.parent_id is None]
                assert [r.name for r in roots] == [sn.QUERY]
                assert all(s.trace_id == t.trace_id for s in t.spans)
                shapes.append(frozenset(s.name for s in t.spans))
            # Same query structure -> same span vocabulary, all 8 ways.
            assert len(set(shapes)) == 1

    def test_literal_sweep_shares_one_trace(self, q3ish):
        session, li_dir, od_dir = q3ish
        _tracing(session, True)
        session.conf.set(ServingConstants.SERVING_BATCHING_WINDOW, "0.4")
        fe = self._frontend(session, 1, batching=True)
        variants = [_build_q3(session, li_dir, od_dir,
                              ship_cut=200 + 10 * i) for i in range(4)]
        serial = [v.to_pandas() for v in variants]
        pend = [fe.submit(v) for v in variants]
        frames = [p.result(timeout=300).to_pandas() for p in pend]
        for a, b in zip(serial, frames):
            assert a.round(6).equals(b.round(6))
        batched = [p for p in pend if p.batched]
        if len(batched) >= 2:  # the window raced shut on slow machines
            traces = {id(p.context.trace): p.context.trace
                      for p in batched}
            assert len(traces) == 1  # ONE shared trace for the sweep
            tr = next(iter(traces.values()))
            sweeps = tr.find(sn.SERVING_SWEEP)
            assert len(sweeps) == 1
            members = tr.find(sn.QUERY)
            assert len(members) == len(batched)
            assert all(m.parent_id == sweeps[0].span_id
                       for m in members)
            assert sweeps[0].attrs["members"] == len(batched)

    def test_live_latency_histogram_feeds_metrics(self, q3ish):
        session, li_dir, od_dir = q3ish
        hs = Hyperspace(session)
        fe = self._frontend(session, 2, batching=False)
        before = Hyperspace(session).metrics()["histograms"].get(
            "serving.latency_ms", {}).get("total_count", 0)
        pend = [fe.submit(_build_q3(session, li_dir, od_dir,
                                    ship_cut=300 + i)) for i in range(5)]
        for p in pend:
            p.result(timeout=300)
        hist = hs.metrics()["histograms"]["serving.latency_ms"]
        assert hist["total_count"] >= before + 5
        assert hist["count"] >= 5
        assert 0 <= hist["p50"] <= hist["p99"]
        assert hist["qps"] > 0
        assert hist["window_s"] == \
            session.hs_conf.telemetry_serving_latency_window()


# ---------------------------------------------------------------------------
# The frozen span-name registry.
# ---------------------------------------------------------------------------

class TestSpanRegistry:
    def test_registry_is_the_expected_frozen_vocabulary(self):
        # Referencing every value here is also what satisfies the
        # scripts/lint.py span-coverage gate — like this list, the
        # registry only changes deliberately.
        assert sn.SPAN_NAMES == frozenset({
            "query", "plan.normalize", "optimize.join_reorder",
            "rewrite.index_rules", "serving.cache_lookup",
            "bank.lookup", "bank.compile", "exec.stage", "exec.fused",
            "io.read", "io.prefetch", "spmd.dispatch", "spmd.compile",
            "serving.sweep", "ingest.append", "ingest.commit",
            "ingest.compact", "artifact.load", "artifact.export",
            "artifact.warmup", "cluster.forward", "cluster.broadcast",
            "cluster.gather", "ingest.source", "ingest.wave",
        })

    def test_join_reorder_span_appears_when_enabled(self, q3ish):
        from hyperspace_tpu.optimizer.constants import OptimizerConstants
        session, li_dir, od_dir = q3ish
        session.conf.set(OptimizerConstants.JOIN_REORDER_ENABLED, "true")
        _tracing(session, True)
        _build_q3(session, li_dir, od_dir).to_arrow()
        tr = Hyperspace(session).last_trace()
        assert tr.find(sn.JOIN_REORDER)


# ---------------------------------------------------------------------------
# Metrics registry: one surface over every subsystem.
# ---------------------------------------------------------------------------

class TestMetricsRegistry:
    def test_metrics_covers_the_five_stats_surfaces(self, q3ish):
        session, li_dir, od_dir = q3ish
        session.conf.set(ServingConstants.RESULT_CACHE_ENABLED, "true")
        hs = Hyperspace(session)
        _build_q3(session, li_dir, od_dir).to_arrow()
        m = hs.metrics()
        assert {"counters", "gauges", "histograms",
                "collectors"} <= set(m)
        cols = m["collectors"]
        # Every counter previously reachable via the five stats APIs.
        assert cols["io"] == hs.io_stats()
        for key in ("pooled_reads", "read_tasks", "read_bytes",
                    "read_seconds", "wait_seconds", "pool_threads"):
            assert key in cols["io"]
        bank = cols["program_bank"]
        for key in ("stages", "programs", "hits", "misses", "evictions"):
            assert key in bank
        # Naming unification complete: canonical `evictions` only — the
        # deprecated pre-r13 `stage_evictions` alias was removed.
        assert "stage_evictions" not in bank
        rc = cols["result_cache"]
        assert set(rc["result_cache"]) >= {"hits", "misses", "evictions"}
        assert "sql_plan_cache" in rc
        spmd = cols["spmd"]
        for key in ("enabled", "mesh_devices", "query_dispatches",
                    "mesh_programs_compiled"):
            assert key in spmd
        assert "serving" in cols

    def test_histogram_window_slides(self):
        from hyperspace_tpu.telemetry.metrics import SlidingHistogram
        h = SlidingHistogram(window_s=10.0)
        h.record(5.0, now=100.0)
        h.record(7.0, now=101.0)
        h.record(15.0, now=104.0)
        snap = h.snapshot(now=105.0)
        # Upper-index percentile convention (matches bench's _pct).
        assert snap["count"] == 3 and snap["p50"] == 7.0
        assert snap["max"] == 15.0
        snap = h.snapshot(now=112.0)  # the two oldest aged out
        assert snap["count"] == 1 and snap["p50"] == 15.0
        assert snap["total_count"] == 3

    def test_histogram_truncation_keeps_qps_honest(self):
        """Past max_samples the oldest in-window samples drop; the
        snapshot must flag it and rate over the RETAINED span instead of
        silently under-reporting QPS (the high-load regime the live
        histogram exists for)."""
        from hyperspace_tpu.telemetry.metrics import SlidingHistogram
        h = SlidingHistogram(window_s=60.0, max_samples=16)
        for i in range(64):  # 64 samples over 6.3s, all in-window
            h.record(float(i), now=100.0 + i * 0.1)
        snap = h.snapshot(now=106.4)
        assert snap["truncated"] is True
        assert snap["count"] == 16
        # Rate over the ~1.5s the retained samples span, NOT count/60.
        assert snap["qps"] > 5.0
        assert snap["p50"] >= 48.0  # percentiles over the newest samples

    def test_histogram_window_owned_not_thrashed(self):
        """Recording-side histogram() asks (window_s=None) never
        re-window a live instrument; only an explicit owner ask does."""
        from hyperspace_tpu.telemetry.metrics import MetricsRegistry
        reg = MetricsRegistry()
        h = reg.histogram("lat", 30.0)
        assert reg.histogram("lat") is h          # recording-side ask
        assert h.window_s == 30.0                 # ... left it alone
        reg.histogram("lat", 10.0)                # owner re-window
        assert h.window_s == 10.0

    def test_tracing_toggle_keeps_result_cache_warm(self, q3ish):
        """telemetry.* keys are excluded from the result-cache config
        hash (like serving.*): flipping tracing on must serve the warm
        entry, not orphan it."""
        session, li_dir, od_dir = q3ish
        session.conf.set(ServingConstants.RESULT_CACHE_ENABLED, "true")
        session.conf.set(
            ServingConstants.RESULT_CACHE_MIN_COMPUTE_SECONDS, "0")
        q = _build_q3(session, li_dir, od_dir)
        q.to_arrow()  # miss + admit, untraced
        _tracing(session, True)
        q.to_arrow()  # must HIT the entry admitted before the toggle
        hs = Hyperspace(session)
        tr = hs.last_trace()
        lookup = tr.find(sn.CACHE_LOOKUP)
        assert len(lookup) == 1 and lookup[0].attrs["hit"] is True

    def test_collector_failure_is_contained(self):
        from hyperspace_tpu.telemetry.metrics import MetricsRegistry

        reg = MetricsRegistry()

        def boom():
            raise RuntimeError("broken stats source")

        reg.register_collector("broken", boom)
        reg.counter_add("fine", 2)
        snap = reg.snapshot()
        assert snap["collectors"]["broken"] == {"error": "collector failed"}
        assert snap["counters"]["fine"] == 2


# ---------------------------------------------------------------------------
# Explain surfacing + profiler hook.
# ---------------------------------------------------------------------------

class TestSurfaces:
    def test_explain_renders_trace_timeline(self, q3ish):
        from hyperspace_tpu.plananalysis.explain import explain_string
        session, li_dir, od_dir = q3ish
        q = _build_q3(session, li_dir, od_dir)
        text = explain_string(session, q.plan)
        assert "Trace:" not in text  # no traced run yet -> untouched
        _tracing(session, True)
        q.to_arrow()
        text = explain_string(session, q.plan)
        assert "Trace:" in text
        section = text.split("Trace:")[-1]
        assert "query" in section
        assert "exec.stage" in section
        assert "self" in section  # self-times rendered

    def test_profiler_brackets_exactly_one_query(self, q3ish, tmp_path):
        session, li_dir, od_dir = q3ish
        from hyperspace_tpu.telemetry import trace as trace_mod
        prof_dir = str(tmp_path / "profile")
        trace_mod.reset_profiler()
        session.conf.set(TC.PROFILER_ENABLED, "true")
        session.conf.set(TC.PROFILER_DIR, prof_dir)
        q = _build_q3(session, li_dir, od_dir)
        try:
            q.to_arrow()
        finally:
            session.conf.set(TC.PROFILER_ENABLED, "false")
        assert os.path.isdir(prof_dir)  # a capture landed
        captured = set()
        for r, _d, files in os.walk(prof_dir):
            captured.update(files)
        before = set(captured)
        # Disarmed (one-shot consumed): a second run adds nothing.
        session.conf.set(TC.PROFILER_ENABLED, "true")
        try:
            q.to_arrow()
        finally:
            session.conf.set(TC.PROFILER_ENABLED, "false")
        after = set()
        for r, _d, files in os.walk(prof_dir):
            after.update(files)
        assert after == before
