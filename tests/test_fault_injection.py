"""Fault injection: every action type crashed mid-op must leave the index
crash-consistent.

The reference has no fault-injection framework (SURVEY §5) — its guarantees
are structural: a crashed action leaves only a transient log state, queries
use ACTIVE entries exclusively, `cancel()` rolls back to the last stable
state, and data under `v__=<n>` version dirs is immutable so no partial
write corrupts a served version (actions/Action.scala:34-103,
CancelAction.scala). These tests make those guarantees executable for
every mutating action by raising inside ``op()`` at the worst moment.
"""

import glob
import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.constants import IndexConstants, States
from hyperspace_tpu.index.log_manager import IndexLogManager
from hyperspace_tpu.plan.expr import col


class Boom(RuntimeError):
    pass


def _crash(*a, **k):
    raise Boom("injected mid-op crash")


@pytest.fixture()
def env(tmp_path):
    rng = np.random.default_rng(33)
    df = pd.DataFrame({
        "k": rng.integers(0, 100, 8_000).astype(np.int64),
        "v": rng.random(8_000),
    })
    d = tmp_path / "data"
    d.mkdir()
    pq.write_table(pa.Table.from_pandas(df), d / "p0.parquet")
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    session.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
    return dict(session=session, hs=Hyperspace(session), path=str(d),
                df=df, sys=str(tmp_path / "indexes"), data_dir=d)


def _log_dir(env, name):
    return os.path.join(env["sys"], name, IndexConstants.HYPERSPACE_LOG)


def _latest_state(env, name):
    mgr = IndexLogManager(os.path.join(env["sys"], name))
    entry = mgr.get_latest_log()
    return entry.state if entry else None


def _append_file(env, tag="extra"):
    rng = np.random.default_rng(7)
    t = pa.table({"k": pa.array(rng.integers(0, 100, 500).astype(np.int64)),
                  "v": pa.array(rng.random(500))})
    pq.write_table(t, env["data_dir"] / f"{tag}.parquet")


class TestCreateCrash:
    def test_crash_leaves_transient_and_invisible(self, env, monkeypatch):
        session, hs = env["session"], env["hs"]
        t = session.read.parquet(env["path"])
        from hyperspace_tpu.actions import create as create_mod
        monkeypatch.setattr(create_mod.CreateAction, "op", _crash)
        with pytest.raises(Boom):
            hs.create_index(t, IndexConfig("cx", ["k"], ["v"]))
        assert _latest_state(env, "cx") == States.CREATING
        # The wedged index is invisible to the rewrite and to ACTIVE listing.
        session.enable_hyperspace()
        q = t.filter(col("k") == 3)
        assert "IndexScan" not in q.optimized_plan().tree_string()
        assert q.to_pandas() is not None  # query still executes
        listed = hs.indexes()
        assert "cx" not in set(listed["name"]) or \
            listed[listed["name"] == "cx"]["state"].iloc[0] != States.ACTIVE

    def test_cancel_then_recreate_succeeds(self, env, monkeypatch):
        session, hs = env["session"], env["hs"]
        t = session.read.parquet(env["path"])
        from hyperspace_tpu.actions import create as create_mod
        monkeypatch.setattr(create_mod.CreateAction, "op", _crash)
        with pytest.raises(Boom):
            hs.create_index(t, IndexConfig("cy", ["k"], ["v"]))
        monkeypatch.undo()
        hs.cancel("cy")
        hs.create_index(t, IndexConfig("cy", ["k"], ["v"]))
        assert _latest_state(env, "cy") == States.ACTIVE
        session.enable_hyperspace()
        q = t.filter(col("k") == 3).select("k", "v")
        assert "IndexScan" in q.optimized_plan().tree_string()


class TestRefreshCrash:
    @pytest.mark.parametrize("mode", ["full", "incremental", "quick"])
    def test_crash_preserves_served_version(self, env, monkeypatch, mode):
        """A refresh crashing mid-op must not disturb the ACTIVE version:
        queries keep using the old index data and answers stay correct."""
        session, hs, df = env["session"], env["hs"], env["df"]
        t = session.read.parquet(env["path"])
        hs.create_index(t, IndexConfig("rx", ["k"], ["v"]))
        v_dirs_before = sorted(glob.glob(
            os.path.join(env["sys"], "rx", "v__=*")))
        _append_file(env)

        from hyperspace_tpu.actions import refresh as refresh_mod
        cls = {"full": refresh_mod.RefreshAction,
               "incremental": refresh_mod.RefreshIncrementalAction,
               "quick": refresh_mod.RefreshQuickAction}[mode]
        monkeypatch.setattr(cls, "op", _crash)
        with pytest.raises(Boom):
            hs.refresh_index("rx", mode)
        monkeypatch.undo()
        assert _latest_state(env, "rx") == States.REFRESHING
        # Served data untouched: the pre-crash version dirs are intact.
        for vd in v_dirs_before:
            assert os.path.isdir(vd)
        # Recovery: cancel → ACTIVE again → refresh completes.
        hs.cancel("rx")
        assert _latest_state(env, "rx") == States.ACTIVE
        hs.refresh_index("rx", mode)
        assert _latest_state(env, "rx") == States.ACTIVE

    def test_post_recovery_answers_match(self, env, monkeypatch):
        session, hs, df = env["session"], env["hs"], env["df"]
        t = session.read.parquet(env["path"])
        hs.create_index(t, IndexConfig("rz", ["k"], ["v"]))
        _append_file(env, "late")
        from hyperspace_tpu.actions import refresh as refresh_mod
        monkeypatch.setattr(refresh_mod.RefreshIncrementalAction, "op", _crash)
        with pytest.raises(Boom):
            hs.refresh_index("rz", "incremental")
        monkeypatch.undo()
        hs.cancel("rz")
        hs.refresh_index("rz", "incremental")
        # Disable-and-compare on the refreshed data (re-read the dir so the
        # relation sees the appended file).
        t2 = session.read.parquet(env["path"])
        q = t2.filter(col("k") == 11).select("k", "v")
        session.enable_hyperspace()
        a = q.to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
        session.disable_hyperspace()
        b = q.to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
        pd.testing.assert_frame_equal(a, b)


class TestOptimizeAndLifecycleCrash:
    def test_optimize_crash_recovers(self, env, monkeypatch):
        session, hs = env["session"], env["hs"]
        t = session.read.parquet(env["path"])
        hs.create_index(t, IndexConfig("ox", ["k"], ["v"]))
        _append_file(env)
        hs.refresh_index("ox", "incremental")
        from hyperspace_tpu.actions import optimize as optimize_mod
        monkeypatch.setattr(optimize_mod.OptimizeAction, "op", _crash)
        with pytest.raises(Boom):
            hs.optimize_index("ox", "full")
        monkeypatch.undo()
        assert _latest_state(env, "ox") == States.OPTIMIZING
        hs.cancel("ox")
        hs.optimize_index("ox", "full")
        assert _latest_state(env, "ox") == States.ACTIVE

    def test_vacuum_crash_leaves_deleted_state(self, env, monkeypatch):
        session, hs = env["session"], env["hs"]
        t = session.read.parquet(env["path"])
        hs.create_index(t, IndexConfig("vx", ["k"], ["v"]))
        hs.delete_index("vx")
        from hyperspace_tpu.actions import lifecycle as lc
        monkeypatch.setattr(lc.VacuumAction, "op", _crash)
        with pytest.raises(Boom):
            hs.vacuum_index("vx")
        monkeypatch.undo()
        assert _latest_state(env, "vx") == States.VACUUMING
        hs.cancel("vx")
        assert _latest_state(env, "vx") == States.DELETED
        hs.restore_index("vx")
        assert _latest_state(env, "vx") == States.ACTIVE


class TestConcurrentActionConflict:
    def test_second_writer_fails_loud_and_harmless(self, env, monkeypatch):
        """While one action holds the transient state, a second action on
        the same index hits the op-log optimistic-concurrency check and
        fails without touching anything (Action.scala:80 semantics)."""
        session, hs = env["session"], env["hs"]
        t = session.read.parquet(env["path"])
        from hyperspace_tpu.actions import create as create_mod
        monkeypatch.setattr(create_mod.CreateAction, "op", _crash)
        with pytest.raises(Boom):
            hs.create_index(t, IndexConfig("cc", ["k"], ["v"]))
        monkeypatch.undo()
        # The wedged CREATING state blocks a rival create until cancel.
        with pytest.raises(HyperspaceException):
            hs.create_index(t, IndexConfig("cc", ["k"], ["v"]))
        hs.cancel("cc")
        hs.create_index(t, IndexConfig("cc", ["k"], ["v"]))
        assert _latest_state(env, "cc") == States.ACTIVE
