"""COUNT(DISTINCT col): grouped + global, null exclusion, strings/dates,
and the paths that must refuse it (two-phase run combination, SPMD).

The reference gets countDistinct from Spark SQL; this engine implements it
as sort-by-(group, value) + first-occurrence flags + segment sum
(executor._count_distinct).
"""

import datetime

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.execution import executor
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col, count, count_distinct, sum_


@pytest.fixture()
def env(tmp_path):
    rng = np.random.default_rng(33)
    n = 2000
    d = tmp_path / "data"
    d.mkdir()
    vals = rng.integers(0, 25, n).astype(np.int64)
    null_mask = rng.random(n) < 0.1
    pq.write_table(pa.table({
        "g": pa.array(rng.integers(0, 8, n).astype(np.int64)),
        "v": pa.array(np.where(null_mask, 0, vals), type=pa.int64(),
                      mask=null_mask),
        "s": pa.array(rng.choice(["x", "y", "z", "w"], n)),
        "dt": pa.array(rng.integers(8000, 8020, n).astype(np.int32),
                       type=pa.int32()).cast(pa.date32()),
    }), d / "p0.parquet")
    session = hst.Session(system_path=str(tmp_path / "idx"))
    session.conf.set(
        IndexConstants.TPU_DISTRIBUTED_MIN_STREAM_ROWS, "0")
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    return session, str(d)


def oracle(pdf, group, valcol):
    return (pdf.groupby(group)[valcol].nunique()
            .rename("nd").reset_index().sort_values(group)
            .reset_index(drop=True))


class TestCountDistinct:
    def test_grouped_int_with_nulls(self, env):
        session, d = env
        df = session.read.parquet(d)
        got = (df.group_by("g").agg(count_distinct(col("v")).alias("nd"))
               .sort("g").to_pandas())
        # pandas nunique skips NaN — same SQL semantics.
        expect = oracle(df.to_pandas(), "g", "v")
        pd.testing.assert_frame_equal(got, expect, check_dtype=False)

    def test_grouped_string(self, env):
        session, d = env
        df = session.read.parquet(d)
        got = (df.group_by("g").agg(count_distinct(col("s")).alias("nd"))
               .sort("g").to_pandas())
        expect = oracle(df.to_pandas(), "g", "s")
        pd.testing.assert_frame_equal(got, expect, check_dtype=False)

    def test_grouped_date(self, env):
        session, d = env
        df = session.read.parquet(d)
        got = (df.group_by("g").agg(count_distinct(col("dt")).alias("nd"))
               .sort("g").to_pandas())
        expect = oracle(df.to_pandas(), "g", "dt")
        pd.testing.assert_frame_equal(got, expect, check_dtype=False)

    def test_global_count_distinct(self, env):
        session, d = env
        df = session.read.parquet(d)
        t = df.agg(count_distinct(col("v")).alias("nd"),
                   count_distinct(col("s")).alias("ns")).to_arrow()
        pdf = df.to_pandas()
        assert t.column("nd").to_pylist() == [pdf["v"].nunique()]
        assert t.column("ns").to_pylist() == [pdf["s"].nunique()]

    def test_mixed_with_other_aggs(self, env):
        session, d = env
        df = session.read.parquet(d)
        got = (df.group_by("g")
               .agg(count_distinct(col("v")).alias("nd"),
                    count(col("v")).alias("c"),
                    sum_(col("g")).alias("sg"))
               .sort("g").to_pandas())
        pdf = df.to_pandas()
        base = pdf.groupby("g").agg(
            nd=("v", "nunique"), c=("v", "count"),
            sg=("g", "sum")).reset_index().sort_values("g") \
            .reset_index(drop=True)
        pd.testing.assert_frame_equal(got, base, check_dtype=False)

    def test_all_null_group_counts_zero(self, env, tmp_path):
        session, _ = env
        d2 = tmp_path / "nulls"
        d2.mkdir()
        pq.write_table(pa.table({
            "g": pa.array([1, 1, 2], type=pa.int64()),
            "v": pa.array([None, None, 5], type=pa.int64()),
        }), d2 / "p0.parquet")
        df = session.read.parquet(str(d2))
        t = (df.group_by("g").agg(count_distinct(col("v")).alias("nd"))
             .sort("g").to_arrow())
        assert t.column("nd").to_pylist() == [0, 1]

    def test_count_distinct_requires_child(self):
        with pytest.raises(ValueError, match="requires a column"):
            from hyperspace_tpu.plan.expr import CountDistinct
            CountDistinct(None)


class TestPathSelection:
    def test_two_phase_path_excluded(self, env, tmp_path):
        """Grouping a bucket-ordered table by a superset of its bucket keys
        normally takes the two-phase run path; CountDistinct must force
        the full-sort path (run partials cannot combine) and still agree
        with the oracle."""
        session, d = env
        hs = Hyperspace(session)
        df = session.read.parquet(d)
        hs.create_index(df, IndexConfig("gIdx", ["g"], ["v", "s"]))
        session.enable_hyperspace()
        before = executor.GROUPBY_TWO_PHASE
        q = (df.filter(col("g") >= 0)
             .group_by("g", "s")
             .agg(count_distinct(col("v")).alias("nd")))
        got = q.to_pandas().sort_values(["g", "s"]).reset_index(drop=True)
        assert executor.GROUPBY_TWO_PHASE == before  # path refused
        pdf = df.to_pandas()
        expect = (pdf.groupby(["g", "s"])["v"].nunique().rename("nd")
                  .reset_index().sort_values(["g", "s"])
                  .reset_index(drop=True))
        pd.testing.assert_frame_equal(got, expect, check_dtype=False)

    def test_spmd_falls_back(self, env):
        """Distinct counts are not decomposable — SPMD must NOT dispatch
        (CountDistinct is deliberately not a Count subclass)."""
        from hyperspace_tpu.execution import spmd
        session, d = env
        df = session.read.parquet(d)
        before = spmd.DISPATCH_COUNT
        got = (df.group_by("g").agg(count_distinct(col("v")).alias("nd"))
               .sort("g").to_pandas())
        assert spmd.DISPATCH_COUNT == before
        expect = oracle(df.to_pandas(), "g", "v")
        pd.testing.assert_frame_equal(got, expect, check_dtype=False)


class TestFloatAndNaN:
    def test_nan_counts_as_one_distinct(self, env, tmp_path):
        """0/0 through Divide yields NaN (validity stays true); Spark
        counts NaN as ONE distinct value per group."""
        session, _ = env
        d2 = tmp_path / "floats"
        d2.mkdir()
        pq.write_table(pa.table({
            "g": pa.array([1, 1, 1, 2, 2], type=pa.int64()),
            "num": pa.array([0.0, 0.0, 2.0, 0.0, 3.0], type=pa.float64()),
            "den": pa.array([0.0, 0.0, 1.0, 0.0, 1.0], type=pa.float64()),
        }), d2 / "p0.parquet")
        df = session.read.parquet(str(d2))
        t = (df.with_column("q", col("num") / col("den"))
             .group_by("g").agg(count_distinct(col("q")).alias("nd"))
             .sort("g").to_arrow())
        # g=1: {NaN, NaN, 2.0} -> 2;  g=2: {NaN, 3.0} -> 2.
        assert t.column("nd").to_pylist() == [2, 2]

    def test_public_helper_rejects_none(self):
        with pytest.raises(ValueError, match="column expression"):
            count_distinct(None)
