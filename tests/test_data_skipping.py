"""Data-skipping index tests: sketch build, scan pruning, refresh lifecycle.

The disable-and-compare oracle applies throughout: pruned-scan results must
equal full-scan results (sketches may only remove files that cannot match).
"""

import datetime
import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import (BloomFilterSketch, DataSkippingIndexConfig,
                                Hyperspace, IndexConfig, MinMaxSketch)
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.constants import IndexConstants, States
from hyperspace_tpu.ops import sketches as sk
from hyperspace_tpu.plan.expr import col
from hyperspace_tpu.plan.nodes import Scan


def write_partitioned(root, name, df, key, parts):
    """Write one file per contiguous key range so min/max sketches have
    non-overlapping ranges to prune on."""
    d = root / name
    d.mkdir(parents=True, exist_ok=True)
    df = df.sort_values(key).reset_index(drop=True)
    step = (len(df) + parts - 1) // parts
    for i in range(parts):
        chunk = df.iloc[i * step:(i + 1) * step]
        if len(chunk):
            pq.write_table(pa.Table.from_pandas(chunk.reset_index(drop=True)),
                           d / f"part{i}.parquet")
    return str(d)


@pytest.fixture()
def env(tmp_path):
    rng = np.random.default_rng(3)
    n = 2000
    df = pd.DataFrame({
        "k": np.arange(n, dtype=np.int64),
        "v": rng.integers(0, 1000, n).astype(np.int64),
        "d": [datetime.date(1995, 1, 1) + datetime.timedelta(days=int(x) % 300)
              for x in np.arange(n)],
        "s": [f"cat{int(x) % 7}" for x in np.arange(n)],
    })
    path = write_partitioned(tmp_path, "data", df, "k", 8)
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    return dict(session=session, hs=Hyperspace(session), path=path,
                df=df, tmp=tmp_path)


def scan_files(plan):
    (leaf,) = [l for l in plan.collect_leaves() if isinstance(l, Scan)]
    return leaf.relation.all_files()


def check_disable_and_compare(session, df):
    session.enable_hyperspace()
    with_idx = df.to_pandas()
    session.disable_hyperspace()
    without = df.to_pandas()
    session.enable_hyperspace()
    a = with_idx.sort_values(list(with_idx.columns)).reset_index(drop=True)
    b = without.sort_values(list(without.columns)).reset_index(drop=True)
    pd.testing.assert_frame_equal(a, b, check_dtype=False)
    return with_idx


class TestSketchPrimitives:
    def test_bloom_roundtrip_int(self):
        import jax.numpy as jnp
        from hyperspace_tpu.execution.columnar import Column
        from hyperspace_tpu.schema import INT64
        values = np.array([3, 17, 99, 12345, -8], dtype=np.int64)
        c = Column(INT64, jnp.asarray(values))
        m, k = sk.bloom_parameters(64, 0.01)
        bits = sk.bloom_build(c, m, k)
        for v in values:
            assert sk.bloom_might_contain(bits, int(v), INT64, m, k)
        misses = sum(sk.bloom_might_contain(bits, int(v), INT64, m, k)
                     for v in range(1000, 1200))
        assert misses <= 10  # fpp well under control.

    def test_bloom_roundtrip_string(self):
        import jax.numpy as jnp
        from hyperspace_tpu.execution.columnar import Column
        from hyperspace_tpu.schema import STRING
        words = np.array(["alpha", "beta", "gamma"])
        c = Column(STRING, jnp.asarray(np.array([0, 1, 2], np.int32)),
                   None, words)
        m, k = sk.bloom_parameters(16, 0.01)
        bits = sk.bloom_build(c, m, k)
        for w in words:
            assert sk.bloom_might_contain(bits, w, STRING, m, k)
        assert not sk.bloom_might_contain(bits, "delta", STRING, m, k)

    def test_minmax_with_nulls(self):
        import jax.numpy as jnp
        from hyperspace_tpu.execution.columnar import Column
        from hyperspace_tpu.schema import INT64
        c = Column(INT64, jnp.asarray(np.array([5, 1, 9], np.int64)),
                   jnp.asarray(np.array([True, False, True])))
        assert sk.minmax_values(c) == (5, 9)
        c_all_null = Column(INT64, jnp.asarray(np.array([5], np.int64)),
                            jnp.asarray(np.array([False])))
        assert sk.minmax_values(c_all_null) == (None, None)


class TestDataSkippingE2E:
    def test_minmax_prunes_range_scan(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, DataSkippingIndexConfig(
            "dsMinMax", [MinMaxSketch("k")]))
        entry = hs.index_manager.get_index("dsMinMax")
        assert entry.state == States.ACTIVE
        assert entry.derivedDataset.kind == "DataSkippingIndex"

        session.enable_hyperspace()
        q = df.filter(col("k") < 250).select("k", "v")
        plan = q.optimized_plan()
        kept = scan_files(plan)
        assert len(kept) == 1  # 8 range-partitioned files, k<250 hits one.
        out = check_disable_and_compare(session, q)
        assert len(out) == 250

    def test_bloom_prunes_equality(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, DataSkippingIndexConfig(
            "dsBloom", [BloomFilterSketch("k", fpp=0.001, expected_items=300)]))
        session.enable_hyperspace()
        q = df.filter(col("k") == 777).select("k", "v")
        kept = scan_files(q.optimized_plan())
        assert len(kept) < 8
        out = check_disable_and_compare(session, q)
        assert len(out) == 1

    def test_string_equality_prunes(self, env, tmp_path):
        session, hs = env["session"], env["hs"]
        # Files partitioned by string category.
        df = env["df"]
        d = tmp_path / "bycat"
        d.mkdir()
        for i, (cat, chunk) in enumerate(df.groupby("s")):
            pq.write_table(pa.Table.from_pandas(chunk.reset_index(drop=True)),
                           d / f"part{i}.parquet")
        data = session.read.parquet(str(d))
        hs.create_index(data, DataSkippingIndexConfig(
            "dsStr", [MinMaxSketch("s"), BloomFilterSketch("s")]))
        session.enable_hyperspace()
        q = data.filter(col("s") == "cat3").select("k", "s")
        kept = scan_files(q.optimized_plan())
        assert len(kept) == 1
        out = check_disable_and_compare(session, q)
        assert len(out) == (df.s == "cat3").sum()

    def test_date_range_prunes(self, env, tmp_path):
        session, hs = env["session"], env["hs"]
        df = env["df"]
        d = write_partitioned(tmp_path, "bydate", df, "d", 6)
        data = session.read.parquet(d)
        hs.create_index(data, DataSkippingIndexConfig(
            "dsDate", [MinMaxSketch("d")]))
        session.enable_hyperspace()
        cutoff = datetime.date(1995, 2, 1)
        q = data.filter(col("d") < cutoff).select("k", "d")
        kept = scan_files(q.optimized_plan())
        assert 0 < len(kept) < 6
        out = check_disable_and_compare(session, q)
        assert len(out) == (df.d < cutoff).sum()

    def test_disjunction_prunes_union(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, DataSkippingIndexConfig(
            "dsOr", [MinMaxSketch("k")]))
        session.enable_hyperspace()
        q = df.filter((col("k") < 100) | (col("k") > 1900)).select("k")
        kept = scan_files(q.optimized_plan())
        assert len(kept) == 2
        out = check_disable_and_compare(session, q)
        assert len(out) == 100 + 99

    def test_in_list_prunes(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, DataSkippingIndexConfig(
            "dsIn", [MinMaxSketch("k")]))
        session.enable_hyperspace()
        q = df.filter(col("k").isin([5, 6, 1999])).select("k", "v")
        kept = scan_files(q.optimized_plan())
        assert len(kept) == 2
        out = check_disable_and_compare(session, q)
        assert len(out) == 3

    def test_unprunable_predicate_keeps_scan(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, DataSkippingIndexConfig(
            "dsNo", [MinMaxSketch("k")]))
        session.enable_hyperspace()
        # Predicate on a non-sketched column: plan unchanged (8 files).
        q = df.filter(col("v") < 100).select("k", "v")
        assert len(scan_files(q.optimized_plan())) == 8

    def test_prune_to_empty(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, DataSkippingIndexConfig(
            "dsEmpty", [MinMaxSketch("k")]))
        session.enable_hyperspace()
        q = df.filter(col("k") > 10_000).select("k", "v")
        kept = scan_files(q.optimized_plan())
        assert kept == []
        out = q.to_pandas()
        assert len(out) == 0

    def test_covering_index_wins_over_skipping(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, DataSkippingIndexConfig(
            "dsBoth", [MinMaxSketch("k")]))
        hs.create_index(df, IndexConfig("ciBoth", ["k"], ["v"]))
        session.enable_hyperspace()
        q = df.filter(col("k") < 250).select("k", "v")
        from hyperspace_tpu.plan.nodes import IndexScan
        leaves = q.optimized_plan().collect_leaves()
        assert any(isinstance(l, IndexScan) and l.index_entry.name == "ciBoth"
                   for l in leaves)

    def test_stale_signature_not_applied(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, DataSkippingIndexConfig(
            "dsStale", [MinMaxSketch("k")]))
        extra = env["df"].iloc[:5].copy()
        extra["k"] += 50_000
        pq.write_table(pa.Table.from_pandas(extra.reset_index(drop=True)),
                       env["tmp"] / "data" / "late.parquet")
        session.enable_hyperspace()
        fresh = session.read.parquet(env["path"])
        q = fresh.filter(col("k") < 250).select("k", "v")
        assert len(scan_files(q.optimized_plan())) == 9  # unpruned.
        check_disable_and_compare(session, q)


class TestDataSkippingRefresh:
    def test_full_refresh(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, DataSkippingIndexConfig(
            "dsRef", [MinMaxSketch("k")]))
        extra = env["df"].iloc[:50].copy()
        extra["k"] += 50_000
        pq.write_table(pa.Table.from_pandas(extra.reset_index(drop=True)),
                       env["tmp"] / "data" / "x.parquet")
        hs.refresh_index("dsRef", "full")
        entry = hs.index_manager.get_index("dsRef")
        assert entry.log_version == 1

        session.enable_hyperspace()
        fresh = session.read.parquet(env["path"])
        q = fresh.filter(col("k") > 49_000).select("k")
        kept = scan_files(q.optimized_plan())
        assert len(kept) == 1
        out = check_disable_and_compare(session, q)
        assert len(out) == 50

    def test_incremental_refresh_with_delete(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, DataSkippingIndexConfig(
            "dsInc", [MinMaxSketch("k"), BloomFilterSketch("k")]))
        os.remove(os.path.join(env["path"], "part0.parquet"))
        extra = env["df"].iloc[:50].copy()
        extra["k"] += 50_000
        pq.write_table(pa.Table.from_pandas(extra.reset_index(drop=True)),
                       env["tmp"] / "data" / "x.parquet")
        hs.refresh_index("dsInc", "incremental")  # no lineage needed.

        session.enable_hyperspace()
        fresh = session.read.parquet(env["path"])
        q = fresh.filter(col("k") == 50_010).select("k", "v")
        kept = scan_files(q.optimized_plan())
        assert len(kept) == 1
        out = check_disable_and_compare(session, q)
        assert len(out) == 1

    def test_quick_refresh_unsupported(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, DataSkippingIndexConfig(
            "dsQ", [MinMaxSketch("k")]))
        pq.write_table(pa.Table.from_pandas(env["df"].iloc[:5]),
                       env["tmp"] / "data" / "y.parquet")
        with pytest.raises(HyperspaceException, match="not supported"):
            hs.refresh_index("dsQ", "quick")

    def test_lifecycle_delete_vacuum(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, DataSkippingIndexConfig(
            "dsLc", [MinMaxSketch("k")]))
        hs.delete_index("dsLc")
        assert hs.index_manager.get_index("dsLc").state == States.DELETED
        session.enable_hyperspace()
        q = df.filter(col("k") < 250).select("k")
        assert len(scan_files(q.optimized_plan())) == 8  # not applied.
        hs.vacuum_index("dsLc")
        assert hs.index_manager.get_index("dsLc").state == States.DOESNOTEXIST

    def test_listing_includes_skipping_index(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, DataSkippingIndexConfig(
            "dsList", [MinMaxSketch("k"), BloomFilterSketch("v")]))
        listing = hs.indexes()
        assert "dsList" in list(listing["name"])


class TestValueListSketch:
    """Exact distinct-values sketch: equality/IN pruning with no false
    positives; over-cardinality files store no list and are always kept."""

    def _build(self, tmp_path, session, regions_per_file):
        """One file per region set; a 'cat' column holds those regions."""
        d = tmp_path / "vl"
        d.mkdir()
        rng = np.random.default_rng(4)
        for i, regions in enumerate(regions_per_file):
            n = 500
            t = pa.table({
                "cat": pa.array(rng.choice(regions, n)),
                "v": pa.array(rng.integers(0, 100, n).astype(np.int64)),
            })
            pq.write_table(t, d / f"part{i}.parquet")
        return str(d)

    def test_equality_prunes_exactly(self, tmp_path):
        from hyperspace_tpu.api import ValueListSketch
        session = hst.Session(system_path=str(tmp_path / "idx"))
        hs = Hyperspace(session)
        path = self._build(tmp_path, session,
                           [["ca", "wa"], ["ny", "nj"], ["tx"], ["ca", "tx"]])
        t = session.read.parquet(path)
        hs.create_index(t, DataSkippingIndexConfig(
            "vl_idx", [ValueListSketch("cat")]))
        session.enable_hyperspace()
        q = t.filter(col("cat") == "tx")
        leaves = q.optimized_plan().collect_leaves()
        kept = leaves[0].relation.all_files()
        assert len(kept) == 2  # files 2 and 3 only
        got = q.to_pandas()
        session.disable_hyperspace()
        raw = q.to_pandas()
        assert len(got) == len(raw)

    def test_in_list_unions_memberships(self, tmp_path):
        from hyperspace_tpu.api import ValueListSketch
        session = hst.Session(system_path=str(tmp_path / "idx"))
        hs = Hyperspace(session)
        path = self._build(tmp_path, session,
                           [["ca"], ["ny"], ["tx"], ["wa"]])
        t = session.read.parquet(path)
        hs.create_index(t, DataSkippingIndexConfig(
            "vl_in", [ValueListSketch("cat")]))
        session.enable_hyperspace()
        q = t.filter(col("cat").isin(["ca", "wa"]))
        kept = q.optimized_plan().collect_leaves()[0].relation.all_files()
        assert len(kept) == 2
        assert len(q.to_pandas()) == 1000  # both files fully match

    def test_over_cardinality_file_never_pruned(self, tmp_path):
        from hyperspace_tpu.api import ValueListSketch
        session = hst.Session(system_path=str(tmp_path / "idx"))
        hs = Hyperspace(session)
        d = tmp_path / "big"
        d.mkdir()
        rng = np.random.default_rng(6)
        # File 0: 3000 distinct ints (over max_values=64) → no list stored.
        pq.write_table(pa.table({
            "k": pa.array(np.arange(3000, dtype=np.int64)),
        }), d / "wide.parquet")
        # File 1: only {1, 2}.
        pq.write_table(pa.table({
            "k": pa.array(rng.choice([1, 2], 500).astype(np.int64)),
        }), d / "narrow.parquet")
        t = session.read.parquet(str(d))
        hs.create_index(t, DataSkippingIndexConfig(
            "vl_oc", [ValueListSketch("k", max_values=64)]))
        session.enable_hyperspace()
        # 7 is absent from BOTH files, but only narrow can prove it.
        q = t.filter(col("k") == 7)
        kept = q.optimized_plan().collect_leaves()[0].relation.all_files()
        assert len(kept) == 1 and kept[0].endswith("wide.parquet")
        got = q.to_pandas()
        session.disable_hyperspace()
        assert len(got) == len(q.to_pandas()) == 1

    def test_int_and_date_values(self, tmp_path):
        from hyperspace_tpu.api import ValueListSketch
        session = hst.Session(system_path=str(tmp_path / "idx"))
        hs = Hyperspace(session)
        d = tmp_path / "dates"
        d.mkdir()
        day = lambda i: datetime.date(2024, 1, 1) + datetime.timedelta(days=i)
        for i in range(3):
            pq.write_table(pa.table({
                "d": pa.array([day(i)] * 100, pa.date32()),
                "v": pa.array(np.arange(100, dtype=np.int64)),
            }), d / f"p{i}.parquet")
        t = session.read.parquet(str(d))
        hs.create_index(t, DataSkippingIndexConfig(
            "vl_d", [ValueListSketch("d")]))
        session.enable_hyperspace()
        q = t.filter(col("d") == day(1))
        kept = q.optimized_plan().collect_leaves()[0].relation.all_files()
        assert len(kept) == 1
        assert len(q.to_pandas()) == 100
