"""whyNot diagnostics + IndexStatistics surfaces.

Parity: the reference's whyNot APIs (`Hyperspace.whyNot`, FILTER_REASONS in
rules/IndexFilter.scala:41-52, reason tags in IndexLogEntryTags.scala:57-63)
and `hs.index(name)` / `hs.indexes()` statistics (IndexStatistics.scala) —
each reason code the rules emit must surface through the public API with an
actionable message.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import avg, col, sum_


@pytest.fixture()
def env(tmp_path):
    d = tmp_path / "data"
    d.mkdir()
    rng = np.random.default_rng(9)
    pq.write_table(pa.Table.from_pandas(pd.DataFrame({
        "k": rng.integers(0, 40, 400).astype(np.int64),
        "v": rng.integers(0, 9, 400).astype(np.int64),
        "w": rng.integers(0, 9, 400).astype(np.int64),
    })), d / "p0.parquet")
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    session.enable_hyperspace()
    return dict(session=session, hs=Hyperspace(session), path=str(d),
                dir=d)


class TestWhyNotReasons:
    def test_col_schema_mismatch(self, env, tmp_path):
        hs, session = env["hs"], env["session"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("kv", ["k"], ["v"]))
        # Query a DIFFERENT table that has none of kv's columns: the
        # candidate collector rejects kv on column schema.
        d2 = tmp_path / "other"
        d2.mkdir()
        pq.write_table(pa.table({
            "x": pa.array(np.arange(10, dtype=np.int64))}),
            d2 / "p0.parquet")
        other = session.read.parquet(str(d2))
        out = hs.why_not(other.filter(col("x") > 3).select("x"))
        assert "kv" in out and "COL_SCHEMA_MISMATCH" in out

    def test_no_first_indexed_col(self, env):
        hs, session = env["hs"], env["session"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("kv2", ["k"], ["v"]))
        # Filter on v only: kv2 covers the columns but its first indexed
        # column (k) is not constrained.
        out = hs.why_not(df.filter(col("v") > 3).select("k", "v"))
        assert "NO_FIRST_INDEXED_COL" in out

    def test_signature_mismatch_after_append(self, env):
        hs, session = env["hs"], env["session"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("kv3", ["k"], ["v"]))
        # Mutate the source (hybrid scan off → signature must mismatch).
        pq.write_table(pa.Table.from_pandas(pd.DataFrame({
            "k": np.array([100], dtype=np.int64),
            "v": np.array([1], dtype=np.int64),
            "w": np.array([1], dtype=np.int64),
        })), env["dir"] / "p1.parquet")
        fresh = session.read.parquet(env["path"])
        out = hs.why_not(fresh.filter(col("k") > 3).select("k", "v"))
        assert "SOURCE_DATA_CHANGED" in out

    def test_outscored_on_tie(self, env):
        hs, session = env["hs"], env["session"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("wide", ["k"], ["v", "w"]))
        hs.create_index(df, IndexConfig("slim", ["k"], ["v"]))
        out = hs.why_not(df.filter(col("k") > 3).select("k", "v"))
        # Specifically WIDE lost the tie (slim won), with the tie-break
        # wording, not a false "scored below" claim.
        assert "[wide] OUTSCORED" in out and "tie" in out
        assert "[slim] OUTSCORED" not in out

    def test_join_no_compatible_pair(self, env, tmp_path):
        hs, session = env["hs"], env["session"]
        d2 = tmp_path / "dim"
        d2.mkdir()
        pq.write_table(pa.table({
            "dk": pa.array(np.arange(40, dtype=np.int64)),
            "dv": pa.array(np.arange(40, dtype=np.int64))}),
            d2 / "p0.parquet")
        df = session.read.parquet(env["path"])
        dim = session.read.parquet(str(d2))
        # Both sides are indexed on the join columns but in OPPOSITE
        # order: usable individually, incompatible as a pair.
        hs.create_index(df, IndexConfig("fact_kv", ["k", "v"], ["w"]))
        hs.create_index(dim, IndexConfig("dim_vd", ["dv", "dk"], []))
        q = (df.join(dim, on=(col("k") == col("dk"))
                     & (col("v") == col("dv")))
             .select("k", "v", "dk", "dv", "w"))
        out = hs.why_not(q)
        assert "[fact_kv] NO_AVAIL_JOIN_INDEX_PAIR" in out
        assert "[dim_vd] NO_AVAIL_JOIN_INDEX_PAIR" in out

    def test_why_not_filtered_to_one_index(self, env):
        hs, session = env["hs"], env["session"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("aa", ["k"], ["v"]))
        hs.create_index(df, IndexConfig("bb", ["v"], ["w"]))
        out = hs.why_not(df.filter(col("w") > 3).select("w"),
                         index_name="bb")
        assert "bb" in out and "aa" not in out

    def test_applied_index_not_reported_as_rejected(self, env):
        hs, session = env["hs"], env["session"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("used", ["k"], ["v"]))
        q = df.filter(col("k") > 3).select("k", "v")
        # The query IS rewritten; why_not must not claim 'used' failed.
        assert "IndexScan" in q.optimized_plan().tree_string()
        out = hs.why_not(q)
        for bad in ("COL_SCHEMA_MISMATCH", "MISSING_REQUIRED_COL",
                    "NO_FIRST_INDEXED_COL_COND", "OUTSCORED"):
            assert f"[used] {bad}" not in out


class TestIndexStatistics:
    def test_summary_row_shape(self, env):
        hs, session = env["hs"], env["session"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("st1", ["k"], ["v"]))
        t = hs.indexes()  # pandas DataFrame (the reference returns a
        #                      Spark DataFrame from the same columns)
        assert len(t) == 1
        # The reference's summary columns (IndexStatistics.scala) plus
        # the session-local usageCount column (advisor dead-index
        # detector, rule_utils.log_index_usage tally).
        assert list(t.columns) == ["name", "indexedColumns",
                                   "includedColumns", "numBuckets",
                                   "schema", "indexLocation", "state",
                                   "usageCount"]
        row = t.iloc[0]
        assert row["name"] == "st1"
        assert row["indexedColumns"] == ["k"]
        assert row["includedColumns"] == ["v"]
        assert row["numBuckets"] == 4
        assert row["state"] == "ACTIVE"
        assert "v__=0" in row["indexLocation"]
        assert row["usageCount"] == 0  # no query has applied it yet

    def test_extended_stats_counts(self, env):
        hs, session = env["hs"], env["session"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("st2", ["k"], ["v"]))
        stat = hs.index("st2").iloc[0]
        assert stat["sourceFileCount"] == 1
        assert stat["indexFileCount"] == 4  # one parquet per bucket
        assert stat["indexSizeBytes"] > 0
        assert stat["sourceSizeBytes"] > 0
        assert stat["appendedFileCount"] == 0
        assert stat["deletedFileCount"] == 0

    def test_stats_track_lifecycle_state(self, env):
        hs, session = env["hs"], env["session"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("st3", ["k"], ["v"]))
        hs.delete_index("st3")
        # Listing defaults to non-deleted states only; the reference shows
        # DELETED indexes through the same API when asked.
        t = hs.indexes()
        st3 = t[t["name"] == "st3"]
        assert len(st3) == 0 or st3.iloc[0]["state"] == "DELETED"

    def test_refresh_bumps_version_location(self, env):
        hs, session = env["hs"], env["session"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("st4", ["k"], ["v"]))
        pq.write_table(pa.Table.from_pandas(pd.DataFrame({
            "k": np.array([7], dtype=np.int64),
            "v": np.array([1], dtype=np.int64),
            "w": np.array([2], dtype=np.int64),
        })), env["dir"] / "p1.parquet")
        hs.refresh_index("st4", "full")
        stat = hs.index("st4").iloc[0]
        assert "v__=1" in stat["indexLocation"]
        assert stat["sourceFileCount"] == 2
