"""User-facing error surfaces: every misuse of the DataFrame / reader /
writer / index API must fail fast with a HyperspaceException naming the
problem — not a deep engine traceback.

Parity: the reference asserts error messages across its suites
(IndexConfigTest, IndexManagerTest's duplicate/invalid cases,
E2EHyperspaceRulesTest's unsupported-plan cases); this file concentrates
the same contract for the TPU-native API.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.plan.expr import col, sum_


@pytest.fixture()
def env(tmp_path):
    d = tmp_path / "data"
    d.mkdir()
    rng = np.random.default_rng(1)
    pq.write_table(pa.Table.from_pandas(pd.DataFrame({
        "k": rng.integers(0, 20, 100).astype(np.int64),
        "v": rng.integers(0, 9, 100).astype(np.int64),
    })), d / "p0.parquet")
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    return session, str(d), tmp_path


class TestPlanConstructionErrors:
    def test_select_unknown_column_names_available(self, env):
        session, d, _ = env
        df = session.read.parquet(d)
        with pytest.raises(HyperspaceException, match="unknown column.*'z'"):
            df.select("k", "z")

    def test_filter_unknown_column(self, env):
        session, d, _ = env
        df = session.read.parquet(d)
        with pytest.raises(HyperspaceException, match="z"):
            df.filter(col("z") > 1)

    def test_duplicate_projection_names(self, env):
        session, d, _ = env
        df = session.read.parquet(d)
        with pytest.raises(HyperspaceException, match="Duplicate"):
            df.select(col("k"), col("v").alias("k"))

    def test_sort_unknown_column(self, env):
        session, d, _ = env
        df = session.read.parquet(d)
        with pytest.raises(HyperspaceException):
            df.sort("nope").to_arrow()

    def test_group_by_unknown_column(self, env):
        session, d, _ = env
        df = session.read.parquet(d)
        with pytest.raises(HyperspaceException):
            (df.group_by("nope").agg(sum_(col("v")).alias("s"))
             .to_arrow())

    def test_join_unknown_key(self, env):
        session, d, _ = env
        df = session.read.parquet(d)
        with pytest.raises(HyperspaceException):
            df.join(df.select(col("k").alias("k2"), col("v").alias("v2")),
                    on=col("missing") == col("k2")).to_arrow()

    def test_join_bad_how(self, env):
        session, d, _ = env
        df = session.read.parquet(d)
        other = df.select(col("k").alias("k2"))
        with pytest.raises(HyperspaceException, match="join type"):
            df.join(other, on=col("k") == col("k2"), how="sideways")

    def test_drop_everything_raises(self, env):
        session, d, _ = env
        df = session.read.parquet(d)
        with pytest.raises(HyperspaceException, match="every column"):
            df.drop("k", "v")

    def test_union_column_mismatch(self, env):
        session, d, _ = env
        df = session.read.parquet(d)
        with pytest.raises(HyperspaceException, match="column mismatch"):
            df.select("k").union(df.select("v"))

    def test_union_dtype_mismatch(self, env, tmp_path):
        session, d, _ = env
        other = tmp_path / "floats"
        other.mkdir()
        pq.write_table(pa.table({
            "k": pa.array([1.5, 2.5], type=pa.float64()),
            "v": pa.array([1, 2], type=pa.int64())}),
            other / "p0.parquet")
        df = session.read.parquet(d)
        f = session.read.parquet(str(other))
        with pytest.raises(HyperspaceException, match="dtype mismatch"):
            df.union(f)


class TestReaderWriterErrors:
    def test_read_missing_dir(self, env):
        session, _, tmp = env
        with pytest.raises(HyperspaceException):
            session.read.parquet(str(tmp / "nope")).to_arrow()

    def test_unknown_format(self, env):
        session, d, _ = env
        with pytest.raises(HyperspaceException, match="format 'xml'"):
            session.read.format("xml").load(d)

    def test_write_refuses_overwrite_by_default(self, env):
        session, d, tmp = env
        df = session.read.parquet(d)
        out = tmp / "out"
        df.write.parquet(str(out))
        with pytest.raises(HyperspaceException, match="mode"):
            df.write.parquet(str(out))

    def test_write_bad_mode(self, env):
        session, d, tmp = env
        df = session.read.parquet(d)
        with pytest.raises(HyperspaceException, match="mode"):
            df.write.mode("upsert").parquet(str(tmp / "o2"))


class TestViewErrors:
    def test_table_unknown_view(self, env):
        session, _, _ = env
        with pytest.raises(HyperspaceException, match="view"):
            session.table("ghost")

    def test_duplicate_view_without_replace(self, env):
        session, d, _ = env
        df = session.read.parquet(d)
        session.create_temp_view("v1", df)
        with pytest.raises(HyperspaceException):
            session.create_temp_view("v1", df)
        session.drop_temp_view("v1")


class TestIndexApiErrors:
    def test_create_index_unknown_indexed_column(self, env):
        session, d, _ = env
        hs = Hyperspace(session)
        df = session.read.parquet(d)
        with pytest.raises(HyperspaceException):
            hs.create_index(df, IndexConfig("i1", ["zzz"], ["v"]))

    def test_create_index_unknown_included_column(self, env):
        session, d, _ = env
        hs = Hyperspace(session)
        df = session.read.parquet(d)
        with pytest.raises(HyperspaceException):
            hs.create_index(df, IndexConfig("i2", ["k"], ["zzz"]))

    def test_create_index_overlapping_columns(self, env):
        session, d, _ = env
        hs = Hyperspace(session)
        df = session.read.parquet(d)
        with pytest.raises(HyperspaceException):
            hs.create_index(df, IndexConfig("i3", ["k"], ["k"]))

    def test_delete_unknown_index(self, env):
        session, _, _ = env
        hs = Hyperspace(session)
        with pytest.raises(HyperspaceException):
            hs.delete_index("ghost")

    def test_refresh_unknown_index(self, env):
        session, _, _ = env
        hs = Hyperspace(session)
        with pytest.raises(HyperspaceException):
            hs.refresh_index("ghost")
