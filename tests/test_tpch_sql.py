"""Verbatim TPC-H SQL texts against the pandas oracle, indexes off AND on.

The reference inherits Spark's full SQL surface, so its users run the
actual TPC-H/TPC-DS query texts (src/test/resources/tpcds/queries/).
This suite is the framework's SQL conformance anchor (VERDICT r3 weakness
#6): the eight query texts below are the standard TPC-H shapes — Q1, Q3,
Q6, Q12, Q14, Q16, Q17, Q19, plus Q4's EXISTS — written as published
(modulo the scale-1 literal parameters), parsed by session.sql, executed,
and checked against an independently-computed pandas answer. Every query
is then re-run with covering indexes created and hyperspace enabled, and
must produce the identical answer (the reference's disable-and-compare
oracle, E2EHyperspaceRulesTest pattern).
"""

from __future__ import annotations

import datetime
import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.exceptions import HyperspaceException


def _dates(rng, n, lo=8000, hi=9800):
    return pa.array(rng.integers(lo, hi, n).astype(np.int32),
                    type=pa.int32()).cast(pa.date32())


def _make_tables(rng):
    """TPC-H-schema tables sized/shaped so every target query selects a
    non-empty answer (Q19's branch predicates are the binding constraint:
    containers, brands, sizes, ship modes and instructions must co-occur)."""
    n_li, n_od, n_pt, n_sup, n_cu, n_ps = 3000, 800, 120, 25, 80, 600
    base_ship = rng.integers(8000, 9800, n_li).astype(np.int32)
    return {
        "lineitem": pa.table({
            "l_orderkey": pa.array(rng.integers(0, n_od, n_li).astype(np.int64)),
            "l_partkey": pa.array(rng.integers(0, n_pt, n_li).astype(np.int64)),
            "l_suppkey": pa.array(rng.integers(0, n_sup, n_li).astype(np.int64)),
            "l_quantity": pa.array(rng.integers(1, 50, n_li).astype(np.int64)),
            "l_extendedprice": pa.array(np.round(rng.uniform(900, 105000, n_li), 2)),
            "l_discount": pa.array(np.round(rng.uniform(0, 0.1, n_li), 2)),
            "l_tax": pa.array(np.round(rng.uniform(0, 0.08, n_li), 2)),
            "l_returnflag": pa.array(rng.choice(["A", "N", "R"], n_li)),
            "l_linestatus": pa.array(rng.choice(["O", "F"], n_li)),
            "l_shipdate": pa.array(base_ship, type=pa.int32()).cast(pa.date32()),
            "l_commitdate": pa.array(
                base_ship + rng.integers(-60, 60, n_li).astype(np.int32),
                type=pa.int32()).cast(pa.date32()),
            "l_receiptdate": pa.array(
                base_ship + rng.integers(1, 90, n_li).astype(np.int32),
                type=pa.int32()).cast(pa.date32()),
            "l_shipmode": pa.array(rng.choice(
                ["MAIL", "SHIP", "AIR", "AIR REG", "TRUCK"], n_li)),
            "l_shipinstruct": pa.array(rng.choice(
                ["DELIVER IN PERSON", "COLLECT COD", "NONE"], n_li)),
        }),
        "orders": pa.table({
            "o_orderkey": pa.array(np.arange(n_od, dtype=np.int64)),
            "o_custkey": pa.array(rng.integers(0, n_cu, n_od).astype(np.int64)),
            "o_orderdate": _dates(rng, n_od),
            "o_orderpriority": pa.array(rng.choice(
                ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED",
                 "5-LOW"], n_od)),
            "o_shippriority": pa.array(np.zeros(n_od, dtype=np.int32)),
        }),
        "customer": pa.table({
            "c_custkey": pa.array(np.arange(n_cu, dtype=np.int64)),
            "c_mktsegment": pa.array(rng.choice(
                ["BUILDING", "AUTOMOBILE", "MACHINERY", "HOUSEHOLD"], n_cu)),
        }),
        "part": pa.table({
            "p_partkey": pa.array(np.arange(n_pt, dtype=np.int64)),
            "p_brand": pa.array(rng.choice(
                ["Brand#12", "Brand#23", "Brand#45"], n_pt)),
            "p_type": pa.array(rng.choice(
                ["PROMO BRUSHED COPPER", "PROMO POLISHED BRASS",
                 "STANDARD POLISHED TIN", "MEDIUM POLISHED NICKEL",
                 "ECONOMY ANODIZED STEEL"], n_pt)),
            "p_size": pa.array(rng.integers(1, 20, n_pt).astype(np.int64)),
            "p_container": pa.array(rng.choice(
                ["SM CASE", "SM BOX", "MED BOX", "MED PKG", "LG BOX",
                 "LG PKG", "JUMBO PKG"], n_pt)),
        }),
        "supplier": pa.table({
            "s_suppkey": pa.array(np.arange(n_sup, dtype=np.int64)),
            "s_comment": pa.array([
                ("sleeps. Customer is upset about Complaints handling"
                 if i % 5 == 0 else "quiet dependable supplier")
                for i in range(n_sup)]),
        }),
        "partsupp": pa.table({
            "ps_partkey": pa.array(rng.integers(0, n_pt, n_ps).astype(np.int64)),
            "ps_suppkey": pa.array(rng.integers(0, n_sup, n_ps).astype(np.int64)),
        }),
    }


@pytest.fixture(scope="module")
def tpch(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("tpch_sql"))
    session = hst.Session(system_path=os.path.join(root, "indexes"))
    tables = _make_tables(np.random.default_rng(20260731))
    frames = {}
    for name, t in tables.items():
        d = os.path.join(root, name)
        os.makedirs(d)
        pq.write_table(t, os.path.join(d, "part0.parquet"))
        session.create_temp_view(name, session.read.parquet(d))
        frames[name] = t.to_pandas()
    return session, frames


def _norm(df: pd.DataFrame) -> pd.DataFrame:
    """Order-insensitive, float-rounded canonical form."""
    out = df.copy()
    for c in out.columns:
        if out[c].dtype == np.float64:
            out[c] = out[c].round(4)
        if str(out[c].dtype).startswith("datetime"):
            out[c] = out[c].astype(str)
        if out[c].dtype == object:
            out[c] = out[c].astype(str)
    return out.sort_values(list(out.columns)).reset_index(drop=True)


def _check(session, sql_text, expected: pd.DataFrame, ordered=False):
    got = session.sql(sql_text).to_pandas()
    assert list(got.columns) == list(expected.columns), \
        f"columns {list(got.columns)} != {list(expected.columns)}"
    if ordered:
        g, e = got.copy(), expected.copy()
        for c in g.columns:
            if g[c].dtype == np.float64:
                g[c] = g[c].round(4)
                e[c] = e[c].round(4)
            if str(g[c].dtype).startswith("datetime") or g[c].dtype == object:
                g[c] = g[c].astype(str)
                e[c] = e[c].astype(str)
        pd.testing.assert_frame_equal(g.reset_index(drop=True),
                                      e.reset_index(drop=True),
                                      check_dtype=False)
    else:
        pd.testing.assert_frame_equal(_norm(got), _norm(expected),
                                      check_dtype=False)
    return got


# ---------------------------------------------------------------------------
# The verbatim query texts (TPC-H v3 standard shapes, scale-1 parameters).
# ---------------------------------------------------------------------------

Q1 = """
select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty,
 sum(l_extendedprice) as sum_base_price,
 sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
 sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
 avg(l_quantity) as avg_qty, avg(l_extendedprice) as avg_price,
 avg(l_discount) as avg_disc, count(*) as count_order
from lineitem
where l_shipdate <= date '1998-12-01' - interval '90' day
group by l_returnflag, l_linestatus
order by l_returnflag, l_linestatus
"""

Q3 = """
select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
 o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
 and l_orderkey = o_orderkey
 and o_orderdate < date '1995-03-15' and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate
limit 10
"""

Q4 = """
select o_orderpriority, count(*) as order_count
from orders
where o_orderdate >= date '1993-07-01'
 and o_orderdate < date '1993-07-01' + interval '3' month
 and exists ( select * from lineitem
   where l_orderkey = o_orderkey and l_commitdate < l_receiptdate )
group by o_orderpriority
order by o_orderpriority
"""

Q6 = """
select sum(l_extendedprice * l_discount) as revenue
from lineitem
where l_shipdate >= date '1994-01-01'
 and l_shipdate < date '1994-01-01' + interval '1' year
 and l_discount between .06 - 0.01 and .06 + 0.01
 and l_quantity < 24
"""

Q12 = """
select l_shipmode,
 sum(case when o_orderpriority = '1-URGENT' or o_orderpriority = '2-HIGH'
     then 1 else 0 end) as high_line_count,
 sum(case when o_orderpriority <> '1-URGENT'
     and o_orderpriority <> '2-HIGH' then 1 else 0 end) as low_line_count
from orders, lineitem
where o_orderkey = l_orderkey and l_shipmode in ('MAIL', 'SHIP')
 and l_commitdate < l_receiptdate and l_shipdate < l_commitdate
 and l_receiptdate >= date '1994-01-01'
 and l_receiptdate < date '1994-01-01' + interval '1' year
group by l_shipmode
order by l_shipmode
"""

Q14 = """
select 100.00 * sum(case when p_type like 'PROMO%'
  then l_extendedprice * (1 - l_discount) else 0 end)
 / sum(l_extendedprice * (1 - l_discount)) as promo_revenue
from lineitem, part
where l_partkey = p_partkey and l_shipdate >= date '1995-09-01'
 and l_shipdate < date '1995-09-01' + interval '1' month
"""

Q16 = """
select p_brand, p_type, p_size, count(distinct ps_suppkey) as supplier_cnt
from partsupp, part
where p_partkey = ps_partkey and p_brand <> 'Brand#45'
 and p_type not like 'MEDIUM POLISHED%'
 and p_size in (1, 3, 5, 7, 9, 11, 14, 19)
 and ps_suppkey not in ( select s_suppkey from supplier
   where s_comment like '%Customer%Complaints%' )
group by p_brand, p_type, p_size
order by supplier_cnt desc, p_brand, p_type, p_size
"""

Q17 = """
select sum(l_extendedprice) / 7.0 as avg_yearly
from lineitem, part
where p_partkey = l_partkey and p_brand = 'Brand#23'
 and p_container = 'MED BOX'
 and l_quantity < ( select 0.2 * avg(l_quantity) from lineitem
   where l_partkey = p_partkey )
"""

Q19 = """
select sum(l_extendedprice* (1 - l_discount)) as revenue
from lineitem, part
where ( p_partkey = l_partkey and p_brand = 'Brand#12'
  and p_container in ( 'SM CASE', 'SM BOX', 'SM PACK', 'SM PKG')
  and l_quantity >= 1 and l_quantity <= 1 + 10
  and p_size between 1 and 5
  and l_shipmode in ('AIR', 'AIR REG')
  and l_shipinstruct = 'DELIVER IN PERSON' )
 or ( p_partkey = l_partkey and p_brand = 'Brand#23'
  and p_container in ('MED BAG', 'MED BOX', 'MED PKG', 'MED PACK')
  and l_quantity >= 10 and l_quantity <= 10 + 10
  and p_size between 1 and 10
  and l_shipmode in ('AIR', 'AIR REG')
  and l_shipinstruct = 'DELIVER IN PERSON' )
 or ( p_partkey = l_partkey and p_brand = 'Brand#45'
  and p_container in ( 'LG CASE', 'LG BOX', 'LG PACK', 'LG PKG')
  and l_quantity >= 20 and l_quantity <= 20 + 10
  and p_size between 1 and 15
  and l_shipmode in ('AIR', 'AIR REG')
  and l_shipinstruct = 'DELIVER IN PERSON' )
"""


# ---------------------------------------------------------------------------
# pandas oracles.
# ---------------------------------------------------------------------------

def _oracle_q1(f):
    li = f["lineitem"]
    m = li[li.l_shipdate <= datetime.date(1998, 9, 2)]
    disc = m.l_extendedprice * (1 - m.l_discount)
    g = m.assign(sum_disc_price=disc, sum_charge=disc * (1 + m.l_tax)) \
        .groupby(["l_returnflag", "l_linestatus"]) \
        .agg(sum_qty=("l_quantity", "sum"),
             sum_base_price=("l_extendedprice", "sum"),
             sum_disc_price=("sum_disc_price", "sum"),
             sum_charge=("sum_charge", "sum"),
             avg_qty=("l_quantity", "mean"),
             avg_price=("l_extendedprice", "mean"),
             avg_disc=("l_discount", "mean"),
             count_order=("l_quantity", "size")) \
        .reset_index().sort_values(["l_returnflag", "l_linestatus"]) \
        .reset_index(drop=True)
    return g


def _oracle_q3(f):
    cu = f["customer"]
    od = f["orders"]
    li = f["lineitem"]
    j = cu[cu.c_mktsegment == "BUILDING"] \
        .merge(od[od.o_orderdate < datetime.date(1995, 3, 15)],
               left_on="c_custkey", right_on="o_custkey") \
        .merge(li[li.l_shipdate > datetime.date(1995, 3, 15)],
               left_on="o_orderkey", right_on="l_orderkey")
    j = j.assign(revenue=j.l_extendedprice * (1 - j.l_discount))
    g = j.groupby(["l_orderkey", "o_orderdate", "o_shippriority"],
                  as_index=False).revenue.sum()
    g = g.sort_values(["revenue", "o_orderdate"],
                      ascending=[False, True]).head(10)
    return g[["l_orderkey", "revenue", "o_orderdate",
              "o_shippriority"]].reset_index(drop=True)


def _oracle_q4(f):
    od, li = f["orders"], f["lineitem"]
    ok = set(li[li.l_commitdate < li.l_receiptdate].l_orderkey)
    m = od[(od.o_orderdate >= datetime.date(1993, 7, 1))
           & (od.o_orderdate < datetime.date(1993, 10, 1))
           & od.o_orderkey.isin(ok)]
    return m.groupby("o_orderpriority").size() \
        .rename("order_count").reset_index() \
        .sort_values("o_orderpriority").reset_index(drop=True)


def _oracle_q6(f):
    li = f["lineitem"]
    m = li[(li.l_shipdate >= datetime.date(1994, 1, 1))
           & (li.l_shipdate < datetime.date(1995, 1, 1))
           & (li.l_discount >= 0.05) & (li.l_discount <= 0.07)
           & (li.l_quantity < 24)]
    return pd.DataFrame({"revenue": [(m.l_extendedprice
                                      * m.l_discount).sum()]})


def _oracle_q12(f):
    j = f["orders"].merge(f["lineitem"], left_on="o_orderkey",
                          right_on="l_orderkey")
    j = j[j.l_shipmode.isin(["MAIL", "SHIP"])
          & (j.l_commitdate < j.l_receiptdate)
          & (j.l_shipdate < j.l_commitdate)
          & (j.l_receiptdate >= datetime.date(1994, 1, 1))
          & (j.l_receiptdate < datetime.date(1995, 1, 1))]
    hi = j.o_orderpriority.isin(["1-URGENT", "2-HIGH"])
    return j.assign(high_line_count=hi.astype(np.int64),
                    low_line_count=(~hi).astype(np.int64)) \
        .groupby("l_shipmode", as_index=False)[
            ["high_line_count", "low_line_count"]].sum() \
        .sort_values("l_shipmode").reset_index(drop=True)


def _oracle_q14(f):
    j = f["lineitem"].merge(f["part"], left_on="l_partkey",
                            right_on="p_partkey")
    j = j[(j.l_shipdate >= datetime.date(1995, 9, 1))
          & (j.l_shipdate < datetime.date(1995, 10, 1))]
    disc = j.l_extendedprice * (1 - j.l_discount)
    promo = disc[j.p_type.str.startswith("PROMO")].sum()
    return pd.DataFrame({"promo_revenue": [100.0 * promo / disc.sum()]})


def _oracle_q16(f):
    sup = f["supplier"]
    bad = set(sup[sup.s_comment.str.match(
        ".*Customer.*Complaints.*")].s_suppkey)
    j = f["partsupp"].merge(f["part"], left_on="ps_partkey",
                            right_on="p_partkey")
    j = j[(j.p_brand != "Brand#45")
          & ~j.p_type.str.startswith("MEDIUM POLISHED")
          & j.p_size.isin([1, 3, 5, 7, 9, 11, 14, 19])
          & ~j.ps_suppkey.isin(bad)]
    g = j.groupby(["p_brand", "p_type", "p_size"]) \
        .ps_suppkey.nunique().rename("supplier_cnt").reset_index()
    return g.sort_values(["supplier_cnt", "p_brand", "p_type", "p_size"],
                         ascending=[False, True, True, True]) \
        .reset_index(drop=True)


def _oracle_q17(f):
    li, pt = f["lineitem"], f["part"]
    thr = li.groupby("l_partkey").l_quantity.mean() * 0.2
    j = li.merge(pt[(pt.p_brand == "Brand#23")
                    & (pt.p_container == "MED BOX")],
                 left_on="l_partkey", right_on="p_partkey")
    j = j[j.l_quantity < j.l_partkey.map(thr)]
    return pd.DataFrame({"avg_yearly": [j.l_extendedprice.sum() / 7.0]})


def _oracle_q19(f):
    j = f["lineitem"].merge(f["part"], left_on="l_partkey",
                            right_on="p_partkey")

    def br(brand, conts, qlo, qhi, smax):
        return ((j.p_brand == brand) & j.p_container.isin(conts)
                & (j.l_quantity >= qlo) & (j.l_quantity <= qhi)
                & (j.p_size >= 1) & (j.p_size <= smax)
                & j.l_shipmode.isin(["AIR", "AIR REG"])
                & (j.l_shipinstruct == "DELIVER IN PERSON"))

    m = br("Brand#12", ["SM CASE", "SM BOX", "SM PACK", "SM PKG"], 1, 11, 5) \
        | br("Brand#23", ["MED BAG", "MED BOX", "MED PKG", "MED PACK"],
             10, 20, 10) \
        | br("Brand#45", ["LG CASE", "LG BOX", "LG PACK", "LG PKG"],
             20, 30, 15)
    return pd.DataFrame({"revenue": [(j[m].l_extendedprice
                                      * (1 - j[m].l_discount)).sum()]})


_CASES = [
    ("q1", Q1, _oracle_q1, True),
    ("q3", Q3, _oracle_q3, True),
    ("q4", Q4, _oracle_q4, True),
    ("q6", Q6, _oracle_q6, False),
    ("q12", Q12, _oracle_q12, True),
    ("q14", Q14, _oracle_q14, False),
    ("q16", Q16, _oracle_q16, True),
    ("q17", Q17, _oracle_q17, False),
    ("q19", Q19, _oracle_q19, False),
]


class TestTpchVerbatim:
    @pytest.mark.parametrize("name,text,oracle,ordered",
                             _CASES, ids=[c[0] for c in _CASES])
    def test_matches_oracle(self, tpch, name, text, oracle, ordered):
        session, frames = tpch
        expected = oracle(frames)
        got = _check(session, text, expected, ordered=ordered)
        # Guard against vacuously-empty answers: the datagen is tuned so
        # every query selects something.
        assert len(got) > 0
        if name in ("q6", "q14", "q17", "q19"):
            assert float(got.iloc[0, 0]) != 0.0

    def test_nonempty_semi_anti_paths(self, tpch):
        """Q4's EXISTS must keep strictly fewer orders than no filter,
        and Q16's NOT IN must exclude at least one supplier (i.e. the
        semi/anti joins actually discriminate)."""
        session, frames = tpch
        q4 = session.sql(Q4).to_pandas()
        total = frames["orders"]
        window = total[(total.o_orderdate >= datetime.date(1993, 7, 1))
                       & (total.o_orderdate < datetime.date(1993, 10, 1))]
        assert 0 < q4.order_count.sum() <= len(window)
        sup = frames["supplier"]
        assert sup.s_comment.str.match(".*Customer.*Complaints.*").any()


class TestTpchWithIndexes:
    """The disable-and-compare oracle with real covering indexes: results
    must be identical with hyperspace enabled, and the rewrites must
    actually fire for the index-friendly shapes."""

    @pytest.fixture(scope="class")
    def indexed(self, tpch):
        session, frames = tpch
        hs = Hyperspace(session)
        li = session.table("lineitem")
        od = session.table("orders")
        pt = session.table("part")
        hs.create_index(li, IndexConfig(
            "sql_li_ok", ["l_orderkey"],
            ["l_extendedprice", "l_discount", "l_shipdate"]))
        hs.create_index(li, IndexConfig(
            "sql_li_ship", ["l_shipdate"],
            ["l_extendedprice", "l_discount", "l_quantity"]))
        hs.create_index(li, IndexConfig(
            "sql_li_pk", ["l_partkey"],
            ["l_quantity", "l_extendedprice", "l_discount", "l_shipdate",
             "l_shipmode", "l_shipinstruct"]))
        hs.create_index(od, IndexConfig(
            "sql_od_ok", ["o_orderkey"],
            ["o_custkey", "o_orderdate", "o_shippriority"]))
        hs.create_index(pt, IndexConfig(
            "sql_pt_pk", ["p_partkey"],
            ["p_brand", "p_container", "p_type", "p_size"]))
        yield session, frames
        session.disable_hyperspace()
        for name in ("sql_li_ok", "sql_li_ship", "sql_li_pk", "sql_od_ok",
                     "sql_pt_pk"):
            hs.delete_index(name)
            hs.vacuum_index(name)

    @pytest.mark.parametrize("name,text,oracle,ordered",
                             _CASES, ids=[c[0] for c in _CASES])
    def test_same_answer_with_indexes(self, indexed, name, text, oracle,
                                      ordered):
        session, frames = indexed
        session.enable_hyperspace()
        try:
            _check(session, text, oracle(frames), ordered=ordered)
        finally:
            session.disable_hyperspace()

    def test_rewrites_fire(self, indexed):
        session, _ = indexed
        session.enable_hyperspace()
        try:
            rewritten = []
            for name, text, _, _ in _CASES:
                plan = session.sql(text).optimized_plan()
                if any("IndexScan" in leaf.simple_string()
                       for leaf in plan.collect_leaves()):
                    rewritten.append(name)
            # Q6 (l_shipdate filter) and the bottom-level lineitem⋈part
            # joins (Q14/Q17/Q19, l_partkey = p_partkey with both sides
            # linear) MUST rewrite. Q3's verbatim 3-table join builds
            # left-deep (customer⋈orders)⋈lineitem, whose top join has a
            # non-linear side — the reference's JoinIndexRule skips it for
            # the same reason (isPlanLinear, JoinIndexRule.scala:166), so
            # no-rewrite there IS parity, not a gap.
            assert "q6" in rewritten
            assert "q14" in rewritten
            assert "q17" in rewritten
            assert len(rewritten) >= 4, rewritten
        finally:
            session.disable_hyperspace()


class TestSqlPlanEquivalence:
    """VERDICT r3 ask #3: the SQL texts must plan identically to their
    hand-built DataFrame forms (Q17's correlated shape and Q16's anti
    join), so the SQL front-end adds no planning divergence."""

    def test_q17_plans_like_dataframe(self, tpch):
        session, _ = tpch
        from hyperspace_tpu.plan.expr import avg, col, sum_
        li = session.table("lineitem")
        pt = session.table("part")
        thr = (li.group_by("l_partkey")
               .agg(avg(col("l_quantity")).alias("__sq0_agg"))
               .select(col("l_partkey").alias("__sq0_k0"),
                       (lit_mul(col("__sq0_agg"))).alias("__sq0_val")))
        df = (li.join(pt.filter((col("p_brand") == "Brand#23")
                                & (col("p_container") == "MED BOX")),
                      on=col("p_partkey") == col("l_partkey"))
              .join(thr, on=col("p_partkey") == col("__sq0_k0"))
              .filter(col("l_quantity") < col("__sq0_val"))
              .agg(sum_(col("l_extendedprice")).alias("__item_0_0"))
              .select((col("__item_0_0") / 7.0).alias("avg_yearly")))
        sql_plan = session.sql(Q17).plan.tree_string()
        df_plan = df.plan.tree_string()
        assert _strip_scan_details(sql_plan) == _strip_scan_details(df_plan)

    def test_q16_anti_join_shape(self, tpch):
        session, _ = tpch
        plan = session.sql(Q16).plan.tree_string()
        assert "Join anti" in plan
        assert "Aggregate [p_brand, p_type, p_size] [supplier_cnt]" in plan

    def test_exists_becomes_semi_join(self, tpch):
        session, _ = tpch
        plan = session.sql(Q4).plan.tree_string()
        assert "Join semi" in plan


def lit_mul(e):
    from hyperspace_tpu.plan.expr import Lit, Multiply
    return Multiply(Lit(0.2), e)


def _strip_scan_details(s: str) -> str:
    import re
    return re.sub(r"Scan [^\n]*", "Scan <relation>", s)


class TestReviewRegressions:
    """Pinned fixes from the round-4 code review of the SQL front-end."""

    def test_self_correlated_in_subquery(self, tpch):
        """Subquery over the SAME table as the outer query: the qualified
        correlation (t2.col = t.col) must survive qualifier stripping —
        the Q21-family shape."""
        session, frames = tpch
        got = session.sql(
            "select o.o_orderkey from orders o where o.o_custkey in "
            "(select o2.o_custkey from orders o2 "
            " where o2.o_custkey = o.o_custkey and o2.o_orderkey = 0)"
        ).to_pandas()
        od = frames["orders"]
        cust0 = set(od[od.o_orderkey == 0].o_custkey)
        exp = od[od.o_custkey.isin(cust0)].o_orderkey
        assert sorted(got.o_orderkey) == sorted(exp)

    def test_case_with_null_branch(self, tpch):
        session, frames = tpch
        got = session.sql(
            "select o_orderkey, case when o_orderpriority = '1-URGENT' "
            "then o_orderpriority else null end as urg from orders"
        ).to_pandas()
        od = frames["orders"]
        exp = od.o_orderpriority.where(od.o_orderpriority == "1-URGENT")
        assert got.urg.isna().sum() == exp.isna().sum()
        assert set(got.urg.dropna()) <= {"1-URGENT"}

    def test_select_star_hides_subquery_helpers(self, tpch):
        session, _ = tpch
        got = session.sql(
            "select * from part where p_size > "
            "(select avg(l_quantity) from lineitem "
            " where l_partkey = p_partkey) limit 3").to_pandas()
        assert not [c for c in got.columns if c.startswith("__sq")]
        assert list(got.columns) == ["p_partkey", "p_brand", "p_type",
                                     "p_size", "p_container"]

    def test_order_by_qualified_alias(self, tpch):
        session, _ = tpch
        got = session.sql(
            "select o.o_orderkey, o.o_orderdate from orders o "
            "order by o.o_orderdate, o.o_orderkey limit 5").to_pandas()
        assert list(got.columns) == ["o_orderkey", "o_orderdate"]
        assert got.o_orderdate.is_monotonic_increasing


class TestSqlSurfaceErrors:
    """New-grammar edges: clear errors, not silent wrong answers."""

    def test_alias_unknown_column(self, tpch):
        session, _ = tpch
        with pytest.raises(HyperspaceException, match="no column"):
            session.sql("select l.nope from lineitem l").to_pandas()

    def test_cross_join_rejected(self, tpch):
        session, _ = tpch
        with pytest.raises(HyperspaceException, match="cross join"):
            session.sql(
                "select l_orderkey from lineitem, part "
                "where l_quantity > 0").to_pandas()

    def test_nested_subquery_rejected(self, tpch):
        session, _ = tpch
        with pytest.raises(HyperspaceException):
            session.sql(
                "select o_orderkey from orders where o_orderkey in "
                "(select l_orderkey from lineitem where l_partkey in "
                "(select p_partkey from part))").to_pandas()

    def test_uncorrelated_scalar_rejected(self, tpch):
        session, _ = tpch
        with pytest.raises(HyperspaceException, match="ncorrelated"):
            session.sql(
                "select l_orderkey from lineitem where l_quantity < "
                "(select avg(l_quantity) from lineitem)").to_pandas()

    def test_interval_against_column_rejected(self, tpch):
        session, _ = tpch
        with pytest.raises(HyperspaceException, match="INTERVAL"):
            session.sql(
                "select l_orderkey from lineitem "
                "where l_shipdate + interval '1' day > "
                "date '1994-01-01'").to_pandas()
