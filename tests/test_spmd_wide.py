"""Widened SPMD coverage (VERDICT r2 #3): row-returning distributed
filter/project/join streams, multi-key broadcast joins, co-partitioned m:n
exchange joins under skew, and capacity escalation.

Oracle pattern matches test_spmd.py: assert the SPMD path is actually taken
(DISPATCH_COUNT advances), and results equal the single-device executor run
via the same public API with distribution disabled.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.execution import spmd
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col, count, sum_


@pytest.fixture()
def session(tmp_system_path):
    s = hst.Session(system_path=tmp_system_path)
    # Gate off: these fixtures are deliberately small meshes.
    s.conf.set(IndexConstants.TPU_DISTRIBUTED_MIN_STREAM_ROWS, "0")
    return s


def write_dir(tmp_path, name, table):
    d = tmp_path / name
    d.mkdir()
    pq.write_table(table, str(d / "part0.parquet"))
    return str(d)


@pytest.fixture()
def fact_dir(tmp_path):
    rng = np.random.default_rng(31)
    n = 5000
    return write_dir(tmp_path, "fact", pa.table({
        "k": rng.integers(0, 400, n).astype(np.int64),
        "k2": rng.integers(0, 6, n).astype(np.int64),
        "tag": rng.choice(["a", "b", "c"], n),
        "v": np.round(rng.uniform(0, 100, n), 3),
    }))


@pytest.fixture()
def dim_dir(tmp_path):
    rng = np.random.default_rng(32)
    rows = []
    t = pa.table({
        "dk": np.repeat(np.arange(400, dtype=np.int64), 6),
        "dk2": np.tile(np.arange(6, dtype=np.int64), 400),
        "dval": rng.integers(0, 50, 2400).astype(np.int64),
    })
    return write_dir(tmp_path, "dim", t)


def run_both(session, make_query, sort_by):
    before = spmd.DISPATCH_COUNT
    dist = make_query().to_pandas()
    assert spmd.DISPATCH_COUNT > before, "SPMD path was not taken"
    session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "false")
    try:
        single = make_query().to_pandas()
    finally:
        session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "true")
    a = dist.sort_values(sort_by).reset_index(drop=True)
    b = single.sort_values(sort_by).reset_index(drop=True)
    pd.testing.assert_frame_equal(a, b, check_dtype=False)
    return a


class TestRowReturningStream:
    def test_filter_returns_rows(self, session, fact_dir):
        f = session.read.parquet(fact_dir)
        out = run_both(
            session,
            lambda: f.filter((col("k") < 50) & (col("tag") != "b"))
                     .select("k", "v"),
            sort_by=["k", "v"])
        assert len(out) > 0

    def test_project_expression_rows(self, session, fact_dir):
        f = session.read.parquet(fact_dir)
        run_both(
            session,
            lambda: f.filter(col("k2") == 3)
                     .select(col("k"), (col("v") * 2 + 1).alias("vv")),
            sort_by=["k", "vv"])

    def test_filter_sort_limit_wrappers(self, session, fact_dir):
        f = session.read.parquet(fact_dir)
        before = spmd.DISPATCH_COUNT
        q = (f.filter(col("k") < 100).select("k", "v")
             .sort("k", "v").limit(20))
        dist = q.to_pandas()
        assert spmd.DISPATCH_COUNT > before
        session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "false")
        single = q.to_pandas()
        session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "true")
        pd.testing.assert_frame_equal(dist, single, check_dtype=False)

    def test_leaf_read_is_filter_pruned(self, session, fact_dir,
                                        monkeypatch):
        """The SPMD leaf load pushes the stage filter's pushable conjuncts
        into the parquet read (mask semantics unchanged) — the stream must
        not materialize the whole source when a filter sits on the leaf."""
        from hyperspace_tpu.execution import executor as ex

        calls = []
        orig = ex._execute_scan

        def spy(plan, needed, pa_filter=None):
            calls.append(pa_filter)
            return orig(plan, needed, pa_filter)

        monkeypatch.setattr(ex, "_execute_scan", spy)
        f = session.read.parquet(fact_dir)
        q = f.filter(col("k") < 25).select("k", "v")
        before = spmd.DISPATCH_COUNT
        dist = q.to_pandas()
        assert spmd.DISPATCH_COUNT > before, "SPMD path was not taken"
        # Only the DISTRIBUTED run's leaf read counts — the single-device
        # comparison below also pushes a filter, which must not be able to
        # satisfy this assertion (last-call-wins would mask a regression).
        assert calls and calls[0] is not None, \
            "SPMD leaf read did not receive the pushable filter"
        monkeypatch.undo()
        session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "false")
        try:
            single = q.to_pandas()
        finally:
            session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "true")
        a = dist.sort_values(["k", "v"]).reset_index(drop=True)
        b = single.sort_values(["k", "v"]).reset_index(drop=True)
        pd.testing.assert_frame_equal(a, b, check_dtype=False)
        assert len(a) > 0

    def test_join_returns_rows(self, session, fact_dir, tmp_path):
        rng = np.random.default_rng(40)
        small = write_dir(tmp_path, "small", pa.table({
            "sk": np.arange(400, dtype=np.int64),
            "sval": rng.integers(0, 9, 400).astype(np.int64),
        }))
        f = session.read.parquet(fact_dir)
        s = session.read.parquet(small)
        run_both(
            session,
            lambda: f.filter(col("k") < 120)
                     .join(s, on=col("k") == col("sk"))
                     .select("k", "v", "sval"),
            sort_by=["k", "v", "sval"])

    def test_nullable_output_columns(self, session, tmp_path):
        rng = np.random.default_rng(41)
        n = 3000
        mask = rng.random(n) < 0.2
        t = pa.table({
            "a": pa.array(rng.integers(0, 50, n), type=pa.int64(),
                          mask=mask),
            "b": pa.array(np.arange(n, dtype=np.int64)),
        })
        d = write_dir(tmp_path, "nulls", t)
        f = session.read.parquet(d)
        out = run_both(session,
                       lambda: f.filter(col("b") < 2000).select("a", "b"),
                       sort_by=["b"])
        assert out["a"].isna().sum() > 0


class TestMultiKeyBroadcastJoin:
    def test_two_key_join_aggregate(self, session, fact_dir, dim_dir):
        f = session.read.parquet(fact_dir)
        d = session.read.parquet(dim_dir)
        run_both(
            session,
            lambda: f.join(d, on=(col("k") == col("dk"))
                           & (col("k2") == col("dk2")))
                     .group_by("dval").agg(sum_(col("v")).alias("sv"),
                                           count(None).alias("n")),
            sort_by=["dval"])

    def test_two_key_join_rows(self, session, fact_dir, dim_dir):
        f = session.read.parquet(fact_dir)
        d = session.read.parquet(dim_dir)
        run_both(
            session,
            lambda: f.filter(col("k") < 80)
                     .join(d, on=(col("k") == col("dk"))
                           & (col("k2") == col("dk2")))
                     .select("k", "k2", "v", "dval"),
            sort_by=["k", "k2", "v", "dval"])


class TestExchangeJoin:
    def test_skewed_m_n_join(self, session, tmp_path):
        """80% of rows share one key (worst-case routing skew): capacity
        escalation must converge and results must match."""
        rng = np.random.default_rng(50)
        n = 4000
        keys = np.where(rng.random(n) < 0.8, 7,
                        rng.integers(0, 100, n)).astype(np.int64)
        left = write_dir(tmp_path, "l", pa.table({
            "k": keys, "v": rng.integers(0, 10, n).astype(np.int64)}))
        # Right side m:n but bounded fan-out (~3 dups per key), so the
        # skewed device's join output fits within the escalation ladder.
        m = 300
        rkeys = rng.integers(0, 100, m).astype(np.int64)
        right = write_dir(tmp_path, "r", pa.table({
            "rk": rkeys, "w": rng.integers(0, 10, m).astype(np.int64)}))
        lf = session.read.parquet(left)
        rf = session.read.parquet(right)
        run_both(
            session,
            lambda: lf.join(rf, on=col("k") == col("rk"))
                      .group_by("k").agg(count(None).alias("n"),
                                         sum_(col("w")).alias("sw")),
            sort_by=["k"])

    def test_m_n_join_row_returning(self, session, tmp_path):
        rng = np.random.default_rng(51)
        left = write_dir(tmp_path, "l2", pa.table({
            "k": rng.integers(0, 30, 1500).astype(np.int64),
            "v": np.arange(1500, dtype=np.int64)}))
        right = write_dir(tmp_path, "r2", pa.table({
            "rk": rng.integers(0, 30, 200).astype(np.int64),
            "w": np.arange(200, dtype=np.int64)}))
        lf = session.read.parquet(left)
        rf = session.read.parquet(right)
        out = run_both(
            session,
            lambda: lf.join(rf, on=col("k") == col("rk"))
                      .select("k", "v", "w"),
            sort_by=["k", "v", "w"])
        # m:n expansion really happened (output ≫ left rows).
        assert len(out) > 1500

    def test_overflow_recompiles_exactly_once(self, session, tmp_path):
        """Output-capacity overflow is retried with the EXACT need the
        program reported — one recompile, never a ×4 escalation ladder
        (VERDICT r3 #6: compiles are the dangerous operation on the TPU
        tunnel, so their count must be bounded and minimal)."""
        rng = np.random.default_rng(53)
        # Uniform keys (send caps fit) but multiplicity 8 on the right:
        # join output per owner device ≈ 8× the stream shard, well past
        # the default output-slot budget of 2×.
        n = 2000
        left = write_dir(tmp_path, "lov", pa.table({
            "k": rng.permutation(np.repeat(np.arange(250), 8))
                 .astype(np.int64)[:n],
            "v": np.arange(n, dtype=np.int64)}))
        right = write_dir(tmp_path, "rov", pa.table({
            "rk": np.repeat(np.arange(250, dtype=np.int64), 8),
            "w": np.arange(2000, dtype=np.int64)}))
        lf = session.read.parquet(left)
        rf = session.read.parquet(right)
        out = run_both(
            session,
            lambda: lf.join(rf, on=col("k") == col("rk"))
                      .group_by("k").agg(count(None).alias("n")),
            sort_by=["k"])
        assert len(out) == 250
        assert spmd.LAST_CAP_ATTEMPTS == 2, (
            f"{spmd.LAST_CAP_ATTEMPTS} capacity attempts — an output "
            "overflow must retry exactly once, with the exact reported "
            "need (attempts=1 would mean the shape stopped overflowing "
            "and the test lost its bite)")

    def test_first_attempt_fits_no_recompile(self, session, tmp_path):
        """A 1:~1 exchange join fits the default capacities outright."""
        rng = np.random.default_rng(54)
        left = write_dir(tmp_path, "lfit", pa.table({
            "k": rng.permutation(1200).astype(np.int64),
            "v": np.arange(1200, dtype=np.int64)}))
        right = write_dir(tmp_path, "rfit", pa.table({
            "rk": np.repeat(np.arange(600, dtype=np.int64), 2),
            "w": np.arange(1200, dtype=np.int64)}))
        lf = session.read.parquet(left)
        rf = session.read.parquet(right)
        run_both(
            session,
            lambda: lf.join(rf, on=col("k") == col("rk"))
                      .group_by("k").agg(count(None).alias("n")),
            sort_by=["k"])
        assert spmd.LAST_CAP_ATTEMPTS == 1

    def test_exchange_join_string_key(self, session, tmp_path):
        rng = np.random.default_rng(52)
        names = np.array([f"n{i:03d}" for i in range(40)])
        left = write_dir(tmp_path, "l3", pa.table({
            "k": names[rng.integers(0, 40, 2000)],
            "v": np.arange(2000, dtype=np.int64)}))
        right = write_dir(tmp_path, "r3", pa.table({
            "rk": names[rng.integers(0, 40, 300)],
            "w": np.arange(300, dtype=np.int64)}))
        lf = session.read.parquet(left)
        rf = session.read.parquet(right)
        run_both(
            session,
            lambda: lf.join(rf, on=col("k") == col("rk"))
                      .group_by("k").agg(count(None).alias("n")),
            sort_by=["k"])


class TestDistinctAndUnion:
    def test_distinct_dispatches_spmd(self, session, fact_dir):
        """distinct() lowers onto the grouped-aggregate machinery (group by
        every column), so it inherits the SPMD dispatch."""
        df = session.read.parquet(fact_dir)
        got = run_both(
            session,
            lambda: df.select("k2", "tag").distinct(),
            sort_by=["k2", "tag"])
        assert len(got) == 18  # 6 k2 values x 3 tags

    def test_union_falls_back_observably(self, session, fact_dir):
        """Union roots are not an SPMD shape: the query must still answer
        (single-device) and the fallback must be visible as an event."""
        from conftest import capture_logger
        session.conf.set(IndexConstants.EVENT_LOGGER_CLASS,
                         "tests.conftest.CaptureLogger")
        sink = capture_logger()
        sink.events.clear()
        df = session.read.parquet(fact_dir)
        q = (df.filter(col("k2") <= 2).select("k", "v")
             .union(df.filter(col("k2") >= 4).select("k", "v"))
             .group_by("k").agg(sum_(col("v")).alias("s"))
             .sort("k").limit(20))
        before = spmd.DISPATCH_COUNT
        got = q.to_pandas()
        session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "false")
        try:
            single = q.to_pandas()
        finally:
            session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "true")
        pd.testing.assert_frame_equal(got, single, check_dtype=False)
        if spmd.DISPATCH_COUNT == before:
            # Fell back — degradation must be observable (VERDICT r2 #4).
            assert any(type(e).__name__ == "DistributedFallbackEvent"
                       for e in sink.events)
