"""Full index residency on an object store (VERDICT r5 #7).

The op log was already proven rename-free (index/log_store.py); this
suite proves the index DATA side too: the whole lifecycle —
create → query → refresh (incremental) → optimize → delete → restore →
vacuum — parameterized over the local filesystem and the built-in
``hsmem://`` object store (fsspec memory filesystem + conditional-put
log adapter, registered in index/data_store.py). Source data stays on
the local lake; the index (log + data files) lives entirely in the
store — the reference's ABFS/S3A deployment shape
(docs/_docs/14-toh-indexes-on-the-lake.md).
"""

import uuid

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col, sum_


@pytest.fixture(params=["local", "hsmem"])
def env(request, tmp_path):
    rng = np.random.default_rng(8)
    n = 2500
    df = pd.DataFrame({
        "k": rng.integers(0, 200, n).astype(np.int64),
        "v": rng.integers(0, 1000, n).astype(np.int64),
        "s": rng.choice(["a", "b", "c"], n),
    })
    src = tmp_path / "src"
    src.mkdir()
    for i in range(4):
        pq.write_table(pa.Table.from_pandas(
            df.iloc[i * (n // 4):(i + 1) * (n // 4)].reset_index(drop=True)),
            src / f"part{i}.parquet")
    if request.param == "local":
        system_path = str(tmp_path / "indexes")
    else:
        # The fsspec memory store is process-global: isolate by unique root.
        system_path = f"hsmem://it-{uuid.uuid4().hex}/indexes"
    session = hst.Session(system_path=system_path)
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    session.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
    return dict(session=session, hs=Hyperspace(session), df=df,
                src=str(src), tmp=tmp_path, kind=request.param,
                system_path=system_path)


def _query(env):
    session = env["session"]
    return (session.read.parquet(env["src"])
            .filter(col("k").between(20, 120))
            .group_by("k").agg(sum_(col("v")).alias("sv")))


def _oracle(df):
    m = df[df.k.between(20, 120)]
    return m.groupby("k").agg(sv=("v", "sum")).reset_index()


def _assert_matches(env, extra=None):
    session = env["session"]
    session.enable_hyperspace()
    got = _query(env).to_pandas()
    session.disable_hyperspace()
    df = env["df"] if extra is None else \
        pd.concat([env["df"], extra], ignore_index=True)
    exp = _oracle(df)
    pd.testing.assert_frame_equal(
        got.sort_values("k").reset_index(drop=True),
        exp.sort_values("k").reset_index(drop=True), check_dtype=False)


def test_full_lifecycle(env):
    session, hs = env["session"], env["hs"]
    hs.create_index(session.read.parquet(env["src"]),
                    IndexConfig("resIdx", ["k"], ["v", "s"]))

    # The rewrite actually uses the store-resident index.
    session.enable_hyperspace()
    q = _query(env)
    assert any("IndexScan" in l.simple_string()
               for l in q.optimized_plan().collect_leaves()), \
        "query did not rewrite to the store-resident index"
    session.disable_hyperspace()
    _assert_matches(env)

    # Incremental refresh over appended source files.
    rng = np.random.default_rng(77)
    extra = pd.DataFrame({
        "k": rng.integers(0, 200, 300).astype(np.int64),
        "v": rng.integers(0, 1000, 300).astype(np.int64),
        "s": rng.choice(["a", "b", "c"], 300),
    })
    pq.write_table(pa.Table.from_pandas(extra),
                   env["tmp"] / "src" / "extra.parquet")
    hs.refresh_index("resIdx", "incremental")
    _assert_matches(env, extra)

    # Optimize (full: compact every bucket's files).
    hs.optimize_index("resIdx", "full")
    _assert_matches(env, extra)

    # Delete (soft) → restore → vacuum (hard).
    hs.delete_index("resIdx")
    assert hs.index("resIdx")["state"].iloc[0] == "DELETED"
    hs.restore_index("resIdx")
    assert hs.index("resIdx")["state"].iloc[0] == "ACTIVE"
    _assert_matches(env, extra)
    hs.delete_index("resIdx")
    hs.vacuum_index("resIdx")
    rows = hs.indexes()
    row = rows[rows["name"] == "resIdx"]
    assert len(row) == 0 or row.iloc[0]["state"] == "DOESNOTEXIST"


def test_listing_and_stats_through_store(env):
    session, hs = env["session"], env["hs"]
    hs.create_index(session.read.parquet(env["src"]),
                    IndexConfig("resIdx2", ["k"], ["v"]))
    rows = hs.indexes()
    assert "resIdx2" in set(rows["name"])
    stats = hs.index("resIdx2")
    assert stats["state"].iloc[0] == "ACTIVE"
    assert int(stats["indexFileCount"].iloc[0]) > 0


def test_no_rename_needed_on_object_store(env):
    """The hsmem store exposes no rename at all — the lifecycle above
    passing IS the proof; this asserts the index's files actually live
    in the object store, not on local disk."""
    if env["kind"] != "hsmem":
        pytest.skip("object-store-only assertion")
    session, hs = env["session"], env["hs"]
    hs.create_index(session.read.parquet(env["src"]),
                    IndexConfig("resIdx3", ["k"], ["v"]))
    entry = session.index_collection_manager.get_index("resIdx3")
    files = list(entry.content.files)
    assert files and all(f.startswith("hsmem://") for f in files), files
