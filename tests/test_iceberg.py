"""Iceberg-analogue integration tests.

Mirrors the reference's IcebergIntegrationTest scenarios (447 LoC,
sources/iceberg/): snapshot-id signatures, time travel by snapshot id, and
hybrid scan over table mutations.
"""

import numpy as np
import pyarrow as pa
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.lake.iceberg import (IcebergConcurrentModificationException,
                                         IcebergTable)
from hyperspace_tpu.plan.expr import col
from hyperspace_tpu.plan.nodes import IndexScan
from hyperspace_tpu.sources.iceberg import IcebergRelation


def _arrow(lo, hi, seed=0):
    rng = np.random.default_rng(seed)
    return pa.table({
        "k": pa.array(np.arange(lo, hi, dtype=np.int64)),
        "grp": pa.array((np.arange(lo, hi) % 7).astype(np.int64)),
        "v": pa.array(rng.uniform(0, 1, hi - lo)),
    })


def _sorted(t):
    return t.sort_by([(c, "ascending") for c in t.column_names])


def _index_leaves(df):
    return [l for l in df.optimized_plan().collect_leaves()
            if isinstance(l, IndexScan)]


class TestIcebergTable:
    def test_create_append_remove_snapshots(self, tmp_path):
        t = IcebergTable(str(tmp_path / "t"))
        s0 = t.create(_arrow(0, 100), max_rows_per_file=50)
        assert t.current_snapshot_id() == s0
        s1 = t.append(_arrow(100, 130))
        assert t.current_snapshot_id() == s1
        assert len(t.snapshot(s0).file_paths) == 2
        assert len(t.snapshot(s1).file_paths) == 3
        victim = t.snapshot(s0).file_paths[0]
        s2 = t.remove_files([victim])
        assert victim not in t.snapshot(s2).file_paths
        assert victim in t.snapshot(s0).file_paths  # snapshots immutable.
        assert t.snapshot_ids() == [s0, s1, s2]

    def test_concurrent_metadata_conflict(self, tmp_path):
        t = IcebergTable(str(tmp_path / "t"))
        t.create(_arrow(0, 10))
        meta = t._read_metadata()
        racer = dict(meta, metadataVersion=meta["metadataVersion"] + 1)
        t._commit_metadata(racer)
        with pytest.raises(IcebergConcurrentModificationException):
            t._commit_metadata(dict(racer))

    def test_record_counts_in_manifest(self, tmp_path):
        t = IcebergTable(str(tmp_path / "t"))
        t.create(_arrow(0, 95), max_rows_per_file=50)
        snap = t.snapshot()
        counts = [f["recordCount"] for f in snap._manifest["files"]]
        assert sorted(counts) == [45, 50]


class TestIcebergIndexIntegration:
    @pytest.fixture()
    def session(self, tmp_system_path):
        s = hst.Session(system_path=tmp_system_path)
        s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
        return s

    def test_index_used_and_answers_match(self, session, tmp_path):
        IcebergTable(str(tmp_path / "t")).create(_arrow(0, 400))
        hs = Hyperspace(session)
        df = session.read.iceberg(str(tmp_path / "t"))
        hs.create_index(df, IndexConfig("iix", ["grp"], ["k", "v"]))
        q = df.filter(col("grp") == 2).select("k", "v")
        session.enable_hyperspace()
        with_idx = _sorted(q.to_arrow())
        assert _index_leaves(q)
        session.disable_hyperspace()
        assert with_idx.equals(_sorted(q.to_arrow()))

    def test_snapshot_signature_and_hybrid_scan(self, session, tmp_path):
        table = IcebergTable(str(tmp_path / "t"))
        s0 = table.create(_arrow(0, 400))
        hs = Hyperspace(session)
        df = session.read.iceberg(str(tmp_path / "t"))
        hs.create_index(df, IndexConfig("iix", ["grp"], ["k"]))
        table.append(_arrow(400, 430))
        df2 = session.read.iceberg(str(tmp_path / "t"))
        q = df2.filter(col("grp") == 3).select("k")
        session.enable_hyperspace()
        assert not _index_leaves(q)  # snapshot changed → signature mismatch.
        session.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, "true")
        leaves = _index_leaves(q)
        assert leaves and leaves[0].appended_files
        with_idx = _sorted(q.to_arrow())
        session.disable_hyperspace()
        assert with_idx.equals(_sorted(q.to_arrow()))
        session.enable_hyperspace()

        # Time travel to the indexed snapshot → exact signature match again.
        q0 = session.read.iceberg(str(tmp_path / "t"), snapshot_id=s0) \
            .filter(col("grp") == 3).select("k")
        leaves = _index_leaves(q0)
        assert leaves and not leaves[0].appended_files

    def test_explain_mentions_iceberg_index(self, session, tmp_path):
        IcebergTable(str(tmp_path / "t")).create(_arrow(0, 100))
        hs = Hyperspace(session)
        df = session.read.iceberg(str(tmp_path / "t"))
        hs.create_index(df, IndexConfig("iix", ["grp"], ["k"]))
        session.enable_hyperspace()
        assert "iix" in hs.explain(df.filter(col("grp") == 1).select("k"))


class TestIcebergRelationBasics:
    def test_signature_snapshot_based(self, tmp_path):
        t = IcebergTable(str(tmp_path / "t"))
        s0 = t.create(_arrow(0, 50))
        sig0 = IcebergRelation(str(tmp_path / "t")).signature()
        assert IcebergRelation(str(tmp_path / "t")).signature() == sig0
        t.append(_arrow(50, 60))
        assert IcebergRelation(str(tmp_path / "t")).signature() != sig0
        assert IcebergRelation(str(tmp_path / "t"),
                               {"snapshotId": str(s0)}).signature() == sig0

    def test_file_infos_match_stat(self, tmp_path):
        t = IcebergTable(str(tmp_path / "t"))
        t.create(_arrow(0, 50))
        rel = IcebergRelation(str(tmp_path / "t"))
        from hyperspace_tpu.util.file_utils import file_info_triple
        assert rel.all_file_infos() == [
            file_info_triple(p) for p in rel.all_files()]
