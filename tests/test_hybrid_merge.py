"""Hybrid Scan keeps the shuffle-free merge join across appended files
(VERDICT r2 #5; parity: RuleUtils.scala:509-567 — the reference re-buckets
appended data at query time so the zero-exchange SMJ survives appends).

Asserts both that results are right (disable-and-compare) AND that the fast
paths were actually taken: HYBRID_MERGE_COUNT (appended rows merged into the
bucket-ordered stream) and FAST_JOIN_COUNT (join skipped its sort).
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.execution import executor
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col, sum_


def write_sample(root, name, df, parts=2):
    d = root / name
    d.mkdir(parents=True, exist_ok=True)
    step = max(1, len(df) // parts)
    for i in range(parts):
        chunk = df.iloc[i * step:(i + 1) * step if i < parts - 1 else len(df)]
        pq.write_table(pa.Table.from_pandas(chunk.reset_index(drop=True)),
                       d / f"part{i}.parquet")
    return str(d)


@pytest.fixture()
def env(tmp_path):
    rng = np.random.default_rng(4)
    n = 3000
    fact = pd.DataFrame({
        "k": rng.integers(0, 300, n).astype(np.int64),
        "v": rng.integers(0, 1000, n).astype(np.int64),
        "w": np.round(rng.uniform(0, 10, n), 3),
    })
    dim = pd.DataFrame({
        "dk": np.arange(300, dtype=np.int64),
        "dval": rng.integers(0, 50, 300).astype(np.int64),
    })
    # 6 parts so a single deleted file stays under the 0.2 deleted-bytes
    # Hybrid Scan threshold.
    fact_path = write_sample(tmp_path, "fact", fact, parts=6)
    dim_path = write_sample(tmp_path, "dim", dim, parts=1)
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    session.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, "true")
    hs = Hyperspace(session)
    hs.create_index(session.read.parquet(fact_path),
                    IndexConfig("factIdx", ["k"], ["v", "w"]))
    hs.create_index(session.read.parquet(dim_path),
                    IndexConfig("dimIdx", ["dk"], ["dval"]))
    return dict(session=session, hs=hs, fact_path=fact_path,
                dim_path=dim_path, fact=fact, dim=dim, tmp=tmp_path)


def append_fact(env, rows, name="extra.parquet"):
    rng = np.random.default_rng(99)
    extra = pd.DataFrame({
        "k": rng.integers(0, 300, rows).astype(np.int64),
        "v": rng.integers(0, 1000, rows).astype(np.int64),
        "w": np.round(rng.uniform(0, 10, rows), 3),
    })
    pq.write_table(pa.Table.from_pandas(extra),
                   env["tmp"] / "fact" / name)
    return extra


def join_query(env):
    session = env["session"]
    f = session.read.parquet(env["fact_path"])
    d = session.read.parquet(env["dim_path"])
    return (f.join(d, on=col("k") == col("dk"))
            .group_by("dval").agg(sum_(col("v")).alias("sv")))


def oracle(env, extra=None):
    fact = env["fact"] if extra is None else \
        pd.concat([env["fact"], extra], ignore_index=True)
    j = fact.merge(env["dim"], left_on="k", right_on="dk")
    return j.groupby("dval").agg(sv=("v", "sum")).reset_index()


class TestHybridMergeJoin:
    def test_no_appends_fast_join(self, env):
        """Baseline: without appends the join already skips its sort."""
        session = env["session"]
        session.enable_hyperspace()
        # Single-device comparison (SPMD would bypass the merge-join path).
        session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "false")
        before = executor.FAST_JOIN_COUNT
        got = join_query(env).to_pandas()
        assert executor.FAST_JOIN_COUNT > before
        exp = oracle(env)
        pd.testing.assert_frame_equal(
            got.sort_values("dval").reset_index(drop=True),
            exp.sort_values("dval").reset_index(drop=True), check_dtype=False)

    def test_appends_keep_fast_join(self, env):
        """With appended source files, the appended rows are re-bucketed and
        merged in WITHOUT dropping bucket order — the join still takes the
        no-re-sort path and results match the source scan."""
        session = env["session"]
        extra = append_fact(env, 400)
        session.enable_hyperspace()
        session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "false")
        q = join_query(env)
        from hyperspace_tpu.plan.nodes import IndexScan
        leaves = q.optimized_plan().collect_leaves()
        scans = [l for l in leaves if isinstance(l, IndexScan)
                 and l.index_entry.name == "factIdx"]
        assert scans and scans[0].appended_files, "hybrid scan not applied"

        m_before = executor.HYBRID_MERGE_COUNT
        j_before = executor.FAST_JOIN_COUNT
        got = q.to_pandas()
        assert executor.HYBRID_MERGE_COUNT > m_before, \
            "appended rows were not merge-unioned into the bucket order"
        assert executor.FAST_JOIN_COUNT > j_before, \
            "join re-sorted despite preserved bucket order"

        exp = oracle(env, extra)
        pd.testing.assert_frame_equal(
            got.sort_values("dval").reset_index(drop=True),
            exp.sort_values("dval").reset_index(drop=True), check_dtype=False)

        # Disable-and-compare through the public API.
        session.disable_hyperspace()
        without = join_query(env).to_pandas()
        pd.testing.assert_frame_equal(
            got.sort_values("dval").reset_index(drop=True),
            without.sort_values("dval").reset_index(drop=True),
            check_dtype=False)

    def test_appends_with_deletes_keep_fast_join(self, env):
        """Appends + lineage-masked deletes together still preserve order
        (the deleted-row filter keeps sortedness; the merge runs after)."""
        import os

        session, hs = env["session"], env["hs"]
        # Rebuild the fact index with lineage (required for delete masking).
        hs.delete_index("factIdx")
        hs.vacuum_index("factIdx")
        session.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
        hs.create_index(session.read.parquet(env["fact_path"]),
                        IndexConfig("factIdx", ["k"], ["v", "w"]))
        # Delete one source file and append another; quick refresh records
        # both in the log so the rewrite masks + merges at query time.
        victim = os.path.join(env["fact_path"], "part0.parquet")
        kept = pd.read_parquet(victim)
        os.remove(victim)
        extra = append_fact(env, 300)
        hs.refresh_index("factIdx", "quick")

        session.enable_hyperspace()
        session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "false")
        m_before = executor.HYBRID_MERGE_COUNT
        j_before = executor.FAST_JOIN_COUNT
        got = join_query(env).to_pandas()
        assert executor.HYBRID_MERGE_COUNT > m_before
        assert executor.FAST_JOIN_COUNT > j_before

        remaining = env["fact"].merge(kept, how="outer", indicator=True) \
            .query("_merge == 'left_only'").drop(columns="_merge")
        env2 = dict(env, fact=remaining)
        exp = oracle(env2, extra)
        pd.testing.assert_frame_equal(
            got.sort_values("dval").reset_index(drop=True),
            exp.sort_values("dval").reset_index(drop=True), check_dtype=False)

    def test_filter_query_appends_results(self, env):
        """Filter path with appended files (order preserved or not, results
        must match the source scan)."""
        session = env["session"]
        append_fact(env, 350)
        session.enable_hyperspace()
        q = (session.read.parquet(env["fact_path"])
             .filter(col("k").between(40, 60)).select("k", "v"))
        got = q.to_pandas()
        session.disable_hyperspace()
        exp = q.to_pandas()
        pd.testing.assert_frame_equal(
            got.sort_values(["k", "v"]).reset_index(drop=True),
            exp.sort_values(["k", "v"]).reset_index(drop=True),
            check_dtype=False)

    def test_chunked_scan_appends_keep_order_preserving_merge(self, env):
        """Beyond the chunk budget (VERDICT r5 #9): the streamed index
        chunks stay bucket-ordered and the appended survivors still merge
        in ORDER-PRESERVINGLY — previously the chunked path degraded to
        concat, so downstream consumers lost the sort-free path exactly
        at the scales that matter. The downstream proof here is the
        group-by on the indexed key skipping its sort."""
        session = env["session"]
        extra = append_fact(env, 400)
        session.enable_hyperspace()
        session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "false")
        # Chunk budget below the index row count forces the chunked path
        # (the same path bench.py's scale-20/50 hybrid phase takes).
        session.conf.set(IndexConstants.TPU_MAX_CHUNK_ROWS, "1024")
        try:
            q = (session.read.parquet(env["fact_path"])
                 .filter(col("k").between(0, 250))
                 .group_by("k").agg(sum_(col("v")).alias("sv")))
            before_chunks = executor.CHUNK_SCAN_STATS["chunks"]
            m_before = executor.HYBRID_MERGE_COUNT
            g_before = executor.GROUPBY_SORT_SKIPPED
            got = q.to_pandas()
            assert executor.CHUNK_SCAN_STATS["chunks"] > before_chunks, \
                "chunked index scan path not taken"
            assert executor.HYBRID_MERGE_COUNT > m_before, \
                "chunked hybrid scan dropped the order-preserving merge"
            assert executor.GROUPBY_SORT_SKIPPED > g_before, \
                "group-by re-sorted despite preserved bucket order"
            fact = pd.concat([env["fact"], extra], ignore_index=True)
            exp = fact[fact.k.between(0, 250)].groupby("k") \
                .agg(sv=("v", "sum")).reset_index()
            pd.testing.assert_frame_equal(
                got.sort_values("k").reset_index(drop=True),
                exp.sort_values("k").reset_index(drop=True),
                check_dtype=False)
        finally:
            session.conf.set(IndexConstants.TPU_MAX_CHUNK_ROWS,
                             IndexConstants.TPU_MAX_CHUNK_ROWS_DEFAULT)
