"""session.sql(): the SQL SELECT subset lowers onto the DataFrame IR —
answers match the equivalent DataFrame query (and pandas), and index
rewrites fire identically.
"""

import datetime

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col, sum_


@pytest.fixture()
def env(tmp_path):
    rng = np.random.default_rng(77)
    n = 1500
    d = tmp_path / "li"
    d.mkdir()
    pq.write_table(pa.Table.from_pandas(pd.DataFrame({
        "okey": rng.integers(0, 100, n).astype(np.int64),
        "qty": rng.integers(1, 50, n).astype(np.int64),
        "price": np.round(rng.uniform(1, 1000, n), 2),
        "flag": rng.choice(["A", "N", "R"], n),
        "ship": pd.to_datetime(
            rng.integers(9000, 9400, n), unit="D").date,
    })), d / "p0.parquet")
    d2 = tmp_path / "od"
    d2.mkdir()
    pq.write_table(pa.table({
        "okey2": pa.array(np.arange(100, dtype=np.int64)),
        "prio": pa.array(rng.choice(["HI", "LO"], 100)),
    }), d2 / "p0.parquet")
    session = hst.Session(system_path=str(tmp_path / "idx"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    session.create_temp_view("li", session.read.parquet(str(d)))
    session.create_temp_view("od", session.read.parquet(str(d2)))
    return session


class TestSelect:
    def test_star_where_order_limit(self, env):
        got = env.sql("SELECT * FROM li WHERE qty > 40 "
                      "ORDER BY okey, qty, price LIMIT 10").to_pandas()
        exp = (env.table("li").filter(col("qty") > 40)
               .sort("okey", "qty", "price").limit(10).to_pandas())
        pd.testing.assert_frame_equal(got, exp)

    def test_projection_arithmetic_alias(self, env):
        got = env.sql("SELECT okey, price * (1 + 0.1) AS taxed FROM li "
                      "WHERE okey = 3").to_pandas()
        exp = (env.table("li").filter(col("okey") == 3)
               .select(col("okey"), (col("price") * 1.1).alias("taxed"))
               .to_pandas())
        pd.testing.assert_frame_equal(got, exp)

    def test_date_literal_and_between(self, env):
        got = env.sql(
            "SELECT okey FROM li WHERE ship BETWEEN DATE '1994-09-01' "
            "AND DATE '1994-12-31'").count()
        d1, d2 = datetime.date(1994, 9, 1), datetime.date(1994, 12, 31)
        exp = env.table("li").filter(col("ship").between(d1, d2)).count()
        assert got == exp > 0

    def test_in_and_not_in(self, env):
        got = env.sql("SELECT okey FROM li WHERE flag IN ('A', 'R') "
                      "AND okey NOT IN (1, 2, 3)").count()
        exp = env.table("li").filter(
            col("flag").isin(["A", "R"])
            & ~col("okey").isin([1, 2, 3])).count()
        assert got == exp > 0

    def test_group_by_having_aggregates(self, env):
        got = env.sql(
            "SELECT flag, SUM(qty) AS total, COUNT(*) AS n, "
            "COUNT(DISTINCT okey) AS nd FROM li "
            "GROUP BY flag HAVING total > 100 ORDER BY flag").to_pandas()
        pdf = env.table("li").to_pandas()
        exp = (pdf.groupby("flag")
               .agg(total=("qty", "sum"), n=("qty", "size"),
                    nd=("okey", "nunique"))
               .reset_index().query("total > 100")
               .sort_values("flag").reset_index(drop=True))
        pd.testing.assert_frame_equal(got, exp, check_dtype=False)

    def test_global_aggregate(self, env):
        t = env.sql("SELECT SUM(price) AS sp, MIN(qty) AS lo, "
                    "MAX(qty) AS hi FROM li").to_arrow()
        pdf = env.table("li").to_pandas()
        assert t.column("sp").to_pylist() == [pytest.approx(pdf.price.sum())]
        assert t.column("lo").to_pylist() == [pdf.qty.min()]
        assert t.column("hi").to_pylist() == [pdf.qty.max()]

    def test_join(self, env):
        got = env.sql(
            "SELECT flag, SUM(price) AS rev FROM li "
            "JOIN od ON okey = okey2 WHERE prio = 'HI' "
            "GROUP BY flag ORDER BY flag").to_pandas()
        li, od = env.table("li"), env.table("od")
        exp = (li.join(od, on=col("okey") == col("okey2"))
               .filter(col("prio") == "HI")
               .group_by("flag").agg(sum_(col("price")).alias("rev"))
               .sort("flag").to_pandas())
        pd.testing.assert_frame_equal(got, exp)

    def test_left_join(self, env):
        got = env.sql("SELECT okey2, COUNT(okey) AS n FROM od "
                      "LEFT JOIN li ON okey2 = okey "
                      "GROUP BY okey2 ORDER BY okey2").to_pandas()
        assert len(got) == 100  # every od row survives


class TestSqlRewrite:
    def test_index_rewrite_fires_for_sql(self, env, tmp_path):
        hs = Hyperspace(env)
        hs.create_index(env.table("li"),
                        IndexConfig("sqlIdx", ["okey"], ["qty", "price"]))
        env.enable_hyperspace()
        q = env.sql("SELECT okey, qty FROM li WHERE okey < 50")
        assert "IndexScan" in q.optimized_plan().tree_string()
        a = q.to_pandas().sort_values(["okey", "qty"]).reset_index(drop=True)
        env.disable_hyperspace()
        b = q.to_pandas().sort_values(["okey", "qty"]).reset_index(drop=True)
        pd.testing.assert_frame_equal(a, b)


class TestSqlErrors:
    def test_unknown_view(self, env):
        with pytest.raises(HyperspaceException, match="temp view"):
            env.sql("SELECT * FROM ghost")

    def test_ungrouped_column_with_aggregate(self, env):
        with pytest.raises(HyperspaceException, match="GROUP BY"):
            env.sql("SELECT okey, SUM(qty) AS s FROM li GROUP BY flag")

    def test_star_with_aggregate(self, env):
        with pytest.raises(HyperspaceException, match="SELECT \\*"):
            env.sql("SELECT * FROM li GROUP BY flag")

    def test_garbage_token(self, env):
        # ';' became a legal token (verbatim TPC-H texts end in one), so
        # the untokenizable character here must be something the grammar
        # will never claim.
        with pytest.raises(HyperspaceException, match="tokenize"):
            env.sql("SELECT @ FROM li")

    def test_misplaced_semicolon(self, env):
        with pytest.raises(HyperspaceException, match="unexpected token"):
            env.sql("SELECT ; FROM li")

    def test_truncated_query(self, env):
        with pytest.raises(HyperspaceException, match="expected"):
            env.sql("SELECT okey FROM")


class TestSqlReviewRegressions:
    def test_group_by_case_insensitive(self, env):
        got = env.sql("SELECT FLAG, SUM(qty) AS s FROM li "
                      "GROUP BY flag ORDER BY flag").to_pandas()
        assert list(got.columns) == ["flag", "s"]
        assert len(got) == 3

    def test_select_order_and_hidden_group_cols(self, env):
        # Aggregate-only SELECT: the group column must NOT leak out.
        t = env.sql("SELECT SUM(qty) AS s FROM li GROUP BY flag").to_arrow()
        assert t.column_names == ["s"] and t.num_rows == 3
        # SELECT order is honored (agg before group col).
        t2 = env.sql("SELECT SUM(qty) AS s, flag FROM li "
                     "GROUP BY flag ORDER BY flag").to_arrow()
        assert t2.column_names == ["s", "flag"]

    def test_having_with_inline_aggregate(self, env):
        got = env.sql("SELECT flag FROM li GROUP BY flag "
                      "HAVING SUM(qty) > 100 ORDER BY flag").to_pandas()
        pdf = env.table("li").to_pandas()
        exp = (pdf.groupby("flag")["qty"].sum().reset_index()
               .query("qty > 100")["flag"]
               .sort_values().reset_index(drop=True))
        assert got["flag"].tolist() == exp.tolist()
        assert list(got.columns) == ["flag"]  # hidden agg projected away

    def test_unary_minus(self, env):
        assert env.sql("SELECT okey FROM li WHERE okey = -1").count() == 0
        got = env.sql("SELECT okey, price * -1 AS neg FROM li "
                      "WHERE okey IN (-5, 3) ORDER BY neg LIMIT 3").to_pandas()
        assert (got["neg"] <= 0).all()

    def test_limit_float_raises_cleanly(self, env):
        with pytest.raises(HyperspaceException, match="LIMIT"):
            env.sql("SELECT okey FROM li LIMIT 10.5")

    def test_scalar_subquery_qualified_aggregate(self, env):
        # The subquery's select item uses the subquery's own alias — it
        # must resolve exactly like qualified names in its WHERE do.
        got = env.sql(
            "SELECT okey, qty FROM li WHERE qty > "
            "(SELECT AVG(l2.qty) FROM li l2 WHERE l2.okey = li.okey) "
            "ORDER BY okey, qty").to_pandas()
        pdf = env.table("li").to_pandas()
        avg = pdf.groupby("okey")["qty"].mean()
        exp = pdf[pdf["qty"] > pdf["okey"].map(avg)][["okey", "qty"]] \
            .sort_values(["okey", "qty"]).reset_index(drop=True)
        pd.testing.assert_frame_equal(got, exp)

    def test_order_by_alias_after_derived_table(self, env):
        # A derived table in FROM runs the select parser re-entrantly; the
        # outer ORDER BY must still resolve against the OUTER aliases.
        got = env.sql(
            "SELECT o.prio, d.qty FROM (SELECT okey, qty FROM li) AS d "
            "JOIN od o ON okey = okey2 ORDER BY o.prio, d.qty LIMIT 5"
        ).to_pandas()
        assert list(got.columns) == ["prio", "qty"]
        assert (got["prio"].values == sorted(got["prio"].values)).all()

    def test_soft_keywords_usable_as_column_names(self, env, tmp_path):
        # YEAR/MONTH/DAY/TRIM/... are grammar words only in their special
        # positions; a table whose columns carry those names stays fully
        # reachable from SQL (Spark reserves almost nothing).
        d = tmp_path / "soft"
        d.mkdir()
        pq.write_table(pa.table({
            "year": pa.array([2024, 2025, 2025], type=pa.int64()),
            "trim": pa.array(["a", "b", "c"]),
        }), d / "p0.parquet")
        env.create_temp_view("soft", env.read.parquet(str(d)))
        got = env.sql("SELECT year, trim FROM soft WHERE year = 2025 "
                      "ORDER BY trim").to_pandas()
        assert got["year"].tolist() == [2025, 2025]
        assert got["trim"].tolist() == ["b", "c"]
        # GROUP BY a soft-keyword column, and alias one.
        g = env.sql("SELECT year, COUNT(*) AS c FROM soft GROUP BY year "
                    "ORDER BY year").to_pandas()
        assert g["c"].tolist() == [1, 2]
        a = env.sql("SELECT okey AS month FROM li LIMIT 1").to_pandas()
        assert list(a.columns) == ["month"]
        # ...while the special positions still work.
        y = env.sql("SELECT EXTRACT(YEAR FROM ship) AS y FROM li LIMIT 1")
        assert y.to_pandas()["y"].iloc[0] >= 1994

    def test_limit_negative_rejected(self, env):
        # SUBSTRING made _int_literal sign-aware; LIMIT must still reject.
        with pytest.raises(HyperspaceException, match="non-negative"):
            env.sql("SELECT okey FROM li LIMIT -5")

    def test_like_matches_across_newlines(self, env, tmp_path):
        d = tmp_path / "nl"
        d.mkdir()
        pq.write_table(pa.table({"s": pa.array(["line1\nline2", "other"])}),
                       d / "p0.parquet")
        env.create_temp_view("nl", env.read.parquet(str(d)))
        got = env.sql("SELECT s FROM nl WHERE s LIKE '%line2'").to_pandas()
        assert got["s"].tolist() == ["line1\nline2"]

    def test_substring_negative_start_counts_from_end(self, env):
        # Spark/Hive substr(-2, 2) takes the LAST two characters.
        got = env.sql("SELECT DISTINCT SUBSTRING(prio, -1, 1) AS t "
                      "FROM od ORDER BY t").to_pandas()
        assert got["t"].tolist() == ["I", "O"]  # HI / LO
        from hyperspace_tpu.plan.expr import col
        df = env.table("od").select(
            col("prio").substr(-2, 2).alias("whole"),
            col("prio").substr(-5, 4).alias("virt"),
        ).to_pandas()
        assert set(df["whole"]) == {"HI", "LO"}
        # Virtual start before the beginning consumes length: the window
        # [-3, 1) clamps to one visible char.
        assert set(df["virt"]) == {"H", "L"}

    def test_cast_folds_or_errors_clearly(self, env):
        got = env.sql("SELECT okey FROM li WHERE okey = CAST('3' AS INT) "
                      "LIMIT 1").to_pandas()
        assert got["okey"].tolist() == [3]
        # DECIMAL(p,s) is accepted as a float64 identity (the TPC-DS house
        # style); other parameterized targets still error clearly.
        d = env.sql("SELECT CAST(price AS DECIMAL(7,2)) p FROM li LIMIT 1") \
            .to_pandas()
        assert len(d) == 1
        with pytest.raises(HyperspaceException, match="CHAR"):
            env.sql("SELECT CAST(price AS CHAR(16)) FROM li")
        with pytest.raises(HyperspaceException, match="does not convert"):
            env.sql("SELECT okey FROM li WHERE okey = CAST('x' AS INT)")

    def test_order_by_expression_restates_select_item(self, env):
        got = env.sql(
            "SELECT okey, price * qty AS total FROM li "
            "ORDER BY price * qty DESC LIMIT 5").to_pandas()
        assert (got["total"].values == sorted(got["total"], reverse=True)
                ).all()
        g2 = env.sql("SELECT flag, SUM(qty) FROM li GROUP BY flag "
                     "ORDER BY SUM(qty) DESC").to_pandas()
        assert g2.iloc[0, 1] == g2.iloc[:, 1].max()
        # An ORDER BY expression that does NOT restate a select item is
        # materialized as a hidden sort column (the TPC-DS q89 shape) —
        # the result is sorted by it and does not expose it.
        g3 = env.sql("SELECT okey FROM li ORDER BY okey + 1").to_pandas()
        assert list(g3.columns) == ["okey"]
        assert g3["okey"].is_monotonic_increasing

    def test_case_else_null_equals_no_else(self, env):
        a = env.sql("SELECT SUM(CASE WHEN flag = 'A' THEN qty ELSE NULL "
                    "END) AS s FROM li").to_pandas()
        b = env.sql("SELECT SUM(CASE WHEN flag = 'A' THEN qty END) AS s "
                    "FROM li").to_pandas()
        assert a["s"][0] == b["s"][0]

    def test_group_by_expression(self, env, tmp_path):
        d = tmp_path / "gz"
        d.mkdir()
        pq.write_table(pa.table({
            "zip": pa.array(["85669a", "85669b", "10001x"]),
            "v": pa.array([1, 2, 3])}), d / "p0.parquet")
        env.create_temp_view("gz", env.read.parquet(str(d)))
        # The q8 shadow shape: the expression's alias reuses the source
        # column name; the expression still reads the original.
        r = env.sql("SELECT substr(zip,1,5) AS zip, SUM(v) AS sv FROM gz "
                    "GROUP BY substr(zip,1,5) ORDER BY zip").to_pandas()
        assert r["zip"].tolist() == ["10001", "85669"]
        assert r["sv"].tolist() == [3, 3]
        # Duplicate keys are redundant, arithmetic group keys work, and
        # an aggregate over a shadowed column refuses clearly.
        r2 = env.sql("SELECT v + v AS d, COUNT(*) AS c FROM gz "
                     "GROUP BY v + v, v + v ORDER BY d").to_pandas()
        assert r2["d"].tolist() == [2, 4, 6]
        with pytest.raises(HyperspaceException, match="shadowed"):
            env.sql("SELECT substr(zip,1,5) AS zip, COUNT(zip) FROM gz "
                    "GROUP BY substr(zip,1,5)")
        with pytest.raises(HyperspaceException, match="restate"):
            env.sql("SELECT v FROM gz GROUP BY v + 1")

    def test_backtick_aliases(self, env):
        r = env.sql("SELECT SUM(qty) AS `total qty ` FROM li").to_pandas()
        assert list(r.columns) == ["total qty "]

    def test_mid_statement_semicolon_rejected(self, env):
        # ';' is legal only as a trailing terminator — never silently
        # dropped mid-statement (that would splice two statements).
        with pytest.raises(HyperspaceException, match="';'"):
            env.sql("SELECT okey FROM li; ORDER BY okey")
        assert env.sql("SELECT okey FROM li LIMIT 1;").count() == 1


class TestSqlDistinctUnionDerived:
    def test_select_distinct(self, env):
        got = env.sql("SELECT DISTINCT flag FROM li ORDER BY flag") \
            .to_pandas()
        assert got["flag"].tolist() == ["A", "N", "R"]

    def test_union_all(self, env):
        n = env.sql("SELECT okey FROM li WHERE okey < 10 "
                    "UNION ALL SELECT okey FROM li WHERE okey >= 90").count()
        pdf = env.table("li").to_pandas()
        assert n == int((pdf.okey < 10).sum() + (pdf.okey >= 90).sum())

    def test_derived_table(self, env):
        got = env.sql(
            "SELECT flag, total FROM "
            "(SELECT flag, SUM(qty) AS total FROM li GROUP BY flag) t "
            "WHERE total > 100 ORDER BY flag").to_pandas()
        pdf = env.table("li").to_pandas()
        exp = (pdf.groupby("flag")["qty"].sum().rename("total")
               .reset_index().query("total > 100")
               .sort_values("flag").reset_index(drop=True))
        pd.testing.assert_frame_equal(got, exp, check_dtype=False)

    def test_join_with_derived_table(self, env):
        got = env.sql(
            "SELECT prio, COUNT(*) AS n FROM "
            "(SELECT okey FROM li WHERE qty > 45) h "
            "JOIN od ON okey = okey2 GROUP BY prio ORDER BY prio") \
            .to_pandas()
        assert set(got["prio"]) <= {"HI", "LO"} and got["n"].sum() > 0

    def test_order_limit_bind_to_whole_union(self, env):
        got = env.sql(
            "SELECT okey FROM li WHERE okey < 5 "
            "UNION ALL SELECT okey FROM li WHERE okey >= 95 "
            "ORDER BY okey DESC LIMIT 4").to_pandas()
        # Sorted over the WHOLE union: the top values come from the
        # second branch only, descending.
        assert (got["okey"] >= 95).all()
        assert got["okey"].is_monotonic_decreasing and len(got) == 4

    def test_union_inside_derived_table(self, env):
        n = env.sql(
            "SELECT okey FROM "
            "(SELECT okey FROM li WHERE okey < 5 "
            " UNION ALL SELECT okey FROM li WHERE okey >= 95) u "
            "WHERE okey <> 0").count()
        pdf = env.table("li").to_pandas()
        assert n == int(((pdf.okey < 5) & (pdf.okey != 0)).sum()
                        + (pdf.okey >= 95).sum())

    def test_group_column_alias_kept(self, env):
        t = env.sql("SELECT flag AS f, SUM(qty) AS s FROM li "
                    "GROUP BY flag ORDER BY f").to_arrow()
        assert t.column_names == ["f", "s"]
