"""Temp views + case-sensitivity conf.

Parity targets: the reference's E2E suite queries indexed data through
views (E2EHyperspaceRulesTest), and its column resolution honors Spark's
spark.sql.caseSensitive (ResolverUtils; default insensitive). Here views
are session-registered plans and the conf key is hyperspace.caseSensitive.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col
from hyperspace_tpu.plan.nodes import IndexScan


@pytest.fixture()
def env(tmp_path):
    rng = np.random.default_rng(21)
    df = pd.DataFrame({
        "Key": rng.integers(0, 200, 10_000).astype(np.int64),
        "Val": rng.random(10_000),
    })
    d = tmp_path / "data"
    d.mkdir()
    pq.write_table(pa.Table.from_pandas(df), d / "p.parquet")
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    return dict(session=session, hs=Hyperspace(session), path=str(d), df=df)


class TestTempViews:
    def test_view_roundtrip_and_drop(self, env):
        session = env["session"]
        t = session.read.parquet(env["path"])
        session.create_temp_view("v1", t)
        got = session.table("V1").to_pandas()  # names case-insensitive
        assert len(got) == len(env["df"])
        assert session.drop_temp_view("v1")
        assert not session.drop_temp_view("v1")
        with pytest.raises(HyperspaceException, match="No such temp view"):
            session.table("v1")

    def test_duplicate_view_requires_replace(self, env):
        session = env["session"]
        t = session.read.parquet(env["path"])
        session.create_temp_view("v", t)
        with pytest.raises(HyperspaceException, match="already exists"):
            session.create_temp_view("v", t)
        session.create_temp_view("v", t.select("Key"), replace=True)
        assert session.table("v").to_pandas().columns.tolist() == ["Key"]

    def test_index_used_through_view(self, env):
        """The reference's view test: a query written against the view is
        rewritten to the index built on the underlying data, and answers
        match the no-index run."""
        session, hs, df = env["session"], env["hs"], env["df"]
        t = session.read.parquet(env["path"])
        hs.create_index(t, IndexConfig("view_idx", ["Key"], ["Val"]))
        session.create_temp_view("sales", t)
        session.enable_hyperspace()
        q = session.table("sales").filter(col("Key") == 7).select("Key", "Val")
        leaves = q.optimized_plan().collect_leaves()
        assert len(leaves) == 1 and isinstance(leaves[0], IndexScan)
        got = q.to_pandas().sort_values(["Key", "Val"]).reset_index(drop=True)
        session.disable_hyperspace()
        raw = q.to_pandas().sort_values(["Key", "Val"]).reset_index(drop=True)
        pd.testing.assert_frame_equal(got, raw)
        assert len(got) == (df.Key == 7).sum()


class TestCaseSensitivity:
    def test_insensitive_by_default(self, env):
        session, hs = env["session"], env["hs"]
        t = session.read.parquet(env["path"])
        # Physical columns are "Key"/"Val"; config names differ in case.
        hs.create_index(t, IndexConfig("ci_idx", ["key"], ["VAL"]))
        row = hs.index("ci_idx").iloc[0]
        assert list(row["indexedColumns"]) == ["Key"]  # resolved to physical
        assert list(row["includedColumns"]) == ["Val"]

    def test_sensitive_mode_rejects_wrong_case(self, env):
        session, hs = env["session"], env["hs"]
        session.conf.set(IndexConstants.CASE_SENSITIVE, "true")
        t = session.read.parquet(env["path"])
        with pytest.raises(HyperspaceException):
            hs.create_index(t, IndexConfig("cs_idx", ["key"], ["Val"]))
        hs.create_index(t, IndexConfig("cs_idx", ["Key"], ["Val"]))
        assert list(hs.index("cs_idx").iloc[0]["indexedColumns"]) == ["Key"]

    def test_sensitive_mode_skipping_sketch(self, env):
        session, hs = env["session"], env["hs"]
        from hyperspace_tpu.api import DataSkippingIndexConfig, MinMaxSketch
        session.conf.set(IndexConstants.CASE_SENSITIVE, "true")
        t = session.read.parquet(env["path"])
        with pytest.raises(HyperspaceException):
            hs.create_index(t, DataSkippingIndexConfig(
                "sk_idx", [MinMaxSketch("KEY")]))
