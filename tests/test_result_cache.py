"""Serving-layer result cache (serving/result_cache.py, fingerprint.py).

Covers the subsystem's contract end to end: key canonicalization, the
two-tier byte-budgeted LRU (device -> host demotion, host eviction), the
admission policy, correctness-first invalidation (refreshIndex / source
changes make stale keys unreachable by construction), the SQL plan memo,
explain surfacing, and the TPC-DS acceptance scenario (repeated query is
byte-identical with a recorded hit; refresh/append cause a miss and a
recompute).
"""

import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col
from hyperspace_tpu.serving.constants import ServingConstants
from hyperspace_tpu.serving.fingerprint import ResultCacheKey, compute_key
from hyperspace_tpu.serving.result_cache import ResultCache, table_nbytes


def _write(d, n=4000, seed=7, name="p0.parquet", k_mod=50):
    rng = np.random.default_rng(seed)
    df = pd.DataFrame({
        "k": rng.integers(0, k_mod, n).astype(np.int64),
        "v": rng.integers(0, 9, n).astype(np.int64),
    })
    os.makedirs(d, exist_ok=True)
    pq.write_table(pa.Table.from_pandas(df), os.path.join(str(d), name))
    return df


def _session(tmp_path, enabled=True):
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    if enabled:
        session.conf.set(ServingConstants.RESULT_CACHE_ENABLED, "true")
        session.conf.set(
            ServingConstants.RESULT_CACHE_MIN_COMPUTE_SECONDS, "0")
    return session


def _host_table(n=64, fill=1):
    """A small host-side Table for unit-level cache entries."""
    from hyperspace_tpu.execution.columnar import Table
    return Table.from_arrow(pa.table(
        {"x": pa.array(np.full(n, fill, np.int64))}))


def _key(tag):
    return ResultCacheKey(f"plan-{tag}", f"src-{tag}", (), "conf")


class TestResultCacheUnit:
    def test_lru_demotes_to_host_then_evicts(self):
        t = _host_table()
        nbytes = table_nbytes(t)
        evicted = []
        cache = ResultCache(device_bytes=2 * nbytes,
                            host_bytes=2 * nbytes,
                            on_evict=lambda *a: evicted.append(a))
        assert cache.put(_key(1), t) == "device"
        assert cache.put(_key(2), t) == "device"
        # Third entry overflows the device tier: key 1 (LRU) demotes.
        assert cache.put(_key(3), t) == "device"
        assert cache.peek(_key(1)) == "host"
        assert cache.stats()["demotions"] == 1
        # Two more: host tier overflows too; the oldest host entry dies.
        cache.put(_key(4), t)
        cache.put(_key(5), t)
        s = cache.stats()
        assert s["evictions"] >= 1
        assert s["device_nbytes"] <= cache.device_bytes
        assert s["host_nbytes"] <= cache.host_bytes
        assert any(a[0] == "host" for a in evicted)
        assert any(a[0] == "device" and a[2] for a in evicted)  # demotions

    def test_get_promotes_recency_and_counts_tiers(self):
        t = _host_table()
        cache = ResultCache(2 * table_nbytes(t), 10 * table_nbytes(t))
        cache.put(_key("a"), t)
        cache.put(_key("b"), t)
        assert cache.get(_key("a"))[1] == "device"  # 'a' now MRU
        cache.put(_key("c"), t)                     # demotes 'b', not 'a'
        assert cache.peek(_key("a")) == "device"
        assert cache.peek(_key("b")) == "host"
        _, tier = cache.get(_key("b"))
        assert tier == "host"
        s = cache.stats()
        assert s["device_hits"] == 1 and s["host_hits"] == 1
        assert cache.get(_key("zzz")) is None
        assert cache.stats()["misses"] == 1

    def test_device_victim_without_host_room_is_evicted(self):
        """hostBytes=0 disables the spill tier: device victims must be
        counted (and reported) as evictions, not as demotions."""
        t = _host_table()
        n = table_nbytes(t)
        evicted = []
        cache = ResultCache(device_bytes=2 * n, host_bytes=0,
                            on_evict=lambda *a: evicted.append(a))
        cache.put(_key(1), t)
        cache.put(_key(2), t)
        cache.put(_key(3), t)
        s = cache.stats()
        assert s["demotions"] == 0 and s["evictions"] == 1
        assert s["host_entries"] == 0
        assert evicted == [("device", n, False)]

    def test_oversized_entry_not_admitted(self):
        t = _host_table(n=4096)
        cache = ResultCache(device_bytes=16, host_bytes=16)
        assert cache.put(_key("big"), t) is None
        assert cache.stats()["admissions"] == 0

    def test_clear_empties_both_tiers(self):
        t = _host_table()
        cache = ResultCache(10 * table_nbytes(t), 10 * table_nbytes(t))
        cache.put(_key(1), t)
        cache.clear()
        s = cache.stats()
        assert s["device_entries"] == s["host_entries"] == 0
        assert s["device_nbytes"] == s["host_nbytes"] == 0


class TestKeyDerivation:
    def test_syntactic_variants_share_fingerprint(self, tmp_path):
        _write(tmp_path / "d")
        session = _session(tmp_path)
        df = session.read.parquet(str(tmp_path / "d"))
        a = df.filter(col("k") == 3).select("k", "v")
        b = df.select("k", "v").filter(col("k") == 3)
        ka = compute_key(session, a.plan)
        kb = compute_key(session, b.plan)
        assert ka is not None and ka == kb

    def test_different_predicates_differ(self, tmp_path):
        _write(tmp_path / "d")
        session = _session(tmp_path)
        df = session.read.parquet(str(tmp_path / "d"))
        ka = compute_key(session, df.filter(col("k") == 3).plan)
        kb = compute_key(session, df.filter(col("k") == 4).plan)
        assert ka != kb

    def test_conf_and_enable_flag_flip_key(self, tmp_path):
        _write(tmp_path / "d")
        session = _session(tmp_path)
        df = session.read.parquet(str(tmp_path / "d"))
        k1 = compute_key(session, df.plan)
        session.enable_hyperspace()
        k2 = compute_key(session, df.plan)
        assert k1 != k2  # the rewrite batch can change row order
        session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 8)
        assert compute_key(session, df.plan) != k2

    def test_source_file_change_flips_signature(self, tmp_path):
        _write(tmp_path / "d")
        session = _session(tmp_path)
        df = session.read.parquet(str(tmp_path / "d"))
        k1 = compute_key(session, df.plan)
        # In-place rewrite of a pinned file (different content => size).
        _write(tmp_path / "d", n=4100, seed=8)
        k2 = compute_key(session, df.plan)
        assert k1.source_signature != k2.source_signature

    def test_unknown_node_is_uncacheable(self, tmp_path):
        from hyperspace_tpu.plan.nodes import LogicalPlan
        from hyperspace_tpu.schema import Schema

        class Odd(LogicalPlan):
            @property
            def schema(self):
                return Schema([])

        _write(tmp_path / "d")
        session = _session(tmp_path)
        assert compute_key(session, Odd()) is None


class TestIntegration:
    def test_default_off_and_identical_answers(self, tmp_path):
        _write(tmp_path / "d")
        session = _session(tmp_path, enabled=False)
        assert session.result_cache is None
        df = session.read.parquet(str(tmp_path / "d"))
        q = df.filter(col("k") < 10).select("k", "v")
        off = q.to_pandas()
        session.conf.set(ServingConstants.RESULT_CACHE_ENABLED, "true")
        session.conf.set(
            ServingConstants.RESULT_CACHE_MIN_COMPUTE_SECONDS, "0")
        assert session.result_cache is not None
        on_miss = q.to_pandas()
        on_hit = q.to_pandas()
        pd.testing.assert_frame_equal(off, on_miss)
        pd.testing.assert_frame_equal(off, on_hit)
        s = session.result_cache.stats()
        assert s["hits"] == 1 and s["misses"] == 1

    def test_hit_is_byte_identical_arrow(self, tmp_path):
        _write(tmp_path / "d")
        session = _session(tmp_path)
        q = session.read.parquet(str(tmp_path / "d")) \
            .filter(col("k") == 1).select("k", "v")
        first = q.to_arrow()
        again = q.to_arrow()
        assert session.result_cache.stats()["hits"] == 1
        assert first.equals(again)

    def test_admission_thresholds_reject(self, tmp_path):
        _write(tmp_path / "d")
        session = _session(tmp_path)
        session.conf.set(
            ServingConstants.RESULT_CACHE_MIN_COMPUTE_SECONDS, "1e6")
        q = session.read.parquet(str(tmp_path / "d")).filter(col("k") == 1)
        q.to_pandas()
        s = session.result_cache.stats()
        assert s["admissions"] == 0 and s["rejections"] == 1
        # Input-byte floor rejects too (tiny source).
        session.conf.set(
            ServingConstants.RESULT_CACHE_MIN_COMPUTE_SECONDS, "0")
        session.conf.set(
            ServingConstants.RESULT_CACHE_MIN_INPUT_BYTES, str(1 << 50))
        q.to_pandas()
        s = session.result_cache.stats()
        assert s["admissions"] == 0 and s["rejections"] == 2

    def test_served_from_host_tier_after_demotion(self, tmp_path):
        _write(tmp_path / "d")
        session = _session(tmp_path)
        q1 = session.read.parquet(str(tmp_path / "d")).filter(col("k") == 1)
        one = q1.to_pandas()
        cache = session.result_cache
        nbytes = cache.stats()["device_nbytes"]
        assert nbytes > 0
        # Shrink the device budget below one entry... by reconfiguring:
        # budget changes rebuild the cache, so refill it instead.
        session.conf.set(
            ServingConstants.RESULT_CACHE_DEVICE_BYTES, str(nbytes))
        cache = session.result_cache
        q1.to_pandas()  # miss (fresh cache) + admit
        # Same rows, reversed projection: an equal-sized second entry
        # under a different key — it fits the device tier and pushes the
        # first entry out (demotion, not eviction).
        q2 = session.read.parquet(str(tmp_path / "d")) \
            .filter(col("k") == 1).select("v", "k")
        q2.to_pandas()  # second entry demotes the first to host
        assert cache.stats()["demotions"] == 1
        served = q1.to_pandas()
        assert cache.stats()["host_hits"] == 1
        pd.testing.assert_frame_equal(served, one)

    def test_threshold_tuning_keeps_warm_entries(self, tmp_path):
        """Admission floors are read live and are NOT part of the cache
        key or instance identity: tuning them must not drop (or orphan)
        warm entries."""
        _write(tmp_path / "d")
        session = _session(tmp_path)
        q = session.read.parquet(str(tmp_path / "d")).filter(col("k") == 1)
        q.to_pandas()
        cache = session.result_cache
        session.conf.set(
            ServingConstants.RESULT_CACHE_MIN_COMPUTE_SECONDS, "999")
        assert session.result_cache is cache  # instance survives
        q.to_pandas()
        assert cache.stats()["hits"] == 1  # warm entry still reachable

    def test_budget_change_rebuilds_cache(self, tmp_path):
        _write(tmp_path / "d")
        session = _session(tmp_path)
        first = session.result_cache
        session.conf.set(
            ServingConstants.RESULT_CACHE_DEVICE_BYTES, str(1 << 20))
        assert session.result_cache is not first

    def test_refresh_index_invalidates(self, tmp_path):
        _write(tmp_path / "d")
        session = _session(tmp_path)
        hs = Hyperspace(session)
        df = session.read.parquet(str(tmp_path / "d"))
        hs.create_index(df, IndexConfig("rcIdx", ["k"], ["v"]))
        session.enable_hyperspace()
        q = df.filter(col("k") == 3).select("k", "v")
        q.to_pandas()
        q.to_pandas()
        cache = session.result_cache
        assert cache.stats()["hits"] == 1
        # A refresh over an UNCHANGED source is a recorded no-op (the
        # action protocol's NoChangesException): the index state is
        # byte-identical, so serving the cached result stays correct.
        hs.refresh_index("rcIdx", "full")
        q.to_pandas()
        assert cache.stats()["hits"] == 2
        # A real refresh (source grew) writes new log entries: the key
        # component pinning the log state flips and the query recomputes.
        _write(tmp_path / "d", n=300, seed=11, name="extra.parquet")
        hs.refresh_index("rcIdx", "full")
        misses = cache.stats()["misses"]
        q.to_pandas()  # pinned file list, but new index state => miss
        assert cache.stats()["misses"] == misses + 1

    def test_source_append_with_fresh_relation_misses(self, tmp_path):
        base = _write(tmp_path / "d")
        session = _session(tmp_path)
        q = session.read.parquet(str(tmp_path / "d")) \
            .filter(col("k") == 3).select("k", "v")
        expected = int((base.k == 3).sum())
        assert len(q.to_pandas()) == expected
        _write(tmp_path / "d", n=200, seed=9, name="extra.parquet", k_mod=4)
        fresh = session.read.parquet(str(tmp_path / "d")) \
            .filter(col("k") == 3).select("k", "v")
        got = len(fresh.to_pandas())
        assert got > expected  # new rows visible: the cache did not serve
        assert session.result_cache.stats()["hits"] == 0


class TestSqlPlanCache:
    def test_sql_plan_memo_hits_and_view_invalidation(self, tmp_path):
        _write(tmp_path / "d")
        session = _session(tmp_path)
        session.create_temp_view(
            "t", session.read.parquet(str(tmp_path / "d")))
        text = "SELECT k, COUNT(*) AS n FROM t GROUP BY k ORDER BY k"
        a = session.sql(text).to_pandas()
        b = session.sql(text).to_pandas()
        assert session._sql_plan_stats == {"hits": 1, "misses": 1}
        pd.testing.assert_frame_equal(a, b)
        # Replacing the view flips the registry version: re-lowered.
        session.create_temp_view(
            "t", session.read.parquet(str(tmp_path / "d")), replace=True)
        session.sql(text)
        assert session._sql_plan_stats["misses"] == 2

    def test_sql_plan_memo_off_without_result_cache(self, tmp_path):
        _write(tmp_path / "d")
        session = _session(tmp_path, enabled=False)
        session.create_temp_view(
            "t", session.read.parquet(str(tmp_path / "d")))
        session.sql("SELECT k FROM t")
        session.sql("SELECT k FROM t")
        assert session._sql_plan_stats == {"hits": 0, "misses": 0}


class TestObservability:
    def test_explain_section_gated_and_reports_hit(self, tmp_path):
        _write(tmp_path / "d")
        off = _session(tmp_path, enabled=False)
        hs_off = Hyperspace(off)
        q_off = off.read.parquet(str(tmp_path / "d")).filter(col("k") == 1)
        assert "Result cache:" not in hs_off.explain(q_off)

        session = _session(tmp_path)
        hs = Hyperspace(session)
        q = session.read.parquet(str(tmp_path / "d")).filter(col("k") == 1)
        text = hs.explain(q)
        assert "Result cache:" in text
        assert "miss - result will be computed" in text
        assert "index table cache:" in text
        q.to_pandas()
        text = hs.explain(q)
        assert "result served from cache (device tier" in text

    def test_stats_facade_and_clear(self, tmp_path):
        _write(tmp_path / "d")
        session = _session(tmp_path)
        hs = Hyperspace(session)
        q = session.read.parquet(str(tmp_path / "d")).filter(col("k") == 1)
        q.to_pandas()
        q.to_pandas()
        stats = hs.result_cache_stats()
        assert stats["result_cache"]["hits"] == 1
        assert "index_table_cache" in stats
        hs.clear_result_cache()
        assert hs.result_cache_stats()["result_cache"]["device_entries"] == 0


@pytest.fixture(scope="module")
def tpcds(tmp_path_factory):
    """TPC-DS acceptance harness: real query texts over the mini catalog,
    with the q3-family covering indexes and the result cache enabled."""
    from goldstandard import tpcds_real

    root = tmp_path_factory.mktemp("tpcds_result_cache")
    session = hst.Session(system_path=str(root / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    session.conf.set(ServingConstants.RESULT_CACHE_ENABLED, "true")
    session.conf.set(ServingConstants.RESULT_CACHE_MIN_COMPUTE_SECONDS, "0")
    tpcds_real.register_tables(session, str(root / "data"))
    hs = Hyperspace(session)
    for table, cfg in tpcds_real.index_configs():
        if cfg.index_name in ("ds_dd_sk", "ds_ss_date"):
            hs.create_index(session.table(table), cfg)
    session.enable_hyperspace()
    return dict(session=session, hs=hs, root=root,
                text=tpcds_real.QUERY_TEXTS["tpcds_real_q3"])


class TestTpcdsAcceptance:
    def test_repeated_query_hits_byte_identical(self, tpcds):
        session, hs = tpcds["session"], tpcds["hs"]
        first = session.sql(tpcds["text"]).to_arrow()
        before = session.result_cache.stats()["hits"]
        again = session.sql(tpcds["text"]).to_arrow()
        assert session.result_cache.stats()["hits"] == before + 1
        assert first.equals(again)  # byte-identical service
        # And equals the cache-off answer (disable-and-compare oracle).
        session.conf.set(ServingConstants.RESULT_CACHE_ENABLED, "false")
        off = session.sql(tpcds["text"]).to_arrow()
        session.conf.set(ServingConstants.RESULT_CACHE_ENABLED, "true")
        assert first.equals(off)

    def test_refresh_index_causes_miss_and_recompute(self, tpcds):
        session, hs = tpcds["session"], tpcds["hs"]
        root = tpcds["root"]
        session.sql(tpcds["text"]).to_arrow()
        # Grow the indexed source so the refresh is not a recorded no-op.
        dd_dir = os.path.join(str(root / "data"), "date_dim")
        dd = pq.read_table(os.path.join(dd_dir, "part0.parquet"))
        pq.write_table(dd.slice(0, 10),
                       os.path.join(dd_dir, "part_extra.parquet"))
        hs.refresh_index("ds_dd_sk", "full")
        cache = session.result_cache
        hits, misses = cache.stats()["hits"], cache.stats()["misses"]
        session.sql(tpcds["text"]).to_arrow()
        s = cache.stats()
        assert s["misses"] == misses + 1 and s["hits"] == hits

    def test_source_append_causes_miss_with_fresh_answer(self, tpcds):
        session = tpcds["session"]
        root = tpcds["root"]
        base = session.sql(tpcds["text"]).to_pandas()
        # Append to store_sales and re-register the view (the serving
        # refresh pattern; a view pins its relation's file snapshot).
        ss_dir = os.path.join(str(root / "data"), "store_sales")
        existing = pq.read_table(
            os.path.join(ss_dir, "part0.parquet")).to_pandas()
        pq.write_table(
            pa.Table.from_pandas(existing.head(200)),
            os.path.join(ss_dir, "part1.parquet"))
        session.create_temp_view(
            "store_sales", session.read.parquet(ss_dir), replace=True)
        cache = session.result_cache
        hits, misses = cache.stats()["hits"], cache.stats()["misses"]
        fresh = session.sql(tpcds["text"]).to_pandas()
        s = cache.stats()
        assert s["hits"] == hits  # no stale hit served
        assert s["misses"] == misses + 1  # recomputed
        # The recompute matches a cache-off run of the same session —
        # the no-staleness oracle (base itself may or may not change
        # depending on which rows the append duplicated).
        session.conf.set(ServingConstants.RESULT_CACHE_ENABLED, "false")
        off = session.sql(tpcds["text"]).to_pandas()
        session.conf.set(ServingConstants.RESULT_CACHE_ENABLED, "true")
        pd.testing.assert_frame_equal(
            fresh.reset_index(drop=True), off.reset_index(drop=True))
        assert len(base) > 0
