"""Op-log optimistic-concurrency races (VERDICT r2 #8).

The reference's protocol: ``writeLog`` creates a temp file and atomically
renames it, refusing to overwrite an existing id
(index/IndexLogManager.scala:168-184); racing actions detect the conflict
when their begin() write fails (actions/Action.scala:80). These tests race
real OS processes on one log id and whole create actions on one index name
— exactly one writer may win each.
"""

import multiprocessing as mp
import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu.index.constants import States
from hyperspace_tpu.index.log_manager import IndexLogManager

from test_log_entry import make_entry


def _racer_write(index_path, log_id, worker, q):
    """Child process: try to claim one log id; report whether we won."""
    mgr = IndexLogManager(index_path)
    entry = make_entry(name=f"worker{worker}")
    q.put((worker, mgr.write_log(log_id, entry)))


class TestLogIdRaces:
    @pytest.mark.parametrize("n_writers", [2, 8])
    def test_exactly_one_writer_wins_id(self, tmp_path, n_writers):
        index_path = str(tmp_path / "idx")
        os.makedirs(index_path)
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        procs = [ctx.Process(target=_racer_write,
                             args=(index_path, 1, w, q))
                 for w in range(n_writers)]
        for p in procs:
            p.start()
        # Generous timeouts: each spawned child pays a full jax import,
        # and the suite may share the machine with a bench run.
        results = [q.get(timeout=300) for _ in procs]
        for p in procs:
            p.join(timeout=300)
        winners = [w for w, ok in results if ok]
        assert len(winners) == 1, f"{len(winners)} writers claimed id 1"
        # The surviving entry is the winner's, intact.
        entry = IndexLogManager(index_path).get_log(1)
        assert entry is not None
        assert entry.name == f"worker{winners[0]}"

    def test_sequential_ids_all_win(self, tmp_path):
        """Writers on DISTINCT ids never conflict."""
        index_path = str(tmp_path / "idx")
        os.makedirs(index_path)
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        procs = [ctx.Process(target=_racer_write,
                             args=(index_path, i, i, q))
                 for i in range(1, 5)]
        for p in procs:
            p.start()
        results = [q.get(timeout=60) for _ in procs]
        for p in procs:
            p.join(timeout=60)
        assert all(ok for _, ok in results)
        mgr = IndexLogManager(index_path)
        assert mgr.get_latest_id() == 4

    def test_loser_can_retry_at_next_id(self, tmp_path):
        index_path = str(tmp_path / "idx")
        os.makedirs(index_path)
        mgr_a = IndexLogManager(index_path)
        mgr_b = IndexLogManager(index_path)
        assert mgr_a.write_log(1, make_entry(name="a"))
        assert not mgr_b.write_log(1, make_entry(name="b"))
        assert mgr_b.write_log(2, make_entry(name="b"))
        assert mgr_a.get_latest_log().name == "b"


def _racer_create(root, worker, q):
    """Child process: race a full createIndex on one shared index name.
    Exactly one action may commit; losers surface a conflict error."""
    import jax
    jax.config.update("jax_platforms", "cpu")

    import hyperspace_tpu as hst
    from hyperspace_tpu.api import Hyperspace, IndexConfig

    session = hst.Session(system_path=os.path.join(root, "indexes"))
    hs = Hyperspace(session)
    df = session.read.parquet(os.path.join(root, "data"))
    try:
        hs.create_index(df, IndexConfig("racedIdx", ["k"], ["v"]))
        q.put((worker, "ok", None))
    except Exception as e:
        q.put((worker, "err", type(e).__name__))


class TestCreateActionRaces:
    def test_concurrent_create_same_name(self, tmp_path):
        rng = np.random.default_rng(0)
        data_dir = tmp_path / "data"
        data_dir.mkdir()
        pq.write_table(pa.Table.from_pandas(pd.DataFrame({
            "k": rng.integers(0, 50, 500).astype(np.int64),
            "v": rng.integers(0, 10, 500).astype(np.int64),
        })), data_dir / "p.parquet")
        (tmp_path / "indexes").mkdir()
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        procs = [ctx.Process(target=_racer_create,
                             args=(str(tmp_path), w, q)) for w in range(3)]
        for p in procs:
            p.start()
        results = [q.get(timeout=300) for _ in procs]
        for p in procs:
            p.join(timeout=300)
        oks = [w for w, status, _ in results if status == "ok"]
        assert len(oks) == 1, f"{len(oks)} concurrent creates committed: {results}"

        # The committed index is usable and ACTIVE.
        import jax
        jax.config.update("jax_platforms", "cpu")
        import hyperspace_tpu as hst
        from hyperspace_tpu.api import Hyperspace

        session = hst.Session(system_path=str(tmp_path / "indexes"))
        hs = Hyperspace(session)
        listing = hs.indexes()
        row = listing[listing["name"] == "racedIdx"]
        assert len(row) == 1 and row.iloc[0]["state"] == States.ACTIVE


class TestCrashRecovery:
    def test_stable_scan_skips_torn_tail(self, tmp_path):
        """A crash mid-action leaves a transient tail; getLatestStableLog
        scans backward past it (IndexLogManager.scala:93-117)."""
        index_path = str(tmp_path / "idx")
        os.makedirs(index_path)
        mgr = IndexLogManager(index_path)
        assert mgr.write_log(1, make_entry(state=States.CREATING))
        assert mgr.write_log(2, make_entry(state=States.ACTIVE))
        assert mgr.write_log(3, make_entry(state=States.REFRESHING))
        # Simulated crash: id 3 is transient, no latestStable pointer.
        stable = mgr.get_latest_stable_log()
        assert stable is not None and stable.state == States.ACTIVE

    def test_corrupt_tail_json_is_skipped(self, tmp_path):
        index_path = str(tmp_path / "idx")
        os.makedirs(index_path)
        mgr = IndexLogManager(index_path)
        assert mgr.write_log(1, make_entry(state=States.ACTIVE))
        # Torn write: half a JSON document at the tail.
        log_dir = os.path.join(index_path, "_hyperspace_log")
        with open(os.path.join(log_dir, "2"), "w") as f:
            f.write('{"name": "torn", "state":')
        stable = mgr.get_latest_stable_log()
        assert stable is not None and stable.state == States.ACTIVE


def _refresh_worker(root, q):
    """Child: run an incremental refresh (slowed by op-log timing jitter is
    unnecessary — the parent queries concurrently while this runs)."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import hyperspace_tpu as hst
    from hyperspace_tpu.api import Hyperspace

    session = hst.Session(system_path=os.path.join(root, "indexes"))
    try:
        Hyperspace(session).refresh_index("rwIdx", "incremental")
        q.put(("refresh", "ok"))
    except Exception as e:  # pragma: no cover - diagnostic channel
        q.put(("refresh", f"err: {e}"))


def _parallel_create_worker(root, name, q):
    import jax
    jax.config.update("jax_platforms", "cpu")
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import hyperspace_tpu as hst
    from hyperspace_tpu.api import Hyperspace, IndexConfig

    session = hst.Session(system_path=os.path.join(root, "indexes"))
    try:
        Hyperspace(session).create_index(
            session.read.parquet(os.path.join(root, "data")),
            IndexConfig(name, ["k"], ["v"]))
        q.put((name, "ok"))
    except Exception as e:
        q.put((name, f"err: {e}"))


class TestReaderWriterRaces:
    """Readers must only ever see stable states: a refresh running in a
    separate process never changes query answers mid-flight, and distinct
    indexes create concurrently without interference (the op logs are
    per-index — the reference's per-index IndexLogManager isolation)."""

    def _seed(self, tmp_path, n=4000):
        rng = np.random.default_rng(5)
        data_dir = tmp_path / "data"
        data_dir.mkdir()
        df = pd.DataFrame({
            "k": rng.integers(0, 60, n).astype(np.int64),
            "v": rng.integers(0, 9, n).astype(np.int64),
        })
        pq.write_table(pa.Table.from_pandas(df), data_dir / "p.parquet")
        (tmp_path / "indexes").mkdir()
        return df

    def test_queries_stable_during_refresh(self, tmp_path):
        import jax
        jax.config.update("jax_platforms", "cpu")
        import hyperspace_tpu as hst
        from hyperspace_tpu.api import Hyperspace, IndexConfig
        from hyperspace_tpu.plan.expr import col

        df = self._seed(tmp_path)
        session = hst.Session(system_path=str(tmp_path / "indexes"))
        hs = Hyperspace(session)
        t = session.read.parquet(str(tmp_path / "data"))
        hs.create_index(t, IndexConfig("rwIdx", ["k"], ["v"]))
        # Append source data so the refresh has real work.
        rng = np.random.default_rng(6)
        pq.write_table(pa.Table.from_pandas(pd.DataFrame({
            "k": rng.integers(0, 60, 1500).astype(np.int64),
            "v": rng.integers(0, 9, 1500).astype(np.int64),
        })), tmp_path / "data" / "extra.parquet")

        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        p = ctx.Process(target=_refresh_worker, args=(str(tmp_path), q))
        p.start()
        # Query repeatedly WHILE the refresh commits; with the ORIGINAL
        # file listing the answers must be the pre-refresh ones every
        # time (snapshot semantics: the plan's relation pins its files).
        session.enable_hyperspace()
        expected = (df.k == 7).sum()
        query = t.filter(col("k") == 7).select("k", "v")
        import time
        deadline = time.monotonic() + 300
        while p.is_alive():
            assert time.monotonic() < deadline, "refresh child hung"
            assert len(query.to_pandas()) == expected
        tag, status = q.get(timeout=300)
        p.join(timeout=300)
        assert status == "ok", status
        # After refresh: a FRESH relation sees old+new rows, indexed.
        t2 = session.read.parquet(str(tmp_path / "data"))
        got = len(t2.filter(col("k") == 7).to_pandas())
        session.disable_hyperspace()
        raw = len(t2.filter(col("k") == 7).to_pandas())
        assert got == raw > expected

    def test_concurrent_creates_of_distinct_indexes(self, tmp_path):
        import jax
        jax.config.update("jax_platforms", "cpu")
        import hyperspace_tpu as hst
        from hyperspace_tpu.api import Hyperspace

        self._seed(tmp_path)
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        names = [f"pidx{i}" for i in range(3)]
        procs = [ctx.Process(target=_parallel_create_worker,
                             args=(str(tmp_path), n, q)) for n in names]
        for p in procs:
            p.start()
        results = dict(q.get(timeout=300) for _ in procs)
        for p in procs:
            p.join(timeout=300)
        assert all(results[n] == "ok" for n in names), results
        session = hst.Session(system_path=str(tmp_path / "indexes"))
        listing = Hyperspace(session).indexes()
        assert set(names) <= set(listing["name"])
        assert (listing[listing["name"].isin(names)]["state"]
                == States.ACTIVE).all()
