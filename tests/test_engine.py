"""Execution engine correctness vs a pandas oracle.

The reference's most valuable test pattern is disable-and-compare (index
result == no-index result); before indexes exist, the engine itself needs an
independent oracle — pandas plays that role here (SURVEY §7 hard-part #5).
"""

import datetime

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.plan.expr import Count, Sum, avg, col, count, max_, min_, sum_


@pytest.fixture(scope="module")
def sample_dir(tmp_path_factory):
    """A small orders/lineitem-like pair of parquet datasets."""
    rng = np.random.default_rng(42)
    root = tmp_path_factory.mktemp("data")
    n_orders, n_items = 500, 2000
    orders = pd.DataFrame({
        "o_orderkey": np.arange(n_orders, dtype=np.int64),
        "o_custkey": rng.integers(0, 100, n_orders).astype(np.int64),
        "o_totalprice": np.round(rng.uniform(10, 1000, n_orders), 2),
        "o_orderdate": [datetime.date(1995, 1, 1) + datetime.timedelta(days=int(d))
                        for d in rng.integers(0, 365, n_orders)],
        "o_orderpriority": rng.choice(
            ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"], n_orders),
    })
    lineitem = pd.DataFrame({
        "l_orderkey": rng.integers(0, n_orders, n_items).astype(np.int64),
        "l_partkey": rng.integers(0, 200, n_items).astype(np.int64),
        "l_quantity": rng.integers(1, 50, n_items).astype(np.int64),
        "l_extendedprice": np.round(rng.uniform(100, 10000, n_items), 2),
        "l_discount": np.round(rng.uniform(0, 0.1, n_items), 2),
        "l_shipdate": [datetime.date(1995, 1, 1) + datetime.timedelta(days=int(d))
                       for d in rng.integers(0, 365, n_items)],
        "l_returnflag": rng.choice(["A", "N", "R"], n_items),
    })
    for name, df in [("orders", orders), ("lineitem", lineitem)]:
        d = root / name
        d.mkdir()
        # Two files each, to exercise multi-file scans.
        half = len(df) // 2
        pq.write_table(pa.Table.from_pandas(df.iloc[:half]), d / "part0.parquet")
        pq.write_table(pa.Table.from_pandas(df.iloc[half:]), d / "part1.parquet")
    return {"root": root, "orders": orders, "lineitem": lineitem}


@pytest.fixture()
def session(sample_dir, tmp_system_path):
    return hst.Session(system_path=tmp_system_path)


def sorted_df(df):
    out = df.sort_values(list(df.columns)).reset_index(drop=True)
    return out


def assert_frames_match(actual: pd.DataFrame, expected: pd.DataFrame):
    actual = sorted_df(actual)
    expected = sorted_df(expected)
    assert list(actual.columns) == list(expected.columns)
    assert len(actual) == len(expected)
    for c in actual.columns:
        a, e = actual[c].to_numpy(), expected[c].to_numpy()
        if a.dtype.kind == "f" or e.dtype.kind == "f":
            np.testing.assert_allclose(a.astype(float), e.astype(float), rtol=1e-9)
        else:
            assert (a == e).all(), f"column {c} differs"


class TestScanFilterProject:
    def test_full_scan(self, session, sample_dir):
        df = session.read.parquet(str(sample_dir["root"] / "orders"))
        out = df.to_pandas()
        exp = sample_dir["orders"].copy()
        out["o_orderdate"] = pd.to_datetime(out["o_orderdate"]).dt.date
        assert_frames_match(out, exp)

    def test_int_filter(self, session, sample_dir):
        df = session.read.parquet(str(sample_dir["root"] / "lineitem"))
        out = df.filter(col("l_quantity") >= 40).select(
            "l_orderkey", "l_quantity").to_pandas()
        li = sample_dir["lineitem"]
        exp = li[li.l_quantity >= 40][["l_orderkey", "l_quantity"]]
        assert_frames_match(out, exp)

    def test_date_range_filter(self, session, sample_dir):
        df = session.read.parquet(str(sample_dir["root"] / "lineitem"))
        lo, hi = datetime.date(1995, 3, 1), datetime.date(1995, 6, 30)
        out = df.filter(col("l_shipdate").between(lo, hi)) \
            .select("l_orderkey", "l_shipdate").to_pandas()
        out["l_shipdate"] = pd.to_datetime(out["l_shipdate"]).dt.date
        li = sample_dir["lineitem"]
        exp = li[(li.l_shipdate >= lo) & (li.l_shipdate <= hi)][
            ["l_orderkey", "l_shipdate"]]
        assert_frames_match(out, exp)

    def test_string_equality_and_range(self, session, sample_dir):
        df = session.read.parquet(str(sample_dir["root"] / "orders"))
        out = df.filter(col("o_orderpriority") == "2-HIGH") \
            .select("o_orderkey").to_pandas()
        od = sample_dir["orders"]
        exp = od[od.o_orderpriority == "2-HIGH"][["o_orderkey"]]
        assert_frames_match(out, exp)
        # Range over strings (order-preserving codes).
        out2 = df.filter(col("o_orderpriority") < "3-MEDIUM") \
            .select("o_orderkey").to_pandas()
        exp2 = od[od.o_orderpriority < "3-MEDIUM"][["o_orderkey"]]
        assert_frames_match(out2, exp2)

    def test_string_literal_not_present(self, session, sample_dir):
        df = session.read.parquet(str(sample_dir["root"] / "orders"))
        assert df.filter(col("o_orderpriority") == "9-NOPE").count() == 0

    def test_in_and_or(self, session, sample_dir):
        df = session.read.parquet(str(sample_dir["root"] / "lineitem"))
        cond = col("l_returnflag").isin(["A", "R"]) & \
            ((col("l_quantity") < 5) | (col("l_quantity") > 45))
        out = df.filter(cond).select("l_orderkey", "l_quantity").to_pandas()
        li = sample_dir["lineitem"]
        exp = li[li.l_returnflag.isin(["A", "R"])
                 & ((li.l_quantity < 5) | (li.l_quantity > 45))][
            ["l_orderkey", "l_quantity"]]
        assert_frames_match(out, exp)

    def test_arithmetic_projection(self, session, sample_dir):
        df = session.read.parquet(str(sample_dir["root"] / "lineitem"))
        revenue = (col("l_extendedprice") * (1 - col("l_discount"))).alias("revenue")
        out = df.select(col("l_orderkey"), revenue).to_pandas()
        li = sample_dir["lineitem"]
        exp = pd.DataFrame({
            "l_orderkey": li.l_orderkey,
            "revenue": li.l_extendedprice * (1 - li.l_discount)})
        assert_frames_match(out, exp)


class TestJoin:
    def test_equi_join(self, session, sample_dir):
        orders = session.read.parquet(str(sample_dir["root"] / "orders"))
        lineitem = session.read.parquet(str(sample_dir["root"] / "lineitem"))
        joined = lineitem.join(orders, on=col("l_orderkey") == col("o_orderkey"))
        out = joined.select("l_orderkey", "o_custkey", "l_quantity").to_pandas()
        li, od = sample_dir["lineitem"], sample_dir["orders"]
        exp = li.merge(od, left_on="l_orderkey", right_on="o_orderkey")[
            ["l_orderkey", "o_custkey", "l_quantity"]]
        assert_frames_match(out, exp)

    def test_join_then_aggregate(self, session, sample_dir):
        orders = session.read.parquet(str(sample_dir["root"] / "orders"))
        lineitem = session.read.parquet(str(sample_dir["root"] / "lineitem"))
        joined = lineitem.join(orders, on=col("l_orderkey") == col("o_orderkey"))
        out = joined.group_by("o_custkey").agg(
            sum_(col("l_quantity")).alias("total_qty")).to_pandas()
        li, od = sample_dir["lineitem"], sample_dir["orders"]
        merged = li.merge(od, left_on="l_orderkey", right_on="o_orderkey")
        exp = merged.groupby("o_custkey", as_index=False).agg(
            total_qty=("l_quantity", "sum"))
        assert_frames_match(out, exp)

    def test_string_key_join_different_dictionaries(self, session, tmp_path):
        t1 = pd.DataFrame({"k1": ["a", "b", "c", "d"], "v1": [1, 2, 3, 4]})
        t2 = pd.DataFrame({"k2": ["b", "c", "e"], "v2": [20, 30, 50]})
        pq.write_table(pa.Table.from_pandas(t1), tmp_path / "t1.parquet")
        pq.write_table(pa.Table.from_pandas(t2), tmp_path / "t2.parquet")
        d1 = session.read.parquet(str(tmp_path / "t1.parquet"))
        d2 = session.read.parquet(str(tmp_path / "t2.parquet"))
        out = d1.join(d2, on=col("k1") == col("k2")) \
            .select("k1", "v1", "v2").to_pandas()
        exp = t1.merge(t2, left_on="k1", right_on="k2")[["k1", "v1", "v2"]]
        assert_frames_match(out, exp)


class TestAggregateSortLimit:
    def test_group_by_multiple_aggs(self, session, sample_dir):
        df = session.read.parquet(str(sample_dir["root"] / "lineitem"))
        out = df.group_by("l_returnflag").agg(
            sum_(col("l_quantity")).alias("sum_qty"),
            avg(col("l_extendedprice")).alias("avg_price"),
            min_(col("l_shipdate")).alias("min_date"),
            max_(col("l_shipdate")).alias("max_date"),
            count(col("l_orderkey")).alias("n"),
        ).to_pandas()
        out["min_date"] = pd.to_datetime(out["min_date"]).dt.date
        out["max_date"] = pd.to_datetime(out["max_date"]).dt.date
        li = sample_dir["lineitem"]
        exp = li.groupby("l_returnflag", as_index=False).agg(
            sum_qty=("l_quantity", "sum"),
            avg_price=("l_extendedprice", "mean"),
            min_date=("l_shipdate", "min"),
            max_date=("l_shipdate", "max"),
            n=("l_orderkey", "count"))
        assert_frames_match(out, exp)

    def test_multi_column_group(self, session, sample_dir):
        df = session.read.parquet(str(sample_dir["root"] / "lineitem"))
        out = df.group_by("l_returnflag", "l_partkey").agg(
            sum_(col("l_quantity")).alias("q")).to_pandas()
        li = sample_dir["lineitem"]
        exp = li.groupby(["l_returnflag", "l_partkey"], as_index=False).agg(
            q=("l_quantity", "sum"))
        assert_frames_match(out, exp)

    def test_global_aggregate(self, session, sample_dir):
        df = session.read.parquet(str(sample_dir["root"] / "lineitem"))
        out = df.agg(sum_(col("l_quantity")).alias("s"),
                     count(col("l_quantity")).alias("n")).to_pandas()
        li = sample_dir["lineitem"]
        assert out["s"][0] == li.l_quantity.sum()
        assert out["n"][0] == len(li)

    def test_sort_desc_limit(self, session, sample_dir):
        df = session.read.parquet(str(sample_dir["root"] / "orders"))
        out = df.select("o_orderkey", "o_totalprice") \
            .sort(("o_totalprice", False)).limit(10).to_pandas()
        od = sample_dir["orders"]
        exp = od.nlargest(10, "o_totalprice")[["o_orderkey", "o_totalprice"]] \
            .reset_index(drop=True)
        np.testing.assert_allclose(out["o_totalprice"], exp["o_totalprice"])

    def test_sort_by_string(self, session, sample_dir):
        df = session.read.parquet(str(sample_dir["root"] / "orders"))
        out = df.select("o_orderpriority", "o_orderkey") \
            .sort("o_orderpriority", "o_orderkey").to_pandas()
        od = sample_dir["orders"]
        exp = od[["o_orderpriority", "o_orderkey"]].sort_values(
            ["o_orderpriority", "o_orderkey"]).reset_index(drop=True)
        assert list(out["o_orderkey"]) == list(exp["o_orderkey"])


class TestQ3Shape:
    def test_tpch_q3_like(self, session, sample_dir):
        """The BASELINE config #2 query shape end-to-end (no index yet)."""
        orders = session.read.parquet(str(sample_dir["root"] / "orders"))
        lineitem = session.read.parquet(str(sample_dir["root"] / "lineitem"))
        cutoff = datetime.date(1995, 6, 15)
        q = (lineitem.filter(col("l_shipdate") > cutoff)
             .join(orders.filter(col("o_orderdate") < cutoff),
                   on=col("l_orderkey") == col("o_orderkey"))
             .group_by("l_orderkey", "o_orderdate")
             .agg(sum_((col("l_extendedprice") * (1 - col("l_discount"))))
                  .alias("revenue"))
             .sort(("revenue", False), "o_orderdate")
             .limit(10))
        out = q.to_pandas()
        li, od = sample_dir["lineitem"], sample_dir["orders"]
        li_f = li[li.l_shipdate > cutoff]
        od_f = od[od.o_orderdate < cutoff]
        merged = li_f.merge(od_f, left_on="l_orderkey", right_on="o_orderkey")
        merged["revenue"] = merged.l_extendedprice * (1 - merged.l_discount)
        exp = merged.groupby(["l_orderkey", "o_orderdate"], as_index=False).agg(
            revenue=("revenue", "sum")).sort_values(
            ["revenue", "o_orderdate"], ascending=[False, True]).head(10) \
            .reset_index(drop=True)
        np.testing.assert_allclose(out["revenue"], exp["revenue"], rtol=1e-9)
        assert list(out["l_orderkey"]) == list(exp["l_orderkey"])


class TestProfilerTrace:
    def test_trace_dir_collects_xla_profile(self, tmp_path):
        """hyperspace.tpu.trace.dir wraps execution in jax.profiler.trace
        (SURVEY §5 XLA-profiler integration)."""
        import os

        import numpy as np
        import pandas as pd
        import pyarrow as pa
        import pyarrow.parquet as pq

        import hyperspace_tpu as hst
        from hyperspace_tpu.index.constants import IndexConstants
        from hyperspace_tpu.plan.expr import col

        d = tmp_path / "data"
        d.mkdir()
        pq.write_table(pa.Table.from_pandas(pd.DataFrame({
            "k": np.arange(1000, dtype=np.int64)})), d / "p.parquet")
        (tmp_path / "idx").mkdir()
        session = hst.Session(system_path=str(tmp_path / "idx"))
        trace_dir = str(tmp_path / "traces")
        session.conf.set(IndexConstants.TPU_TRACE_DIR, trace_dir)
        out = session.read.parquet(str(d)).filter(col("k") < 10).to_pandas()
        assert len(out) == 10
        found = [os.path.join(r, f) for r, _, fs in os.walk(trace_dir)
                 for f in fs]
        assert found, "no profiler trace files written"


class TestNullableInt64Precision:
    def test_large_int64_with_nulls_roundtrips_exactly(self, tmp_path):
        """A nullable int64 column must NOT round-trip through float64
        (NaN-null): values beyond ±2^53 would silently lose precision."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        big = 9_007_199_254_740_995  # 2^53 + 3: not float64-representable
        t = pa.table({"v": pa.array([big, None, -big, 7], pa.int64())})
        d = tmp_path / "p"
        d.mkdir()
        pq.write_table(t, d / "x.parquet")
        session = hst.Session(system_path=str(tmp_path / "idx"))
        got = session.read.parquet(str(d)).to_arrow()
        assert got.column("v").to_pylist() == [big, None, -big, 7]
