"""DataFrame.write: the output side of the user API (df.write analogue).

Round-trips each format through the engine's own readers, honors
error/overwrite/append modes, and writes the REWRITTEN result when
hyperspace is enabled (the rewrite is semantics-preserving, so the bytes
must equal the no-index run's)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.plan.expr import col


@pytest.fixture()
def env(tmp_path):
    rng = np.random.default_rng(13)
    df = pd.DataFrame({
        "k": rng.integers(0, 80, 6000).astype(np.int64),
        "v": np.round(rng.random(6000), 5),
        "s": rng.choice(["aa", "bb", "cc"], 6000),
    })
    d = tmp_path / "data"
    d.mkdir()
    pq.write_table(pa.Table.from_pandas(df), d / "p.parquet")
    session = hst.Session(system_path=str(tmp_path / "idx"))
    return dict(session=session, hs=Hyperspace(session),
                path=str(d), df=df, tmp=tmp_path)


def _q(session, path):
    return session.read.parquet(path).filter(col("k") < 30).select("k", "v")


class TestWriteFormats:
    @pytest.mark.parametrize("fmt", ["parquet", "csv", "json", "avro"])
    def test_round_trip(self, env, fmt):
        session = env["session"]
        q = _q(session, env["path"])
        out = str(env["tmp"] / f"out_{fmt}")
        getattr(q.write, fmt)(out)
        back = getattr(session.read, fmt)(out).to_pandas()
        exp = q.to_pandas()
        key = ["k", "v"]
        pd.testing.assert_frame_equal(
            back.sort_values(key).reset_index(drop=True).astype(
                {"k": "int64", "v": "float64"}),
            exp.sort_values(key).reset_index(drop=True), check_dtype=False)


class TestWriteModes:
    def test_error_mode_refuses_overwrite(self, env):
        session = env["session"]
        q = _q(session, env["path"])
        out = str(env["tmp"] / "out")
        q.write.parquet(out)
        with pytest.raises(HyperspaceException, match="not empty"):
            q.write.parquet(out)

    def test_overwrite_replaces(self, env):
        session = env["session"]
        q = _q(session, env["path"])
        out = str(env["tmp"] / "out")
        q.write.parquet(out)
        q.write.mode("overwrite").parquet(out)
        assert session.read.parquet(out).count() == q.count()

    def test_append_adds_rows(self, env):
        session = env["session"]
        q = _q(session, env["path"])
        out = str(env["tmp"] / "out")
        q.write.parquet(out)
        q.write.mode("append").parquet(out)
        assert session.read.parquet(out).count() == 2 * q.count()

    def test_unknown_mode_raises(self, env):
        with pytest.raises(HyperspaceException, match="Unknown write mode"):
            _q(env["session"], env["path"]).write.mode("nope")

    def test_error_mode_sees_any_contents_not_just_parts(self, env):
        out = env["tmp"] / "occupied"
        out.mkdir()
        (out / "_SUCCESS").write_text("")
        with pytest.raises(HyperspaceException, match="not empty"):
            _q(env["session"], env["path"]).write.parquet(str(out))

    def test_file_destination_is_loud(self, env):
        f = env["tmp"] / "a_file"
        f.write_text("x")
        with pytest.raises(HyperspaceException, match="is a file"):
            _q(env["session"], env["path"]).write.parquet(str(f))

    def test_overwrite_own_source_is_safe(self, env):
        """write.mode('overwrite') back onto the query's own source dir:
        the result materializes BEFORE the deletion, so data survives."""
        session = env["session"]
        src = str(env["tmp"] / "self")
        _q(session, env["path"]).write.parquet(src)
        q2 = session.read.parquet(src).filter(col("k") < 10)
        expected = q2.count()
        q2.write.mode("overwrite").parquet(src)
        assert session.read.parquet(src).count() == expected


class TestWriteUnderRewrite:
    def test_written_bytes_match_no_index_run(self, env):
        session, hs = env["session"], env["hs"]
        t = session.read.parquet(env["path"])
        hs.create_index(t, IndexConfig("w_idx", ["k"], ["v"]))
        q = _q(session, env["path"])
        out_idx = str(env["tmp"] / "with_idx")
        out_raw = str(env["tmp"] / "without")
        session.enable_hyperspace()
        assert "IndexScan" in q.optimized_plan().tree_string()
        q.write.parquet(out_idx)
        session.disable_hyperspace()
        q.write.parquet(out_raw)
        a = session.read.parquet(out_idx).to_pandas()
        b = session.read.parquet(out_raw).to_pandas()
        key = ["k", "v"]
        pd.testing.assert_frame_equal(
            a.sort_values(key).reset_index(drop=True),
            b.sort_values(key).reset_index(drop=True))
