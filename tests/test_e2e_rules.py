"""End-to-end index lifecycle + rewrite tests.

Parity: E2EHyperspaceRulesTest.scala (the reference's backbone suite) — the
core oracle is disable-and-compare: query results with hyperspace enabled
(index used) must equal results with it disabled (source scanned).
"""

import datetime

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.constants import IndexConstants, States
from hyperspace_tpu.plan.expr import col, sum_
from hyperspace_tpu.plan.nodes import IndexScan


def write_sample(root, name, df, parts=2):
    d = root / name
    d.mkdir(parents=True, exist_ok=True)
    step = max(1, len(df) // parts)
    for i in range(parts):
        chunk = df.iloc[i * step:(i + 1) * step if i < parts - 1 else len(df)]
        pq.write_table(pa.Table.from_pandas(chunk.reset_index(drop=True)),
                       d / f"part{i}.parquet")
    return str(d)


@pytest.fixture()
def env(tmp_path):
    rng = np.random.default_rng(0)
    n = 2000
    lineitem = pd.DataFrame({
        "l_orderkey": rng.integers(0, 500, n).astype(np.int64),
        "l_quantity": rng.integers(1, 50, n).astype(np.int64),
        "l_extendedprice": np.round(rng.uniform(100, 10000, n), 2),
        "l_discount": np.round(rng.uniform(0, 0.1, n), 2),
        "l_shipdate": [datetime.date(1995, 1, 1) + datetime.timedelta(days=int(d))
                       for d in rng.integers(0, 365, n)],
    })
    orders = pd.DataFrame({
        "o_orderkey": np.arange(500, dtype=np.int64),
        "o_custkey": rng.integers(0, 100, 500).astype(np.int64),
        "o_orderdate": [datetime.date(1995, 1, 1) + datetime.timedelta(days=int(d))
                        for d in rng.integers(0, 365, 500)],
    })
    li_path = write_sample(tmp_path, "lineitem", lineitem)
    od_path = write_sample(tmp_path, "orders", orders)
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 8)
    return dict(session=session, hs=Hyperspace(session),
                li_path=li_path, od_path=od_path,
                lineitem=lineitem, orders=orders, tmp=tmp_path)


def uses_index(df, name):
    plan = df.optimized_plan()
    return any(isinstance(l, IndexScan) and l.index_entry.name == name
               for l in plan.collect_leaves())


def check_disable_and_compare(session, df):
    """The reference's core oracle (E2EHyperspaceRulesTest.verifyIndexUsage)."""
    session.enable_hyperspace()
    with_index = df.to_pandas()
    session.disable_hyperspace()
    without = df.to_pandas()
    session.enable_hyperspace()
    a = with_index.sort_values(list(with_index.columns)).reset_index(drop=True)
    b = without.sort_values(list(without.columns)).reset_index(drop=True)
    pd.testing.assert_frame_equal(a, b, check_dtype=False)
    return with_index


class TestFilterIndexE2E:
    def test_filter_rewrite_and_results(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["li_path"])
        hs.create_index(df, IndexConfig(
            "filterIdx", ["l_shipdate"], ["l_orderkey", "l_quantity"]))
        q = df.filter(col("l_shipdate") > datetime.date(1995, 7, 1)) \
            .select("l_orderkey", "l_quantity")
        session.enable_hyperspace()
        assert uses_index(q, "filterIdx")
        check_disable_and_compare(session, q)

    def test_not_used_when_not_covering(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["li_path"])
        hs.create_index(df, IndexConfig("smallIdx", ["l_shipdate"], ["l_orderkey"]))
        session.enable_hyperspace()
        # l_extendedprice is not covered → no rewrite.
        q = df.filter(col("l_shipdate") > datetime.date(1995, 7, 1)) \
            .select("l_orderkey", "l_extendedprice")
        assert not uses_index(q, "smallIdx")

    def test_not_used_when_filter_not_on_first_indexed(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["li_path"])
        hs.create_index(df, IndexConfig(
            "orderIdx", ["l_shipdate"], ["l_quantity"]))
        session.enable_hyperspace()
        q = df.filter(col("l_quantity") > 10).select("l_quantity")
        assert not uses_index(q, "orderIdx")

    def test_case_insensitive_columns(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["li_path"])
        hs.create_index(df, IndexConfig(
            "caseIdx", ["L_SHIPDATE"], ["L_ORDERKEY"]))
        entry = hs.index_manager.get_index("caseIdx")
        assert entry.indexed_columns == ["l_shipdate"]
        session.enable_hyperspace()
        q = df.filter(col("l_shipdate") > datetime.date(1995, 7, 1)) \
            .select("l_orderkey")
        assert uses_index(q, "caseIdx")

    def test_signature_mismatch_after_source_change(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["li_path"])
        hs.create_index(df, IndexConfig("sigIdx", ["l_shipdate"], ["l_orderkey"]))
        # Append a new source file → signature changes → index not used.
        extra = env["lineitem"].iloc[:5]
        pq.write_table(pa.Table.from_pandas(extra.reset_index(drop=True)),
                       env["tmp"] / "lineitem" / "extra.parquet")
        session.enable_hyperspace()
        fresh = session.read.parquet(env["li_path"])
        q = fresh.filter(col("l_shipdate") > datetime.date(1995, 7, 1)) \
            .select("l_orderkey")
        assert not uses_index(q, "sigIdx")
        # But results still correct (scan path).
        check_disable_and_compare(session, q)


class TestJoinIndexE2E:
    def test_join_rewrite_and_results(self, env):
        session, hs = env["session"], env["hs"]
        li = session.read.parquet(env["li_path"])
        od = session.read.parquet(env["od_path"])
        hs.create_index(li, IndexConfig(
            "liJoinIdx", ["l_orderkey"],
            ["l_extendedprice", "l_discount", "l_shipdate"]))
        hs.create_index(od, IndexConfig(
            "odJoinIdx", ["o_orderkey"], ["o_custkey", "o_orderdate"]))
        q = (li.filter(col("l_shipdate") > datetime.date(1995, 6, 1))
             .join(od, on=col("l_orderkey") == col("o_orderkey"))
             .group_by("o_custkey")
             .agg(sum_(col("l_extendedprice") * (1 - col("l_discount")))
                  .alias("revenue")))
        session.enable_hyperspace()
        assert uses_index(q, "liJoinIdx") and uses_index(q, "odJoinIdx")
        check_disable_and_compare(session, q)

    def test_join_no_compatible_pair(self, env):
        session, hs = env["session"], env["hs"]
        li = session.read.parquet(env["li_path"])
        od = session.read.parquet(env["od_path"])
        hs.create_index(li, IndexConfig("liOnly", ["l_orderkey"], ["l_quantity"]))
        session.enable_hyperspace()
        q = li.join(od, on=col("l_orderkey") == col("o_orderkey")) \
            .select("l_quantity", "o_custkey")
        assert not uses_index(q, "liOnly")


class TestLifecycleE2E:
    def test_delete_restore_vacuum(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["li_path"])
        hs.create_index(df, IndexConfig("lcIdx", ["l_shipdate"], ["l_orderkey"]))
        q = df.filter(col("l_shipdate") > datetime.date(1995, 7, 1)) \
            .select("l_orderkey")
        session.enable_hyperspace()
        assert uses_index(q, "lcIdx")

        hs.delete_index("lcIdx")
        assert hs.index_manager.get_index("lcIdx").state == States.DELETED
        assert not uses_index(q, "lcIdx")

        hs.restore_index("lcIdx")
        assert hs.index_manager.get_index("lcIdx").state == States.ACTIVE
        assert uses_index(q, "lcIdx")

        hs.delete_index("lcIdx")
        hs.vacuum_index("lcIdx")
        assert hs.index_manager.get_index("lcIdx").state == States.DOESNOTEXIST
        # Data dirs physically removed.
        from hyperspace_tpu.index.data_manager import IndexDataManager
        dm = IndexDataManager(str(env["tmp"] / "indexes" / "lcIdx"))
        assert dm.get_all_version_ids() == []

    def test_vacuum_requires_deleted(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["li_path"])
        hs.create_index(df, IndexConfig("vIdx", ["l_shipdate"], ["l_orderkey"]))
        with pytest.raises(HyperspaceException):
            hs.vacuum_index("vIdx")

    def test_create_duplicate_name_fails(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["li_path"])
        hs.create_index(df, IndexConfig("dupIdx", ["l_shipdate"], ["l_orderkey"]))
        with pytest.raises(HyperspaceException):
            hs.create_index(df, IndexConfig("dupIdx", ["l_shipdate"], ["l_orderkey"]))

    def test_create_bad_column_fails(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["li_path"])
        with pytest.raises(HyperspaceException):
            hs.create_index(df, IndexConfig("badIdx", ["no_such_col"], []))

    def test_indexes_listing(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["li_path"])
        hs.create_index(df, IndexConfig("listIdx", ["l_shipdate"], ["l_orderkey"]))
        listing = hs.indexes()
        assert list(listing["name"]) == ["listIdx"]
        assert listing["state"][0] == States.ACTIVE
        assert listing["numBuckets"][0] == 8
        stats = hs.index("listIdx")
        assert stats["sourceFileCount"][0] == 2
        assert stats["indexFileCount"][0] > 0

    def test_explain_mentions_index(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["li_path"])
        hs.create_index(df, IndexConfig("expIdx", ["l_shipdate"], ["l_orderkey"]))
        q = df.filter(col("l_shipdate") > datetime.date(1995, 7, 1)) \
            .select("l_orderkey")
        text = hs.explain(q, verbose=True)
        assert "expIdx" in text and "Indexes used" in text


class TestIndexData:
    def test_bucket_files_sorted_and_bucketed(self, env):
        """Index parquet layout invariant: one file per non-empty bucket,
        rows within a bucket sorted by the indexed column."""
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["li_path"])
        hs.create_index(df, IndexConfig("bIdx", ["l_orderkey"], ["l_quantity"]))
        from hyperspace_tpu.ops.index_build import bucket_id_from_file
        entry = hs.index_manager.get_index("bIdx")
        files = sorted(entry.content.files)
        assert 0 < len(files) <= 8
        for f in files:
            b = bucket_id_from_file(f)
            assert b is not None and 0 <= b < 8
            t = pq.read_table(f)
            keys = t.column("l_orderkey").to_pylist()
            assert keys == sorted(keys)
        total = sum(pq.read_table(f).num_rows for f in files)
        assert total == len(env["lineitem"])


class TestFastPathCorrectness:
    """Regressions for the shuffle-free join fast path + bucket pruning."""

    def test_join_negative_keys(self, env, tmp_path):
        session, hs = env["session"], env["hs"]
        rng = np.random.default_rng(5)
        t1 = pd.DataFrame({"k1": rng.integers(-50, 50, 400).astype(np.int64),
                           "v1": np.arange(400, dtype=np.int64)})
        t2 = pd.DataFrame({"k2": np.arange(-50, 50, dtype=np.int64),
                           "v2": np.arange(100, dtype=np.int64)})
        p1 = write_sample(tmp_path, "neg1", t1)
        p2 = write_sample(tmp_path, "neg2", t2)
        d1, d2 = session.read.parquet(p1), session.read.parquet(p2)
        hs.create_index(d1, IndexConfig("negIdx1", ["k1"], ["v1"]))
        hs.create_index(d2, IndexConfig("negIdx2", ["k2"], ["v2"]))
        q = d1.join(d2, on=col("k1") == col("k2")).select("k1", "v1", "v2")
        session.enable_hyperspace()
        assert uses_index(q, "negIdx1") and uses_index(q, "negIdx2")
        out = check_disable_and_compare(session, q)
        exp = t1.merge(t2, left_on="k1", right_on="k2")
        assert len(out) == len(exp)

    def test_bucket_pruning_multi_column_index(self, env, tmp_path):
        session, hs = env["session"], env["hs"]
        session.conf.set(IndexConstants.INDEX_FILTER_RULE_USE_BUCKET_SPEC, "true")
        rng = np.random.default_rng(6)
        t = pd.DataFrame({"a": rng.integers(0, 10, 500).astype(np.int64),
                          "b": rng.integers(0, 10, 500).astype(np.int64),
                          "v": np.arange(500, dtype=np.int64)})
        p = write_sample(tmp_path, "mc", t)
        d = session.read.parquet(p)
        hs.create_index(d, IndexConfig("mcIdx", ["a", "b"], ["v"]))
        session.enable_hyperspace()
        # Equality on only the first indexed column: bucket pruning must NOT
        # drop rows (bucket is a hash of both columns).
        q = d.filter(col("a") == 7).select("a", "b", "v")
        assert uses_index(q, "mcIdx")
        out = check_disable_and_compare(session, q)
        assert len(out) == (t.a == 7).sum()
        # Equality on both columns: pruning may engage, results still equal.
        q2 = d.filter((col("a") == 7) & (col("b") == 3)).select("v")
        out2 = check_disable_and_compare(session, q2)
        assert len(out2) == ((t.a == 7) & (t.b == 3)).sum()
        session.conf.set(IndexConstants.INDEX_FILTER_RULE_USE_BUCKET_SPEC, "false")

    def test_bucket_pruning_equality_single(self, env):
        session, hs = env["session"], env["hs"]
        session.conf.set(IndexConstants.INDEX_FILTER_RULE_USE_BUCKET_SPEC, "true")
        df = session.read.parquet(env["li_path"])
        hs.create_index(df, IndexConfig("eqIdx", ["l_orderkey"], ["l_quantity"]))
        session.enable_hyperspace()
        q = df.filter(col("l_orderkey") == 42).select("l_orderkey", "l_quantity")
        assert uses_index(q, "eqIdx")
        out = check_disable_and_compare(session, q)
        li = env["lineitem"]
        assert len(out) == (li.l_orderkey == 42).sum()
        session.conf.set(IndexConstants.INDEX_FILTER_RULE_USE_BUCKET_SPEC, "false")


class TestIndexScanProjection:
    def test_bare_filter_query_has_no_phantom_columns(self, env):
        """Index files live under v__=<n>/ — pyarrow hive-infers a phantom
        v__ column when read without an explicit column list; a bare
        (projection-less) rewritten query must not leak it."""
        session, hs = env["session"], env["hs"]
        hs.create_index(session.read.parquet(env["li_path"]),
                        IndexConfig("bareIdx", ["l_orderkey"],
                                    ["l_quantity", "l_extendedprice",
                                     "l_discount", "l_shipdate"]))
        session.enable_hyperspace()
        q = session.read.parquet(env["li_path"]).filter(col("l_orderkey") == 7)
        assert uses_index(q, "bareIdx")
        out = q.to_pandas()
        assert "v__" not in out.columns
        assert sorted(out.columns) == sorted(env["lineitem"].columns)
        check_disable_and_compare(session, q)
