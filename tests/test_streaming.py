"""Streaming ingestion tier end to end (streaming/).

Acceptance contracts of the append/commit path:

- **Freshness with zero refresh passes**: a commit-then-query loop is
  answered from IndexScans over sketches/indexes the commits themselves
  kept fresh — StreamingIndexDeltaEvents appear, Refresh*ActionEvents do
  NOT — and the answers are byte-identical to a cold rebuild over the
  same final data.
- **Crash safety**: kill -9 mid-commit (the armed ``ingest.publish`` /
  ``ingest.stage`` fault points) leaves a wreck ``recover()`` resolves —
  undo (staged batch rolled back, pre-commit answers restored) when
  publication was torn, redo (commit finalized) when every batch file
  landed — and ``compact()`` after recovery changes no answer.
- **Compaction**: op-log entry count and query-time log-read bytes drop
  while query results and a second ``recover()`` stay byte-identical;
  a second ``compact()`` folds nothing.
- **Standing queries**: subscriptions re-fire per commit through the
  8-thread serving frontend and deliver the same rows as re-running the
  plan after each commit.
- **Hot-path memo**: the op-log lookup cache (``ingest.append`` /
  ``ingest.commit`` / ``ingest.compact`` spans' supporting satellite)
  stops repeated queries from re-listing/re-reading log entries.
"""

import glob
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import (BloomFilterSketch, DataSkippingIndexConfig,
                                Hyperspace, IndexConfig, MinMaxSketch)
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.constants import (IndexConstants, STABLE_STATES,
                                            States)
from hyperspace_tpu.index.log_manager import (IndexLogManager,
                                              get_lookup_cache)
from hyperspace_tpu.plan.expr import col
from hyperspace_tpu.streaming.constants import StreamingConstants as SC
from hyperspace_tpu.streaming.ingest import table_key, table_log_dir

from conftest import capture_logger as sink  # noqa: E402

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rng(seed=17):
    return np.random.default_rng(seed)


def _frame(rng, n):
    return pd.DataFrame({
        "k": rng.integers(0, 40, n).astype(np.int64),
        "v": rng.integers(0, 9, n).astype(np.int64)})


def _write_base(d, rng, n=2000):
    os.makedirs(d, exist_ok=True)
    pq.write_table(pa.Table.from_pandas(_frame(rng, n)),
                   os.path.join(d, "p0.parquet"))


def _session(tmp_path, capture=False):
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    session.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
    session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "false")
    if capture:
        session.conf.set(IndexConstants.EVENT_LOGGER_CLASS,
                         "tests.conftest.CaptureLogger")
        sink().events.clear()
    return session


def _lake(tmp_path, capture=False, skipping=True):
    """Base table + covering index cx(k;v) [+ skipping index sx]."""
    data = str(tmp_path / "tbl")
    _write_base(data, _rng())
    session = _session(tmp_path, capture=capture)
    hs = Hyperspace(session)
    t = session.read.parquet(data)
    hs.create_index(t, IndexConfig("cx", ["k"], ["v"]))
    if skipping:
        hs.create_index(t, DataSkippingIndexConfig(
            "sx", [MinMaxSketch("k"),
                   BloomFilterSketch("v", expected_items=4096)]))
    return session, hs, data


def _answers(session, data):
    """(enabled, disabled) sorted answers for the probe query over a
    FRESH relation listing."""
    t = session.read.parquet(data)
    q = t.filter(col("k") == 7).select("k", "v")
    session.enable_hyperspace()
    a = q.to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
    session.disable_hyperspace()
    b = q.to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
    return a, b


# ---------------------------------------------------------------------------
# Freshness: commit-then-query with zero refresh passes.
# ---------------------------------------------------------------------------

class TestCommitThenQuery:
    def test_fresh_indexes_zero_refreshes_byte_identical(self, tmp_path):
        session, hs, data = _lake(tmp_path, capture=True)
        rng = _rng(23)
        batches = []
        for i in range(3):
            batch = _frame(rng, 400 + 50 * i)
            batches.append(batch)
            hs.append(data, batch)
            out = hs.commit(data)
            assert out["committed_batches"] == 1
            assert sorted(out["indexes_updated"]) == ["cx", "sx"]

            # Fresh query: the covering index applies EXACTLY (no
            # hybrid-scan conf is set, so only an exact signature match
            # rewrites) and answers match the raw scan.
            t = session.read.parquet(data)
            q = t.filter(col("k") == 7).select("k", "v")
            session.enable_hyperspace()
            opt = session.optimize(q.plan, diagnostic=True).tree_string()
            assert "IndexScan" in opt, opt
            a = q.to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
            session.disable_hyperspace()
            b = q.to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
            pd.testing.assert_frame_equal(a, b)

        names = [type(e).__name__ for e in sink().events]
        # Load-time indexing means NO refresh pass of any kind ran...
        assert "RefreshActionEvent" not in names
        assert "RefreshIncrementalActionEvent" not in names
        assert "RefreshQuickActionEvent" not in names
        # ...the streaming deltas did the work instead.
        assert names.count("StreamingIndexDeltaEvent") >= 6  # 2/idx/commit
        assert names.count("StreamingAppendEvent") == 3
        assert names.count("StreamingCommitEvent") >= 2  # start+success
        appends = [e for e in sink().events
                   if type(e).__name__ == "StreamingAppendEvent"]
        assert all(e.covering_deltas == 1 and e.sketch_deltas == 1
                   for e in appends)

        # Byte-identical to a COLD rebuild: a second lake indexed from
        # scratch over the same final data answers identically.
        cold_root = tmp_path / "cold"
        cold_root.mkdir()
        cold = hst.Session(system_path=str(cold_root / "indexes"))
        cold.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
        cold.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
        cold.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "false")
        cold_hs = Hyperspace(cold)
        ct = cold.read.parquet(data)
        cold_hs.create_index(ct, IndexConfig("cx", ["k"], ["v"]))
        cold.enable_hyperspace()
        cq = cold.read.parquet(data).filter(col("k") == 7).select("k", "v")
        assert "IndexScan" in cold.optimize(
            cq.plan, diagnostic=True).tree_string()
        cold_a = cq.to_pandas().sort_values(["k", "v"]).reset_index(
            drop=True)
        session.enable_hyperspace()
        warm_q = session.read.parquet(data).filter(
            col("k") == 7).select("k", "v")
        warm_a = warm_q.to_pandas().sort_values(["k", "v"]).reset_index(
            drop=True)
        pd.testing.assert_frame_equal(warm_a, cold_a)

    def test_sketches_fresh_per_commit(self, tmp_path):
        session, hs, data = _lake(tmp_path)
        rng = _rng(5)
        for _ in range(2):
            hs.append(data, _frame(rng, 300))
            hs.commit(data)
        # The sketch table covers every file, including both committed
        # batches — load-time sketching, no refresh ran.
        entry = session.index_collection_manager.get_index("sx")
        sketch_file = [f for f in entry.content.files
                       if f.endswith("sketches.parquet")]
        assert len(sketch_file) == 1
        table = pq.read_table(sketch_file[0], partitioning=None)
        files = sorted(table.column("_file").to_pylist())
        on_disk = sorted(
            session.read.parquet(data).plan.relation.all_files())
        assert files == on_disk
        # And the skipping rule prunes with them: a predicate outside
        # every file's range keeps zero files.
        session.enable_hyperspace()
        q = session.read.parquet(data).filter(col("k") >= 1000)
        leaves = [leaf for leaf in q.optimized_plan().collect_leaves()
                  if getattr(leaf, "relation", None) is not None]
        kept = min((len(le.relation.all_files()) for le in leaves),
                   default=0)
        assert kept == 0
        assert q.count() == 0

    def test_layout_drift_between_append_and_commit_skips_delta(
            self, tmp_path):
        """A delete+recreate at a different bucket count between append
        and commit must NOT land the staged delta — it was routed for
        the old bucketing, and landing it would silently break bucket
        pruning. The index is skipped (hybrid scan covers the files)
        and answers stay byte-identical."""
        session, hs, data = _lake(tmp_path, skipping=False)
        rng = _rng(121)
        hs.append(data, _frame(rng, 300))
        hs.delete_index("cx")
        hs.vacuum_index("cx")
        session.conf.set("hyperspace.index.numBuckets", 8)
        hs.create_index(session.read.parquet(data),
                        IndexConfig("cx", ["k"], ["v"]))
        out = hs.commit(data)
        assert out["indexes_skipped"] == ["cx"], out
        assert out["indexes_updated"] == []
        a, b = _answers(session, data)
        pd.testing.assert_frame_equal(a, b)
        # The skip happened pre-begin: the index log is clean (latest
        # entry stable), so no recover() is needed afterwards.
        entry = session.index_collection_manager.get_index("cx")
        assert entry is not None and entry.num_buckets == 8

    def test_load_time_indexing_off_falls_back(self, tmp_path):
        session, hs, data = _lake(tmp_path, skipping=False)
        session.conf.set(SC.LOAD_TIME_INDEXING, "false")
        rng = _rng(9)
        hs.append(data, _frame(rng, 200))
        out = hs.commit(data)
        assert out["indexes_updated"] == []
        # Files are visible; answers stay correct (plain scan or hybrid).
        a, b = _answers(session, data)
        pd.testing.assert_frame_equal(a, b)
        assert len(session.read.parquet(data).plan.relation.all_files()) \
            == 2

    def test_never_committed_staging_swept_by_recover(self, tmp_path):
        """Staged batches of a table that never reached its first
        commit (no streaming log exists) are still found and swept —
        the staged-table marker records where they live."""
        from hyperspace_tpu.streaming.ingest import get_queue
        session = _session(tmp_path)
        hs = Hyperspace(session)
        data = str(tmp_path / "orphan")
        hs.append(data, _frame(_rng(93), 60))
        staging = os.path.join(data, SC.STAGING_DIR)
        assert len(os.listdir(staging)) == 1
        summary = hs.recover()
        assert summary["streaming"]["staging_swept"] >= 1
        assert not os.path.isdir(staging)
        assert get_queue().staged_count(os.path.abspath(data)) == 0
        # The discarded bootstrap no longer pins a schema: a DIFFERENT
        # first schema is accepted on the still-empty table.
        hs.append(data, pd.DataFrame({"x": np.asarray([1, 2], np.int64)}))
        assert hs.commit(data)["committed_batches"] == 1
        assert session.read.parquet(data).columns == ["x"]

    def test_failed_staging_write_leaves_no_file_or_memo(self, tmp_path,
                                                         monkeypatch):
        """A pq.write_table failure mid-append (disk full) must clean
        up the partial staging file AND unpin the schema memo its own
        discarded batch bootstrapped — a retry with a different first
        schema succeeds on the still-empty table."""
        import pyarrow.parquet as pq_mod
        session = _session(tmp_path)
        hs = Hyperspace(session)
        data = str(tmp_path / "newt")

        real = pq_mod.write_table

        def boom(*a, **k):
            raise OSError(28, "No space left on device")

        monkeypatch.setattr(pq_mod, "write_table", boom)
        with pytest.raises(OSError):
            hs.append(data, pd.DataFrame(
                {"a": np.asarray([1, 2], np.int64)}))
        monkeypatch.setattr(pq_mod, "write_table", real)
        staging = os.path.join(data, SC.STAGING_DIR)
        assert not os.path.isdir(staging) or not os.listdir(staging)
        # The memo is gone: a different first schema is accepted.
        hs.append(data, pd.DataFrame({"x": np.asarray([3], np.int64)}))
        assert hs.commit(data)["committed_batches"] == 1
        assert session.read.parquet(data).columns == ["x"]

    def test_failed_prebuild_write_cleans_index_staging(self, tmp_path,
                                                        monkeypatch):
        """A covering-delta prebuild that dies mid bucket write must
        remove its partial staging dir — it never reached
        staged.covering, so append()'s cleanup can't see it."""
        from hyperspace_tpu.actions import create as create_mod
        session, hs, data = _lake(tmp_path, skipping=False)

        def boom(*a, **k):
            raise OSError(28, "No space left on device")

        monkeypatch.setattr(create_mod, "_write_bucket_files", boom)
        with pytest.raises(OSError):
            hs.append(data, _frame(_rng(61), 100))
        stagings = glob.glob(str(
            tmp_path / "**" / SC.STAGING_DIR / "*"), recursive=True)
        assert stagings == []

    def test_commit_already_covered_by_racing_refresh_skips(
            self, tmp_path):
        """A refresh that raced into the publish->land window and
        indexed the batch file must not be landed on top of — the
        delta would put the same rows in the index twice."""
        from hyperspace_tpu.streaming.ingest import (
            _LandCoveringDeltas, _staging_dir)
        session, hs, data = _lake(tmp_path, skipping=False)
        hs.append(data, _frame(_rng(67), 100))
        hs.commit(data)
        entry = session.index_collection_manager.get_index("cx")
        batch_file = next(f for f in (i.name for i in
                                      entry.source_file_info_set)
                          if SC.INGEST_FILE_PREFIX in os.path.basename(f))
        # Rebuild the landing for the already-covered batch by hand —
        # the deterministic stand-in for the race.
        from hyperspace_tpu.index.data_manager import IndexDataManager
        from hyperspace_tpu.index.log_manager import IndexLogManager
        from hyperspace_tpu.index.path_resolver import PathResolver
        from hyperspace_tpu.streaming.ingest import (
            _covering_layout, _CoveringDelta, StagedBatch)
        resolver = PathResolver(session.hs_conf)
        index_path = resolver.get_index_path("cx")
        staged_dir = os.path.join(_staging_dir(index_path), "ghost")
        os.makedirs(staged_dir)
        with open(os.path.join(staged_dir, "junk"), "w") as f:
            f.write("x")
        batch = StagedBatch("ghost", os.path.abspath(data), "", batch_file,
                            100, 1, 1, None)
        delta = _CoveringDelta("cx", index_path, staged_dir, None,
                               _covering_layout(entry))
        action = _LandCoveringDeltas(
            session, IndexLogManager(index_path),
            IndexDataManager(index_path), os.path.abspath(data),
            [(batch, delta)])
        with pytest.raises(HyperspaceException, match="already covers"):
            action.validate()
        assert not os.path.isdir(staged_dir)  # dead files removed

    def test_commit_does_not_walk_table_dir(self, tmp_path, monkeypatch):
        """The commit write path stays O(batch): landing deltas pins
        schema and file list from the prev entry instead of re-walking
        the table directory per index."""
        from hyperspace_tpu.util import file_utils as fu
        session, hs, data = _lake(tmp_path)
        hs.append(data, _frame(_rng(71), 100))
        walked = []
        real = fu.list_leaf_files

        def spy(path, *a, **k):
            walked.append(os.path.abspath(str(path)))
            return real(path, *a, **k)

        monkeypatch.setattr(fu, "list_leaf_files", spy)
        hs.commit(data)
        assert os.path.abspath(data) not in walked

    def test_bootstrap_table_from_appends_alone(self, tmp_path):
        """A table born from the streaming path: no base file, no
        indexes — the first commit creates the table log and the files
        become queryable."""
        session = _session(tmp_path)
        hs = Hyperspace(session)
        data = str(tmp_path / "newborn")
        rng = _rng(47)
        hs.append(data, _frame(rng, 120))
        hs.append(data, _frame(rng, 80))
        out = hs.commit(data)
        assert out["committed_batches"] == 2
        assert session.read.parquet(data).count() == 200
        mgr = IndexLogManager(table_log_dir(session, data))
        assert mgr.get_latest_stable_log().state == States.ACTIVE
        # And the stream keeps flowing.
        hs.append(data, _frame(rng, 30))
        hs.commit(data)
        assert session.read.parquet(data).count() == 230

    def test_append_backpressure_and_schema_checks(self, tmp_path):
        session, hs, data = _lake(tmp_path, skipping=False)
        session.conf.set(SC.MAX_STAGED_BATCHES, "2")
        rng = _rng(3)
        with pytest.raises(HyperspaceException, match="schema mismatch"):
            hs.append(data, pd.DataFrame({"other": [1, 2]}))
        with pytest.raises(HyperspaceException, match="type fork"):
            hs.append(data, pd.DataFrame(
                {"k": ["a", "b"], "v": np.asarray([1, 2], np.int64)}))
        with pytest.raises(HyperspaceException, match="empty batch"):
            hs.append(data, pd.DataFrame({"k": [], "v": []}))
        hs.append(data, _frame(rng, 50))
        hs.append(data, _frame(rng, 50))
        # Backpressure rejects BEFORE staging/prebuilding (no leaked
        # staging files for a refused append).
        with pytest.raises(HyperspaceException,
                           match="maxStagedBatches"):
            hs.append(data, _frame(rng, 50))
        assert len(os.listdir(os.path.join(data, SC.STAGING_DIR))) == 2
        # Staged batches are invisible until commit.
        assert len(session.read.parquet(data).plan.relation.all_files()) \
            == 1
        hs.commit(data)
        assert len(session.read.parquet(data).plan.relation.all_files()) \
            == 3

    def test_result_cache_invalidates_per_commit(self, tmp_path):
        """The r06 log-version cache keys invalidate by construction:
        a committed batch flips every index's latest-entry fingerprint,
        so post-commit queries can never serve a pre-commit entry."""
        session, hs, data = _lake(tmp_path, skipping=False)
        session.conf.set("serving.result_cache.enabled", "true")
        session.conf.set("serving.result_cache.minComputeSeconds", "0")
        session.conf.set("serving.result_cache.minInputBytes", "0")
        session.enable_hyperspace()
        rng = _rng(31)
        t = session.read.parquet(data)
        n0 = t.count()
        assert t.count() == n0  # warm repeat (cache hit or not — equal)
        assert session.result_cache is not None
        hs.append(data, _frame(rng, 123))
        hs.commit(data)
        assert session.read.parquet(data).count() == n0 + 123


# ---------------------------------------------------------------------------
# Crash safety: kill -9 mid-commit, then recover.
# ---------------------------------------------------------------------------

_CHILD = textwrap.dedent("""
    import os, sys
    import numpy as np
    import pandas as pd

    point, spec, data_dir, sys_dir = sys.argv[1:5]

    import hyperspace_tpu as hst
    from hyperspace_tpu.api import Hyperspace, IndexConfig

    session = hst.Session(system_path=sys_dir)
    session.conf.set("hyperspace.index.numBuckets", 4)
    session.conf.set("hyperspace.index.lineage.enabled", "true")
    session.conf.set("hyperspace.tpu.distributed.enabled", "false")
    hs = Hyperspace(session)

    rng = np.random.default_rng(41)
    def frame(n):
        return pd.DataFrame({
            "k": rng.integers(0, 40, n).astype(np.int64),
            "v": rng.integers(0, 9, n).astype(np.int64)})

    # A healthy first commit establishes the table log.
    hs.append(data_dir, frame(150))
    hs.commit(data_dir)

    hs.append(data_dir, frame(200))
    session.conf.set(
        "hyperspace.tpu.robustness.faults." + point, spec)
    if point == "ingest.stage":
        hs.append(data_dir, frame(99))   # dies while staging
    else:
        hs.commit(data_dir)              # dies while publishing
    print("CHILD-SURVIVED")
""")


def _run_child(tmp_path, point, spec):
    script = str(tmp_path / "child.py")
    with open(script, "w") as f:
        f.write(_CHILD)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, script, point, spec, str(tmp_path / "tbl"),
         str(tmp_path / "indexes")],
        env=env, capture_output=True, text=True, timeout=420, cwd=ROOT)


class TestCrashRecovery:
    def _prepare(self, tmp_path):
        data = str(tmp_path / "tbl")
        _write_base(data, _rng())
        (tmp_path / "indexes").mkdir(exist_ok=True)
        session = _session(tmp_path)
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(data),
                        IndexConfig("cx", ["k"], ["v"]))
        return session, hs, data

    @pytest.mark.parametrize("point,spec", [
        ("ingest.publish", "kill:nth=1"),
        ("ingest.stage", "kill:nth=1"),
    ])
    def test_kill9_then_recover_rolls_back(self, tmp_path, point, spec):
        session, hs, data = self._prepare(tmp_path)
        proc = _run_child(tmp_path, point, spec)
        assert proc.returncode == -signal.SIGKILL, \
            f"rc={proc.returncode}\n{proc.stdout}\n{proc.stderr}"
        assert "CHILD-SURVIVED" not in proc.stdout

        log_dir = table_log_dir(session, data)
        mgr = IndexLogManager(log_dir)
        if point == "ingest.publish":
            # The commit died between begin and publication: transient
            # tip, batch file never visible.
            assert mgr.get_latest_log().state == States.REFRESHING
        # Ground truth before recovery: the first (healthy) commit only.
        expected_files = 2  # p0 + first committed batch

        summary = hs.recover()
        assert not summary["errors"], summary
        stream = summary["streaming"]
        key = table_key(data)
        assert key in stream["tables"]
        if point == "ingest.publish":
            assert key in stream["rolled_back"]
        assert stream["staging_swept"] >= 1  # the dead appender's batch

        # The staged batch rolled back: the table serves exactly the
        # pre-crash committed state, and the log tip is stable again.
        files = session.read.parquet(data).plan.relation.all_files()
        assert len(files) == expected_files
        assert mgr.get_latest_log().state in STABLE_STATES
        assert not glob.glob(os.path.join(data, SC.STAGING_DIR, "*"))
        a, b = _answers(session, data)
        pd.testing.assert_frame_equal(a, b)

        # recover() again is a no-op; compact() after recovery changes
        # no answer and a second compact folds nothing.
        again = hs.recover()
        assert not again["streaming"]["rolled_back"]
        assert again["streaming"]["staging_swept"] == 0
        before = a
        hs.compact(None)
        a2, b2 = _answers(session, data)
        pd.testing.assert_frame_equal(a2, before)
        pd.testing.assert_frame_equal(a2, b2)
        second = hs.compact(None)
        assert not second["compacted"], second

        # The interrupted ingestion completes on the recovered lake.
        hs.append(data, _frame(_rng(77), 120))
        out = hs.commit(data)
        assert out["committed_batches"] == 1
        a3, b3 = _answers(session, data)
        pd.testing.assert_frame_equal(a3, b3)

    def test_redo_when_publication_completed(self, tmp_path):
        """A crash AFTER every batch file landed (torn only the final
        entry) redoes the commit instead of rolling it back."""
        from hyperspace_tpu.streaming.ingest import (_StreamingCommitAction,
                                                     get_queue)
        session, hs, data = self._prepare(tmp_path)
        hs.append(data, _frame(_rng(51), 150))
        hs.commit(data)

        hs.append(data, _frame(_rng(52), 250))
        queue = get_queue()
        batches = queue.pop_all(os.path.abspath(data))
        assert batches
        log_mgr = IndexLogManager(table_log_dir(session, data))
        action = _StreamingCommitAction(session, log_mgr,
                                        os.path.abspath(data), batches)
        # Simulate the wreck: begin + publish, no final entry (the
        # crash-harness state right after op() returned).
        action.validate()
        action._begin()
        action.op()
        assert log_mgr.get_latest_log().state == States.REFRESHING

        summary = hs.recover()
        assert not summary["errors"], summary
        assert table_key(data) in summary["streaming"]["completed"]
        assert log_mgr.get_latest_log().state == States.ACTIVE
        # The batch stayed committed: 3 visible files, parity holds.
        files = session.read.parquet(data).plan.relation.all_files()
        assert len(files) == 3
        a, b = _answers(session, data)
        pd.testing.assert_frame_equal(a, b)

    def test_commit_conflict_requeues(self, tmp_path):
        """Losing the put-if-absent race (a cross-process committer)
        re-queues the staged batches for retry."""
        from hyperspace_tpu.index.log_entry import IndexLogEntry
        from hyperspace_tpu.streaming.ingest import get_queue
        session, hs, data = self._prepare(tmp_path)
        hs.append(data, _frame(_rng(61), 100))
        hs.commit(data)
        hs.append(data, _frame(_rng(62), 100))

        # A foreign writer claims the next log id first.
        log_mgr = IndexLogManager(table_log_dir(session, data))
        latest = log_mgr.get_latest_log()
        squatter = IndexLogEntry.from_json(latest.to_json())
        squatter.state = States.REFRESHING
        assert log_mgr.write_log(latest.id + 1, squatter)

        before = get_queue().staged_count(os.path.abspath(data))
        assert before == 1
        with pytest.raises(HyperspaceException):
            hs.commit(data)
        # The loser re-queued its batches instead of losing them.
        assert get_queue().staged_count(os.path.abspath(data)) == before

        # Recovery clears the squatter's wreck — and, per the operator
        # contract, sweeps ALL staged state (a dead appender's batches
        # are indistinguishable from ours).
        assert not hs.recover()["errors"]
        assert get_queue().staged_count(os.path.abspath(data)) == 0
        # The ingestion path is healthy again.
        hs.append(data, _frame(_rng(63), 100))
        out = hs.commit(data)
        assert out["committed_batches"] == 1
        a, b = _answers(session, data)
        pd.testing.assert_frame_equal(a, b)


    def test_lineage_drift_repaired_at_commit(self, tmp_path):
        """A racing writer can move the index's id base between append
        and commit; the committed delta repairs its lineage column in
        place instead of wrecking the commit."""
        from hyperspace_tpu.streaming.ingest import get_queue
        session, hs, data = self._prepare(tmp_path)
        hs.append(data, _frame(_rng(53), 180))
        queue = get_queue()
        with queue._lock:  # white-box: force a wrong prediction
            staged = queue._staged[os.path.abspath(data)]
            assert staged[0].covering[0].lineage_id is not None
            staged[0].covering[0].lineage_id += 7
        out = hs.commit(data)
        assert out["committed_batches"] == 1
        # The landed index rows carry the COMMITTED id: masking deleted
        # files by lineage stays sound, and answers match the raw scan.
        entry = session.index_collection_manager.get_index("cx")
        batch_file = next(f for f in entry.content.files
                          if "part-ingest-" not in f)
        assert batch_file  # index content exists
        ingest_info = next(
            f for f in entry.relation.data.content.file_infos
            if SC.INGEST_FILE_PREFIX in f.name)
        delta_files = [f for f in entry.content.files
                       if f.split("v__=")[-1].startswith("1")]
        import pyarrow.parquet as _pq
        ids = set()
        for f in delta_files:
            t = _pq.read_table(f, partitioning=None)
            if "_data_file_id" in t.schema.names:
                ids.update(t.column("_data_file_id").to_pylist())
        assert ids == {ingest_info.id}
        a, b = _answers(session, data)
        pd.testing.assert_frame_equal(a, b)

    def test_mid_protocol_failure_abandons_inflight(self, tmp_path):
        """A commit failing AFTER op started must not leave its batches
        counted as in-flight (poisoned backpressure/lineage) — they are
        abandoned to the recovery sweep."""
        from hyperspace_tpu.streaming.ingest import get_queue
        session, hs, data = self._prepare(tmp_path)
        hs.append(data, _frame(_rng(57), 90))
        session.conf.set(
            "hyperspace.tpu.robustness.faults.ingest.publish",
            "error:nth=1,exc=OSError")
        with pytest.raises(Exception):
            hs.commit(data)
        session.conf.unset(
            "hyperspace.tpu.robustness.faults.ingest.publish")
        assert get_queue().staged_count(os.path.abspath(data)) == 0
        assert not hs.recover()["errors"]
        hs.append(data, _frame(_rng(58), 90))
        assert hs.commit(data)["committed_batches"] == 1
        a, b = _answers(session, data)
        pd.testing.assert_frame_equal(a, b)

    def test_torn_streaming_log_tip_recovers(self, tmp_path):
        """An unparseable tip entry (crash mid entry upload) blocks
        commit() with a 'run recover()' error — and recover() deletes
        the torn file instead of skipping it forever."""
        session, hs, data = self._prepare(tmp_path)
        hs.append(data, _frame(_rng(59), 80))
        hs.commit(data)
        log_dir = os.path.join(table_log_dir(session, data),
                               IndexConstants.HYPERSPACE_LOG)
        mgr = IndexLogManager(table_log_dir(session, data))
        torn_id = mgr.get_latest_id() + 1
        with open(os.path.join(log_dir, str(torn_id)), "w") as f:
            f.write("{not json")
        hs.append(data, _frame(_rng(60), 80))
        with pytest.raises(HyperspaceException, match="recover"):
            hs.commit(data)
        assert not hs.recover()["errors"]
        assert not os.path.exists(os.path.join(log_dir, str(torn_id)))
        hs.append(data, _frame(_rng(64), 80))
        assert hs.commit(data)["committed_batches"] == 1

    def test_torn_end_entry_redoes_in_one_pass(self, tmp_path):
        """A crash that tore the final (end) entry — transient entry
        beneath it, batch files already published — must resolve in ONE
        recover() pass: delete the torn tip, then fall through to the
        redo branch."""
        from hyperspace_tpu.streaming.ingest import (_StreamingCommitAction,
                                                     get_queue)
        session, hs, data = self._prepare(tmp_path)
        hs.append(data, _frame(_rng(66), 100))
        hs.commit(data)
        hs.append(data, _frame(_rng(67), 100))
        batches = get_queue().pop_all(os.path.abspath(data))
        log_mgr = IndexLogManager(table_log_dir(session, data))
        action = _StreamingCommitAction(session, log_mgr,
                                        os.path.abspath(data), batches)
        action.validate()
        action._begin()
        action.op()  # files published; final entry never written...
        torn_id = log_mgr.get_latest_id() + 1
        log_dir = os.path.join(table_log_dir(session, data),
                               IndexConstants.HYPERSPACE_LOG)
        with open(os.path.join(log_dir, str(torn_id)), "w") as f:
            f.write("{torn end")  # ...except as a torn write
        summary = hs.recover()
        assert not summary["errors"], summary
        assert summary["streaming"]["torn_entries"] == 1
        assert table_key(data) in summary["streaming"]["completed"]
        assert log_mgr.get_latest_log().state == States.ACTIVE
        assert session.read.parquet(data).count() == 2000 + 200
        # The stream flows on without a second recover().
        hs.append(data, _frame(_rng(68), 50))
        assert hs.commit(data)["committed_batches"] == 1
        a, b = _answers(session, data)
        pd.testing.assert_frame_equal(a, b)


# ---------------------------------------------------------------------------
# Concurrency: appenders vs readers, serving-path hammer.
# ---------------------------------------------------------------------------

class TestConcurrency:
    def test_appenders_vs_readers(self, tmp_path):
        session, hs, data = _lake(tmp_path, skipping=False)
        errors = []
        sizes = {2000}
        stop = threading.Event()

        def reader():
            while not stop.is_set():
                try:
                    n = session.read.parquet(data).count()
                    # Every observed count is a committed prefix size.
                    if n not in sizes:
                        errors.append(f"saw {n}, valid {sorted(sizes)}")
                except Exception as e:  # noqa: BLE001 — collected
                    errors.append(f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=reader) for _ in range(2)]
        for t in threads:
            t.start()
        rng = _rng(71)
        total = 2000
        try:
            for i in range(4):
                n = 100 + 10 * i
                hs.append(data, _frame(rng, n))
                total += n
                sizes.add(total)
                hs.commit(data)
        finally:
            stop.set()
            for t in threads:
                t.join()
        assert not errors, errors[:5]
        assert session.read.parquet(data).count() == total
        a, b = _answers(session, data)
        pd.testing.assert_frame_equal(a, b)

    def test_concurrent_appends_one_table(self, tmp_path):
        """Appends race from many threads (serialized per table by the
        commit queue); one commit lands them all, lineage ids intact."""
        session, hs, data = _lake(tmp_path, skipping=False)
        errors = []

        def worker(seed):
            try:
                hs.append(data, _frame(_rng(seed), 60))
            except Exception as e:  # noqa: BLE001 — collected
                errors.append(f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=worker, args=(100 + i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        out = hs.commit(data)
        assert out["committed_batches"] == 6
        assert out["indexes_updated"] == ["cx"]
        assert session.read.parquet(data).count() == 2000 + 6 * 60
        session.enable_hyperspace()
        q = session.read.parquet(data).filter(col("k") == 3).select("k")
        assert "IndexScan" in session.optimize(
            q.plan, diagnostic=True).tree_string()
        a, b = _answers(session, data)
        pd.testing.assert_frame_equal(a, b)


# ---------------------------------------------------------------------------
# Standing queries through the serving frontend.
# ---------------------------------------------------------------------------

class TestStandingQueries:
    def _frontend(self, session):
        from hyperspace_tpu.serving import frontend as fe_mod
        # Commits notify the PROCESS-DEFAULT frontend; make this test's
        # frontend the default (first-constructed-wins otherwise).
        with fe_mod._DEFAULT_LOCK:
            fe_mod._DEFAULT = None
        session.conf.set("hyperspace.tpu.serving.maxConcurrency", "8")
        session.conf.set("hyperspace.tpu.serving.queueDepth", "64")
        return fe_mod.ServingFrontend(session)

    def test_subscription_delivers_per_commit(self, tmp_path):
        session, hs, data = _lake(tmp_path, capture=True,
                                  skipping=False)
        front = self._frontend(session)
        t = session.read.parquet(data)
        sub = front.subscribe(t.filter(col("k") == 7).select("k", "v"))
        rng = _rng(81)
        expected = []
        sizes = []
        for i in range(3):
            hs.append(data, _frame(rng, 150))
            out = hs.commit(data)
            assert out["subscriptions_fired"] == 1
            # Ground truth: re-run the plan over a FRESH listing after
            # this commit — a standing query follows the stream, so
            # each delivery must include the rows this commit landed.
            exp = (session.read.parquet(data)
                   .filter(col("k") == 7).select("k", "v").to_pandas()
                   .sort_values(["k", "v"]).reset_index(drop=True))
            expected.append(exp)
            sizes.append(len(exp))
        deliveries = sub.wait_for(3, timeout=60.0)
        assert len(deliveries) == 3
        for d, exp in zip(deliveries, expected):
            assert d.ok, d.error
            got = pd.DataFrame(
                {n: np.asarray(c.data) for n, c in
                 d.result.to_host().columns.items()}).sort_values(
                ["k", "v"]).reset_index(drop=True)
            pd.testing.assert_frame_equal(got, exp)
        # The deliveries genuinely tracked the growing table.
        assert sizes == sorted(sizes) and sizes[-1] > sizes[0]
        assert any(type(e).__name__ == "StandingQueryEvent"
                   for e in sink().events)
        assert sub.unsubscribe()
        hs.append(data, _frame(rng, 50))
        out = hs.commit(data)
        assert out["subscriptions_fired"] == 0

    def test_unrelated_table_commit_does_not_fire(self, tmp_path):
        """A commit to a table a subscription never reads must not burn
        a worker slot on it."""
        session, hs, data = _lake(tmp_path, skipping=False)
        front = self._frontend(session)
        sub = front.subscribe(
            session.read.parquet(data).select("k"))
        assert sub.tables  # source roots recorded
        other = str(tmp_path / "other")
        hs.append(other, _frame(_rng(85), 40))
        out = hs.commit(other)
        assert out["subscriptions_fired"] == 0
        hs.append(data, _frame(_rng(86), 40))
        assert hs.commit(data)["subscriptions_fired"] == 1
        assert sub.wait_for(1, timeout=30.0)

    def test_latest_is_max_by_seq_not_completion_order(self):
        """A slow earlier fire completing after a later one must not
        shadow the newer commit's answer in latest()."""
        from hyperspace_tpu.streaming.subscriptions import (
            SubscriptionRegistry)
        reg = SubscriptionRegistry()
        sub = reg.subscribe(None, object(), None, "c", None, 8, 16)
        s1 = sub._next_seq()
        s2 = sub._next_seq()
        sub._deliver(s2, "t", result="new")
        sub._deliver(s1, "t", result="old")  # earlier fire lands last
        d = sub.latest(timeout=1.0)
        assert d.seq == s2 and d.result == "new"

    def test_unsubscribe_wakes_blocked_waiter(self, tmp_path):
        """A waiter blocked in wait_for must raise promptly when the
        subscription closes, not sit out its full timeout."""
        from hyperspace_tpu.exceptions import HyperspaceException
        session, hs, data = _lake(tmp_path, skipping=False)
        front = self._frontend(session)
        sub = front.subscribe(session.read.parquet(data).select("k"))
        caught = []
        started = threading.Event()

        def waiter():
            started.set()
            try:
                sub.wait_for(1, timeout=300.0)
            except Exception as e:
                caught.append(e)

        th = threading.Thread(target=waiter)
        th.start()
        started.wait(10.0)
        time.sleep(0.05)  # let the waiter enter the condition wait
        assert sub.unsubscribe()
        th.join(10.0)
        assert not th.is_alive(), "waiter still blocked after close"
        assert caught and isinstance(caught[0], HyperspaceException)
        assert "closed" in str(caught[0])

    def test_hammer_subscriptions_and_adhoc(self, tmp_path):
        """Commits re-fire 4 standing queries while ad-hoc submits
        hammer the same 8-thread frontend; every delivery and every
        ad-hoc result completes."""
        session, hs, data = _lake(tmp_path, skipping=False)
        front = self._frontend(session)
        t = session.read.parquet(data)
        subs = [front.subscribe(t.filter(col("k") == k).select("k", "v"),
                                deadline_ms=60000.0)
                for k in (1, 2, 3, 4)]
        rng = _rng(91)
        pendings = []
        for i in range(3):
            hs.append(data, _frame(rng, 120))
            hs.commit(data)
            for k in (5, 6):
                pendings.append(front.submit(
                    t.filter(col("k") == k).select("k", "v"),
                    session=session))
        for sub in subs:
            deliveries = sub.wait_for(3, timeout=120.0)
            assert all(d.ok for d in deliveries), \
                [str(d.error) for d in deliveries if not d.ok]
        for p in pendings:
            p.result(timeout=120.0)
        front.drain()
        stats = front.stats()
        assert stats["subscriptions"]["live"] == 4
        assert stats["subscriptions"]["fired_queries"] == 12
        assert stats["failed"] == 0

    def test_submit_crash_never_escapes_commit(self, tmp_path):
        """A non-rejection submit-time failure is delivered as the
        fire's error — commit() (which already published durably) must
        not raise, and later subscriptions still fire."""
        session, hs, data = _lake(tmp_path, skipping=False)
        front = self._frontend(session)
        t = session.read.parquet(data)
        bad = front.subscribe(t.select("k"))
        bad.plan = object()  # fresh_plan falls back; submit() blows up
        good = front.subscribe(t.select("v"))
        hs.append(data, _frame(_rng(87), 40))
        out = hs.commit(data)  # must NOT raise
        assert out["committed_batches"] == 1
        assert not bad.latest(timeout=10.0).ok
        assert good.wait_for(1, timeout=30.0)[0].ok

    def test_rejected_fire_delivers_error(self, tmp_path):
        session, hs, data = _lake(tmp_path, skipping=False)
        from hyperspace_tpu.streaming.subscriptions import (
            SubscriptionRegistry)
        front = self._frontend(session)
        t = session.read.parquet(data)
        sub = front.subscribe(t.select("k"))
        assert isinstance(front._subscriptions, SubscriptionRegistry)
        # Choke admission so the fire is shed — the subscription sees
        # the rejection as an error delivery, never a silent skip.
        session.conf.set("hyperspace.tpu.serving.queueDepth", "1")
        front._queue.extend([object()])  # fake a full queue
        try:
            fired = front.notify_commit(session, data)
        finally:
            front._queue.clear()
        assert fired == 0
        d = sub.latest(timeout=10.0)
        assert not d.ok


# ---------------------------------------------------------------------------
# Compaction + the op-log lookup cache.
# ---------------------------------------------------------------------------

def _count_log_files(path):
    log = os.path.join(path, IndexConstants.HYPERSPACE_LOG)
    return len([n for n in os.listdir(log) if n.isdigit()])


class TestCompaction:
    def test_entries_and_log_read_bytes_drop(self, tmp_path, monkeypatch):
        from hyperspace_tpu.index import log_store
        session, hs, data = _lake(tmp_path, skipping=False)
        rng = _rng(13)
        for _ in range(5):
            hs.append(data, _frame(rng, 80))
            hs.commit(data)
        idx_path = os.path.join(str(tmp_path / "indexes"), "cx")
        entries_before = _count_log_files(idx_path)
        assert entries_before >= 10  # create + 5 commits × 2

        a_before, b_before = _answers(session, data)
        pd.testing.assert_frame_equal(a_before, b_before)

        # Query-time log reads, cold-cache, before compaction. The probe
        # covers both hot-path shapes: the per-query result-cache key
        # derivation (lists the log dir + reads the tip entry) and the
        # version scan the versioned-source/hybrid rules run
        # (get_index_versions walks EVERY entry — the O(n-entries) read).
        read_bytes = {"n": 0}
        listed = {"n": 0}
        real_read = log_store.LocalFsLogStore.read
        real_list = log_store.LocalFsLogStore.list_numeric_ids

        def counting_read(self, path):
            data_ = real_read(self, path)
            if data_ is not None:
                read_bytes["n"] += len(data_)
            return data_

        def counting_list(self, path):
            ids = real_list(self, path)
            listed["n"] += len(ids)
            return ids

        monkeypatch.setattr(log_store.LocalFsLogStore, "read",
                            counting_read)
        monkeypatch.setattr(log_store.LocalFsLogStore,
                            "list_numeric_ids", counting_list)

        def cold_probe():
            get_lookup_cache().clear()
            session.index_collection_manager.clear_cache()
            read_bytes["n"] = listed["n"] = 0
            session.enable_hyperspace()
            session.read.parquet(data).filter(
                col("k") == 7).select("k", "v").to_pandas()
            IndexLogManager(idx_path).get_index_versions(
                [States.ACTIVE, States.DELETED])
            return read_bytes["n"], listed["n"]

        bytes_before, listed_before = cold_probe()
        out = hs.compact(None)
        folded = out["compacted"]["cx"]["entries_folded"]
        assert folded >= entries_before - 1
        entries_after = _count_log_files(idx_path)
        assert entries_after == 1  # just the checkpoint
        bytes_after, listed_after = cold_probe()
        assert bytes_after < bytes_before, (bytes_before, bytes_after)
        assert listed_after < listed_before, (listed_before, listed_after)
        monkeypatch.undo()

        # Results and recover() byte-identical across the compaction.
        a_after, b_after = _answers(session, data)
        pd.testing.assert_frame_equal(a_after, a_before)
        pd.testing.assert_frame_equal(a_after, b_after)
        summary = hs.recover()
        assert not summary["errors"]
        assert not summary["cancelled"]
        assert not summary["vacuumed"]

        # A second compact folds nothing (idempotent).
        again = hs.compact(None)
        assert "cx" not in again["compacted"]
        # The checkpoint pins the compaction generation.
        tip = IndexLogManager(idx_path).get_latest_stable_log()
        assert tip.properties[SC.COMPACTION_GENERATION_PROPERTY] == "1"
        assert SC.COMPACTED_THROUGH_PROPERTY in tip.properties

        # Post-compaction ingestion keeps working and carries the
        # generation forward.
        hs.append(data, _frame(rng, 60))
        hs.commit(data)
        tip2 = IndexLogManager(idx_path).get_latest_stable_log()
        assert tip2.properties[SC.COMPACTION_GENERATION_PROPERTY] == "1"
        a2, b2 = _answers(session, data)
        pd.testing.assert_frame_equal(a2, b2)

    def test_compaction_vacuums_superseded_versions(self, tmp_path):
        session, hs, data = _lake(tmp_path, capture=True)
        rng = _rng(19)
        for _ in range(3):
            hs.append(data, _frame(rng, 90))
            hs.commit(data)
        # The sketch index rewrites its whole (tiny) table per commit,
        # so superseded v__ dirs accumulate — compaction vacuums them.
        sx_path = os.path.join(str(tmp_path / "indexes"), "sx")
        vdirs_before = len(glob.glob(os.path.join(sx_path, "v__=*")))
        assert vdirs_before == 4
        out = hs.compact(None)
        assert out["compacted"]["sx"]["versions_vacuumed"] == 3
        assert len(glob.glob(os.path.join(sx_path, "v__=*"))) == 1
        compaction_events = [
            e for e in sink().events
            if type(e).__name__ == "StreamingCompactionEvent"]
        assert {e.subject for e in compaction_events} >= {"cx", "sx"}
        assert all(e.generation == 1 for e in compaction_events)
        sx_event = next(e for e in compaction_events if e.subject == "sx")
        assert sx_event.versions_vacuumed == 3
        assert sx_event.entries_folded >= 6
        a, b = _answers(session, data)
        pd.testing.assert_frame_equal(a, b)

    def test_compaction_skips_transient_tip(self, tmp_path):
        from hyperspace_tpu.index.log_entry import IndexLogEntry
        session, hs, data = _lake(tmp_path, skipping=False)
        rng = _rng(29)
        for _ in range(3):
            hs.append(data, _frame(rng, 50))
            hs.commit(data)
        idx_path = os.path.join(str(tmp_path / "indexes"), "cx")
        mgr = IndexLogManager(idx_path)
        latest = mgr.get_latest_log()
        wreck = IndexLogEntry.from_json(latest.to_json())
        wreck.state = States.REFRESHING
        assert mgr.write_log(latest.id + 1, wreck)
        out = hs.compact(["cx"])
        assert "cx" in out["skipped"]
        assert "transient" in out["skipped"]["cx"]


class TestOpLogLookupCache:
    def test_repeat_queries_stop_rereading_logs(self, tmp_path,
                                                monkeypatch):
        from hyperspace_tpu.index import log_store
        from hyperspace_tpu.index.log_manager import LogLookupCache
        # Disable the racy-token guard: this test's writes all happen
        # "just now", and the guard (correctly) refuses to pin tokens
        # that fresh on coarse-timestamp filesystems.
        monkeypatch.setattr(LogLookupCache, "_RACY_WINDOW_NS", 0)
        session, hs, data = _lake(tmp_path, skipping=False)
        rng = _rng(37)
        for _ in range(3):
            hs.append(data, _frame(rng, 70))
            hs.commit(data)
        session.enable_hyperspace()

        reads = {"n": 0}
        lists = {"n": 0}
        real_read = log_store.LocalFsLogStore.read
        real_list = log_store.LocalFsLogStore.list_numeric_ids

        def counting_read(self, path):
            reads["n"] += 1
            return real_read(self, path)

        def counting_list(self, path):
            lists["n"] += 1
            return real_list(self, path)

        monkeypatch.setattr(log_store.LocalFsLogStore, "read",
                            counting_read)
        monkeypatch.setattr(log_store.LocalFsLogStore,
                            "list_numeric_ids", counting_list)

        ids = session.index_collection_manager.latest_log_ids()
        warm_reads, warm_lists = reads["n"], lists["n"]
        # The exec trace the satellite asks for: repeats are pure memo
        # hits — zero further entry reads, zero further dir listings.
        for _ in range(5):
            assert session.index_collection_manager.latest_log_ids() == ids
        assert reads["n"] == warm_reads
        assert lists["n"] == warm_lists

        # A mutation invalidates: the fingerprint changes and is
        # re-read, never served stale.
        hs.append(data, _frame(rng, 40))
        hs.commit(data)
        ids2 = session.index_collection_manager.latest_log_ids()
        assert ids2 != ids
        stats = get_lookup_cache().stats()
        assert stats["hits"] > 0 and stats["invalidations"] > 0

    def test_cross_process_writes_invalidate_by_mtime(self, tmp_path):
        """A writer this process never saw (no in-process invalidation)
        still flips the memo: the log-dir mtime token changes."""
        session, hs, data = _lake(tmp_path, skipping=False)
        idx_path = os.path.join(str(tmp_path / "indexes"), "cx")
        mgr = IndexLogManager(idx_path)
        fp1 = mgr.latest_entry_fingerprint()
        assert mgr.latest_entry_fingerprint() == fp1  # memo hit
        # Simulate the foreign process: raw file write, bypassing every
        # IndexLogManager invalidation hook.
        from hyperspace_tpu.index.log_entry import IndexLogEntry
        latest = mgr.get_latest_log()
        foreign = IndexLogEntry.from_json(latest.to_json())
        foreign.state = States.DELETED
        foreign.id = latest.id + 1
        log_dir = os.path.join(idx_path, IndexConstants.HYPERSPACE_LOG)
        time.sleep(0.01)  # ensure a distinct mtime tick
        with open(os.path.join(log_dir, str(latest.id + 1)), "w") as f:
            f.write(foreign.to_json())
        fp2 = mgr.latest_entry_fingerprint()
        assert fp2 != fp1
        assert fp2[0] == latest.id + 1


# ---------------------------------------------------------------------------
# Registry references (frozen span/fault registries demand observation).
# ---------------------------------------------------------------------------

class TestRegistries:
    def test_ingest_names_registered(self):
        from hyperspace_tpu.robustness import fault_names as FN
        from hyperspace_tpu.telemetry import span_names as SN
        assert SN.INGEST_APPEND == "ingest.append"
        assert SN.INGEST_COMMIT == "ingest.commit"
        assert SN.INGEST_COMPACT == "ingest.compact"
        assert {SN.INGEST_APPEND, SN.INGEST_COMMIT,
                SN.INGEST_COMPACT} <= SN.SPAN_NAMES
        assert FN.INGEST_STAGE == "ingest.stage"
        assert FN.INGEST_PUBLISH == "ingest.publish"
        assert {FN.INGEST_STAGE, FN.INGEST_PUBLISH} <= FN.FAULT_NAMES

    def test_ingest_spans_recorded_under_tracing(self, tmp_path):
        """With tracing on, append/commit/compact open a maintenance
        trace and record their spans (the span registry's 'every name
        observed by a test' contract)."""
        session, hs, data = _lake(tmp_path, skipping=False)
        session.conf.set("hyperspace.tpu.telemetry.trace.enabled",
                         "true")
        rng = _rng(43)

        def span_names_of(trace):
            return [s.name for s in trace.spans] \
                if hasattr(trace, "spans") else \
                [s.name for s in trace._spans]

        hs.append(data, _frame(rng, 60))
        assert "ingest.append" in span_names_of(session._last_trace)
        hs.commit(data)
        assert "ingest.commit" in span_names_of(session._last_trace)
        hs.append(data, _frame(rng, 60))
        hs.commit(data)
        session.conf.set(SC.COMPACTION_MIN_ENTRIES, "1")
        out = hs.compact(["cx"])
        assert out["compacted"], out
        assert "ingest.compact" in span_names_of(session._last_trace)
