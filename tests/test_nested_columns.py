"""Nested-column support: struct leaves flatten to dotted names end-to-end.

Parity: CreateIndexNestedTest.scala, RefreshIndexNestedTest.scala and the
nested-field cases of E2EHyperspaceRulesTest (the reference flattens nested
fields into ``__hs_nested.``-prefixed flat columns, ResolverUtils.scala:112-162;
our engine flattens struct leaves into dotted flat names at the IO boundary,
so nested fields behave as ordinary columns everywhere downstream).
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col
from hyperspace_tpu.plan.nodes import IndexScan


def write_nested(root, n=600, parts=2, seed=3):
    rng = np.random.default_rng(seed)
    d = root / "nested"
    d.mkdir(parents=True, exist_ok=True)
    ids = np.arange(n, dtype=np.int64)
    leaf = rng.integers(0, 50, n).astype(np.int64)
    qty = rng.integers(1, 100, n).astype(np.int64)
    table = pa.table({
        "id": pa.array(ids),
        "nested": pa.array([
            {"leaf": {"cnt": int(leaf[i])}, "qty": int(qty[i])}
            for i in range(n)]),
    })
    step = n // parts
    for i in range(parts):
        lo = i * step
        hi = (i + 1) * step if i < parts - 1 else n
        pq.write_table(table.slice(lo, hi - lo), d / f"part{i}.parquet")
    return str(d), pd.DataFrame({"id": ids, "nested.leaf.cnt": leaf,
                                 "nested.qty": qty})


@pytest.fixture()
def env(tmp_path):
    path, flat = write_nested(tmp_path)
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    return dict(session=session, hs=Hyperspace(session), path=path, flat=flat)


class TestNestedScan:
    def test_schema_flattens_struct_leaves(self, env):
        df = env["session"].read.parquet(env["path"])
        assert set(df.plan.schema.names) == {"id", "nested.leaf.cnt",
                                             "nested.qty"}

    def test_scan_and_filter_on_nested_leaf(self, env):
        df = env["session"].read.parquet(env["path"])
        got = df.filter(col("nested.leaf.cnt") == 7).select("id") \
            .to_arrow().to_pandas()
        want = env["flat"].query("`nested.leaf.cnt` == 7")["id"]
        assert sorted(got["id"]) == sorted(want)

    def test_project_nested_leaf(self, env):
        df = env["session"].read.parquet(env["path"])
        got = df.select("nested.qty").to_arrow().to_pandas()
        assert sorted(got["nested.qty"]) == sorted(env["flat"]["nested.qty"])


class TestNestedIndex:
    def test_create_index_on_nested_column(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig(
            "nidx", ["nested.leaf.cnt"], ["id", "nested.qty"]))
        entry = hs.index_manager.get_index("nidx")
        assert entry.indexed_columns == ["nested.leaf.cnt"]
        assert "nested.leaf.cnt" in entry.schema.names

    def test_filter_rewrite_and_oracle_on_nested(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig(
            "nidx", ["nested.leaf.cnt"], ["id", "nested.qty"]))
        session.enable_hyperspace()
        q = df.filter(col("nested.leaf.cnt") == 7).select("id", "nested.qty")
        assert any(isinstance(l, IndexScan)
                   for l in q.optimized_plan().collect_leaves())
        got = q.to_arrow().to_pandas().sort_values("id").reset_index(drop=True)
        session.disable_hyperspace()
        want = q.to_arrow().to_pandas().sort_values("id").reset_index(drop=True)
        pd.testing.assert_frame_equal(got, want)

    def test_join_rewrite_on_nested_key(self, env, tmp_path):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["path"])
        # Dimension table keyed by the nested leaf's value domain.
        dim = pd.DataFrame({"cnt": np.arange(50, dtype=np.int64),
                            "label": np.arange(50, dtype=np.int64) * 10})
        dim_dir = tmp_path / "dim"
        dim_dir.mkdir()
        pq.write_table(pa.Table.from_pandas(dim), dim_dir / "d.parquet")
        ddf = session.read.parquet(str(dim_dir))

        hs.create_index(df, IndexConfig(
            "fact_idx", ["nested.leaf.cnt"], ["id"]))
        hs.create_index(ddf, IndexConfig("dim_idx", ["cnt"], ["label"]))
        session.enable_hyperspace()
        q = df.join(ddf, on=col("nested.leaf.cnt") == col("cnt")) \
            .select("id", "label")
        idx_scans = [l for l in q.optimized_plan().collect_leaves()
                     if isinstance(l, IndexScan)]
        assert len(idx_scans) == 2 and all(s.use_bucket_spec for s in idx_scans)
        got = q.to_arrow().to_pandas().sort_values(["id", "label"]
                                                   ).reset_index(drop=True)
        session.disable_hyperspace()
        want = q.to_arrow().to_pandas().sort_values(["id", "label"]
                                                    ).reset_index(drop=True)
        pd.testing.assert_frame_equal(got, want)

    def test_refresh_incremental_nested(self, env, tmp_path):
        session, hs = env["session"], env["hs"]
        session.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig(
            "nidx", ["nested.leaf.cnt"], ["id"]))
        # Append a file with new rows.
        extra = pa.table({
            "id": pa.array(np.arange(10_000, 10_020, dtype=np.int64)),
            "nested": pa.array([{"leaf": {"cnt": 7}, "qty": 1}] * 20),
        })
        pq.write_table(extra, tmp_path / "nested" / "extra.parquet")
        hs.refresh_index("nidx", "incremental")

        session.enable_hyperspace()
        q = session.read.parquet(env["path"]) \
            .filter(col("nested.leaf.cnt") == 7).select("id")
        assert any(isinstance(l, IndexScan)
                   for l in q.optimized_plan().collect_leaves())
        got = sorted(q.to_arrow().to_pandas()["id"])
        session.disable_hyperspace()
        want = sorted(q.to_arrow().to_pandas()["id"])
        assert got == want and len([i for i in got if i >= 10_000]) == 20
