"""Case-insensitive column resolution in the DataFrame API (Spark analyzer
parity: references resolve against the schema case-insensitively unless
``hyperspace.caseSensitive=true``). The rules already honored the conf;
this pins the API layer — filter/select/sort/group_by/join/agg/
with_column/drop all accept any-case spellings, and rewrites still fire.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col, count_distinct, sum_


@pytest.fixture()
def env(tmp_path):
    rng = np.random.default_rng(55)
    d = tmp_path / "data"
    d.mkdir()
    pq.write_table(pa.Table.from_pandas(pd.DataFrame({
        "Key": rng.integers(0, 30, 600).astype(np.int64),
        "Val": rng.integers(0, 9, 600).astype(np.int64),
        "Tag": rng.choice(["a", "b"], 600),
    })), d / "p0.parquet")
    session = hst.Session(system_path=str(tmp_path / "idx"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    return session, str(d)


class TestResolution:
    def test_filter_select_any_case(self, env):
        session, d = env
        df = session.read.parquet(d)
        got = (df.filter(col("KEY") > 10).select("key", "VAL")
               .to_arrow())
        # Output keeps the SCHEMA's spelling, not the query's.
        assert got.column_names == ["Key", "Val"]
        assert got.num_rows > 0

    def test_group_sort_agg_any_case(self, env):
        session, d = env
        df = session.read.parquet(d)
        got = (df.group_by("tag")
               .agg(sum_(col("VAL")).alias("s"),
                    count_distinct(col("key")).alias("nd"))
               .sort("TAG").to_pandas())
        pdf = df.to_pandas()
        expect = (pdf.groupby("Tag")
                  .agg(s=("Val", "sum"), nd=("Key", "nunique"))
                  .reset_index().rename(columns={"Tag": "Tag"})
                  .sort_values("Tag").reset_index(drop=True))
        pd.testing.assert_frame_equal(
            got.rename(columns={"Tag": "Tag"}), expect, check_dtype=False)

    def test_join_keys_any_case(self, env, tmp_path):
        session, d = env
        d2 = tmp_path / "dim"
        d2.mkdir()
        pq.write_table(pa.table({
            "DKey": pa.array(np.arange(30, dtype=np.int64)),
            "DVal": pa.array(np.arange(30, dtype=np.int64) * 10)}),
            d2 / "p0.parquet")
        df = session.read.parquet(d)
        dim = session.read.parquet(str(d2))
        got = (df.join(dim, on=col("key") == col("dkey"))
               .select("Key", "DVal").to_arrow())
        assert got.num_rows == 600

    def test_with_column_replace_and_drop_any_case(self, env):
        session, d = env
        df = session.read.parquet(d)
        out = (df.with_column("VAL", col("val") * 2)
               .drop("TAG").to_arrow())
        # Spark parity: the REPLACED column keeps the caller's spelling
        # (withColumn emits col.as(the user's name)).
        assert out.column_names == ["Key", "VAL"]
        orig = df.to_pandas()["Val"] * 2
        assert out.column("VAL").to_pylist() == orig.tolist()

    def test_writer_layouts_any_case(self, env, tmp_path):
        session, d = env
        df = session.read.parquet(d)
        out1 = str(tmp_path / "b")
        df.write.bucket_by(2, "KEY").parquet(out1)
        assert session.read.parquet(out1).count() == 600
        out2 = str(tmp_path / "p")
        df.write.partition_by("tag").parquet(out2)
        import os
        assert any(x.startswith("Tag=") for x in os.listdir(out2))

    def test_rewrite_fires_through_wrong_case(self, env):
        session, d = env
        hs = Hyperspace(session)
        df = session.read.parquet(d)
        hs.create_index(df, IndexConfig("ciIdx", ["Key"], ["Val"]))
        session.enable_hyperspace()
        q = df.filter(col("KEY") > 5).select("key", "val")
        assert "IndexScan" in q.optimized_plan().tree_string()
        # Oracle.
        a = q.to_pandas().sort_values(["Key", "Val"]).reset_index(drop=True)
        session.disable_hyperspace()
        b = q.to_pandas().sort_values(["Key", "Val"]).reset_index(drop=True)
        pd.testing.assert_frame_equal(a, b)

    def test_unknown_name_error_keeps_user_spelling(self, env):
        session, d = env
        df = session.read.parquet(d)
        with pytest.raises(HyperspaceException, match="'GhOsT'"):
            df.select("GhOsT")

    def test_case_sensitive_mode_rejects_wrong_case(self, env):
        session, d = env
        session.conf.set("hyperspace.caseSensitive", "true")
        df = session.read.parquet(d)
        with pytest.raises(HyperspaceException, match="KEY"):
            df.filter(col("KEY") > 1).to_arrow()
        # Exact spelling still works.
        assert df.filter(col("Key") > 1).count() > 0

    def test_ambiguous_names_raise(self, env, tmp_path):
        session, _ = env
        d2 = tmp_path / "amb"
        d2.mkdir()
        pq.write_table(pa.table({
            "x": pa.array([1, 2], type=pa.int64()),
            "X": pa.array([3, 4], type=pa.int64())}), d2 / "p0.parquet")
        df = session.read.parquet(str(d2))
        with pytest.raises(HyperspaceException, match="Ambiguous"):
            df.select("x")
