"""Always-on production observability (the r18 layer): head-sampled
trace retention + tail-keep, the anomaly flight recorder, SLO monitors,
OpenMetrics exposition, metrics_delta, and explain_analyze.

Acceptance set:
- tracing defaults ON; untraced-configuration results stay
  byte-identical; sampleRate=0 leaves NO trace for a healthy query but
  a deadline-breached (or faulted, or slow) query's trace is tail-kept;
- under an injected r14 fault the flight recorder auto-captures the
  offending query's full trace and ``dump_flight_recorder()`` emits
  schema-valid Perfetto JSON containing it;
- ``metrics_text()`` round-trips through the STRICT OpenMetrics parser
  and ``health()`` flips on a forced SLO breach with a matching
  SloBreachEvent;
- the frozen telemetry/metric_names.py vocabulary (this file is also
  the scripts/lint.py metric-coverage witness).
"""

from __future__ import annotations

import json
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace
from hyperspace_tpu.exceptions import QueryDeadlineError
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col, sum_
from hyperspace_tpu.robustness import fault_names as fn
from hyperspace_tpu.robustness.constants import RobustnessConstants as RC
from hyperspace_tpu.telemetry import metric_names as mn
from hyperspace_tpu.telemetry.constants import TelemetryConstants as TC

from conftest import capture_logger  # noqa: E402

N_ROWS = 800
N_FILES = 4


@pytest.fixture()
def data_dir(tmp_path):
    rng = np.random.default_rng(21)
    d = tmp_path / "data"
    os.makedirs(d)
    for i in range(N_FILES):
        t = pa.table({
            "k": pa.array(rng.integers(0, 40, N_ROWS).astype(np.int64)),
            "g": pa.array(rng.integers(0, 5, N_ROWS).astype(np.int64)),
            "v": pa.array(rng.uniform(0, 100, N_ROWS).round(3)),
        })
        pq.write_table(t, os.path.join(d, f"p{i}.parquet"))
    return str(d)


def _session(tmp_path, tag, **conf):
    s = hst.Session(system_path=str(tmp_path / f"idx_{tag}"))
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    for k, v in conf.items():
        s.conf.set(k, v)
    return s


def _query(session, data_dir):
    return session.read.parquet(data_dir).filter(
        col("k") == 3).select("k", "v")


# ---------------------------------------------------------------------------
# Head-sampled retention + tail-keep.
# ---------------------------------------------------------------------------

class TestTraceSampling:
    def test_tracing_defaults_on_and_retains(self, tmp_path, data_dir):
        session = _session(tmp_path, "on")
        assert session.hs_conf.telemetry_trace_enabled()
        assert session.hs_conf.telemetry_trace_sample_rate() == 1.0
        hs = Hyperspace(session)
        before = hs.metrics()
        _query(session, data_dir).to_arrow()
        tr = hs.last_trace()
        assert tr is not None and tr.sampled and tr.retained
        d = hs.metrics_delta(before)
        assert d.get("counters.trace.sampled") == 1

    def test_rate_zero_healthy_query_leaves_none(self, tmp_path,
                                                 data_dir):
        session = _session(
            tmp_path, "r0",
            **{TC.TRACE_SAMPLE_RATE: "0", TC.TRACE_TAIL_SLOW_MS: "1e9"})
        hs = Hyperspace(session)
        before = hs.metrics()
        a = _query(session, data_dir).to_arrow()
        assert hs.last_trace() is None
        assert hs.metrics_delta(before).get(
            "counters.trace.discarded") == 1
        # Byte identity: the sampled-off-retention result equals the
        # tracing-disabled result (the always-on-at-production-cost
        # contract).
        off = _session(tmp_path, "off", **{TC.TRACE_ENABLED: "false"})
        b = _query(off, data_dir).to_arrow()
        assert a.equals(b)
        assert Hyperspace(off).last_trace() is None

    def test_deadline_breach_is_tail_kept_at_rate_zero(self, tmp_path,
                                                       data_dir):
        """THE acceptance pair: the coin said no, the deadline breach
        keeps the trace anyway — and a healthy same-shape query
        (previous test) left none."""
        session = _session(
            tmp_path, "dl",
            **{TC.TRACE_SAMPLE_RATE: "0", TC.TRACE_TAIL_SLOW_MS: "1e9",
               RC.DEADLINE_MS: "0.0001"})
        hs = Hyperspace(session)
        before = hs.metrics()
        with pytest.raises(QueryDeadlineError):
            _query(session, data_dir).to_arrow()
        tr = hs.last_trace()
        assert tr is not None and not tr.sampled and tr.retained
        assert "query.cancelled" in tr.keep_reasons
        d = hs.metrics_delta(before)
        assert d.get("counters.trace.tail_kept") == 1
        assert "counters.trace.sampled" not in d

    def test_slow_query_is_tail_kept_by_threshold(self, tmp_path,
                                                  data_dir):
        session = _session(
            tmp_path, "slow",
            **{TC.TRACE_SAMPLE_RATE: "0", TC.TRACE_TAIL_SLOW_MS: "0.001"})
        hs = Hyperspace(session)
        _query(session, data_dir).to_arrow()  # any real query is slower
        tr = hs.last_trace()
        assert tr is not None and "slow" in tr.keep_reasons

    def test_sample_rate_clamped_and_coin_extremes(self, tmp_path):
        from hyperspace_tpu.telemetry import trace as trace_mod
        s = _session(tmp_path, "coin", **{TC.TRACE_SAMPLE_RATE: "7"})
        assert s.hs_conf.telemetry_trace_sample_rate() == 1.0
        assert trace_mod.sample_coin(s) is True
        s.conf.set(TC.TRACE_SAMPLE_RATE, "-3")
        assert s.hs_conf.telemetry_trace_sample_rate() == 0.0
        assert trace_mod.sample_coin(s) is False


# ---------------------------------------------------------------------------
# Flight recorder.
# ---------------------------------------------------------------------------

def _assert_perfetto_schema(doc: dict) -> None:
    assert {"traceEvents", "displayTimeUnit", "otherData"} <= set(doc)
    for ev in doc["traceEvents"]:
        assert {"name", "ph", "ts", "pid", "tid"} <= set(ev)
        assert ev["ph"] in ("X", "i")
        assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
        if ev["ph"] == "X":
            assert ev["dur"] >= 0
        else:
            assert ev["s"] == "p"


class TestFlightRecorder:
    def test_injected_fault_auto_captures_the_query_trace(
            self, tmp_path, data_dir):
        """r14-harness acceptance: an armed fault point fires, the
        offending query's FULL trace is auto-kept (sample coin said
        no), and the Perfetto dump contains it plus the anomaly."""
        session = _session(
            tmp_path, "fault",
            **{TC.TRACE_SAMPLE_RATE: "0", TC.TRACE_TAIL_SLOW_MS: "1e9",
               RC.RETRY_BASE_MS: "0",
               f"{RC.FAULTS_PREFIX}.{fn.IO_POOLED_READ}": "transient"})
        hs = Hyperspace(session)
        from hyperspace_tpu.robustness.faults import InjectedFaultError
        with pytest.raises(InjectedFaultError):
            _query(session, data_dir).to_arrow()
        tr = hs.last_trace()
        assert tr is not None and not tr.sampled and tr.retained
        assert tr.find("query")  # the full span tree, not a stub
        from hyperspace_tpu.telemetry.flight_recorder import get_recorder
        kinds = [a["kind"] for a in get_recorder().anomalies()]
        assert "retry.exhausted" in kinds
        out = str(tmp_path / "dump.json")
        doc = json.loads(hs.dump_flight_recorder(out))
        _assert_perfetto_schema(doc)
        assert tr.trace_id in doc["otherData"]["trace_ids"]
        span_ev = [e for e in doc["traceEvents"] if e["ph"] == "X"
                   and e["args"].get("trace_id") == tr.trace_id]
        assert span_ev, "the offending query's spans must be in the dump"
        anoms = [e for e in doc["traceEvents"]
                 if e["name"] == "anomaly:retry.exhausted"]
        assert anoms
        # dump(path) wrote the same document.
        with open(out, encoding="utf-8") as f:
            assert json.load(f)["otherData"]["trace_ids"] == \
                doc["otherData"]["trace_ids"]

    def test_anomaly_forces_metrics_snapshot_and_counter(self, tmp_path):
        from hyperspace_tpu.telemetry.flight_recorder import (
            get_recorder, note_anomaly)
        from hyperspace_tpu.telemetry.metrics import get_registry
        rec = get_recorder()
        before = get_registry().snapshot()["counters"].get(
            "flight_recorder.anomalies", 0)
        snaps_before = rec.stats()["snapshots"]
        note_anomaly("test.anomaly", "synthetic")
        after = get_registry().snapshot()["counters"][
            "flight_recorder.anomalies"]
        assert after == before + 1
        assert rec.stats()["snapshots"] >= min(snaps_before + 0, 1)
        assert any(a["kind"] == "test.anomaly"
                   for a in rec.anomalies())

    def test_rings_are_bounded(self):
        from hyperspace_tpu.telemetry.flight_recorder import FlightRecorder
        rec = FlightRecorder(max_traces=2)
        for i in range(10):
            rec.note_event(f"E{i}", "m", "", "")
            rec.note_anomaly(f"k{i}", "d")
        s = rec.stats()
        assert s["events"] == 10 and s["event_total"] == 10
        assert s["anomalies"] == 10 and s["anomaly_total"] == 10
        # Trace ring: deque maxlen honored + conf re-cap applies.
        class _T:
            def __init__(self, i):
                self.trace_id = f"t{i}"
                self.created_wall_ms = 0
                self.spans = []
            def span_events(self, base_us=0.0, with_trace_id=False):
                return []
        for i in range(5):
            rec.note_trace(_T(i))
        assert rec.stats()["traces"] == 2
        rec.note_trace(_T(99), cap=4)
        assert rec.stats()["traces"] == 3

    def test_recorder_is_a_metrics_collector(self, tmp_path, data_dir):
        session = _session(tmp_path, "coll")
        hs = Hyperspace(session)
        _query(session, data_dir).to_arrow()
        stats = hs.metrics()["collectors"]["flight_recorder"]
        assert stats["trace_total"] >= 1
        assert stats["event_total"] >= 1


# ---------------------------------------------------------------------------
# SLO monitors.
# ---------------------------------------------------------------------------

def _breach_events():
    return [e for e in capture_logger().events
            if type(e).__name__ == "SloBreachEvent"]


class TestSloMonitors:
    def test_monitor_unit_objectives(self):
        from hyperspace_tpu.telemetry.slo import SloMonitor
        mon = SloMonitor()
        for i in range(10):
            mon.record(10.0 + i, error=(i == 0), degraded=(i < 2),
                       now=100.0 + i)

        class _Conf:
            def telemetry_slo_window_s(self):
                return 60.0
            def telemetry_slo_min_count(self):
                return 1
            def telemetry_slo_p99_ms(self):
                return 5.0
            def telemetry_slo_error_rate(self):
                return 0.5
            def telemetry_slo_degrade_rate(self):
                return 0.0

        class _S:
            hs_conf = _Conf()

        v = mon.evaluate(_S(), now=110.0, emit=False)
        assert v["healthy"] is False
        obj = v["objectives"]
        assert obj["p99_latency_ms"]["breached"] is True
        assert obj["p99_latency_ms"]["observed"] == 19.0
        assert obj["error_rate"]["breached"] is False  # 0.1 <= 0.5
        assert obj["degrade_rate"]["armed"] is False
        # Window slides: far future -> empty window, nothing breaches.
        v2 = mon.evaluate(_S(), now=1000.0, emit=False)
        assert v2["count"] == 0 and v2["healthy"] is True

    def test_short_window_does_not_destroy_longer_window_history(self):
        """The monitor is a process singleton but windowS is
        per-session conf: one session's 60s evaluation must not pop
        samples a 600s evaluation still needs."""
        from hyperspace_tpu.telemetry.slo import SloMonitor
        mon = SloMonitor()
        mon.record(5.0, False, False, now=100.0)
        mon.record(7.0, False, False, now=400.0)

        class _Conf:
            window = 60.0
            def telemetry_slo_window_s(self):
                return self.window
            def telemetry_slo_min_count(self):
                return 1
            def telemetry_slo_p99_ms(self):
                return 0.0
            def telemetry_slo_error_rate(self):
                return 0.0
            def telemetry_slo_degrade_rate(self):
                return 0.0

        class _S:
            hs_conf = _Conf()

        assert mon.evaluate(_S(), now=430.0, emit=False)["count"] == 1
        _S.hs_conf.window = 600.0  # the longer window still sees both
        assert mon.evaluate(_S(), now=430.0, emit=False)["count"] == 2

    def test_window_feeds_even_with_slo_disabled(self, tmp_path,
                                                 data_dir):
        """slo.enabled=false gates objective evaluation only — the
        window keeps recording, so the trace sampler's ADAPTIVE
        tail-keep threshold stays alive."""
        from hyperspace_tpu.telemetry.slo import get_monitor
        session = _session(tmp_path, "slooff",
                           **{TC.SLO_ENABLED: "false"})
        t0 = get_monitor().total
        _query(session, data_dir).to_arrow()
        assert get_monitor().total == t0 + 1

    def test_forced_breach_flips_health_with_matching_event(
            self, tmp_path, data_dir):
        """Acceptance: health() flips on a forced SLO breach and a
        SloBreachEvent with the same objective lands in the log —
        edge-triggered, so holding the breach emits no duplicate."""
        session = _session(
            tmp_path, "slo",
            **{TC.SLO_MIN_COUNT: "1", TC.SLO_P99_MS: "1000000",
               IndexConstants.EVENT_LOGGER_CLASS:
                   "tests.conftest.CaptureLogger"})
        hs = Hyperspace(session)
        _query(session, data_dir).to_arrow()
        assert hs.health()["healthy"] is True  # huge objective: fine
        n0 = len(_breach_events())
        before = hs.metrics()
        session.conf.set(TC.SLO_P99_MS, "0.000001")  # unmeetable
        h = hs.health()
        assert h["healthy"] is False
        assert h["objectives"]["p99_latency_ms"]["breached"] is True
        new = _breach_events()[n0:]
        assert len(new) == 1
        assert new[0].objective == "p99_latency_ms"
        assert new[0].observed > new[0].threshold
        assert hs.metrics_delta(before).get(
            "counters.slo.breaches") == 1
        # Still breached: edge-triggered, no second event.
        assert hs.health()["healthy"] is False
        assert len(_breach_events()) == n0 + 1
        # The edge is per (objective, threshold): an evaluation under a
        # DIFFERENT (here: disarming-ly huge) threshold is healthy but
        # does not reset the breach edge...
        session.conf.set(TC.SLO_P99_MS, "1000000")
        assert hs.health()["healthy"] is True
        session.conf.set(TC.SLO_P99_MS, "0.000001")
        assert hs.health()["healthy"] is False
        assert len(_breach_events()) == n0 + 1  # continuation, no storm
        # ...while a breach of a NEW armed threshold emits its own
        # transition event.
        session.conf.set(TC.SLO_P99_MS, "0.000002")
        assert hs.health()["healthy"] is False
        assert len(_breach_events()) == n0 + 2

    def test_error_and_degrade_rates_feed_the_window(self, tmp_path,
                                                     data_dir):
        """A query that fails counts toward error rate; a query that
        rode a degradation ladder counts toward degrade rate (the
        QueryContext.degraded flag robustness/faults.note sets)."""
        session = _session(
            tmp_path, "rates",
            **{TC.SLO_MIN_COUNT: "1", TC.SLO_ERROR_RATE: "1e9",
               RC.RETRY_BASE_MS: "0"})
        hs = Hyperspace(session)
        from hyperspace_tpu.telemetry.slo import get_monitor
        mon = get_monitor()
        e0, d0 = mon.error_total, mon.degraded_total
        # Error: an armed non-transient fault fails the query.
        session.conf.set(f"{RC.FAULTS_PREFIX}.{fn.SCAN_PARQUET_DECODE}",
                         "error")
        with pytest.raises(Exception):
            _query(session, data_dir).to_arrow()
        assert mon.error_total == e0 + 1
        # Degrade: a bank-compile fault absorbed by the ladder.
        session.conf.unset(f"{RC.FAULTS_PREFIX}.{fn.SCAN_PARQUET_DECODE}")
        session.conf.set(f"{RC.FAULTS_PREFIX}.{fn.BANK_COMPILE}",
                         "error:times=1")
        session.read.parquet(data_dir).filter(
            col("g") == 1).select("g", "v").to_arrow()
        assert mon.degraded_total >= d0 + 1


# ---------------------------------------------------------------------------
# OpenMetrics exposition + HTTP endpoint + metrics_delta.
# ---------------------------------------------------------------------------

class TestOpenMetrics:
    def test_text_round_trips_through_strict_openmetrics_parser(
            self, tmp_path, data_dir):
        from prometheus_client.openmetrics.parser import \
            text_string_to_metric_families
        session = _session(tmp_path, "om")
        hs = Hyperspace(session)
        _query(session, data_dir).to_arrow()
        snap = hs.metrics()
        text = hs.metrics_text()
        assert text.endswith("# EOF\n")
        families = {f.name: f for f in
                    text_string_to_metric_families(text)}
        assert families, "exposition must parse into metric families"
        # Counters: registry values survive the round trip exactly.
        tr_sampled = snap["counters"]["trace.sampled"]
        fam = families["hst_trace_sampled"]
        assert fam.type == "counter"
        assert fam.samples[0].value == tr_sampled
        # Histograms: per-quantile gauges.
        assert "hst_query_latency_ms_p99" in families
        # Collectors: io pool counters are scrapeable.
        io_fam = families["hst_io_read_tasks"]
        assert io_fam.type == "gauge"
        assert io_fam.samples[0].value == \
            snap["collectors"]["io"]["read_tasks"]

    def test_name_collisions_prefer_the_registry_instrument(self):
        """When a collector leaf sanitizes to the same family name as a
        registry counter, the counter is exported (first-wins, pinned)
        and the family appears exactly once — double emission would be
        invalid OpenMetrics."""
        from hyperspace_tpu.telemetry.exposition import render_text
        text = render_text({
            "counters": {"serving.sweep_invocations": 7},
            "gauges": {}, "histograms": {},
            "collectors": {"serving": {"sweep_invocations": 3}},
        })
        assert text.count("# TYPE hst_serving_sweep_invocations ") == 1
        assert "hst_serving_sweep_invocations_total 7" in text
        assert "hst_serving_sweep_invocations 3" not in text

    def test_http_endpoint_serves_and_404s(self, tmp_path, data_dir):
        import urllib.error
        import urllib.request
        session = _session(tmp_path, "http")
        hs = Hyperspace(session)
        _query(session, data_dir).to_arrow()
        port = hs.serve_metrics(port=0)  # ephemeral localhost bind
        try:
            assert port > 0
            # Idempotent while up.
            assert hs.serve_metrics(port=0) == port
            url = f"http://127.0.0.1:{port}/metrics"
            with urllib.request.urlopen(url, timeout=10) as resp:
                assert resp.status == 200
                assert "openmetrics-text" in resp.headers["Content-Type"]
                body = resp.read().decode("utf-8")
            from prometheus_client.openmetrics.parser import \
                text_string_to_metric_families
            names = {f.name for f in text_string_to_metric_families(body)}
            assert "hst_trace_sampled" in names
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/other", timeout=10)
        finally:
            hs.stop_serving_metrics()

    def test_conf_port_default_is_off(self, tmp_path):
        from hyperspace_tpu.exceptions import HyperspaceException
        session = _session(tmp_path, "port")
        assert session.hs_conf.telemetry_export_http_port() == 0
        # Conf 0 means OFF: serve_metrics() without an explicit port
        # must refuse, not silently bind an ephemeral listener.
        with pytest.raises(HyperspaceException):
            Hyperspace(session).serve_metrics()

    def test_metrics_delta_shapes(self, tmp_path, data_dir):
        session = _session(tmp_path, "delta")
        hs = Hyperspace(session)
        _query(session, data_dir).to_arrow()
        before = hs.metrics()
        assert hs.metrics_delta(before, before) == {}
        _query(session, data_dir).to_arrow()
        d = hs.metrics_delta(before)
        assert d["counters.trace.sampled"] == 1
        assert all(isinstance(v, float) for v in d.values())
        # Flattening skips labels, keeps booleans as 0/1.
        from hyperspace_tpu.telemetry.exposition import flatten
        flat = flatten({"a": {"b": 2, "s": "label", "t": True,
                              "l": [1, 2], "n": None}})
        assert flat == {"a.b": 2.0, "a.t": 1.0}


# ---------------------------------------------------------------------------
# explain_analyze.
# ---------------------------------------------------------------------------

class TestExplainAnalyze:
    def test_report_fuses_trace_joins_and_tallies(self, tmp_path,
                                                  data_dir):
        from hyperspace_tpu.optimizer.constants import OptimizerConstants
        session = _session(
            tmp_path, "ea",
            **{OptimizerConstants.JOIN_REORDER_ENABLED: "true",
               # The coin must not matter: explain_analyze pins it.
               TC.TRACE_SAMPLE_RATE: "0",
               TC.TRACE_TAIL_SLOW_MS: "1e9"})
        hs = Hyperspace(session)
        left = session.read.parquet(data_dir).filter(col("k") < 30)
        dim_dir = str(tmp_path / "dim")
        os.makedirs(dim_dir)
        pq.write_table(pa.table({
            "g2": pa.array(np.arange(5, dtype=np.int64)),
            "w": pa.array(np.arange(5, dtype=np.float64)),
        }), os.path.join(dim_dir, "d0.parquet"))
        dim = session.read.parquet(dim_dir)
        q = (left.join(dim, on=col("g") == col("g2"))
             .group_by("g").agg(sum_(col("v") * col("w")).alias("s"))
             .sort("g"))
        report = hs.explain_analyze(q)
        assert "== Explain Analyze ==" in report
        assert "Trace:" in report and "query" in report
        assert "Tallies:" in report
        assert "io: tasks=" in report
        assert "bank:" in report and "robustness:" in report
        assert "row(s)" in report
        if session._last_join_order:
            assert "Joins (estimated vs actual):" in report
            assert "join +" in report
        # The forced trace was retained despite sampleRate=0.
        assert hs.last_trace() is not None

    def test_q_error_math(self):
        from hyperspace_tpu.plananalysis.analyze import _q_error
        assert _q_error(100, 100) == 1.0
        assert _q_error(10, 1000) == 100.0
        assert _q_error(1000, 10) == 100.0
        assert _q_error(0, 0) == 1.0  # clamped, never div-by-zero


# ---------------------------------------------------------------------------
# The frozen metric-name registry (also the lint coverage witness).
# ---------------------------------------------------------------------------

class TestMetricNameRegistry:
    def test_registry_is_the_expected_frozen_vocabulary(self):
        # Referencing every value here is also what satisfies the
        # scripts/lint.py metric-coverage gate — like the span-names
        # list, this registry only changes deliberately.
        assert mn.METRIC_NAMES == frozenset({
            "trace.sampled", "trace.tail_kept", "trace.discarded",
            "flight_recorder.anomalies", "slo.breaches",
            "serving.sweep_invocations", "serving.latency_ms",
            "query.latency_ms", "io", "program_bank", "serving",
            "robustness", "streaming", "fusion", "flight_recorder",
            "artifacts", "cluster", "buffer_pool",
        })

    def test_sweep_invocations_counter_still_feeds(self, tmp_path,
                                                   data_dir):
        """The pre-r18 push counter kept its registered name."""
        from hyperspace_tpu.telemetry.metrics import get_registry
        reg = get_registry()
        before = reg.snapshot()["counters"].get(
            mn.SERVING_SWEEP_INVOCATIONS, 0)
        reg.counter_add(mn.SERVING_SWEEP_INVOCATIONS, 2)
        assert reg.snapshot()["counters"][
            mn.SERVING_SWEEP_INVOCATIONS] == before + 2
