"""SPMD query execution (execution/spmd.py) over the 8-device CPU mesh.

The product query path for multi-chip: aggregation subtrees run SPMD with
XLA collectives; everything here asserts (a) the SPMD path is actually
taken (DISPATCH_COUNT advances), and (b) results equal the single-device
executor (disable-and-compare through the same public DataFrame API) or a
pandas oracle where the single-device path lacks the capability.
"""

import os

import jax
import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.execution import spmd
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import avg, col, count, max_, min_, sum_


@pytest.fixture()
def session(tmp_system_path):
    s = hst.Session(system_path=tmp_system_path)
    # Gate off: these fixtures are deliberately small meshes.
    s.conf.set(IndexConstants.TPU_DISTRIBUTED_MIN_STREAM_ROWS, "0")
    return s


@pytest.fixture()
def lineitem_dir(tmp_path):
    rng = np.random.default_rng(11)
    n = 6000
    t = pa.table({
        "l_orderkey": rng.integers(0, 500, n).astype(np.int64),
        "l_partkey": rng.integers(0, 80, n).astype(np.int64),
        "l_qty": rng.integers(1, 50, n).astype(np.int64),
        "l_price": np.round(rng.uniform(100, 1000, n), 2),
        "l_tag": rng.choice(["a", "b", "c", "d"], n),
    })
    d = tmp_path / "lineitem"
    d.mkdir()
    pq.write_table(t, str(d / "part0.parquet"))
    return str(d)


@pytest.fixture()
def orders_dir(tmp_path):
    rng = np.random.default_rng(12)
    n = 500
    t = pa.table({
        "o_orderkey": np.arange(n, dtype=np.int64),
        "o_pri": rng.integers(0, 4, n).astype(np.int64),
        "o_flag": rng.choice(["X", "Y"], n),
    })
    d = tmp_path / "orders"
    d.mkdir()
    pq.write_table(t, str(d / "part0.parquet"))
    return str(d)


def run_both(session, make_query):
    """Run the query with SPMD enabled (asserting dispatch) and disabled;
    return both arrow tables."""
    before = spmd.DISPATCH_COUNT
    dist = make_query().to_arrow()
    assert spmd.DISPATCH_COUNT > before, "SPMD path was not taken"
    session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "false")
    try:
        single = make_query().to_arrow()
    finally:
        session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "true")
    return dist, single


def assert_tables_equal(a, b, float_cols=()):
    pa_, pb = a.to_pydict(), b.to_pydict()
    assert list(pa_.keys()) == list(pb.keys())
    for k in pa_:
        if k in float_cols:
            assert np.allclose(pa_[k], pb[k], equal_nan=True), k
        else:
            assert pa_[k] == pb[k], k


class TestGlobalAggregate:
    def test_filter_sum_count(self, session, lineitem_dir):
        li = session.read.parquet(lineitem_dir)
        d, s = run_both(session, lambda: li.filter(col("l_qty") > 25).agg(
            sum_(col("l_price")).alias("sp"), count(None).alias("n")))
        assert_tables_equal(d, s, float_cols=("sp",))

    def test_min_max_avg(self, session, lineitem_dir):
        li = session.read.parquet(lineitem_dir)
        d, s = run_both(session, lambda: li.filter(col("l_tag") != "d").agg(
            min_(col("l_price")).alias("mn"), max_(col("l_qty")).alias("mx"),
            avg(col("l_price")).alias("av")))
        assert_tables_equal(d, s, float_cols=("mn", "av"))

    def test_min_max_string(self, session, lineitem_dir):
        li = session.read.parquet(lineitem_dir)
        d, s = run_both(session, lambda: li.filter(col("l_qty") < 10).agg(
            min_(col("l_tag")).alias("mn"), max_(col("l_tag")).alias("mx")))
        assert_tables_equal(d, s)

    def test_arithmetic_agg_expr(self, session, lineitem_dir):
        li = session.read.parquet(lineitem_dir)
        d, s = run_both(session, lambda: li.agg(
            sum_(col("l_price") * col("l_qty")).alias("rev")))
        assert_tables_equal(d, s, float_cols=("rev",))


class TestGroupedAggregate:
    def test_group_by_int(self, session, lineitem_dir):
        li = session.read.parquet(lineitem_dir)
        d, s = run_both(
            session,
            lambda: li.filter(col("l_qty") > 5).group_by("l_orderkey").agg(
                sum_(col("l_price")).alias("sp"), count(None).alias("n"),
                min_(col("l_qty")).alias("mq")))
        assert_tables_equal(d, s, float_cols=("sp",))

    def test_group_by_string(self, session, lineitem_dir):
        li = session.read.parquet(lineitem_dir)
        d, s = run_both(session, lambda: li.group_by("l_tag").agg(
            avg(col("l_price")).alias("ap")))
        assert_tables_equal(d, s, float_cols=("ap",))

    def test_group_by_two_cols(self, session, lineitem_dir):
        li = session.read.parquet(lineitem_dir)
        d, s = run_both(
            session,
            lambda: li.group_by("l_tag", "l_partkey").agg(
                count(None).alias("n"), max_(col("l_price")).alias("mp")))
        assert_tables_equal(d, s, float_cols=("mp",))


class TestDistributedFinalMerge:
    """The grouped two-phase shuffle: partial groups hash-route to owner
    devices and combine there, so the host receives disjoint final groups
    (spmd.py distributed-final-merge block). The virtual CPU mesh defaults
    to the host merge (cost decision, _use_routed_merge), so these tests
    force the routed path on."""

    @pytest.fixture(autouse=True)
    def _force_routed(self, monkeypatch):
        monkeypatch.setenv("HST_SPMD_ROUTED_MERGE", "on")

    def test_host_receives_disjoint_groups(self, session, lineitem_dir,
                                           monkeypatch):
        """Every (key, null-flag) group must appear on exactly one device
        after the routed merge — pinned by inspecting the program outputs
        the host merge consumes."""
        captured = {}
        orig = spmd._merge_grouped

        def spy(out, agg_specs, group_cols, col_meta):
            captured["out"] = out
            captured["group_cols"] = list(group_cols)
            return orig(out, agg_specs, group_cols, col_meta)

        monkeypatch.setattr(spmd, "_merge_grouped", spy)
        li = session.read.parquet(lineitem_dir)
        d, s = run_both(session, lambda: li.group_by("l_orderkey").agg(
            sum_(col("l_price")).alias("sp")))
        assert_tables_equal(d, s, float_cols=("sp",))
        out = captured["out"]
        n_dev = len(jax.devices())
        gvalid = np.asarray(jax.device_get(out["gvalid"]))
        keys = np.asarray(jax.device_get(out["g:l_orderkey"]))
        per_dev = len(gvalid) // n_dev
        owner_sets = []
        for dd in range(n_dev):
            sl = slice(dd * per_dev, (dd + 1) * per_dev)
            owner_sets.append(set(keys[sl][gvalid[sl]].tolist()))
        for i in range(n_dev):
            for j in range(i + 1, n_dev):
                dup = owner_sets[i] & owner_sets[j]
                assert not dup, f"groups {dup} owned by devices {i} and {j}"

    def test_capacity_escalation_on_many_groups(self, session, tmp_path,
                                                monkeypatch):
        """With G pinned tiny, per-device partials fit but a single owner
        can exceed G2=G — escalation must recompile and still produce the
        exact answer (hard bound n_dev*G makes it terminate)."""
        monkeypatch.setattr(spmd, "MAX_LOCAL_GROUPS", 16)
        rng = np.random.default_rng(44)
        n = 128 * 31
        # ≤16 distinct keys per device shard (shards are contiguous row
        # ranges), 128 distinct overall.
        keys = np.repeat(np.arange(128, dtype=np.int64), n // 128)
        t = pa.table({"k": keys, "v": np.round(rng.uniform(0, 10, n), 3)})
        d = tmp_path / "manygroups"
        d.mkdir()
        pq.write_table(t, str(d / "p.parquet"))
        df = session.read.parquet(str(d))
        dist, single = run_both(session, lambda: df.group_by("k").agg(
            sum_(col("v")).alias("sv"), count(None).alias("n")))
        assert_tables_equal(dist, single, float_cols=("sv",))
        assert dist.num_rows == 128


class TestBroadcastJoin:
    def test_join_grouped(self, session, lineitem_dir, orders_dir):
        li = session.read.parquet(lineitem_dir)
        od = session.read.parquet(orders_dir)
        d, s = run_both(
            session,
            lambda: li.join(od, on=col("l_orderkey") == col("o_orderkey"))
            .filter(col("o_pri") < 2)
            .group_by("o_flag")
            .agg(sum_(col("l_price")).alias("sp"), count(None).alias("n")))
        assert_tables_equal(d, s, float_cols=("sp",))

    def test_join_global(self, session, lineitem_dir, orders_dir):
        li = session.read.parquet(lineitem_dir)
        od = session.read.parquet(orders_dir)
        d, s = run_both(
            session,
            lambda: li.join(od, on=col("l_orderkey") == col("o_orderkey"))
            .agg(sum_(col("o_pri")).alias("so"), count(None).alias("n")))
        assert_tables_equal(d, s)

    def test_project_redefined_join_key(self, session, lineitem_dir,
                                        orders_dir):
        # A Project below the Join that *redefines* the stream join key
        # (computed expression under the same name) must feed the join
        # prep the post-project metadata, not stale leaf metadata.
        li = session.read.parquet(lineitem_dir)
        od = session.read.parquet(orders_dir)
        d, s = run_both(
            session,
            lambda: li.select((col("l_orderkey") + 0).alias("l_orderkey"),
                              "l_price")
            .join(od, on=col("l_orderkey") == col("o_orderkey"))
            .agg(sum_(col("l_price")).alias("sp"),
                 sum_(col("o_pri")).alias("so"), count(None).alias("n")))
        assert_tables_equal(d, s, float_cols=("sp",))

    def test_many_to_many_exchange_join(self, session, lineitem_dir):
        # Self-join on a non-unique key: the broadcast m:1 requirement
        # fails, and the SPMD path now routes BOTH sides over the mesh
        # with an all-to-all and merge-joins locally (the reference's
        # shuffle join) instead of falling back.
        li = session.read.parquet(lineitem_dir)
        li2 = li.select(col("l_orderkey").alias("r_orderkey"),
                        col("l_qty").alias("r_qty"))
        before = spmd.DISPATCH_COUNT
        out = (li.join(li2, on=col("l_orderkey") == col("r_orderkey"))
               .agg(count(None).alias("n"))).to_arrow()
        assert spmd.DISPATCH_COUNT > before, "exchange join was not taken"
        # Oracle: sum of squared per-key multiplicities.
        t = pq.read_table(os.path.join(lineitem_dir, "part0.parquet"))
        counts = pd.Series(t.column("l_orderkey").to_numpy()).value_counts()
        assert out.to_pydict()["n"] == [int((counts ** 2).sum())]


class TestNullables:
    @pytest.fixture()
    def null_dir(self, tmp_path):
        rng = np.random.default_rng(13)
        n = 4000
        g = rng.integers(0, 20, n).astype(np.float64)
        g[rng.random(n) < 0.1] = np.nan
        v = rng.uniform(0, 100, n)
        v[rng.random(n) < 0.2] = np.nan
        t = pa.table({
            "g": pa.array([None if np.isnan(x) else int(x) for x in g],
                          type=pa.int64()),
            "v": pa.array([None if np.isnan(x) else x for x in v]),
            "w": rng.uniform(0, 1, n),
        })
        d = tmp_path / "nulls"
        d.mkdir()
        pq.write_table(t, str(d / "part0.parquet"))
        return str(d)

    def test_global_agg_null_values(self, session, null_dir):
        df = session.read.parquet(null_dir)
        d, s = run_both(session, lambda: df.agg(
            sum_(col("v")).alias("sv"), count(col("v")).alias("nv"),
            count(None).alias("n")))
        assert_tables_equal(d, s, float_cols=("sv",))

    def test_grouped_nullable_values(self, session, null_dir):
        df = session.read.parquet(null_dir)
        d, s = run_both(
            session,
            lambda: df.select((col("w") * 0).alias("w_bucket"), "v")
            .group_by("w_bucket")
            .agg(sum_(col("v")).alias("sv"), count(col("v")).alias("nv")))
        assert_tables_equal(d, s, float_cols=("sv",))

    def test_nullable_group_key_spmd_only(self, session, null_dir):
        # The single-device executor still raises on nullable group keys;
        # the SPMD path supports them (null = its own group, null-first).
        # Oracle: pandas.
        df = session.read.parquet(null_dir)
        before = spmd.DISPATCH_COUNT
        out = (df.group_by("g")
               .agg(sum_(col("w")).alias("sw"), count(None).alias("n"))
               ).to_arrow()
        assert spmd.DISPATCH_COUNT > before
        pdf = pq.read_table(os.path.join(null_dir, "part0.parquet")).to_pandas()
        ref = (pdf.groupby("g", dropna=False)
               .agg(sw=("w", "sum"), n=("w", "size")).reset_index())
        # null-first ordering in our output; pandas puts NaN last.
        got = out.to_pydict()
        assert got["g"][0] is None
        ref_null = ref[ref.g.isna()]
        assert got["n"][0] == int(ref_null["n"].iloc[0])
        assert abs(got["sw"][0] - float(ref_null["sw"].iloc[0])) < 1e-9
        nn = ref[~ref.g.isna()].sort_values("g")
        assert got["g"][1:] == [int(x) for x in nn["g"]]
        assert got["n"][1:] == [int(x) for x in nn["n"]]
        assert np.allclose(got["sw"][1:], nn["sw"].to_numpy())

    def test_nullable_group_key_negative_values(self, session, tmp_path):
        # Null group must sort FIRST even with negative keys present (nulls
        # are encoded as value 0 on device; only the null-flag being the
        # more significant sort key keeps them ahead of negatives in the
        # host merge).
        rng = np.random.default_rng(14)
        n = 3000
        g = rng.integers(-10, 10, n).astype(np.int64)
        null_at = rng.random(n) < 0.1
        t = pa.table({
            "g": pa.array([None if m else int(x)
                           for x, m in zip(g, null_at)], type=pa.int64()),
            "w": rng.uniform(0, 1, n),
        })
        d = tmp_path / "neg_nulls"
        d.mkdir()
        pq.write_table(t, str(d / "part0.parquet"))
        df = session.read.parquet(str(d))
        before = spmd.DISPATCH_COUNT
        out = (df.group_by("g")
               .agg(sum_(col("w")).alias("sw"), count(None).alias("n"))
               ).to_arrow()
        assert spmd.DISPATCH_COUNT > before
        got = out.to_pydict()
        assert got["g"][0] is None, "null group must come first"
        assert got["g"][1:] == sorted(got["g"][1:])
        pdf = t.to_pandas()
        ref = (pdf.groupby("g", dropna=False)
               .agg(sw=("w", "sum"), n=("w", "size")).reset_index())
        ref_null = ref[ref.g.isna()]
        assert got["n"][0] == int(ref_null["n"].iloc[0])
        assert abs(got["sw"][0] - float(ref_null["sw"].iloc[0])) < 1e-9
        nn = ref[~ref.g.isna()].sort_values("g")
        assert got["g"][1:] == [int(x) for x in nn["g"]]
        assert np.allclose(got["sw"][1:], nn["sw"].to_numpy())


class TestFallbacks:
    def test_disabled_conf(self, session, lineitem_dir):
        session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "false")
        li = session.read.parquet(lineitem_dir)
        before = spmd.DISPATCH_COUNT
        li.agg(count(None).alias("n")).to_arrow()
        assert spmd.DISPATCH_COUNT == before

    def test_sort_above_spmd_aggregate(self, session, lineitem_dir):
        # Sort/Limit above the Aggregate run single-device on the merged
        # (small) result; the subtree below still executes SPMD.
        li = session.read.parquet(lineitem_dir)
        d, s = run_both(
            session,
            lambda: li.group_by("l_orderkey")
            .agg(sum_(col("l_price")).alias("sp"))
            .sort(("sp", False)).limit(5))
        assert_tables_equal(d, s, float_cols=("sp",))
