"""Index ranker tests (parity: rankers/JoinIndexRankerTest.scala:1-126 and
FilterIndexRankerTest.scala — fake IndexLogEntrys with controlled bucket
counts / file sizes, asserting which candidate wins under each policy).

Unit layer: FilterIndexRanker / JoinIndexRanker over synthetic entries with
a mocked session conf. E2E layer: two real candidate indexes on one table,
asserting the rewrite picks the ranked winner.
"""

from unittest import mock

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.index.constants import IndexConstants, States
from hyperspace_tpu.plan.expr import col
from hyperspace_tpu.plan.nodes import IndexScan
from hyperspace_tpu.rules.rankers import FilterIndexRanker, JoinIndexRanker

from test_log_entry import make_content, make_entry


def entry(name, num_buckets=8, file_sizes=(10, 10)):
    e = make_entry(name, States.ACTIVE)
    e.derivedDataset.num_buckets = num_buckets
    files = [f"part{i}.parquet" for i in range(len(file_sizes))]
    e.content = make_content(f"/indexes/{name}/v__=0", files,
                             sizes=dict(zip(files, file_sizes)))
    return e


def session_with(hybrid: bool):
    s = mock.MagicMock(name="session")
    s.hs_conf.hybrid_scan_enabled.return_value = hybrid
    return s


class TestFilterIndexRanker:
    def test_empty_returns_none(self):
        assert FilterIndexRanker.rank(session_with(False), None, []) is None

    def test_smallest_index_wins_without_hybrid(self):
        small = entry("big_name_small_files", file_sizes=(1, 1))
        large = entry("a_large", file_sizes=(1000, 1000))
        got = FilterIndexRanker.rank(
            session_with(False), None, [large, small])
        assert got is small

    def test_size_tie_breaks_lexicographically(self):
        a = entry("alpha", file_sizes=(5,))
        b = entry("beta", file_sizes=(5,))
        got = FilterIndexRanker.rank(session_with(False), None, [b, a])
        assert got is a

    def test_prefix_names_tie_break(self):
        # "ab" < "abc" must win the tie regardless of candidate order.
        ab = entry("ab", file_sizes=(5,))
        abc = entry("abc", file_sizes=(5,))
        assert FilterIndexRanker.rank(
            session_with(False), None, [abc, ab]) is ab
        assert FilterIndexRanker.rank(
            session_with(False), None, [ab, abc]) is ab

    def test_hybrid_prefers_max_common_bytes(self):
        # Under Hybrid Scan the candidate overlapping the source most wins
        # even when it is larger on disk.
        stale = entry("stale", file_sizes=(1,))
        fresh = entry("fresh", file_sizes=(1000,))
        with mock.patch(
                "hyperspace_tpu.rules.rankers.common_source_bytes",
                side_effect=lambda e, rel: {"stale": 10, "fresh": 900}[e.name]):
            got = FilterIndexRanker.rank(
                session_with(True), mock.MagicMock(), [stale, fresh])
        assert got is fresh

    def test_hybrid_common_bytes_tie_breaks_by_name(self):
        x = entry("x_idx")
        a = entry("a_idx")
        with mock.patch(
                "hyperspace_tpu.rules.rankers.common_source_bytes",
                return_value=42):
            got = FilterIndexRanker.rank(
                session_with(True), mock.MagicMock(), [x, a])
        assert got is a


class TestJoinIndexRanker:
    def test_empty_returns_none(self):
        assert JoinIndexRanker.rank(
            session_with(False), None, None, []) is None

    def test_equal_buckets_beat_more_buckets(self):
        # (8, 8) outranks (16, 12) even though the latter has more buckets:
        # equal counts mean a zero-exchange aligned merge join.
        even = (entry("l1", 8), entry("r1", 8))
        uneven = (entry("l2", 16), entry("r2", 12))
        got = JoinIndexRanker.rank(
            session_with(False), None, None, [uneven, even])
        assert got is even

    def test_among_equal_pairs_more_buckets_win(self):
        fine = (entry("l1", 16), entry("r1", 16))
        coarse = (entry("l2", 4), entry("r2", 4))
        got = JoinIndexRanker.rank(
            session_with(False), None, None, [coarse, fine])
        assert got is fine

    def test_full_tie_breaks_by_names(self):
        p1 = (entry("a", 8), entry("z", 8))
        p2 = (entry("a", 8), entry("b", 8))
        got = JoinIndexRanker.rank(
            session_with(False), None, None, [p1, p2])
        assert got is p2

    def test_hybrid_uses_common_bytes_after_buckets(self):
        overlap = {"l1": 100, "r1": 100, "l2": 5, "r2": 5}
        big_overlap = (entry("l1", 8), entry("r1", 8))
        small_overlap = (entry("l2", 8), entry("r2", 8))
        with mock.patch(
                "hyperspace_tpu.rules.rankers.common_source_bytes",
                side_effect=lambda e, rel: overlap[e.name]):
            got = JoinIndexRanker.rank(
                session_with(True), mock.MagicMock(), mock.MagicMock(),
                [small_overlap, big_overlap])
        assert got is big_overlap

    def test_hybrid_common_bytes_outrank_buckets_among_equal_pairs(self):
        """Reference branch (JoinIndexRanker.scala:75-80): when both
        pairs are internally equal-bucket and Hybrid Scan is on, common
        source bytes dominate the bucket count (the pre-r4 key compared
        bucket sums first — the ADVICE r3 divergence)."""
        overlap = {"l1": 1000, "r1": 1000, "l2": 5, "r2": 5}
        coarse_common = (entry("l1", 8), entry("r1", 8))
        fine_rare = (entry("l2", 16), entry("r2", 16))
        with mock.patch(
                "hyperspace_tpu.rules.rankers.common_source_bytes",
                side_effect=lambda e, rel: overlap[e.name]):
            got = JoinIndexRanker.rank(
                session_with(True), mock.MagicMock(), mock.MagicMock(),
                [fine_rare, coarse_common])
        assert got is coarse_common

    def test_hybrid_common_bytes_decide_among_unequal_pairs(self):
        """Reference branch (JoinIndexRanker.scala:86-91): both pairs
        unequal-bucket → common bytes alone decide under Hybrid Scan."""
        overlap = {"l1": 5, "r1": 5, "l2": 800, "r2": 800}
        rare = (entry("l1", 16), entry("r1", 8))
        common = (entry("l2", 4), entry("r2", 2))
        with mock.patch(
                "hyperspace_tpu.rules.rankers.common_source_bytes",
                side_effect=lambda e, rel: overlap[e.name]):
            got = JoinIndexRanker.rank(
                session_with(True), mock.MagicMock(), mock.MagicMock(),
                [rare, common])
        assert got is common

    def test_non_hybrid_unequal_pairs_keep_input_order(self):
        """Reference: sortWith returns true for every unequal-unequal
        compare without Hybrid Scan — input order is preserved."""
        first = (entry("l1", 16), entry("r1", 8))
        second = (entry("l2", 64), entry("r2", 32))
        got = JoinIndexRanker.rank(
            session_with(False), None, None, [first, second])
        assert got is first

    def test_bucket_rules_dominate_common_bytes(self):
        overlap = {"l1": 1, "r1": 1, "l2": 1000, "r2": 1000}
        even_small = (entry("l1", 8), entry("r1", 8))
        uneven_big = (entry("l2", 16), entry("r2", 8))
        with mock.patch(
                "hyperspace_tpu.rules.rankers.common_source_bytes",
                side_effect=lambda e, rel: overlap[e.name]):
            got = JoinIndexRanker.rank(
                session_with(True), mock.MagicMock(), mock.MagicMock(),
                [uneven_big, even_small])
        assert got is even_small


# ---------------------------------------------------------------------------
# E2E: two real candidates on one table; the rewrite must take the winner.
# ---------------------------------------------------------------------------

@pytest.fixture()
def env(tmp_path):
    rng = np.random.default_rng(11)
    d = tmp_path / "data"
    d.mkdir()
    pq.write_table(pa.Table.from_pandas(pd.DataFrame({
        "k": rng.integers(0, 80, 800).astype(np.int64),
        "v": rng.integers(0, 9, 800).astype(np.int64),
        "w": rng.integers(0, 9, 800).astype(np.int64),
    })), d / "p0.parquet")
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    session.enable_hyperspace()
    return dict(session=session, hs=Hyperspace(session), path=str(d))


class TestRankerE2E:
    def _used_index(self, df):
        leaves = df.optimized_plan().collect_leaves()
        used = [l.index_entry.name for l in leaves
                if isinstance(l, IndexScan)]
        return used[0] if used else None

    def test_filter_query_uses_smaller_candidate(self, env):
        session, hs = env["session"], env["hs"]
        df = session.read.parquet(env["path"])
        # Both cover the query; "wide" includes an extra column so its
        # files are strictly larger than "slim"'s.
        hs.create_index(df, IndexConfig("wide", ["k"], ["v", "w"]))
        hs.create_index(df, IndexConfig("slim", ["k"], ["v"]))
        # Disable hybrid scan so the min-size policy is active.
        session.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, "false")
        q = df.filter(col("k") > 40).select("k", "v")
        assert self._used_index(q) == "slim"
        # Oracle: same answers either way (order-insensitive — the index
        # path returns bucket-sorted rows).
        key = lambda t: t.sort_values(["k", "v"]).reset_index(drop=True)
        session.disable_hyperspace()
        expect = key(q.to_pandas())
        session.enable_hyperspace()
        pd.testing.assert_frame_equal(key(q.to_pandas()), expect)

    def test_join_prefers_equal_bucket_pair(self, env, tmp_path):
        session, hs = env["session"], env["hs"]
        rng = np.random.default_rng(12)
        d2 = tmp_path / "dim"
        d2.mkdir()
        pq.write_table(pa.Table.from_pandas(pd.DataFrame({
            "dk": np.arange(80, dtype=np.int64),
            "dv": rng.integers(0, 5, 80).astype(np.int64),
        })), d2 / "p0.parquet")
        fact = session.read.parquet(env["path"])
        dim = session.read.parquet(str(d2))
        # Fact side: two candidates, 4 and 8 buckets. Dim side: 8 buckets.
        # The (8, 8) pair must win over (4, 8).
        session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
        hs.create_index(fact, IndexConfig("fact4", ["k"], ["v"]))
        session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 8)
        hs.create_index(fact, IndexConfig("fact8", ["k"], ["v"]))
        hs.create_index(dim, IndexConfig("dim8", ["dk"], ["dv"]))
        q = (fact.join(dim, on=col("k") == col("dk"))
             .select("k", "v", "dv"))
        leaves = q.optimized_plan().collect_leaves()
        used = sorted(l.index_entry.name for l in leaves
                      if isinstance(l, IndexScan))
        assert used == ["dim8", "fact8"]
