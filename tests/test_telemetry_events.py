"""Telemetry event breadth (parity: telemetry/HyperspaceEvent.scala:28-156 +
the MockEventLogger installed in every reference suite): each lifecycle
action emits start/success events through the conf-pluggable logger, a
failed action emits a failure event, and the rewrite rules emit index-usage
events."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col


from conftest import capture_logger as sink  # noqa: E402


@pytest.fixture()
def env(tmp_path):
    rng = np.random.default_rng(3)
    d = tmp_path / "data"
    d.mkdir()
    pq.write_table(pa.Table.from_pandas(pd.DataFrame({
        "k": rng.integers(0, 60, 500).astype(np.int64),
        "v": rng.integers(0, 9, 500).astype(np.int64),
    })), d / "p0.parquet")
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    session.conf.set(IndexConstants.EVENT_LOGGER_CLASS,
                     "tests.conftest.CaptureLogger")
    sink().events.clear()
    return dict(session=session, hs=Hyperspace(session), path=str(d))


def names_of(events):
    return [type(e).__name__ for e in events]


def take_new(mark):
    evs = sink().events[mark:]
    return evs, len(sink().events)


class TestActionEvents:
    def test_lifecycle_emits_start_and_success_per_action(self, env):
        hs, session = env["hs"], env["session"]
        df = session.read.parquet(env["path"])
        mark = 0

        hs.create_index(df, IndexConfig("tIdx", ["k"], ["v"]))
        evs, mark = take_new(mark)
        assert names_of(evs).count("CreateActionEvent") == 2  # start+success
        assert "started" in evs[0].message.lower()
        assert "succeeded" in evs[-1].message.lower()
        assert evs[0].index_name == "tIdx"

        hs.delete_index("tIdx")
        evs, mark = take_new(mark)
        assert names_of(evs) == ["DeleteActionEvent", "DeleteActionEvent"]

        hs.restore_index("tIdx")
        evs, mark = take_new(mark)
        assert names_of(evs) == ["RestoreActionEvent", "RestoreActionEvent"]

        hs.refresh_index("tIdx", "full")
        evs, mark = take_new(mark)
        assert names_of(evs).count("RefreshActionEvent") == 2

        hs.optimize_index("tIdx", "full")
        evs, mark = take_new(mark)
        assert names_of(evs).count("OptimizeActionEvent") == 2

        hs.delete_index("tIdx")
        _, mark = take_new(mark)
        hs.vacuum_index("tIdx")
        evs, mark = take_new(mark)
        assert names_of(evs) == ["VacuumActionEvent", "VacuumActionEvent"]

    def test_failed_action_emits_failure_event(self, env):
        hs, session = env["hs"], env["session"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("fIdx", ["k"], ["v"]))
        mark = len(sink().events)
        with pytest.raises(HyperspaceException):
            hs.create_index(df, IndexConfig("fIdx", ["k"], ["v"]))  # dup name
        evs, _ = take_new(mark)
        assert any("failed" in e.message.lower() for e in evs)

    def test_refresh_modes_emit_distinct_event_types(self, env):
        session = env["session"]
        session.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
        hs = env["hs"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("modes", ["k"], ["v"]))
        rng = np.random.default_rng(5)
        import pathlib
        extra = pd.DataFrame({
            "k": rng.integers(0, 60, 80).astype(np.int64),
            "v": rng.integers(0, 9, 80).astype(np.int64)})
        pq.write_table(pa.Table.from_pandas(extra),
                       pathlib.Path(env["path"]) / "extra1.parquet")
        mark = len(sink().events)
        hs.refresh_index("modes", "incremental")
        evs, mark = take_new(mark)
        assert "RefreshIncrementalActionEvent" in names_of(evs)
        pq.write_table(pa.Table.from_pandas(extra),
                       pathlib.Path(env["path"]) / "extra2.parquet")
        hs.refresh_index("modes", "quick")
        evs, _ = take_new(mark)
        assert "RefreshQuickActionEvent" in names_of(evs)


class TestCacheEvents:
    """Serving result-cache events (serving/result_cache.py) + the
    index-table-cache probe events (execution/executor.py): hit/miss/
    admit/evict all flow through the conf-pluggable logger."""

    def _serving(self, env):
        from hyperspace_tpu.serving.constants import ServingConstants
        session = env["session"]
        session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "false")
        session.conf.set(ServingConstants.RESULT_CACHE_ENABLED, "true")
        session.conf.set(
            ServingConstants.RESULT_CACHE_MIN_COMPUTE_SECONDS, "0")
        return session

    def test_result_cache_miss_admit_then_hit(self, env):
        session = self._serving(env)
        q = session.read.parquet(env["path"]) \
            .filter(col("k") == 3).select("k", "v")
        mark = len(sink().events)
        q.to_pandas()
        evs, mark = take_new(mark)
        assert "ResultCacheMissEvent" in names_of(evs)
        assert "ResultCacheAdmitEvent" in names_of(evs)
        admit = [e for e in evs
                 if type(e).__name__ == "ResultCacheAdmitEvent"][0]
        assert admit.tier == "device" and admit.nbytes > 0
        assert admit.key_digest
        q.to_pandas()
        evs, _ = take_new(mark)
        hits = [e for e in evs
                if type(e).__name__ == "ResultCacheHitEvent"]
        assert hits and hits[0].key_digest == admit.key_digest
        assert "result served from cache" in hits[0].message

    def test_result_cache_eviction_event_on_demotion(self, env):
        from hyperspace_tpu.serving.constants import ServingConstants
        session = self._serving(env)
        q1 = session.read.parquet(env["path"]).filter(col("k") == 3)
        q1.to_pandas()
        nbytes = session.result_cache.stats()["device_nbytes"]
        session.conf.set(
            ServingConstants.RESULT_CACHE_DEVICE_BYTES, str(nbytes))
        q1.to_pandas()  # refill the rebuilt cache
        mark = len(sink().events)
        session.read.parquet(env["path"]) \
            .filter(col("k") == 3).select("v", "k").to_pandas()
        evs, _ = take_new(mark)
        evictions = [e for e in evs
                     if type(e).__name__ == "ResultCacheEvictionEvent"]
        assert evictions and evictions[0].tier == "device"
        assert evictions[0].demoted

    def test_index_cache_probe_events(self, env):
        from hyperspace_tpu.plan import expr as E
        session = self._serving(env)
        hs = env["hs"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("icIdx", ["k"], ["v"]))
        session.enable_hyperspace()
        # Group-bys probe the HBM index-table cache without a pushable
        # filter (leading-column equality filters take the pruned-read
        # path, which bypasses the cache by design).
        mark = len(sink().events)
        df.group_by("k").agg(E.Sum(col("v")).alias("s")).to_pandas()
        evs, mark = take_new(mark)
        misses = [e for e in evs
                  if type(e).__name__ == "IndexCacheMissEvent"]
        assert misses and misses[0].index_name == "icIdx"
        # Different aggregate over the SAME columns: the RESULT cache
        # misses (new plan), but the index table probe now hits HBM.
        df.group_by("k").agg(E.Avg(col("v")).alias("a")).to_pandas()
        evs, _ = take_new(mark)
        hits = [e for e in evs
                if type(e).__name__ == "IndexCacheHitEvent"]
        assert hits and hits[0].index_name == "icIdx"


class TestUsageEvents:
    def test_rewrite_emits_index_usage_event(self, env):
        hs, session = env["hs"], env["session"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("useIdx", ["k"], ["v"]))
        session.enable_hyperspace()
        mark = len(sink().events)
        df.filter(col("k") == 3).select("k", "v").to_pandas()
        evs, _ = take_new(mark)
        usage = [e for e in evs
                 if type(e).__name__ == "HyperspaceIndexUsageEvent"]
        assert usage and "useIdx" in usage[0].index_names

    def test_why_not_is_silent(self, env):
        """Diagnostic passes must not emit usage telemetry."""
        hs, session = env["hs"], env["session"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("silent", ["k"], ["v"]))
        session.enable_hyperspace()
        mark = len(sink().events)
        hs.why_not(df.filter(col("k") == 3).select("k", "v"))
        evs, _ = take_new(mark)
        assert not [e for e in evs
                    if type(e).__name__ == "HyperspaceIndexUsageEvent"]


class TestIoEvents:
    """Parallel-I/O events (parallel/io.py): a pooled multi-file fan-out
    emits IoReadEvent, a completed prefetch stream emits IoWaitEvent,
    and explain() grows an "I/O:" section once the pool has worked."""

    @pytest.fixture()
    def io_env(self, tmp_path):
        rng = np.random.default_rng(9)
        d = tmp_path / "iodata"
        d.mkdir()
        for i in range(5):
            pq.write_table(pa.Table.from_pandas(pd.DataFrame({
                "k": rng.integers(0, 60, 400).astype(np.int64),
                "v": rng.integers(0, 9, 400).astype(np.int64),
            })), d / f"p{i}.parquet")
        session = hst.Session(system_path=str(tmp_path / "indexes"))
        session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
        session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "false")
        session.conf.set(IndexConstants.TPU_IO_THREADS, 4)
        session.conf.set(IndexConstants.EVENT_LOGGER_CLASS,
                         "tests.conftest.CaptureLogger")
        sink().events.clear()
        return dict(session=session, hs=Hyperspace(session), path=str(d))

    def test_pooled_scan_emits_io_read_event(self, io_env):
        session = io_env["session"]
        mark = len(sink().events)
        session.read.parquet(io_env["path"]) \
            .filter(col("k") > 5).select("k", "v").to_pandas()
        evs, _ = take_new(mark)
        reads = [e for e in evs if type(e).__name__ == "IoReadEvent"]
        assert reads
        assert reads[0].files > 1 and reads[0].threads == 4
        assert reads[0].nbytes > 0

    def test_chunked_scan_emits_io_wait_event(self, io_env):
        session = io_env["session"]
        session.conf.set(IndexConstants.TPU_MAX_CHUNK_ROWS, 300)
        mark = len(sink().events)
        session.read.parquet(io_env["path"]) \
            .filter(col("k") > 30).select("k", "v").to_pandas()
        evs, _ = take_new(mark)
        waits = [e for e in evs if type(e).__name__ == "IoWaitEvent"]
        assert waits
        assert waits[0].where == "dataset_chunks"
        assert waits[0].items > 0

    def test_sketch_build_emits_io_read_event(self, io_env):
        from hyperspace_tpu.api import DataSkippingIndexConfig, MinMaxSketch
        session, hs = io_env["session"], io_env["hs"]
        df = session.read.parquet(io_env["path"])
        mark = len(sink().events)
        hs.create_index(df, DataSkippingIndexConfig(
            "skEvt", [MinMaxSketch("k")]))
        evs, _ = take_new(mark)
        reads = [e for e in evs if type(e).__name__ == "IoReadEvent"
                 and "sketch_build" in e.message]
        assert reads and reads[0].files == 5

    def test_explain_reports_io_section(self, io_env):
        session, hs = io_env["session"], io_env["hs"]
        df = session.read.parquet(io_env["path"])
        df.filter(col("k") > 5).select("k", "v").to_pandas()
        text = hs.explain(df.filter(col("k") > 5).select("k", "v"))
        assert "I/O:" in text
        assert "reader pool: on" in text
        assert "time split:" in text


class TestEventTaxonomy:
    """The event-class hierarchy is load-bearing: sinks filter on the
    shared bases (one isinstance check per family), so every concrete
    event must sit under its family base. This also keeps the lint
    telemetry-coverage gate honest for the abstract bases and for
    events only error paths emit (CancelActionEvent)."""

    def test_crud_events_share_the_crud_base(self):
        from hyperspace_tpu.telemetry import events as ev
        for cls in (ev.CreateActionEvent, ev.DeleteActionEvent,
                    ev.RestoreActionEvent, ev.VacuumActionEvent,
                    ev.CancelActionEvent, ev.RefreshActionEvent,
                    ev.RefreshIncrementalActionEvent,
                    ev.RefreshQuickActionEvent, ev.OptimizeActionEvent):
            assert issubclass(cls, ev.HyperspaceIndexCRUDEvent)
            assert issubclass(cls, ev.HyperspaceEvent)

    def test_cache_events_share_their_probe_bases(self):
        from hyperspace_tpu.telemetry import events as ev
        for cls in (ev.ResultCacheHitEvent, ev.ResultCacheMissEvent,
                    ev.ResultCacheAdmitEvent, ev.ResultCacheEvictionEvent):
            assert issubclass(cls, ev.ResultCacheEvent)
        for cls in (ev.IndexCacheHitEvent, ev.IndexCacheMissEvent):
            assert issubclass(cls, ev.IndexCacheProbeEvent)

    def test_cancel_event_emitted_by_cancel_action(self, env):
        """cancel() on a wedged (transient-state) index emits
        CancelActionEvent start+success like every other lifecycle
        action."""
        import copy
        import os

        from hyperspace_tpu.index.constants import States
        from hyperspace_tpu.index.log_manager import IndexLogManager

        hs, session = env["hs"], env["session"]
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("cxIdx", ["k"], ["v"]))
        # Simulate a crash mid-refresh so cancel is legal.
        lm = IndexLogManager(os.path.join(
            session.hs_conf.system_path(), "cxIdx"))
        wedged = copy.deepcopy(lm.get_latest_log())
        wedged.state = States.REFRESHING
        assert lm.write_log(lm.get_latest_id() + 1, wedged)
        mark = len(sink().events)
        hs.cancel("cxIdx")
        evs, _ = take_new(mark)
        assert names_of(evs).count("CancelActionEvent") == 2


class TestEventLogging:
    """telemetry/logging.py itself: the conf-pluggable logger plumbing
    (class loading, per-class instance memoization, the mixin, the
    shared fallback-emission helper) and trace-id correlation in the
    records a logger receives."""

    def test_default_and_empty_are_noop(self):
        from hyperspace_tpu.telemetry.logging import (NoOpEventLogger,
                                                      get_logger)
        assert isinstance(get_logger(None), NoOpEventLogger)
        assert isinstance(get_logger(""), NoOpEventLogger)
        # The no-op sink accepts any event silently.
        from hyperspace_tpu.telemetry.events import HyperspaceEvent
        get_logger(None).log_event(HyperspaceEvent(message="x"))

    def test_logger_instances_memoized_per_class_name(self):
        from hyperspace_tpu.telemetry.logging import get_logger
        a = get_logger("tests.conftest.CaptureLogger")
        b = get_logger("tests.conftest.CaptureLogger")
        assert a is b

    def test_unloadable_class_raises_typed(self):
        from hyperspace_tpu.telemetry.logging import get_logger
        with pytest.raises(HyperspaceException):
            get_logger("tests.conftest.NoSuchLogger")
        with pytest.raises(HyperspaceException):
            get_logger("no.such.module.Logger")

    def test_base_logger_is_abstract(self):
        from hyperspace_tpu.telemetry.events import HyperspaceEvent
        from hyperspace_tpu.telemetry.logging import EventLogger
        with pytest.raises(NotImplementedError):
            EventLogger().log_event(HyperspaceEvent())

    def test_mixin_routes_through_conf_selected_logger(self, env):
        from hyperspace_tpu.telemetry.events import HyperspaceEvent
        from hyperspace_tpu.telemetry.logging import HyperspaceEventLogging

        class Emitter(HyperspaceEventLogging):
            pass

        mark = len(sink().events)
        Emitter().log_event(env["session"],
                            HyperspaceEvent(message="via mixin"))
        evs, _ = take_new(mark)
        assert [e.message for e in evs] == ["via mixin"]

    def test_emit_distributed_fallback_shared_helper(self, env):
        from hyperspace_tpu.telemetry.logging import \
            emit_distributed_fallback
        mark = len(sink().events)
        emit_distributed_fallback(env["session"], "spmd_query",
                                  "capacity exceeded")
        evs, _ = take_new(mark)
        assert names_of(evs) == ["DistributedFallbackEvent"]
        assert evs[0].where == "spmd_query"
        assert evs[0].reason == "capacity exceeded"

    def test_log_records_correlate_with_the_active_trace(self, env):
        """Events logged inside a traced execution carry the trace/span
        stamp of the query that emitted them; outside, both stamps are
        empty — the correlation contract log consumers join on."""
        from hyperspace_tpu.serving.context import QueryContext
        from hyperspace_tpu.telemetry import trace as trace_mod
        from hyperspace_tpu.telemetry.events import HyperspaceEvent
        from hyperspace_tpu.telemetry.logging import get_logger

        session = env["session"]
        logger = get_logger("tests.conftest.CaptureLogger")
        mark = len(sink().events)
        ctx = QueryContext.for_session(session)
        with trace_mod.query_trace(session, ctx) as root:
            assert root is not None
            logger.log_event(HyperspaceEvent(message="inside"))
            tid, sid = trace_mod.active_ids()
        logger.log_event(HyperspaceEvent(message="outside"))
        evs, _ = take_new(mark)
        by_msg = {e.message: e for e in evs}
        assert by_msg["inside"].trace_id == tid == ctx.trace.trace_id
        assert by_msg["inside"].span_id == sid != ""
        assert by_msg["outside"].trace_id == ""
        assert by_msg["outside"].span_id == ""
