"""Distributed ORDER BY (VERDICT r5 #4): range-partitioned sample sort
over the 8-device CPU mesh (execution/spmd.py mode="sort").

The reference inherits Spark's range-partitioned global sort via exchange
planning (consumed through rules/RuleUtils.scala); here the innermost Sort
above an SPMD stream chain runs ON the mesh — per-device key sampling, one
all_gather for splitters, one all_to_all routing, local lex sort — and the
host concatenates already-sorted device ranges. Tests assert the path is
taken (SORT_DISPATCH_COUNT advances) and results equal the single-device
sort exactly (including null placement, descending keys, strings,
multi-key orders, and skewed key distributions that force the capacity
retry).
"""

import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.execution import spmd
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col


@pytest.fixture(autouse=True)
def _force_spmd_sort(monkeypatch):
    # auto keeps the host sort on single-host CPU meshes (the collectives
    # would run on the same silicon); tests force the distributed path.
    monkeypatch.setenv("HST_SPMD_SORT", "on")


@pytest.fixture()
def session(tmp_system_path):
    s = hst.Session(system_path=tmp_system_path)
    # Gate off: these fixtures are deliberately small meshes.
    s.conf.set(IndexConstants.TPU_DISTRIBUTED_MIN_STREAM_ROWS, "0")
    return s


@pytest.fixture()
def data_dir(tmp_path):
    rng = np.random.default_rng(23)
    n = 8000
    v = np.round(rng.uniform(0, 1000, n), 2)
    t = pa.table({
        "k": pa.array(rng.integers(0, 4000, n).astype(np.int64)),
        "s": pa.array(rng.choice(["aa", "bb", "cc", "dd", "ee"], n)),
        "v": pa.array(v),
        "nv": pa.array([float(x) if ok else None for x, ok in
                        zip(v, rng.random(n) > 0.12)], type=pa.float64()),
    })
    d = tmp_path / "t"
    d.mkdir()
    pq.write_table(t, str(d / "p.parquet"))
    return str(d)


def _dispatched(fn):
    before = spmd.SORT_DISPATCH_COUNT
    out = fn()
    assert spmd.SORT_DISPATCH_COUNT == before + 1, \
        "distributed sort path was not taken"
    return out


def _single_device(session, df, monkeypatch):
    """The same query with the distributed sort disabled (host sort)."""
    monkeypatch.setenv("HST_SPMD_SORT", "off")
    out = df.to_pandas()
    monkeypatch.setenv("HST_SPMD_SORT", "on")
    return out


def test_ascending_int_key(session, data_dir, monkeypatch):
    df = session.read.parquet(data_dir).filter(col("v") > 500).sort("k")
    out = _dispatched(df.to_pandas)
    exp = _single_device(session, df, monkeypatch)
    pd.testing.assert_series_equal(out["k"], exp["k"])
    assert out["k"].is_monotonic_increasing
    # Same row multiset regardless of tie order.
    pd.testing.assert_frame_equal(
        out.sort_values(list(out.columns)).reset_index(drop=True),
        exp.sort_values(list(exp.columns)).reset_index(drop=True))


def test_descending_nullable_key_nulls_last(session, data_dir, monkeypatch):
    df = session.read.parquet(data_dir).filter(col("v") > 100) \
        .sort(("nv", False))
    out = _dispatched(df.to_pandas)
    exp = _single_device(session, df, monkeypatch)
    assert list(out["nv"].fillna(-1.0)) == list(exp["nv"].fillna(-1.0))
    nulls = out["nv"].isna().to_numpy()
    assert not nulls[:-nulls.sum()].any() if nulls.sum() else True


def test_ascending_nullable_key_nulls_first(session, data_dir, monkeypatch):
    df = session.read.parquet(data_dir).sort("nv")
    out = _dispatched(df.to_pandas)
    nulls = out["nv"].isna().to_numpy()
    assert nulls[:nulls.sum()].all()  # all nulls lead
    rest = out["nv"].to_numpy()[nulls.sum():]
    assert (np.diff(rest) >= 0).all()


def test_multi_key_string_then_int_desc(session, data_dir, monkeypatch):
    df = session.read.parquet(data_dir).filter(col("v") > 50) \
        .sort("s", ("k", False))
    out = _dispatched(df.to_pandas)
    exp = _single_device(session, df, monkeypatch)
    assert list(out["s"]) == list(exp["s"])
    assert list(out["k"]) == list(exp["k"])


def test_skewed_keys_force_capacity_retry(session, data_dir, tmp_path,
                                          monkeypatch):
    """90% of rows share one key value: every one of them routes to a
    single device, overflowing the balanced initial capacity — the exact
    -need retry must recover."""
    rng = np.random.default_rng(7)
    n = 4000
    k = rng.integers(0, 1000, n).astype(np.int64)
    k[: (9 * n) // 10] = 42
    t = pa.table({"k": pa.array(k),
                  "v": pa.array(np.round(rng.uniform(0, 10, n), 2))})
    d = tmp_path / "skew"
    d.mkdir()
    pq.write_table(t, str(d / "p.parquet"))
    df = session.read.parquet(str(d)).filter(col("v") >= 0).sort("k")
    out = _dispatched(df.to_pandas)
    assert out["k"].is_monotonic_increasing
    assert len(out) == n
    assert spmd.LAST_CAP_ATTEMPTS >= 2  # the retry actually fired


def test_sort_under_limit(session, data_dir, monkeypatch):
    df = session.read.parquet(data_dir).filter(col("v") > 500) \
        .sort("k").limit(25)
    out = _dispatched(df.to_pandas)
    exp = _single_device(session, df, monkeypatch)
    pd.testing.assert_series_equal(out["k"], exp["k"])
    assert len(out) == 25


def test_join_then_distributed_sort(session, data_dir, tmp_path,
                                    monkeypatch):
    rng = np.random.default_rng(9)
    t = pa.table({"k2": pa.array(np.arange(4000, dtype=np.int64)),
                  "w": pa.array(np.round(rng.uniform(0, 5, 4000), 2))})
    d = tmp_path / "dim"
    d.mkdir()
    pq.write_table(t, str(d / "p.parquet"))
    left = session.read.parquet(data_dir)
    right = session.read.parquet(str(d))
    df = left.join(right, on=col("k") == col("k2"), how="inner") \
        .filter(col("v") > 300).sort("k", ("v", False))
    out = _dispatched(df.to_pandas)
    exp = _single_device(session, df, monkeypatch)
    assert list(out["k"]) == list(exp["k"])
    assert list(out["v"]) == list(exp["v"])


def test_auto_keeps_host_sort_on_cpu(session, data_dir, monkeypatch):
    monkeypatch.setenv("HST_SPMD_SORT", "auto")
    before = spmd.SORT_DISPATCH_COUNT
    df = session.read.parquet(data_dir).filter(col("v") > 500).sort("k")
    out = df.to_pandas()
    assert spmd.SORT_DISPATCH_COUNT == before  # host sort on CPU mesh
    assert out["k"].is_monotonic_increasing
