"""SQL front-end: WITH clauses (CTEs) and window functions.

The reference's very first TPC-DS golden query needs a CTE
(reference src/test/resources/tpcds/queries/q1.sql:1 — WITH
customer_total_return AS ...) and the corpus is full of OVER clauses;
session.sql now lowers both onto the DataFrame IR. Oracles here are
pandas recomputations of the same queries.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.exceptions import HyperspaceException


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    root = tmp_path_factory.mktemp("sqlcte")
    rng = np.random.default_rng(5)
    n = 350
    t = pa.table({
        "g": pa.array(rng.integers(0, 5, n).astype(np.int64)),
        "o": pa.array(rng.integers(0, 30, n).astype(np.int64)),
        "v": pa.array(np.round(rng.uniform(0, 100, n), 2)),
    })
    d = root / "t"
    d.mkdir()
    pq.write_table(t, str(d / "p.parquet"))
    session = hst.Session(system_path=str(root / "idx"))
    session.create_temp_view("t", session.read.parquet(str(d)))
    return session, t.to_pandas()


def test_cte_basic(env):
    session, pdf = env
    out = session.sql("""
        WITH top AS (SELECT g, sum(v) sv FROM t GROUP BY g)
        SELECT g, sv FROM top WHERE sv > 0 ORDER BY g
    """).to_pandas()
    exp = pdf.groupby("g", as_index=False)["v"].sum() \
        .rename(columns={"v": "sv"}).sort_values("g").reset_index(drop=True)
    pd.testing.assert_frame_equal(out, exp, rtol=1e-9)


def test_cte_chained_and_joined(env):
    session, pdf = env
    out = session.sql("""
        WITH a AS (SELECT g, o, sum(v) sv FROM t GROUP BY g, o),
             b AS (SELECT g bg, max(sv) msv FROM a GROUP BY g)
        SELECT a.g, a.o, a.sv FROM a, b
        WHERE a.g = b.bg AND a.sv = b.msv
        ORDER BY g, o
    """).to_pandas()
    agg = pdf.groupby(["g", "o"], as_index=False)["v"].sum() \
        .rename(columns={"v": "sv"})
    mx = agg.groupby("g")["sv"].transform("max")
    exp = agg[agg.sv == mx].sort_values(["g", "o"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(out, exp, rtol=1e-9)


def test_cte_scalar_subquery_q1_shape(env):
    """The TPC-DS q1 pattern: one CTE read twice — as the main relation
    and inside a correlated scalar subquery with aggregate arithmetic."""
    session, pdf = env
    out = session.sql("""
        WITH ctr AS (SELECT g ctr_g, o ctr_o, sum(v) ctr_total
                     FROM t GROUP BY g, o)
        SELECT ctr_g, ctr_o FROM ctr ctr1
        WHERE ctr1.ctr_total > (SELECT avg(ctr_total) * 1.2 FROM ctr ctr2
                                WHERE ctr1.ctr_g = ctr2.ctr_g)
        ORDER BY ctr_g, ctr_o
    """).to_pandas()
    agg = pdf.groupby(["g", "o"], as_index=False)["v"].sum() \
        .rename(columns={"g": "ctr_g", "o": "ctr_o", "v": "ctr_total"})
    thresh = agg.groupby("ctr_g")["ctr_total"].transform("mean") * 1.2
    exp = agg[agg.ctr_total > thresh][["ctr_g", "ctr_o"]] \
        .sort_values(["ctr_g", "ctr_o"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(out, exp)


def test_window_rank_in_sql(env):
    session, pdf = env
    out = session.sql("""
        SELECT g, o, v, rank() OVER (PARTITION BY g ORDER BY v DESC) rk
        FROM t ORDER BY g, rk, o, v LIMIT 60
    """).to_pandas()
    exp = pdf.assign(rk=pdf.groupby("g")["v"].rank(
        method="min", ascending=False).astype("int64"))
    exp = exp.sort_values(["g", "rk", "o", "v"]).head(60) \
        .reset_index(drop=True)[["g", "o", "v", "rk"]]
    pd.testing.assert_frame_equal(out, exp)


def test_window_over_grouped_query(env):
    """The q12/q20/q98 shape: ratio of a group aggregate to a windowed
    total over a coarser partition."""
    session, pdf = env
    out = session.sql("""
        SELECT g, o, sum(v) rev,
               sum(v) * 100 / sum(sum(v)) OVER (PARTITION BY g) ratio
        FROM t GROUP BY g, o ORDER BY g, o
    """).to_pandas()
    agg = pdf.groupby(["g", "o"], as_index=False)["v"].sum() \
        .rename(columns={"v": "rev"})
    agg["ratio"] = agg["rev"] * 100 / agg.groupby("g")["rev"].transform("sum")
    exp = agg.sort_values(["g", "o"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(out, exp, rtol=1e-9)


def test_window_rows_frame_in_sql(env):
    session, pdf = env
    out = session.sql("""
        SELECT g, o, sum(v) OVER (PARTITION BY g ORDER BY o
          ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) cume
        FROM t ORDER BY g, o, cume
    """).to_pandas()
    exp = pdf.sort_values(["g", "o"], kind="stable")
    exp = exp.assign(cume=exp.groupby("g")["v"].cumsum())
    exp = exp.sort_values(["g", "o", "cume"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(out[["g", "o", "cume"]],
                                  exp[["g", "o", "cume"]], rtol=1e-9)


def test_window_in_cte_filtered_outside(env):
    """The q53/q63 shape: window computed in a derived table, filtered in
    the outer query."""
    session, pdf = env
    out = session.sql("""
        SELECT g, o, sv, avg_sv FROM (
          SELECT g, o, sum(v) sv,
                 avg(sum(v)) OVER (PARTITION BY g) avg_sv
          FROM t GROUP BY g, o
        ) tmp WHERE sv > avg_sv ORDER BY g, o
    """).to_pandas()
    agg = pdf.groupby(["g", "o"], as_index=False)["v"].sum() \
        .rename(columns={"v": "sv"})
    agg["avg_sv"] = agg.groupby("g")["sv"].transform("mean")
    exp = agg[agg.sv > agg.avg_sv].sort_values(["g", "o"]) \
        .reset_index(drop=True)
    pd.testing.assert_frame_equal(out, exp, rtol=1e-9)


def test_coalesce(env):
    session, pdf = env
    out = session.sql(
        "SELECT g, coalesce(o, 0 - 1) co FROM t ORDER BY g, co LIMIT 10"
    ).to_pandas()
    assert (out["co"] >= 0).all()


def test_unsupported_frame_is_clear_error(env):
    session, _ = env
    with pytest.raises(HyperspaceException, match="UNBOUNDED PRECEDING"):
        session.sql("""
            SELECT sum(v) OVER (PARTITION BY g ORDER BY o
              ROWS BETWEEN 1 PRECEDING AND 1 FOLLOWING) x FROM t
        """)


def test_decimal_cast_accepted(env):
    session, pdf = env
    out = session.sql(
        "SELECT cast(sum(v) AS DECIMAL(15, 4)) s FROM t").to_pandas()
    assert abs(out["s"][0] - pdf["v"].sum()) < 1e-6


def test_soft_keywords_stay_identifiers(env):
    """rank / row / over remain usable as aliases (Spark reserves almost
    nothing)."""
    session, _ = env
    out = session.sql("SELECT g AS rank, o AS row FROM t LIMIT 5").to_pandas()
    assert list(out.columns) == ["rank", "row"]
