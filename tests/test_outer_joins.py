"""Outer joins (left / right / full) in the execution engine.

The reference's JoinIndexRule only rewrites INNER equi-joins (Spark
executes the rest without indexes); since this framework ships its own
engine, the engine itself must execute outer joins — padded with nulls on
the non-preserved side, null join keys never matching (SQL semantics).
Oracle: pandas merge with how= equivalents.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.plan.expr import col


@pytest.fixture()
def env(tmp_path):
    rng = np.random.default_rng(8)
    n_l, n_r = 3000, 900
    left = pd.DataFrame({
        "lk": rng.integers(0, 1200, n_l).astype(np.int64),
        "lv": np.round(rng.random(n_l), 4),
        "ls": rng.choice(["x", "y", "z"], n_l),
    })
    # ~8% null keys on the left.
    left.loc[rng.random(n_l) < 0.08, "lk"] = None
    right = pd.DataFrame({
        "rk": rng.integers(0, 1200, n_r).astype(np.int64),
        "rw": rng.integers(0, 50, n_r).astype(np.int64),
    })
    right.loc[rng.random(n_r) < 0.08, "rk"] = None
    ld, rd = tmp_path / "l", tmp_path / "r"
    ld.mkdir(), rd.mkdir()
    pq.write_table(pa.Table.from_pandas(left, preserve_index=False),
                   ld / "p.parquet")
    pq.write_table(pa.Table.from_pandas(right, preserve_index=False),
                   rd / "p.parquet")
    session = hst.Session(system_path=str(tmp_path / "idx"))
    return dict(session=session, hs=Hyperspace(session),
                l=str(ld), r=str(rd), left=left, right=right)


def _oracle(left, right, how):
    """SQL-semantics oracle: pandas merge treats NaN keys as EQUAL (NaN
    joins NaN), so null-key rows are split out and handled per SQL —
    never matching, preserved only by the outer side(s)."""
    l_valid = left.dropna(subset=["lk"])
    l_null = left[left["lk"].isna()]
    r_valid = right.dropna(subset=["rk"])
    r_null = right[right["rk"].isna()]
    inner = l_valid.merge(r_valid, left_on="lk", right_on="rk", how="inner")
    if how == "inner":
        return inner
    l_unmatched = pd.concat(
        [l_valid[~l_valid["lk"].isin(set(r_valid["rk"]))], l_null])
    r_unmatched = pd.concat(
        [r_valid[~r_valid["rk"].isin(set(l_valid["lk"]))], r_null])
    if how == "left":
        return pd.concat([inner, l_unmatched], ignore_index=True)
    if how == "right":
        return pd.concat([inner, r_unmatched], ignore_index=True)
    return pd.concat([inner, l_unmatched, r_unmatched], ignore_index=True)


def _norm(df, cols):
    return df[cols].sort_values(cols, na_position="first") \
        .reset_index(drop=True).astype("object")


def _check(engine_df, oracle_df):
    cols = list(engine_df.columns)
    a = _norm(engine_df, cols)
    b = _norm(oracle_df, cols)
    assert len(a) == len(b), (len(a), len(b))
    for c in cols:
        va, vb = a[c].to_numpy(), b[c].to_numpy()
        for x, y in zip(va, vb):
            if x is None or (isinstance(x, float) and np.isnan(x)):
                assert y is None or (isinstance(y, float) and np.isnan(y))
            elif isinstance(x, float):
                assert abs(x - y) < 1e-9
            else:
                assert x == y, (c, x, y)


class TestOuterJoins:
    @pytest.mark.parametrize("how", ["left", "right", "full"])
    def test_matches_pandas(self, env, how):
        session = env["session"]
        lt = session.read.parquet(env["l"])
        rt = session.read.parquet(env["r"])
        q = lt.join(rt, on=col("lk") == col("rk"), how=how)
        got = q.to_pandas()
        pandas_how = {"left": "left", "right": "right",
                      "full": "outer"}[how]
        exp = _oracle(env["left"], env["right"], pandas_how)
        _check(got, exp)

    def test_left_null_keys_are_preserved_unmatched(self, env):
        session = env["session"]
        lt = session.read.parquet(env["l"])
        rt = session.read.parquet(env["r"])
        q = lt.join(rt, on=col("lk") == col("rk"), how="left")
        got = q.to_pandas()
        n_null_keys = env["left"]["lk"].isna().sum()
        null_rows = got[got["lk"].isna()]
        assert len(null_rows) == n_null_keys
        assert null_rows["rw"].isna().all()  # padded, never matched

    def test_inner_unchanged(self, env):
        session = env["session"]
        lt = session.read.parquet(env["l"])
        rt = session.read.parquet(env["r"])
        q = lt.join(rt, on=col("lk") == col("rk"), how="inner")
        got = q.to_pandas()
        exp = _oracle(env["left"], env["right"], "inner")
        _check(got, exp)

    def test_string_payloads_and_schema_nullability(self, env):
        session = env["session"]
        lt = session.read.parquet(env["l"])
        rt = session.read.parquet(env["r"])
        q = lt.join(rt, on=col("lk") == col("rk"), how="full")
        # Both sides' columns become nullable in the output schema.
        sch = q.plan.schema
        assert all(sch.field(n).nullable for n in sch.names)
        got = q.to_pandas()
        assert got["ls"].isna().any()  # right-unmatched rows pad left cols

    def test_rule_does_not_rewrite_outer(self, env):
        """The JoinIndexRule is inner-only (reference parity) — an outer
        join over indexed sides must execute on the source scans."""
        session, hs = env["session"], env["hs"]
        lt = session.read.parquet(env["l"])
        rt = session.read.parquet(env["r"])
        hs.create_index(lt, IndexConfig("ol_idx", ["lk"], ["lv", "ls"]))
        hs.create_index(rt, IndexConfig("or_idx", ["rk"], ["rw"]))
        session.enable_hyperspace()
        outer = lt.join(rt, on=col("lk") == col("rk"), how="left")
        assert "IndexScan" not in outer.optimized_plan().tree_string()
        inner = lt.join(rt, on=col("lk") == col("rk"), how="inner")
        assert "IndexScan" in inner.optimized_plan().tree_string()
        # And the outer result is still correct with hyperspace on.
        got = outer.to_pandas()
        _check(got, _oracle(env["left"], env["right"], "left"))
