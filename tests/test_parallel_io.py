"""Parallel I/O subsystem (parallel/io.py): pool/prefetch unit behavior,
the thread-hammer concurrency sweep (tests/test_result_cache_concurrency
style), and — the contract that matters — BYTE-IDENTITY at any thread
count: query results, sketch table file bytes, chunked-build index files,
and FileIdTracker provenance must be identical at io.threads ∈
{1, 4, oversubscribed}, because the pool's ordered gather makes the
parallelism invisible to every consumer.

Sessions run on the CPU platform via conftest with the default
distributed tier (partitioned-jit SPMD over the virtual 8-device mesh).
"""

import glob
import os
import threading
import time

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import (BloomFilterSketch, DataSkippingIndexConfig,
                                Hyperspace, IndexConfig)
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.index.log_entry import FileIdTracker
from hyperspace_tpu.parallel import io as pio
from hyperspace_tpu.plan.expr import col, sum_

# sequential baseline / pooled / oversubscribed (beyond any sane cpu count)
THREAD_SWEEP = [1, 4, 32]


def _session(tmp_path, threads, tag=""):
    sp = tmp_path / f"indexes_{tag}_{threads}"
    sp.mkdir(parents=True, exist_ok=True)
    s = hst.Session(system_path=str(sp))
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    s.conf.set(IndexConstants.TPU_IO_THREADS, threads)
    return s


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    """Several parquet part files with int/float/string columns (string
    dictionaries are the subtle cross-file unification case)."""
    root = tmp_path_factory.mktemp("io_data")
    d = root / "data"
    d.mkdir()
    rng = np.random.default_rng(29)
    for i in range(7):
        n = 900 + 40 * i  # distinct per-file lengths
        pq.write_table(pa.table({
            "k": pa.array(rng.integers(0, 50, n).astype(np.int64)),
            "v": pa.array(np.round(rng.uniform(0, 10, n), 3)),
            "s": pa.array(rng.choice(["ant", "bee", "cat", "dog"], n)),
        }), d / f"p{i}.parquet")
    return str(d)


# ---------------------------------------------------------------------------
# Unit behavior of the primitives.
# ---------------------------------------------------------------------------

class TestPoolPrimitives:
    def test_map_ordered_preserves_order(self):
        p = pio.IoParams(threads=4)
        out = pio.map_ordered(lambda x: x * x, range(64), params=p)
        assert out == [x * x for x in range(64)]

    def test_map_ordered_propagates_exceptions(self):
        p = pio.IoParams(threads=4)

        def boom(x):
            if x == 13:
                raise ValueError("boom 13")
            return x

        with pytest.raises(ValueError, match="boom 13"):
            pio.map_ordered(boom, range(32), params=p)

    def test_byte_budget_serializes_oversized_items(self):
        """Every item's weight exceeds the budget: the submission window
        must collapse to one in-flight task at a time."""
        p = pio.IoParams(threads=8, max_inflight_bytes=10)
        lock = threading.Lock()
        state = {"cur": 0, "max": 0}

        def fn(x):
            with lock:
                state["cur"] += 1
                state["max"] = max(state["max"], state["cur"])
            time.sleep(0.005)
            with lock:
                state["cur"] -= 1
            return x

        out = pio.map_ordered(fn, range(16), weight=lambda x: 100, params=p)
        assert out == list(range(16))
        assert state["max"] == 1

    def test_unweighted_items_do_run_concurrently(self):
        p = pio.IoParams(threads=8)
        lock = threading.Lock()
        state = {"cur": 0, "max": 0}

        def fn(x):
            with lock:
                state["cur"] += 1
                state["max"] = max(state["max"], state["cur"])
            time.sleep(0.01)
            with lock:
                state["cur"] -= 1
            return x

        pio.map_ordered(fn, range(32), params=p)
        assert state["max"] > 1

    def test_nested_fanout_runs_sequentially_without_deadlock(self):
        p = pio.IoParams(threads=2)

        def outer(x):
            assert pio.in_worker()
            inner = pio.map_ordered(lambda y: y + x, range(20), params=p)
            return sum(inner)

        out = pio.map_ordered(outer, range(40), params=p)
        assert out == [sum(y + x for y in range(20)) for x in range(40)]

    def test_prefetch_iter_order_and_close(self):
        p = pio.IoParams(threads=4, prefetch_depth=3)
        assert list(pio.prefetch_iter(iter(range(100)), params=p)) == \
            list(range(100))

        produced = []

        def gen():
            i = 0
            while True:
                produced.append(i)
                yield i
                i += 1

        it = pio.prefetch_iter(gen(), params=p)
        got = []
        for v in it:
            got.append(v)
            if v >= 5:
                break
        it.close()
        assert got == list(range(6))
        # Producer ran at most depth ahead of what the consumer took.
        assert len(produced) <= 6 + 3 + 1

    def test_prefetch_iter_propagates_exceptions(self):
        p = pio.IoParams(threads=4)

        def gen():
            yield 1
            yield 2
            raise RuntimeError("stream died")

        it = pio.prefetch_iter(gen(), params=p)
        assert next(it) == 1
        with pytest.raises(RuntimeError, match="stream died"):
            list(it)

    def test_threads_one_is_fully_sequential(self):
        p = pio.IoParams(threads=1)
        seen_threads = set()

        def fn(x):
            seen_threads.add(threading.get_ident())
            return x

        pio.map_ordered(fn, range(8), params=p)
        list(pio.prefetch_iter(iter(range(8)), params=p))
        assert seen_threads == {threading.get_ident()}


class TestPoolHammer:
    def test_concurrent_streams_from_many_threads(self):
        """The serving access pattern: many threads each running pooled
        fan-outs and prefetch streams against the one process pool."""
        p = pio.IoParams(threads=4, prefetch_depth=2)
        errors = []

        def worker(seed):
            try:
                rng = np.random.default_rng(seed)
                for _ in range(5):
                    items = [int(x) for x in rng.integers(0, 1000, 30)]
                    assert pio.map_ordered(
                        lambda x: x * 3, items, params=p,
                        weight=lambda x: x) == [x * 3 for x in items]
                    assert list(pio.prefetch_iter(
                        iter(items), params=p,
                        nbytes=lambda x: x)) == items
            except Exception as e:  # surfaced below
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors


# ---------------------------------------------------------------------------
# Byte-identity across thread counts.
# ---------------------------------------------------------------------------

class TestScanDeterminism:
    def test_query_results_identical_across_thread_counts(
            self, dataset, tmp_path):
        results = []
        for threads in THREAD_SWEEP:
            s = _session(tmp_path, threads, "scan")
            df = s.read.parquet(dataset)
            q = df.filter(col("k") > 10).select("k", "v", "s")
            agg = df.group_by("s").agg(sum_(col("v")).alias("sv"))
            results.append((q.to_arrow(), agg.to_arrow()))
        base_q, base_agg = results[0]
        for got_q, got_agg in results[1:]:
            assert got_q.equals(base_q)
            assert got_agg.equals(base_agg)

    def test_chunked_scan_identical_across_thread_counts(
            self, dataset, tmp_path):
        """Force the streaming (prefetched) scan path with a tiny chunk
        budget; survivors and their order must match the sequential
        stream exactly."""
        results = []
        for threads in THREAD_SWEEP:
            s = _session(tmp_path, threads, "chunk")
            s.conf.set(IndexConstants.TPU_MAX_CHUNK_ROWS, 500)
            q = s.read.parquet(dataset) \
                .filter(col("k") > 25).select("k", "v", "s")
            results.append(q.to_arrow())
        for got in results[1:]:
            assert got.equals(results[0])

    def test_partitioned_csv_grouped_reads_identical(self, tmp_path):
        """The sources/partitions.py satellite: non-parquet partitioned
        reads batch per-partition file groups; values and row order must
        equal the old per-file loop (= the threads=1 result)."""
        rng = np.random.default_rng(31)
        root = tmp_path / "pdata"
        expected_frames = []
        for region in ("asia", "emea", "na"):
            for part in range(2):
                d = root / f"region={region}"
                d.mkdir(parents=True, exist_ok=True)
                f = pd.DataFrame({
                    "id": rng.integers(0, 500, 120).astype(np.int64),
                    "amount": np.round(rng.uniform(0, 50, 120), 2),
                })
                f.to_csv(d / f"part{part}.csv", index=False)
                expected_frames.append(f.assign(region=region))
        results = []
        for threads in THREAD_SWEEP:
            s = _session(tmp_path, threads, "csv")
            q = s.read.csv(str(root)).select("id", "amount", "region")
            results.append(q.to_arrow())
        for got in results[1:]:
            assert got.equals(results[0])
        # And the values are right (not merely consistently wrong).
        got = results[0].to_pandas()
        exp = pd.concat(expected_frames, ignore_index=True)
        key = ["id", "amount", "region"]
        pd.testing.assert_frame_equal(
            got.sort_values(key).reset_index(drop=True),
            exp.sort_values(key).reset_index(drop=True), check_dtype=False)


class TestSketchDeterminism:
    def test_sketch_table_bytes_and_provenance_identical(
            self, dataset, tmp_path):
        sketch_bytes = []
        trackers = []
        for threads in THREAD_SWEEP:
            s = _session(tmp_path, threads, "sk")
            hs = Hyperspace(s)
            df = s.read.parquet(dataset)
            hs.create_index(df, DataSkippingIndexConfig(
                "sk", [BloomFilterSketch("k", expected_items=2000)]))
            files = glob.glob(os.path.join(
                str(tmp_path / f"indexes_sk_{threads}"), "**",
                "sketches.parquet"), recursive=True)
            assert len(files) == 1
            with open(files[0], "rb") as f:
                sketch_bytes.append(f.read())

            # FileIdTracker provenance straight off the build helper.
            from hyperspace_tpu.actions.create_skipping import \
                build_sketch_rows
            from hyperspace_tpu.index.log_entry import Sketch
            relation = df.plan.relation
            tracker = FileIdTracker()
            with pio.use_session(s):
                rows = build_sketch_rows(
                    relation, [Sketch("MinMax", "k", {})],
                    relation.all_files(), tracker)
            trackers.append((rows["_file_id"], tracker.file_to_id_mapping))
        for b in sketch_bytes[1:]:
            assert b == sketch_bytes[0]
        for ids, mapping in trackers[1:]:
            assert ids == trackers[0][0]
            assert mapping == trackers[0][1]


class TestBuildDeterminism:
    def test_chunked_lineage_build_identical_across_thread_counts(
            self, dataset, tmp_path):
        """The spill-merge path (double-buffered read-back) + lineage ids
        from the prefetched chunk stream's provenance: every bucket file
        must hold identical rows in identical order."""
        per_threads = []
        for threads in THREAD_SWEEP:
            s = _session(tmp_path, threads, "bld")
            s.conf.set(IndexConstants.TPU_MAX_CHUNK_ROWS, 700)
            s.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
            hs = Hyperspace(s)
            df = s.read.parquet(dataset)
            hs.create_index(df, IndexConfig("cov", ["k"], ["v", "s"]))
            files = sorted(
                glob.glob(os.path.join(
                    str(tmp_path / f"indexes_bld_{threads}"), "**",
                    "*.parquet"), recursive=True))
            assert files
            per_threads.append(
                [(os.path.basename(f), pq.read_table(f)) for f in files])
        base = per_threads[0]
        for built in per_threads[1:]:
            assert [n for n, _ in built] == [n for n, _ in base]
            for (_, got), (_, exp) in zip(built, base):
                assert got.equals(exp)

    def test_indexed_query_identical_across_thread_counts(
            self, dataset, tmp_path):
        results = []
        for threads in THREAD_SWEEP:
            s = _session(tmp_path, threads, "q")
            hs = Hyperspace(s)
            df = s.read.parquet(dataset)
            hs.create_index(df, IndexConfig("qidx", ["k"], ["v"]))
            s.enable_hyperspace()
            q = df.filter(col("k") == 7).select("k", "v")
            results.append(q.to_arrow())
        for got in results[1:]:
            assert got.equals(results[0])
