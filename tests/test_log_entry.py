"""IndexLogEntry JSON round-trip + FileIdTracker tests.

Parity: reference IndexLogEntryTest.scala / FileIdTrackerTest.scala.
"""

import pytest

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.constants import IndexConstants, States
from hyperspace_tpu.index.log_entry import (
    Content, CoveringIndex, DataSkippingIndex, Directory, FileIdTracker, FileInfo, Hdfs,
    IndexLogEntry, LogicalPlanFingerprint, Relation, Signature, Sketch, Source, SourcePlan,
    Update)
from hyperspace_tpu.schema import Field, Schema


def make_content(prefix, names, tracker=None, sizes=None):
    files = [FileInfo(n, (sizes or {}).get(n, 10), 100, i) for i, n in enumerate(names)]
    root = Directory("/", [], [Directory(prefix.strip("/"), files, [])])
    return Content(root)


def make_entry(name="idx1", state=States.ACTIVE):
    schema = Schema([Field("a", "int64", False), Field("b", "string")])
    ci = CoveringIndex(["a"], ["b"], schema, 8, {IndexConstants.LINEAGE_PROPERTY: "true"})
    src_content = make_content("/data", ["f1.parquet", "f2.parquet"])
    rel = Relation(["/data"], Hdfs(src_content), schema, "parquet", {"opt": "1"})
    fingerprint = LogicalPlanFingerprint(
        [Signature("FileBasedSignatureProvider", "abc123")])
    source = Source(SourcePlan([rel], fingerprint))
    idx_content = make_content("/indexes/idx1/v__=0", ["part0.parquet", "part1.parquet"])
    entry = IndexLogEntry.create(name, ci, idx_content, source, {})
    entry.state = state
    entry.id = 1
    return entry


class TestIndexLogEntry:
    def test_json_round_trip(self):
        entry = make_entry()
        text = entry.to_json()
        back = IndexLogEntry.from_json(text)
        assert back.name == entry.name
        assert back.state == States.ACTIVE
        assert back.id == 1
        assert back.derivedDataset.indexed_columns == ["a"]
        assert back.derivedDataset.included_columns == ["b"]
        assert back.derivedDataset.num_buckets == 8
        assert back.schema.names == ["a", "b"]
        assert back.relation.fileFormat == "parquet"
        assert back.relation.options == {"opt": "1"}
        assert back.signature.signatures[0].value == "abc123"
        assert back.has_lineage_column()
        assert back.properties[IndexConstants.HYPERSPACE_VERSION_PROPERTY]
        # Round-trip again for stability.
        assert IndexLogEntry.from_json(back.to_json()).to_json() == text

    def test_dataskipping_round_trip(self):
        schema = Schema([Field("file_id", "int64", False), Field("min_a", "int64")])
        ds = DataSkippingIndex([Sketch("MinMax", "a"), Sketch("BloomFilter", "b")], schema)
        entry = make_entry()
        entry.derivedDataset = ds
        back = IndexLogEntry.from_json(entry.to_json())
        assert back.derivedDataset.kind == "DataSkippingIndex"
        assert [s.kind for s in back.derivedDataset.sketches] == ["MinMax", "BloomFilter"]
        assert back.derivedDataset.indexed_columns == ["a", "b"]

    def test_file_info_equality_ignores_id(self):
        a = FileInfo("f", 1, 2, 10)
        b = FileInfo("f", 1, 2, 99)
        assert a == b and hash(a) == hash(b)
        assert a != FileInfo("f", 1, 3, 10)

    def test_content_files_and_fileinfos(self):
        c = make_content("/data", ["f1", "f2"])
        assert sorted(c.files) == ["/data/f1", "/data/f2"]
        infos = c.file_infos
        assert {f.name for f in infos} == {"/data/f1", "/data/f2"}

    def test_update_round_trip(self):
        entry = make_entry()
        appended = make_content("/data", ["f3"])
        entry.relation.data.update = Update(appendedFiles=appended)
        back = IndexLogEntry.from_json(entry.to_json())
        assert {f.name for f in back.appended_files} == {"/data/f3"}
        assert back.deleted_files == set()

    def test_directory_merge(self):
        d1 = Directory("/", [], [Directory("a", [FileInfo("x", 1, 1, 0)], [])])
        d2 = Directory("/", [], [Directory("a", [FileInfo("y", 1, 1, 1)], []),
                                 Directory("b", [FileInfo("z", 1, 1, 2)], [])])
        merged = d1.merge(d2)
        names = {d.name for d in merged.subDirs}
        assert names == {"a", "b"}
        a = next(d for d in merged.subDirs if d.name == "a")
        assert {f.name for f in a.files} == {"x", "y"}

    def test_directory_merge_name_mismatch(self):
        with pytest.raises(HyperspaceException):
            Directory("a").merge(Directory("b"))


class TestFileIdTracker:
    def test_add_file_assigns_sequential_ids(self):
        t = FileIdTracker()
        assert t.add_file("/p/f1", 10, 100) == 0
        assert t.add_file("/p/f2", 10, 100) == 1
        # Same triple → same id.
        assert t.add_file("/p/f1", 10, 100) == 0
        # Changed mtime → new id.
        assert t.add_file("/p/f1", 10, 101) == 2
        assert t.max_file_id == 2

    def test_add_file_info_conflict(self):
        t = FileIdTracker()
        t.add_file_info({FileInfo("/p/f1", 10, 100, 5)})
        assert t.max_file_id == 5
        with pytest.raises(HyperspaceException):
            t.add_file_info({FileInfo("/p/f1", 10, 100, 6)})

    def test_add_file_info_unknown_id(self):
        t = FileIdTracker()
        with pytest.raises(HyperspaceException):
            t.add_file_info({FileInfo("/p/f1", 10, 100, IndexConstants.UNKNOWN_FILE_ID)})


class TestDirectoryFromLeafFiles:
    def test_tree_structure(self, tmp_path):
        (tmp_path / "a").mkdir()
        (tmp_path / "a" / "f1.parquet").write_text("x" * 10)
        (tmp_path / "a" / "f2.parquet").write_text("y" * 20)
        (tmp_path / "b").mkdir()
        (tmp_path / "b" / "f3.parquet").write_text("z" * 30)
        tracker = FileIdTracker()
        content = Content.from_directory(str(tmp_path), tracker)
        files = sorted(content.files)
        assert [f.split("/")[-1] for f in files] == ["f1.parquet", "f2.parquet", "f3.parquet"]
        sizes = {f.name.split("/")[-1]: f.size for f in content.file_infos}
        assert sizes == {"f1.parquet": 10, "f2.parquet": 20, "f3.parquet": 30}
        assert tracker.max_file_id == 2
