"""Distributed build over the virtual 8-device CPU mesh.

The reference validates its distribution semantics on single-process Spark
local[4] (SURVEY §4); our equivalent is XLA host-platform device
virtualization: a real all-to-all bucket exchange runs across 8 CPU devices.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from hyperspace_tpu.execution.columnar import Table
from hyperspace_tpu.ops import index_build
from hyperspace_tpu.parallel import (device_bucket_range, distributed_build_sorted_buckets,
                                     make_mesh)


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    return make_mesh()


def make_table(n=1000, seed=3):
    rng = np.random.default_rng(seed)
    df = pd.DataFrame({
        "k": rng.integers(0, 200, n).astype(np.int64),
        "v": rng.uniform(0, 1, n),
        "s": rng.choice(["x", "y", "z", "w"], n),
    })
    return Table.from_arrow(pa.Table.from_pandas(df)), df


class TestDistributedBuild:
    def test_row_conservation_and_sortedness(self, mesh):
        table, df = make_table()
        num_buckets = 16
        out, valid, bids = distributed_build_sorted_buckets(
            table, ["k"], num_buckets, mesh)
        valid_np = np.asarray(jax.device_get(valid))
        bids_np = np.asarray(jax.device_get(bids))
        assert int(valid_np.sum()) == len(df)

        # Check per-device shards: rows belong to the device's bucket range,
        # sorted by (bucket, key) with padding at the tail.
        n_dev = 8
        shard_len = valid_np.shape[0] // n_dev
        k_np = np.asarray(jax.device_get(out.column("k").data))
        for d in range(n_dev):
            lo, hi = d * shard_len, (d + 1) * shard_len
            v = valid_np[lo:hi]
            b = bids_np[lo:hi][v]
            k = k_np[lo:hi][v]
            blo, bhi = device_bucket_range(d, n_dev, num_buckets)
            assert ((b >= blo) & (b < bhi)).all()
            # Sorted by (bucket, key).
            order = np.lexsort((k, b))
            assert (order == np.arange(len(order))).all()
            # Padding strictly at the tail.
            if (~v).any():
                assert not v[np.argmax(~v):].any()

    def test_matches_single_device_bucketing(self, mesh):
        """Distributed and single-device builds agree on bucket contents."""
        table, df = make_table(500, seed=9)
        num_buckets = 8
        out, valid, bids = distributed_build_sorted_buckets(
            table, ["k"], num_buckets, mesh)
        valid_np = np.asarray(jax.device_get(valid))
        dist_k = np.asarray(jax.device_get(out.column("k").data))[valid_np]
        dist_b = np.asarray(jax.device_get(bids))[valid_np]

        sorted_table, bounds = index_build.build_sorted_buckets(
            table, ["k"], num_buckets)
        single_k = np.asarray(jax.device_get(sorted_table.column("k").data))
        for b in range(num_buckets):
            lo, hi = int(bounds[b]), int(bounds[b + 1])
            np.testing.assert_array_equal(
                np.sort(single_k[lo:hi]), np.sort(dist_k[dist_b == b]))

    def test_string_key_distribution(self, mesh):
        table, df = make_table(400, seed=11)
        out, valid, bids = distributed_build_sorted_buckets(
            table, ["s"], 8, mesh)
        valid_np = np.asarray(jax.device_get(valid))
        assert int(valid_np.sum()) == len(df)
        # Same string → same bucket everywhere.
        s_codes = np.asarray(jax.device_get(out.column("s").data))[valid_np]
        b = np.asarray(jax.device_get(bids))[valid_np]
        for code in np.unique(s_codes):
            assert len(np.unique(b[s_codes == code])) == 1

    def test_skew_overflow_retry(self, mesh):
        """All rows in one bucket: capacity retry must still succeed."""
        n = 800
        df = pd.DataFrame({"k": np.full(n, 7, np.int64), "v": np.arange(n, dtype=np.float64)})
        table = Table.from_arrow(pa.Table.from_pandas(df))
        out, valid, bids = distributed_build_sorted_buckets(
            table, ["k"], 4, mesh, capacity_factor=0.5)
        valid_np = np.asarray(jax.device_get(valid))
        assert int(valid_np.sum()) == n
        b = np.asarray(jax.device_get(bids))[valid_np]
        assert len(np.unique(b)) == 1


class TestDistributedQuery:
    def test_range_agg_matches_pandas(self, mesh):
        from hyperspace_tpu.parallel import distributed_range_agg

        table, df = make_table(2000, seed=5)
        count, sums = distributed_range_agg(
            table, "k", 50, 120, ("v",), mesh)
        want = df[(df.k >= 50) & (df.k <= 120)]
        assert count == len(want)
        np.testing.assert_allclose(float(sums["v"]), want.v.sum(), rtol=1e-12)

    def test_range_agg_exclusive_bounds(self, mesh):
        from hyperspace_tpu.parallel import distributed_range_agg

        table, df = make_table(700, seed=6)
        count, _ = distributed_range_agg(
            table, "k", 50, 120, (), mesh, lo_incl=False, hi_incl=False)
        assert count == len(df[(df.k > 50) & (df.k < 120)])

    def test_join_agg_copartitioned(self, mesh):
        """Full pipeline: distributed build of both sides, then the
        shuffle-free co-partitioned join aggregate; totals must match the
        pandas join."""
        from hyperspace_tpu.parallel import distributed_join_agg

        rng = np.random.default_rng(7)
        n_l, n_r, nb = 1500, 400, 16
        ldf = pd.DataFrame({"k": rng.integers(0, 120, n_l).astype(np.int64),
                            "lv": rng.uniform(0, 10, n_l)})
        rdf = pd.DataFrame({"k": rng.integers(0, 120, n_r).astype(np.int64),
                            "rv": rng.uniform(0, 10, n_r)})
        lt, lvalid, _ = distributed_build_sorted_buckets(
            Table.from_arrow(pa.Table.from_pandas(ldf)), ["k"], nb, mesh)
        rt, rvalid, _ = distributed_build_sorted_buckets(
            Table.from_arrow(pa.Table.from_pandas(rdf)), ["k"], nb, mesh)
        count, lsum, rsum = distributed_join_agg(
            lt, lvalid, rt, rvalid, "k", "lv", "rv", mesh)
        joined = ldf.merge(rdf, on="k")
        assert count == len(joined)
        np.testing.assert_allclose(lsum, joined.lv.sum(), rtol=1e-9)
        np.testing.assert_allclose(rsum, joined.rv.sum(), rtol=1e-9)

    def test_join_agg_empty_matches(self, mesh):
        from hyperspace_tpu.parallel import distributed_join_agg

        ldf = pd.DataFrame({"k": np.arange(0, 50, dtype=np.int64),
                            "lv": np.ones(50)})
        rdf = pd.DataFrame({"k": np.arange(100, 120, dtype=np.int64),
                            "rv": np.ones(20)})
        lt, lvalid, _ = distributed_build_sorted_buckets(
            Table.from_arrow(pa.Table.from_pandas(ldf)), ["k"], 8, mesh)
        rt, rvalid, _ = distributed_build_sorted_buckets(
            Table.from_arrow(pa.Table.from_pandas(rdf)), ["k"], 8, mesh)
        count, lsum, rsum = distributed_join_agg(
            lt, lvalid, rt, rvalid, "k", "lv", "rv", mesh)
        assert (count, lsum, rsum) == (0, 0.0, 0.0)

    def test_join_agg_rejects_nullable_key(self, mesh):
        from hyperspace_tpu.exceptions import HyperspaceException
        from hyperspace_tpu.parallel import distributed_join_agg

        lt = Table.from_arrow(pa.table({
            "k": pa.array([1, None, 3], type=pa.int64()),
            "lv": pa.array([1.0, 2.0, 3.0])}))
        rt = Table.from_arrow(pa.table({
            "k": pa.array([1, 2, 3], type=pa.int64()),
            "rv": pa.array([1.0, 2.0, 3.0])}))
        valid = jnp.ones(3, jnp.bool_)
        with pytest.raises(HyperspaceException, match="nullable"):
            distributed_join_agg(lt, valid, rt, valid, "k", "lv", "rv", mesh)

    def test_join_agg_sentinel_valued_key(self, mesh):
        """A legitimate key equal to int64 max must not match padding rows."""
        from hyperspace_tpu.parallel import distributed_join_agg

        imax = np.iinfo(np.int64).max
        ldf = pd.DataFrame({"k": np.array([imax, 5, imax], dtype=np.int64),
                            "lv": np.array([1.0, 2.0, 3.0])})
        rdf = pd.DataFrame({"k": np.array([imax, 7], dtype=np.int64),
                            "rv": np.array([10.0, 20.0])})
        lt, lvalid, _ = distributed_build_sorted_buckets(
            Table.from_arrow(pa.Table.from_pandas(ldf)), ["k"], 8, mesh)
        rt, rvalid, _ = distributed_build_sorted_buckets(
            Table.from_arrow(pa.Table.from_pandas(rdf)), ["k"], 8, mesh)
        count, lsum, rsum = distributed_join_agg(
            lt, lvalid, rt, rvalid, "k", "lv", "rv", mesh)
        joined = ldf.merge(rdf, on="k")
        assert count == len(joined) == 2
        np.testing.assert_allclose(lsum, joined.lv.sum())
        np.testing.assert_allclose(rsum, joined.rv.sum())


class TestMultihost:
    def test_single_process_noop_and_global_mesh(self):
        """Without a coordinator the initialize is a no-op, and the global
        mesh spans every visible device (8 on the CI virtual mesh)."""
        import jax

        from hyperspace_tpu.parallel.multihost import (global_mesh,
                                                       initialize_multihost)

        info = initialize_multihost()
        assert info["initialized"] is False
        assert info["process_count"] == 1
        assert info["global_devices"] == len(jax.devices())
        mesh = global_mesh()
        assert mesh.devices.size == len(jax.devices())
