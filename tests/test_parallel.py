"""Distributed build over the virtual 8-device CPU mesh.

The reference validates its distribution semantics on single-process Spark
local[4] (SURVEY §4); our equivalent is XLA host-platform device
virtualization: a real all-to-all bucket exchange runs across 8 CPU devices.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from hyperspace_tpu.execution.columnar import Table
from hyperspace_tpu.ops import index_build
from hyperspace_tpu.parallel import (device_bucket_range, distributed_build_sorted_buckets,
                                     make_mesh)


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    return make_mesh()


def make_table(n=1000, seed=3):
    rng = np.random.default_rng(seed)
    df = pd.DataFrame({
        "k": rng.integers(0, 200, n).astype(np.int64),
        "v": rng.uniform(0, 1, n),
        "s": rng.choice(["x", "y", "z", "w"], n),
    })
    return Table.from_arrow(pa.Table.from_pandas(df)), df


class TestDistributedBuild:
    def test_row_conservation_and_sortedness(self, mesh):
        table, df = make_table()
        num_buckets = 16
        out, valid, bids = distributed_build_sorted_buckets(
            table, ["k"], num_buckets, mesh)
        valid_np = np.asarray(jax.device_get(valid))
        bids_np = np.asarray(jax.device_get(bids))
        assert int(valid_np.sum()) == len(df)

        # Check per-device shards: rows belong to the device's bucket range,
        # sorted by (bucket, key) with padding at the tail.
        n_dev = 8
        shard_len = valid_np.shape[0] // n_dev
        k_np = np.asarray(jax.device_get(out.column("k").data))
        for d in range(n_dev):
            lo, hi = d * shard_len, (d + 1) * shard_len
            v = valid_np[lo:hi]
            b = bids_np[lo:hi][v]
            k = k_np[lo:hi][v]
            blo, bhi = device_bucket_range(d, n_dev, num_buckets)
            assert ((b >= blo) & (b < bhi)).all()
            # Sorted by (bucket, key).
            order = np.lexsort((k, b))
            assert (order == np.arange(len(order))).all()
            # Padding strictly at the tail.
            if (~v).any():
                assert not v[np.argmax(~v):].any()

    def test_matches_single_device_bucketing(self, mesh):
        """Distributed and single-device builds agree on bucket contents."""
        table, df = make_table(500, seed=9)
        num_buckets = 8
        out, valid, bids = distributed_build_sorted_buckets(
            table, ["k"], num_buckets, mesh)
        valid_np = np.asarray(jax.device_get(valid))
        dist_k = np.asarray(jax.device_get(out.column("k").data))[valid_np]
        dist_b = np.asarray(jax.device_get(bids))[valid_np]

        sorted_table, bounds = index_build.build_sorted_buckets(
            table, ["k"], num_buckets)
        single_k = np.asarray(jax.device_get(sorted_table.column("k").data))
        for b in range(num_buckets):
            lo, hi = int(bounds[b]), int(bounds[b + 1])
            np.testing.assert_array_equal(
                np.sort(single_k[lo:hi]), np.sort(dist_k[dist_b == b]))

    def test_string_key_distribution(self, mesh):
        table, df = make_table(400, seed=11)
        out, valid, bids = distributed_build_sorted_buckets(
            table, ["s"], 8, mesh)
        valid_np = np.asarray(jax.device_get(valid))
        assert int(valid_np.sum()) == len(df)
        # Same string → same bucket everywhere.
        s_codes = np.asarray(jax.device_get(out.column("s").data))[valid_np]
        b = np.asarray(jax.device_get(bids))[valid_np]
        for code in np.unique(s_codes):
            assert len(np.unique(b[s_codes == code])) == 1

    def test_skew_overflow_retry(self, mesh):
        """All rows in one bucket: capacity retry must still succeed."""
        n = 800
        df = pd.DataFrame({"k": np.full(n, 7, np.int64), "v": np.arange(n, dtype=np.float64)})
        table = Table.from_arrow(pa.Table.from_pandas(df))
        out, valid, bids = distributed_build_sorted_buckets(
            table, ["k"], 4, mesh, capacity_factor=0.5)
        valid_np = np.asarray(jax.device_get(valid))
        assert int(valid_np.sum()) == n
        b = np.asarray(jax.device_get(bids))[valid_np]
        assert len(np.unique(b)) == 1
