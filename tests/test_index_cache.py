"""HBM-resident index cache tests."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.execution import index_cache
from hyperspace_tpu.execution.index_cache import IndexTableCache
from hyperspace_tpu.execution.columnar import Column, Table
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col


@pytest.fixture(autouse=True)
def fresh_cache():
    index_cache.get_cache().clear()
    yield
    index_cache.get_cache().clear()


def _table(n):
    import jax.numpy as jnp
    return Table({"x": Column("int64", jnp.arange(n))})


class TestLru:
    def test_hit_returns_same_object(self):
        c = IndexTableCache(1 << 20)
        t = _table(10)
        c.put(("k",), t)
        assert c.get(("k",)) is t
        assert (c.hits, c.misses) == (1, 0)

    def test_eviction_by_bytes(self):
        c = IndexTableCache(max_bytes=3 * 800)  # 100 int64 rows = 800 B.
        for i in range(5):
            c.put((i,), _table(100))
        assert c.get((0,)) is None and c.get((1,)) is None
        assert c.get((4,)) is not None
        assert c.nbytes <= 3 * 800

    def test_oversized_entry_skipped(self):
        c = IndexTableCache(max_bytes=100)
        c.put(("big",), _table(1000))
        assert c.get(("big",)) is None
        assert c.nbytes == 0


class TestExecutorIntegration:
    @pytest.fixture()
    def env(self, tmp_system_path, tmp_path):
        rng = np.random.default_rng(0)
        d = tmp_path / "t"
        d.mkdir()
        pq.write_table(pa.table({
            "k": pa.array(rng.integers(0, 50, 1200).astype(np.int64)),
            "v": pa.array(rng.uniform(0, 1, 1200)),
        }), str(d / "p.parquet"))
        session = hst.Session(system_path=tmp_system_path)
        session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
        hs = Hyperspace(session)
        df = session.read.parquet(str(d))
        hs.create_index(df, IndexConfig("cix", ["k"], ["v"]))
        session.enable_hyperspace()
        return session, df

    def test_second_query_hits_cache(self, env):
        # A group-by over the indexed key consumes the WHOLE index — the
        # cache serves that read (leading-column filters take the pruned
        # parquet path instead, tested below).
        from hyperspace_tpu.plan.expr import sum_
        session, df = env
        q = df.group_by("k").agg(sum_(col("v")).alias("sv"))
        cache = index_cache.get_cache()
        cache.clear()
        hits0, misses0 = cache.hits, cache.misses
        r1 = q.to_arrow()
        misses_after_first = cache.misses
        assert misses_after_first >= misses0 + 1
        r2 = q.to_arrow()
        assert cache.hits >= hits0 + 1
        assert cache.misses == misses_after_first
        assert r1.equals(r2)

    def test_leading_column_filter_bypasses_cache(self, env):
        """On the single-device path, a filter constraining the leading
        indexed column must take the row-group-pruned parquet read, not
        the cached full-table mask — the cache path cost a 6M-row device
        filter per query at SF1 and inverted the filter benchmark (0.85x).
        (The SPMD mesh path materializes the leaf through the cache and
        filters by mask — its row-sharded stream has no pruned-read
        equivalent yet.)"""
        session, df = env
        session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "false")
        q = df.filter(col("k") > 10).select("k", "v")
        cache = index_cache.get_cache()
        cache.clear()
        hits0, misses0 = cache.hits, cache.misses
        r1 = q.to_arrow()
        r2 = q.to_arrow()
        assert (cache.hits, cache.misses) == (hits0, misses0)
        assert r1.equals(r2)
        # Same rows as the no-index path.
        session.disable_hyperspace()
        key = lambda t: t.sort_by([(c, "ascending") for c in t.column_names])
        assert key(r1).equals(key(q.to_arrow()))
        session.enable_hyperspace()

    def test_results_match_disabled_cache(self, env, monkeypatch):
        session, df = env
        q = df.filter(col("k").between(5, 25)).select("k", "v")
        key = lambda t: t.sort_by([(c, "ascending") for c in t.column_names])
        warm = key(q.to_arrow())
        warm2 = key(q.to_arrow())  # cached path.
        monkeypatch.setenv("HST_INDEX_CACHE", "off")
        cold = key(q.to_arrow())
        assert warm.equals(cold) and warm2.equals(cold)
        session.disable_hyperspace()
        assert key(q.to_arrow()).equals(cold)

    def test_refresh_uses_new_key(self, env, tmp_path):
        """After incremental refresh, queries read the new file set (no
        stale cache hits — the key includes the file tuple)."""
        session, df = env
        hs = Hyperspace(session)
        q = df.filter(col("k") > 10).select("k", "v")
        before = q.to_arrow()
        rng = np.random.default_rng(1)
        pq.write_table(pa.table({
            "k": pa.array(rng.integers(0, 50, 300).astype(np.int64)),
            "v": pa.array(rng.uniform(0, 1, 300)),
        }), str(tmp_path / "t" / "p2.parquet"))
        hs.refresh_index("cix", "incremental")
        # Re-list the source (the old DataFrame pins its file listing).
        df2 = session.read.parquet(str(tmp_path / "t"))
        q2 = df2.filter(col("k") > 10).select("k", "v")
        after = q2.to_arrow()
        assert after.num_rows > before.num_rows
        session.disable_hyperspace()
        key = lambda t: t.sort_by([(c, "ascending") for c in t.column_names])
        assert key(after).equals(key(q2.to_arrow()))
