"""Pallas kernel equivalence tests.

On CPU the kernels run in interpret mode (forced via set_mode("on")); each
test asserts bit-identical results against the pure-jnp reference path, so
the TPU kernels are validated for semantics here and for speed on hardware
by bench.py.
"""

import datetime

import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pytest

from hyperspace_tpu.execution.columnar import Table
from hyperspace_tpu.ops import index_build, kernels, pallas_kernels, sketches


@pytest.fixture()
def pallas_on():
    pallas_kernels.set_mode("on")
    yield
    pallas_kernels.set_mode("auto")


def _rand_table(n=1000, seed=0):
    rng = np.random.default_rng(seed)
    return Table.from_arrow(pa.table({
        "i64": pa.array(rng.integers(-10**12, 10**12, n, dtype=np.int64)),
        "i32": pa.array(rng.integers(-10**6, 10**6, n).astype(np.int32)),
        "f64": pa.array(rng.uniform(-1e6, 1e6, n)),
        "s": pa.array(rng.choice(["x", "y", "zz", "w"], n)),
        "d": pa.array((rng.integers(0, 20000, n)).astype(np.int32),
                      type=pa.int32()).cast(pa.date32()),
    }))


class TestFusedHashBucket:
    @pytest.mark.parametrize("cols", [["i64"], ["i32"], ["s"],
                                      ["i64", "s"], ["d", "i32", "f64"]])
    def test_matches_jnp_path(self, pallas_on, cols):
        t = _rand_table()
        got = np.asarray(index_build.bucket_ids_for(t, cols, 37))
        pallas_kernels.set_mode("off")
        want = np.asarray(index_build.bucket_ids_for(t, cols, 37))
        np.testing.assert_array_equal(got, want)

    def test_hash_matches_hash32_values(self, pallas_on):
        t = _rand_table(500)
        col = t.column("i64")
        folded = [kernels.fold_u32(col.data, col.dtype, col.dictionary)]
        h, bids = pallas_kernels.fused_hash_bucket(folded, 16)
        want = np.asarray(kernels.hash32_values(col.data, col.dtype))
        np.testing.assert_array_equal(np.asarray(h), want)
        np.testing.assert_array_equal(
            np.asarray(bids), want % np.uint32(16))

    def test_non_multiple_of_block(self, pallas_on):
        # Exercise padding: n far from a (256*128) boundary and tiny n.
        for n in (3, 130, 32769):
            x = jnp.arange(n, dtype=jnp.int32)
            folded = [kernels.fold_u32(x, "int32")]
            h, bids = pallas_kernels.fused_hash_bucket(folded, 8)
            assert h.shape[0] == n and bids.shape[0] == n
            want = np.asarray(kernels.hash32_values(x, "int32"))
            np.testing.assert_array_equal(np.asarray(h), want)


class TestFusedCompare:
    @pytest.mark.parametrize("op,sym", [
        ("EqualTo", "=="), ("LessThan", "<"), ("LessThanOrEqual", "<="),
        ("GreaterThan", ">"), ("GreaterThanOrEqual", ">=")])
    def test_compare_literal_dispatch(self, pallas_on, op, sym):
        from hyperspace_tpu.execution.evaluator import compare_literal

        t = _rand_table(777)
        col = t.column("i32")
        got = np.asarray(compare_literal(col, op, 1234))
        pallas_kernels.set_mode("off")
        want = np.asarray(compare_literal(col, op, 1234))
        np.testing.assert_array_equal(got, want)

    def test_range_mask(self, pallas_on):
        x = jnp.asarray(np.random.default_rng(1).integers(0, 100, 5000)
                        .astype(np.int32))
        for lo_i in (True, False):
            for hi_i in (True, False):
                got = np.asarray(
                    pallas_kernels.fused_range_mask(x, 20, 60, lo_i, hi_i))
                ml = (x >= 20) if lo_i else (x > 20)
                mh = (x <= 60) if hi_i else (x < 60)
                np.testing.assert_array_equal(got, np.asarray(ml & mh))


class TestMaskedMinMax:
    def test_minmax_with_validity(self, pallas_on):
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.uniform(-50, 50, 3000).astype(np.float32))
        valid = jnp.asarray(rng.random(3000) > 0.3)
        mn, mx = pallas_kernels.masked_minmax(x, valid)
        xs = np.asarray(x)[np.asarray(valid)]
        assert float(mn) == xs.min()
        assert float(mx) == xs.max()

    def test_minmax_values_dispatch_date(self, pallas_on):
        t = _rand_table(400)
        col = t.column("d")
        got = sketches.minmax_values(col)
        pallas_kernels.set_mode("off")
        want = sketches.minmax_values(col)
        assert got == want
        assert isinstance(got[0], datetime.date)

    def test_minmax_values_dispatch_int32_nulls(self, pallas_on):
        arr = pa.array([5, None, -7, 3, None], type=pa.int32())
        t = Table.from_arrow(pa.table({"v": arr}))
        assert sketches.minmax_values(t.column("v")) == (-7, 5)


class TestHistogram:
    def test_counts(self, pallas_on):
        rng = np.random.default_rng(3)
        bids = jnp.asarray(rng.integers(0, 13, 10_000).astype(np.int32))
        got = np.asarray(pallas_kernels.bucket_histogram(bids, 13))
        want = np.bincount(np.asarray(bids), minlength=13)
        np.testing.assert_array_equal(got, want)

    def test_empty_tail_not_counted(self, pallas_on):
        bids = jnp.asarray(np.array([0, 1, 2], dtype=np.int32))
        got = np.asarray(pallas_kernels.bucket_histogram(bids, 4))
        np.testing.assert_array_equal(got, [1, 1, 1, 0])


class TestFusedBetween:
    def test_between_dispatches_to_range_kernel(self, pallas_on):
        """And(col >= lo, col <= hi) over a date column must give the same
        mask with the fused kernel as with the two-compare fallback."""
        from hyperspace_tpu.execution.evaluator import eval_predicate_mask
        from hyperspace_tpu.plan.expr import col

        t = _rand_table(3000)
        epoch = datetime.date(1970, 1, 1)
        cond = col("d").between(epoch + datetime.timedelta(days=5000),
                                epoch + datetime.timedelta(days=15000))
        got = np.asarray(eval_predicate_mask(t, cond))
        pallas_kernels.set_mode("off")
        want = np.asarray(eval_predicate_mask(t, cond))
        np.testing.assert_array_equal(got, want)
        assert want.any() and not want.all()

    def test_between_with_nulls_matches(self, pallas_on):
        from hyperspace_tpu.execution.evaluator import eval_predicate_mask
        from hyperspace_tpu.plan.expr import col

        arr = pa.array([1, None, 7, 12, None, 5], type=pa.int32())
        t = Table.from_arrow(pa.table({"v": arr}))
        cond = col("v").between(2, 10)
        got = np.asarray(eval_predicate_mask(t, cond))
        pallas_kernels.set_mode("off")
        want = np.asarray(eval_predicate_mask(t, cond))
        np.testing.assert_array_equal(got, want)

    def test_boundaries_from_histogram(self, pallas_on):
        """build_sorted_buckets boundary offsets must be identical with the
        histogram path (pallas) and the searchsorted path (fallback)."""
        t = _rand_table(4000)
        _, got = index_build.build_sorted_buckets(t, ["i64"], 16)
        pallas_kernels.set_mode("off")
        _, want = index_build.build_sorted_buckets(t, ["i64"], 16)
        np.testing.assert_array_equal(got, want)


class TestEndToEndWithPallas:
    def test_index_query_equivalence(self, pallas_on, tmp_system_path, tmp_path):
        """Full create-index → rewritten query with pallas forced on; results
        must equal the non-indexed scan (the disable-and-compare oracle)."""
        import pyarrow.parquet as pq

        import hyperspace_tpu as hst
        from hyperspace_tpu.api import Hyperspace, IndexConfig
        from hyperspace_tpu.plan.expr import col

        rng = np.random.default_rng(5)
        data_dir = tmp_path / "data"
        data_dir.mkdir()
        pq.write_table(pa.table({
            "k": pa.array(rng.integers(0, 50, 2000).astype(np.int64)),
            "v": pa.array(rng.uniform(0, 1, 2000)),
        }), str(data_dir / "part0.parquet"))

        session = hst.Session(system_path=tmp_system_path)
        hs = Hyperspace(session)
        df = session.read.parquet(str(data_dir))
        hs.create_index(df, IndexConfig("pidx", ["k"], ["v"]))

        q = df.filter(col("k") == 7).select("k", "v")
        session.enable_hyperspace()
        with_idx = q.to_arrow().sort_by([("k", "ascending"), ("v", "ascending")])
        assert any("IndexScan" in l.simple_string()
                   for l in q.optimized_plan().collect_leaves())
        session.disable_hyperspace()
        without = q.to_arrow().sort_by([("k", "ascending"), ("v", "ascending")])
        assert with_idx.equals(without)
