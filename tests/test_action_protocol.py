"""Mock-based action protocol tests: the begin/op/end state machine verified
against stubbed log/data managers, with zero I/O.

Parity: the reference's action suites (actions/CreateActionTest.scala,
RefreshActionTest.scala, DeleteActionTest.scala, RestoreActionTest.scala,
VacuumActionTest.scala, CancelActionTest.scala) drive the same protocol with
Mockito mocks of IndexLogManager/IndexDataManager — validation failures,
acquire-state conflicts, and the exact order of log writes are asserted
without touching a filesystem.
"""

from unittest import mock

import pytest

from hyperspace_tpu.actions.action import Action
from hyperspace_tpu.actions.lifecycle import (CancelAction, DeleteAction,
                                              RestoreAction, VacuumAction)
from hyperspace_tpu.exceptions import HyperspaceException, NoChangesException
from hyperspace_tpu.index.constants import States
from hyperspace_tpu.index.log_entry import IndexLogEntry
from hyperspace_tpu.telemetry.events import CreateActionEvent

from test_log_entry import make_entry


def make_session():
    session = mock.MagicMock(name="session")
    session.hs_conf.event_logger_class.return_value = None  # no-op logger
    return session


def make_log_manager(latest_id=4, stable=None, latest=None):
    lm = mock.MagicMock(name="log_manager")
    lm.get_latest_id.return_value = latest_id
    lm.get_latest_stable_log.return_value = stable
    lm.get_latest_log.return_value = latest
    lm.write_log.return_value = True
    lm.delete_latest_stable_log.return_value = True
    lm.create_latest_stable_log.return_value = True
    return lm


class ProbeAction(Action):
    """Minimal concrete action recording when op() ran."""

    transient_state = States.CREATING
    final_state = States.ACTIVE

    def __init__(self, session, log_manager, fail_op=None, fail_validate=None):
        super().__init__(session, log_manager)
        self.op_calls = 0
        self.fail_op = fail_op
        self.fail_validate = fail_validate

    @property
    def log_entry(self):
        return make_entry("probe_idx", States.DOESNOTEXIST)

    def validate(self):
        if self.fail_validate is not None:
            raise self.fail_validate

    def op(self):
        self.op_calls += 1
        if self.fail_op is not None:
            raise self.fail_op

    def event(self, message):
        return CreateActionEvent(message=message, index_name="probe_idx")


class TestProtocolOrder:
    def test_happy_path_writes_in_order(self):
        lm = make_log_manager(latest_id=4)
        action = ProbeAction(make_session(), lm)
        action.run()

        assert action.op_calls == 1
        # Exact call order on the log manager: transient write, stable-tag
        # delete, final write, stable-tag create (Action.scala:34-108).
        calls = [c for c in lm.method_calls
                 if c[0] in ("write_log", "delete_latest_stable_log",
                             "create_latest_stable_log")]
        assert [c[0] for c in calls] == [
            "write_log", "delete_latest_stable_log", "write_log",
            "create_latest_stable_log"]
        first_write, _, final_write, stable = calls
        assert first_write.args[0] == 5 and final_write.args[0] == 6
        assert first_write.args[1].state == States.CREATING
        assert final_write.args[1].state == States.ACTIVE
        assert stable.args == (6,)

    def test_entry_reevaluated_between_begin_and_end(self):
        # log_entry is a property read twice so op() results can land in the
        # final entry; the two written entries must be distinct objects.
        lm = make_log_manager()
        action = ProbeAction(make_session(), lm)
        action.run()
        entries = [c.args[1] for c in lm.method_calls if c[0] == "write_log"]
        assert entries[0] is not entries[1]

    def test_base_id_with_empty_log(self):
        lm = make_log_manager(latest_id=None)
        action = ProbeAction(make_session(), lm)
        assert action.base_id == -1
        action.run()
        ids = [c.args[0] for c in lm.method_calls if c[0] == "write_log"]
        assert ids == [0, 1]

    def test_base_id_cached_across_reads(self):
        lm = make_log_manager(latest_id=7)
        action = ProbeAction(make_session(), lm)
        assert action.base_id == 7 and action.end_id == 9
        assert action.base_id == 7
        lm.get_latest_id.assert_called_once()


class TestProtocolFailures:
    def test_acquire_conflict_skips_op(self):
        # Another writer claimed baseId+1: no op(), no final write.
        lm = make_log_manager()
        lm.write_log.return_value = False
        action = ProbeAction(make_session(), lm)
        with pytest.raises(HyperspaceException, match="acquire proper state"):
            action.run()
        assert action.op_calls == 0
        lm.delete_latest_stable_log.assert_not_called()
        lm.create_latest_stable_log.assert_not_called()

    def test_op_failure_leaves_transient_state(self):
        lm = make_log_manager()
        action = ProbeAction(make_session(), lm, fail_op=RuntimeError("boom"))
        with pytest.raises(RuntimeError, match="boom"):
            action.run()
        # Only the transient write happened; latestStable untouched — the
        # wreck is visible for CancelAction (crash recovery).
        writes = [c for c in lm.method_calls if c[0] == "write_log"]
        assert len(writes) == 1 and writes[0].args[1].state == States.CREATING
        lm.create_latest_stable_log.assert_not_called()

    def test_validate_failure_writes_nothing(self):
        lm = make_log_manager()
        action = ProbeAction(make_session(), lm,
                             fail_validate=HyperspaceException("invalid"))
        with pytest.raises(HyperspaceException, match="invalid"):
            action.run()
        assert action.op_calls == 0
        lm.write_log.assert_not_called()

    def test_no_changes_is_quiet_noop(self):
        lm = make_log_manager()
        action = ProbeAction(make_session(), lm,
                             fail_validate=NoChangesException("nothing to do"))
        action.run()  # swallowed, not raised
        assert action.op_calls == 0
        lm.write_log.assert_not_called()

    def test_stable_tag_delete_failure_aborts_end(self):
        lm = make_log_manager()
        lm.delete_latest_stable_log.return_value = False
        action = ProbeAction(make_session(), lm)
        with pytest.raises(HyperspaceException, match="latest stable log"):
            action.run()
        # op ran, transient written, but no final write / stable re-tag.
        assert action.op_calls == 1
        writes = [c for c in lm.method_calls if c[0] == "write_log"]
        assert len(writes) == 1
        lm.create_latest_stable_log.assert_not_called()


class TestTransitionActions:
    def test_delete_requires_active(self):
        lm = make_log_manager(stable=make_entry("i", States.DELETED))
        with pytest.raises(HyperspaceException, match="only supported"):
            DeleteAction(make_session(), lm).run()
        lm.write_log.assert_not_called()

    def test_delete_writes_deleting_then_deleted(self):
        lm = make_log_manager(stable=make_entry("i", States.ACTIVE))
        DeleteAction(make_session(), lm).run()
        states = [c.args[1].state for c in lm.method_calls
                  if c[0] == "write_log"]
        assert states == [States.DELETING, States.DELETED]

    def test_restore_requires_deleted(self):
        lm = make_log_manager(stable=make_entry("i", States.ACTIVE))
        with pytest.raises(HyperspaceException, match="only supported"):
            RestoreAction(make_session(), lm).run()

    def test_restore_reactivates(self):
        lm = make_log_manager(stable=make_entry("i", States.DELETED))
        RestoreAction(make_session(), lm).run()
        states = [c.args[1].state for c in lm.method_calls
                  if c[0] == "write_log"]
        assert states == [States.RESTORING, States.ACTIVE]

    def test_transition_preserves_entry_content(self):
        stable = make_entry("keepme", States.ACTIVE)
        lm = make_log_manager(stable=stable)
        DeleteAction(make_session(), lm).run()
        final = [c.args[1] for c in lm.method_calls
                 if c[0] == "write_log"][-1]
        assert final.name == "keepme"
        assert final.derivedDataset.indexed_columns == \
            stable.derivedDataset.indexed_columns
        # A fresh copy, not mutation of the stable entry in place.
        assert stable.state == States.ACTIVE

    def test_vacuum_deletes_every_version(self):
        lm = make_log_manager(stable=make_entry("i", States.DELETED))
        dm = mock.MagicMock(name="data_manager")
        dm.get_all_version_ids.return_value = [0, 1, 2]
        VacuumAction(make_session(), lm, data_manager=dm).run()
        assert [c.args for c in dm.delete.call_args_list] == [(0,), (1,), (2,)]
        states = [c.args[1].state for c in lm.method_calls
                  if c[0] == "write_log"]
        assert states == [States.VACUUMING, States.DOESNOTEXIST]

    def test_vacuum_requires_deleted(self):
        lm = make_log_manager(stable=make_entry("i", States.ACTIVE))
        dm = mock.MagicMock(name="data_manager")
        with pytest.raises(HyperspaceException, match="only supported"):
            VacuumAction(make_session(), lm, data_manager=dm).run()
        dm.delete.assert_not_called()


class TestCancelAction:
    def test_cancel_on_stable_latest_raises(self):
        stable = make_entry("i", States.ACTIVE)
        lm = make_log_manager(stable=stable, latest=stable)
        with pytest.raises(HyperspaceException, match="not needed"):
            CancelAction(make_session(), lm).run()

    def test_cancel_rolls_back_to_stable_state(self):
        stable = make_entry("i", States.ACTIVE)
        wreck = make_entry("i", States.REFRESHING)
        lm = make_log_manager(stable=stable, latest=wreck)
        CancelAction(make_session(), lm).run()
        states = [c.args[1].state for c in lm.method_calls
                  if c[0] == "write_log"]
        assert states == [States.CANCELLING, States.ACTIVE]

    def test_cancel_first_create_rolls_to_doesnotexist(self):
        wreck = make_entry("i", States.CREATING)
        lm = make_log_manager(stable=None, latest=wreck)
        CancelAction(make_session(), lm).run()
        states = [c.args[1].state for c in lm.method_calls
                  if c[0] == "write_log"]
        assert states == [States.CANCELLING, States.DOESNOTEXIST]

    def test_cancel_without_any_log_raises(self):
        lm = make_log_manager(stable=None, latest=None)
        with pytest.raises(HyperspaceException, match="No log entry"):
            CancelAction(make_session(), lm).run()
