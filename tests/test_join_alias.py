"""Join-rule alias/base-namespace regression tests (multi-Project chains,
filters above renames, duplicate alias pairs)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col
from hyperspace_tpu.plan.nodes import IndexScan


@pytest.fixture()
def env(tmp_system_path, tmp_path):
    rng = np.random.default_rng(7)
    n = 600
    d1 = tmp_path / "t1"
    d2 = tmp_path / "t2"
    d1.mkdir(), d2.mkdir()
    pq.write_table(pa.table({
        "a": pa.array(rng.integers(0, 30, n).astype(np.int32)),
        "b": pa.array(rng.uniform(0, 1, n)),
        "c": pa.array(rng.uniform(0, 1, n)),
    }), str(d1 / "p.parquet"))
    pq.write_table(pa.table({
        "k": pa.array(np.arange(40, dtype=np.int32)),
        "v": pa.array(rng.uniform(0, 1, 40)),
    }), str(d2 / "p.parquet"))
    session = hst.Session(system_path=tmp_system_path)
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    hs = Hyperspace(session)
    df1 = session.read.parquet(str(d1))
    df2 = session.read.parquet(str(d2))
    hs.create_index(df1, IndexConfig("i1", ["a"], ["b"]))
    hs.create_index(df2, IndexConfig("i2", ["k"], ["v"]))
    session.enable_hyperspace()
    return session, df1, df2


def _key(t):
    return t.sort_by([(c, "ascending") for c in t.column_names])


def _oracle(session, q):
    with_idx = _key(q.to_arrow())
    session.disable_hyperspace()
    without = _key(q.to_arrow())
    session.enable_hyperspace()
    assert with_idx.equals(without)


def _leaves(q):
    return [l for l in q.optimized_plan().collect_leaves()
            if isinstance(l, IndexScan)]


class TestJoinAliasHandling:
    def test_stacked_projects_skip_not_crash(self, env):
        """An inner Project reading a non-covered column must skip the
        rewrite cleanly (it used to raise during plan rebuilding)."""
        session, df1, df2 = env
        q = df1.select("a", "c").select("a") \
            .join(df2, on=col("a") == col("k"))
        plan = q.optimized_plan()  # must not raise.
        assert not any(isinstance(l, IndexScan) for l in plan.collect_leaves())
        _oracle(session, q)

    def test_stacked_projects_rewrite_when_covered(self, env):
        session, df1, df2 = env
        q = df1.select("a", "b").select("a") \
            .join(df2, on=col("a") == col("k"))
        assert len(_leaves(q)) == 2
        _oracle(session, q)

    def test_filter_above_alias_is_covered(self, env):
        """Filter over the renamed column: coverage must translate x→a."""
        session, df1, df2 = env
        q = df1.select(col("a").alias("x"), col("b")) \
            .filter(col("x") > 5) \
            .join(df2, on=col("x") == col("k"))
        assert len(_leaves(q)) == 2
        _oracle(session, q)

    def test_duplicate_alias_pairs_collapse(self, env):
        """Two alias pairs of one base pair must still rewrite (dedup in
        base space)."""
        session, df1, df2 = env
        left = df1.select(col("a").alias("x"), col("a").alias("y"),
                          col("b"))
        right = df2.select(col("k").alias("u"), col("k").alias("w"),
                           col("v"))
        q = left.join(right, on=(col("x") == col("u")) & (col("y") == col("w")))
        assert len(_leaves(q)) == 2
        _oracle(session, q)

    def test_computed_join_key_disqualifies(self, env):
        session, df1, df2 = env
        q = df1.select((col("a") * 1).alias("x"), col("b")) \
            .join(df2, on=col("x") == col("k"))
        assert not _leaves(q)
        _oracle(session, q)
