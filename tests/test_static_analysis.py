"""The static-analysis framework (scripts/analysis/) end to end.

Four contracts:

1. **Parity** — the ported gates emit a byte-identical finding set to
   the retired monolith (legacy_reference.collect), on the live tree
   AND on a fixture tree seeded with a violation of every gate.
2. **Pipeline** — every source parses exactly once per run; the warm
   (cached) run completes in well under half the monolith's wall-clock.
3. **Dataflow passes** — lock discipline (HS301/302), host-sync
   accounting (HS311/312), and thread handoff (HS321) each catch seeded
   violations (positive), stay silent on the sanctioned idioms
   (negative), honor `# hst: disable=` suppressions, and flag unused
   suppressions/exemptions.
4. **Convicted fixes** — the product races the lock pass surfaced
   (chunk-stats watermarks, compile-listener double-registration,
   dispatch tallies) stay fixed, and merge_join_indices under tracing
   raises the typed error instead of a ConcretizationTypeError.

Plus the CI gate: `python scripts/lint.py --json` over the real tree
must report zero non-baselined findings (tier-1; analyzer regressions
fail pytest).
"""

from __future__ import annotations

import ast
import json
import os
import re
import subprocess
import sys
import threading
import time

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = os.path.join(ROOT, "scripts")
if SCRIPTS not in sys.path:
    sys.path.insert(0, SCRIPTS)

from analysis import diagnostics, engine  # noqa: E402
from analysis import handoff_pass, hostsync_pass, lock_pass  # noqa: E402
from analysis import serialization_pass  # noqa: E402
from analysis import legacy_reference as legacy  # noqa: E402


# ---------------------------------------------------------------------------
# Fixture-tree scaffolding.
# ---------------------------------------------------------------------------

_MINIMAL = {
    "docs/configuration.md": "hyperspace.tpu.documented.key\n",
    "hyperspace_tpu/telemetry/span_names.py":
        'QUERY = "query"\n',
    "hyperspace_tpu/robustness/fault_names.py":
        'IO_POOLED_READ = "io.pooled_read"\n',
    "hyperspace_tpu/execution/fusion_boundaries.py":
        'SORT = "sort"\n',
    "hyperspace_tpu/telemetry/metric_names.py":
        'SERVING_LATENCY_MS = "serving.latency_ms"\n',
    "tests/test_cover.py":
        '_ = ["query", "io.pooled_read", "sort", "serving.latency_ms"]\n',
    "bench.py": "",
    "__graft_entry__.py": "",
}


def scaffold(tmp_path, files=None) -> str:
    """A minimal lintable tree; ``files`` overlay/extend the base."""
    merged = dict(_MINIMAL)
    merged.update(files or {})
    for rel, text in merged.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text)
    (tmp_path / "scripts").mkdir(exist_ok=True)
    return str(tmp_path)


def run_codes(root, **kw):
    res = engine.run(root, use_cache=False, **kw)
    return res, [d.code for d in res.problems
                 if not d.suppressed and not d.baselined]


# ---------------------------------------------------------------------------
# 1. Parity with the monolith.
# ---------------------------------------------------------------------------

# One violation per ported gate (plus clean control lines).
_SEEDED = {
    "hyperspace_tpu/style_victim.py": (
        "import os\n"
        "import json\n"                      # unused import
        "x = 1\t\n"                          # tab + trailing whitespace
        "y = '" + "a" * 120 + "'\n"          # long line
        "z = os.environ.get('HST_X')\n"      # env read
        "k = 'hyperspace.tpu.mystery.key'\n"  # undocumented config key
    ),
    "hyperspace_tpu/jit_victim.py": (
        "import jax\n"
        "f = jax.jit(lambda v: v)\n"          # jit outside allowlist
        "g = jax.pmap\n"                      # banned name
    ),
    "hyperspace_tpu/parallel/mesh.py": (
        "import jax\n"
        "h = jax.jit(lambda v: v)\n"          # no sharding marker
    ),
    "hyperspace_tpu/state_victim.py": (
        "_CACHE = {}\n"
        "def put(k, v):\n"
        "    _CACHE[k] = v\n"                 # mutated module state
    ),
    "hyperspace_tpu/span_victim.py": (
        "def f(trace):\n"
        "    with trace.span('freeform'):\n"  # unregistered span
        "        pass\n"
        "def g(faults):\n"
        "    fault_point = faults.fault_point\n"
        "    fault_point('free.fault')\n"     # unregistered fault
        "def h():\n"
        "    note_boundary('free.kind')\n"    # unregistered boundary
        "def m(reg):\n"
        "    reg.counter_add('free.metric')\n"  # unregistered metric
    ),
    "hyperspace_tpu/except_victim.py": (
        "def f():\n"
        "    try:\n"
        "        return 1\n"
        "    except:\n"                       # bare except
        "        pass\n"
    ),
    "hyperspace_tpu/thread_victim.py": (
        "from concurrent.futures import ThreadPoolExecutor\n"
        "def f(work):\n"
        "    with ThreadPoolExecutor(2) as ex:\n"
        "        return ex.map(work, [1])\n"
    ),
    "hyperspace_tpu/socket_victim.py": (
        "import socket\n"
        "def f():\n"
        "    return socket.create_connection(('h', 1))\n"
    ),
    "hyperspace_tpu/broken_victim.py": "def f(:\n",  # syntax error
    "hyperspace_tpu/telemetry/events.py": (
        "class OrphanEvent:\n"
        "    pass\n"                          # never referenced in tests
    ),
    # registry values never referenced under tests/ (coverage gates)
    "hyperspace_tpu/telemetry/span_names.py":
        'QUERY = "query"\nORPHAN_SPAN = "orphan.span"\n',
    "hyperspace_tpu/robustness/fault_names.py":
        'IO_POOLED_READ = "io.pooled_read"\n'
        'ORPHAN_FAULT = "orphan.fault"\n',
    "hyperspace_tpu/execution/fusion_boundaries.py":
        'SORT = "sort"\nORPHAN_KIND = "orphan.kind"\n',
    "hyperspace_tpu/telemetry/metric_names.py":
        'SERVING_LATENCY_MS = "serving.latency_ms"\n'
        'ORPHAN_METRIC = "orphan.metric"\n',
}


class TestParity:
    def _both(self, root):
        problems, files = legacy.collect(root)
        res = engine.run(root, ported_only=True, use_cache=False)
        mine = [d.text() for d in res.problems
                if not d.suppressed and not d.baselined]
        return problems, files, mine, res.file_count

    def test_live_tree_byte_identical(self):
        problems, files, mine, my_files = self._both(ROOT)
        assert mine == problems
        assert my_files == files

    def test_seeded_fixture_byte_identical(self, tmp_path):
        root = scaffold(tmp_path, _SEEDED)
        problems, files, mine, my_files = self._both(root)
        assert problems, "fixture must actually trip the gates"
        assert mine == problems
        assert my_files == files
        # Every ported gate fired at least once on the fixture.
        text = "\n".join(problems)
        for token in ("tab character", "trailing whitespace",
                      "line longer than", "unused import",
                      "ad-hoc env read", "is not documented",
                      "jax.jit outside", "forbidden repo-wide",
                      "distributed module", "module-level mutable state",
                      "span name must", "fault-point name must",
                      "boundary kind must", "metric name must",
                      "bare 'except:'",
                      "thread/pool construction",
                      "socket creation outside", "syntax error",
                      "never referenced under tests/"):
            assert token in text, f"gate output missing: {token}"


class TestPipeline:
    def test_parses_each_file_exactly_once(self):
        res = engine.run(ROOT, use_cache=False)
        assert res.parse_count == res.file_count

    def test_warm_run_well_under_half_the_monolith(self, tmp_path):
        t0 = time.perf_counter()
        legacy.collect(ROOT)
        legacy_s = time.perf_counter() - t0
        # Prime, then time the warm cached run (the steady state a
        # developer/CI loop pays). The monolith re-walked every tree
        # ~12x per run and had no cache at all.
        engine.run(ROOT, use_cache=True)
        t0 = time.perf_counter()
        res = engine.run(ROOT, use_cache=True)
        warm_s = time.perf_counter() - t0
        assert res.parse_count == 0, "warm run must not re-parse"
        assert warm_s < 0.5 * legacy_s, \
            f"warm {warm_s:.3f}s vs monolith {legacy_s:.3f}s"

    def test_cache_tracks_edits(self, tmp_path):
        root = scaffold(tmp_path)
        victim = tmp_path / "hyperspace_tpu" / "v.py"
        victim.write_text("x = 1\t\n")
        r1 = engine.run(root, use_cache=True)
        assert any(d.code == "HS101" for d in r1.problems)
        r2 = engine.run(root, use_cache=True)
        assert [d.text() for d in r2.problems] == \
            [d.text() for d in r1.problems]
        assert r2.parse_count == 0
        victim.write_text("x = 1\n")
        r3 = engine.run(root, use_cache=True)
        assert not any(d.code == "HS101" for d in r3.problems)
        assert r3.parse_count == 1  # only the edited file re-parsed


# ---------------------------------------------------------------------------
# 2. Framework: codes, docs, suppressions, baseline, json, CLI.
# ---------------------------------------------------------------------------

class TestFramework:
    def test_code_registry_frozen(self):
        codes = set(diagnostics.CODES)
        assert all(re.fullmatch(r"HS\d{3}", c) for c in codes)
        assert codes == {
            "HS001", "HS002", "HS003", "HS004", "HS005",
            "HS101", "HS102", "HS103", "HS104",
            "HS201", "HS202", "HS203", "HS204", "HS205", "HS206",
            "HS207", "HS208", "HS209", "HS210", "HS211", "HS212",
            "HS213", "HS214", "HS215", "HS216", "HS217",
            "HS301", "HS302", "HS311", "HS312", "HS321", "HS331",
            "HS341", "HS342",
        }

    def test_doc_table_in_lockstep(self):
        with open(os.path.join(ROOT, "docs", "static_analysis.md")) as f:
            doc = f.read()
        documented = set(re.findall(r"\bHS\d{3}\b", doc))
        assert documented == set(diagnostics.CODES)

    def test_exemption_justifications_printed(self):
        out = subprocess.run(
            [sys.executable, os.path.join(SCRIPTS, "lint.py"),
             "--exemptions"],
            capture_output=True, text=True, cwd=ROOT)
        assert out.returncode == 0
        assert "justification" in out.stdout
        assert "one-scalar" in out.stdout or "scalar" in out.stdout
        assert "self-check harness" in out.stdout

    def test_suppression_and_unused_directive(self, tmp_path):
        root = scaffold(tmp_path, {
            "hyperspace_tpu/v.py": (
                "y = '" + "a" * 110 + "'  # hst: disable=HS103\n"
                "z = 2  # hst: disable=HS104\n"),
        })
        res, codes = run_codes(root)
        assert "HS103" not in codes          # suppressed
        assert codes.count("HS002") == 1     # unused directive flagged
        sup = [d for d in res.problems if d.suppressed]
        assert [d.code for d in sup] == ["HS103"]

    def test_baseline_grandfathers_and_goes_stale(self, tmp_path):
        root = scaffold(tmp_path, {"hyperspace_tpu/v.py": "x = 1\t\n"})
        engine.write_baseline(root)
        res = engine.run(root, use_cache=False)
        tabs = [d for d in res.problems if d.code == "HS101"]
        assert tabs and all(d.baselined for d in tabs)
        assert not [d for d in res.active() if d.code == "HS101"]
        (tmp_path / "hyperspace_tpu" / "v.py").write_text("x = 1\n")
        res2 = engine.run(root, use_cache=False)
        assert any(d.code == "HS005" for d in res2.problems)

    def test_cli_json_on_fixture(self, tmp_path):
        root = scaffold(tmp_path, {"hyperspace_tpu/v.py": "x = 1\t\n"})
        out = subprocess.run(
            [sys.executable, os.path.join(SCRIPTS, "lint.py"),
             "--json", "--no-cache", "--root", root],
            capture_output=True, text=True, cwd=ROOT)
        assert out.returncode == 1
        payload = json.loads(out.stdout)
        assert payload["count"] >= 1
        tab = [p for p in payload["problems"] if p["code"] == "HS101"][0]
        assert tab["path"].endswith("v.py") and tab["line"] == 1
        assert tab["title"] == "tab character"

    def test_legacy_helper_reexports_for_old_tests(self):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "hst_lint_shim", os.path.join(SCRIPTS, "lint.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.mutable_state_sites(ast.parse(
            "_C = {}\ndef f(k):\n    _C[k] = 1\n"))
        assert mod.except_swallow_sites(ast.parse(
            "try:\n    x = 1\nexcept:\n    pass\n"))


# ---------------------------------------------------------------------------
# 3a. Lock-discipline pass.
# ---------------------------------------------------------------------------

_BANK_BAD = """\
import threading
from collections import OrderedDict


class ProgramBank:
    def __init__(self):
        self._lock = threading.Lock()
        self._stages = OrderedDict()
        self.hits = 0

    def lookup(self, key):
        self.hits += 1
        self._stages[key] = 1
        return self._stages.get(key)
"""

_BANK_OK = """\
import threading
from collections import OrderedDict


class ProgramBank:
    def __init__(self):
        self._lock = threading.Lock()
        self._stages = OrderedDict()
        self.hits = 0

    def lookup(self, key):
        with self._lock:
            self.hits += 1
            self._stages[key] = 1
            return self._stages.get(key)
"""

_SHARDING_BAD = """\
import threading

COMPILE_COUNT = 0
DISPATCH_COUNT = 0
_COUNT_LOCK = threading.Lock()


def dispatch():
    global DISPATCH_COUNT
    DISPATCH_COUNT += 1
"""


class TestLockPass:
    def test_unguarded_class_mutation_flagged(self, tmp_path):
        root = scaffold(tmp_path, {
            "hyperspace_tpu/serving/program_bank.py": _BANK_BAD})
        _res, codes = run_codes(root)
        assert "HS302" in codes  # self.hits += 1
        assert "HS301" in codes  # self._stages[key] = 1

    def test_guarded_class_clean(self, tmp_path):
        root = scaffold(tmp_path, {
            "hyperspace_tpu/serving/program_bank.py": _BANK_OK})
        _res, codes = run_codes(root)
        assert "HS301" not in codes and "HS302" not in codes

    def test_init_exempt_and_unregistered_class_ignored(self, tmp_path):
        root = scaffold(tmp_path, {
            "hyperspace_tpu/serving/program_bank.py": (
                "class SomethingElse:\n"
                "    def bump(self):\n"
                "        self.n = 1\n")})
        _res, codes = run_codes(root)
        assert "HS301" not in codes and "HS302" not in codes

    def test_delegate_method_is_exempt_and_counted_used(self, tmp_path):
        root = scaffold(tmp_path, {
            "hyperspace_tpu/serving/result_cache.py": (
                "import threading\n\n\n"
                "class ResultCache:\n"
                "    def __init__(self):\n"
                "        self._lock = threading.Lock()\n"
                "        self._device = {}\n"
                "    def _drop(self, key):\n"
                "        self._device.pop(key, None)\n")})
        _res, codes = run_codes(root)
        assert "HS301" not in codes
        # The used delegate exemption must not be reported as unused.
        stale = [d for d in _res.problems if d.code == "HS004"
                 and "ResultCache._drop" in d.message]
        assert not stale

    def test_unguarded_global_rmw_flagged_then_fixed(self, tmp_path):
        root = scaffold(tmp_path, {
            "hyperspace_tpu/parallel/sharding.py": _SHARDING_BAD})
        _res, codes = run_codes(root)
        assert "HS302" in codes
        fixed = _SHARDING_BAD.replace(
            "    global DISPATCH_COUNT\n    DISPATCH_COUNT += 1\n",
            "    global DISPATCH_COUNT\n    with _COUNT_LOCK:\n"
            "        DISPATCH_COUNT += 1\n")
        root2 = scaffold(tmp_path / "b", {
            "hyperspace_tpu/parallel/sharding.py": fixed})
        _res2, codes2 = run_codes(root2)
        assert "HS302" not in codes2 and "HS301" not in codes2

    def test_suppression_applies(self, tmp_path):
        bad = _SHARDING_BAD.replace(
            "    DISPATCH_COUNT += 1",
            "    DISPATCH_COUNT += 1  # hst: disable=HS302")
        root = scaffold(tmp_path, {
            "hyperspace_tpu/parallel/sharding.py": bad})
        _res, codes = run_codes(root)
        assert "HS302" not in codes

    def test_deferred_callable_under_lock_is_not_guarded(self, tmp_path):
        """A nested def/lambda defined INSIDE `with self._lock` runs
        later, unlocked — its mutations must still be flagged."""
        root = scaffold(tmp_path, {
            "hyperspace_tpu/serving/program_bank.py": (
                "import threading\n\n\n"
                "class ProgramBank:\n"
                "    def __init__(self):\n"
                "        self._lock = threading.Lock()\n"
                "        self.hits = 0\n"
                "    def lookup(self, pool):\n"
                "        with self._lock:\n"
                "            def cb():\n"
                "                self.hits += 1\n"
                "            pool(cb)\n")})
        _res, codes = run_codes(root)
        assert "HS302" in codes

    def test_nested_def_with_its_own_lock_is_clean(self, tmp_path):
        root = scaffold(tmp_path, {
            "hyperspace_tpu/serving/program_bank.py": (
                "import threading\n\n\n"
                "class ProgramBank:\n"
                "    def __init__(self):\n"
                "        self._lock = threading.Lock()\n"
                "        self.hits = 0\n"
                "    def lookup(self, pool):\n"
                "        def cb():\n"
                "            with self._lock:\n"
                "                self.hits += 1\n"
                "        pool(cb)\n")})
        _res, codes = run_codes(root)
        assert "HS301" not in codes and "HS302" not in codes

    def test_live_registry_matches_real_tree(self):
        """Stripping one real lock reintroduces the race AND the pass
        catches it — the regression guard for the r16 counter fixes."""
        with open(os.path.join(
                ROOT, "hyperspace_tpu", "parallel", "sharding.py")) as f:
            real = f.read()
        broken = real.replace(
            "        with _COUNT_LOCK:\n            DISPATCH_COUNT += 1",
            "        DISPATCH_COUNT += 1")
        assert broken != real
        src = _FakeSource("hyperspace_tpu/parallel/sharding.py", broken)
        diags = lock_pass.check_file(src, _FakeCtx())
        assert any(d.code == "HS302" and "DISPATCH_COUNT" in d.message
                   for d in diags)
        clean = lock_pass.check_file(
            _FakeSource("hyperspace_tpu/parallel/sharding.py", real),
            _FakeCtx())
        assert not clean


class _FakeSource:
    """SourceFile stand-in for direct pass-level checks."""

    def __init__(self, slash_rel, text):
        self.rel = slash_rel
        self.slash_rel = slash_rel
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text)
        self.is_package = slash_rel.startswith("hyperspace_tpu/")
        self._index = None

    @property
    def index(self):
        if self._index is None:
            self._index = engine.NodeIndex(self.tree)
        return self._index


class _FakeCtx:
    def __init__(self):
        self.used = set()

    def note_exemption(self, eid):
        self.used.add(eid)


# ---------------------------------------------------------------------------
# 3b. Host-sync pass.
# ---------------------------------------------------------------------------

class TestHostSyncPass:
    def _codes(self, tmp_path, kernels_text, sub="a"):
        root = scaffold(tmp_path / sub, {
            "hyperspace_tpu/ops/kernels.py": kernels_text})
        return run_codes(root)

    def test_item_inside_jitted_body_flagged(self, tmp_path):
        _res, codes = self._codes(tmp_path, (
            "import jax\n"
            "import jax.numpy as jnp\n\n\n"
            "@jax.jit\n"
            "def bad(x):\n"
            "    return x.sum().item()\n"))
        assert "HS311" in codes

    def test_tracer_branch_sync_flagged(self, tmp_path):
        _res, codes = self._codes(tmp_path, (
            "import jax.numpy as jnp\n"
            "from ..execution import shapes\n\n\n"
            "def join(keys):\n"
            "    if shapes._is_tracer(keys):\n"
            "        return int(jnp.sum(keys))\n"
            "    return 0\n"))
        assert "HS311" in codes

    def test_static_args_and_shapes_are_not_syncs(self, tmp_path):
        _res, codes = self._codes(tmp_path, (
            "from functools import partial\n\n"
            "import jax\n"
            "import jax.numpy as jnp\n\n\n"
            "@partial(jax.jit, static_argnames=('n',))\n"
            "def ok(x, n):\n"
            "    m = int(n) + int(x.shape[0])\n"
            "    return jnp.zeros(m)\n"))
        assert "HS311" not in codes and "HS312" not in codes

    def test_unallowlisted_host_sync_flagged(self, tmp_path):
        _res, codes = self._codes(tmp_path, (
            "import jax.numpy as jnp\n\n\n"
            "def rogue(mask):\n"
            "    return int(jnp.sum(mask))\n"))
        assert "HS312" in codes

    def test_allowlisted_site_within_budget_clean(self, tmp_path):
        _res, codes = self._codes(tmp_path, (
            "import jax.numpy as jnp\n\n\n"
            "def mask_count_nonzero(mask, valid_rows, padded):\n"
            "    m = int(jnp.sum(mask))\n"
            "    return m\n"))
        assert "HS312" not in codes

    def test_allowlisted_site_over_budget_flagged(self, tmp_path):
        _res, codes = self._codes(tmp_path, (
            "import jax.numpy as jnp\n\n\n"
            "def mask_count_nonzero(mask, valid_rows, padded):\n"
            "    a = int(jnp.sum(mask))\n"
            "    b = int(jnp.max(mask))\n"
            "    c = int(jnp.min(mask))\n"
            "    return a + b + c\n"))
        assert "HS312" in codes

    def test_suppression_applies(self, tmp_path):
        _res, codes = self._codes(tmp_path, (
            "import jax.numpy as jnp\n\n\n"
            "def rogue(mask):\n"
            "    return int(jnp.sum(mask))  # hst: disable=HS312\n"))
        assert "HS312" not in codes

    def test_device_get_flagged_everywhere_in_scope(self, tmp_path):
        _res, codes = self._codes(tmp_path, (
            "import jax\n\n\n"
            "def fetch(x):\n"
            "    return jax.device_get(x)\n"))
        assert "HS312" in codes

    def test_unused_allowlist_entry_is_hs004(self, tmp_path):
        # A scaffold tree has no kernels.py sync sites at all, so every
        # kernels.py hostsync exemption goes unused.
        root = scaffold(tmp_path / "u")
        _res, codes = run_codes(root)
        assert "HS004" in codes
        msgs = [d.message for d in _res.problems if d.code == "HS004"]
        assert any("mask_count_nonzero" in m for m in msgs)

    def test_stale_extra_traced_root_is_flagged(self, tmp_path,
                                                monkeypatch):
        """A registered traced root that no longer resolves must not
        silently drop HS311 coverage — it surfaces as HS004."""
        monkeypatch.setattr(
            hostsync_pass, "EXTRA_TRACED_ROOTS",
            {"hyperspace_tpu/ops/kernels.py": frozenset({"vanished"})})
        src = _FakeSource("hyperspace_tpu/ops/kernels.py", "x = 1\n")
        diags = hostsync_pass.check_file(src, _FakeCtx())
        assert [d.code for d in diags] == ["HS004"]
        assert "vanished" in diags[0].message

    def test_real_tree_one_scalar_contract_holds(self):
        """The live kernels.py/fusion.py sync sites exactly match the
        frozen budgets (and adding one more sync would fail: proven by
        the over-budget fixture above)."""
        for rel in ("hyperspace_tpu/ops/kernels.py",
                    "hyperspace_tpu/execution/fusion.py"):
            with open(os.path.join(ROOT, *rel.split("/"))) as f:
                src = _FakeSource(rel, f.read())
            diags = hostsync_pass.check_file(src, _FakeCtx())
            assert diags == [], [d.text() for d in diags]


# ---------------------------------------------------------------------------
# 3c. Thread-handoff pass.
# ---------------------------------------------------------------------------

_HANDOFF_BAD = """\
import threading


def active_context():
    return None


def worker():
    ctx = active_context()
    return ctx


def launch():
    t = threading.Thread(target=worker)
    t.start()
"""

_HANDOFF_WRAPPED = """\
import contextvars
import threading


def active_context():
    return None


def worker():
    ctx = active_context()
    return ctx


def launch():
    snap = contextvars.copy_context()
    t = threading.Thread(target=snap.run, args=(worker,))
    t.start()
"""

_HANDOFF_TRANSITIVE = """\
import contextvars
import threading

_CV = contextvars.ContextVar("x", default=None)


def helper():
    return _CV.get()


def worker():
    return helper()


def launch(pool):
    pool.submit(worker)
"""

_HANDOFF_EXPLICIT = """\
import threading


def fault_point(name, reg=None):
    return reg


def launch(reg):
    def worker():
        return fault_point("io.pooled_read", reg=reg)
    t = threading.Thread(target=worker)
    t.start()
"""


class TestHandoffPass:
    def _codes(self, tmp_path, text, sub="a"):
        root = scaffold(tmp_path / sub, {
            "hyperspace_tpu/parallel/io.py": text})
        return run_codes(root)

    def test_raw_thread_handoff_flagged(self, tmp_path):
        res, codes = self._codes(tmp_path, _HANDOFF_BAD)
        assert "HS321" in codes
        d = [d for d in res.problems if d.code == "HS321"][0]
        assert "active_context()" in d.message
        assert d.related is not None  # points at the ambient read

    def test_copy_context_wrap_is_clean(self, tmp_path):
        _res, codes = self._codes(tmp_path, _HANDOFF_WRAPPED)
        assert "HS321" not in codes

    def test_transitive_contextvar_get_flagged(self, tmp_path):
        _res, codes = self._codes(tmp_path, _HANDOFF_TRANSITIVE)
        assert "HS321" in codes

    def test_explicit_state_argument_is_clean(self, tmp_path):
        _res, codes = self._codes(tmp_path, _HANDOFF_EXPLICIT)
        assert "HS321" not in codes

    def test_suppression_applies(self, tmp_path):
        bad = _HANDOFF_BAD.replace(
            "    t = threading.Thread(target=worker)",
            "    t = threading.Thread(target=worker)"
            "  # hst: disable=HS321")
        _res, codes = self._codes(tmp_path, bad)
        assert "HS321" not in codes

    def test_live_io_and_frontend_are_clean(self):
        for rel in ("hyperspace_tpu/parallel/io.py",
                    "hyperspace_tpu/serving/frontend.py"):
            with open(os.path.join(ROOT, *rel.split("/"))) as f:
                src = _FakeSource(rel, f.read())
            diags = handoff_pass.check_file(src, _FakeCtx())
            assert diags == [], [d.text() for d in diags]


# ---------------------------------------------------------------------------
# 3d. Serialization-boundary pass.
# ---------------------------------------------------------------------------

_SER_IMPORT = """\
from jax.experimental import serialize_executable as _se


def export(compiled):
    return _se.serialize(compiled)
"""

_SER_EXPORT_IMPORT = """\
from jax import export


def f(fn):
    return export.export(fn)
"""

_SER_PICKLE = """\
import pickle


def stash(compiled):
    return pickle.dumps(compiled)
"""

_SER_CLEAN = """\
import pickle


def stash(rows):
    return pickle.dumps(rows)
"""


class TestSerializationPass:
    def _codes(self, tmp_path, text, sub="a",
               rel="hyperspace_tpu/serving/victim.py"):
        root = scaffold(tmp_path / sub, {rel: text})
        return run_codes(root)

    def test_serialize_executable_import_flagged(self, tmp_path):
        res, codes = self._codes(tmp_path, _SER_IMPORT)
        assert "HS331" in codes
        d = [d for d in res.problems if d.code == "HS331"][0]
        assert "artifacts/store.py" in d.message

    def test_jax_export_import_flagged(self, tmp_path):
        _res, codes = self._codes(tmp_path, _SER_EXPORT_IMPORT)
        assert "HS331" in codes

    def test_pickle_of_compiled_flagged(self, tmp_path):
        _res, codes = self._codes(tmp_path, _SER_PICKLE)
        assert "HS331" in codes

    def test_pickle_of_plain_data_is_clean(self, tmp_path):
        _res, codes = self._codes(tmp_path, _SER_CLEAN)
        assert "HS331" not in codes

    def test_store_module_is_exempt(self, tmp_path):
        _res, codes = self._codes(
            tmp_path, _SER_IMPORT,
            rel="hyperspace_tpu/artifacts/store.py")
        assert "HS331" not in codes

    def test_suppression_applies(self, tmp_path):
        bad = _SER_IMPORT.replace(
            "from jax.experimental import serialize_executable as _se",
            "from jax.experimental import serialize_executable as _se"
            "  # hst: disable=HS331")
        _res, codes = self._codes(tmp_path, bad)
        assert "HS331" not in codes

    def test_live_store_and_manager_are_clean(self):
        # store.py consumes the allowlist entry; manager.py (opaque
        # handles only) and the result cache (pickles row payloads,
        # not executables) must not trip the gate.
        for rel in ("hyperspace_tpu/artifacts/store.py",
                    "hyperspace_tpu/artifacts/manager.py",
                    "hyperspace_tpu/serving/result_cache.py"):
            with open(os.path.join(ROOT, *rel.split("/"))) as f:
                src = _FakeSource(rel, f.read())
            diags = serialization_pass.check_file(src, _FakeCtx())
            assert diags == [], [d.text() for d in diags]


# ---------------------------------------------------------------------------
# 4. Convicted product fixes stay fixed.
# ---------------------------------------------------------------------------

class TestConvictedFixes:
    def test_chunk_scan_stats_exact_under_threads(self):
        from hyperspace_tpu.execution import executor
        before = executor.CHUNK_SCAN_STATS["chunks"]

        def bump():
            for _ in range(500):
                executor._note_chunk_scan(1)

        threads = [threading.Thread(target=bump) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert executor.CHUNK_SCAN_STATS["chunks"] == before + 4000

    def test_index_build_stats_exact_under_threads(self):
        from hyperspace_tpu.ops import index_build
        before = index_build.CHUNK_STATS["spill_bytes"]

        def bump():
            for _ in range(500):
                index_build._bump_chunk_stat("spill_bytes", 2)
                index_build._note_device_rows(7)

        threads = [threading.Thread(target=bump) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert index_build.CHUNK_STATS["spill_bytes"] == before + 8000
        assert index_build.CHUNK_STATS["max_device_rows"] >= 7

    def test_compile_listener_registers_exactly_once(self, monkeypatch):
        from hyperspace_tpu.execution import shapes
        calls = []
        monkeypatch.setattr(
            shapes.jax.monitoring,
            "register_event_duration_secs_listener",
            lambda fn: calls.append(fn))
        monkeypatch.setattr(shapes, "_listener_installed", False)
        barrier = threading.Barrier(8)

        def install():
            barrier.wait()
            shapes.install_compile_counter()

        threads = [threading.Thread(target=install) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(calls) == 1

    def test_merge_join_under_tracing_raises_typed(self):
        import jax
        import jax.numpy as jnp

        from hyperspace_tpu.exceptions import HyperspaceException
        from hyperspace_tpu.ops import kernels

        def traced(lk, rk):
            return kernels.merge_join_indices(lk, rk)

        with pytest.raises(HyperspaceException, match="under tracing"):
            jax.jit(traced)(jnp.arange(4), jnp.arange(4))

    def test_spmd_counters_move_under_lock_on_live_tree(self):
        """Static regression: stripping any counter lock in the spmd /
        fusion / distributed_build modules trips HS302 (see
        TestLockPass.test_live_registry_matches_real_tree for the
        sharding variant)."""
        for rel in ("hyperspace_tpu/execution/spmd.py",
                    "hyperspace_tpu/execution/fusion.py",
                    "hyperspace_tpu/parallel/distributed_build.py",
                    "hyperspace_tpu/execution/executor.py",
                    "hyperspace_tpu/ops/index_build.py",
                    "hyperspace_tpu/execution/shapes.py"):
            with open(os.path.join(ROOT, *rel.split("/"))) as f:
                src = _FakeSource(rel, f.read())
            diags = lock_pass.check_file(src, _FakeCtx())
            assert diags == [], [d.text() for d in diags]


# ---------------------------------------------------------------------------
# 5. CI gate: the real tree is clean through the real entrypoint.
# ---------------------------------------------------------------------------

class TestLintCI:
    def test_repo_reports_zero_nonbaselined_findings(self):
        out = subprocess.run(
            [sys.executable, os.path.join(SCRIPTS, "lint.py"),
             "--json", "--no-cache"],
            capture_output=True, text=True, cwd=ROOT)
        assert out.returncode == 0, out.stdout + out.stderr
        payload = json.loads(out.stdout)
        assert payload["count"] == 0
        bad = [p for p in payload["problems"]
               if not p["suppressed"] and not p["baselined"]]
        assert bad == []
