"""Partitioned (hive key=value) data + json/orc source formats
(VERDICT r2 #6/#9; parity: sources/interfaces.scala:43-247
partitionSchema/partitionBasePath, DefaultFileBasedSource.scala:37-44
format list, HybridScanForPartitionedDataTest).
"""

import datetime
import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col, count, sum_
from hyperspace_tpu.plan.nodes import IndexScan, Scan


@pytest.fixture()
def session(tmp_system_path):
    s = hst.Session(system_path=tmp_system_path)
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    return s


def write_partitioned(tmp_path, name="part_data"):
    """root/region=.../year=.../partN.parquet with 3 regions x 2 years."""
    rng = np.random.default_rng(61)
    root = tmp_path / name
    frames = []
    for region in ("asia", "emea", "na"):
        for year in (2020, 2021):
            n = 400
            df = pd.DataFrame({
                "id": rng.integers(0, 10_000, n).astype(np.int64),
                "amount": np.round(rng.uniform(0, 500, n), 2),
            })
            d = root / f"region={region}" / f"year={year}"
            d.mkdir(parents=True)
            pq.write_table(pa.Table.from_pandas(df), d / "part0.parquet")
            df = df.assign(region=region, year=year)
            frames.append(df)
    return str(root), pd.concat(frames, ignore_index=True)


class TestPartitionDiscovery:
    def test_schema_includes_partition_columns(self, session, tmp_path):
        root, full = write_partitioned(tmp_path)
        df = session.read.parquet(root)
        names = df.plan.schema.names
        assert "region" in names and "year" in names
        assert df.plan.schema.field("year").dtype == "int64"
        assert df.plan.schema.field("region").dtype == "string"

    def test_scan_materializes_partition_columns(self, session, tmp_path):
        root, full = write_partitioned(tmp_path)
        got = session.read.parquet(root) \
            .select("id", "amount", "region", "year").to_pandas()
        key = ["id", "amount", "region", "year"]
        pd.testing.assert_frame_equal(
            got.sort_values(key).reset_index(drop=True),
            full[key].sort_values(key).reset_index(drop=True),
            check_dtype=False)

    def test_group_by_partition_column(self, session, tmp_path):
        root, full = write_partitioned(tmp_path)
        got = session.read.parquet(root).group_by("region", "year") \
            .agg(sum_(col("amount")).alias("sa"), count(None).alias("n")) \
            .to_pandas()
        exp = full.groupby(["region", "year"]).agg(
            sa=("amount", "sum"), n=("amount", "size")).reset_index()
        key = ["region", "year"]
        g = got.sort_values(key).reset_index(drop=True)
        e = exp.sort_values(key).reset_index(drop=True)
        assert g["n"].tolist() == e["n"].tolist()
        assert np.allclose(g["sa"], e["sa"])


class TestPartitionPruning:
    def test_equality_prunes_files(self, session, tmp_path):
        root, full = write_partitioned(tmp_path)
        q = session.read.parquet(root) \
            .filter((col("region") == "emea") & (col("year") == 2021)) \
            .select("id", "amount")
        plan = q.optimized_plan()
        scans = [l for l in plan.collect_leaves() if isinstance(l, Scan)]
        assert scans and len(scans[0].relation.all_files()) == 1, \
            "partition pruning did not narrow the file list"
        got = q.to_pandas()
        exp = full[(full.region == "emea") & (full.year == 2021)][
            ["id", "amount"]]
        pd.testing.assert_frame_equal(
            got.sort_values(["id", "amount"]).reset_index(drop=True),
            exp.sort_values(["id", "amount"]).reset_index(drop=True),
            check_dtype=False)

    def test_range_and_in_prune(self, session, tmp_path):
        root, full = write_partitioned(tmp_path)
        q = session.read.parquet(root) \
            .filter(col("region").isin(["asia", "na"])
                    & (col("year") > 2020)) \
            .select("id", "region", "year")
        plan = q.optimized_plan()
        scans = [l for l in plan.collect_leaves() if isinstance(l, Scan)]
        assert scans and len(scans[0].relation.all_files()) == 2
        got = q.to_pandas()
        exp = full[full.region.isin(["asia", "na"]) & (full.year > 2020)][
            ["id", "region", "year"]]
        assert len(got) == len(exp)

    def test_pruning_works_when_disabled(self, session, tmp_path):
        """Partition pruning is engine-level (always on), not hyperspace."""
        root, _ = write_partitioned(tmp_path)
        session.disable_hyperspace()
        q = session.read.parquet(root).filter(col("year") == 2020) \
            .select("id")
        scans = [l for l in q.optimized_plan().collect_leaves()
                 if isinstance(l, Scan)]
        assert scans and len(scans[0].relation.all_files()) == 3


class TestPartitionedIndexing:
    def test_index_over_partition_column(self, session, tmp_path):
        """A covering index whose included column IS a partition column:
        build reads path-derived values, query round-trips them."""
        root, full = write_partitioned(tmp_path)
        hs = Hyperspace(session)
        df = session.read.parquet(root)
        hs.create_index(df, IndexConfig("pidx", ["id"],
                                        ["amount", "region"]))
        session.enable_hyperspace()
        q = df.filter(col("id") < 2000).select("id", "amount", "region")
        assert any(isinstance(l, IndexScan)
                   for l in q.optimized_plan().collect_leaves())
        got = q.to_pandas()
        session.disable_hyperspace()
        exp = q.to_pandas()
        key = ["id", "amount", "region"]
        pd.testing.assert_frame_equal(
            got.sort_values(key).reset_index(drop=True),
            exp.sort_values(key).reset_index(drop=True), check_dtype=False)

    def test_hybrid_scan_partitioned_append(self, session, tmp_path):
        """New partition directory appended after indexing: hybrid scan
        merges it and results match the source scan (parity:
        HybridScanForPartitionedDataTest)."""
        root, full = write_partitioned(tmp_path)
        session.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, "true")
        hs = Hyperspace(session)
        df = session.read.parquet(root)
        hs.create_index(df, IndexConfig("hidx", ["id"], ["amount"]))
        # Append a whole new partition dir.
        rng = np.random.default_rng(62)
        d = tmp_path / "part_data" / "region=latam" / "year=2021"
        d.mkdir(parents=True)
        extra = pd.DataFrame({
            "id": rng.integers(0, 10_000, 150).astype(np.int64),
            "amount": np.round(rng.uniform(0, 500, 150), 2),
        })
        pq.write_table(pa.Table.from_pandas(extra), d / "part0.parquet")

        session.enable_hyperspace()
        # Fresh reader: file listings are cached per relation instance.
        q = session.read.parquet(root) \
            .filter(col("id") < 3000).select("id", "amount")
        leaves = q.optimized_plan().collect_leaves()
        idx = [l for l in leaves if isinstance(l, IndexScan)]
        assert idx and idx[0].appended_files
        got = q.to_pandas()
        session.disable_hyperspace()
        exp = q.to_pandas()
        pd.testing.assert_frame_equal(
            got.sort_values(["id", "amount"]).reset_index(drop=True),
            exp.sort_values(["id", "amount"]).reset_index(drop=True),
            check_dtype=False)


class TestJsonOrcFormats:
    def _roundtrip(self, session, tmp_path, fmt, writer):
        rng = np.random.default_rng(63)
        df = pd.DataFrame({
            "k": rng.integers(0, 50, 500).astype(np.int64),
            "v": np.round(rng.uniform(0, 10, 500), 3),
            "s": rng.choice(["p", "q", "r"], 500),
        })
        d = tmp_path / fmt
        d.mkdir()
        writer(df, d)
        q = getattr(session.read, fmt)(str(d)) \
            .filter(col("k") < 25).select("k", "v", "s")
        got = q.to_pandas()
        exp = df[df.k < 25][["k", "v", "s"]]
        key = ["k", "v", "s"]
        pd.testing.assert_frame_equal(
            got.sort_values(key).reset_index(drop=True),
            exp.sort_values(key).reset_index(drop=True), check_dtype=False)
        return df, str(d)

    def test_json_scan(self, session, tmp_path):
        self._roundtrip(
            session, tmp_path, "json",
            lambda df, d: df.to_json(d / "part0.json", orient="records",
                                     lines=True))

    def test_orc_scan(self, session, tmp_path):
        import pyarrow.orc as pa_orc
        self._roundtrip(
            session, tmp_path, "orc",
            lambda df, d: pa_orc.write_table(
                pa.Table.from_pandas(df), str(d / "part0.orc")))

    def test_json_index_end_to_end(self, session, tmp_path):
        df, d = self._roundtrip(
            session, tmp_path, "json",
            lambda df, d: df.to_json(d / "part0.json", orient="records",
                                     lines=True))
        hs = Hyperspace(session)
        reader = session.read.json(d)
        hs.create_index(reader, IndexConfig("jidx", ["k"], ["v"]))
        session.enable_hyperspace()
        q = reader.filter(col("k") == 7).select("k", "v")
        assert any(isinstance(l, IndexScan)
                   for l in q.optimized_plan().collect_leaves())
        got = q.to_pandas()
        exp = df[df.k == 7][["k", "v"]]
        assert len(got) == len(exp)

    def test_orc_index_end_to_end(self, session, tmp_path):
        import pyarrow.orc as pa_orc
        df, d = self._roundtrip(
            session, tmp_path, "orc",
            lambda df, d: pa_orc.write_table(
                pa.Table.from_pandas(df), str(d / "part0.orc")))
        hs = Hyperspace(session)
        reader = session.read.orc(d)
        hs.create_index(reader, IndexConfig("oidx", ["k"], ["v"]))
        session.enable_hyperspace()
        q = reader.filter(col("k") == 9).select("k", "v")
        assert any(isinstance(l, IndexScan)
                   for l in q.optimized_plan().collect_leaves())
        got = q.to_pandas()
        exp = df[df.k == 9][["k", "v"]]
        assert len(got) == len(exp)


class TestPartitionPruningEdges:
    def test_fractional_literal_not_truncated(self, session, tmp_path):
        """`year < 2020.5` must keep year=2020 (int(2020.5) truncation
        would wrongly prune it)."""
        root, full = write_partitioned(tmp_path)
        q = session.read.parquet(root).filter(col("year") < 2020.5) \
            .select("id", "year")
        scans = [l for l in q.optimized_plan().collect_leaves()
                 if isinstance(l, Scan)]
        assert scans and len(scans[0].relation.all_files()) == 3
        got = q.to_pandas()
        assert set(got["year"]) == {2020}
        assert len(got) == len(full[full.year == 2020])

    def test_partition_only_projection_no_extra_columns(self, session,
                                                        tmp_path):
        """Selecting only partition columns must not leak the dummy
        physical column read for row counts."""
        root, full = write_partitioned(tmp_path)
        got = session.read.parquet(root).select("region", "year").to_pandas()
        assert sorted(got.columns) == ["region", "year"]
        assert len(got) == len(full)

    def test_index_still_used_with_partition_filter(self, session, tmp_path):
        """Partition pruning must not break index signatures: it runs
        AFTER the rewrite batch, so an index query that ALSO filters on a
        partition column keeps its index."""
        root, full = write_partitioned(tmp_path)
        hs = Hyperspace(session)
        df = session.read.parquet(root)
        hs.create_index(df, IndexConfig("bothIdx", ["id"],
                                        ["amount", "region", "year"]))
        session.enable_hyperspace()
        q = df.filter((col("id") < 2000) & (col("region") == "emea")) \
            .select("id", "amount", "region")
        leaves = q.optimized_plan().collect_leaves()
        assert any(isinstance(l, IndexScan) and l.index_entry.name == "bothIdx"
                   for l in leaves), "partition filter killed the index"
        got = q.to_pandas()
        session.disable_hyperspace()
        exp = q.to_pandas()
        key = ["id", "amount", "region"]
        pd.testing.assert_frame_equal(
            got.sort_values(key).reset_index(drop=True),
            exp.sort_values(key).reset_index(drop=True), check_dtype=False)


class TestTextFormat:
    def test_text_scan_and_filter(self, session, tmp_path):
        d = tmp_path / "txt"
        d.mkdir()
        (d / "a.txt").write_text("alpha\nbravo\ncharlie\n")
        (d / "b.txt").write_text("delta\necho\n")
        df = session.read.text(str(d))
        assert df.plan.schema.names == ["value"]
        got = df.to_pandas()
        assert sorted(got["value"]) == ["alpha", "bravo", "charlie",
                                        "delta", "echo"]
        f = df.filter(col("value") > "c").to_pandas()
        assert sorted(f["value"]) == ["charlie", "delta", "echo"]

    def test_text_line_terminators_match_hadoop(self, session, tmp_path):
        """Hadoop's LineReader treats \\n, \\r, and \\r\\n all as line
        terminators; \\x0b (vertical tab) is NOT one — it stays inside the
        line (the str.splitlines divergence)."""
        d = tmp_path / "txt2"
        d.mkdir()
        (d / "mixed.txt").write_text(
            "one\r\ntwo\rthree\nfo\x0bur\r", newline="")
        got = session.read.text(str(d)).to_pandas()
        assert list(got["value"]) == ["one", "two", "three", "fo\x0bur"]
