"""GroupByIndexRule + bucket-order sort-skip (the Q17 optimization).

The rule rewrites an unfiltered group-by to scan a covering index whose
indexed columns equal the grouping keys; the executor then skips the
group-by sort because bucket order makes equal key tuples contiguous
(executor.GROUPBY_SORT_SKIPPED). Oracle: disable-and-compare.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.execution import executor
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import avg, col, count, sum_
from hyperspace_tpu.plan.nodes import IndexScan


@pytest.fixture()
def env(tmp_path):
    rng = np.random.default_rng(77)
    n = 5000
    df = pd.DataFrame({
        "pk": rng.integers(0, 200, n).astype(np.int64),
        "qty": rng.integers(1, 50, n).astype(np.int64),
        "price": np.round(rng.uniform(10, 1000, n), 2),
        "other": rng.integers(0, 5, n).astype(np.int64),
    })
    d = tmp_path / "data"
    d.mkdir()
    for i in range(2):
        pq.write_table(pa.Table.from_pandas(
            df.iloc[i * (n // 2):(i + 1) * (n // 2)].reset_index(drop=True)),
            d / f"part{i}.parquet")
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 8)
    # Single-device comparison: the sort-skip is a single-device fast path
    # (the SPMD aggregate path shards and re-sorts per device regardless).
    session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "false")
    hs = Hyperspace(session)
    hs.create_index(session.read.parquet(str(d)),
                    IndexConfig("gIdx", ["pk"], ["qty", "price"]))
    return dict(session=session, hs=hs, path=str(d), df=df)


class TestGroupByIndexRule:
    def test_unfiltered_groupby_rewrites_and_skips_sort(self, env):
        session = env["session"]
        session.enable_hyperspace()
        q = session.read.parquet(env["path"]).group_by("pk").agg(
            avg(col("qty")).alias("aq"), sum_(col("price")).alias("sp"))
        plan = q.optimized_plan()
        assert any(isinstance(l, IndexScan) and l.index_entry.name == "gIdx"
                   for l in plan.collect_leaves()), "group-by rewrite missing"
        before = executor.GROUPBY_SORT_SKIPPED
        got = q.to_pandas()
        assert executor.GROUPBY_SORT_SKIPPED > before, "sort was not skipped"
        session.disable_hyperspace()
        exp = q.to_pandas()
        pd.testing.assert_frame_equal(
            got.sort_values("pk").reset_index(drop=True),
            exp.sort_values("pk").reset_index(drop=True), check_dtype=False)

    def test_groupby_with_filter_still_skips(self, env):
        """Filters above the index scan keep bucket order, so a filtered
        group-by on the indexed key also skips its sort."""
        session = env["session"]
        session.enable_hyperspace()
        q = (session.read.parquet(env["path"])
             .filter(col("qty") > 10).group_by("pk")
             .agg(count(None).alias("n")))
        before = executor.GROUPBY_SORT_SKIPPED
        got = q.to_pandas()
        assert executor.GROUPBY_SORT_SKIPPED > before
        session.disable_hyperspace()
        exp = q.to_pandas()
        pd.testing.assert_frame_equal(
            got.sort_values("pk").reset_index(drop=True),
            exp.sort_values("pk").reset_index(drop=True), check_dtype=False)

    def test_uncovered_agg_column_not_rewritten(self, env):
        session = env["session"]
        session.enable_hyperspace()
        q = session.read.parquet(env["path"]).group_by("pk").agg(
            sum_(col("other")).alias("so"))  # 'other' not covered
        assert not any(isinstance(l, IndexScan)
                       for l in q.optimized_plan().collect_leaves())
        # Still correct via the source scan.
        got = q.to_pandas()
        exp = env["df"].groupby("pk").agg(so=("other", "sum")).reset_index()
        g = got.sort_values("pk").reset_index(drop=True)
        assert np.array_equal(g["so"].to_numpy(), exp["so"].to_numpy())

    def test_group_key_mismatch_not_rewritten(self, env):
        session = env["session"]
        session.enable_hyperspace()
        q = session.read.parquet(env["path"]).group_by("other").agg(
            count(None).alias("n"))
        assert not any(isinstance(l, IndexScan)
                       for l in q.optimized_plan().collect_leaves())


class TestTwoPhaseGroupBy:
    def test_join_output_groupby_superset_skips_sort(self, env):
        """Q3 shape: join output keeps the probe side's bucket order, and a
        group-by on a SUPERSET of the bucket keys runs the two-phase
        run-based aggregation instead of sorting all rows."""
        session, hs = env["session"], env["hs"]
        rng = np.random.default_rng(88)
        dim = pd.DataFrame({
            "dk": np.arange(200, dtype=np.int64),
            "dval": rng.integers(0, 30, 200).astype(np.int64),
        })
        import pathlib
        ddir = pathlib.Path(env["path"]).parent / "dim"
        ddir.mkdir()
        pq.write_table(pa.Table.from_pandas(dim), ddir / "p.parquet")
        hs.create_index(session.read.parquet(str(ddir)),
                        IndexConfig("dimIdx", ["dk"], ["dval"]))
        session.enable_hyperspace()
        f = session.read.parquet(env["path"])
        dd = session.read.parquet(str(ddir))
        q = (f.join(dd, on=col("pk") == col("dk"))
             .group_by("pk", "dval")
             .agg(sum_(col("price")).alias("sp"),
                  avg(col("qty")).alias("aq"),
                  count(None).alias("n")))
        before = executor.GROUPBY_TWO_PHASE
        got = q.to_pandas()
        assert executor.GROUPBY_TWO_PHASE > before, \
            "two-phase group-by path not taken"
        session.disable_hyperspace()
        exp = q.to_pandas()
        pd.testing.assert_frame_equal(
            got.sort_values(["pk", "dval"]).reset_index(drop=True),
            exp.sort_values(["pk", "dval"]).reset_index(drop=True),
            check_dtype=False)
