"""Crash-recovery harness: kill -9 a child mid-action, recover, verify.

THE robustness acceptance (ISSUE r11): a subprocess running a real
create / refresh / optimize / vacuum is SIGKILL'd at an injected fault
point inside the op-log protocol (``kill`` specs on the frozen fault
registry — robustness/faults.py delivers a genuine unhandleable
``kill -9`` at the exact boundary), then a fresh session must:

- land its recovery scan on the latest STABLE log entry (the backward
  scan survives a stale/missing latestStable cache);
- roll orphaned transient states (CREATING/REFRESHING/OPTIMIZING/
  VACUUMING) back via ``Hyperspace.recover()`` (the protocol's own
  CancelAction underneath);
- vacuum partial index data versions no committed entry references;
- answer queries byte-identically to an uncrashed lake (index-enabled
  answers == plain-scan ground truth over the same files);
- complete the interrupted action successfully afterwards.

Crash positions per action: ``log.write nth=1`` (die before ANY
protocol write — lake untouched), ``action.op`` (transient state
committed, no data), ``log.write nth=2`` (op done, final entry never
written — the canonical mid-action wreck), ``log.stable`` (final entry
committed, latestStable cache stale).
"""

import glob
import os
import signal
import subprocess
import sys
import textwrap

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace
from hyperspace_tpu.index.constants import (IndexConstants, STABLE_STATES,
                                            States)
from hyperspace_tpu.index.log_manager import IndexLogManager
from hyperspace_tpu.plan.expr import col

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The child driver: builds the lake up to the target action with faults
# DISARMED, then arms the kill spec and runs the action that dies.
_CHILD = textwrap.dedent("""
    import os, sys
    import numpy as np
    import pandas as pd
    import pyarrow as pa
    import pyarrow.parquet as pq

    mode, point, spec, data_dir, sys_dir = sys.argv[1:6]

    import hyperspace_tpu as hst
    from hyperspace_tpu.api import Hyperspace, IndexConfig

    session = hst.Session(system_path=sys_dir)
    session.conf.set("hyperspace.index.numBuckets", 4)
    session.conf.set("hyperspace.index.lineage.enabled", "true")
    session.conf.set("hyperspace.tpu.distributed.enabled", "false")
    hs = Hyperspace(session)

    def arm():
        session.conf.set(
            "hyperspace.tpu.robustness.faults." + point, spec)

    def append_file(tag):
        rng = np.random.default_rng(5)
        t = pa.table({
            "k": pa.array(rng.integers(0, 40, 500).astype(np.int64)),
            "v": pa.array(rng.integers(0, 9, 500).astype(np.int64))})
        pq.write_table(t, os.path.join(data_dir, tag + ".parquet"))

    t = session.read.parquet(data_dir)
    cfg = IndexConfig("cx", ["k"], ["v"])
    if mode == "create":
        arm()
        hs.create_index(t, cfg)
    elif mode == "refresh":
        hs.create_index(t, cfg)
        append_file("extra")
        arm()
        hs.refresh_index("cx", "incremental")
    elif mode == "optimize":
        hs.create_index(t, cfg)
        append_file("extra")
        hs.refresh_index("cx", "incremental")
        arm()
        hs.optimize_index("cx", "full")
    elif mode == "vacuum":
        hs.create_index(t, cfg)
        hs.delete_index("cx")
        arm()
        hs.vacuum_index("cx")
    print("CHILD-SURVIVED")  # a kill spec must never let us get here
""")

# (action, fault point, kill spec, expected latest-log state right
# after the crash; None = the protocol never wrote anything).
CASES = [
    ("create", "log.write", "kill:nth=1", None),
    ("create", "action.op", "kill:nth=1", States.CREATING),
    ("create", "log.write", "kill:nth=2", States.CREATING),
    ("create", "log.stable", "kill:nth=1", States.ACTIVE),
    ("refresh", "log.write", "kill:nth=2", States.REFRESHING),
    ("refresh", "log.stable", "kill:nth=1", States.ACTIVE),
    ("optimize", "log.write", "kill:nth=2", States.OPTIMIZING),
    ("optimize", "log.stable", "kill:nth=1", States.ACTIVE),
    ("vacuum", "log.write", "kill:nth=2", States.VACUUMING),
    ("vacuum", "log.stable", "kill:nth=1", States.DOESNOTEXIST),
]


def _write_data(d):
    rng = np.random.default_rng(17)
    df = pd.DataFrame({
        "k": rng.integers(0, 40, 2000).astype(np.int64),
        "v": rng.integers(0, 9, 2000).astype(np.int64)})
    os.makedirs(d, exist_ok=True)
    pq.write_table(pa.Table.from_pandas(df), os.path.join(d, "p0.parquet"))


def _run_child(tmp_path, mode, point, spec):
    script = str(tmp_path / "child.py")
    with open(script, "w") as f:
        f.write(_CHILD)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, script, mode, point, spec,
         str(tmp_path / "data"), str(tmp_path / "indexes")],
        env=env, capture_output=True, text=True, timeout=420, cwd=ROOT)


def _session(tmp_path):
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    session.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
    session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "false")
    return session


@pytest.mark.parametrize("mode,point,spec,crashed_state", CASES)
def test_kill9_then_recover_then_serve(tmp_path, mode, point, spec,
                                       crashed_state):
    _write_data(str(tmp_path / "data"))
    proc = _run_child(tmp_path, mode, point, spec)

    # The child died by SIGKILL at the fault point — not by finishing,
    # not by a python exception.
    assert proc.returncode == -signal.SIGKILL, \
        f"rc={proc.returncode}\nstdout:{proc.stdout}\nstderr:{proc.stderr}"
    assert "CHILD-SURVIVED" not in proc.stdout

    idx_path = os.path.join(str(tmp_path / "indexes"), "cx")
    mgr = IndexLogManager(idx_path)
    latest = mgr.get_latest_log()
    if crashed_state is None:
        assert latest is None  # the kill preceded every protocol write
    else:
        assert latest.state == crashed_state

    # The recovery scan lands on the latest stable entry even when the
    # crash tore the latestStable cache window.
    stable = mgr.get_latest_stable_log()
    if stable is not None:
        assert stable.state in STABLE_STATES

    vdirs_before = {int(os.path.basename(p).split("=")[1])
                    for p in glob.glob(os.path.join(idx_path, "v__=*"))}

    session = _session(tmp_path)
    hs = Hyperspace(session)
    summary = hs.recover()
    assert not summary["errors"], summary

    # Transient wrecks rolled back; stable crash points untouched.
    if crashed_state is not None and crashed_state not in STABLE_STATES:
        assert summary["cancelled"] == ["cx"]
        recovered = IndexLogManager(idx_path).get_latest_log()
        assert recovered.state in STABLE_STATES
    else:
        assert summary["cancelled"] == []

    # Partial data versions vacuumed: exactly the unreferenced dirs are
    # gone, and a second sweep is a no-op (the lake is clean).
    vacuumed = set(summary["vacuumed"].get("cx", []))
    vdirs_after = {int(os.path.basename(p).split("=")[1])
                   for p in glob.glob(os.path.join(idx_path, "v__=*"))}
    assert vdirs_after == vdirs_before - vacuumed
    again = hs.recover()
    assert not again["cancelled"] and not again["vacuumed"], again

    # Byte-identical serving: index-enabled answers == plain-scan ground
    # truth over the same files (what an uncrashed lake answers).
    t = session.read.parquet(str(tmp_path / "data"))
    q = t.filter(col("k") == 7).select("k", "v")
    session.enable_hyperspace()
    a = q.to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
    session.disable_hyperspace()
    b = q.to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(a, b)

    # The interrupted action completes on the recovered lake.
    from hyperspace_tpu.api import IndexConfig
    if mode == "create":
        if IndexLogManager(idx_path).get_latest_stable_log() is None or \
                IndexLogManager(idx_path).get_latest_stable_log().state \
                != States.ACTIVE:
            hs.create_index(t, IndexConfig("cx", ["k"], ["v"]))
        assert IndexLogManager(idx_path).get_latest_stable_log().state \
            == States.ACTIVE
    elif mode == "refresh":
        hs.refresh_index("cx", "incremental")
        assert IndexLogManager(idx_path).get_latest_stable_log().state \
            == States.ACTIVE
    elif mode == "optimize":
        hs.optimize_index("cx", "full")
        assert IndexLogManager(idx_path).get_latest_stable_log().state \
            == States.ACTIVE
    elif mode == "vacuum":
        state = IndexLogManager(idx_path).get_latest_stable_log().state
        if state == States.DELETED:
            hs.vacuum_index("cx")
        assert IndexLogManager(idx_path).get_latest_stable_log().state \
            == States.DOESNOTEXIST
        assert not glob.glob(os.path.join(idx_path, "v__=*"))
