"""Hyperspace.why_not coverage across ALL THREE rule families — filter,
join, and data-skipping — including the no-index and wrong-column cases
(the diagnostic surface the advisor's reports point users at).

Sessions run with the default distributed tier (partitioned-jit SPMD
over the virtual 8-device CPU mesh).
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import (BloomFilterSketch, DataSkippingIndexConfig,
                                Hyperspace, IndexConfig, MinMaxSketch)
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col


@pytest.fixture()
def env(tmp_path):
    d = tmp_path / "fact"
    d.mkdir()
    rng = np.random.default_rng(11)
    # Two time-ordered parts so MinMax sketches could prune.
    ks = np.sort(rng.integers(0, 100, 800)).astype(np.int64)
    t = pa.table({
        "k": pa.array(ks),
        "v": pa.array(rng.integers(0, 9, 800).astype(np.int64)),
        "w": pa.array(rng.integers(0, 9, 800).astype(np.int64)),
    })
    pq.write_table(t.slice(0, 400), d / "p0.parquet")
    pq.write_table(t.slice(400, 400), d / "p1.parquet")
    d2 = tmp_path / "dim"
    d2.mkdir()
    pq.write_table(pa.table({
        "dk": pa.array(np.arange(100, dtype=np.int64)),
        "dv": pa.array(np.arange(100, dtype=np.int64)),
    }), d2 / "p0.parquet")
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    session.enable_hyperspace()
    return dict(session=session, hs=Hyperspace(session),
                fact=str(d), dim=str(d2))


class TestWhyNotNoIndex:
    def test_no_index_at_all(self, env):
        session, hs = env["session"], env["hs"]
        q = session.read.parquet(env["fact"]).filter(col("k") > 3) \
            .select("k", "v")
        assert hs.why_not(q) == "No reason recorded."

    def test_named_index_does_not_exist(self, env):
        session, hs = env["session"], env["hs"]
        q = session.read.parquet(env["fact"]).filter(col("k") > 3) \
            .select("k", "v")
        out = hs.why_not(q, index_name="ghost")
        assert "No reasons recorded for index 'ghost'" in out


class TestWhyNotFilterRule:
    def test_wrong_first_indexed_column(self, env):
        session, hs = env["session"], env["hs"]
        fact = session.read.parquet(env["fact"])
        hs.create_index(fact, IndexConfig("on_v", ["v"], ["k"]))
        out = hs.why_not(fact.filter(col("k") > 3).select("k", "v"))
        assert "[on_v] NO_FIRST_INDEXED_COL_COND" in out

    def test_missing_required_column(self, env):
        session, hs = env["session"], env["hs"]
        fact = session.read.parquet(env["fact"])
        hs.create_index(fact, IndexConfig("kv", ["k"], ["v"]))
        # The query also needs w, which kv does not carry.
        out = hs.why_not(fact.filter(col("k") > 3).select("k", "w"))
        assert "[kv] MISSING_REQUIRED_COL" in out


class TestWhyNotJoinRule:
    def test_join_one_side_unindexed(self, env):
        session, hs = env["session"], env["hs"]
        fact = session.read.parquet(env["fact"])
        dim = session.read.parquet(env["dim"])
        hs.create_index(fact, IndexConfig("f_k", ["k"], ["v"]))
        q = fact.join(dim, on=col("k") == col("dk")) \
            .select("k", "v", "dv")
        out = hs.why_not(q)
        # f_k alone cannot make the pair; it must NOT be reported as
        # applied, and no false reason may claim it covers nothing.
        assert "Applied indexes" not in out
        assert "[f_k] MISSING_REQUIRED_COL" not in out

    def test_join_wrong_indexed_columns(self, env):
        session, hs = env["session"], env["hs"]
        fact = session.read.parquet(env["fact"])
        dim = session.read.parquet(env["dim"])
        # Indexed on v, not on the join column k.
        hs.create_index(fact, IndexConfig("f_wrong", ["v"], ["k"]))
        hs.create_index(dim, IndexConfig("d_ok", ["dk"], ["dv"]))
        q = fact.join(dim, on=col("k") == col("dk")) \
            .select("k", "v", "dv")
        out = hs.why_not(q)
        assert "[f_wrong] NOT_ALL_JOIN_COL_INDEXED" in out


class TestWhyNotDataSkippingRule:
    def test_wrong_column_sketch(self, env):
        session, hs = env["session"], env["hs"]
        fact = session.read.parquet(env["fact"])
        # Sketch on v; the predicate constrains k only.
        hs.create_index(fact, DataSkippingIndexConfig(
            "skip_v", [MinMaxSketch("v")]))
        out = hs.why_not(fact.filter(col("k") > 3).select("k", "v", "w"))
        assert "[skip_v] NO_APPLICABLE_SKETCH" in out
        assert "sketched columns: ['v']" in out

    def test_unsupported_predicate_shape(self, env):
        session, hs = env["session"], env["hs"]
        fact = session.read.parquet(env["fact"])
        hs.create_index(fact, DataSkippingIndexConfig(
            "skip_b", [BloomFilterSketch("k")]))
        # A Bloom sketch cannot refute a range predicate.
        out = hs.why_not(fact.filter(col("k") > 3).select("k", "v", "w"))
        assert "[skip_b] NO_APPLICABLE_SKETCH" in out

    def test_stale_sketch_after_source_change(self, env):
        session, hs = env["session"], env["hs"]
        fact = session.read.parquet(env["fact"])
        hs.create_index(fact, DataSkippingIndexConfig(
            "skip_k", [MinMaxSketch("k")]))
        pq.write_table(pa.table({
            "k": pa.array(np.array([500], dtype=np.int64)),
            "v": pa.array(np.array([1], dtype=np.int64)),
            "w": pa.array(np.array([1], dtype=np.int64)),
        }), f"{env['fact']}/p2.parquet")
        fresh = session.read.parquet(env["fact"])
        out = hs.why_not(fresh.filter(col("k") > 990).select("k", "v", "w"))
        assert "[skip_k] SOURCE_DATA_CHANGED" in out

    def test_applied_sketch_not_reported_as_failed(self, env):
        session, hs = env["session"], env["hs"]
        fact = session.read.parquet(env["fact"])
        hs.create_index(fact, DataSkippingIndexConfig(
            "skip_k", [MinMaxSketch("k")]))
        # k is time-ordered across the two parts: a tight range prunes.
        q = fact.filter(col("k") > 95).select("k", "v", "w")
        plan = q.optimized_plan()
        assert any(getattr(l, "skipping_note", None)
                   for l in plan.collect_leaves())
        out = hs.why_not(q)
        assert "[skip_k] NO_APPLICABLE_SKETCH" not in out
