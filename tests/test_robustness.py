"""Robustness layer (robustness/{faults,retry,recovery}.py + the
degradation ladders wired through io/executor/spmd/bank/cache/frontend).

Covers: fault-spec parsing and registry semantics (nth/times/p, typed
errors, latency), the disarmed-is-a-no-op contract (byte-identical
results), retry with backoff for transient faults at pooled reads and
op-log writes (RetryEvent, original-error surfacing), per-query
deadlines + cooperative cancellation (conf and submit-time, queue
fast-fail, freed slots, QueryCancelledEvent), and every
graceful-degradation ladder proven under injection with byte-identical
answers: SPMD dispatch/compile fault -> single-device, program-bank
compile fault -> uncached eager, result-cache device_put fault -> host
tier, corrupt spill read-back -> miss (never a wrong answer), sweep
member fault -> per-member re-execution, worker death -> member
release. Plus in-process crash recovery (rollback + orphan vacuum) and
the new lint gates.
"""

import os
import threading
import time

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.exceptions import (HyperspaceException,
                                       QueryDeadlineError)
from hyperspace_tpu.index.constants import IndexConstants, States
from hyperspace_tpu.plan.expr import col, sum_
from hyperspace_tpu.robustness import fault_names as FN
from hyperspace_tpu.robustness import faults, retry
from hyperspace_tpu.robustness.constants import RobustnessConstants as RC
from hyperspace_tpu.robustness.faults import (FaultRegistry, FaultSpec,
                                              InjectedFaultError,
                                              TransientInjectedFaultError)
from hyperspace_tpu.serving.constants import ServingConstants
from hyperspace_tpu.serving.frontend import ServingFrontend

from conftest import capture_logger


def _fkey(point: str) -> str:
    return f"{RC.FAULTS_PREFIX}.{point}"


def _write(d, n=4000, seed=7, files=3):
    rng = np.random.default_rng(seed)
    df = pd.DataFrame({
        "k": rng.integers(0, 50, n).astype(np.int64),
        "v": rng.integers(0, 9, n).astype(np.int64),
    })
    os.makedirs(str(d), exist_ok=True)
    step = max(n // files, 1)
    for i in range(files):
        lo = i * step
        hi = (i + 1) * step if i < files - 1 else n
        pq.write_table(pa.Table.from_pandas(df.iloc[lo:hi]),
                       os.path.join(str(d), f"p{i}.parquet"))
    return df


def _session(tmp_path, capture_events=False, **conf):
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    if capture_events:
        session.conf.set(IndexConstants.EVENT_LOGGER_CLASS,
                         "tests.conftest.CaptureLogger")
    for k, v in conf.items():
        session.conf.set(k, v)
    return session


def _query(session, d):
    return session.read.parquet(str(d)).filter(col("k") < 20) \
        .group_by("k").agg(sum_(col("v")).alias("sv")).sort("k")


# ---------------------------------------------------------------------------
# Fault specs + registry semantics.
# ---------------------------------------------------------------------------

class TestFaultSpecs:
    def test_frozen_registry_equality(self):
        """The fault-point vocabulary, spelled out literally — THE
        coverage reference the scripts/lint.py fault-discipline gate
        checks registered names against (the span-registry precedent).
        Growing the registry means growing this set AND injecting the
        new point somewhere under tests/."""
        assert FN.FAULT_NAMES == frozenset({
            "io.pooled_read", "io.prefetch_produce",
            "scan.parquet_decode", "spmd.dispatch", "spmd.compile",
            "bank.compile", "result_cache.device_put",
            "result_cache.spill_read", "log.write", "log.stable",
            "action.op", "serving.worker", "ingest.stage",
            "ingest.publish", "artifacts.write", "artifacts.read",
            "cluster.forward", "cluster.broadcast",
            "streaming.source", "buffer.load",
        })

    def test_parse_kinds_and_options(self):
        s = FaultSpec.parse(FN.SCAN_PARQUET_DECODE,
                            "error:p=0.5,nth=3,times=2,exc=OSError")
        assert (s.kind, s.p, s.nth, s.times, s.exc) == \
            ("error", 0.5, 3, 2, OSError)
        lat = FaultSpec.parse(FN.IO_POOLED_READ, "latency:ms=5")
        assert lat.kind == "latency" and lat.ms == 5.0
        assert FaultSpec.parse(FN.LOG_WRITE, "kill").kind == "kill"
        assert FaultSpec.parse(FN.LOG_STABLE, "transient").kind \
            == "transient"

    def test_unknown_name_kind_option_raise(self):
        with pytest.raises(HyperspaceException):
            FaultSpec.parse("not.a.point", "error")
        with pytest.raises(HyperspaceException):
            FaultSpec.parse(FN.LOG_WRITE, "explode")
        with pytest.raises(HyperspaceException):
            FaultSpec.parse(FN.LOG_WRITE, "error:bogus=1")
        with pytest.raises(HyperspaceException):
            FaultSpec.parse(FN.LOG_WRITE, "error:exc=NoSuchError")

    def test_registry_nth_and_times(self):
        reg = FaultRegistry.from_conf_specs(
            {FN.IO_POOLED_READ: "error:nth=2"})
        reg.trigger(FN.IO_POOLED_READ)  # hit 1: silent
        with pytest.raises(InjectedFaultError):
            reg.trigger(FN.IO_POOLED_READ)  # hit 2: fires
        reg.trigger(FN.IO_POOLED_READ)  # hit 3: silent again
        reg = FaultRegistry.from_conf_specs(
            {FN.IO_POOLED_READ: "transient:times=2"})
        for _ in range(2):
            with pytest.raises(TransientInjectedFaultError):
                reg.trigger(FN.IO_POOLED_READ)
        reg.trigger(FN.IO_POOLED_READ)  # budget exhausted: silent

    def test_conf_armed_probability_varies_across_queries(self, tmp_path):
        """p= specs must SAMPLE per query under conf arming, not replay
        one RNG draw for every execute (which would make p=0.5 fire for
        either all queries or none): each per-run scope derives its seed
        from (conf seed, scope ordinal)."""
        _write(tmp_path / "d", n=400, files=1)
        session = _session(
            tmp_path, **{_fkey(FN.SCAN_PARQUET_DECODE): "error:p=0.5"})
        q = session.read.parquet(str(tmp_path / "d")).filter(col("k") < 5)
        outcomes = []
        for _ in range(20):
            try:
                q.to_arrow()
                outcomes.append(True)
            except InjectedFaultError:
                outcomes.append(False)
        assert any(outcomes) and not all(outcomes)

    def test_registry_probability_deterministic_by_seed(self):
        def fired(seed):
            reg = FaultRegistry.from_conf_specs(
                {FN.IO_POOLED_READ: "error:p=0.5"}, seed=seed)
            out = []
            for _ in range(20):
                try:
                    reg.trigger(FN.IO_POOLED_READ)
                    out.append(False)
                except InjectedFaultError:
                    out.append(True)
            return out

        assert fired(7) == fired(7)
        assert any(fired(7)) and not all(fired(7))

    def test_unarmed_point_is_silent(self):
        reg = FaultRegistry.from_conf_specs({FN.LOG_WRITE: "error"})
        reg.trigger(FN.SPMD_DISPATCH)  # armed registry, different point
        faults.fault_point(FN.SPMD_DISPATCH)  # no scope at all


# ---------------------------------------------------------------------------
# Injection through the engine + the disarmed no-op contract.
# ---------------------------------------------------------------------------

class TestFaultPoints:
    def test_disarmed_byte_identical(self, tmp_path):
        _write(tmp_path / "d")
        session = _session(tmp_path)
        q = _query(session, tmp_path / "d")
        a = q.to_arrow()
        assert faults.armed() is None
        b = q.to_arrow()
        assert a.equals(b)

    def test_error_injection_is_typed(self, tmp_path):
        _write(tmp_path / "d")
        session = _session(
            tmp_path, **{_fkey(FN.SCAN_PARQUET_DECODE): "error"})
        q = _query(session, tmp_path / "d")
        with pytest.raises(InjectedFaultError) as err:
            q.to_arrow()
        assert isinstance(err.value, HyperspaceException)
        # Disarm: the same session recovers immediately (conf is live).
        session.conf.unset(_fkey(FN.SCAN_PARQUET_DECODE))
        assert q.to_arrow().num_rows > 0

    def test_latency_injection_slows_not_breaks(self, tmp_path):
        _write(tmp_path / "d", files=1)
        session = _session(tmp_path)
        q = _query(session, tmp_path / "d")
        base = q.to_arrow()  # warm compiles
        t0 = time.perf_counter()
        base = q.to_arrow()
        warm_s = time.perf_counter() - t0
        session.conf.set(_fkey(FN.SCAN_PARQUET_DECODE), "latency:ms=120")
        t0 = time.perf_counter()
        slow = q.to_arrow()
        slow_s = time.perf_counter() - t0
        assert slow.equals(base)
        assert slow_s >= warm_s + 0.1

    def test_prefetch_producer_fault_surfaces_at_consumer(self):
        from hyperspace_tpu.parallel import io as pio
        reg = FaultRegistry.from_conf_specs(
            {FN.IO_PREFETCH_PRODUCE: "error:nth=3"})
        with faults.scope(reg):
            it = pio.prefetch_iter(iter(range(10)), label="test")
            got = []
            with pytest.raises(InjectedFaultError):
                for x in it:
                    got.append(x)
        assert got == [0, 1]  # items before the injected advance


# ---------------------------------------------------------------------------
# Retry with exponential backoff + jitter.
# ---------------------------------------------------------------------------

class TestRetry:
    def test_transient_recovers_and_counts(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("flaky mount")
            return "ok"

        before = faults.stats()["retries"]
        pol = retry.RetryPolicy(max_attempts=3, base_ms=0.1)
        assert retry.call(flaky, where="unit", policy=pol) == "ok"
        assert calls["n"] == 3
        assert faults.stats()["retries"] == before + 2

    def test_deterministic_oserrors_not_retried(self):
        """FileNotFoundError/PermissionError-class OSErrors fail the
        same way every attempt — they must surface immediately, not
        after a backoff ladder that pollutes the retry telemetry."""
        calls = {"n": 0}

        def missing():
            calls["n"] += 1
            raise FileNotFoundError("gone for good")

        with pytest.raises(FileNotFoundError):
            retry.call(missing, where="unit",
                       policy=retry.RetryPolicy(3, 0.1))
        assert calls["n"] == 1

    def test_non_transient_not_retried(self):
        calls = {"n": 0}

        def broken():
            calls["n"] += 1
            raise ValueError("deterministic")

        with pytest.raises(ValueError):
            retry.call(broken, where="unit",
                       policy=retry.RetryPolicy(3, 0.1))
        assert calls["n"] == 1

    def test_exhaustion_surfaces_original_error(self):
        errs = [OSError("first"), OSError("second"), OSError("third")]

        def always():
            raise errs.pop(0)

        with pytest.raises(OSError) as err:
            retry.call(always, where="unit",
                       policy=retry.RetryPolicy(3, 0.1))
        assert "first" in str(err.value)

    def test_pooled_read_retry_end_to_end(self, tmp_path):
        """Transient faults inside pooled reader tasks are absorbed by
        the retry (ordered gather: results byte-identical), with a
        RetryEvent per recovered sequence."""
        _write(tmp_path / "d")
        session = _session(tmp_path, capture_events=True)
        q = _query(session, tmp_path / "d")
        base = q.to_arrow()
        # Drop the base read's buffers from the process buffer pool —
        # a warm repeat would be served from HBM without any pooled
        # reader tasks, and the injected fault would never fire.
        from hyperspace_tpu.execution import buffer_pool
        buffer_pool.get_pool().clear()
        sink = capture_logger()
        n_before = len(sink.events)
        session.conf.set(_fkey(FN.IO_POOLED_READ), "transient:times=2")
        got = q.to_arrow()
        assert got.equals(base)
        evs = [e for e in sink.events[n_before:]
               if type(e).__name__ == "RetryEvent"]
        assert evs and all(e.succeeded for e in evs)
        assert all(e.where == "io.pooled_read" for e in evs)
        assert any("TransientInjectedFaultError" in e.error for e in evs)

    def test_pooled_read_retry_exhaustion(self, tmp_path):
        _write(tmp_path / "d")
        session = _session(tmp_path)
        session.conf.set(RC.RETRY_MAX_ATTEMPTS, "2")
        session.conf.set(RC.RETRY_BASE_MS, "1")
        session.conf.set(_fkey(FN.IO_POOLED_READ), "transient")
        with pytest.raises(TransientInjectedFaultError):
            _query(session, tmp_path / "d").to_arrow()

    def test_oplog_store_write_retry(self, tmp_path):
        """A flaky LogStore (OSError on the first two conditional puts)
        is absorbed: write_log succeeds via retry, protocol unchanged."""
        from hyperspace_tpu.index.log_manager import IndexLogManager
        from hyperspace_tpu.index.log_store import InMemoryObjectStore
        from test_log_entry import make_entry

        class Flaky(InMemoryObjectStore):
            def __init__(self):
                super().__init__()
                self.failures = 2

            def put_if_absent(self, path, data):
                if self.failures > 0:
                    self.failures -= 1
                    raise OSError("transient store error")
                return super().put_if_absent(path, data)

        store = Flaky()
        mgr = IndexLogManager(str(tmp_path / "ix"), store=store)
        assert mgr.write_log(0, make_entry(state=States.CREATING)) is True
        assert store.failures == 0
        assert mgr.get_latest_id() == 0

    def test_oplog_write_self_win_after_transient(self, tmp_path):
        """A put that COMMITS the entry and then raises transiently must
        not read as losing the optimistic-concurrency race to itself:
        write_log compares the stored bytes and reports the win."""
        from hyperspace_tpu.index.log_manager import IndexLogManager
        from hyperspace_tpu.index.log_store import InMemoryObjectStore
        from test_log_entry import make_entry

        class CommitThenRaise(InMemoryObjectStore):
            def __init__(self):
                super().__init__()
                self.armed = 1

            def put_if_absent(self, path, data):
                won = super().put_if_absent(path, data)
                if won and self.armed > 0:
                    self.armed -= 1
                    raise OSError("post-commit cleanup failure")
                return won

        mgr = IndexLogManager(str(tmp_path / "ix"),
                              store=CommitThenRaise())
        assert mgr.write_log(0, make_entry(state=States.CREATING)) is True
        # A GENUINE loss (someone else's bytes) still reads as a loss.
        other = CommitThenRaise()
        other.armed = 0
        mgr2 = IndexLogManager(str(tmp_path / "ix2"), store=other)
        assert mgr2.write_log(0, make_entry(state=States.CREATING))
        assert mgr2.write_log(0, make_entry(state=States.ACTIVE)) is False

    def test_oplog_fault_point_transient_via_create(self, tmp_path):
        """End to end: transient faults armed at log.write during a real
        create_index retry to success — the index lands ACTIVE."""
        _write(tmp_path / "d", files=1)
        session = _session(tmp_path, capture_events=True)
        session.conf.set(_fkey(FN.LOG_WRITE), "transient:times=2")
        session.conf.set(RC.RETRY_BASE_MS, "1")
        hs = Hyperspace(session)
        t = session.read.parquet(str(tmp_path / "d"))
        hs.create_index(t, IndexConfig("rix", ["k"], ["v"]))
        from hyperspace_tpu.index.log_manager import IndexLogManager
        mgr = IndexLogManager(
            os.path.join(str(tmp_path / "indexes"), "rix"))
        assert mgr.get_latest_stable_log().state == States.ACTIVE


# ---------------------------------------------------------------------------
# Deadlines + cooperative cancellation.
# ---------------------------------------------------------------------------

class TestDeadline:
    def test_conf_deadline_cancels_with_typed_error(self, tmp_path):
        _write(tmp_path / "d")
        session = _session(tmp_path, capture_events=True)
        session.conf.set(_fkey(FN.SCAN_PARQUET_DECODE), "latency:ms=80")
        session.conf.set(RC.DEADLINE_MS, "25")
        sink = capture_logger()
        n_before = len(sink.events)
        before = faults.stats()["deadline_cancellations"]
        with pytest.raises(QueryDeadlineError):
            _query(session, tmp_path / "d").to_arrow()
        assert faults.stats()["deadline_cancellations"] == before + 1
        evs = [e for e in sink.events[n_before:]
               if type(e).__name__ == "QueryCancelledEvent"]
        assert len(evs) == 1 and evs[0].elapsed_ms >= 25
        # Deadline off again: the query runs fine.
        session.conf.unset(RC.DEADLINE_MS)
        session.conf.unset(_fkey(FN.SCAN_PARQUET_DECODE))
        assert _query(session, tmp_path / "d").to_arrow().num_rows > 0

    def test_submit_deadline_frees_slot(self, tmp_path):
        """ServingFrontend.submit(deadline_ms=...) cancels a slow query
        with the typed error, frees the worker slot, and leaves the
        frontend fully serviceable."""
        _write(tmp_path / "d")
        session = _session(tmp_path)
        session.conf.set(_fkey(FN.SCAN_PARQUET_DECODE), "latency:ms=100")
        fe = ServingFrontend(session)
        p = fe.submit(_query(session, tmp_path / "d"), deadline_ms=30)
        with pytest.raises(QueryDeadlineError):
            p.result(timeout=120)
        fe.drain()
        st = fe.stats()
        assert st["active_workers"] == 0 and st["queued"] == 0
        assert st["inflight_bytes"] == 0
        session.conf.unset(_fkey(FN.SCAN_PARQUET_DECODE))
        ok = fe.submit(_query(session, tmp_path / "d"))
        assert ok.result(timeout=120).num_rows > 0
        fe.drain()

    def test_expired_in_queue_fast_fails(self, tmp_path):
        """An entry whose deadline expires while QUEUED is cancelled
        before paying any execution (the serving.queue fast path), with
        a QueryCancelledEvent carrying the REAL submit-time query id."""
        _write(tmp_path / "d")
        gate = threading.Event()

        class Gated(hst.Session):
            def execute(self, plan, context=None):
                assert gate.wait(timeout=60)
                return super().execute(plan, context)

        session = Gated(system_path=str(tmp_path / "indexes"))
        session.conf.set(IndexConstants.EVENT_LOGGER_CLASS,
                         "tests.conftest.CaptureLogger")
        session.conf.set(ServingConstants.SERVING_MAX_CONCURRENCY, "1")
        session.conf.set(ServingConstants.SERVING_BATCHING_ENABLED,
                         "false")
        fe = ServingFrontend(session)
        q = _query(session, tmp_path / "d")
        sink = capture_logger()
        n_before = len(sink.events)
        blocker = fe.submit(q)           # occupies the one worker
        doomed = fe.submit(q, deadline_ms=20)
        assert doomed.query_id > 0       # allocated at submit time
        time.sleep(0.08)                 # let the deadline lapse queued
        gate.set()
        blocker.result(timeout=120)
        with pytest.raises(QueryDeadlineError) as err:
            doomed.result(timeout=120)
        assert "serving.queue" in str(err.value)
        evs = [e for e in sink.events[n_before:]
               if type(e).__name__ == "QueryCancelledEvent"]
        assert len(evs) == 1 and evs[0].query_id == doomed.query_id
        fe.drain()
        assert fe.stats()["active_workers"] == 0


# ---------------------------------------------------------------------------
# Graceful-degradation ladders (each proven under fault injection with
# byte-identical answers).
# ---------------------------------------------------------------------------

class TestDegradationLadders:
    def _spmd_session(self, tmp_path, **conf):
        session = _session(tmp_path, capture_events=True, **conf)
        session.conf.set(IndexConstants.TPU_DISTRIBUTED_MIN_STREAM_ROWS,
                         "0")
        return session

    def test_spmd_dispatch_fault_falls_back_byte_identical(self, tmp_path):
        _write(tmp_path / "d", seed=11)
        session = self._spmd_session(tmp_path)
        q = _query(session, tmp_path / "d")
        baseline = q.to_arrow()
        sink = capture_logger()
        n_before = len(sink.events)
        before = faults.stats()["degraded_spmd"]
        session.conf.set(_fkey(FN.SPMD_DISPATCH), "error")
        got = q.to_arrow()
        assert got.equals(baseline)
        assert faults.stats()["degraded_spmd"] == before + 1
        falls = [e for e in sink.events[n_before:]
                 if type(e).__name__ == "DistributedFallbackEvent"]
        assert any(e.reason.startswith("fault:") for e in falls)

    def test_spmd_compile_fault_falls_back_byte_identical(self, tmp_path):
        from hyperspace_tpu.serving.program_bank import get_bank
        _write(tmp_path / "d", n=2777, seed=13)
        session = self._spmd_session(tmp_path)
        q = session.read.parquet(str(tmp_path / "d")) \
            .filter(col("v") >= 2).group_by("v") \
            .agg(sum_(col("k")).alias("sk")).sort("v")
        baseline = q.to_arrow()
        get_bank().clear()  # force a fresh MeshProgram compile attempt
        before = faults.stats()["degraded_spmd"]
        session.conf.set(_fkey(FN.SPMD_COMPILE), "error")
        got = q.to_arrow()
        assert got.equals(baseline)
        assert faults.stats()["degraded_spmd"] == before + 1

    def test_spmd_degrade_off_fails_loud(self, tmp_path):
        _write(tmp_path / "d", seed=17)
        session = self._spmd_session(
            tmp_path, **{RC.DEGRADE_ENABLED: "false",
                         _fkey(FN.SPMD_DISPATCH): "error"})
        with pytest.raises(InjectedFaultError):
            _query(session, tmp_path / "d").to_arrow()

    def test_device_put_degrade_off_fails_loud(self, tmp_path):
        """Every ladder honors the one master switch: with degradation
        off, a device_put fault propagates instead of silently landing
        the entry in the host tier."""
        _write(tmp_path / "d", seed=61)
        session = _session(
            tmp_path,
            **{RC.DEGRADE_ENABLED: "false",
               ServingConstants.RESULT_CACHE_ENABLED: "true",
               ServingConstants.RESULT_CACHE_MIN_COMPUTE_SECONDS: "0",
               _fkey(FN.RESULT_CACHE_DEVICE_PUT): "error"})
        with pytest.raises(InjectedFaultError):
            _query(session, tmp_path / "d").to_arrow()

    def test_bank_compile_fault_runs_uncached_eager(self, tmp_path):
        from hyperspace_tpu.serving.program_bank import get_bank
        _write(tmp_path / "d", seed=19)
        session = _session(tmp_path)
        q = _query(session, tmp_path / "d")
        baseline = q.to_arrow()
        get_bank().clear()  # next lookup is a miss -> factory runs
        before = faults.stats()["degraded_bank_compile"]
        session.conf.set(_fkey(FN.BANK_COMPILE), "error:nth=1")
        got = q.to_arrow()
        assert got.equals(baseline)
        assert faults.stats()["degraded_bank_compile"] == before + 1

    def test_device_put_fault_degrades_to_host_tier(self, tmp_path):
        _write(tmp_path / "d", seed=23)
        session = _session(tmp_path, capture_events=True)
        session.conf.set(ServingConstants.RESULT_CACHE_ENABLED, "true")
        session.conf.set(ServingConstants.RESULT_CACHE_MIN_COMPUTE_SECONDS,
                         "0")
        session.conf.set(_fkey(FN.RESULT_CACHE_DEVICE_PUT), "error")
        q = _query(session, tmp_path / "d")
        before = faults.stats()["degraded_device_put"]
        first = q.to_arrow()
        assert faults.stats()["degraded_device_put"] == before + 1
        cache = session.result_cache
        st = cache.stats()
        assert st["host_entries"] == 1 and st["device_entries"] == 0
        again = q.to_arrow()  # served from the host tier
        assert again.equals(first)
        assert cache.stats()["host_hits"] >= 1


class TestSpillTier:
    def _host_table(self, tmp_path, seed=29):
        _write(tmp_path / "d", seed=seed)
        session = _session(tmp_path)
        return session, _query(session, tmp_path / "d").execute().to_host()

    def test_host_victims_spill_and_read_back(self, tmp_path):
        from hyperspace_tpu.serving.result_cache import (ResultCache,
                                                         table_nbytes)
        session, t = self._host_table(tmp_path)
        n = table_nbytes(t)
        spill = tmp_path / "spill"
        rc = ResultCache(device_bytes=0, host_bytes=n,
                         spill_dir=str(spill), spill_bytes=10 * n)
        assert rc.put("a", t) == "host"
        assert rc.put("b", t) == "host"  # "a" demotes to disk
        assert rc.peek("a") == "spill" and rc.peek("b") == "host"
        got, tier = rc.get("a")
        assert tier == "spill"
        assert got.to_arrow().equals(t.to_arrow())
        st = rc.stats()
        assert st["spill_hits"] == 1 and st["spill_entries"] == 1
        assert st["demotions"] >= 1
        # The hit PROMOTED "a" back to the host tier (repeat hits must
        # not pay disk + deserialize), displacing "b" to disk.
        assert rc.peek("a") == "host" and rc.peek("b") == "spill"
        got2, tier2 = rc.get("a")
        assert tier2 == "host" and got2.to_arrow().equals(t.to_arrow())

    def test_corrupt_spill_is_a_miss_never_an_error(self, tmp_path):
        """THE satellite bugfix: garbage bytes in a spilled entry read
        back as a MISS (entry evicted, file dropped) — no exception, no
        wrong answer."""
        from hyperspace_tpu.serving.result_cache import (ResultCache,
                                                         table_nbytes)
        session, t = self._host_table(tmp_path, seed=31)
        n = table_nbytes(t)
        rc = ResultCache(device_bytes=0, host_bytes=n,
                         spill_dir=str(tmp_path / "spill"),
                         spill_bytes=10 * n)
        rc.put("a", t)
        rc.put("b", t)
        path = rc._spill["a"][0]
        with open(path, "wb") as f:
            f.write(b"garbage bytes, definitely not a spilled table")
        assert rc.get("a") is None  # miss, not an exception
        st = rc.stats()
        assert st["spill_corruptions"] == 1
        assert st["spill_entries"] == 0 and not os.path.exists(path)

    def test_truncated_spill_is_a_miss(self, tmp_path):
        from hyperspace_tpu.serving.result_cache import (ResultCache,
                                                         table_nbytes)
        session, t = self._host_table(tmp_path, seed=37)
        n = table_nbytes(t)
        rc = ResultCache(device_bytes=0, host_bytes=n,
                         spill_dir=str(tmp_path / "spill"),
                         spill_bytes=10 * n)
        rc.put("a", t)
        rc.put("b", t)
        path = rc._spill["a"][0]
        data = open(path, "rb").read()
        with open(path, "wb") as f:
            f.write(data[:len(data) // 2])  # torn tail: crash mid-spill
        assert rc.get("a") is None
        assert rc.stats()["spill_corruptions"] == 1

    def test_spill_read_fault_point_is_a_miss(self, tmp_path):
        from hyperspace_tpu.serving.result_cache import (ResultCache,
                                                         table_nbytes)
        session, t = self._host_table(tmp_path, seed=41)
        n = table_nbytes(t)
        rc = ResultCache(device_bytes=0, host_bytes=n,
                         spill_dir=str(tmp_path / "spill"),
                         spill_bytes=10 * n)
        rc.put("a", t)
        rc.put("b", t)
        reg = FaultRegistry.from_conf_specs(
            {FN.RESULT_CACHE_SPILL_READ: "error"})
        with faults.scope(reg):
            assert rc.get("a") is None
        assert rc.stats()["spill_corruptions"] == 1

    def test_end_to_end_corrupt_spill_recomputes_with_event(self, tmp_path):
        """Through the session: a corrupted spill entry produces a
        correct recomputed answer plus a ResultCacheMissEvent with
        reason="spill-corrupt"."""
        from hyperspace_tpu.serving.result_cache import table_nbytes
        _write(tmp_path / "d", seed=43)
        session = _session(tmp_path, capture_events=True)
        qa = _query(session, tmp_path / "d")
        qb = session.read.parquet(str(tmp_path / "d")) \
            .filter(col("v") < 5).group_by("v") \
            .agg(sum_(col("k")).alias("sk")).sort("v")
        n = table_nbytes(qa.execute().to_host())
        session.conf.set(ServingConstants.RESULT_CACHE_ENABLED, "true")
        session.conf.set(ServingConstants.RESULT_CACHE_MIN_COMPUTE_SECONDS,
                         "0")
        session.conf.set(ServingConstants.RESULT_CACHE_DEVICE_BYTES, "1")
        # Exactly one result fits the host tier: admitting the second
        # overflows it and the first (LRU) spills to disk.
        session.conf.set(ServingConstants.RESULT_CACHE_HOST_BYTES, str(n))
        session.conf.set(ServingConstants.RESULT_CACHE_SPILL_DIR,
                         str(tmp_path / "spill"))
        a1 = qa.to_arrow()      # admitted to host
        qb.to_arrow()           # admitted; qa's entry spills to disk
        cache = session.result_cache
        assert cache.stats()["spill_entries"] == 1
        path = next(iter(cache._spill.values()))[0]
        with open(path, "wb") as f:
            f.write(b"\x00\x01garbage")
        sink = capture_logger()
        n_before = len(sink.events)
        a2 = qa.to_arrow()      # corrupt read-back -> miss -> recompute
        assert a2.equals(a1)    # never a wrong answer
        evs = [e for e in sink.events[n_before:]
               if type(e).__name__ == "ResultCacheMissEvent"
               and e.reason == "spill-corrupt"]
        assert len(evs) == 1
        assert cache.stats()["spill_corruptions"] == 1


class TestServingLadders:
    def _variants(self, session, path, n):
        r = session.read.parquet(str(path))
        return [r.filter(col("k") < i + 3).group_by("k")
                .agg(sum_(col("v")).alias("sv")).sort("k")
                for i in range(n)]

    def test_sweep_member_fault_falls_back_per_member(self, tmp_path):
        """One member's injected fault inside the shared sweep re-runs
        that member standalone: every member's answer is byte-identical
        to serial, siblings never poisoned."""
        _write(tmp_path / "d", n=5000, files=2, seed=47)
        session = _session(
            tmp_path,
            **{ServingConstants.SERVING_MAX_CONCURRENCY: "1",
               ServingConstants.SERVING_BATCHING_WINDOW: "0.5"})
        qs = self._variants(session, tmp_path / "d", 4)
        serial = [q.to_arrow() for q in qs]
        fe = ServingFrontend(session)
        before = faults.stats()["member_fallbacks"]
        # One registry for the WHOLE wave (the submit-time snapshots
        # carry it), so nth counts across members: the first scan decode
        # — inside the first sweep member — fails, the fallback's rerun
        # passes.
        reg = FaultRegistry.from_conf_specs(
            {FN.SCAN_PARQUET_DECODE: "error:nth=1"})
        with faults.scope(reg):
            pend = [fe.submit(q) for q in qs]
        tables = [p.result(timeout=180) for p in pend]
        for ref, got in zip(serial, tables):
            assert ref.equals(got.to_arrow())
        assert faults.stats()["member_fallbacks"] == before + 1
        fe.drain()

    def test_worker_death_releases_members(self, tmp_path):
        """A worker dying while holding a batch window releases its
        members to per-member execution — no stranded futures, no leaked
        slots, correct answers."""
        _write(tmp_path / "d", seed=53)
        session = _session(
            tmp_path,
            **{ServingConstants.SERVING_MAX_CONCURRENCY: "1",
               ServingConstants.SERVING_BATCHING_WINDOW: "0.3"})
        qs = self._variants(session, tmp_path / "d", 3)
        serial = [q.to_arrow() for q in qs]
        fe = ServingFrontend(session)
        before = faults.stats()["worker_releases"]
        reg = FaultRegistry.from_conf_specs(
            {FN.SERVING_WORKER: "error:nth=1"})
        with faults.scope(reg):
            pend = [fe.submit(q) for q in qs]
        for ref, p in zip(serial, pend):
            assert ref.equals(p.result(timeout=180).to_arrow())
        assert faults.stats()["worker_releases"] >= before + 1
        fe.drain()
        st = fe.stats()
        assert st["active_workers"] == 0 and st["inflight_bytes"] == 0


# ---------------------------------------------------------------------------
# In-process crash recovery (the subprocess kill -9 harness lives in
# test_crash_recovery.py; this covers the recovery sweep's semantics).
# ---------------------------------------------------------------------------

class TestRecovery:
    def _env(self, tmp_path):
        rng = np.random.default_rng(33)
        df = pd.DataFrame({
            "k": rng.integers(0, 100, 6000).astype(np.int64),
            "v": rng.random(6000)})
        d = tmp_path / "data"
        d.mkdir()
        pq.write_table(pa.Table.from_pandas(df), d / "p0.parquet")
        session = _session(tmp_path)
        session.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
        return session, Hyperspace(session), str(d)

    def test_recover_rolls_back_crashed_create_and_vacuums(
            self, tmp_path, monkeypatch):
        from hyperspace_tpu.actions import create as create_mod
        from hyperspace_tpu.index.log_manager import IndexLogManager
        session, hs, d = self._env(tmp_path)
        t = session.read.parquet(d)

        orig_op = create_mod.CreateAction.op

        def crash_after_data(self):
            orig_op(self)  # write the index data, then die pre-commit
            raise RuntimeError("crash after op")

        monkeypatch.setattr(create_mod.CreateAction, "op",
                            crash_after_data)
        with pytest.raises(RuntimeError):
            hs.create_index(t, IndexConfig("cx", ["k"], ["v"]))
        monkeypatch.undo()
        idx_path = os.path.join(str(tmp_path / "indexes"), "cx")
        assert IndexLogManager(idx_path).get_latest_log().state \
            == States.CREATING
        import glob
        assert glob.glob(os.path.join(idx_path, "v__=*"))  # partial data
        summary = hs.recover()
        assert summary["cancelled"] == ["cx"]
        assert summary["vacuumed"]["cx"]  # the partial version is gone
        assert not glob.glob(os.path.join(idx_path, "v__=*"))
        latest = IndexLogManager(idx_path).get_latest_log()
        assert latest.state == States.DOESNOTEXIST
        # The lake is fully serviceable: re-create succeeds and queries
        # answer identically to a scan.
        hs.create_index(t, IndexConfig("cx", ["k"], ["v"]))
        session.enable_hyperspace()
        a = t.filter(col("k") == 3).select("k", "v").to_pandas()
        session.disable_hyperspace()
        b = t.filter(col("k") == 3).select("k", "v").to_pandas()
        pd.testing.assert_frame_equal(
            a.sort_values(["k", "v"]).reset_index(drop=True),
            b.sort_values(["k", "v"]).reset_index(drop=True))

    def test_recover_keeps_served_versions(self, tmp_path, monkeypatch):
        """A refresh crash: recovery rolls back to ACTIVE, vacuums only
        the crashed version, keeps the served one."""
        from hyperspace_tpu.actions import refresh as refresh_mod
        from hyperspace_tpu.index.log_manager import IndexLogManager
        session, hs, d = self._env(tmp_path)
        t = session.read.parquet(d)
        hs.create_index(t, IndexConfig("rx", ["k"], ["v"]))
        rng = np.random.default_rng(5)
        pq.write_table(pa.table({
            "k": pa.array(rng.integers(0, 100, 400).astype(np.int64)),
            "v": pa.array(rng.random(400))}),
            os.path.join(d, "extra.parquet"))

        orig_op = refresh_mod.RefreshIncrementalAction.op

        def crash_after_data(self):
            orig_op(self)
            raise RuntimeError("crash after refresh op")

        monkeypatch.setattr(refresh_mod.RefreshIncrementalAction, "op",
                            crash_after_data)
        with pytest.raises(RuntimeError):
            hs.refresh_index("rx", "incremental")
        monkeypatch.undo()
        summary = hs.recover()
        assert summary["cancelled"] == ["rx"]
        idx_path = os.path.join(str(tmp_path / "indexes"), "rx")
        assert IndexLogManager(idx_path).get_latest_stable_log().state \
            == States.ACTIVE
        import glob
        vdirs = glob.glob(os.path.join(idx_path, "v__=*"))
        assert [os.path.basename(v) for v in vdirs] == ["v__=0"]
        # Healthy lake: recovery again is a no-op.
        again = hs.recover()
        assert not again["cancelled"] and not again["vacuumed"]

    def test_recover_removes_unreferenced_orphan_dir(self, tmp_path):
        session, hs, d = self._env(tmp_path)
        t = session.read.parquet(d)
        hs.create_index(t, IndexConfig("ox", ["k"], ["v"]))
        idx_path = os.path.join(str(tmp_path / "indexes"), "ox")
        orphan = os.path.join(idx_path, "v__=9")
        os.makedirs(orphan)
        with open(os.path.join(orphan, "junk.parquet"), "wb") as f:
            f.write(b"partial")
        summary = hs.recover()
        assert summary["vacuumed"]["ox"] == [9]
        assert not os.path.isdir(orphan)
        assert os.path.isdir(os.path.join(idx_path, "v__=0"))


# ---------------------------------------------------------------------------
# Observability surfaces + the metrics collector.
# ---------------------------------------------------------------------------

class TestRobustnessObservability:
    def test_explain_section_gated_and_rendered(self, tmp_path):
        _write(tmp_path / "d", seed=59)
        session = _session(tmp_path)
        hs = Hyperspace(session)
        q = _query(session, tmp_path / "d")
        saved = faults.stats()
        faults.reset_stats()
        try:
            assert "Robustness:" not in hs.explain(q)
            session.conf.set(_fkey(FN.IO_POOLED_READ), "transient:times=1")
            q.to_arrow()
            text = hs.explain(q)
            assert "Robustness:" in text
            assert "fault points armed: 1" in text
            assert FN.IO_POOLED_READ in text
            assert "retries=1" in text
        finally:
            faults.reset_stats()
            faults.note(**{k: v for k, v in saved.items() if v})

    def test_robustness_keys_excluded_from_cache_key(self, tmp_path):
        """Toggling robustness knobs (a deadline, arming a fault) must
        NOT orphan warm result-cache entries — the r13 telemetry-key
        precedent: these knobs never change a computed answer."""
        _write(tmp_path / "d", seed=67)
        session = _session(tmp_path)
        session.conf.set(ServingConstants.RESULT_CACHE_ENABLED, "true")
        session.conf.set(ServingConstants.RESULT_CACHE_MIN_COMPUTE_SECONDS,
                         "0")
        q = _query(session, tmp_path / "d")
        first = q.to_arrow()  # miss + admit
        cache = session.result_cache
        hits_before = cache.stats()["hits"]
        session.conf.set(RC.DEADLINE_MS, "600000")
        session.conf.set(RC.RETRY_MAX_ATTEMPTS, "5")
        session.conf.set(_fkey(FN.IO_POOLED_READ), "error:p=0")
        again = q.to_arrow()
        assert again.equals(first)
        assert cache.stats()["hits"] == hits_before + 1  # still warm

    def test_metrics_registry_collector(self, tmp_path):
        session = _session(tmp_path)
        m = Hyperspace(session).metrics()
        assert "robustness" in m["collectors"]
        assert set(m["collectors"]["robustness"]) >= {
            "injected", "retries", "deadline_cancellations",
            "degraded_spmd", "spill_corruptions"}


# ---------------------------------------------------------------------------
# The lint gates (satellite: fault-name discipline + except-swallow ban).
# ---------------------------------------------------------------------------

class TestLintGates:
    def _lint(self):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "hst_lint", os.path.join(
                os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))), "scripts", "lint.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_fault_site_gate(self):
        import ast
        lint = self._lint()
        names = {"IO_POOLED_READ": "io.pooled_read"}
        bad = ast.parse("_faults.fault_point('free.form')")
        assert lint.fault_site_violations(bad, names)
        ok = ast.parse("_faults.fault_point(_fn.IO_POOLED_READ)")
        assert not lint.fault_site_violations(ok, names)
        ok_lit = ast.parse("faults.fault_point('io.pooled_read')")
        assert not lint.fault_site_violations(ok_lit, names)

    def test_except_swallow_gate(self):
        import ast
        lint = self._lint()
        bare = ast.parse("try:\n    x = 1\nexcept:\n    x = 2\n")
        assert lint.except_swallow_sites(bare)
        swallow = ast.parse(
            "try:\n    x = 1\nexcept BaseException:\n    pass\n")
        assert lint.except_swallow_sites(swallow)
        ok = ast.parse(
            "try:\n    x = 1\nexcept BaseException as e:\n    raise\n")
        assert not lint.except_swallow_sites(ok)
        ok2 = ast.parse(
            "try:\n    x = 1\nexcept Exception:\n    pass\n")
        assert not lint.except_swallow_sites(ok2)

    def test_repo_is_clean(self):
        """The real gates over the real tree: zero problems (same
        invocation CI runs)."""
        import subprocess
        import sys
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        proc = subprocess.run(
            [sys.executable, os.path.join(root, "scripts", "lint.py")],
            capture_output=True, text=True, cwd=root)
        assert proc.returncode == 0, proc.stdout + proc.stderr
