"""The op-log protocol over a conditional-put OBJECT store (SURVEY §7
hard-part 4; VERDICT r3 missing #6).

The local filesystem's link-into-place atomicity is NOT part of the log
protocol's contract — only conditional put-if-absent is. These tests run
the full lifecycle (CREATING→ACTIVE, latestStable cache, stale/torn-tail
recovery scans, multi-writer races) against InMemoryObjectStore, the
S3/GCS-semantics double (flat keys, LIST prefix, conditional PUT, no
rename), proving an object-store deployment needs nothing more.
"""

import threading

import pytest

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.constants import States
from hyperspace_tpu.index.log_entry import IndexLogEntry
from hyperspace_tpu.index.log_manager import IndexLogManager
from hyperspace_tpu.index.log_store import (InMemoryObjectStore,
                                            LocalFsLogStore, register_scheme,
                                            store_for_path)
from test_log_entry import make_entry


def entry(state: str, version: int = 1) -> IndexLogEntry:
    del version  # make_entry's fingerprint fixes the version; ids matter here
    return make_entry(state=state)


@pytest.fixture()
def mgr():
    return IndexLogManager("s3://bucket/indexes/idx",
                           store=InMemoryObjectStore())


class TestProtocolOverObjectStore:
    def test_lifecycle_and_latest_stable(self, mgr):
        assert mgr.write_log(0, entry(States.CREATING))
        assert mgr.write_log(1, entry(States.ACTIVE))
        assert not mgr.write_log(1, entry(States.ACTIVE)), \
            "the conditional PUT must refuse an existing id"
        assert mgr.create_latest_stable_log(1)
        got = mgr.get_latest_stable_log()
        assert got is not None and got.state == States.ACTIVE

    def test_backward_scan_past_transient_tail(self, mgr):
        mgr.write_log(0, entry(States.CREATING))
        mgr.write_log(1, entry(States.ACTIVE))
        mgr.create_latest_stable_log(1)
        mgr.write_log(2, entry(States.REFRESHING))
        mgr.delete_latest_stable_log()
        got = mgr.get_latest_stable_log()
        assert got is not None and got.state == States.ACTIVE and got.id == 1

    def test_torn_tail_recovers(self):
        store = InMemoryObjectStore()
        mgr = IndexLogManager("s3://b/idx", store=store)
        mgr.write_log(0, entry(States.CREATING))
        mgr.write_log(1, entry(States.ACTIVE))
        mgr.write_log(2, entry(States.REFRESHING))
        # Crash mid-upload: the tail object is a truncated JSON blob.
        store.corrupt(mgr._path_from_id(2))
        got = mgr.get_latest_stable_log()
        assert got is not None and got.id == 1

    def test_race_exactly_one_winner(self):
        store = InMemoryObjectStore()
        mgr = IndexLogManager("s3://b/idx", store=store)
        wins = []
        barrier = threading.Barrier(16)

        def contend(i):
            barrier.wait()
            if mgr.write_log(5, entry(States.CREATING)):
                wins.append(i)

        ts = [threading.Thread(target=contend, args=(i,)) for i in range(16)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert len(wins) == 1, f"winners: {wins}"

    def test_latest_id_lists_only_numeric_keys(self, mgr):
        mgr.write_log(0, entry(States.CREATING))
        mgr.write_log(1, entry(States.ACTIVE))
        mgr.create_latest_stable_log(1)  # writes the latestStable key too
        assert mgr.get_latest_id() == 1


class TestStoreResolution:
    def test_plain_path_is_local(self, tmp_path):
        assert isinstance(store_for_path(str(tmp_path)), LocalFsLogStore)
        assert isinstance(store_for_path(f"file://{tmp_path}"),
                          LocalFsLogStore)

    def test_file_uri_addresses_the_real_path(self, tmp_path):
        """file:// must strip to the filesystem path — otherwise os.*
        would silently create a literal './file:...' tree under cwd."""
        mgr = IndexLogManager(f"file://{tmp_path}/idx")
        assert mgr.write_log(0, entry(States.CREATING))
        import os
        assert os.path.isfile(str(tmp_path / "idx" / "_hyperspace_log" / "0"))
        # The same log is visible through the plain-path spelling.
        assert IndexLogManager(str(tmp_path / "idx")).get_latest_id() == 0

    def test_unregistered_scheme_is_a_clear_error(self):
        with pytest.raises(HyperspaceException, match="register_scheme"):
            store_for_path("abfss://container/path")

    def test_registered_scheme_wins(self):
        mem = InMemoryObjectStore()
        register_scheme("testmem", lambda p: mem)
        assert store_for_path("testmem://x/y") is mem
