"""Concurrent serving tier (serving/{context,program_bank,batcher,frontend}).

Covers the subsystem's contract end to end: the explicit QueryContext
threading (result-cache pinning, per-query io attribution, locked
session write-backs), the process-wide compiled-program bank (two
sessions share one warm workload's compiles), admission control
(queueDepth / admission.maxBytes rejection with events), cross-query
literal batching (N variants -> 1 batched invocation, byte-identical
per-query results), cross-session result-cache sharing, the
thread-safety hammer for session state concurrent execute() touches,
and the mixed TPC-H/TPC-DS concurrency soak (M threads x K queries
identical to serial execution).

Sessions run with the default distributed tier (partitioned-jit SPMD
over the virtual 8-device CPU mesh; the r12 port retired the old
quarantine).
"""

import os
import threading

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace
from hyperspace_tpu.exceptions import ServingRejectedError
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col, sum_
from hyperspace_tpu.serving import batcher
from hyperspace_tpu.serving.constants import ServingConstants
from hyperspace_tpu.serving.context import QueryContext, active_context
from hyperspace_tpu.serving.frontend import PendingQuery, ServingFrontend
from hyperspace_tpu.serving.program_bank import ProgramBank, get_bank

from conftest import capture_logger


def _write(d, n=4000, seed=7, files=1):
    rng = np.random.default_rng(seed)
    df = pd.DataFrame({
        "k": rng.integers(0, 50, n).astype(np.int64),
        "v": rng.integers(0, 9, n).astype(np.int64),
    })
    os.makedirs(str(d), exist_ok=True)
    step = max(n // files, 1)
    for i in range(files):
        lo = i * step
        hi = (i + 1) * step if i < files - 1 else n
        pq.write_table(pa.Table.from_pandas(df.iloc[lo:hi]),
                       os.path.join(str(d), f"p{i}.parquet"))
    return df


def _session(tmp_path, capture_events=False, **conf):
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    if capture_events:
        session.conf.set(IndexConstants.EVENT_LOGGER_CLASS,
                         "tests.conftest.CaptureLogger")
    for k, v in conf.items():
        session.conf.set(k, v)
    return session


def _variants(session, path, n=8):
    """n literal-variant aggregation queries (one canonical template)."""
    r = session.read.parquet(str(path))
    return [r.filter(col("k") < i + 3).group_by("k")
            .agg(sum_(col("v")).alias("sv")).sort("k")
            for i in range(n)]


class _GatedSession(hst.Session):
    """Session whose execute() blocks until released — deterministic
    queue-occupancy control for the admission tests."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.gate = threading.Event()

    def execute(self, plan, context=None):
        assert self.gate.wait(timeout=60), "gate never released"
        return super().execute(plan, context)


def _wait_until(pred, timeout=30.0):
    import time
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.005)
    return False


# ---------------------------------------------------------------------------
# QueryContext: the explicit per-query state object.
# ---------------------------------------------------------------------------

class TestQueryContext:
    def test_execute_activates_a_context(self, tmp_path):
        from hyperspace_tpu import session as session_mod
        _write(tmp_path / "d")
        session = _session(tmp_path)
        seen = {}
        df = session.read.parquet(str(tmp_path / "d"))
        orig = session_mod.Session._run_optimized

        def spy(self, optimized):
            seen["ctx"] = active_context()
            return orig(self, optimized)

        session_mod.Session._run_optimized = spy
        try:
            df.filter(col("k") < 5).count()
        finally:
            session_mod.Session._run_optimized = orig
        assert isinstance(seen["ctx"], QueryContext)
        assert seen["ctx"].session is session
        assert active_context() is None  # deactivated after execute

    def test_explicit_context_pins_the_result_cache(self, tmp_path):
        """A context-carried cache overrides the session's own (the
        frontend's cross-session sharing mechanism)."""
        from hyperspace_tpu.serving.constants import ServingConstants
        from hyperspace_tpu.serving.result_cache import ResultCache
        _write(tmp_path / "d")
        session = _session(tmp_path)
        # Admission must not depend on wall-clock: with the filter
        # program already warm (earlier tests share the structure) the
        # execution can beat the 5ms default floor.
        session.conf.set(
            ServingConstants.RESULT_CACHE_MIN_COMPUTE_SECONDS, "0")
        shared = ResultCache(device_bytes=1 << 24, host_bytes=1 << 24)
        df = session.read.parquet(str(tmp_path / "d")).filter(col("k") < 9)
        ctx = QueryContext(session, result_cache=shared)
        with ctx.activate():
            t1 = session.execute(df.plan, context=ctx)
        assert session.result_cache is None  # session flag still off
        s = shared.stats()
        assert s["misses"] == 1 and s["admissions"] == 1
        ctx2 = QueryContext(session, result_cache=shared)
        t2 = session.execute(df.plan, context=ctx2)
        assert shared.stats()["hits"] == 1
        assert t1.to_arrow().equals(t2.to_arrow())

    def test_join_actual_recorded_through_context(self, tmp_path):
        _write(tmp_path / "a", seed=1)
        _write(tmp_path / "b", seed=2)
        session = _session(tmp_path)
        a = session.read.parquet(str(tmp_path / "a"))
        b = session.read.parquet(str(tmp_path / "b"))
        q = a.join(b.select(col("k").alias("k2"), col("v").alias("v2")),
                   on=col("k") == col("k2"))
        q.count()
        assert len(session._join_actuals) == 1


# ---------------------------------------------------------------------------
# Program bank: explicit, bounded, instrumented, process-wide.
# ---------------------------------------------------------------------------

class TestProgramBank:
    def test_hit_miss_accounting(self):
        bank = ProgramBank(max_stages=2)
        made = []
        fn = bank.lookup(("s1",), (128,), lambda: made.append(1) or
                         (lambda *a: "r1"))
        assert fn() == "r1" and made == [1]
        # Same stage, same shapes: hit, factory NOT called again.
        bank.lookup(("s1",), (128,), lambda: made.append(2))
        assert made == [1]
        # Same stage, NEW shape class: miss (a compile is expected).
        bank.lookup(("s1",), (256,), lambda: made.append(3))
        assert made == [1]
        s = bank.stats()
        # "evictions" is THE canonical spelling (telemetry/metrics.py
        # naming); the deprecated "stage_evictions" alias is GONE — the
        # exact-dict assert pins both facts.
        assert s == {"stages": 1, "programs": 2, "hits": 1, "misses": 2,
                     "evictions": 0, "stages_by_kind": {"s1": 1}}
        assert "stage_evictions" not in s

    def test_lru_stage_eviction(self):
        bank = ProgramBank(max_stages=2)
        for i in range(3):
            bank.lookup((f"s{i}",), (1,), lambda: object())
        s = bank.stats()
        assert s["stages"] == 2 and s["evictions"] == 1

    def test_two_sessions_share_warm_programs(self, tmp_path):
        """THE multi-tenant acceptance: total compiles for two sessions
        running the same warm workload stay within 1.2x one session's
        compile count (the bank + jax executable cache are
        process-wide)."""
        from hyperspace_tpu.execution import shapes
        _write(tmp_path / "d", n=6000, seed=11)

        def workload(session):
            r = session.read.parquet(str(tmp_path / "d"))
            out = []
            for i in (2, 5, 9):
                out.append(r.filter((col("k") < 30 + i) & (col("v") > 1))
                           .group_by("k")
                           .agg(sum_(col("v")).alias("s")).sort("k")
                           .to_arrow())
                out.append(r.filter(col("k").isin([i, i + 1, i + 7]))
                           .select("k", "v").to_arrow())
            return out

        sess_a = _session(tmp_path)
        c0 = shapes.compile_count()
        ref = workload(sess_a)
        c_a = shapes.compile_count() - c0
        sess_b = _session(tmp_path)
        c1 = shapes.compile_count()
        got = workload(sess_b)
        c_b = shapes.compile_count() - c1
        for x, y in zip(ref, got):
            assert x.equals(y)
        # Second tenant rides the first tenant's compiles.
        assert c_a + c_b <= 1.2 * c_a + 1, (c_a, c_b)

    def test_bank_events_observed(self, tmp_path):
        """ProgramBankMissEvent per new program; ProgramBankHitEvent on
        first reuse — both through the active context's session logger."""
        from hyperspace_tpu.telemetry.events import (ProgramBankEvent,
                                                     ProgramBankHitEvent,
                                                     ProgramBankMissEvent)
        assert issubclass(ProgramBankHitEvent, ProgramBankEvent)
        assert issubclass(ProgramBankMissEvent, ProgramBankEvent)
        _write(tmp_path / "d", n=512, seed=23)
        session = _session(tmp_path, capture_events=True)
        sink = capture_logger()
        sink.events.clear()
        r = session.read.parquet(str(tmp_path / "d"))
        # A fresh predicate structure (column/op mix unused elsewhere in
        # this module) registers new programs, then reuses them.
        q1 = r.filter((col("v") >= 3) | (col("k") == 7))
        q2 = r.filter((col("v") >= 5) | (col("k") == 9))
        q1.count()
        q2.count()
        names = [type(e).__name__ for e in sink.events]
        assert "ProgramBankMissEvent" in names
        assert "ProgramBankHitEvent" in names
        ev = next(e for e in sink.events
                  if type(e).__name__ == "ProgramBankMissEvent")
        assert ev.stage_digest and ev.shape_vec


# ---------------------------------------------------------------------------
# Admission control.
# ---------------------------------------------------------------------------

class TestAdmission:
    def test_queue_depth_rejection(self, tmp_path):
        _write(tmp_path / "d")
        session = _GatedSession(system_path=str(tmp_path / "indexes"))
        session.conf.set(IndexConstants.EVENT_LOGGER_CLASS,
                         "tests.conftest.CaptureLogger")
        session.conf.set(ServingConstants.SERVING_QUEUE_DEPTH, "1")
        session.conf.set(ServingConstants.SERVING_MAX_CONCURRENCY, "1")
        session.conf.set(ServingConstants.SERVING_BATCHING_ENABLED,
                         "false")
        sink = capture_logger()
        sink.events.clear()
        fe = ServingFrontend(session)
        qs = _variants(session, tmp_path / "d", 3)
        p1 = fe.submit(qs[0])
        # The worker must have TAKEN q1 (it blocks inside execute).
        assert _wait_until(lambda: fe.stats()["queued"] == 0
                           and fe.stats()["active_workers"] == 1)
        p2 = fe.submit(qs[1])          # fills the depth-1 queue
        with pytest.raises(ServingRejectedError) as err:
            fe.submit(qs[2])
        assert "queue full" in str(err.value)
        st = fe.stats()
        assert st["rejected"] == 1 and st["admitted"] == 2
        names = [type(e).__name__ for e in sink.events]
        assert names.count("ServingAdmitEvent") == 2
        assert names.count("ServingRejectEvent") == 1
        session.gate.set()
        assert p1.result(timeout=60).num_rows >= 0
        assert p2.result(timeout=60).num_rows >= 0
        fe.drain()

    def test_worker_survives_bad_conf(self, tmp_path):
        """A mid-drain error (malformed batching.window) must not kill
        the worker NOR fail the innocent query: the r14 robustness
        release hands the un-started member to per-member execution —
        the answer arrives despite the bad conf, and no active_workers
        / inflight_bytes leak."""
        from hyperspace_tpu.robustness import faults as _faults
        _write(tmp_path / "d", seed=97)
        session = _session(tmp_path)
        session.conf.set(ServingConstants.SERVING_BATCHING_WINDOW, "0.3s")
        fe = ServingFrontend(session)
        q = _variants(session, tmp_path / "d", 1)[0]
        releases_before = _faults.stats()["worker_releases"]
        p = fe.submit(q)
        assert p.result(timeout=60).num_rows >= 0  # released, re-run solo
        assert _faults.stats()["worker_releases"] == releases_before + 1
        fe.drain()
        st = fe.stats()
        assert st["active_workers"] == 0
        assert st["inflight_bytes"] == 0
        assert st["completed"] >= 1 and st["failed"] == 0
        session.conf.set(ServingConstants.SERVING_BATCHING_WINDOW, "0.01")
        assert fe.submit(q).result(timeout=60).num_rows >= 0

    def test_byte_budget_rejection_but_lone_query_always_runs(
            self, tmp_path):
        _write(tmp_path / "d")
        session = _GatedSession(system_path=str(tmp_path / "indexes"))
        session.conf.set(ServingConstants.SERVING_MAX_CONCURRENCY, "1")
        session.conf.set(ServingConstants.SERVING_BATCHING_ENABLED,
                         "false")
        session.conf.set(ServingConstants.SERVING_ADMISSION_MAX_BYTES,
                         "1")
        fe = ServingFrontend(session)
        qs = _variants(session, tmp_path / "d", 2)
        p1 = fe.submit(qs[0])  # over budget alone, but nothing in flight
        assert p1.estimated_bytes > 1
        with pytest.raises(ServingRejectedError) as err:
            fe.submit(qs[1])
        assert "byte budget" in str(err.value)
        session.gate.set()
        p1.result(timeout=60)
        fe.drain()


# ---------------------------------------------------------------------------
# Cross-query literal batching.
# ---------------------------------------------------------------------------

class TestLiteralBatching:
    def test_template_key_matches_literal_variants_only(self, tmp_path):
        _write(tmp_path / "d")
        session = _session(tmp_path)
        qs = _variants(session, tmp_path / "d", 2)
        from hyperspace_tpu.serving.fingerprint import normalize
        k0 = batcher.template_key(session, normalize(qs[0].plan))
        k1 = batcher.template_key(session, normalize(qs[1].plan))
        assert k0 is not None and k0 == k1
        other = session.read.parquet(str(tmp_path / "d")) \
            .filter(col("v") < 3).group_by("k") \
            .agg(sum_(col("v")).alias("sv")).sort("k")
        ko = batcher.template_key(session, normalize(other.plan))
        assert ko is not None and ko != k0  # different column: no batch

    def test_eight_variants_one_invocation_byte_identical(self, tmp_path):
        """THE literal-batching acceptance: N=8 literal-variant queries
        execute as ONE batched invocation (one shared scan, one vmapped
        sweep) with per-query results identical to serial execution."""
        _write(tmp_path / "d", n=5000, files=2, seed=31)
        session = _session(
            tmp_path, capture_events=True,
            **{ServingConstants.SERVING_MAX_CONCURRENCY: "1",
               ServingConstants.SERVING_BATCHING_WINDOW: "0.5"})
        sink = capture_logger()
        sink.events.clear()
        qs = _variants(session, tmp_path / "d", 8)
        serial = [q.to_arrow() for q in qs]
        fe = ServingFrontend(session)
        pend = [fe.submit(q, client=f"user{i}")
                for i, q in enumerate(qs)]
        tables = [p.result(timeout=120) for p in pend]
        for ref, got in zip(serial, tables):
            assert ref.equals(got.to_arrow())
        st = fe.stats()
        assert st["batches"] == 1
        assert st["batched_queries"] == 8
        assert st["sweep_invocations"] == 1
        assert st["shared_scans"] == 1
        assert st["shared_scan_hits"] == 7
        assert all(p.batched and p.batch_size == 8 for p in pend)
        evs = [e for e in sink.events
               if type(e).__name__ == "ServingBatchEvent"]
        assert len(evs) == 1
        assert evs[0].size == 8 and evs[0].sweep_invocations == 1
        assert evs[0].shared_scans == 1 and evs[0].positions == 1

    def test_batching_disabled_still_identical(self, tmp_path):
        _write(tmp_path / "d", seed=41)
        session = _session(
            tmp_path,
            **{ServingConstants.SERVING_BATCHING_ENABLED: "false"})
        qs = _variants(session, tmp_path / "d", 4)
        serial = [q.to_arrow() for q in qs]
        fe = ServingFrontend(session)
        pend = [fe.submit(q) for q in qs]
        for ref, p in zip(serial, pend):
            assert ref.equals(p.result(timeout=120).to_arrow())
        assert fe.stats()["batches"] == 0

    def test_mixed_batchable_and_not(self, tmp_path):
        """Batchables interleaved with a structurally different query:
        everyone gets the right answer, non-members run solo."""
        _write(tmp_path / "d", seed=43)
        session = _session(
            tmp_path,
            **{ServingConstants.SERVING_MAX_CONCURRENCY: "1",
               ServingConstants.SERVING_BATCHING_WINDOW: "0.4"})
        r = session.read.parquet(str(tmp_path / "d"))
        qs = _variants(session, tmp_path / "d", 3)
        solo = r.filter(col("v") >= 4).select("v").sort("v").limit(5)
        batch = [qs[0], solo, qs[1], qs[2]]
        serial = [q.to_arrow() for q in batch]
        fe = ServingFrontend(session)
        pend = [fe.submit(q) for q in batch]
        for ref, p in zip(serial, pend):
            assert ref.equals(p.result(timeout=120).to_arrow())
        assert not pend[1].batched

    def test_float32_literals_byte_identical(self, tmp_path):
        """The stacked literal matrix must reproduce the single-query
        path's WEAK-scalar promotion: a python float literal casts DOWN
        to a float32 column there, so a strong float64 matrix (numpy's
        default) would promote the column instead and flip comparisons
        near the f32 rounding boundary (f32(1.1) > 1.1 is False weakly,
        True in float64)."""
        d = tmp_path / "d"
        os.makedirs(str(d))
        vals = np.asarray([1.1, 1.0999999, 1.1000001, 0.5, 2.0] * 800,
                          dtype=np.float32)
        pq.write_table(
            pa.table({"x": pa.array(vals, type=pa.float32()),
                      "k": pa.array(np.arange(vals.size) % 7,
                                    type=pa.int64())}),
            os.path.join(str(d), "p.parquet"))
        session = _session(
            tmp_path,
            **{ServingConstants.SERVING_MAX_CONCURRENCY: "1",
               ServingConstants.SERVING_BATCHING_WINDOW: "0.4"})
        lits = [1.1, 1.1000001, 1.0999999, 1.1, 0.5, 1.1, 2.0, 1.1]
        qs = [session.read.parquet(str(d)).filter(col("x") > v).select("k")
              for v in lits]
        serial = [q.to_arrow() for q in qs]
        fe = ServingFrontend(session)
        pend = [fe.submit(q) for q in qs]
        tables = [p.result(timeout=120) for p in pend]
        for ref, got in zip(serial, tables):
            assert ref.equals(got.to_arrow())
        st = fe.stats()
        assert st["batches"] == 1 and st["sweep_invocations"] == 1, st


# ---------------------------------------------------------------------------
# Cross-session result-cache sharing.
# ---------------------------------------------------------------------------

class TestSharedResultCache:
    def test_tenant_b_hits_tenant_a_result(self, tmp_path):
        _write(tmp_path / "d", seed=53)
        conf = {
            ServingConstants.RESULT_CACHE_ENABLED: "true",
            ServingConstants.RESULT_CACHE_MIN_COMPUTE_SECONDS: "0",
            ServingConstants.SERVING_BATCHING_ENABLED: "false",
        }
        gov = _session(tmp_path, **conf)
        sess_a = _session(tmp_path, **conf)
        sess_b = _session(tmp_path, **conf)
        fe = ServingFrontend(gov)
        qa = _variants(sess_a, tmp_path / "d", 1)[0]
        qb = _variants(sess_b, tmp_path / "d", 1)[0]
        ta = fe.submit(qa).result(timeout=120)
        fe.drain()
        tb = fe.submit(qb).result(timeout=120)
        shared = fe.result_cache()
        s = shared.stats()
        assert s["admissions"] == 1
        assert s["hits"] == 1, s  # tenant B served tenant A's bytes
        assert ta.to_arrow().equals(tb.to_arrow())
        # The sessions' OWN caches never saw the traffic: the context
        # carried the frontend's shared instance.
        assert sess_a.result_cache.stats()["misses"] == 0
        assert sess_b.result_cache.stats()["misses"] == 0


# ---------------------------------------------------------------------------
# Per-query io attribution (satellite: contextvars into pool workers).
# ---------------------------------------------------------------------------

class TestIoAttribution:
    def test_reads_attributed_to_the_right_query(self, tmp_path):
        _write(tmp_path / "small", n=2000, files=2, seed=61)
        _write(tmp_path / "big", n=12000, files=6, seed=62)
        session = _session(
            tmp_path,
            **{IndexConstants.TPU_IO_THREADS: "8",
               ServingConstants.SERVING_MAX_CONCURRENCY: "2",
               ServingConstants.SERVING_BATCHING_ENABLED: "false"})
        small = session.read.parquet(str(tmp_path / "small")) \
            .filter(col("k") < 10)
        big = session.read.parquet(str(tmp_path / "big")) \
            .filter(col("k") < 10)
        fe = ServingFrontend(session)
        ps = fe.submit(small, client="small")
        pb = fe.submit(big, client="big")
        ps.result(timeout=120)
        pb.result(timeout=120)
        io_s = ps.context.io_stats()
        io_b = pb.context.io_stats()
        # Worker threads entered the submitters' copied contexts, so
        # each query's reads landed on ITS context, proportionally.
        assert io_s["read_tasks"] > 0
        assert io_b["read_tasks"] > 0
        assert io_b["read_bytes"] > io_s["read_bytes"]

    def test_direct_execute_attributes_too(self, tmp_path):
        _write(tmp_path / "d", n=4000, files=4, seed=63)
        session = _session(tmp_path,
                           **{IndexConstants.TPU_IO_THREADS: "4"})
        df = session.read.parquet(str(tmp_path / "d")).filter(col("k") < 7)
        ctx = QueryContext.for_session(session)
        session.execute(df.plan, context=ctx)
        assert ctx.io_stats()["read_tasks"] > 0


# ---------------------------------------------------------------------------
# Session-state thread safety (satellite: the hammer).
# ---------------------------------------------------------------------------

class TestSessionThreadSafety:
    def test_concurrent_execute_hammer(self, tmp_path):
        """8 threads x joins+filters on ONE session with advisor capture
        on: the workload log, join-actual LRU, sql-plan memo and
        result-cache holder must neither corrupt nor raise."""
        from hyperspace_tpu.advisor.constants import AdvisorConstants
        _write(tmp_path / "a", seed=71)
        _write(tmp_path / "b", seed=72)
        session = _session(tmp_path)
        session.conf.set(AdvisorConstants.CAPTURE_ENABLED, "true")
        a = session.read.parquet(str(tmp_path / "a"))
        b = session.read.parquet(str(tmp_path / "b"))
        per_thread = 6
        errors = []

        def worker(tid):
            try:
                for i in range(per_thread):
                    q = a.filter(col("k") < 10 + tid + i).join(
                        b.select(col("k").alias("k2"), col("v").alias("v2")),
                        on=col("k") == col("k2"))
                    q.count()
            except BaseException as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
            assert not t.is_alive(), "hammer thread hung"
        assert not errors, errors
        assert len(session._workload_log) == 8 * per_thread
        assert len(session._join_actuals) > 0
        for rec in session._workload_log.snapshot():
            assert rec.latency_s >= 0

    def test_temp_view_registration_is_locked(self, tmp_path):
        _write(tmp_path / "d", seed=73)
        session = _session(tmp_path)
        df = session.read.parquet(str(tmp_path / "d"))
        errors = []

        def worker(tid):
            try:
                for i in range(50):
                    session.create_temp_view(f"v_{tid}_{i}", df)
                    assert session.table(f"v_{tid}_{i}") is not None
            except BaseException as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        assert len(session._temp_views) == 8 * 50
        assert session._temp_views_version == 8 * 50


# ---------------------------------------------------------------------------
# Mixed TPC-H / TPC-DS concurrency soak.
# ---------------------------------------------------------------------------

SOAK_QUERIES = ["tpch_q1", "tpch_q3", "tpch_q6", "tpch_q12",
                "tpcds_q1_like", "tpcds_q3_like", "tpcds_q42_like",
                "tpch_q17"]


class TestConcurrencySoak:
    @pytest.mark.parametrize("through_frontend", [True, False])
    def test_m_threads_k_queries_identical_to_serial(
            self, tmp_path, through_frontend):
        """M=8 client threads x mixed TPC-H/TPC-DS queries across TWO
        independent sessions produce answers identical to serial
        execution — through the frontend and via raw concurrent
        Session.execute alike; zero deadlocks (hard join timeouts)."""
        from goldstandard import tpc
        root = str(tmp_path / "tpc")
        ref_session = _session(tmp_path)
        dfs = tpc.register_tables(ref_session, root)
        serial = {name: tpc.queries(dfs)[name].to_arrow()
                  for name in SOAK_QUERIES}

        sessions = [_session(tmp_path) for _ in range(2)]
        plans = []
        for s in sessions:
            qdict = tpc.queries(tpc.register_tables(s, root))
            plans.append({n: qdict[n] for n in SOAK_QUERIES})
        fe = ServingFrontend(sessions[0]) if through_frontend else None

        results = {}
        errors = []

        def client(tid):
            try:
                session_ix = tid % 2
                for j, name in enumerate(SOAK_QUERIES):
                    if (j + tid) % 2 == 0:
                        continue  # each thread runs half the mix
                    q = plans[session_ix][name]
                    if fe is not None:
                        table = fe.submit(q, client=f"c{tid}") \
                            .result(timeout=300)
                    else:
                        table = q.execute()
                    results[(tid, name)] = table.to_arrow()
            except BaseException as e:  # pragma: no cover
                errors.append((tid, e))

        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
            assert not t.is_alive(), "soak client hung (deadlock?)"
        assert not errors, errors
        assert len(results) == 8 * len(SOAK_QUERIES) // 2
        for (tid, name), table in results.items():
            assert table.equals(serial[name]), \
                f"thread {tid} query {name} diverged from serial"


# ---------------------------------------------------------------------------
# Observability surfaces.
# ---------------------------------------------------------------------------

class TestServingObservability:
    def test_serving_stats_and_explain_section(self, tmp_path):
        _write(tmp_path / "d", seed=83)
        session = _session(
            tmp_path, **{ServingConstants.SERVING_ENABLED: "true"})
        hs = Hyperspace(session)
        stats = hs.serving_stats()
        assert "program_bank" in stats
        df = session.read.parquet(str(tmp_path / "d")).filter(col("k") < 4)
        text = hs.explain(df)
        assert "Serving:" in text
        assert "program bank:" in text
        fe = hs.serving_frontend()
        assert isinstance(fe.submit(df), PendingQuery)
        fe.drain()
        stats = hs.serving_stats()
        assert stats["submitted"] >= 1 and stats["frontend"] is True
        text = hs.explain(df)
        assert "queries: submitted=" in text

    def test_disabled_serving_explain_silent(self, tmp_path):
        _write(tmp_path / "d", seed=89)
        session = _session(tmp_path)
        hs = Hyperspace(session)
        from hyperspace_tpu.serving import frontend as fe_mod
        if fe_mod._DEFAULT is None:
            text = hs.explain(
                session.read.parquet(str(tmp_path / "d")))
            assert "Serving:" not in text

    def test_default_frontend_requires_enabled(self, tmp_path):
        from hyperspace_tpu.exceptions import HyperspaceException
        from hyperspace_tpu.serving.frontend import get_frontend
        session = _session(tmp_path)
        with pytest.raises(HyperspaceException):
            get_frontend(session)

    def test_direct_construction_registers_default(self, tmp_path,
                                                   monkeypatch):
        """Construction is the opt-in (README/bench construct directly),
        so a directly-built frontend must be visible to serving_stats()
        and explain's Serving section, not just get_frontend()'s."""
        from hyperspace_tpu.serving import frontend as fe_mod
        monkeypatch.setattr(fe_mod, "_DEFAULT", None)
        _write(tmp_path / "d", seed=97)
        session = _session(tmp_path)
        fe = ServingFrontend(session)
        assert fe_mod._DEFAULT is fe
        df = session.read.parquet(str(tmp_path / "d")).filter(col("k") < 4)
        fe.submit(df).result(timeout=120)
        fe.drain()
        stats = Hyperspace(session).serving_stats()
        assert stats["frontend"] is True and stats["submitted"] >= 1


# ---------------------------------------------------------------------------
# The lint ratchet (satellite: no new module-level mutable state).
# ---------------------------------------------------------------------------

class TestMutableStateGate:
    def _sites(self, src):
        import ast
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "lint_under_test",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "scripts", "lint.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.mutable_state_sites(ast.parse(src))

    def test_flags_mutated_module_dict(self):
        sites = self._sites(
            "_CACHE = {}\n"
            "def put(k, v):\n"
            "    _CACHE[k] = v\n")
        assert [name for _, name in sites] == ["_CACHE"]

    def test_allows_constant_lookup_tables_and_locals(self):
        assert self._sites(
            "_TABLE = {'a': 1}\n"
            "def f():\n"
            "    x = []\n"
            "    x.append(1)\n"
            "    return _TABLE['a'], x\n") == []

    def test_flags_mutator_methods_and_constructors(self):
        sites = self._sites(
            "from collections import OrderedDict\n"
            "_LRU = OrderedDict()\n"
            "def touch(k):\n"
            "    _LRU.move_to_end(k)\n")
        assert [name for _, name in sites] == ["_LRU"]

    def test_repo_is_clean_under_the_gate(self):
        import subprocess
        import sys
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        out = subprocess.run(
            [sys.executable, os.path.join(root, "scripts", "lint.py")],
            capture_output=True, text=True)
        assert "module-level mutable state" not in out.stdout
