"""SPMD outer / semi / anti / multi-key joins (VERDICT r3 #7): the plan
shapes that used to fall back to single-device now run distributed.

Spark (the reference's engine) distributes every join type
(RuleUtils.scala delegates to Spark's shuffle machinery); here:
  - left outer rides both strategies (broadcast m:1 keeps unmatched
    stream rows with invalid right columns; exchange pads per shard),
  - right/full outer ride the exchange (each right row is owned by
    exactly one device after the hash route, so local match status is
    global and unmatched rows append without coordination),
  - semi/anti are keys-only broadcasts (duplicates fine),
  - multi-key m:n joins route on the bit-packed composite.

Oracle pattern matches test_spmd.py: assert SPMD was actually taken
(DISPATCH_COUNT advances) and compare against the single-device executor
with distribution disabled.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.execution import spmd
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col, count, sum_


@pytest.fixture()
def session(tmp_system_path):
    s = hst.Session(system_path=tmp_system_path)
    # Gate off: these fixtures are deliberately small meshes.
    s.conf.set(IndexConstants.TPU_DISTRIBUTED_MIN_STREAM_ROWS, "0")
    return s


def write_dir(tmp_path, name, table):
    d = tmp_path / name
    d.mkdir()
    pq.write_table(table, str(d / "part0.parquet"))
    return str(d)


def run_both(session, make_query, sort_by):
    before = spmd.DISPATCH_COUNT
    dist = make_query().to_pandas()
    assert spmd.DISPATCH_COUNT > before, "SPMD path was not taken"
    session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "false")
    try:
        single = make_query().to_pandas()
    finally:
        session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "true")
    a = dist.sort_values(sort_by).reset_index(drop=True)
    b = single.sort_values(sort_by).reset_index(drop=True)
    pd.testing.assert_frame_equal(a, b, check_dtype=False)
    return a


@pytest.fixture()
def fact_dim(tmp_path):
    """Fact keys 0..119; dim covers only 0..79 (m:1, unique) so a left
    join leaves 1/3 of fact unmatched."""
    rng = np.random.default_rng(60)
    n = 3000
    fact = write_dir(tmp_path, "fact", pa.table({
        "k": rng.integers(0, 120, n).astype(np.int64),
        "v": rng.integers(0, 50, n).astype(np.int64),
    }))
    dim = write_dir(tmp_path, "dim", pa.table({
        "dk": np.arange(80, dtype=np.int64),
        "dval": rng.integers(0, 9, 80).astype(np.int64),
    }))
    return fact, dim


class TestLeftOuterBroadcast:
    def test_stream(self, session, fact_dim):
        fact, dim = fact_dim
        lf = session.read.parquet(fact)
        rf = session.read.parquet(dim)
        out = run_both(
            session,
            lambda: lf.join(rf, on=col("k") == col("dk"), how="left")
                      .select("k", "v", "dval"),
            sort_by=["k", "v"])
        assert len(out) == 3000  # every fact row survives
        assert out[out.k >= 80]["dval"].isna().all()
        assert out[out.k < 80]["dval"].notna().all()

    def test_aggregate_skips_nulls(self, session, fact_dim):
        fact, dim = fact_dim
        lf = session.read.parquet(fact)
        rf = session.read.parquet(dim)
        run_both(
            session,
            lambda: lf.join(rf, on=col("k") == col("dk"), how="left")
                      .group_by("k").agg(count(None).alias("n"),
                                         sum_(col("dval")).alias("sd")),
            sort_by=["k"])

    def test_group_by_nullable_right_col(self, session, fact_dim):
        """Unmatched rows fall into the null group — nullable key meta
        must propagate through the join into the grouped aggregate."""
        fact, dim = fact_dim
        lf = session.read.parquet(fact)
        rf = session.read.parquet(dim)
        out = run_both(
            session,
            lambda: lf.join(rf, on=col("k") == col("dk"), how="left")
                      .group_by("dval").agg(count(None).alias("n")),
            sort_by=["dval"])
        assert out["dval"].isna().any()  # the null group exists


class TestExchangeOuter:
    @pytest.fixture()
    def mn(self, tmp_path):
        """m:n with one-sided key ranges: left 0..59, right 30..89 with
        ~3 dups per key — both unmatched-left and unmatched-right exist."""
        rng = np.random.default_rng(61)
        left = write_dir(tmp_path, "l", pa.table({
            "k": rng.integers(0, 60, 1200).astype(np.int64),
            "v": np.arange(1200, dtype=np.int64),
        }))
        right = write_dir(tmp_path, "r", pa.table({
            "rk": rng.integers(30, 90, 180).astype(np.int64),
            "w": np.arange(180, dtype=np.int64),
        }))
        return left, right

    def test_left_outer(self, session, mn):
        left, right = mn
        lf = session.read.parquet(left)
        rf = session.read.parquet(right)
        out = run_both(
            session,
            lambda: lf.join(rf, on=col("k") == col("rk"), how="left")
                      .select("k", "v", "w"),
            sort_by=["k", "v", "w"])
        assert out[out.k < 30]["w"].isna().all()

    def test_right_outer(self, session, mn):
        left, right = mn
        lf = session.read.parquet(left)
        rf = session.read.parquet(right)
        out = run_both(
            session,
            lambda: lf.join(rf, on=col("k") == col("rk"), how="right")
                      .select("k", "rk", "w"),
            sort_by=["rk", "w", "k"])
        assert out[out.rk >= 60]["k"].isna().all()
        assert set(out["rk"]) >= {60}  # unmatched right rows surfaced

    def test_full_outer(self, session, mn):
        left, right = mn
        lf = session.read.parquet(left)
        rf = session.read.parquet(right)
        out = run_both(
            session,
            lambda: lf.join(rf, on=col("k") == col("rk"), how="full")
                      .select("k", "v", "rk", "w"),
            sort_by=["k", "rk", "v", "w"])
        assert out["k"].isna().any() and out["rk"].isna().any()

    def test_left_outer_aggregate(self, session, mn):
        left, right = mn
        lf = session.read.parquet(left)
        rf = session.read.parquet(right)
        run_both(
            session,
            lambda: lf.join(rf, on=col("k") == col("rk"), how="left")
                      .group_by("k").agg(count(None).alias("n"),
                                         sum_(col("w")).alias("sw")),
            sort_by=["k"])

    def test_full_outer_aggregate_null_group(self, session, mn):
        left, right = mn
        lf = session.read.parquet(left)
        rf = session.read.parquet(right)
        out = run_both(
            session,
            lambda: lf.join(rf, on=col("k") == col("rk"), how="full")
                      .group_by("k").agg(count(None).alias("n")),
            sort_by=["k"])
        assert out["k"].isna().any()  # the appendix rows' null group


class TestOuterNullKeys:
    """Null join keys match nothing, but outer joins must still EMIT the
    preserving side's null-key rows as unmatched — the single-device
    executor does (_execute_outer_join), and the exchange path carries a
    key-validity flag so they survive the route."""

    @pytest.fixture()
    def dirs(self, tmp_path):
        rng = np.random.default_rng(66)
        lk = rng.integers(0, 50, 900).astype(np.float64)
        lk[rng.permutation(900)[:30]] = np.nan
        left = write_dir(tmp_path, "nkl", pa.table({
            "k": pa.array([None if np.isnan(x) else int(x) for x in lk],
                          type=pa.int64()),
            "v": np.arange(900, dtype=np.int64),
        }))
        rk = rng.integers(20, 70, 120).astype(np.float64)
        rk[rng.permutation(120)[:8]] = np.nan
        right = write_dir(tmp_path, "nkr", pa.table({
            "rk": pa.array([None if np.isnan(x) else int(x) for x in rk],
                           type=pa.int64()),
            "w": np.arange(120, dtype=np.int64),
        }))
        return left, right

    def test_full_outer_null_keys(self, session, dirs):
        left, right = dirs
        lf = session.read.parquet(left)
        rf = session.read.parquet(right)
        out = run_both(
            session,
            lambda: lf.join(rf, on=col("k") == col("rk"), how="full")
                      .select("k", "v", "rk", "w"),
            sort_by=["k", "rk", "v", "w"])
        # Null-key rows from BOTH sides surface as unmatched.
        assert out["v"].notna().sum() >= 900
        assert out["w"].notna().sum() >= 120

    def test_right_outer_null_keys(self, session, dirs):
        left, right = dirs
        lf = session.read.parquet(left)
        rf = session.read.parquet(right)
        out = run_both(
            session,
            lambda: lf.join(rf, on=col("k") == col("rk"), how="right")
                      .select("k", "rk", "w"),
            sort_by=["rk", "w", "k"])
        # Every right row appears at least once, incl. the 8 null-key ones.
        assert out["w"].nunique() == 120
        assert out["rk"].isna().sum() >= 8


class TestProjectBelowOuterJoin:
    def test_projected_key_full_outer(self, session, tmp_path):
        """A Project below a right/full outer join creates columns the
        leaf metadata never saw — prep must read the projected meta, not
        crash past the fallback net (r4 review regression)."""
        rng = np.random.default_rng(67)
        left = write_dir(tmp_path, "pl", pa.table({
            "k": rng.integers(0, 40, 800).astype(np.int64),
            "v": np.arange(800, dtype=np.int64)}))
        right = write_dir(tmp_path, "pr", pa.table({
            "rk": rng.integers(20, 60, 100).astype(np.int64),
            "w": np.arange(100, dtype=np.int64)}))
        lf = session.read.parquet(left)
        rf = session.read.parquet(right)
        run_both(
            session,
            lambda: lf.select((col("k") + 1).alias("k2"), "v")
                      .join(rf, on=col("k2") == col("rk"), how="full")
                      .select("k2", "v", "rk", "w"),
            sort_by=["k2", "rk", "v", "w"])


class TestSemiAnti:
    @pytest.fixture()
    def dirs(self, tmp_path):
        rng = np.random.default_rng(62)
        left = write_dir(tmp_path, "sl", pa.table({
            "k": rng.integers(0, 100, 2000).astype(np.int64),
            "v": np.arange(2000, dtype=np.int64),
        }))
        # Duplicate probe keys: a plain broadcast join would refuse (m:1),
        # but semi/anti must not care.
        right = write_dir(tmp_path, "sr", pa.table({
            "rk": np.repeat(rng.permutation(100)[:40], 3).astype(np.int64),
        }))
        return left, right

    def test_semi(self, session, dirs):
        left, right = dirs
        lf = session.read.parquet(left)
        rf = session.read.parquet(right)
        out = run_both(
            session,
            lambda: lf.join(rf, on=col("k") == col("rk"), how="semi")
                      .select("k", "v"),
            sort_by=["v"])
        assert 0 < len(out) < 2000

    def test_anti(self, session, dirs):
        left, right = dirs
        lf = session.read.parquet(left)
        rf = session.read.parquet(right)
        semi = run_both(
            session,
            lambda: lf.join(rf, on=col("k") == col("rk"), how="semi")
                      .select("v"), sort_by=["v"])
        anti = run_both(
            session,
            lambda: lf.join(rf, on=col("k") == col("rk"), how="anti")
                      .select("v"), sort_by=["v"])
        assert len(semi) + len(anti) == 2000

    def test_semi_aggregate(self, session, dirs):
        left, right = dirs
        lf = session.read.parquet(left)
        rf = session.read.parquet(right)
        run_both(
            session,
            lambda: lf.join(rf, on=col("k") == col("rk"), how="semi")
                      .group_by("k").agg(count(None).alias("n")),
            sort_by=["k"])


class TestMultiKeyExchange:
    def test_two_key_m_n(self, session, tmp_path):
        """Duplicate (k1, k2) pairs on both sides: the broadcast side
        refuses (m:1) and the exchange must route on the packed
        composite so equal TUPLES meet on one device."""
        rng = np.random.default_rng(63)
        left = write_dir(tmp_path, "m2l", pa.table({
            "a": rng.integers(0, 25, 1500).astype(np.int64),
            "b": rng.integers(0, 4, 1500).astype(np.int64),
            "v": np.arange(1500, dtype=np.int64),
        }))
        right = write_dir(tmp_path, "m2r", pa.table({
            "ra": np.repeat(np.arange(25, dtype=np.int64), 8),
            "rb": np.tile(np.arange(4, dtype=np.int64), 50),
            "w": np.arange(200, dtype=np.int64),
        }))
        lf = session.read.parquet(left)
        rf = session.read.parquet(right)
        run_both(
            session,
            lambda: lf.join(rf, on=(col("a") == col("ra"))
                            & (col("b") == col("rb")))
                      .group_by("a").agg(count(None).alias("n"),
                                         sum_(col("w")).alias("sw")),
            sort_by=["a"])

    def test_three_key_left_outer(self, session, tmp_path):
        rng = np.random.default_rng(64)
        left = write_dir(tmp_path, "m3l", pa.table({
            "a": rng.integers(0, 10, 900).astype(np.int64),
            "b": rng.integers(0, 5, 900).astype(np.int64),
            "c": rng.integers(0, 3, 900).astype(np.int64),
            "v": np.arange(900, dtype=np.int64),
        }))
        # Right covers half the key space, with dups.
        right = write_dir(tmp_path, "m3r", pa.table({
            "ra": np.repeat(np.arange(5, dtype=np.int64), 30),
            "rb": np.tile(np.repeat(np.arange(5, dtype=np.int64), 6), 5),
            "rc": np.tile(np.arange(3, dtype=np.int64), 50),
            "w": np.arange(150, dtype=np.int64),
        }))
        lf = session.read.parquet(left)
        rf = session.read.parquet(right)
        out = run_both(
            session,
            lambda: lf.join(
                rf, on=(col("a") == col("ra")) & (col("b") == col("rb"))
                & (col("c") == col("rc")), how="left")
                .select("a", "b", "c", "v", "w"),
            sort_by=["a", "b", "c", "v", "w"])
        assert out[out.a >= 5]["w"].isna().all()

    def test_string_key_left_outer_exchange(self, session, tmp_path):
        rng = np.random.default_rng(65)
        names = np.array([f"s{i:02d}" for i in range(30)])
        left = write_dir(tmp_path, "skl", pa.table({
            "k": names[rng.integers(0, 30, 1000)],
            "v": np.arange(1000, dtype=np.int64),
        }))
        # Only the first 18 names, duplicated (m:n).
        right = write_dir(tmp_path, "skr", pa.table({
            "rk": names[rng.integers(0, 18, 120)],
            "w": np.arange(120, dtype=np.int64),
        }))
        lf = session.read.parquet(left)
        rf = session.read.parquet(right)
        out = run_both(
            session,
            lambda: lf.join(rf, on=col("k") == col("rk"), how="left")
                      .select("k", "rk", "v", "w"),
            sort_by=["k", "v", "w"])
        unmatched = out[out["w"].isna()]
        assert len(unmatched) > 0
        assert unmatched["rk"].isna().all()
        # Matched rows surface the right key's own spelling.
        matched = out[out["w"].notna()]
        assert (matched["k"] == matched["rk"]).all()
