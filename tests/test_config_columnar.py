"""Config-system contract + columnar Table edge cases.

Parity: the reference's HyperspaceConf/IndexConstants suites pin key
precedence, defaults, and parse behavior (util/HyperspaceConfTest-style
assertions inside other suites); the columnar layer is this framework's
own (the engine Spark provides in the reference) so its invariants —
dictionary re-unification on concat, validity widening, host/device
round-trips — get direct coverage.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import hyperspace_tpu as hst
from hyperspace_tpu.config import Conf, HyperspaceConf
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.execution.columnar import Column, Table
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.schema import DATE, INT64, STRING


class TestConf:
    def test_set_get_roundtrip_stringifies(self):
        c = Conf()
        c.set("a.b", 42)
        assert c.get("a.b") == "42"  # values normalize to strings
        c.set("a.b", True)
        assert c.get("a.b") == "True"

    def test_get_default_and_contains(self):
        c = Conf({"x": "1"})
        assert c.get("y") is None
        assert c.get("y", "fallback") == "fallback"
        assert c.contains("x") and not c.contains("y")

    def test_unset(self):
        c = Conf({"x": "1"})
        c.unset("x")
        assert c.get("x") is None
        c.unset("x")  # idempotent

    def test_copy_is_independent(self):
        c = Conf({"x": "1"})
        d = c.copy()
        d.set("x", "2")
        assert c.get("x") == "1" and d.get("x") == "2"

    def test_session_conf_chaining(self, tmp_path):
        s = hst.Session(system_path=str(tmp_path / "idx"))
        s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 8) \
            .set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
        assert s.hs_conf.num_bucket_count() == 8
        assert s.hs_conf.index_lineage_enabled() is True


class TestHyperspaceConfDefaults:
    def make(self, **kv):
        return HyperspaceConf(Conf({k: str(v) for k, v in kv.items()}))

    def test_reference_defaults(self):
        hc = self.make()
        # The reference's IndexConstants defaults (IndexConstants.scala).
        assert hc.num_bucket_count() == 200
        assert hc.hybrid_scan_enabled() is False
        assert hc.hybrid_scan_appended_ratio_threshold() == pytest.approx(0.3)
        assert hc.hybrid_scan_deleted_ratio_threshold() == pytest.approx(0.2)
        assert hc.optimize_file_size_threshold() == 256 * 1024 * 1024
        assert hc.index_cache_expiry_seconds() == 300
        assert hc.case_sensitive() is False
        assert hc.event_logger_class() is None

    def test_boolean_parsing_is_case_insensitive(self):
        assert self.make(**{
            IndexConstants.INDEX_HYBRID_SCAN_ENABLED: "TRUE"
        }).hybrid_scan_enabled() is True
        assert self.make(**{
            IndexConstants.INDEX_HYBRID_SCAN_ENABLED: "False"
        }).hybrid_scan_enabled() is False

    def test_numeric_overrides(self):
        hc = self.make(**{IndexConstants.INDEX_NUM_BUCKETS: "16"})
        assert hc.num_bucket_count() == 16


class TestColumnarConcat:
    def int_col(self, vals, validity=None):
        v = None if validity is None else jnp.asarray(validity)
        return Column(INT64, jnp.asarray(np.asarray(vals, np.int64)), v)

    def str_col(self, codes, dictionary):
        return Column(STRING, jnp.asarray(np.asarray(codes, np.int32)),
                      None, np.asarray(dictionary, object))

    def test_concat_dtype_mismatch_raises(self):
        a = Table({"x": self.int_col([1, 2])})
        b = Table({"x": Column(DATE, jnp.asarray(np.asarray([1], np.int32)))})
        with pytest.raises(HyperspaceException, match="dtype mismatch"):
            Table.concat([a, b])

    def test_concat_skips_empty_tables(self):
        a = Table({"x": self.int_col([1, 2])})
        empty = Table({"x": self.int_col([])})
        out = Table.concat([empty, a, empty])
        np.testing.assert_array_equal(np.asarray(out.column("x").data), [1, 2])

    def test_concat_widens_validity(self):
        # One side has no validity (all valid); the union must keep the
        # other side's nulls and mark the first side all-true.
        a = Table({"x": self.int_col([1, 2])})
        b = Table({"x": self.int_col([3, 4], validity=[True, False])})
        out = Table.concat([a, b])
        np.testing.assert_array_equal(
            np.asarray(out.column("x").validity),
            [True, True, True, False])

    def test_concat_reunifies_string_dictionaries(self):
        # Different dictionaries for the same logical values: codes must be
        # remapped onto one dictionary, values preserved.
        a = Table({"s": self.str_col([0, 1], ["apple", "pear"])})
        b = Table({"s": self.str_col([0, 1], ["banana", "apple"])})
        out = Table.concat([a, b])
        col = out.column("s")
        dic = list(col.dictionary)
        got = [dic[int(c)] for c in np.asarray(col.data)]
        assert got == ["apple", "pear", "banana", "apple"]
        # Order-preserving dictionary: codes must compare like the strings.
        order = np.argsort(np.asarray(col.data, np.int64))
        assert [got[i] for i in order] == sorted(got)

    def test_to_host_roundtrip_preserves_everything(self):
        t = Table({
            "x": self.int_col([5, 6, 7], validity=[True, False, True]),
            "s": self.str_col([1, 0, 1], ["aa", "bb"]),
        }, bucket_order=(4, ("x",)))
        h = t.to_host()
        assert h.bucket_order == (4, ("x",))
        back = h.to_arrow()
        assert back.column("x").to_pylist() == [5, None, 7]
        assert back.column("s").to_pylist() == ["bb", "aa", "bb"]


class TestTableSliceTake:
    def test_slice_preserves_bucket_order(self):
        t = Table({"x": Column(INT64, jnp.arange(10))},
                  bucket_order=(2, ("x",)))
        s = t.slice(2, 5)
        assert s.bucket_order == (2, ("x",))
        assert s.num_rows == 3

    def test_filter_mask_length_mismatch_raises(self):
        t = Table({"x": Column(INT64, jnp.arange(4))})
        with pytest.raises(HyperspaceException, match="mask length"):
            t.filter(jnp.ones(3, jnp.bool_))

    def test_take_reorders_all_columns(self):
        t = Table({
            "x": Column(INT64, jnp.asarray(np.asarray([10, 20, 30], np.int64))),
            "y": Column(INT64, jnp.asarray(np.asarray([1, 2, 3], np.int64))),
        })
        out = t.take(jnp.asarray(np.asarray([2, 0], np.int32)))
        np.testing.assert_array_equal(np.asarray(out.column("x").data), [30, 10])
        np.testing.assert_array_equal(np.asarray(out.column("y").data), [3, 1])
